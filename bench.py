"""Benchmark: TPC-H wall-clock on generated lineitem data.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.
Metric = engine rows/sec through the full path (plan → optimize → translate →
execute) over the BENCH_QUERIES subset (default: the 9 scan/join/agg-heavy
queries 1,3,4,5,6,10,12,14,19 — the shape of the reference's Q1-Q10 benchmark):
total lineitem rows touched per query run divided by total wall-clock. Baseline
anchor: reference NativeRunner TPC-H throughput on server CPU (BASELINE.md §6),
scaled to one chip.

Environment knobs:
    BENCH_SF=10           scale factor (default 1; SF10 ~60M lineitem rows)
    BENCH_QUERIES=1,..,22 query subset (default the 9-query headline set)
    BENCH_REPS=5          timed repetitions (best-of; tunnel jitter guard)
    BENCH_SUITE=tpcds     run the TPC-DS store-sales suite instead of TPC-H
                          (benchmarking/tpcds; default queries 3,7,19,42,52,55,96)
    BENCH_SUITE=ai        run the multimodal/AI pipeline capture on the
                          device-UDF tier: seeded encoder, scan text ->
                          embed -> zero-shot classify -> groupby count,
                          asserting device-vs-host bit-parity, zero repeat
                          weight re-upload, and coalesced super-batches
    BENCH_AI_ROWS=N       ai-suite corpus rows (default 4096)
    BENCH_AI_BATCH_ROWS=N ai-suite scan batch rows (default 512 — multi-batch
                          so the dispatch coalescer engages)
    BENCH_SHUFFLE=1       run the 2-worker shuffle microbench instead: a
                          socket-transport distributed groupby whose JSON
                          carries the wire/logical byte counters and the
                          derived compression/overlap ratios
    BENCH_SHUFFLE_ROWS=N  microbench fact rows (default 200_000)
    BENCH_FUSION=1        run the whole-stage fusion microbench instead: an
                          8-morsel filter→project→UDF→agg chain captured
                          fused (region_mode=on) vs unfused, asserting the
                          fused region cuts device dispatches with
                          bit-identical results
    BENCH_FUSION_ROWS=N   fusion microbench fact rows (default 64_000)
    BENCH_PALLAS=1        run the Pallas kernel-tier microbench instead:
                          grouped aggs through the blocked segment-reduce
                          kernel (int64 extremes past 2^53 included), a star
                          join-agg through the hash-probe join kernel, and
                          (with >= 8 devices — the XLA flag is forced like
                          BENCH_MESH) a hash repartition through the
                          in-kernel ICI ring permute with ZERO standalone
                          all_to_all dispatches — every section bit-checked
                          against the XLA tiers, with the derived
                          pallas_dispatch_ratio in the JSON
    BENCH_PALLAS_ROWS=N   pallas microbench fact rows (default 50_000)
    BENCH_SERVE=1         run the serving-tier bench instead: a 2-worker
                          ServingSession replaying a mixed repeat-heavy query
                          stream from >= 4 concurrent clients (CPU backend,
                          device_mode=on), reporting p50/p99 latency and
                          queries/sec, asserting bit-identical results vs
                          serial execution, prepared-cache hits > 0, and a
                          FLAT hbm_h2d byte count across the repeat phase
                          (zero re-upload — warm residency as a product)
    BENCH_SERVE_NET=1     with BENCH_SERVE=1: replay the same mixed stream
                          over the NETWORK instead — an in-process gateway
                          (daft_tpu/gateway) serves a multi-PROCESS client
                          swarm speaking the wire protocol; reports
                          p50/p99/QPS, the result-cache hit rate, and the
                          warm-vs-uncached repeat latency, asserting
                          bit-identical results vs in-process serial
                          execution, a nonzero result-cache hit rate, and
                          warm repeats faster than uncached ones
    BENCH_SERVE_WORKERS=N   session worker threads (default 2)
    BENCH_SERVE_CLIENTS=N   concurrent client threads/processes (default 4)
    BENCH_SERVE_QUERIES=N   queries per client (default 12)
    BENCH_SERVE_ROWS=N      table rows (default 200_000)
    BENCH_OOM=1           run the out-of-core capture instead: the TPC-H
                          query subset with lineitem round-tripped through
                          parquet (streaming scans) and DAFT_TPU_MEMORY_LIMIT
                          pinned to BENCH_OOM_FRACTION of the dataset bytes —
                          asserting bit-identical results vs the unbudgeted
                          run and spill_bytes > 0, recording spill/scan/
                          backpressure counters, rss_high_water_bytes and
                          host_bytes_high_water. SF100-capable: pair with
                          BENCH_SF=100 on a box whose disk fits the spill.
    BENCH_OOM_FRACTION=f  budget as a fraction of dataset bytes (default 0.1)
    BENCH_PROFILE=1       after timing, save a per-query Chrome-trace timeline
                          (explain_analyze(profile=...)) — open in Perfetto
    BENCH_PROFILE_DIR=d   where the trace JSONs land (default ".")

Compare mode (the perf regression gate — see Makefile `bench-gate`):
    python bench.py --compare OLD.json NEW.json
prints the per-query speedup table and exits non-zero when NEW regresses
any query (or the headline rows/sec) by more than 5%.

The run reports which engine paths actually executed: device_batches counts
real XLA dispatches of the TPU agg/join stages (ops/counters.py), so a number
produced entirely on host CPU is visible as device_batches == 0. The JSON also
carries a per-query millisecond breakdown (best-of-reps) — the driver's
one-line contract is preserved; the extra keys ride along.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

SF = float(os.environ.get("BENCH_SF", 1.0))
BASELINE_ROWS_PER_SEC = 50e6

# BENCH_MESH=1 on CPU CI simulates an 8-chip host; the XLA flag must be in the
# environment before the first jax backend init (imports below are lazy, so
# mutating it here still works — same trick as tests/conftest.py).
# BENCH_PALLAS gets the same 8 virtual devices so its ring-permute section
# can run the fused repartition off-silicon.
if os.environ.get("BENCH_MESH") or os.environ.get("BENCH_PALLAS"):
    _xla = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _xla:
        os.environ["XLA_FLAGS"] = (
            _xla + " --xla_force_host_platform_device_count=8").strip()
SUITE = os.environ.get("BENCH_SUITE", "tpch")
_DEFAULT_QUERIES = {"tpch": "1,3,4,5,6,10,12,14,19",
                    "tpcds": "3,7,19,33,42,52,55,56,96",
                    "ai": ""}  # the ai suite runs named pipelines, not numbered queries
if SUITE not in _DEFAULT_QUERIES:
    raise SystemExit(f"unknown BENCH_SUITE={SUITE!r} "
                     f"(expected one of {sorted(_DEFAULT_QUERIES)})")
QUERIES = [int(x) for x in os.environ.get(
    "BENCH_QUERIES", _DEFAULT_QUERIES[SUITE]).split(",") if x]
REPS = int(os.environ.get("BENCH_REPS", 5))


def _calibration_dict() -> dict:
    """The effective DAFT_TPU_COST_* calibration the capture ran under ({}
    when the process never calibrated) — every bench JSON records it so two
    captures are comparable knowing which terms priced their placements."""
    from daft_tpu.ops.costmodel import calibration_dict

    return calibration_dict()


def _placement_brief(placements: list) -> list:
    """Compact per-query placement verdicts for the bench JSON: one dict per
    decision with the chosen tier, the reason/margin, and the model-error
    ratio for dispatched stages (full per-term records stay in the process
    ledger / event log — the capture records the verdicts)."""
    out = []
    for p in placements:
        rec = {"site": p.get("site"), "chosen": p.get("chosen")}
        for k in ("reason", "margin", "error_ratio", "cached", "forced"):
            v = p.get(k)
            if v:
                rec[k] = v
        # which tiers were PRICED, with their totals — a join verdict must
        # show the mesh arm present (ms), not silently absent
        tiers = {t: round(p[t]["total"] * 1e3, 3)
                 for t in ("device", "host", "mesh")
                 if isinstance(p.get(t), dict) and "total" in p[t]}
        if tiers:
            rec["cost_ms"] = tiers
        out.append(rec)
    return out


def _derive_mesh_ratio(metric_totals: dict) -> None:
    """Attach mesh_dispatch_ratio — the mesh share of all device dispatches
    (mesh + single-chip) — wherever the raw counters landed, so a capture
    records whether the in-mesh SPMD tier engaged."""
    mesh_disp = metric_totals.get("mesh_dispatches", 0)
    single_disp = (metric_totals.get("device_grouped_batches", 0)
                   + metric_totals.get("device_stage_batches", 0))
    # recorded explicitly even at 0.0: a host-only capture states "the mesh
    # tier did not engage" instead of omitting the field
    metric_totals["mesh_dispatch_ratio"] = round(
        mesh_disp / max(mesh_disp + single_disp, 1), 4)


def _derive_fusion_ratio(metric_totals: dict) -> None:
    """Attach fused_dispatch_ratio — the mean operators amortized per device
    dispatch across the fused regions (device_region_ops_fused /
    device_region_dispatches) — so every capture records how much of each
    operator chain one RTT carried. 0.0 = no fused region dispatched."""
    disp = metric_totals.get("device_region_dispatches", 0)
    ops = metric_totals.get("device_region_ops_fused", 0)
    metric_totals["fused_dispatch_ratio"] = round(ops / max(disp, 1), 4)


def _derive_pallas_ratio(metric_totals: dict) -> None:
    """Attach pallas_dispatch_ratio — Pallas kernel launches (segment-reduce
    + hash-probe + fused ring-permute) per device stage dispatch (single-chip
    + mesh) — recorded explicitly even at 0.0 so every capture states whether
    the in-kernel tier engaged instead of omitting the field. Can exceed 1.0:
    one join stage launches one probe kernel per adjacent dim."""
    pal = (metric_totals.get("pallas_dispatches", 0)
           + metric_totals.get("pallas_probe_dispatches", 0)
           + metric_totals.get("mesh_fused_permute_dispatches", 0))
    disp = (metric_totals.get("device_grouped_batches", 0)
            + metric_totals.get("device_stage_batches", 0)
            + metric_totals.get("mesh_dispatches", 0))
    metric_totals["pallas_dispatch_ratio"] = round(pal / max(disp, 1), 4)


def _derive_shuffle_ratios(metric_totals: dict) -> None:
    """Attach the derived shuffle transport ratios wherever the raw counters
    landed, so a capture round can attribute wire savings without
    post-processing: compression = wire/logical bytes written (< 1 means the
    codec paid), overlap = overlapped transfer seconds / cumulative fetch
    seconds (> 0 means the pipelined fan-in actually overlapped transfers)."""
    wire = metric_totals.get("shuffle_wire_bytes", 0)
    logical = metric_totals.get("shuffle_logical_bytes", 0)
    # 0.0 = no shuffle crossed this capture (explicit, not omitted)
    metric_totals["shuffle_compression_ratio"] = \
        round(wire / logical, 4) if logical else 0.0
    cum = metric_totals.get("shuffle_fetch_seconds", 0.0)
    overlap = metric_totals.get("shuffle_overlap_seconds", 0.0)
    if cum:
        metric_totals["shuffle_overlap_ratio"] = round(overlap / cum, 4)


def _derive_spill_ratios(metric_totals: dict) -> None:
    """Attach the derived spill-IO overlap wherever the raw counters landed.
    The counter discipline mirrors the shuffle transport's: the cumulative
    pair (spill_write_seconds / spill_read_seconds) sums per-batch IO time
    wherever it ran, the wall pair (spill_write_wall_seconds /
    spill_read_wall_seconds) sums only the time a CONSUMER actually stalled
    on that IO, so cumulative - wall = time the pool hid behind compute.
    overlap_ratio > 0 means the async path actually overlapped; 0 with
    nonzero cumulative time means everything ran on the caller (the
    DAFT_TPU_SPILL_IO_THREADS=0 compat path, or a pool that never got
    ahead)."""
    w_cum = metric_totals.get("spill_write_seconds", 0.0)
    w_wall = metric_totals.get("spill_write_wall_seconds", 0.0)
    r_cum = metric_totals.get("spill_read_seconds", 0.0)
    r_wall = metric_totals.get("spill_read_wall_seconds", 0.0)
    overlap = max(w_cum - w_wall, 0.0) + max(r_cum - r_wall, 0.0)
    cum = w_cum + r_cum
    if cum:
        metric_totals["spill_io_overlap_seconds"] = round(overlap, 6)
        metric_totals["spill_io_overlap_ratio"] = round(overlap / cum, 4)


def shuffle_microbench() -> None:
    """2-worker socket-transport shuffle microbench (BENCH_SHUFFLE=1): a
    distributed groupby that crosses the pipelined compressed shuffle, traced
    so worker-side transport counters are re-homed into the driver registry.
    Prints the same one-JSON-line contract as the main bench."""
    import daft_tpu
    from daft_tpu.distributed.runner import DistributedRunner
    from daft_tpu import col
    from daft_tpu.observability.metrics import registry
    from daft_tpu.observability.runtime_stats import (StatsCollector,
                                                      set_collector)

    n = int(os.environ.get("BENCH_SHUFFLE_ROWS", 200_000))
    df = daft_tpu.from_pydict({
        "k": [i % 997 for i in range(n)],
        "v": [float(i % 8191) for i in range(n)],
        "w": [i % 31 for i in range(n)],
    })
    q = df.groupby("k").agg(col("v").sum().alias("s"),
                            col("w").max().alias("mw"))
    runner = DistributedRunner(num_workers=2, n_partitions=4,
                               shuffle_transport="socket")
    try:
        before = registry().snapshot()
        collector = StatsCollector()  # forces traced tasks -> shuffle counters
        elapsed = float("inf")
        for _ in range(REPS):
            set_collector(collector)
            try:
                t0 = time.perf_counter()
                rows = sum(p.num_rows for p in runner.run(q._builder))
                elapsed = min(elapsed, time.perf_counter() - t0)
            finally:
                set_collector(None)
        metric_totals = {k: v for k, v in registry().diff(before).items()
                         if k.startswith("shuffle_")}
        _derive_shuffle_ratios(metric_totals)
        _emit({
            "metric": "shuffle_microbench_rows_per_sec",
            "value": round(n / elapsed, 1),
            "unit": "rows/sec",
            "vs_baseline": round((n / elapsed) / BASELINE_ROWS_PER_SEC, 4),
            "group_rows": rows,
            "fact_rows": n,
            "reps": REPS,
            "calibration": _calibration_dict(),
            "metrics": metric_totals,
        })
    finally:
        runner.shutdown()


def fusion_microbench() -> None:
    """BENCH_FUSION=1: whole-stage fusion capture — an 8-morsel
    filter→project→UDF→agg chain on the device tier, run fused
    (region_mode=on: the UDF output plane feeds the agg program in ONE
    device dispatch per morsel) and unfused (region_mode=off: the UDF stage
    and the agg stage each dispatch per morsel). Asserts the fused capture
    cuts device dispatches with bit-identical results and emits both
    counts plus the derived fused_dispatch_ratio."""
    import numpy as np

    import daft_tpu
    from daft_tpu import col
    from daft_tpu.config import execution_config_ctx
    from daft_tpu.datatype import DataType
    from daft_tpu.ops import counters

    n = int(os.environ.get("BENCH_FUSION_ROWS", 64_000))
    rng = np.random.default_rng(0)
    data = {"v": rng.integers(1, 1000, n).tolist()}
    w = rng.standard_normal(8).astype(np.float32)
    score = daft_tpu.func(
        lambda params, x: x * params["w"].sum(),
        on_device=True, return_dtype=DataType.float32(),
        device_params=lambda: {"w": w}, device_key="bench_fusion:score")

    def q(d):
        return (d.where(col("v") > 3)
                .select((col("v") * 2).alias("x"))
                .select(score(col("x")).alias("y"))
                .agg(col("y").sum().alias("s")))

    def run(region_mode):
        counters.reset()
        best = float("inf")
        with execution_config_ctx(device_mode="on", device_min_rows=1,
                                  mesh_devices=1, region_mode=region_mode):
            d = daft_tpu.from_pydict(data).into_partitions(8)
            out = None
            for _ in range(REPS):
                counters.reset()
                t0 = time.perf_counter()
                out = q(d).to_pydict()
                best = min(best, time.perf_counter() - t0)
        # completed device executions = one finalize d2h round trip each:
        # the fused region runs the whole chain behind ONE, the unfused
        # chain pays one per operator stage (UDF run + agg run)
        disp = counters.device_stage_runs + counters.device_udf_runs
        totals = {k: v for k, v in counters.snapshot().items() if v}
        _derive_fusion_ratio(totals)
        _derive_pallas_ratio(totals)
        return out, disp, best, totals

    fused_out, fused_disp, fused_s, fused_totals = run("on")
    unfused_out, unfused_disp, unfused_s, _ = run("off")
    assert fused_out == unfused_out, \
        "fused region result diverged from the unfused chain"
    assert 0 < fused_disp < unfused_disp, \
        f"fusion did not cut dispatches ({fused_disp} vs {unfused_disp})"
    _emit({
        "metric": "fusion_microbench_rows_per_sec",
        "value": round(n / fused_s, 1),
        "unit": "rows/sec",
        "vs_baseline": round((n / fused_s) / BASELINE_ROWS_PER_SEC, 4),
        "fused_dispatches": fused_disp,
        "unfused_dispatches": unfused_disp,
        "unfused_rows_per_sec": round(n / unfused_s, 1),
        "fact_rows": n,
        "reps": REPS,
        "calibration": _calibration_dict(),
        "metrics": fused_totals,
    })


def pallas_microbench() -> None:
    """BENCH_PALLAS=1: the Pallas kernel-tier capture — three sections, all
    bit-checked against the XLA tiers (off silicon the kernels run in
    interpret mode; pallas_mode=on is the parity switch):

    1. grouped aggs through the blocked segment-reduce kernel — integer
       sums, count, and int64 min/max past 2^53 (the widened eligibility:
       refined hi/lo digit planes, exact over the full int64 range):
       pallas_dispatches > 0, bit-identical to pallas_mode=off;
    2. a star join-agg through the hash-probe join kernel (null fact keys,
       misses): pallas_probe_dispatches > 0, bit-identical to off;
    3. (>= 8 devices) a hash repartition through the in-kernel ICI ring
       permute: mesh_fused_permute_dispatches > 0 with ZERO standalone
       all_to_all dispatches, partitions identical to the classic exchange.

    CPU CI invocation (make bench-pallas):

        BENCH_PALLAS=1 JAX_PLATFORMS=cpu python bench.py
    """
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        try:
            jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
        except Exception:
            pass

    import numpy as np

    import daft_tpu
    from daft_tpu import col
    from daft_tpu.config import execution_config_ctx
    from daft_tpu.ops import counters

    n = int(os.environ.get("BENCH_PALLAS_ROWS", 50_000))
    rng = np.random.default_rng(7)
    big = 1 << 53
    fact = daft_tpu.from_pydict({
        "fk": [int(x) if x % 37 else None for x in rng.integers(0, 500, n)],
        "q": rng.integers(0, 50, n).tolist(),
        "big": (big + rng.integers(0, 1000, n)).tolist(),
    }).collect()
    dim = daft_tpu.from_pydict({
        "dk": list(range(500)),
        "grp": [f"g{i % 7}" for i in range(500)],
        "w": [float(i % 13) for i in range(500)],
    }).collect()

    def q_grouped():
        return (fact.groupby("fk")
                .agg(col("q").sum().alias("sq"),
                     col("q").count().alias("cq"),
                     col("big").min().alias("lo"),
                     col("big").max().alias("hi"))
                .sort("fk").collect())

    def q_join():
        return (fact.join(dim, left_on="fk", right_on="dk")
                .groupby("grp")
                .agg(col("q").sum().alias("sq"),
                     col("w").sum().alias("sw"))
                .sort("grp").collect())

    shapes = {"grouped_kernel": q_grouped, "probe_join": q_join}
    ref = {}
    with execution_config_ctx(device_mode="on", device_min_rows=1,
                              mesh_devices=1, pallas_mode="off"):
        for name, qf in shapes.items():
            ref[name] = qf().to_pydict()
    counters.reset()
    per_query = {name: float("inf") for name in shapes}
    out = {}
    with execution_config_ctx(device_mode="on", device_min_rows=1,
                              mesh_devices=1, pallas_mode="on"):
        for qf in shapes.values():
            qf().to_pydict()  # warmup: kernel compiles + plane residency
        elapsed = float("inf")
        for _ in range(REPS):
            t0 = time.perf_counter()
            for name, qf in shapes.items():
                tq = time.perf_counter()
                out[name] = qf().to_pydict()
                per_query[name] = min(per_query[name],
                                      time.perf_counter() - tq)
            elapsed = min(elapsed, time.perf_counter() - t0)
    snap = counters.snapshot()
    assert snap.get("pallas_dispatches", 0) > 0, \
        "segment-reduce kernel never dispatched — not a pallas capture"
    assert snap.get("pallas_probe_dispatches", 0) > 0, \
        "hash-probe join kernel never dispatched — not a pallas capture"
    assert snap.get("pallas_fallbacks", 0) == 0, \
        f"kernel tier latched a fallback: {counters.rejections}"
    for name in shapes:
        assert out[name] == ref[name], \
            f"{name} diverged from the XLA tier under pallas_mode=on"

    fused_metrics: dict = {}
    if len(jax.devices()) >= 8:
        rep_rows = min(n, 40_000)
        rep_df = daft_tpu.from_pydict({
            "k": rng.integers(0, 997, rep_rows).tolist(),
            "v": (rng.random(rep_rows) * 100).tolist(),
        })
        with execution_config_ctx(device_mode="on", mesh_devices=8,
                                  device_min_rows=1, pallas_mode="off"):
            classic = rep_df.repartition(8, col("k")).collect()
        counters.reset()
        with execution_config_ctx(device_mode="on", mesh_devices=8,
                                  device_min_rows=1, pallas_mode="on"):
            fused = rep_df.repartition(8, col("k")).collect()
        assert counters.mesh_alltoall_dispatches == 0, \
            "fused repartition still issued standalone all_to_all dispatches"
        assert counters.mesh_fused_permute_dispatches > 0, \
            "in-kernel ring permute never dispatched"
        from daft_tpu.core.recordbatch import RecordBatch as _RB

        def _pd(p):
            bs = [b for b in p.batches if b.num_rows]
            if not bs:
                return {}
            b = bs[0] if len(bs) == 1 else _RB.concat(bs)
            return {c: b.get_column(c).to_pylist() for c in ("k", "v")}

        for cp, fp in zip(classic._result, fused._result):
            assert _pd(cp) == _pd(fp), \
                "ring-permute partitions diverge from the classic exchange"
        fused_metrics = {
            "mesh_fused_permute_dispatches":
                int(counters.mesh_fused_permute_dispatches),
            "fused_repartition_alltoall_dispatches": 0,
        }

    metric_totals = {k: v for k, v in snap.items() if v}
    _derive_pallas_ratio(metric_totals)
    metric_totals.update(fused_metrics)
    rows_per_sec = n * len(shapes) / elapsed
    _emit({
        "metric": "pallas_microbench_rows_per_sec",
        "value": round(rows_per_sec, 1),
        "unit": "rows/sec",
        "vs_baseline": round(rows_per_sec / BASELINE_ROWS_PER_SEC, 4),
        "per_query_ms": {name: round(per_query[name] * 1000, 1)
                         for name in shapes},
        "pallas_dispatch_ratio": metric_totals["pallas_dispatch_ratio"],
        "bit_identical": True,
        "ring_permute_checked": bool(fused_metrics),
        "fact_rows": n,
        "reps": REPS,
        "calibration": _calibration_dict(),
        "metrics": metric_totals,
    })


def mesh_microbench() -> None:
    """BENCH_MESH=1: the multi-chip capture — three sections, all checked
    against the host path:

    1. a TPC-H-shaped groupby executed with its device stage sharded across
       8 devices via shard_map, fed by the streaming morsel/coalescer path,
       BIT-IDENTICAL vs single-chip and host (quantity aggregates are
       integer-valued, so every f64 partial is exact in any reduction order);
    2. real TPC-H JOIN queries (q12 grouped join-agg, q14 ungrouped) through
       the mesh join tier (ops/mesh_stage.MeshJoin*Run): mesh_dispatches > 0
       with q12 bit-identical (integer 0/1 sums — exact in any order) and
       q14 within float tolerance; the run is priced under
       DAFT_TPU_PLACEMENT_PRICE_FORCED so every join verdict carries ALL
       THREE tiers' CostBreakdowns (mesh arm priced, not absent);
    3. an intra-host hash repartition routed over ICI (jax.lax.all_to_all)
       instead of the host shuffle — bit-identical partitions with ZERO
       shuffle wire bytes while the exchange moved real plane bytes
       (asserted: wire < ici — the co-located-worker wire-byte drop).

    CPU CI invocation (the MULTICHIP harness environment):

        BENCH_MESH=1 JAX_PLATFORMS=cpu \\
        XLA_FLAGS=--xla_force_host_platform_device_count=8 python bench.py
    """
    # this environment may pre-import jax pinned to a tunneled backend; route
    # to the env-requested platform via jax.config like tests/conftest.py
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        try:
            jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
        except Exception:
            pass

    import daft_tpu
    from daft_tpu import col
    from daft_tpu.config import execution_config_ctx
    from daft_tpu.ops import counters
    from benchmarking.tpch.datagen import load_dataframes

    tables = {k: v.collect() for k, v in load_dataframes(sf=SF, seed=0).items()}
    lineitem = tables["lineitem"]
    n = lineitem.count_rows()

    def q():
        return (lineitem
                .groupby("l_returnflag", "l_linestatus")
                .agg(col("l_quantity").sum().alias("sum_qty"),
                     col("l_quantity").mean().alias("avg_qty"),
                     col("l_quantity").min().alias("min_qty"),
                     col("l_quantity").max().alias("max_qty"),
                     col("l_quantity").count().alias("count_order"))
                .sort("l_returnflag", "l_linestatus"))

    counters.reset()
    with execution_config_ctx(device_mode="on", mesh_devices=8,
                              device_min_rows=1):
        q().to_pydict()  # warmup: compile + shard-resident planes
        h2d_warm = counters.snapshot().get("hbm_h2d_bytes", 0)
        elapsed = float("inf")
        for _ in range(REPS):
            t0 = time.perf_counter()
            mesh_out = q().to_pydict()
            elapsed = min(elapsed, time.perf_counter() - t0)
        h2d_after = counters.snapshot().get("hbm_h2d_bytes", 0)
    mesh_runs = counters.mesh_grouped_runs
    mesh_disp = counters.mesh_dispatches
    assert mesh_runs > 0 and mesh_disp > 0, \
        "mesh path never executed — BENCH_MESH capture is not a mesh capture"
    metric_totals = {k: v for k, v in counters.snapshot().items() if v}
    _derive_mesh_ratio(metric_totals)
    _derive_fusion_ratio(metric_totals)
    _derive_pallas_ratio(metric_totals)
    # repeat-query residency: sharded planes resident => h2d flat after warmup
    metric_totals["mesh_repeat_h2d_bytes"] = int(h2d_after - h2d_warm)
    assert metric_totals["mesh_repeat_h2d_bytes"] == 0, \
        "repeat mesh query re-uploaded bytes — sharded residency broken"

    with execution_config_ctx(device_mode="on", mesh_devices=1,
                              device_min_rows=1):
        single_out = q().to_pydict()
    with execution_config_ctx(device_mode="off"):
        host_out = q().to_pydict()
    if not (mesh_out == single_out == host_out):
        raise AssertionError(
            "mesh result differs from single-chip/host — parity broken")

    # ---- section 2: TPC-H join queries through the mesh join tier ----------
    from benchmarking.tpch.queries import ALL_QUERIES
    from daft_tpu.observability import placement as _placement

    join_queries = [12, 14]  # grouped + ungrouped star shapes
    os.environ["DAFT_TPU_PLACEMENT_PRICE_FORCED"] = "1"
    try:
        with execution_config_ctx(device_mode="off"):
            join_host = {q: ALL_QUERIES[q](tables).to_pydict()
                         for q in join_queries}
        join_placement = {}
        join_ms = {}
        with execution_config_ctx(device_mode="on", mesh_devices=8,
                                  device_min_rows=1):
            # warmup pass first (main()'s discipline): the timed + scoped
            # runs below must not embed jit-compile time — these forced
            # records feed the calibrate tool, and compile seconds counted
            # as dispatch would inflate the mesh term suggestions
            for qi in join_queries:
                ALL_QUERIES[qi](tables).to_pydict()
            join_disp_before = counters.mesh_dispatches
            join_mesh = {}
            for qi in join_queries:
                with _placement.query_scope() as pscope:
                    t0 = time.perf_counter()
                    join_mesh[qi] = ALL_QUERIES[qi](tables).to_pydict()
                    join_ms[qi] = round((time.perf_counter() - t0) * 1000, 1)
                join_placement[qi] = _placement_brief(pscope.to_dicts())
    finally:
        os.environ.pop("DAFT_TPU_PLACEMENT_PRICE_FORCED", None)
    mesh_join_disp = counters.mesh_dispatches - join_disp_before
    assert counters.mesh_join_runs > 0 and mesh_join_disp > 0, \
        "mesh join tier never dispatched — the join wiring is not engaged"
    assert join_mesh[12] == join_host[12], \
        "q12 mesh join diverged from host (integer sums must be exact)"
    _q14m = join_mesh[14]["promo_revenue"][0]
    _q14h = join_host[14]["promo_revenue"][0]
    assert abs(_q14m - _q14h) <= 1e-9 * max(abs(_q14h), 1.0), \
        f"q14 mesh join outside float tolerance ({_q14m} vs {_q14h})"
    # the join verdicts must carry the mesh arm: at least one record with
    # a priced mesh breakdown (forced pricing populates all three tiers)
    _rec = [r for r in _placement.ledger().snapshot()
            if r.get("site") in ("join agg", "join topn") and r.get("mesh")
            and r.get("device") and r.get("host")]
    assert _rec, "join placement records missing the mesh CostBreakdown"
    metric_totals.update({k: v for k, v in counters.snapshot().items() if v})
    _derive_mesh_ratio(metric_totals)
    _derive_fusion_ratio(metric_totals)
    _derive_pallas_ratio(metric_totals)

    # ---- section 3: intra-host repartition over ICI ------------------------
    from daft_tpu.observability.metrics import registry as _registry

    rep_rows = 200_000
    rep_df = daft_tpu.from_pydict({
        "k": [i % 997 for i in range(rep_rows)],
        "v": [float(i % 8191) for i in range(rep_rows)],
    })
    with execution_config_ctx(device_mode="off"):
        host_parts = rep_df.repartition(8, col("k")).collect()
    wire_before = _registry().get("shuffle_wire_bytes")
    ici_before = _registry().get("mesh_alltoall_ici_bytes")
    with execution_config_ctx(device_mode="on", mesh_devices=8,
                              device_min_rows=1):
        mesh_parts = rep_df.repartition(8, col("k")).collect()
    wire_delta = _registry().get("shuffle_wire_bytes") - wire_before
    ici_delta = _registry().get("mesh_alltoall_ici_bytes") - ici_before
    assert ici_delta > 0, "all_to_all repartition never engaged"
    assert wire_delta < ici_delta, \
        "co-located repartition still paid shuffle wire bytes"
    from daft_tpu.core.recordbatch import RecordBatch as _RB

    def _part_dict(p):
        bs = [b for b in p.batches if b.num_rows]
        if not bs:
            return {}
        b = bs[0] if len(bs) == 1 else _RB.concat(bs)
        return {c: b.get_column(c).to_pylist() for c in ("k", "v")}

    for hp, mp in zip(host_parts._result, mesh_parts._result):
        assert _part_dict(hp) == _part_dict(mp), \
            "ICI repartition partitions diverge from the host shuffle"
    metric_totals["mesh_alltoall_ici_bytes"] = int(ici_delta)
    metric_totals["shuffle_wire_bytes_colocated"] = int(wire_delta)

    _emit({
        "metric": f"tpch_sf{SF}_mesh_groupby_rows_per_sec",
        "value": round(n / elapsed, 1),
        "unit": "rows/sec",
        "vs_baseline": round((n / elapsed) / BASELINE_ROWS_PER_SEC, 4),
        "mesh_devices": len(jax.devices()),
        "bit_identical": True,
        "mesh_join_dispatches": int(mesh_join_disp),
        "per_query_ms": {f"q{qi}": join_ms[qi] for qi in join_queries},
        "placement": {f"q{qi}": v for qi, v in sorted(join_placement.items())
                      if v},
        "fact_rows": n,
        "reps": REPS,
        "calibration": _calibration_dict(),
        "metrics": metric_totals,
    })


def serve_bench() -> None:
    """BENCH_SERVE=1: the serving-tier capture (see module docstring). The
    JSON keeps the capture-record shape bench.py --compare understands:
    per_query_ms carries each query SHAPE's p99 so a serve capture gates
    against a prior one exactly like the TPC-H per-query table."""
    import statistics
    import threading

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        try:
            jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
        except Exception:
            pass

    import daft_tpu
    from daft_tpu import col
    from daft_tpu.config import execution_config_ctx
    from daft_tpu.observability.metrics import registry
    from daft_tpu.serving import ServingSession

    workers = int(os.environ.get("BENCH_SERVE_WORKERS", 2))
    clients = int(os.environ.get("BENCH_SERVE_CLIENTS", 4))
    per_client = int(os.environ.get("BENCH_SERVE_QUERIES", 12))
    n = int(os.environ.get("BENCH_SERVE_ROWS", 200_000))

    df = daft_tpu.from_pydict({
        "k": [i % 601 for i in range(n)],
        "v": [float(i % 8191) for i in range(n)],
        "w": [i % 97 for i in range(n)],
    })
    # the mixed stream: three shapes, replayed identically (repeat-heavy —
    # the marquee serving scenario: many tenants hammering a few prepared
    # queries over one warm table)
    shapes = {
        "groupby_sum": lambda: df.groupby("k").agg(
            col("v").sum().alias("s"), col("w").max().alias("mw")).sort("k"),
        "filter_sum": lambda: df.where(col("w") > 48).agg(
            col("v").sum().alias("s")),
        "groupby_minmax": lambda: df.groupby("w").agg(
            col("v").min().alias("lo"), col("v").max().alias("hi")).sort("w"),
    }
    with execution_config_ctx(device_mode="on", device_min_rows=1,
                              mesh_devices=1):
        ref = {name: q().to_pydict() for name, q in shapes.items()}
        sess = ServingSession(max_concurrent=workers)
        try:
            # warm phase: each shape once through the session — plans enter
            # the prepared cache, column planes enter HBM residency
            for name, q in shapes.items():
                assert sess.run(q()) is not None
            h2d_warm = registry().get("hbm_h2d_bytes")
            reg_before = registry().snapshot()
            lat: dict = {name: [] for name in shapes}
            mismatches: list = []
            lock = threading.Lock()

            def client(cid: int) -> None:
                names = list(shapes)
                for i in range(per_client):
                    name = names[(cid + i) % len(names)]
                    t0 = time.perf_counter()
                    fut = sess.submit(shapes[name](), tenant=f"client-{cid}")
                    out = fut.to_pydict()
                    dt = time.perf_counter() - t0
                    with lock:
                        lat[name].append(dt)
                        if out != ref[name]:
                            mismatches.append(name)

            threads = [threading.Thread(target=client, args=(c,))
                       for c in range(clients)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            elapsed = time.perf_counter() - t0
            h2d_after = registry().get("hbm_h2d_bytes")
            diff = registry().diff(reg_before)
        finally:
            sess.close()

    assert not mismatches, f"serve results diverged from serial: {mismatches}"
    total = clients * per_client
    all_lat = sorted(x for xs in lat.values() for x in xs)

    def pct(xs, q):
        return xs[min(int(q * len(xs)), len(xs) - 1)] if xs else 0.0

    prepared_hits = int(diff.get("serve_prepared_hits", 0))
    assert prepared_hits > 0, "no prepared-cache hits in a repeat-heavy stream"
    repeat_h2d = int(h2d_after - h2d_warm)
    assert repeat_h2d == 0, \
        f"repeat queries re-uploaded {repeat_h2d} bytes — warm residency broken"
    metric_totals = {k: v for k, v in diff.items()
                     if k.startswith(("serve_", "admission_", "hbm_",
                                      "device_", "dispatch_"))}
    metric_totals["serve_repeat_h2d_bytes"] = repeat_h2d
    rows_per_sec = n * total / elapsed
    _emit({
        "metric": "serve_queries_per_sec",
        "value": round(total / elapsed, 2),
        "unit": "queries/sec",
        "vs_baseline": round(rows_per_sec / BASELINE_ROWS_PER_SEC, 4),
        "p50_ms": round(pct(all_lat, 0.5) * 1000, 1),
        "p99_ms": round(pct(all_lat, 0.99) * 1000, 1),
        "per_query_ms": {name: round(pct(sorted(xs), 0.99) * 1000, 1)
                         for name, xs in lat.items()},
        "mean_ms": round(statistics.mean(all_lat) * 1000, 1) if all_lat else 0,
        "queries": total,
        "clients": clients,
        "serve_workers": workers,
        "bit_identical": True,
        "fact_rows": n,
        "calibration": _calibration_dict(),
        "metrics": metric_totals,
    })


def _net_swarm_client(host: str, port: int, cid: int, per_client: int,
                      sqls: dict, ref: dict, outq, barrier) -> None:
    """One swarm process: prepare every shape once, then replay the mixed
    stream by handle, timing execute+fetch end to end over the wire and
    checking every result against the serial reference. Runs in a CHILD process
    (real sockets, real serialization boundary — nothing shared with the
    server but the wire)."""
    from daft_tpu.gateway import GatewayClient

    results = []
    mismatches = []
    with GatewayClient(host, port, tenant=f"client-{cid}",
                       connect_retries=10) as c:
        handles = {name: c.prepare(s) for name, s in sqls.items()}
        names = list(sqls)
        # interpreter startup + prepare round trips stay OUT of the timed
        # window: every client holds here until the whole swarm is connected
        barrier.wait(timeout=120)
        for i in range(per_client):
            name = names[(cid + i) % len(names)]
            t0 = time.perf_counter()
            qid = c.execute(handle=handles[name])
            out = c.fetch_pydict(qid)
            dt = time.perf_counter() - t0
            if out != ref[name]:
                mismatches.append(name)
            results.append((name, dt, c.last_fetch.get("source", "")))
    outq.put((cid, results, mismatches))


def serve_bench_net() -> None:
    """BENCH_SERVE=1 BENCH_SERVE_NET=1: the gateway capture — the serve
    bench's mixed repeat-heavy stream replayed over the wire protocol by a
    multi-process client swarm against an in-process GatewayServer. Keeps
    the capture-record shape --compare understands (per_query_ms = per-shape
    wire p99). Extra headline columns: result_cache_hit_rate and the
    uncached-vs-warm repeat latency (the result cache's visible win)."""
    import multiprocessing as mp
    import statistics

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        try:
            jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
        except Exception:
            pass

    import daft_tpu
    from daft_tpu.config import execution_config_ctx
    from daft_tpu.gateway import GatewayClient, GatewayServer
    from daft_tpu.observability.metrics import registry

    workers = int(os.environ.get("BENCH_SERVE_WORKERS", 2))
    clients = int(os.environ.get("BENCH_SERVE_CLIENTS", 4))
    per_client = int(os.environ.get("BENCH_SERVE_QUERIES", 12))
    n = int(os.environ.get("BENCH_SERVE_ROWS", 200_000))

    df = daft_tpu.from_pydict({
        "k": [i % 601 for i in range(n)],
        "v": [float(i % 8191) for i in range(n)],
        "w": [i % 97 for i in range(n)],
    })
    # the serve bench's three shapes, as the SQL the wire carries
    sqls = {
        "groupby_sum": "SELECT k, SUM(v) AS s, MAX(w) AS mw FROM t "
                       "GROUP BY k ORDER BY k",
        "filter_sum": "SELECT SUM(v) AS s FROM t WHERE w > 48",
        "groupby_minmax": "SELECT w, MIN(v) AS lo, MAX(v) AS hi FROM t "
                          "GROUP BY w ORDER BY w",
    }
    with execution_config_ctx(device_mode="on", device_min_rows=1,
                              mesh_devices=1):
        # serial in-process reference: what every wire result must equal
        ref = {name: daft_tpu.sql(s, t=df).to_pydict()
               for name, s in sqls.items()}
        reg_before = registry().snapshot()
        with GatewayServer(tables={"t": df},
                           max_concurrent=workers) as srv:
            # cold phase: one wire round per shape from the bench process —
            # these EXECUTE (result-cache misses) and measure the uncached
            # repeat latency the warm swarm is judged against
            cold_lat: list = []
            with GatewayClient(srv.host, srv.port, tenant="bench-cold") as c:
                for name, s in sqls.items():
                    t0 = time.perf_counter()
                    out = c.query(s)
                    cold_lat.append(time.perf_counter() - t0)
                    assert out == ref[name], f"cold {name} diverged"
                    assert c.last_source == "executed", \
                        f"cold {name} unexpectedly served from {c.last_source}"
            # warm phase: the multi-process swarm replays by prepared handle.
            # spawn, not fork: the bench process is multithreaded (gateway
            # accept loop, serving workers, JAX internals) and a forked child
            # can inherit a held lock; spawned clients import fresh and touch
            # nothing but the socket
            ctx = mp.get_context("spawn")
            outq = ctx.Queue()
            barrier = ctx.Barrier(clients + 1)
            procs = [ctx.Process(target=_net_swarm_client,
                                 args=(srv.host, srv.port, cid, per_client,
                                       sqls, ref, outq, barrier))
                     for cid in range(clients)]
            for p in procs:
                p.start()
            barrier.wait(timeout=120)
            t0 = time.perf_counter()
            reports = [outq.get(timeout=300) for _ in procs]
            for p in procs:
                p.join(timeout=60)
            elapsed = time.perf_counter() - t0
            stats = None
            with GatewayClient(srv.host, srv.port, tenant="bench-stats") as c:
                stats = c.stats()
        diff = registry().diff(reg_before)

    mismatches = sorted({m for _cid, _res, ms in reports for m in ms})
    assert not mismatches, \
        f"wire results diverged from in-process serial: {mismatches}"
    lat: dict = {name: [] for name in sqls}
    warm_cached: list = []
    for _cid, results, _ms in reports:
        for name, dt, source in results:
            lat[name].append(dt)
            if source in ("result_cache", "checkpoint"):
                warm_cached.append(dt)
    hits = int(diff.get("result_cache_hits", 0))
    misses = int(diff.get("result_cache_misses", 0))
    hit_rate = hits / max(hits + misses, 1)
    assert hits > 0, "no result-cache hits in a repeat-heavy wire stream"
    uncached_ms = statistics.mean(cold_lat) * 1000
    warm_ms = (statistics.mean(warm_cached) * 1000 if warm_cached
               else uncached_ms)
    assert warm_ms < uncached_ms, \
        (f"warm repeats ({warm_ms:.1f} ms) not faster than uncached "
         f"({uncached_ms:.1f} ms) — result cache not paying for itself")
    total = clients * per_client
    all_lat = sorted(x for xs in lat.values() for x in xs)

    def pct(xs, q):
        return xs[min(int(q * len(xs)), len(xs) - 1)] if xs else 0.0

    metric_totals = {k: v for k, v in diff.items()
                     if k.startswith(("gateway_", "result_cache_", "serve_",
                                      "admission_", "hbm_", "device_"))}
    rows_per_sec = n * total / elapsed
    _emit({
        "metric": "serve_net_queries_per_sec",
        "value": round(total / elapsed, 2),
        "unit": "queries/sec",
        "vs_baseline": round(rows_per_sec / BASELINE_ROWS_PER_SEC, 4),
        "p50_ms": round(pct(all_lat, 0.5) * 1000, 1),
        "p99_ms": round(pct(all_lat, 0.99) * 1000, 1),
        "per_query_ms": {name: round(pct(sorted(xs), 0.99) * 1000, 1)
                         for name, xs in lat.items()},
        "mean_ms": round(statistics.mean(all_lat) * 1000, 1) if all_lat else 0,
        "result_cache_hit_rate": round(hit_rate, 4),
        "uncached_repeat_ms": round(uncached_ms, 1),
        "warm_repeat_ms": round(warm_ms, 1),
        "result_cache": (stats or {}).get("result_cache", {}),
        "queries": total,
        "clients": clients,
        "serve_workers": workers,
        "bit_identical": True,
        "fact_rows": n,
        "calibration": _calibration_dict(),
        "metrics": metric_totals,
    })


def ai_bench() -> None:
    """BENCH_SUITE=ai: the multimodal/AI pipeline capture on the device-UDF
    tier (ops/udf_stage.py) — a seeded deterministic encoder runs scan text
    -> embed -> zero-shot classify -> groupby count through the staged
    device path, asserting:

    - BIT-IDENTICAL results vs the host-UDF path (the classify pipeline is
      argmax-decoded, so it is robust to coalescing's batch-shape changes;
      the embed pipeline compares exactly on the single-dispatch shape);
    - ZERO repeat weight re-upload (device_udf_weight_h2d_bytes flat across
      the timed reps — weights are residency-managed, not per-query);
    - device_udf_dispatches > 0 with coalesced super-batches
      (coalesce_morsels_in > dispatch_coalesced over a multi-batch scan).

    Reports rows/sec + per_query_ms in the --compare-compatible shape. CPU
    CI invocation: ``BENCH_SUITE=ai JAX_PLATFORMS=cpu python bench.py``
    (make bench-ai)."""
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        try:
            jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
        except Exception:
            pass

    import daft_tpu
    from daft_tpu import col
    from daft_tpu.config import execution_config_ctx
    from daft_tpu.functions.ai import classify_text, embed_text
    from daft_tpu.ops import counters

    n = int(os.environ.get("BENCH_AI_ROWS", 4096))
    batch_rows = int(os.environ.get("BENCH_AI_BATCH_ROWS", 512))
    labels = ["alpha topic", "beta topic", "gamma topic", "delta topic"]
    words = [f"term{i}" for i in range(31)]
    texts = [" ".join(words[(i * k) % len(words)] for k in (1, 3, 7))
             for i in range(n)]
    base = daft_tpu.from_pydict({"id": list(range(n)), "text": texts})
    # multi-batch scan: the coalescer must see a morsel STREAM, not one slab
    df = base.into_batches(batch_rows).collect()

    def q_embed():
        return df.select(col("id"),
                         embed_text(col("text"), provider="jax").alias("e"))

    def q_classify():
        return (df.select(classify_text(col("text"), labels,
                                        provider="jax").alias("label"))
                  .groupby("label").agg(col("label").count().alias("n"))
                  .sort("label"))

    shapes = {"embed": q_embed, "classify_groupby": q_classify}
    with execution_config_ctx(device_mode="on", device_min_rows=1,
                              mesh_devices=1):
        counters.reset()
        # warmup: model load + weight h2d + jit compiles
        for q in shapes.values():
            q().to_pydict()
        w_warm = counters.device_udf_weight_h2d_bytes
        per_query = {name: float("inf") for name in shapes}
        dev_out = {}
        elapsed = float("inf")
        for _ in range(REPS):
            t0 = time.perf_counter()
            for name, q in shapes.items():
                tq = time.perf_counter()
                dev_out[name] = q().to_pydict()
                per_query[name] = min(per_query[name],
                                      time.perf_counter() - tq)
            elapsed = min(elapsed, time.perf_counter() - t0)
        repeat_weight_h2d = counters.device_udf_weight_h2d_bytes - w_warm
        metric_totals = {k: v for k, v in counters.snapshot().items() if v}
        per_query_profile = _profile_pass(
            {name: (lambda q=q: q().to_pydict()) for name, q in shapes.items()})
    assert counters.device_udf_dispatches > 0, \
        "device-UDF tier never dispatched — BENCH_SUITE=ai is not an ai capture"
    assert repeat_weight_h2d == 0, \
        f"repeat queries re-uploaded {repeat_weight_h2d} weight bytes — " \
        "residency-managed weights broken"
    morsels_in = metric_totals.get("coalesce_morsels_in", 0)
    coalesced = metric_totals.get("dispatch_coalesced", 0)
    assert morsels_in > coalesced > 0, \
        f"no coalesced super-batches ({morsels_in} morsels -> {coalesced} dispatches)"

    with execution_config_ctx(device_mode="off"):
        host_out = {name: q().to_pydict() for name, q in shapes.items()}
    # classify is argmax-decoded -> exact across batch shapes; embed floats
    # are exact only when dispatch shapes match, so gate on classify
    assert dev_out["classify_groupby"] == host_out["classify_groupby"], \
        "device classify pipeline diverged from the host-UDF path"
    embed_ok = dev_out["embed"] == host_out["embed"]

    metric_totals["ai_repeat_weight_h2d_bytes"] = int(repeat_weight_h2d)
    from daft_tpu.device.residency import manager as _residency

    _res = _residency().stats()
    for k in ("hbm_bytes_resident", "hbm_bytes_high_water", "hbm_entries"):
        metric_totals[k] = _res[k]

    rows_per_sec = n * len(shapes) / elapsed
    _emit({
        "metric": f"ai_{len(shapes)}q_rows_per_sec",
        "value": round(rows_per_sec, 1),
        "unit": "rows/sec",
        "vs_baseline": round(rows_per_sec / BASELINE_ROWS_PER_SEC, 4),
        "device_batches": int(metric_totals.get("device_udf_dispatches", 0)),
        "per_query_ms": {name: round(per_query[name] * 1000, 1)
                         for name in shapes},
        "per_query_profile": per_query_profile,
        "bit_identical": True,
        "embed_bit_identical": bool(embed_ok),
        "labels": len(labels),
        "fact_rows": n,
        "reps": REPS,
        "calibration": _calibration_dict(),
        "metrics": metric_totals,
    })


def _rss_high_water_bytes() -> int:
    """Process RSS high-water via getrusage (ru_maxrss is KiB on Linux,
    bytes on macOS); 0 where the platform doesn't report it."""
    try:
        import resource

        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    except Exception:
        return 0  # platform without getrusage: the field is advisory
    if sys.platform == "darwin":
        return int(peak)
    return int(peak) * 1024


def oom_bench() -> None:
    """BENCH_OOM=1: the out-of-core capture (see module docstring). The
    dataset's fact table round-trips through parquet so the scans exercise
    the StreamingScan split/backpressure path, the host budget pins to a
    fraction of the measured dataset bytes, and the budgeted run must be
    bit-identical to the unbudgeted one with spill counters > 0. JSON keeps
    the capture-record shape bench.py --compare understands."""
    import tempfile

    import daft_tpu
    from benchmarking.tpch.datagen import load_dataframes
    from benchmarking.tpch.queries import ALL_QUERIES
    from daft_tpu.config import execution_config_ctx
    from daft_tpu.execution import memory as _mem
    from daft_tpu.observability.metrics import registry

    frac = float(os.environ.get("BENCH_OOM_FRACTION", 0.1))
    tables = {k: v.collect() for k, v in load_dataframes(sf=SF, seed=0).items()}
    total_bytes = sum(p.size_bytes()
                      for df in tables.values()
                      for p in df.iter_partitions())
    budget = max(int(total_bytes * frac), 1 << 20)

    with tempfile.TemporaryDirectory(prefix="daft_tpu_bench_oom_") as d:
        # the fact table comes back through parquet: streaming scans with
        # row-group split planning feed every query's pipeline
        tables["lineitem"].write_parquet(os.path.join(d, "lineitem"))
        tables["lineitem"] = daft_tpu.read_parquet(
            os.path.join(d, "lineitem", "*.parquet"))

        with execution_config_ctx(memory_limit_bytes=0, device_mode="off"):
            expected = {q: ALL_QUERIES[q](tables).to_pydict() for q in QUERIES}

        _mem.reset_counters()
        _mem.manager().clear()
        reg_before = registry().snapshot()
        per_query = {q: float("inf") for q in QUERIES}
        elapsed = float("inf")
        with execution_config_ctx(memory_limit_bytes=budget, device_mode="off"):
            mismatches = []
            with _mem.manager().query_scope() as scope:
                for _ in range(REPS):
                    t0 = time.perf_counter()
                    for q in QUERIES:
                        tq = time.perf_counter()
                        out = ALL_QUERIES[q](tables).to_pydict()
                        per_query[q] = min(per_query[q], time.perf_counter() - tq)
                        if out != expected[q]:
                            mismatches.append(q)
                    elapsed = min(elapsed, time.perf_counter() - t0)
        diff = registry().diff(reg_before)
        n_lineitem = tables["lineitem"].count_rows()
        # per-operator attribution pass under the same budget, AFTER the
        # registry diff so the profile run's own spill/scan deltas cannot
        # inflate the capture-level totals above
        with execution_config_ctx(memory_limit_bytes=budget, device_mode="off"):
            per_query_profile = _profile_pass(
                {f"q{q}": (lambda q=q: ALL_QUERIES[q](tables).to_pydict())
                 for q in QUERIES})

        # sync-vs-async spill A/B on the same dataset (still inside the
        # tempdir: the leg's scan goes through the parquet round-trip too)
        spill_ab = _spill_ab(tables, total_bytes)

    assert not mismatches, \
        f"budgeted results diverged from unbudgeted: {sorted(set(mismatches))}"
    assert diff.get("spill_bytes", 0) > 0, \
        "budget never triggered a spill — BENCH_OOM capture is not an " \
        "out-of-core capture (lower BENCH_OOM_FRACTION or raise BENCH_SF)"

    metric_totals = {k: int(v) if float(v).is_integer() else v
                     for k, v in diff.items()
                     if k.startswith(("spill_", "scan_", "host_"))}
    _derive_spill_ratios(metric_totals)
    metric_totals["host_bytes_high_water"] = _mem.manager().high_water_bytes()
    metric_totals["host_scope_peak_bytes"] = scope.peak_bytes()
    metric_totals["rss_high_water_bytes"] = _rss_high_water_bytes()
    rows_per_sec = n_lineitem * len(QUERIES) / elapsed
    _emit({
        "metric": f"tpch_sf{SF}_oom_{len(QUERIES)}q_rows_per_sec",
        "value": round(rows_per_sec, 1),
        "unit": "rows/sec",
        "vs_baseline": round(rows_per_sec / BASELINE_ROWS_PER_SEC, 4),
        "per_query_ms": {f"q{q}": round(per_query[q] * 1000, 1) for q in QUERIES},
        "per_query_profile": per_query_profile,
        "bit_identical": True,
        "memory_limit_bytes": budget,
        "dataset_bytes": int(total_bytes),
        "rss_high_water_bytes": metric_totals["rss_high_water_bytes"],
        "host_bytes_high_water": metric_totals["host_bytes_high_water"],
        "fact_rows": n_lineitem,
        "sf": SF,
        "reps": REPS,
        "calibration": _calibration_dict(),
        "metrics": metric_totals,
        "spill_ab": spill_ab,
    })


def _spill_ab(tables: dict, total_bytes: float) -> dict:
    """The sync-vs-async spill A/B that rides inside the BENCH_OOM capture:
    the same 3-column external sort under the same 1% budget, once with
    DAFT_TPU_SPILL_IO_THREADS=0 (compat path — every compression+write and
    every decode on the caller's thread) and once with the async default.
    Both legs must be bit-identical; each leg records its spill counter
    deltas with the derived overlap attached, so the capture shows WHERE
    the wall moved (write stalls shrinking, overlap seconds appearing), not
    just a speedup number."""
    from daft_tpu.config import execution_config_ctx
    from daft_tpu.execution import memory as _mem
    from daft_tpu.observability.metrics import registry

    budget = max(int(total_bytes * 0.01), 1 << 20)
    df = tables["lineitem"]
    keys = ["l_extendedprice", "l_orderkey", "l_linenumber"]

    def leg(**overrides):
        _mem.reset_counters()
        _mem.manager().clear()
        before = registry().snapshot()
        with execution_config_ctx(memory_limit_bytes=budget,
                                  device_mode="off", **overrides):
            t0 = time.perf_counter()
            out = df.sort(keys).to_pydict()
            wall = time.perf_counter() - t0
        metrics = {k: int(v) if float(v).is_integer() else round(v, 6)
                   for k, v in registry().diff(before).items()
                   if k.startswith("spill_")}
        _derive_spill_ratios(metrics)
        return out, wall, metrics

    sync_out, sync_wall, sync_metrics = leg(spill_io_threads=0,
                                            spill_prefetch_batches=0)
    async_out, async_wall, async_metrics = leg()
    assert async_out == sync_out, \
        "spill A/B legs diverged — overlapped IO must never change results"
    assert sync_metrics.get("spill_bytes", 0) > 0, \
        "spill A/B budget never spilled — not an out-of-core comparison"
    return {
        "budget_bytes": budget,
        "sort_keys": keys,
        "sync_wall_seconds": round(sync_wall, 4),
        "async_wall_seconds": round(async_wall, 4),
        "speedup": round(sync_wall / async_wall, 4) if async_wall else 0.0,
        "bit_identical": True,
        "sync_metrics": sync_metrics,
        "async_metrics": async_metrics,
    }


def merge_microbench(rows: int = 200_000) -> dict:
    """Quick out-of-core merge microbench — the BENCH_OOM_ROWS quick mode
    and the tier-1 regression test in tests/test_spill_async.py share this
    body. A synthetic sort is forced through a multi-run external merge
    under a tiny fixed budget, then three contracts are asserted:

      1. bit-identical to the unbudgeted in-memory sort;
      2. spill_merge_sort_rows stays O(rows) per merge level — far below
         the old per-round full re-argsort, whose cost grew with the
         in-flight window every round (~rows x fan-in on a deep cascade);
      3. the spill_prefetch_inflight high-water never exceeds the
         configured DAFT_TPU_SPILL_PREFETCH_BATCHES depth.

    Returns the measurements so the JSON emitter / test can inspect them."""
    import numpy as np

    import daft_tpu
    from daft_tpu.config import execution_config, execution_config_ctx
    from daft_tpu.execution import memory as _mem
    from daft_tpu.observability.metrics import registry

    rng = np.random.default_rng(7)
    df = daft_tpu.from_pydict({
        "k": rng.integers(0, max(rows, 1), size=rows),
        "g": rng.integers(0, 997, size=rows),
        "v": rng.standard_normal(rows),
    }).into_batches(max(rows // 64, 256)).collect()
    input_bytes = sum(p.size_bytes() for p in df.iter_partitions())

    with execution_config_ctx(memory_limit_bytes=0, device_mode="off"):
        expected = df.sort(["k", "g"]).to_pydict()

    # ~48 runs: deep enough that the fan-in cascade (intermediate merges)
    # engages, so the sort-rows bound below exercises multi-level merging
    budget = max(input_bytes // 48, 48 << 10)
    _mem.reset_counters()
    _mem.manager().clear()
    before = registry().snapshot()
    with execution_config_ctx(memory_limit_bytes=budget, device_mode="off"):
        t0 = time.perf_counter()
        out = df.sort(["k", "g"]).to_pydict()
        wall = time.perf_counter() - t0
    diff = registry().diff(before)

    assert out == expected, "budgeted merge diverged from in-memory sort"
    runs = int(diff.get("spill_runs", 0))
    assert runs >= 2, f"budget produced only {runs} run(s) — not external"
    merge_rows = int(diff.get("spill_merge_sort_rows", 0))
    # each row is keyed/argsorted at most once per merge level (cascade +
    # final), and single-source stretches skip the argsort entirely; the
    # old merge's bound was ~rows x fan-in across the morsel rounds
    levels = 1 + (1 if diff.get("spill_merge_passes", 0) else 0)
    old_bound = rows * max(runs // 2, 4)
    assert 0 < merge_rows <= rows * (levels + 1), (
        f"spill_merge_sort_rows={merge_rows} outside the carry-preserving "
        f"bound for {rows} rows x {levels} merge level(s)")
    depth = execution_config().spill_prefetch_batches
    high_water = registry().snapshot().get("spill_prefetch_inflight", 0)
    assert high_water <= depth, (
        f"prefetch high-water {high_water} above the configured depth "
        f"{depth}")
    metrics = {k: int(v) if float(v).is_integer() else round(v, 6)
               for k, v in diff.items() if k.startswith("spill_")}
    _derive_spill_ratios(metrics)
    return {
        "rows": rows,
        "runs": runs,
        "wall_seconds": round(wall, 4),
        "merge_sort_rows": merge_rows,
        "old_merge_bound_rows": int(old_bound),
        "prefetch_high_water": int(high_water),
        "prefetch_depth": depth,
        "budget_bytes": budget,
        "input_bytes": int(input_bytes),
        "metrics": metrics,
    }


def oom_merge_microbench() -> None:
    """BENCH_OOM=1 BENCH_OOM_ROWS=N: the quick mode `make bench-oom-quick`
    drives — merge_microbench scaled to N synthetic rows, emitted in the
    capture-record shape so --compare can gate on it like any other run."""
    rows = int(os.environ.get("BENCH_OOM_ROWS", 200_000))
    r = merge_microbench(rows)
    rows_per_sec = r["rows"] / r["wall_seconds"] if r["wall_seconds"] else 0.0
    _emit({
        "metric": f"oom_merge_{r['rows']}rows_rows_per_sec",
        "value": round(rows_per_sec, 1),
        "unit": "rows/sec",
        "runs": r["runs"],
        "merge_sort_rows": r["merge_sort_rows"],
        "old_merge_bound_rows": r["old_merge_bound_rows"],
        "prefetch_high_water": r["prefetch_high_water"],
        "prefetch_depth": r["prefetch_depth"],
        "memory_limit_bytes": r["budget_bytes"],
        "dataset_bytes": r["input_bytes"],
        "bit_identical": True,
        "calibration": _calibration_dict(),
        "metrics": r["metrics"],
    })


REGRESSION_TOLERANCE = 0.05   # >5% slower than OLD fails the gate


def _validate_capture(data: dict) -> None:
    """The capture-record contract `--compare` relies on: a dict carrying at
    least the headline metric/value pair (per_query_ms rides along for
    suite captures). Raises with the offending shape — bench.py refuses to
    EMIT a capture its own loader could not read back (the BENCH_r05
    lesson: a committed artifact that the gate silently half-parses is a
    regression hiding place)."""
    if not isinstance(data, dict):
        raise SystemExit(f"bench capture must be a JSON object, got "
                         f"{type(data).__name__}")
    missing = [k for k in ("metric", "value") if k not in data]
    if missing:
        raise SystemExit(
            f"bench capture is missing {missing} — not a capture record "
            f"(keys: {sorted(data)[:8]})")


def _emit(out: dict) -> None:
    """Print the one-JSON-line capture, refusing to emit anything the
    --compare loader cannot round-trip."""
    line = json.dumps(out)
    _validate_capture(json.loads(line))
    print(line)


def _load_capture(path: str) -> dict:
    """A bench JSON — either the raw one-line output of this script or a
    driver capture record wrapping it under "parsed". Fails LOUDLY on any
    other shape instead of returning a dict the comparison loops would
    silently treat as an empty query set."""
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, dict) and "metric" not in data \
            and isinstance(data.get("parsed"), dict):
        data = data["parsed"]
    _validate_capture(data)
    return data


def compare(old_path: str, new_path: str) -> int:
    """Per-query speedup table OLD -> NEW; returns the number of regressions
    (queries or the headline metric slower by more than the tolerance)."""
    old = _load_capture(old_path)
    new = _load_capture(new_path)
    old_q = old.get("per_query_ms", {})
    new_q = new.get("per_query_ms", {})
    # per-query placement FLIP column: which queries moved between host and
    # device capture between the two runs (per_query_device counts device
    # dispatches per query) — a re-capture then shows exactly which join
    # queries the mesh tier flipped, next to their speedups
    old_d = old.get("per_query_device", {})
    new_d = new.get("per_query_device", {})

    def _flip(q: str) -> str:
        if q not in old_d or q not in new_d:
            return ""
        o, n = old_d.get(q, 0), new_d.get(q, 0)
        if o == 0 and n > 0:
            return "host->device"
        if o > 0 and n == 0:
            return "device->host"
        return ""

    regressions = []
    # a query that vanished from NEW is lost coverage, not a pass: a
    # regression hiding in a dropped query must fail the gate loudly
    for q in sorted(set(old_q) - set(new_q)):
        print(f"{q:<8} missing from NEW capture  <-- REGRESSION")
        regressions.append(q)
    print(f"{'query':<8} {'old ms':>10} {'new ms':>10} {'speedup':>8} "
          f"{'placement':>13}")
    for q in sorted(set(old_q) & set(new_q),
                    key=lambda s: int(s[1:]) if s[1:].isdigit() else 0):
        o, n = old_q[q], new_q[q]
        speedup = o / n if n else float("inf")
        flag = ""
        if n > o * (1 + REGRESSION_TOLERANCE):
            flag = "  <-- REGRESSION"
            regressions.append(q)
        print(f"{q:<8} {o:>10.1f} {n:>10.1f} {speedup:>7.2f}x "
              f"{_flip(q):>13}{flag}")
    ov, nv = old.get("value", 0), new.get("value", 0)
    if ov and nv:
        flag = ""
        if nv < ov * (1 - REGRESSION_TOLERANCE):
            flag = "  <-- REGRESSION"
            regressions.append("rows_per_sec")
        print(f"{'TOTAL':<8} {'':>10} {'':>10} {nv / ov:>7.2f}x{flag}  "
              f"({old.get('metric', '?')}: {ov:g} -> {nv:g} rows/sec)")
    # spill-IO overlap movement: derived here too, so captures recorded
    # before the ratio landed in `metrics` still compare (the raw counter
    # pairs are enough to reconstruct it)
    om = dict(old.get("metrics", {}) or {})
    nm = dict(new.get("metrics", {}) or {})
    _derive_spill_ratios(om)
    _derive_spill_ratios(nm)
    if "spill_io_overlap_ratio" in om or "spill_io_overlap_ratio" in nm:
        print(f"spill IO overlap ratio: "
              f"{om.get('spill_io_overlap_ratio', 0.0):.0%} -> "
              f"{nm.get('spill_io_overlap_ratio', 0.0):.0%} "
              f"(overlapped {om.get('spill_io_overlap_seconds', 0.0):g}s -> "
              f"{nm.get('spill_io_overlap_seconds', 0.0):g}s)")
    # cost-model drift: a WARNING, not a gate failure — prediction error
    # moving >2x between captures means the calibration (or the model's
    # terms) no longer matches the silicon, and placement verdicts near the
    # boundary may have flipped for the wrong reason. Recalibrate via
    # `make calibrate-report` and commit the suggested overrides.
    oe = old.get("cost_model_error_ratio")
    ne = new.get("cost_model_error_ratio")
    if oe and ne and (ne > 2 * oe or ne < oe / 2):
        print(f"WARNING: cost_model_error_ratio drifted {oe:g} -> {ne:g} "
              f"(> 2x): placement predictions diverged from measured "
              f"dispatches — run `make calibrate-report` and refresh the "
              f"DAFT_TPU_COST_* overrides")
    if regressions:
        # regression attribution (doctor's lens, inline): name the top
        # regressed queries with their operator/counter deltas so the FAIL
        # line says WHAT got slower, not just that something did. Old
        # captures without per_query_profile degrade to capture-level
        # counter movement — the loader and attribution are shape-tolerant.
        from daft_tpu.tools.doctor import attribution_lines

        q_regressed = [r for r in regressions if r in old_q]
        for line in attribution_lines(old, new, q_regressed):
            print(line)
        print(f"FAIL: {len(regressions)} regression(s) > "
              f"{REGRESSION_TOLERANCE:.0%}: {', '.join(regressions)}")
        top = sorted(q_regressed,
                     key=lambda q: (new_q.get(q, 0) / old_q[q]) if old_q.get(q)
                     else float("inf"), reverse=True)[:3]
        if top:
            print("worst offenders: "
                  + "; ".join(f"{q} {new_q[q] / old_q[q]:.2f}x slower"
                              for q in top if old_q.get(q) and q in new_q)
                  + " — see attribution above for operator/counter deltas")
    else:
        print(f"OK: no regressions > {REGRESSION_TOLERANCE:.0%} "
              f"across {len(set(old_q) & set(new_q))} queries")
    return len(regressions)


# counter families worth carrying per query in per_query_profile: the
# engine-tax attribution set (scans/spills/ledger/shuffle/h2d + dispatch
# shape). Everything else stays in the capture-level metrics dict.
_PROFILE_COUNTER_PREFIXES = ("scan_", "spill_", "host_", "shuffle_", "hbm_",
                             "device_", "mesh_", "dispatch_", "coalesce_")


def _profile_pass(thunks: dict) -> dict:
    """Per-operator profiles for the capture (schema v10): one extra
    instrumented run per query AFTER the timed reps — the StatsCollector
    compute/starve/blocked self-time split per physical operator plus the
    per-query registry counter deltas for the engine-tax families
    (scan/spill/ledger/shuffle/h2d). Runs after timing for the same reason
    _save_profiles does: collector overhead never contaminates the headline
    number. The result lands in the capture as per_query_profile — the raw
    material doctor's regression attribution ranks when --compare fails."""
    from daft_tpu.observability.metrics import registry
    from daft_tpu.observability.runtime_stats import (StatsCollector,
                                                      set_collector)

    profile = {}
    for label, run in thunks.items():
        before = registry().snapshot()
        collector = StatsCollector()
        set_collector(collector)
        try:
            run()
        finally:
            set_collector(None)
        deltas = {k: (int(v) if float(v).is_integer() else round(v, 6))
                  for k, v in registry().diff(before).items()
                  if k.startswith(_PROFILE_COUNTER_PREFIXES)}
        ops = sorted(collector.finish(), key=lambda s: s.seconds, reverse=True)
        profile[label] = {
            "operators": [{
                "name": s.name,
                "rows": s.rows_out,
                "seconds": round(s.seconds, 6),
                "compute": round(s.compute_seconds, 6),
                "starve": round(s.starve_seconds, 6),
                "blocked": round(s.blocked_seconds, 6),
            } for s in ops],
            "counters": deltas,
        }
    return profile


def _save_profiles(tables, ALL_QUERIES) -> None:
    """BENCH_PROFILE=1: one Chrome-trace timeline per query via
    explain_analyze(profile=...) — an extra instrumented run AFTER the timed
    reps, so profiling overhead never contaminates the headline number."""
    out_dir = os.environ.get("BENCH_PROFILE_DIR", ".")
    os.makedirs(out_dir, exist_ok=True)
    for q in QUERIES:
        path = os.path.join(out_dir, f"bench_trace_{SUITE}_sf{SF:g}_q{q}.json")
        ALL_QUERIES[q](tables).explain_analyze(profile=path)
        print(f"profile: {path}", file=sys.stderr)


def main() -> None:
    if os.environ.get("BENCH_OOM"):
        if os.environ.get("BENCH_OOM_ROWS"):
            oom_merge_microbench()   # quick mode: synthetic merge capture
        else:
            oom_bench()
        return
    if os.environ.get("BENCH_MESH"):
        mesh_microbench()
        return
    if os.environ.get("BENCH_SHUFFLE"):
        shuffle_microbench()
        return
    if os.environ.get("BENCH_FUSION"):
        fusion_microbench()
        return
    if os.environ.get("BENCH_PALLAS"):
        pallas_microbench()
        return
    if os.environ.get("BENCH_SERVE"):
        if os.environ.get("BENCH_SERVE_NET"):
            serve_bench_net()
        else:
            serve_bench()
        return
    if SUITE == "ai":
        ai_bench()
        return
    if SUITE == "tpcds":
        from benchmarking.tpcds.datagen import load_dataframes
        from benchmarking.tpcds.queries import ALL_QUERIES
        fact = "store_sales"
    else:
        from benchmarking.tpch.datagen import load_dataframes
        from benchmarking.tpch.queries import ALL_QUERIES
        fact = "lineitem"

    from daft_tpu.ops import counters

    tables = {k: v.collect() for k, v in load_dataframes(sf=SF, seed=0).items()}
    n_lineitem = tables[fact].count_rows()

    from daft_tpu.observability import placement as _placement

    # warmup (compile caches, device column residency, key dictionaries).
    # Placement verdicts are collected HERE, on the first execution of each
    # query: the warmup run prices every decision fresh (full per-tier cost
    # breakdowns + margins), while later reps are served from the verdict
    # caches and would record margin-less cached records for exactly the
    # host-rejected join queries the capture needs to explain.
    q_placement = {}                       # per-query placement verdicts
    for q in QUERIES:
        with _placement.query_scope() as pscope:
            ALL_QUERIES[q](tables).to_pydict()
        q_placement[q] = _placement_brief(pscope.to_dicts())

    from daft_tpu.execution import memory as _mem

    counters.reset()
    _mem.reset_counters()
    # best-of-N timed repetitions: the tunneled device's d2h round trip
    # occasionally spikes 5-10x, which is link jitter, not engine throughput
    per_query = {q: float("inf") for q in QUERIES}
    q_device = {q: 0 for q in QUERIES}     # device dispatches, total across reps
    q_reject = {}                          # why a query stayed on host (first seen)
    metric_totals = {}                     # registry snapshot summed over the last rep
    elapsed = float("inf")
    for rep in range(REPS):
        t0 = time.perf_counter()
        for q in QUERIES:
            counters.reset()
            # spill counters live in the registry but outside COUNTER_NAMES:
            # reset per query too, or the summed snapshot loop below would
            # multiply the process-cumulative value once per query
            _mem.reset_counters()
            tq = time.perf_counter()
            ALL_QUERIES[q](tables).to_pydict()
            per_query[q] = min(per_query[q], time.perf_counter() - tq)
            # grouped + ungrouped stage batches count each dispatch exactly
            # once (join/topn counters overlay the same dispatches)
            rep_batches = (counters.device_grouped_batches
                           + counters.device_stage_batches)
            q_device[q] += rep_batches
            if rep_batches == 0 and counters.rejections and q not in q_reject:
                q_reject[q] = max(counters.rejections,
                                  key=counters.rejections.get)
            if rep == REPS - 1:
                # one full pass over the query set: per-query registry deltas
                # (device counters + shuffle bytes) summed for attribution.
                # cost_*/placement_*/flight_* series are process-cumulative
                # (outside the counters.reset() scope) — summing them once per
                # query would multiply them; cost/placement land below from
                # live state, flight_* only moves on anomalies
                for k, v in counters.snapshot().items():
                    if v and not k.startswith(("cost_", "placement_",
                                               "flight_")):
                        metric_totals[k] = metric_totals.get(k, 0) + v
        elapsed = min(elapsed, time.perf_counter() - t0)

    # HBM residency gauges (resident bytes, high-water, entry count) come
    # from the manager's own state — process-lifetime values, replacing the
    # meaningless per-query gauge sums. The hbm_* COUNTERS are left alone:
    # counters.reset() zeroes them per query, so the summed snapshot loop
    # above already accumulated true per-query deltas for them.
    from daft_tpu.device.residency import manager as _residency

    _res = _residency().stats()
    for k in ("hbm_bytes_resident", "hbm_bytes_high_water", "hbm_entries"):
        metric_totals[k] = _res[k]

    # Host-memory attribution (the out-of-core tier): ledger high-water off
    # the manager's own state + the process RSS high-water, so every capture
    # (budgeted or not) records how much host memory the run actually took.
    metric_totals["host_bytes_high_water"] = _mem.manager().high_water_bytes()
    metric_totals["rss_high_water_bytes"] = _rss_high_water_bytes()

    # Distributed placement attribution: the sched_* counters accumulated in
    # the snapshot loop above already carry sched_bytes_avoided etc.; derive
    # the affinity hit RATE so a device capture shows locality wins alongside
    # the HBM gauges without post-processing.
    hits = metric_totals.get("sched_affinity_hits", 0)
    misses = metric_totals.get("sched_affinity_misses", 0)
    metric_totals["sched_affinity_hit_rate"] = round(
        hits / (hits + misses), 4) if (hits or misses) else 0.0

    # Dispatch-coalescing attribution: whether the RTT amortization actually
    # paid on this capture. bucket_fill_ratio = real rows / padded bucket rows
    # across coalesced dispatches (padding efficiency); dispatch_rtts_saved =
    # morsels consumed minus dispatches issued (each saved dispatch is one
    # avoided ~90ms round trip on a tunneled link).
    cap_rows = metric_totals.get("bucket_capacity_rows", 0)
    if cap_rows:
        metric_totals["bucket_fill_ratio"] = round(
            metric_totals.get("bucket_fill_rows", 0) / cap_rows, 4)
    morsels_in = metric_totals.get("coalesce_morsels_in", 0)
    if morsels_in:
        metric_totals["dispatch_rtts_saved"] = int(
            morsels_in - metric_totals.get("dispatch_coalesced", 0))

    # Mesh-tier attribution: what fraction of device dispatches ran sharded
    # across the local mesh (the in-mesh SPMD tier) — the next real-chip
    # SF10/TPC-DS re-capture records mesh engagement alongside the HBM and
    # coalescing numbers.
    _derive_mesh_ratio(metric_totals)

    # Fused-region attribution: mean operators amortized per device dispatch
    # (the tentpole's "N ops, 1 RTT" claim at capture granularity).
    _derive_fusion_ratio(metric_totals)
    _derive_pallas_ratio(metric_totals)

    # Shuffle transport attribution: compression + overlap ratios derived
    # from the wire/logical byte and cumulative/overlap second counters
    # (only present when the capture crossed a distributed shuffle).
    _derive_shuffle_ratios(metric_totals)

    per_query_profile = _profile_pass(
        {f"q{q}": (lambda q=q: ALL_QUERIES[q](tables).to_pydict())
         for q in QUERIES})

    if os.environ.get("BENCH_PROFILE"):
        _save_profiles(tables, ALL_QUERIES)

    # Placement attribution: per-query verdicts from the decision ledger
    # (which tier each stage chose and why, margins, cached-vs-fresh), the
    # aggregate prediction-error stats for dispatched stages, and the
    # calibration terms the capture priced with — bench.py --compare warns
    # when cost_model_error_ratio drifts >2x between captures. The
    # placement_* counters report process-lifetime values (like the hbm
    # gauges), not per-query sums.
    from daft_tpu.observability.metrics import registry as _registry
    from daft_tpu.observability.placement import ledger as _ledger

    for k, v in _registry().snapshot().items():
        if k.startswith("placement_") and v:
            metric_totals[k] = v

    err = _ledger().error_summary()
    rows_per_sec = n_lineitem * len(QUERIES) / elapsed
    out = {
        "metric": f"{SUITE}_sf{SF}_{len(QUERIES)}q_rows_per_sec",
        "value": round(rows_per_sec, 1),
        "unit": "rows/sec",
        "vs_baseline": round(rows_per_sec / BASELINE_ROWS_PER_SEC, 4),
        "device_batches": sum(q_device.values()),
        "per_query_ms": {f"q{q}": round(per_query[q] * 1000, 1) for q in QUERIES},
        "per_query_profile": per_query_profile,
        "per_query_device": {f"q{q}": q_device[q] for q in QUERIES},
        "host_reasons": {f"q{q}": r for q, r in sorted(q_reject.items())},
        "placement": {f"q{q}": v for q, v in sorted(q_placement.items()) if v},
        "calibration": _calibration_dict(),
        "metrics": metric_totals,
        "sf": SF,
        "fact_rows": n_lineitem,
    }
    if err.get("samples"):
        out["cost_model_error_ratio"] = err["median"]
        out["cost_model_error"] = err
    _emit(out)


if __name__ == "__main__":
    if len(sys.argv) >= 2 and sys.argv[1] == "--compare":
        if len(sys.argv) != 4:
            print("usage: python bench.py --compare OLD.json NEW.json",
                  file=sys.stderr)
            sys.exit(2)
        sys.exit(1 if compare(sys.argv[2], sys.argv[3]) else 0)
    main()
