"""Benchmark harness: TPC-H Q1+Q6 on generated lineitem data.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.
Baseline anchor (BASELINE.md): reference NativeRunner TPC-H; we report rows/sec
through the full engine path (plan → optimize → translate → execute) for a
Q1-shape grouped aggregation + Q6-shape filter-agg over SF~0.1-scale data.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

N_ROWS = int(os.environ.get("BENCH_ROWS", 6_000_000))
# reference anchor: Daft native runner sustains O(100M) rows/sec/core-group on
# this shape on server CPU; per-chip target from BASELINE.json
BASELINE_ROWS_PER_SEC = 50e6


def gen_lineitem(n: int):
    rng = np.random.default_rng(42)
    return {
        "l_quantity": rng.uniform(1, 50, n).round(0),
        "l_extendedprice": rng.uniform(900, 105000, n).round(2),
        "l_discount": rng.uniform(0.0, 0.1, n).round(2),
        "l_tax": rng.uniform(0.0, 0.08, n).round(2),
        "l_returnflag": rng.choice(np.array(["A", "N", "R"]), n),
        "l_linestatus": rng.choice(np.array(["F", "O"]), n),
        "l_shipdate_days": rng.integers(8000, 10600, n),
    }


def main() -> None:
    import daft_tpu as dt
    from daft_tpu import col

    data = gen_lineitem(N_ROWS)
    df = dt.from_pydict(data).collect()

    # warmup (compile caches, etc.)
    _ = run_q6(df, col)
    _ = run_q1(df, col)

    t0 = time.perf_counter()
    run_q6(df, col)
    t_q6 = time.perf_counter() - t0

    t0 = time.perf_counter()
    run_q1(df, col)
    t_q1 = time.perf_counter() - t0

    total_rows = 2 * N_ROWS
    rows_per_sec = total_rows / (t_q1 + t_q6)
    print(json.dumps({
        "metric": "tpch_q1q6_rows_per_sec",
        "value": round(rows_per_sec, 1),
        "unit": "rows/sec",
        "vs_baseline": round(rows_per_sec / BASELINE_ROWS_PER_SEC, 4),
    }))


def run_q6(df, col):
    return (
        df.where(
            (col("l_shipdate_days") >= 8766) & (col("l_shipdate_days") < 9131)
            & (col("l_discount") >= 0.05) & (col("l_discount") <= 0.07)
            & (col("l_quantity") < 24)
        )
        .agg((col("l_extendedprice") * col("l_discount")).sum().alias("revenue"))
        .to_pydict()
    )


def run_q1(df, col):
    return (
        df.where(col("l_shipdate_days") <= 10471)
        .groupby("l_returnflag", "l_linestatus")
        .agg(
            col("l_quantity").sum().alias("sum_qty"),
            col("l_extendedprice").sum().alias("sum_base_price"),
            (col("l_extendedprice") * (1 - col("l_discount"))).sum().alias("sum_disc_price"),
            (col("l_extendedprice") * (1 - col("l_discount")) * (1 + col("l_tax"))).sum().alias("sum_charge"),
            col("l_quantity").mean().alias("avg_qty"),
            col("l_extendedprice").mean().alias("avg_price"),
            col("l_discount").mean().alias("avg_disc"),
            col("l_quantity").count().alias("count_order"),
        )
        .sort(["l_returnflag", "l_linestatus"])
        .to_pydict()
    )


if __name__ == "__main__":
    main()
