"""Iceberg + Delta Lake read connectors and the filesystem catalog.

Tables are constructed on disk in the exact on-disk layout the specs define
(Iceberg v2 metadata JSON + Avro manifest list/manifests; Delta _delta_log
newline-JSON actions), then read back through the engine: schema mapping,
log/snapshot replay, partition + stats pruning through Pushdowns, and the
session catalog + SQL path."""

import json
import os

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import daft_tpu
from daft_tpu import col
from daft_tpu.io.avro import write_container
from daft_tpu.io.scan import Pushdowns


# ======================================================================================
# fixture builders
# ======================================================================================


def _write_parquet(path, rows):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    pq.write_table(pa.table(rows), path)
    return os.path.getsize(path)


_MANIFEST_ENTRY_SCHEMA = {
    "type": "record", "name": "manifest_entry", "fields": [
        {"name": "status", "type": "int"},
        {"name": "data_file", "type": {
            "type": "record", "name": "r2", "fields": [
                {"name": "content", "type": "int"},
                {"name": "file_path", "type": "string"},
                {"name": "file_format", "type": "string"},
                {"name": "partition", "type": {
                    "type": "record", "name": "r102", "fields": [
                        {"name": "p", "type": ["null", "string"]}]}},
                {"name": "record_count", "type": "long"},
                {"name": "file_size_in_bytes", "type": "long"},
            ]}},
    ]}

_MANIFEST_LIST_SCHEMA = {
    "type": "record", "name": "manifest_file", "fields": [
        {"name": "manifest_path", "type": "string"},
        {"name": "content", "type": "int"},
        {"name": "added_snapshot_id", "type": "long"},
    ]}


@pytest.fixture
def iceberg_table(tmp_path):
    """Two identity-partitioned data files (p='a', p='b') under one snapshot."""
    t = str(tmp_path / "wh" / "sales" / "events")
    loc = "file:///original/warehouse/events"  # written elsewhere: tests re-anchoring
    files = []
    for pval, ks in (("a", [1, 2, 3]), ("b", [10, 20])):
        path = os.path.join(t, "data", f"p={pval}", "f.parquet")
        size = _write_parquet(path, {
            "k": pa.array(ks, pa.int64()),
            "v": pa.array([float(k) * 0.5 for k in ks], pa.float64()),
            "p": pa.array([pval] * len(ks), pa.string()),
        })
        files.append((f"{loc}/data/p={pval}/f.parquet", pval, len(ks), size))

    mdir = os.path.join(t, "metadata")
    os.makedirs(mdir, exist_ok=True)
    entries = [{"status": 1, "data_file": {
        "content": 0, "file_path": fp, "file_format": "PARQUET",
        "partition": {"p": pval}, "record_count": n, "file_size_in_bytes": size,
    }} for fp, pval, n, size in files]
    write_container(os.path.join(mdir, "m0.avro"), _MANIFEST_ENTRY_SCHEMA, entries)
    write_container(os.path.join(mdir, "snap-99.avro"), _MANIFEST_LIST_SCHEMA,
                    [{"manifest_path": f"{loc}/metadata/m0.avro", "content": 0,
                      "added_snapshot_id": 99}])
    meta = {
        "format-version": 2, "table-uuid": "0000", "location": loc,
        "current-schema-id": 0,
        "schemas": [{"schema-id": 0, "type": "struct", "fields": [
            {"id": 1, "name": "k", "type": "long", "required": False},
            {"id": 2, "name": "v", "type": "double", "required": False},
            {"id": 3, "name": "p", "type": "string", "required": False},
        ]}],
        "default-spec-id": 0,
        "partition-specs": [{"spec-id": 0, "fields": [
            {"name": "p", "transform": "identity", "source-id": 3, "field-id": 1000}]}],
        "current-snapshot-id": 99,
        "snapshots": [{"snapshot-id": 99, "timestamp-ms": 0,
                       "manifest-list": f"{loc}/metadata/snap-99.avro"}],
    }
    with open(os.path.join(mdir, "v1.metadata.json"), "w") as f:
        json.dump(meta, f)
    return t, str(tmp_path / "wh")


@pytest.fixture
def delta_table(tmp_path):
    """Partitioned delta table with a remove action in a second commit."""
    t = str(tmp_path / "dw" / "orders")
    log = os.path.join(t, "_delta_log")
    os.makedirs(log, exist_ok=True)
    # data files do NOT contain the partition column
    _write_parquet(os.path.join(t, "p=x", "f1.parquet"),
                   {"k": pa.array([1, 2], pa.int64()),
                    "v": pa.array([1.0, 2.0], pa.float64())})
    _write_parquet(os.path.join(t, "p=y", "f2.parquet"),
                   {"k": pa.array([30, 40], pa.int64()),
                    "v": pa.array([3.0, 4.0], pa.float64())})
    _write_parquet(os.path.join(t, "p=x", "dead.parquet"),
                   {"k": pa.array([999], pa.int64()),
                    "v": pa.array([9.9], pa.float64())})
    schema_string = json.dumps({"type": "struct", "fields": [
        {"name": "k", "type": "long", "nullable": True, "metadata": {}},
        {"name": "v", "type": "double", "nullable": True, "metadata": {}},
        {"name": "p", "type": "string", "nullable": True, "metadata": {}},
    ]})
    v0 = [
        {"protocol": {"minReaderVersion": 1, "minWriterVersion": 2}},
        {"metaData": {"id": "m", "schemaString": schema_string,
                      "partitionColumns": ["p"], "configuration": {}}},
        {"add": {"path": "p=x/f1.parquet", "partitionValues": {"p": "x"},
                 "size": 1, "modificationTime": 0, "dataChange": True,
                 "stats": json.dumps({"numRecords": 2, "minValues": {"k": 1},
                                      "maxValues": {"k": 2}})}},
        {"add": {"path": "p=x/dead.parquet", "partitionValues": {"p": "x"},
                 "size": 1, "modificationTime": 0, "dataChange": True,
                 "stats": json.dumps({"numRecords": 1, "minValues": {"k": 999},
                                      "maxValues": {"k": 999}})}},
    ]
    v1 = [
        {"remove": {"path": "p=x/dead.parquet", "dataChange": True}},
        {"add": {"path": "p=y/f2.parquet", "partitionValues": {"p": "y"},
                 "size": 1, "modificationTime": 0, "dataChange": True,
                 "stats": json.dumps({"numRecords": 2, "minValues": {"k": 30},
                                      "maxValues": {"k": 40}})}},
    ]
    for i, actions in enumerate((v0, v1)):
        with open(os.path.join(log, f"{i:020d}.json"), "w") as f:
            for a in actions:
                f.write(json.dumps(a) + "\n")
    return t, str(tmp_path / "dw")


# ======================================================================================
# iceberg
# ======================================================================================


def test_iceberg_read_schema_and_rows(iceberg_table):
    t, _root = iceberg_table
    df = daft_tpu.read_iceberg(t)
    assert df.column_names == ["k", "v", "p"]
    out = df.sort("k").to_pydict()
    assert out["k"] == [1, 2, 3, 10, 20]
    assert out["p"] == ["a", "a", "a", "b", "b"]
    assert out["v"] == [0.5, 1.0, 1.5, 5.0, 10.0]


def test_iceberg_partition_pruning(iceberg_table):
    t, _root = iceberg_table
    from daft_tpu.io.iceberg import IcebergScanOperator

    op = IcebergScanOperator(t)
    assert len(op.to_scan_tasks(Pushdowns())) == 2
    pruned = op.to_scan_tasks(Pushdowns(filters=col("p") == "a"))
    assert len(pruned) == 1 and "p=a" in pruned[0].source_label
    # engine-level: the pushdown happens through the optimizer
    out = daft_tpu.read_iceberg(t).where(col("p") == "b").sort("k").to_pydict()
    assert out["k"] == [10, 20]


def test_iceberg_approx_rows_and_predicate(iceberg_table):
    t, _root = iceberg_table
    from daft_tpu.io.iceberg import IcebergScanOperator

    assert IcebergScanOperator(t).approx_num_rows(Pushdowns()) == 5.0
    out = daft_tpu.read_iceberg(t).where(col("k") >= 3).sum("k").to_pydict()
    assert out["k"] == [33]


# ======================================================================================
# delta
# ======================================================================================


def test_delta_read_replays_log_and_restores_partition_columns(delta_table):
    t, _root = delta_table
    df = daft_tpu.read_deltalake(t)
    assert df.column_names == ["k", "v", "p"]
    out = df.sort("k").to_pydict()
    assert out["k"] == [1, 2, 30, 40]           # dead.parquet removed by v1
    assert out["p"] == ["x", "x", "y", "y"]     # partition col reconstructed


def test_delta_partition_and_stats_pruning(delta_table):
    t, _root = delta_table
    from daft_tpu.io.delta import DeltaScanOperator

    op = DeltaScanOperator(t)
    assert len(op.to_scan_tasks(Pushdowns())) == 2
    by_part = op.to_scan_tasks(Pushdowns(filters=col("p") == "y"))
    assert len(by_part) == 1 and "f2" in by_part[0].source_label
    by_stats = op.to_scan_tasks(Pushdowns(filters=col("k") > 25))
    assert len(by_stats) == 1 and "f2" in by_stats[0].source_label
    out = daft_tpu.read_deltalake(t).where(col("p") == "x").sum("v").to_pydict()
    assert out["v"] == [3.0]


# ======================================================================================
# catalog + SQL
# ======================================================================================


def test_filesystem_catalog_lists_and_loads(iceberg_table):
    _t, root = iceberg_table
    from daft_tpu.session import FilesystemCatalog, Session

    cat = FilesystemCatalog(root, name="wh")
    assert cat.list_tables() == ["sales.events"]
    s = Session()
    s.attach_catalog(cat, alias="wh")
    out = s.sql("SELECT p, SUM(k) AS sk FROM wh.sales.events GROUP BY p ORDER BY p")
    assert out.to_pydict() == {"p": ["a", "b"], "sk": [6, 30]}


def test_filesystem_catalog_delta(delta_table):
    _t, root = delta_table
    from daft_tpu.session import FilesystemCatalog, Session

    s = Session()
    s.attach_catalog(FilesystemCatalog(root, name="dw"), alias="dw")
    out = s.sql("SELECT p, COUNT(*) AS n FROM dw.orders GROUP BY p ORDER BY p")
    assert out.to_pydict() == {"p": ["x", "y"], "n": [2, 2]}
