import numpy as np
import pyarrow as pa
import pytest

from daft_tpu import DataType, RecordBatch, MicroPartition, Schema, Series
from daft_tpu.core.kernels.groupby import make_groups
from daft_tpu.core.kernels.join import join_indices
from daft_tpu.core.kernels.sort import multi_argsort


def test_from_pydict_roundtrip():
    b = RecordBatch.from_pydict({"a": [1, 2, 3], "b": ["x", "y", None]})
    assert b.num_rows == 3
    assert b.column_names() == ["a", "b"]
    assert b.to_pydict() == {"a": [1, 2, 3], "b": ["x", "y", None]}
    t = b.to_arrow()
    assert t.num_rows == 3
    b2 = RecordBatch.from_arrow(t)
    assert b2.to_pydict() == b.to_pydict()


def test_row_ops():
    b = RecordBatch.from_pydict({"a": [1, 2, 3, 4], "b": ["w", "x", "y", "z"]})
    assert b.slice(1, 3).to_pydict() == {"a": [2, 3], "b": ["x", "y"]}
    assert b.take(np.array([3, 0])).to_pydict() == {"a": [4, 1], "b": ["z", "w"]}
    mask = Series.from_pylist([True, False, True, False])
    assert b.filter_by_mask(mask).to_pydict() == {"a": [1, 3], "b": ["w", "y"]}
    c = RecordBatch.concat([b, b.slice(0, 1)])
    assert c.num_rows == 5


def test_multi_sort():
    b = RecordBatch.from_pydict({"g": ["b", "a", "b", "a"], "v": [1, 4, 3, 2]})
    idx = multi_argsort([b.get_column("g"), b.get_column("v")], [False, True])
    out = b.take(idx).to_pydict()
    assert out["g"] == ["a", "a", "b", "b"]
    assert out["v"] == [4, 2, 3, 1]


def test_multi_sort_nulls():
    b = RecordBatch.from_pydict({"v": [10.5, 20.0, None, 5.25]})
    asc = b.take(multi_argsort([b.get_column("v")], [False])).to_pydict()["v"]
    assert asc == [5.25, 10.5, 20.0, None]
    desc = b.take(multi_argsort([b.get_column("v")], [True])).to_pydict()["v"]
    assert desc == [None, 20.0, 10.5, 5.25]
    desc_nl = b.take(multi_argsort([b.get_column("v")], [True], [False])).to_pydict()["v"]
    assert desc_nl == [20.0, 10.5, 5.25, None]


def test_make_groups():
    keys = [Series.from_pylist(["a", "b", "a", None, "b", None], "k")]
    first_idx, gids, counts = make_groups(keys)
    assert list(first_idx) == [0, 1, 3]
    assert list(gids) == [0, 1, 0, 2, 1, 2]
    assert list(counts) == [2, 2, 2]


def test_join_indices_inner():
    l = [Series.from_pylist([1, 2, 3, None], "k")]
    r = [Series.from_pylist([2, 2, 4, None], "k")]
    lidx, ridx = join_indices(l, r, "inner")
    pairs = sorted(zip(lidx.tolist(), ridx.tolist()))
    assert pairs == [(1, 0), (1, 1)]


def test_join_indices_left_outer():
    l = [Series.from_pylist([1, 2], "k")]
    r = [Series.from_pylist([2, 3], "k")]
    lidx, ridx = join_indices(l, r, "left")
    assert set(zip(lidx.tolist(), ridx.tolist())) == {(1, 0), (0, -1)}
    lidx, ridx = join_indices(l, r, "outer")
    assert set(zip(lidx.tolist(), ridx.tolist())) == {(1, 0), (0, -1), (-1, 1)}


def test_join_semi_anti():
    l = [Series.from_pylist([1, 2, 3], "k")]
    r = [Series.from_pylist([2], "k")]
    lidx, _ = join_indices(l, r, "semi")
    assert lidx.tolist() == [1]
    lidx, _ = join_indices(l, r, "anti")
    assert lidx.tolist() == [0, 2]


def test_multicol_join():
    l = [Series.from_pylist([1, 1, 2], "a"), Series.from_pylist(["x", "y", "x"], "b")]
    r = [Series.from_pylist([1, 2], "a"), Series.from_pylist(["y", "x"], "b")]
    lidx, ridx = join_indices(l, r, "inner")
    assert sorted(zip(lidx.tolist(), ridx.tolist())) == [(1, 0), (2, 1)]


def test_partition_by_hash():
    b = RecordBatch.from_pydict({"k": list(range(100)), "v": list(range(100))})
    parts = b.partition_by_hash([b.get_column("k")], 4)
    assert len(parts) == 4
    assert sum(p.num_rows for p in parts) == 100
    all_k = sorted(v for p in parts for v in p.to_pydict()["k"])
    assert all_k == list(range(100))
    # same key always goes to same partition
    parts2 = b.partition_by_hash([b.get_column("k")], 4)
    assert [p.to_pydict() for p in parts] == [p.to_pydict() for p in parts2]


def test_partition_by_range():
    b = RecordBatch.from_pydict({"k": [5, 1, 9, 3, 7]})
    boundaries = RecordBatch.from_pydict({"k": [4, 8]})
    parts = b.partition_by_range([b.get_column("k")], boundaries, [False])
    assert len(parts) == 3
    assert sorted(parts[0].to_pydict()["k"]) == [1, 3]
    assert sorted(parts[1].to_pydict()["k"]) == [5, 7]
    assert sorted(parts[2].to_pydict()["k"]) == [9]


def test_partition_by_value():
    b = RecordBatch.from_pydict({"k": ["a", "b", "a"], "v": [1, 2, 3]})
    parts, keys = b.partition_by_value([b.get_column("k")])
    assert len(parts) == 2
    assert keys.to_pydict() == {"k": ["a", "b"]}
    assert parts[0].to_pydict() == {"k": ["a", "a"], "v": [1, 3]}


def test_micropartition():
    b1 = RecordBatch.from_pydict({"a": [1, 2]})
    b2 = RecordBatch.from_pydict({"a": [3]})
    mp = MicroPartition.from_batches([b1, b2])
    assert len(mp) == 3
    assert mp.to_pydict() == {"a": [1, 2, 3]}
    assert mp.head(2).to_pydict() == {"a": [1, 2]}
    assert mp.slice(1, 3).to_pydict() == {"a": [2, 3]}
    stats = mp.statistics()
    assert stats.columns["a"].min == 1
    assert stats.columns["a"].max == 3
    morsels = mp.split_into_batches(1)
    assert len(morsels) == 3


def test_cast_to_schema():
    b = RecordBatch.from_pydict({"a": [1, 2]})
    target = Schema.from_pydict({"a": DataType.float64(), "b": DataType.string()})
    out = b.cast_to_schema(target)
    assert out.to_pydict() == {"a": [1.0, 2.0], "b": [None, None]}
