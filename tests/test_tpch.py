"""TPC-H correctness: cross-validate queries against independent pandas
implementations on deterministic generated data (reference test model:
tests/benchmarks/test_local_tpch.py vs golden answers)."""

import datetime
import sys

import numpy as np
import pandas as pd
import pytest

sys.path.insert(0, "/root/repo")

from benchmarking.tpch.datagen import load_dataframes
from benchmarking.tpch.queries import ALL_QUERIES


@pytest.fixture(scope="module")
def tables():
    t = load_dataframes(sf=0.01, seed=0)
    return {k: v.collect() for k, v in t.items()}


@pytest.fixture(scope="module")
def pdf(tables):
    return {k: v.to_pandas() for k, v in tables.items()}


def _close(a, b, tol=1e-6):
    if a is None and b is None:
        return True
    if isinstance(a, float) or isinstance(b, float):
        return abs(float(a) - float(b)) <= tol * max(1.0, abs(float(b)))
    return a == b


def assert_frame_matches(out: dict, expected: pd.DataFrame):
    assert list(out.keys()) == list(expected.columns), (list(out.keys()), list(expected.columns))
    n = len(next(iter(out.values()))) if out else 0
    assert n == len(expected), f"row count {n} != {len(expected)}"
    for c in expected.columns:
        got = out[c]
        exp = expected[c].tolist()
        for i, (g, e) in enumerate(zip(got, exp)):
            e = None if (isinstance(e, float) and np.isnan(e)) else e
            assert _close(g, e), f"col {c} row {i}: {g} != {e}"


def test_q1(tables, pdf):
    out = ALL_QUERIES[1](tables).to_pydict()
    L = pdf["lineitem"]
    f = L[L.l_shipdate <= datetime.date(1998, 9, 2)].copy()
    f["disc_price"] = f.l_extendedprice * (1 - f.l_discount)
    f["charge"] = f.disc_price * (1 + f.l_tax)
    g = f.groupby(["l_returnflag", "l_linestatus"], as_index=False).agg(
        sum_qty=("l_quantity", "sum"),
        sum_base_price=("l_extendedprice", "sum"),
        sum_disc_price=("disc_price", "sum"),
        sum_charge=("charge", "sum"),
        avg_qty=("l_quantity", "mean"),
        avg_price=("l_extendedprice", "mean"),
        avg_disc=("l_discount", "mean"),
        count_order=("l_quantity", "count"),
    ).sort_values(["l_returnflag", "l_linestatus"]).reset_index(drop=True)
    assert_frame_matches(out, g)


def test_q3(tables, pdf):
    out = ALL_QUERIES[3](tables).to_pydict()
    C, O, L = pdf["customer"], pdf["orders"], pdf["lineitem"]
    m = (
        C[C.c_mktsegment == "BUILDING"]
        .merge(O[O.o_orderdate < datetime.date(1995, 3, 15)], left_on="c_custkey", right_on="o_custkey")
        .merge(L[L.l_shipdate > datetime.date(1995, 3, 15)], left_on="o_orderkey", right_on="l_orderkey")
    )
    m["revenue"] = m.l_extendedprice * (1 - m.l_discount)
    g = (
        m.groupby(["o_orderkey", "o_orderdate", "o_shippriority"], as_index=False)
        .agg(revenue=("revenue", "sum"))
        .rename(columns={"o_orderkey": "l_orderkey"})
        [["l_orderkey", "revenue", "o_orderdate", "o_shippriority"]]
        .sort_values(["revenue", "o_orderdate"], ascending=[False, True])
        .head(10).reset_index(drop=True)
    )
    assert_frame_matches(out, g)


def test_q4(tables, pdf):
    out = ALL_QUERIES[4](tables).to_pydict()
    O, L = pdf["orders"], pdf["lineitem"]
    late_orders = set(L[L.l_commitdate < L.l_receiptdate].l_orderkey)
    f = O[
        (O.o_orderdate >= datetime.date(1993, 7, 1))
        & (O.o_orderdate < datetime.date(1993, 10, 1))
        & O.o_orderkey.isin(late_orders)
    ]
    g = (
        f.groupby("o_orderpriority", as_index=False)
        .agg(order_count=("o_orderkey", "count"))
        .sort_values("o_orderpriority").reset_index(drop=True)
    )
    assert_frame_matches(out, g)


def test_q5(tables, pdf):
    out = ALL_QUERIES[5](tables).to_pydict()
    C, O, L, S, N, R = (pdf["customer"], pdf["orders"], pdf["lineitem"],
                        pdf["supplier"], pdf["nation"], pdf["region"])
    m = (
        R[R.r_name == "ASIA"]
        .merge(N, left_on="r_regionkey", right_on="n_regionkey")
        .merge(C, left_on="n_nationkey", right_on="c_nationkey")
        .merge(O[(O.o_orderdate >= datetime.date(1994, 1, 1)) & (O.o_orderdate < datetime.date(1995, 1, 1))],
               left_on="c_custkey", right_on="o_custkey")
        .merge(L, left_on="o_orderkey", right_on="l_orderkey")
        .merge(S, left_on=["l_suppkey", "n_nationkey"], right_on=["s_suppkey", "s_nationkey"])
    )
    m["revenue"] = m.l_extendedprice * (1 - m.l_discount)
    g = (
        m.groupby("n_name", as_index=False).agg(revenue=("revenue", "sum"))
        .sort_values("revenue", ascending=False).reset_index(drop=True)
    )
    assert_frame_matches(out, g)


def test_q6(tables, pdf):
    out = ALL_QUERIES[6](tables).to_pydict()
    L = pdf["lineitem"]
    f = L[
        (L.l_shipdate >= datetime.date(1994, 1, 1)) & (L.l_shipdate < datetime.date(1995, 1, 1))
        & (L.l_discount >= 0.05) & (L.l_discount <= 0.07) & (L.l_quantity < 24)
    ]
    expected = (f.l_extendedprice * f.l_discount).sum()
    assert _close(out["revenue"][0], expected)


def test_q7(tables, pdf):
    out = ALL_QUERIES[7](tables).to_pydict()
    L, S, O, C, N = pdf["lineitem"], pdf["supplier"], pdf["orders"], pdf["customer"], pdf["nation"]
    m = (
        L[(L.l_shipdate >= datetime.date(1995, 1, 1)) & (L.l_shipdate <= datetime.date(1996, 12, 31))]
        .merge(S, left_on="l_suppkey", right_on="s_suppkey")
        .merge(N.rename(columns={"n_nationkey": "snk", "n_name": "supp_nation"})[["snk", "supp_nation"]],
               left_on="s_nationkey", right_on="snk")
        .merge(O, left_on="l_orderkey", right_on="o_orderkey")
        .merge(C, left_on="o_custkey", right_on="c_custkey")
        .merge(N.rename(columns={"n_nationkey": "cnk", "n_name": "cust_nation"})[["cnk", "cust_nation"]],
               left_on="c_nationkey", right_on="cnk")
    )
    m = m[
        ((m.supp_nation == "FRANCE") & (m.cust_nation == "GERMANY"))
        | ((m.supp_nation == "GERMANY") & (m.cust_nation == "FRANCE"))
    ].copy()
    m["l_year"] = pd.to_datetime(m.l_shipdate).dt.year
    m["volume"] = m.l_extendedprice * (1 - m.l_discount)
    g = (
        m.groupby(["supp_nation", "cust_nation", "l_year"], as_index=False)
        .agg(revenue=("volume", "sum"))
        .sort_values(["supp_nation", "cust_nation", "l_year"]).reset_index(drop=True)
    )
    assert_frame_matches(out, g)


def test_q10(tables, pdf):
    out = ALL_QUERIES[10](tables).to_pydict()
    C, O, L, N = pdf["customer"], pdf["orders"], pdf["lineitem"], pdf["nation"]
    m = (
        O[(O.o_orderdate >= datetime.date(1993, 10, 1)) & (O.o_orderdate < datetime.date(1994, 1, 1))]
        .merge(L[L.l_returnflag == "R"], left_on="o_orderkey", right_on="l_orderkey")
        .merge(C, left_on="o_custkey", right_on="c_custkey")
        .merge(N, left_on="c_nationkey", right_on="n_nationkey")
    )
    m["revenue"] = m.l_extendedprice * (1 - m.l_discount)
    g = (
        m.groupby(["o_custkey", "c_name", "c_acctbal", "c_phone", "n_name", "c_address", "c_comment"],
                  as_index=False)
        .agg(revenue=("revenue", "sum"))
        .rename(columns={"o_custkey": "c_custkey"})
        [["c_custkey", "c_name", "revenue", "c_acctbal", "n_name", "c_address", "c_phone", "c_comment"]]
        .sort_values(["revenue", "c_custkey"], ascending=[False, True])
        .head(20).reset_index(drop=True)
    )
    assert_frame_matches(out, g)


def test_q12(tables, pdf):
    out = ALL_QUERIES[12](tables).to_pydict()
    O, L = pdf["orders"], pdf["lineitem"]
    f = L[
        L.l_shipmode.isin(["MAIL", "SHIP"])
        & (L.l_commitdate < L.l_receiptdate)
        & (L.l_shipdate < L.l_commitdate)
        & (L.l_receiptdate >= datetime.date(1994, 1, 1))
        & (L.l_receiptdate < datetime.date(1995, 1, 1))
    ].merge(O, left_on="l_orderkey", right_on="o_orderkey")
    f["high"] = f.o_orderpriority.isin(["1-URGENT", "2-HIGH"]).astype(int)
    f["low"] = 1 - f.high
    g = (
        f.groupby("l_shipmode", as_index=False)
        .agg(high_line_count=("high", "sum"), low_line_count=("low", "sum"))
        .sort_values("l_shipmode").reset_index(drop=True)
    )
    assert_frame_matches(out, g)


def test_q14(tables, pdf):
    out = ALL_QUERIES[14](tables).to_pydict()
    L, P = pdf["lineitem"], pdf["part"]
    m = L[
        (L.l_shipdate >= datetime.date(1995, 9, 1)) & (L.l_shipdate < datetime.date(1995, 10, 1))
    ].merge(P, left_on="l_partkey", right_on="p_partkey")
    m["revenue"] = m.l_extendedprice * (1 - m.l_discount)
    promo = m[m.p_type.str.startswith("PROMO")].revenue.sum()
    expected = 100.0 * promo / m.revenue.sum()
    assert _close(out["promo_revenue"][0], expected)


def test_q17(tables, pdf):
    out = ALL_QUERIES[17](tables).to_pydict()
    L, P = pdf["lineitem"], pdf["part"]
    brand = P[(P.p_brand == "Brand#23") & (P.p_container == "MED BOX")]
    m = L.merge(brand, left_on="l_partkey", right_on="p_partkey")
    avg = m.groupby("l_partkey").l_quantity.transform("mean")
    expected = m[m.l_quantity < 0.2 * avg].l_extendedprice.sum() / 7.0
    got = out["avg_yearly"][0]
    if expected == 0:
        assert got is None or got == 0
    else:
        assert _close(got, expected)


def test_q18(tables, pdf):
    out = ALL_QUERIES[18](tables).to_pydict()
    L = pdf["lineitem"]
    sums = L.groupby("l_orderkey").l_quantity.sum()
    big = set(sums[sums > 300].index)
    total_rows = len(out["o_orderkey"])
    assert set(out["o_orderkey"]) <= big or total_rows == 0


def test_q19(tables, pdf):
    out = ALL_QUERIES[19](tables).to_pydict()
    L, P = pdf["lineitem"], pdf["part"]
    m = L[
        L.l_shipmode.isin(["AIR", "REG AIR"]) & (L.l_shipinstruct == "DELIVER IN PERSON")
    ].merge(P, left_on="l_partkey", right_on="p_partkey")
    sm = (m.p_brand == "Brand#12") & m.p_container.isin(["SM CASE", "SM BOX", "SM PACK", "SM PKG"]) \
        & (m.l_quantity >= 1) & (m.l_quantity <= 11) & (m.p_size <= 5)
    med = (m.p_brand == "Brand#23") & m.p_container.isin(["MED BAG", "MED BOX", "MED PKG", "MED PACK"]) \
        & (m.l_quantity >= 10) & (m.l_quantity <= 20) & (m.p_size <= 10)
    lg = (m.p_brand == "Brand#34") & m.p_container.isin(["LG CASE", "LG BOX", "LG PACK", "LG PKG"]) \
        & (m.l_quantity >= 20) & (m.l_quantity <= 30) & (m.p_size <= 15)
    f = m[(m.p_size >= 1) & (sm | med | lg)]
    expected = (f.l_extendedprice * (1 - f.l_discount)).sum()
    got = out["revenue"][0]
    if len(f) == 0:
        assert got is None
    else:
        assert _close(got, expected)


def test_all_queries_run(tables):
    for i, q in ALL_QUERIES.items():
        out = q(tables).to_pydict()
        assert isinstance(out, dict), f"Q{i}"


def test_q2(tables, pdf):
    out = ALL_QUERIES[2](tables).to_pydict()
    P, S, PS, N, R = pdf["part"], pdf["supplier"], pdf["partsupp"], pdf["nation"], pdf["region"]
    europe = (R[R.r_name == "EUROPE"]
              .merge(N, left_on="r_regionkey", right_on="n_regionkey")
              .merge(S, left_on="n_nationkey", right_on="s_nationkey")
              .merge(PS, left_on="s_suppkey", right_on="ps_suppkey"))
    brass = P[(P.p_size == 15) & P.p_type.str.endswith("BRASS")]
    merged = europe.merge(brass, left_on="ps_partkey", right_on="p_partkey")
    min_cost = (merged.groupby("ps_partkey", as_index=False)
                .agg(min_cost=("ps_supplycost", "min")))
    res = merged.merge(min_cost, on="ps_partkey")
    res = res[res.ps_supplycost == res.min_cost]
    res = res.drop(columns=["p_partkey"]).rename(columns={"ps_partkey": "p_partkey"})[
        ["s_acctbal", "s_name", "n_name", "p_partkey", "p_mfgr", "s_address",
         "s_phone", "s_comment"]]
    res = res.sort_values(["s_acctbal", "n_name", "s_name", "p_partkey"],
                          ascending=[False, True, True, True]).head(100)
    assert_frame_matches(out, res.reset_index(drop=True))


def test_q8(tables, pdf):
    out = ALL_QUERIES[8](tables).to_pydict()
    P, S, L, O, C, N, R = (pdf["part"], pdf["supplier"], pdf["lineitem"], pdf["orders"],
                           pdf["customer"], pdf["nation"], pdf["region"])
    n1 = N[["n_nationkey", "n_regionkey"]].rename(
        columns={"n_nationkey": "cust_nationkey", "n_regionkey": "cust_regionkey"})
    n2 = N[["n_nationkey", "n_name"]].rename(
        columns={"n_nationkey": "supp_nationkey", "n_name": "supp_nation"})
    f = (P[P.p_type == "ECONOMY ANODIZED STEEL"]
         .merge(L, left_on="p_partkey", right_on="l_partkey")
         .merge(S, left_on="l_suppkey", right_on="s_suppkey")
         .merge(O, left_on="l_orderkey", right_on="o_orderkey"))
    f = f[(f.o_orderdate >= datetime.date(1995, 1, 1)) & (f.o_orderdate <= datetime.date(1996, 12, 31))]
    f = (f.merge(C, left_on="o_custkey", right_on="c_custkey")
         .merge(n1, left_on="c_nationkey", right_on="cust_nationkey"))
    f = f.merge(R[R.r_name == "AMERICA"], left_on="cust_regionkey", right_on="r_regionkey")
    f = f.merge(n2, left_on="s_nationkey", right_on="supp_nationkey")
    f["o_year"] = pd.to_datetime(f.o_orderdate).dt.year
    f["volume"] = f.l_extendedprice * (1 - f.l_discount)
    f["brazil_volume"] = np.where(f.supp_nation == "BRAZIL", f.volume, 0.0)
    g = f.groupby("o_year", as_index=False).agg(
        brazil=("brazil_volume", "sum"), total=("volume", "sum"))
    g["mkt_share"] = g.brazil / g.total
    expected = g[["o_year", "mkt_share"]].sort_values("o_year").reset_index(drop=True)
    assert_frame_matches(out, expected)


def test_q9(tables, pdf):
    out = ALL_QUERIES[9](tables).to_pydict()
    P, S, L, PS, O, N = (pdf["part"], pdf["supplier"], pdf["lineitem"], pdf["partsupp"],
                         pdf["orders"], pdf["nation"])
    f = (P[P.p_name.str.contains("green")]
         .merge(L, left_on="p_partkey", right_on="l_partkey")
         .merge(S, left_on="l_suppkey", right_on="s_suppkey")
         .merge(PS, left_on=["l_suppkey", "p_partkey"], right_on=["ps_suppkey", "ps_partkey"])
         .merge(O, left_on="l_orderkey", right_on="o_orderkey")
         .merge(N, left_on="s_nationkey", right_on="n_nationkey"))
    f["o_year"] = pd.to_datetime(f.o_orderdate).dt.year
    f["amount"] = f.l_extendedprice * (1 - f.l_discount) - f.ps_supplycost * f.l_quantity
    g = (f.rename(columns={"n_name": "nation"})
         .groupby(["nation", "o_year"], as_index=False)
         .agg(sum_profit=("amount", "sum")))
    expected = g.sort_values(["nation", "o_year"], ascending=[True, False]).reset_index(drop=True)
    assert_frame_matches(out, expected)


def test_q11(tables, pdf):
    out = ALL_QUERIES[11](tables).to_pydict()
    PS, S, N = pdf["partsupp"], pdf["supplier"], pdf["nation"]
    g = (N[N.n_name == "GERMANY"]
         .merge(S, left_on="n_nationkey", right_on="s_nationkey")
         .merge(PS, left_on="s_suppkey", right_on="ps_suppkey"))
    g["value"] = g.ps_supplycost * g.ps_availqty
    total = g.value.sum()
    by_part = g.groupby("ps_partkey", as_index=False).agg(value=("value", "sum"))
    expected = by_part[by_part.value > total * 0.0001][["ps_partkey", "value"]]
    expected = expected.sort_values(["value", "ps_partkey"],
                                    ascending=[False, True]).reset_index(drop=True)
    assert_frame_matches(out, expected)


def test_q13(tables, pdf):
    out = ALL_QUERIES[13](tables).to_pydict()
    C, O = pdf["customer"], pdf["orders"]
    filtered = O[~O.o_comment.str.contains("special requests")]
    m = C.merge(filtered, left_on="c_custkey", right_on="o_custkey", how="left")
    per_cust = m.groupby("c_custkey", as_index=False).agg(c_count=("o_orderkey", "count"))
    g = per_cust.groupby("c_count", as_index=False).agg(custdist=("c_custkey", "count"))
    expected = g.sort_values(["custdist", "c_count"],
                             ascending=[False, False]).reset_index(drop=True)
    assert_frame_matches(out, expected)


def test_q15(tables, pdf):
    out = ALL_QUERIES[15](tables).to_pydict()
    L, S = pdf["lineitem"], pdf["supplier"]
    f = L[(L.l_shipdate >= datetime.date(1996, 1, 1)) & (L.l_shipdate < datetime.date(1996, 4, 1))].copy()
    f["rev"] = f.l_extendedprice * (1 - f.l_discount)
    rev = (f.groupby("l_suppkey", as_index=False).agg(total_revenue=("rev", "sum"))
           .rename(columns={"l_suppkey": "supplier_no"}))
    top = rev[rev.total_revenue == rev.total_revenue.max()]
    expected = (top.merge(S, left_on="supplier_no", right_on="s_suppkey")
                .rename(columns={"supplier_no": "s_suppkey2"}))
    expected = expected.assign(s_suppkey=expected.s_suppkey2)[
        ["s_suppkey", "s_name", "s_address", "s_phone", "total_revenue"]]
    expected = expected.sort_values("s_suppkey").reset_index(drop=True)
    assert_frame_matches(out, expected)


def test_q16(tables, pdf):
    out = ALL_QUERIES[16](tables).to_pydict()
    PS, P, S = pdf["partsupp"], pdf["part"], pdf["supplier"]
    complainers = S[S.s_comment.str.contains("Customer Complaints")].s_suppkey
    f = P[(P.p_brand != "Brand#45")
          & ~P.p_type.str.startswith("MEDIUM POLISHED")
          & P.p_size.isin([49, 14, 23, 45, 19, 3, 36, 9])]
    f = f.merge(PS, left_on="p_partkey", right_on="ps_partkey")
    f = f[~f.ps_suppkey.isin(complainers)]
    f = f.drop_duplicates(["p_brand", "p_type", "p_size", "ps_suppkey"])
    g = (f.groupby(["p_brand", "p_type", "p_size"], as_index=False)
         .agg(supplier_cnt=("ps_suppkey", "count")))
    expected = g.sort_values(["supplier_cnt", "p_brand", "p_type", "p_size"],
                             ascending=[False, True, True, True]).reset_index(drop=True)
    assert_frame_matches(out, expected)


def test_q18_full(tables, pdf):
    out = ALL_QUERIES[18](tables).to_pydict()
    C, O, L = pdf["customer"], pdf["orders"], pdf["lineitem"]
    big = (L.groupby("l_orderkey", as_index=False).agg(sum_qty=("l_quantity", "sum")))
    big = big[big.sum_qty > 300].l_orderkey
    f = O[O.o_orderkey.isin(big)]
    f = f.merge(C, left_on="o_custkey", right_on="c_custkey")
    f = f.merge(L, left_on="o_orderkey", right_on="l_orderkey")
    g = (f.rename(columns={"o_custkey": "c_custkey2"})
         .groupby(["c_name", "c_custkey2", "o_orderkey", "o_orderdate", "o_totalprice"],
                  as_index=False)
         .agg(col6=("l_quantity", "sum"))
         .rename(columns={"c_custkey2": "c_custkey"}))
    expected = (g.sort_values(["o_totalprice", "o_orderdate"], ascending=[False, True])
                .head(100).reset_index(drop=True))
    assert_frame_matches(out, expected)


def test_q20(tables, pdf):
    out = ALL_QUERIES[20](tables).to_pydict()
    S, N, PS, P, L = pdf["supplier"], pdf["nation"], pdf["partsupp"], pdf["part"], pdf["lineitem"]
    forest = P[P.p_name.str.startswith("forest")].p_partkey
    f = L[(L.l_shipdate >= datetime.date(1994, 1, 1)) & (L.l_shipdate < datetime.date(1995, 1, 1))]
    shipped = (f.groupby(["l_partkey", "l_suppkey"], as_index=False)
               .agg(total_shipped=("l_quantity", "sum")))
    q = PS[PS.ps_partkey.isin(forest)].merge(
        shipped, left_on=["ps_partkey", "ps_suppkey"], right_on=["l_partkey", "l_suppkey"])
    q = q[q.ps_availqty > 0.5 * q.total_shipped]
    canada = N[N.n_name == "CANADA"].n_nationkey
    expected = S[S.s_suppkey.isin(q.ps_suppkey) & S.s_nationkey.isin(canada)][
        ["s_name", "s_address"]].sort_values("s_name").reset_index(drop=True)
    assert_frame_matches(out, expected)


def test_q21(tables, pdf):
    out = ALL_QUERIES[21](tables).to_pydict()
    S, L, O, N = pdf["supplier"], pdf["lineitem"], pdf["orders"], pdf["nation"]
    late = L[L.l_receiptdate > L.l_commitdate]
    multi = L.groupby("l_orderkey")["l_suppkey"].nunique()
    multi = set(multi[multi > 1].index)
    single_late = late.groupby("l_orderkey")["l_suppkey"].nunique()
    single_late = set(single_late[single_late == 1].index)
    f_orders = set(O[O.o_orderstatus == "F"].o_orderkey)
    f = late[late.l_orderkey.isin(f_orders)
             & late.l_orderkey.isin(multi)
             & late.l_orderkey.isin(single_late)]
    f = f.merge(S, left_on="l_suppkey", right_on="s_suppkey")
    saudi = set(N[N.n_name == "SAUDI ARABIA"].n_nationkey)
    f = f[f.s_nationkey.isin(saudi)]
    g = f.groupby("s_name", as_index=False).agg(numwait=("l_orderkey", "count"))
    expected = (g.sort_values(["numwait", "s_name"], ascending=[False, True])
                .head(100).reset_index(drop=True))
    assert_frame_matches(out, expected)


def test_q22(tables, pdf):
    out = ALL_QUERIES[22](tables).to_pydict()
    C, O = pdf["customer"], pdf["orders"]
    codes = ["13", "31", "23", "29", "30", "18", "17"]
    c = C.copy()
    c["cntrycode"] = c.c_phone.str[:2]
    eligible = c[c.cntrycode.isin(codes)]
    avg_bal = eligible[eligible.c_acctbal > 0.0].c_acctbal.mean()
    no_orders = eligible[~eligible.c_custkey.isin(O.o_custkey)]
    f = no_orders[no_orders.c_acctbal > avg_bal]
    g = f.groupby("cntrycode", as_index=False).agg(
        numcust=("c_acctbal", "count"), totacctbal=("c_acctbal", "sum"))
    expected = g.sort_values("cntrycode").reset_index(drop=True)
    assert_frame_matches(out, expected)
