"""IO reader/writer tests (reference test model: tests/io/*)."""

import os

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import daft_tpu as dt
from daft_tpu import col


@pytest.fixture
def pq_dir(tmp_path):
    d = tmp_path / "data"
    d.mkdir()
    for i in range(3):
        t = pa.table({
            "a": list(range(i * 10, i * 10 + 10)),
            "b": [float(x) * 1.5 for x in range(10)],
            "s": [f"row{i}_{j}" for j in range(10)],
        })
        pq.write_table(t, d / f"part{i}.parquet")
    return str(d)


def test_read_parquet_dir(pq_dir):
    df = dt.read_parquet(pq_dir)
    assert df.column_names == ["a", "b", "s"]
    assert df.count_rows() == 30


def test_read_parquet_glob(pq_dir):
    df = dt.read_parquet(pq_dir + "/*.parquet")
    assert df.count_rows() == 30


def test_parquet_column_pushdown(pq_dir):
    df = dt.read_parquet(pq_dir).select("a")
    out = df.to_pydict()
    assert sorted(out["a"]) == list(range(30))
    # check the optimized plan pushed columns into the scan
    opt = df._builder.optimize().plan
    from daft_tpu.plan.logical import Project, ScanSource

    scans = [n for n in opt.walk() if isinstance(n, ScanSource)]
    assert scans and scans[0].pushdowns.columns == ["a"]


def test_parquet_filter_pushdown(pq_dir):
    df = dt.read_parquet(pq_dir).where(col("a") < 5)
    assert sorted(df.to_pydict()["a"]) == [0, 1, 2, 3, 4]


def test_parquet_limit_pushdown(pq_dir):
    df = dt.read_parquet(pq_dir).limit(7)
    assert df.count_rows() == 7


def test_write_parquet_roundtrip(tmp_path):
    df = dt.from_pydict({"x": [1, 2, 3], "y": ["a", "b", "c"]})
    res = df.write_parquet(str(tmp_path / "out"))
    paths = res.to_pydict()["path"]
    assert len(paths) == 1
    back = dt.read_parquet(paths).sort("x").to_pydict()
    assert back == {"x": [1, 2, 3], "y": ["a", "b", "c"]}


def test_write_parquet_partitioned(tmp_path):
    df = dt.from_pydict({"x": [1, 2, 3, 4], "p": ["a", "b", "a", "b"]})
    res = df.write_parquet(str(tmp_path / "out"), partition_cols=["p"])
    paths = sorted(res.to_pydict()["path"])
    assert len(paths) == 2
    assert any("p=a" in p for p in paths) and any("p=b" in p for p in paths)


def test_csv_roundtrip(tmp_path):
    df = dt.from_pydict({"x": [1, 2, 3], "y": ["a", "b", "c"]})
    res = df.write_csv(str(tmp_path / "out"))
    paths = res.to_pydict()["path"]
    back = dt.read_csv(paths).sort("x").to_pydict()
    assert back == {"x": [1, 2, 3], "y": ["a", "b", "c"]}


def test_json_roundtrip(tmp_path):
    df = dt.from_pydict({"x": [1, 2, 3], "y": ["a", "b", "c"]})
    res = df.write_json(str(tmp_path / "out"))
    paths = res.to_pydict()["path"]
    back = dt.read_json(paths).sort("x").to_pydict()
    assert back == {"x": [1, 2, 3], "y": ["a", "b", "c"]}


def test_from_glob_path(pq_dir):
    df = dt.from_glob_path(pq_dir + "/*.parquet")
    out = df.to_pydict()
    assert len(out["path"]) == 3
    assert all(s > 0 for s in out["size"])


def test_read_csv_no_headers(tmp_path):
    p = tmp_path / "x.csv"
    p.write_text("1,a\n2,b\n")
    df = dt.read_csv(str(p), has_headers=False)
    assert df.column_names == ["column_1", "column_2"]
    assert df.count_rows() == 2
