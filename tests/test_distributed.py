"""Multi-device (8-way virtual CPU mesh) tests for the sharded execution path.

Covers daft_tpu.parallel.distributed: data-parallel filter+agg with psum
combination, and the exact sharded groupby (unique + segment-reduce +
all_gather merge). Reference bar: hermetic distributed tests,
/root/reference/src/daft-distributed/src/scheduling/scheduler/mod.rs:257-298.
"""

import numpy as np
import pytest

import jax

from daft_tpu import col
from daft_tpu.datatype import DataType, Field
from daft_tpu.expressions.expressions import AggExpr
from daft_tpu.parallel.distributed import (
    default_mesh,
    groupby_host,
    shard_columns,
    shard_row_mask,
    sharded_filter_agg_step,
    sharded_groupby_step,
)
from daft_tpu.schema import Schema


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= 8, "conftest must provision 8 virtual devices"
    return default_mesh(8)


SCHEMA = Schema([
    Field("x", DataType.float64()),
    Field("y", DataType.float64()),
])


def _make_cols(n, rng, null_every=0):
    x = rng.uniform(0, 100, n)
    y = rng.uniform(-5, 5, n)
    xv = np.ones(n, bool)
    yv = np.ones(n, bool)
    if null_every:
        yv[::null_every] = False
    return {"x": (x, xv), "y": (y, yv)}


def test_mesh_has_8_devices(mesh):
    assert mesh.shape["dp"] == 8


def test_sharded_filter_agg_sum(mesh):
    rng = np.random.default_rng(0)
    n = 1000
    cols = _make_cols(n, rng)
    pred = col("x") > 50.0
    step = sharded_filter_agg_step(mesh, SCHEMA, pred, [("s", AggExpr("sum", col("y")))])
    out = step(shard_columns(mesh, cols, n), shard_row_mask(mesh, n))
    got = float(np.asarray(out[("s", "sum")][0]))
    keep = cols["x"][0] > 50.0
    np.testing.assert_allclose(got, cols["y"][0][keep].sum(), rtol=1e-9)


def test_sharded_filter_agg_count_modes(mesh):
    rng = np.random.default_rng(1)
    n = 333  # not a multiple of 8: exercises padding rows
    cols = _make_cols(n, rng, null_every=7)
    step = sharded_filter_agg_step(mesh, SCHEMA, None, [
        ("c_valid", AggExpr("count", col("y"))),
        ("c_all", AggExpr("count", col("y"), {"mode": "all"})),
    ])
    out = step(shard_columns(mesh, cols, n), shard_row_mask(mesh, n))
    n_valid = int(cols["y"][1].sum())
    assert int(np.asarray(out[("c_valid", "count")][0])) == n_valid
    assert int(np.asarray(out[("c_all", "count")][0])) == n


def test_sharded_filter_agg_mean_min_max(mesh):
    rng = np.random.default_rng(2)
    n = 4096
    cols = _make_cols(n, rng)
    step = sharded_filter_agg_step(mesh, SCHEMA, None, [
        ("m", AggExpr("mean", col("y"))),
        ("lo", AggExpr("min", col("y"))),
        ("hi", AggExpr("max", col("y"))),
    ])
    out = step(shard_columns(mesh, cols, n), shard_row_mask(mesh, n))
    y = cols["y"][0]
    s = float(np.asarray(out[("m", "sum")][0]))
    c = int(np.asarray(out[("m", "count")][0]))
    np.testing.assert_allclose(s / c, y.mean(), rtol=1e-9)
    np.testing.assert_allclose(float(np.asarray(out[("lo", "min")][0])), y.min())
    np.testing.assert_allclose(float(np.asarray(out[("hi", "max")][0])), y.max())


def test_sharded_filter_agg_nulls_excluded(mesh):
    rng = np.random.default_rng(3)
    n = 512
    cols = _make_cols(n, rng, null_every=3)
    step = sharded_filter_agg_step(mesh, SCHEMA, None, [("s", AggExpr("sum", col("y")))])
    out = step(shard_columns(mesh, cols, n), shard_row_mask(mesh, n))
    got = float(np.asarray(out[("s", "sum")][0]))
    np.testing.assert_allclose(got, cols["y"][0][cols["y"][1]].sum(), rtol=1e-9)


def test_sharded_filter_agg_output_replicated(mesh):
    rng = np.random.default_rng(4)
    n = 64
    cols = _make_cols(n, rng)
    step = sharded_filter_agg_step(mesh, SCHEMA, None, [("s", AggExpr("sum", col("x")))])
    out = step(shard_columns(mesh, cols, n), shard_row_mask(mesh, n))
    val = out[("s", "sum")][0]
    assert val.sharding.is_fully_replicated


def test_groupby_exact_no_bucket_collisions(mesh):
    # keys that all collide mod small bucket counts — the round-1 bug shape
    keys = np.array([0, 32, 64, 96, 128] * 40, dtype=np.int64)
    vals = np.arange(200, dtype=np.float64)
    gk, cols_out = groupby_host(mesh, keys, np.ones(200, bool),
                                [(vals, np.ones(200, bool))], ["sum"])
    assert sorted(gk.tolist()) == [0, 32, 64, 96, 128]
    got = dict(zip(gk.tolist(), cols_out[0][0].tolist()))
    for k in [0, 32, 64, 96, 128]:
        np.testing.assert_allclose(got[k], vals[keys == k].sum())


def test_groupby_negative_and_huge_keys(mesh):
    keys = np.array([-7, 2**40, -7, 3, 2**40, 3, -7], dtype=np.int64)
    vals = np.array([1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 7.0])
    gk, cols_out = groupby_host(mesh, keys, np.ones(7, bool),
                                [(vals, np.ones(7, bool))], ["sum"])
    got = dict(zip(gk.tolist(), cols_out[0][0].tolist()))
    assert got == {-7: 11.0, 3: 10.0, 2**40: 7.0}


def test_groupby_null_keys_excluded(mesh):
    keys = np.array([1, 2, 1, 2, 3], dtype=np.int64)
    kv = np.array([True, True, True, False, False])
    vals = np.ones(5)
    gk, cols_out = groupby_host(mesh, keys, kv, [(vals, np.ones(5, bool))], ["count"])
    got = dict(zip(gk.tolist(), cols_out[0][0].tolist()))
    assert got == {1: 2, 2: 1}


def test_groupby_all_null_value_group_invalid(mesh):
    keys = np.array([1, 1, 2, 2], dtype=np.int64)
    vals = np.array([5.0, 6.0, 0.0, 0.0])
    vvalid = np.array([True, True, False, False])
    gk, cols_out = groupby_host(mesh, keys, np.ones(4, bool), [(vals, vvalid)], ["sum"])
    got = {k: (v, ok) for k, v, ok in zip(gk.tolist(), *cols_out[0:1][0])}
    assert got[1] == (11.0, True)
    assert got[2][1] == False  # noqa: E712 — all-null group => invalid sum


def test_groupby_mean_min_max(mesh):
    rng = np.random.default_rng(5)
    n = 1000
    keys = rng.integers(0, 13, n).astype(np.int64) * 1_000_003  # sparse key domain
    vals = rng.uniform(-10, 10, n)
    gk, cols_out = groupby_host(
        mesh, keys, np.ones(n, bool),
        [(vals, np.ones(n, bool))] * 3, ["mean", "min", "max"])
    for k, mv, lo, hi in zip(gk.tolist(), cols_out[0][0], cols_out[1][0], cols_out[2][0]):
        sel = vals[keys == k]
        np.testing.assert_allclose(mv, sel.mean(), rtol=1e-9)
        np.testing.assert_allclose(lo, sel.min())
        np.testing.assert_allclose(hi, sel.max())


def test_groupby_overflow_retries_to_correct_answer(mesh):
    # 600 distinct keys with initial capacity 16 => overflow path must double up
    n = 600
    keys = np.arange(n, dtype=np.int64) * 7919
    vals = np.ones(n)
    gk, cols_out = groupby_host(mesh, keys, np.ones(n, bool),
                                [(vals, np.ones(n, bool))], ["sum"], capacity=16)
    assert len(gk) == n
    np.testing.assert_allclose(cols_out[0][0], np.ones(n))


def test_groupby_step_overflow_flag(mesh):
    n = 64
    keys = np.arange(n, dtype=np.int64)
    cols = {"k": (keys, np.ones(n, bool)), "v": (np.ones(n), np.ones(n, bool))}
    sh = shard_columns(mesh, cols, n)
    step = sharded_groupby_step(mesh, ["sum"], capacity=4)
    _, _, overflow, _ = step(sh["k"][0], sh["k"][1], sh["v"][0], sh["v"][1])
    assert bool(np.asarray(overflow))


def test_groupby_random_vs_numpy(mesh):
    rng = np.random.default_rng(6)
    n = 5000
    keys = rng.integers(-1000, 1000, n).astype(np.int64)
    vals = rng.normal(size=n)
    vvalid = rng.random(n) > 0.1
    gk, cols_out = groupby_host(mesh, keys, np.ones(n, bool), [(vals, vvalid)], ["sum"])
    expect_keys = np.unique(keys)
    assert sorted(gk.tolist()) == expect_keys.tolist()
    got = dict(zip(gk.tolist(), cols_out[0][0].tolist()))
    for k in expect_keys:
        sel = vals[(keys == k) & vvalid]
        if len(sel):
            np.testing.assert_allclose(got[int(k)], sel.sum(), rtol=1e-8, atol=1e-8)


def test_shard_columns_pads_with_invalid(mesh):
    n = 10
    cols = {"x": (np.arange(n, dtype=np.float64), np.ones(n, bool))}
    out = shard_columns(mesh, cols, n)
    vals, valid = np.asarray(out["x"][0]), np.asarray(out["x"][1])
    assert len(vals) % 8 == 0
    assert valid.sum() == n
    assert vals[:n].tolist() == list(range(n))


def test_engine_grouped_agg_on_mesh_matches_host():
    """VERDICT r3 item: df.groupby().agg() on a mesh-enabled session must
    execute the mesh-sharded groupby (counter-asserted) with host-equal results."""
    import numpy as np

    import daft_tpu
    from daft_tpu import col
    from daft_tpu.config import execution_config_ctx
    from daft_tpu.ops import counters

    rng = np.random.default_rng(7)
    n = 5000
    df = daft_tpu.from_pydict({
        "k": rng.choice(["a", "b", "c", None, "d"], n).tolist(),
        "v": [None if i % 13 == 0 else float(i % 101) for i in range(n)],
        "w": rng.integers(0, 1000, n).tolist(),
    })

    def q(d):
        return (d.where(col("w") < 900)
                .groupby("k")
                .agg(col("v").sum().alias("s"), col("v").mean().alias("m"),
                     col("v").min().alias("lo"), col("v").max().alias("hi"),
                     col("v").count().alias("c"))
                .sort("k"))

    counters.reset()
    with execution_config_ctx(device_mode="on", mesh_devices=8):
        mesh_out = q(df).to_pydict()
    assert counters.mesh_grouped_runs > 0, "mesh path never executed"
    with execution_config_ctx(device_mode="off", mesh_devices=0):
        host_out = q(df).to_pydict()
    assert mesh_out["k"] == host_out["k"]
    assert mesh_out["c"] == host_out["c"]
    for c in ("s", "m", "lo", "hi"):
        np.testing.assert_allclose(
            np.array(mesh_out[c], dtype=float), np.array(host_out[c], dtype=float),
            rtol=1e-9)


def test_mesh_grouped_agg_empty_after_filter():
    """Predicate filtering out every row must return an empty result, not crash."""
    import daft_tpu
    from daft_tpu import col
    from daft_tpu.config import execution_config_ctx

    df = daft_tpu.from_pydict({"k": ["a", "b"], "v": [1.0, 2.0], "w": [1, 2]})
    with execution_config_ctx(device_mode="on", mesh_devices=8):
        out = (df.where(col("w") > 100).groupby("k")
               .agg(col("v").sum().alias("s")).to_pydict())
    assert out == {"k": [], "s": []}


def test_autoscaling_scale_up():
    """Pending demand beyond capacity * threshold grows the pool toward
    max_workers (reference: scheduler/default.rs get_autoscaling_request)."""
    from daft_tpu.distributed.scheduler import Scheduler
    from daft_tpu.distributed.worker import SubPlanTask

    sched = Scheduler({"w0": 1})
    assert sched.get_autoscaling_request() is None
    for i in range(4):
        sched.submit(SubPlanTask(task_id=f"t{i}", plan_blob=b"", strategy=None,
                                 priority=0))
    req = sched.get_autoscaling_request()
    assert req is not None and len(req) == 4
    # with ample capacity no request fires
    sched2 = Scheduler({"w0": 8})
    sched2.submit(SubPlanTask(task_id="t", plan_blob=b"", strategy=None,
                              priority=0))
    assert sched2.get_autoscaling_request() is None


def test_autoscaling_pool_grows():
    """A pool with max_workers > num_workers spawns extra workers when the
    task queue exceeds capacity, and completes all tasks."""
    import daft_tpu
    from daft_tpu.distributed.runner import DistributedRunner

    from daft_tpu import col

    runner = DistributedRunner(num_workers=1, n_partitions=6, max_workers=3)
    try:
        n = 20_000
        left = daft_tpu.from_pydict({"id": list(range(n)), "v": list(range(n))})
        right = daft_tpu.from_pydict({"id": list(range(0, n, 2)),
                                      "w": list(range(0, n, 2))})
        q = left.join(right, on="id", how="inner")
        parts = runner.run(q._builder)
        total = sum(p.num_rows for p in parts)
        assert total == n // 2
        assert len(runner._pool.workers) > 1, "pool never scaled up"
    finally:
        runner.shutdown()
