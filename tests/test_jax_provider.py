"""On-device (JAX) AI provider: embed/classify with ZERO network
(VERDICT r4 next #7 — the TPU-native engine runs models on its own device;
reference contrast daft/ai/transformers runs torch on host)."""

import numpy as np

import daft_tpu
from daft_tpu.ai.provider import get_provider
from daft_tpu.functions.ai import classify_text, embed_text


def test_embedder_deterministic_and_normalized():
    e = get_provider("jax").get_text_embedder()
    v1 = e.embed_text(["hello tpu world", "data engines"])
    v2 = e.embed_text(["hello tpu world", "data engines"])
    assert len(v1) == 2 and len(v1[0]) == e.dimensions
    np.testing.assert_allclose(v1[0], v2[0], rtol=1e-5)
    assert abs(np.linalg.norm(v1[0]) - 1.0) < 1e-4
    # different texts embed differently
    assert not np.allclose(v1[0], v1[1])


def test_embedder_batch_padding_stable():
    e = get_provider("jax").get_text_embedder()
    solo = e.embed_text(["padding should not change me"])[0]
    batch = e.embed_text(["padding should not change me"] + [f"t{i}" for i in range(6)])[0]
    np.testing.assert_allclose(solo, batch, atol=1e-5)


def test_embed_text_expression_with_nulls():
    df = daft_tpu.from_pydict({"t": ["alpha beta", None, "gamma"]})
    out = df.select(embed_text(daft_tpu.col("t"), provider="jax").alias("e")) \
        .to_pydict()
    assert out["e"][1] is None
    assert len(out["e"][0]) == len(out["e"][2]) > 0


def test_classifier_separates_self_labels():
    c = get_provider("jax").get_text_classifier()
    # a label classifies as itself in embedding space (cosine with itself = 1)
    labels = ["alpha bravo", "charlie delta", "echo foxtrot"]
    assert c.classify_text(list(labels), labels) == labels


def test_classify_expression():
    df = daft_tpu.from_pydict({"t": ["red green", "blue yellow"]})
    out = df.select(classify_text(daft_tpu.col("t"), ["red green", "blue yellow"],
                                  provider="jax").alias("c")).to_pydict()
    assert out["c"] == ["red green", "blue yellow"]
