"""Lazy File type: ranged reads, file()/file_path/file_size/file_read.

Reference parity: src/daft-file/ (lazy handle + ranged reads) and
daft/file/file.py (File python surface).
"""

import os

import pytest

import daft_tpu
from daft_tpu import col
from daft_tpu.filetype import File


@pytest.fixture
def paths(tmp_path):
    p1 = tmp_path / "a.txt"
    p1.write_text("hello world")
    p2 = tmp_path / "b.bin"
    p2.write_bytes(bytes(range(100)))
    return str(p1), str(p2)


def test_file_object_lazy_ranged(paths):
    p1, p2 = paths
    f = File(p1)
    assert f.size() == 11
    assert f.name == "a.txt"
    assert f.mime_type() == "text/plain"
    with f.open() as h:
        assert h.seekable() and h.readable() and not h.writable()
        h.seek(6)
        assert h.read(5) == b"world"
        assert h.tell() == 11
        assert h.read() == b""
        h.seek(-5, os.SEEK_END)
        assert h.read() == b"world"


def test_file_to_tempfile(paths):
    p1, _ = paths
    with File(p1).to_tempfile() as tmp:
        assert open(tmp.name, "rb").read() == b"hello world"


def test_file_column_expressions(paths):
    p1, p2 = paths
    df = daft_tpu.from_pydict({"p": [p1, p2, None]})
    fdf = df.select(daft_tpu.file(col("p")).alias("f"))
    assert fdf.schema["f"].dtype == daft_tpu.DataType.file()
    out = fdf.select(col("f").file_path().alias("path"),
                     col("f").file_size().alias("sz"),
                     col("f").file_read(offset=1, length=3).alias("c")).to_pydict()
    assert out["path"] == [p1, p2, None]
    assert out["sz"] == [11, 100, None]
    assert out["c"] == [b"ell", bytes([1, 2, 3]), None]


def test_file_read_whole(paths):
    p1, _ = paths
    df = daft_tpu.from_pydict({"p": [p1]})
    out = df.select(daft_tpu.file(col("p")).file_read()).to_pydict()
    assert out["p"] == [b"hello world"]


def test_from_files(tmp_path, paths):
    import daft_tpu

    out = daft_tpu.from_files(str(tmp_path / "*.txt")).to_pydict()
    assert "file" in out and "path" in out and "size" in out
    assert out["size"] == [11]


def test_read_lance_gated():
    import daft_tpu

    with pytest.raises(ImportError, match="lance"):
        daft_tpu.read_lance("/nonexistent")
