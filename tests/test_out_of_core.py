"""Out-of-core execution: blocking operators must respect memory_limit_bytes
by spilling (Grace hash partitions for agg/join, range-bucketed runs for sort)
and produce results identical to the unbounded in-memory paths."""

import numpy as np
import pytest

import daft_tpu
from daft_tpu import col
from daft_tpu.config import execution_config_ctx
from daft_tpu.execution import memory as mem


@pytest.fixture
def data():
    rng = np.random.default_rng(42)
    n = 50_000
    return daft_tpu.from_pydict({
        "k": rng.integers(0, 500, n).tolist(),
        "s": rng.choice(["aa", "bb", "cc", None, "dd"], n).tolist(),
        "v": [None if i % 17 == 0 else float(i % 1009) for i in range(n)],
    })


def _with_and_without_cap(q):
    mem.reset_counters()
    with execution_config_ctx(memory_limit_bytes=64 * 1024, device_mode="off"):
        capped = q().to_pydict()
    assert mem.spills > 0, "memory cap never triggered a spill"
    mem.reset_counters()
    with execution_config_ctx(memory_limit_bytes=0, device_mode="off"):
        unbounded = q().to_pydict()
    assert mem.spills == 0
    return capped, unbounded


def test_grouped_agg_spills_and_matches(data):
    def q():
        return (data.groupby("k")
                .agg(col("v").sum().alias("sv"), col("v").mean().alias("mv"),
                     col("v").count().alias("c"), col("v").min().alias("lo"),
                     col("v").max().alias("hi"))
                .sort("k"))

    capped, unbounded = _with_and_without_cap(q)
    assert capped["k"] == unbounded["k"]
    assert capped["c"] == unbounded["c"]
    for c in ("sv", "mv", "lo", "hi"):
        np.testing.assert_allclose(capped[c], unbounded[c], rtol=1e-12)


def test_grouped_agg_string_keys_with_nulls_spills(data):
    def q():
        return (data.groupby("s").agg(col("v").sum().alias("sv")).sort("s"))

    capped, unbounded = _with_and_without_cap(q)
    assert capped == unbounded


def test_count_distinct_grace_raw_spill(data):
    """Unsplittable aggs (count_distinct) Grace-partition raw rows by key."""
    def q():
        return (data.groupby("k")
                .agg(col("v").count_distinct().alias("cd"))
                .sort("k"))

    capped, unbounded = _with_and_without_cap(q)
    assert capped == unbounded


def test_external_sort_matches(data):
    def q():
        return data.sort(["v", "k"])

    capped, unbounded = _with_and_without_cap(q)
    assert capped == unbounded


def test_external_sort_descending_nulls(data):
    def q():
        return data.sort(["v"], desc=True)

    capped, unbounded = _with_and_without_cap(q)
    assert capped == unbounded


def test_external_sort_string_key(data):
    def q():
        return data.sort(["s", "v"])

    capped, unbounded = _with_and_without_cap(q)
    assert capped == unbounded


def test_grace_join_matches(data):
    rng = np.random.default_rng(7)
    other = daft_tpu.from_pydict({
        "k": rng.integers(0, 500, 30_000).tolist(),
        "w": rng.uniform(0, 1, 30_000).tolist(),
    })

    def q():
        return (data.join(other, on="k")
                .groupby("k").agg(col("w").sum().alias("sw"))
                .sort("k"))

    capped, unbounded = _with_and_without_cap(q)
    assert capped["k"] == unbounded["k"]
    np.testing.assert_allclose(capped["sw"], unbounded["sw"], rtol=1e-12)


def test_grace_outer_join_matches(data):
    left = daft_tpu.from_pydict({
        "k": list(range(20_000)),
        "x": [float(i) for i in range(20_000)],
    })
    right = daft_tpu.from_pydict({
        "k": list(range(10_000, 30_000)),
        "y": [float(i) for i in range(10_000, 30_000)],
    })

    def q():
        return left.join(right, on="k", how="outer").sort("k")

    capped, unbounded = _with_and_without_cap(q)
    assert capped == unbounded


def test_tpch_q1_under_memory_cap():
    """A TPC-H pipeline completes under an enforced memory cap with exact results."""
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from benchmarking.tpch.datagen import load_dataframes
    from benchmarking.tpch.queries import ALL_QUERIES

    tables = {k: v.collect() for k, v in load_dataframes(sf=0.05, seed=0).items()}
    mem.reset_counters()
    with execution_config_ctx(memory_limit_bytes=256 * 1024, device_mode="off"):
        capped = ALL_QUERIES[1](tables).to_pydict()
    assert mem.spills > 0
    with execution_config_ctx(memory_limit_bytes=0, device_mode="off"):
        unbounded = ALL_QUERIES[1](tables).to_pydict()
    assert capped["l_returnflag"] == unbounded["l_returnflag"]
    for c in capped:
        if isinstance(capped[c][0], float):
            np.testing.assert_allclose(capped[c], unbounded[c], rtol=1e-12)
        else:
            assert capped[c] == unbounded[c]


def test_external_sort_presorted_input_resplits():
    """Already-sorted input defeats prefix boundary sampling (everything lands
    in the last bucket); the bucket re-splits recursively from its own data
    instead of materializing the whole dataset."""
    n = 60_000
    df = daft_tpu.from_pydict({"v": [float(i) for i in range(n)]})

    def q():
        return df.sort(["v"])

    capped, unbounded = _with_and_without_cap(q)
    assert capped == unbounded


def test_window_spills_and_matches(data):
    """Out-of-core window: over budget, the stream Grace-partitions by the
    PARTITION BY keys and each spill partition evaluates independently
    (reference: sinks/window_partition_only.rs)."""
    from daft_tpu import Window
    from daft_tpu.functions import rank

    w = Window().partition_by("k").order_by("v")

    def q():
        return (data.select(
            col("k"), col("v"),
            col("v").sum().over(w).alias("ws"),
            rank().over(w).alias("wr"),
        ).sort(["k", "v", "ws"]))

    capped, unbounded = _with_and_without_cap(q)
    assert capped == unbounded


def test_global_window_over_budget_still_exact(data):
    from daft_tpu import Window

    w = Window().order_by("v")

    def q():
        return data.select(col("v"), col("v").sum().over(w).alias("c")).sort(["v", "c"])

    mem.reset_counters()
    with execution_config_ctx(memory_limit_bytes=64 * 1024, device_mode="off"):
        capped = q().to_pydict()
    with execution_config_ctx(memory_limit_bytes=0, device_mode="off"):
        unbounded = q().to_pydict()
    assert capped == unbounded


def test_count_distinct_spills_and_matches(data):
    """Unsplittable ungrouped aggs over budget spill the raw stream once and
    Grace-partition each count_distinct's value column — no unbounded buffer."""
    def q():
        return data.agg(
            col("s").count_distinct().alias("ds"),
            col("v").count_distinct().alias("dv"),
            col("v").sum().alias("sv"),
        )

    capped, unbounded = _with_and_without_cap(q)
    assert capped == unbounded


def test_streaming_dedup_incremental_matches(data):
    """Dedup keeps first occurrences via the amortized probe-table path; force
    several rebuilds with a small input stream by distinct-ing a high-dup col."""
    def q():
        return data.distinct("k").sort("k")

    with execution_config_ctx(device_mode="off"):
        out = q().to_pydict()
    ks = [k for k in out["k"]]
    assert len(ks) == len(set(ks))
    assert sorted(set(data.to_pydict()["k"])) == sorted(ks)


def test_sort_merge_join_strategy_matches_hash(data):
    dim = daft_tpu.from_pydict({"k": list(range(0, 500, 3)),
                                "w": [float(i) for i in range(0, 500, 3)]})
    for how in ("inner", "left", "semi", "anti", "right", "outer"):
        sm = (data.join(dim, on="k", how=how, strategy="sort_merge")
              .sort(["k", "v"]).limit(200).to_pydict())
        hj = data.join(dim, on="k", how=how).sort(["k", "v"]).limit(200).to_pydict()
        assert sm == hj, how


def test_sort_merge_algorithm_kernel_parity():
    """join_indices(algorithm='sort_merge') (order-preserving encode + sorted
    merge) must produce the same pairs as the hash algorithm."""
    import numpy as np

    from daft_tpu.core.kernels.join import join_indices
    from daft_tpu.core.series import Series

    rng = np.random.default_rng(5)
    l = [Series.from_pylist([int(x) if x % 7 else None for x in rng.integers(0, 40, 200)], "a")]
    r = [Series.from_pylist([int(x) if x % 5 else None for x in rng.integers(0, 40, 80)], "a")]
    for how in ("inner", "left", "semi", "anti", "outer"):
        for nen in (False, True):
            h = join_indices(l, r, how, nen)
            s = join_indices(l, r, how, nen, algorithm="sort_merge")
            assert np.array_equal(h[0], s[0]) and np.array_equal(h[1], s[1]), (how, nen)


def test_streaming_dedup_rebuild_path():
    """Enough distinct keys to cross the 64k rebuild threshold: the amortized
    ProbeTable build+probe branch must run and stay exact (keep-first)."""
    n = 150_000
    df = daft_tpu.from_pydict({
        "k": [i % 140_000 for i in range(n)],
        "v": list(range(n)),
    })
    with execution_config_ctx(device_mode="off", pipeline_mode="off"):
        # multiple batches so later batches PROBE the rebuilt table
        out = df.into_batches(32 * 1024).select(col("k")).distinct("k").to_pydict()
    assert len(out["k"]) == 140_000
    assert out["k"][:5] == [0, 1, 2, 3, 4]  # keep-first preserves stream order
