"""Out-of-core execution: blocking operators must respect memory_limit_bytes
by spilling (Grace hash partitions for agg/join, range-bucketed runs for sort)
and produce results identical to the unbounded in-memory paths."""

import numpy as np
import pytest

import daft_tpu
from daft_tpu import col
from daft_tpu.config import execution_config_ctx
from daft_tpu.execution import memory as mem


@pytest.fixture
def data():
    rng = np.random.default_rng(42)
    n = 50_000
    return daft_tpu.from_pydict({
        "k": rng.integers(0, 500, n).tolist(),
        "s": rng.choice(["aa", "bb", "cc", None, "dd"], n).tolist(),
        "v": [None if i % 17 == 0 else float(i % 1009) for i in range(n)],
    })


def _with_and_without_cap(q):
    mem.reset_counters()
    with execution_config_ctx(memory_limit_bytes=64 * 1024, device_mode="off"):
        capped = q().to_pydict()
    assert mem.spills > 0, "memory cap never triggered a spill"
    mem.reset_counters()
    with execution_config_ctx(memory_limit_bytes=0, device_mode="off"):
        unbounded = q().to_pydict()
    assert mem.spills == 0
    return capped, unbounded


def test_grouped_agg_spills_and_matches(data):
    def q():
        return (data.groupby("k")
                .agg(col("v").sum().alias("sv"), col("v").mean().alias("mv"),
                     col("v").count().alias("c"), col("v").min().alias("lo"),
                     col("v").max().alias("hi"))
                .sort("k"))

    capped, unbounded = _with_and_without_cap(q)
    assert capped["k"] == unbounded["k"]
    assert capped["c"] == unbounded["c"]
    for c in ("sv", "mv", "lo", "hi"):
        np.testing.assert_allclose(capped[c], unbounded[c], rtol=1e-12)


def test_grouped_agg_string_keys_with_nulls_spills(data):
    def q():
        return (data.groupby("s").agg(col("v").sum().alias("sv")).sort("s"))

    capped, unbounded = _with_and_without_cap(q)
    assert capped == unbounded


def test_count_distinct_grace_raw_spill(data):
    """Unsplittable aggs (count_distinct) Grace-partition raw rows by key."""
    def q():
        return (data.groupby("k")
                .agg(col("v").count_distinct().alias("cd"))
                .sort("k"))

    capped, unbounded = _with_and_without_cap(q)
    assert capped == unbounded


def test_external_sort_matches(data):
    def q():
        return data.sort(["v", "k"])

    capped, unbounded = _with_and_without_cap(q)
    assert capped == unbounded


def test_external_sort_descending_nulls(data):
    def q():
        return data.sort(["v"], desc=True)

    capped, unbounded = _with_and_without_cap(q)
    assert capped == unbounded


def test_external_sort_string_key(data):
    def q():
        return data.sort(["s", "v"])

    capped, unbounded = _with_and_without_cap(q)
    assert capped == unbounded


def test_grace_join_matches(data):
    rng = np.random.default_rng(7)
    other = daft_tpu.from_pydict({
        "k": rng.integers(0, 500, 30_000).tolist(),
        "w": rng.uniform(0, 1, 30_000).tolist(),
    })

    def q():
        return (data.join(other, on="k")
                .groupby("k").agg(col("w").sum().alias("sw"))
                .sort("k"))

    capped, unbounded = _with_and_without_cap(q)
    assert capped["k"] == unbounded["k"]
    np.testing.assert_allclose(capped["sw"], unbounded["sw"], rtol=1e-12)


def test_grace_outer_join_matches(data):
    left = daft_tpu.from_pydict({
        "k": list(range(20_000)),
        "x": [float(i) for i in range(20_000)],
    })
    right = daft_tpu.from_pydict({
        "k": list(range(10_000, 30_000)),
        "y": [float(i) for i in range(10_000, 30_000)],
    })

    def q():
        return left.join(right, on="k", how="outer").sort("k")

    capped, unbounded = _with_and_without_cap(q)
    assert capped == unbounded


def test_tpch_q1_under_memory_cap():
    """A TPC-H pipeline completes under an enforced memory cap with exact results."""
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from benchmarking.tpch.datagen import load_dataframes
    from benchmarking.tpch.queries import ALL_QUERIES

    tables = {k: v.collect() for k, v in load_dataframes(sf=0.05, seed=0).items()}
    mem.reset_counters()
    with execution_config_ctx(memory_limit_bytes=256 * 1024, device_mode="off"):
        capped = ALL_QUERIES[1](tables).to_pydict()
    assert mem.spills > 0
    with execution_config_ctx(memory_limit_bytes=0, device_mode="off"):
        unbounded = ALL_QUERIES[1](tables).to_pydict()
    assert capped["l_returnflag"] == unbounded["l_returnflag"]
    for c in capped:
        if isinstance(capped[c][0], float):
            np.testing.assert_allclose(capped[c], unbounded[c], rtol=1e-12)
        else:
            assert capped[c] == unbounded[c]


def test_external_sort_presorted_input_resplits():
    """Already-sorted input defeats prefix boundary sampling (everything lands
    in the last bucket); the bucket re-splits recursively from its own data
    instead of materializing the whole dataset."""
    n = 60_000
    df = daft_tpu.from_pydict({"v": [float(i) for i in range(n)]})

    def q():
        return df.sort(["v"])

    capped, unbounded = _with_and_without_cap(q)
    assert capped == unbounded
