import datetime

import numpy as np
import pytest

import daft_tpu
from daft_tpu import DataType, RecordBatch, Schema, Series
from daft_tpu.expressions import col, lit


def B(**data):
    return RecordBatch.from_pydict(data)


def test_column_and_literal():
    b = B(a=[1, 2, 3])
    assert b.eval_expression(col("a")).to_pylist() == [1, 2, 3]
    out = b.eval_expression_list([col("a"), lit(7).alias("seven")])
    assert out.to_pydict() == {"a": [1, 2, 3], "seven": [7, 7, 7]}


def test_arithmetic_and_schema():
    b = B(a=[1, 2, None], x=[1.5, 2.5, 3.5])
    s = Schema.from_pydict({"a": DataType.int64(), "x": DataType.float64()})
    e = (col("a") + 1) * col("x")
    assert e.to_field(s).dtype == DataType.float64()
    assert b.eval_expression(e).to_pylist() == [3.0, 7.5, None]
    assert (col("a") / 2).to_field(s).dtype == DataType.float64()
    assert b.eval_expression(col("a") / 2).to_pylist() == [0.5, 1.0, None]
    assert b.eval_expression(2 - col("a")).to_pylist() == [1, 0, None]


def test_comparison_and_logic():
    b = B(a=[1, 2, 3, None])
    e = (col("a") > 1) & (col("a") < 3)
    assert b.eval_expression(e).to_pylist() == [False, True, False, None]
    assert b.eval_expression(~(col("a") >= 2)).to_pylist() == [True, False, False, None]


def test_null_ops():
    b = B(a=[1, None, 3])
    assert b.eval_expression(col("a").is_null()).to_pylist() == [False, True, False]
    assert b.eval_expression(col("a").not_null()).to_pylist() == [True, False, True]
    assert b.eval_expression(col("a").fill_null(0)).to_pylist() == [1, 0, 3]


def test_is_in_between_if_else():
    b = B(a=[1, 2, 3, 4])
    assert b.eval_expression(col("a").is_in([2, 4])).to_pylist() == [False, True, False, True]
    assert b.eval_expression(col("a").between(2, 3)).to_pylist() == [False, True, True, False]
    e = (col("a") % 2 == 0).if_else(lit("even"), lit("odd"))
    assert b.eval_expression(e).to_pylist() == ["odd", "even", "odd", "even"]


def test_cast_and_alias():
    b = B(a=[1, 2])
    out = b.eval_expression_list([col("a").cast(DataType.string()).alias("s")])
    assert out.to_pydict() == {"s": ["1", "2"]}
    s = Schema.from_pydict({"a": DataType.int64()})
    assert col("a").cast(DataType.float32()).to_field(s).dtype == DataType.float32()


def test_numeric_functions():
    b = B(x=[1.0, 4.0, None])
    assert b.eval_expression(col("x").sqrt()).to_pylist() == [1.0, 2.0, None]
    out = b.eval_expression(col("x").exp()).to_pylist()
    assert abs(out[0] - np.e) < 1e-9 and out[2] is None
    assert b.eval_expression(col("x").log2()).to_pylist()[1] == 2.0
    b2 = B(x=[1.4, -2.7])
    assert b2.eval_expression(col("x").floor()).to_pylist() == [1.0, -3.0]
    assert b2.eval_expression(col("x").ceil()).to_pylist() == [2.0, -2.0]
    assert b2.eval_expression(col("x").abs()).to_pylist() == [1.4, 2.7]
    assert b2.eval_expression(col("x").round(0)).to_pylist() == [1.0, -3.0]


def test_string_functions():
    b = B(s=["Hello World", "foo", None])
    assert b.eval_expression(col("s").str.upper()).to_pylist() == ["HELLO WORLD", "FOO", None]
    assert b.eval_expression(col("s").str.lower()).to_pylist() == ["hello world", "foo", None]
    assert b.eval_expression(col("s").str.length()).to_pylist() == [11, 3, None]
    assert b.eval_expression(col("s").str.contains("oo")).to_pylist() == [False, True, None]
    assert b.eval_expression(col("s").str.startswith("He")).to_pylist() == [True, False, None]
    assert b.eval_expression(col("s").str.endswith("ld")).to_pylist() == [True, False, None]
    assert b.eval_expression(col("s").str.split(" ")).to_pylist() == [["Hello", "World"], ["foo"], None]
    assert b.eval_expression(col("s").str.substr(0, 4)).to_pylist() == ["Hell", "foo", None]
    assert b.eval_expression(col("s").str.replace("o", "0")).to_pylist() == ["Hell0 W0rld", "f00", None]
    assert b.eval_expression(col("s").str.reverse()).to_pylist() == ["dlroW olleH", "oof", None]
    assert b.eval_expression(col("s").str.left(2)).to_pylist() == ["He", "fo", None]
    assert b.eval_expression(col("s").str.like("He%")).to_pylist() == [True, False, None]
    assert b.eval_expression(col("s").str.find("World")).to_pylist() == [6, -1, None]


def test_string_concat_expr():
    b = B(a=["x", "y"], b=["1", "2"])
    out = b.eval_expression(col("a") + col("b"))
    assert out.to_pylist() == ["x1", "y2"]
    out = b.eval_expression(col("a").str.concat("-suffix"))
    assert out.to_pylist() == ["x-suffix", "y-suffix"]


def test_temporal_functions():
    ts = [datetime.datetime(2024, 3, 15, 10, 30, 45), datetime.datetime(2021, 12, 1, 0, 0, 0), None]
    b = B(t=ts)
    assert b.eval_expression(col("t").dt.year()).to_pylist() == [2024, 2021, None]
    assert b.eval_expression(col("t").dt.month()).to_pylist() == [3, 12, None]
    assert b.eval_expression(col("t").dt.day()).to_pylist() == [15, 1, None]
    assert b.eval_expression(col("t").dt.hour()).to_pylist() == [10, 0, None]
    assert b.eval_expression(col("t").dt.minute()).to_pylist() == [30, 0, None]
    assert b.eval_expression(col("t").dt.date()).to_pylist() == [
        datetime.date(2024, 3, 15), datetime.date(2021, 12, 1), None,
    ]
    # temporal arithmetic typing
    s = Schema.from_pydict({"t": DataType.timestamp("us")})
    assert (col("t") - col("t")).to_field(s).dtype == DataType.duration("us")


def test_to_date_parse():
    b = B(s=["2024-01-05", "not a date", None])
    out = b.eval_expression(col("s").str.to_date("%Y-%m-%d")).to_pylist()
    assert out == [datetime.date(2024, 1, 5), None, None]


def test_list_functions():
    b = B(l=[[1, 2, 3], [4], None, []])
    assert b.eval_expression(col("l").list.length()).to_pylist() == [3, 1, None, 0]
    assert b.eval_expression(col("l").list.sum()).to_pylist() == [6, 4, None, None]
    assert b.eval_expression(col("l").list.mean()).to_pylist() == [2.0, 4.0, None, None]
    assert b.eval_expression(col("l").list.min()).to_pylist() == [1, 4, None, None]
    assert b.eval_expression(col("l").list.max()).to_pylist() == [3, 4, None, None]
    assert b.eval_expression(col("l").list.get(0)).to_pylist() == [1, 4, None, None]
    assert b.eval_expression(col("l").list.get(5, default=-1)).to_pylist() == [-1, -1, None, -1]
    assert b.eval_expression(col("l").list.contains(2)).to_pylist() == [True, False, None, False]
    assert b.eval_expression(col("l").list.slice(0, 2)).to_pylist() == [[1, 2], [4], None, []]


def test_list_join():
    b = B(l=[["a", "b"], ["c"], None])
    assert b.eval_expression(col("l").list.join(",")).to_pylist() == ["a,b", "c", None]


def test_float_namespace():
    b = B(x=[1.0, float("nan"), None, float("inf")])
    assert b.eval_expression(col("x").float.is_nan()).to_pylist() == [False, True, None, False]
    assert b.eval_expression(col("x").float.is_inf()).to_pylist() == [False, False, None, True]
    out = b.eval_expression(col("x").float.fill_nan(0.0)).to_pylist()
    assert out == [1.0, 0.0, None, float("inf")]


def test_embedding_distance():
    b = RecordBatch.from_pydict({
        "e": Series.from_numpy(np.array([[1.0, 0.0], [0.0, 1.0]]), "e",
                               DataType.embedding(DataType.float64(), 2)),
    })
    q = np.array([1.0, 0.0])
    out = b.eval_expression(col("e").embedding.cosine_distance(lit(q))).to_pylist()
    assert abs(out[0] - 0.0) < 1e-9
    assert abs(out[1] - 1.0) < 1e-9


def test_struct_get():
    b = B(s=[{"x": 1, "y": "a"}, {"x": 2, "y": "b"}])
    assert b.eval_expression(col("s").struct.get("x")).to_pylist() == [1, 2]
    assert b.eval_expression(col("s").struct.get("y")).to_pylist() == ["a", "b"]


def test_hash_and_minhash_exprs():
    b = B(s=["hello world", "hello world", "goodbye"])
    h = b.eval_expression(col("s").hash()).to_pylist()
    assert h[0] == h[1] != h[2]
    mh = b.eval_expression(col("s").minhash(num_hashes=8, ngram_size=1)).to_pylist()
    assert list(mh[0]) == list(mh[1])
    assert list(mh[0]) != list(mh[2])


def test_udf_rowwise():
    @daft_tpu.func
    def add_one(x: int) -> int:
        return x + 1

    b = B(a=[1, 2, 3])
    assert b.eval_expression(add_one(col("a"))).to_pylist() == [2, 3, 4]


def test_udf_batch():
    @daft_tpu.func(is_batch=True, return_dtype=DataType.float64())
    def double(s):
        import numpy as np
        return Series.from_numpy(s.to_numpy() * 2.0, "out")

    b = B(a=[1.0, 2.0])
    assert b.eval_expression(double(col("a"))).to_pylist() == [2.0, 4.0]


def test_type_errors():
    s = Schema.from_pydict({"a": DataType.int64(), "s": DataType.string()})
    with pytest.raises(ValueError):
        (col("a") & col("a")).to_field(s)  # logical op on ints
    with pytest.raises(ValueError):
        (col("s") * col("a")).to_field(s)
    with pytest.raises(KeyError):
        col("zzz").to_field(s)
    with pytest.raises(ValueError):
        bool(col("a") > 1)


def test_agg_expr_typing():
    s = Schema.from_pydict({"a": DataType.int32(), "f": DataType.float32()})
    assert col("a").sum().to_field(s).dtype == DataType.int64()
    assert col("a").mean().to_field(s).dtype == DataType.float64()
    assert col("a").count().to_field(s).dtype == DataType.uint64()
    assert col("f").min().to_field(s).dtype == DataType.float32()
    assert col("a").agg_list().to_field(s).dtype == DataType.list(DataType.int32())
    with pytest.raises(ValueError):
        b = B(a=[1])
        b.eval_expression(col("a").sum())


def test_referenced_columns_and_transform():
    e = (col("a") + col("b")) * col("a")
    assert e.referenced_columns() == ["a", "b"]
    # rewrite col(a) -> col(z)
    from daft_tpu.expressions.expressions import ColumnRef

    e2 = e.transform(lambda n: ColumnRef("z") if isinstance(n, ColumnRef) and n._name == "a" else None)
    assert e2.referenced_columns() == ["z", "b"]


def test_stddev_var_ddof_small_groups_null():
    # count <= ddof must yield NULL, not inf/NaN (one-phase and two-phase kernels)
    import daft_tpu
    from daft_tpu import col
    df = daft_tpu.from_pydict({"k": ["a", "a", "b"], "v": [1.0, 3.0, 5.0]})
    out = (
        df.groupby("k")
        .agg(
            col("v").var(ddof=1).alias("v1"),
            col("v").stddev(ddof=1).alias("s1"),
        )
        .sort("k")
        .to_pydict()
    )
    assert out["v1"][0] == 2.0
    assert out["v1"][1] is None
    assert out["s1"][1] is None
