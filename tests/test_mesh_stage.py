"""In-mesh SPMD device stages (ops/mesh_stage.py) under 8 forced host devices.

Covers the r7 tentpole: bit-exact parity of mesh vs single-chip vs host for
grouped/ungrouped aggregation and the sharded join feed (including int64
exactness — the PR-2 quantization lesson), the group-table capacity-growth
re-run path, coalesced feeds, sharded resident planes (repeat h2d flat, pin
scopes under a tiny HBM budget), the cost-model ICI tier flip at calibrated
boundaries, the loud single-chip fallback when a forced mesh exceeds the
local device count, and the zero-overhead guard (mesh off => no mesh
imports). Run standalone via `make test-mesh`.
"""

import os
import sys

import numpy as np
import pytest

import jax

import daft_tpu
from daft_tpu import col
from daft_tpu.config import execution_config_ctx
from daft_tpu.observability.metrics import registry
from daft_tpu.ops import counters


pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices — see conftest")


def _groupby_query(d):
    return (d.where(col("w") < 900)
            .groupby("k")
            .agg(col("v").sum().alias("s"), col("v").mean().alias("m"),
                 col("v").min().alias("lo"), col("v").max().alias("hi"),
                 col("v").count().alias("c"), col("big").sum().alias("bs"))
            .sort("k"))


@pytest.fixture(scope="module")
def df():
    rng = np.random.default_rng(7)
    n = 5000
    return daft_tpu.from_pydict({
        "k": rng.choice(["a", "b", "c", None, "d"], n).tolist(),
        "v": [None if i % 13 == 0 else float(i % 101) for i in range(n)],
        "w": rng.integers(0, 1000, n).tolist(),
        # > 2^53: any float round-trip of the sum is observable
        "big": (2**53 + rng.integers(0, 1000, n)).tolist(),
    })


def test_grouped_parity_mesh_vs_single_vs_host(df):
    """Streaming mesh grouped stage: same results as single-chip and host,
    with int64 sums EXACT and the mesh counters proving the path ran."""
    counters.reset()
    with execution_config_ctx(device_mode="on", mesh_devices=8):
        mesh_out = _groupby_query(df).to_pydict()
    assert counters.mesh_grouped_runs > 0
    assert counters.mesh_dispatches > 0
    counters.reset()
    with execution_config_ctx(device_mode="on", mesh_devices=1):
        single_out = _groupby_query(df).to_pydict()
    assert counters.mesh_dispatches == 0, "mesh_devices=1 must stay single-chip"
    assert counters.device_grouped_batches > 0
    with execution_config_ctx(device_mode="off"):
        host_out = _groupby_query(df).to_pydict()
    for out in (mesh_out, single_out):
        assert out["k"] == host_out["k"]
        assert out["c"] == host_out["c"]
        for c in ("s", "m", "lo", "hi"):
            np.testing.assert_allclose(
                np.array(out[c], dtype=float),
                np.array(host_out[c], dtype=float), rtol=1e-12)
    # int64 sums: the mesh kernel segment-reduces in int64 end to end, so it
    # is EXACT even though the float min/max in this query forces the
    # single-chip stage into f64 mode (whose int sums round past 2^53 — a
    # pre-existing single-chip limitation, asserted only to its tolerance)
    assert mesh_out["bs"] == host_out["bs"], "mesh int64 sum not exact"
    np.testing.assert_allclose(np.array(single_out["bs"], dtype=float),
                               np.array(host_out["bs"], dtype=float),
                               rtol=1e-12)


def test_ungrouped_parity_mesh_vs_host(df):
    def q(d):
        return d.where(col("w") < 900).agg(
            col("v").sum().alias("s"), col("v").count().alias("c"),
            col("v").min().alias("lo"), col("v").mean().alias("m"),
            col("big").sum().alias("bs"))

    counters.reset()
    with execution_config_ctx(device_mode="on", mesh_devices=8):
        mesh_out = q(df).to_pydict()
    assert counters.mesh_dispatches > 0
    with execution_config_ctx(device_mode="off"):
        host_out = q(df).to_pydict()
    assert mesh_out["c"] == host_out["c"]
    assert mesh_out["bs"] == host_out["bs"], "int64 sum not exact"
    np.testing.assert_allclose(mesh_out["s"], host_out["s"], rtol=1e-12)
    np.testing.assert_allclose(mesh_out["m"], host_out["m"], rtol=1e-12)
    np.testing.assert_allclose(mesh_out["lo"], host_out["lo"])


def test_mesh_empty_after_filter():
    df = daft_tpu.from_pydict({"k": ["a", "b"], "v": [1.0, 2.0], "w": [1, 2]})
    with execution_config_ctx(device_mode="on", mesh_devices=8):
        out = (df.where(col("w") > 100).groupby("k")
               .agg(col("v").sum().alias("s")).to_pydict())
    assert out == {"k": [], "s": []}


# ---- sharded join feed ---------------------------------------------------------------


def test_sharded_join_feed_ungrouped_int64_exact():
    """Fact rows sharded, dim planes replicated: probe = local gather,
    reduce = psum over ICI. int64 dim sums must be bit-exact."""
    from daft_tpu.ops.mesh_stage import mesh_join_ungrouped_agg
    from daft_tpu.parallel.distributed import default_mesh

    mesh = default_mesh(8)
    rng = np.random.default_rng(0)
    n, dim_n = 10_000, 64
    idx = rng.integers(-1, dim_n, n).astype(np.int64)  # -1 = no match
    dim_vals = (2**53 + rng.integers(0, 10_000, dim_n)).astype(np.int64)
    fact_vals = rng.normal(size=n)
    fact_valid = rng.random(n) > 0.1
    before = counters.mesh_dispatches
    res = mesh_join_ungrouped_agg(
        mesh, n, [idx],
        [(dim_vals, np.ones(dim_n, bool)), (fact_vals, fact_valid),
         (dim_vals, np.ones(dim_n, bool))],
        [("sum", 0), ("mean", -1), ("max", 0)])
    assert counters.mesh_dispatches > before
    keep = idx >= 0
    assert res[0] == int(dim_vals[idx[keep]].sum()), "int64 join sum not exact"
    np.testing.assert_allclose(
        res[1], fact_vals[keep & fact_valid].mean(), rtol=1e-12)
    assert res[2] == int(dim_vals[idx[keep]].max())


def test_sharded_join_feed_grouped_matches_numpy():
    """Grouped join feed: dim group codes gathered to fact rows (broadcast
    probe), exact sharded groupby merges per-shard tables over ICI."""
    from daft_tpu.ops.mesh_stage import mesh_join_grouped_agg
    from daft_tpu.parallel.distributed import default_mesh

    mesh = default_mesh(8)
    rng = np.random.default_rng(1)
    n, dim_n, n_codes = 8_000, 50, 7
    idx = rng.integers(-1, dim_n, n).astype(np.int64)
    dim_codes = rng.integers(0, n_codes, dim_n).astype(np.int64)
    fact_vals = (2**53 + rng.integers(0, 1000, n)).astype(np.int64)
    gk, cols = mesh_join_grouped_agg(
        mesh, n, idx, dim_codes,
        [(fact_vals, np.ones(n, bool), -1)], ["sum"], num_codes=n_codes)
    keep = idx >= 0
    codes = dim_codes[idx[keep]]
    expected = {int(c): int(fact_vals[keep][codes == c].sum())
                for c in np.unique(codes)}
    got = dict(zip(gk.tolist(), cols[0][0].tolist()))
    assert got == expected, "grouped join feed not bit-exact"


# ---- capacity growth (overflow re-run) -----------------------------------------------


def test_group_table_capacity_growth():
    """A batch with more groups than the run's table capacity grows the
    static capacity (recompile at the new shape — the streaming analogue of
    groupby_host's overflow retry) instead of overflowing on device."""
    from daft_tpu.ops.mesh_stage import try_build_mesh_grouped_agg_stage

    n_keys = 300
    df = daft_tpu.from_pydict({"k": list(range(n_keys)) * 10,
                               "v": list(range(n_keys * 10))}).collect()
    batch = df._result[0].batches[0]
    stage = try_build_mesh_grouped_agg_stage(
        df.schema, None, [col("k")], [col("v").sum().alias("s")], 8,
        initial_capacity=16)
    assert stage is not None
    run = stage.start_run()
    before = counters.mesh_capacity_growths
    run.feed_batch(batch)
    keys, results = run.finalize()
    assert counters.mesh_capacity_growths > before
    assert len(keys) == n_keys
    vals, valid = results[0]
    assert valid.all()
    arr_k = np.array(list(range(n_keys)) * 10)
    arr_v = np.arange(n_keys * 10)
    for i, (key,) in enumerate(keys[:5]):
        assert int(vals[i]) == int(arr_v[arr_k == key].sum())


# ---- coalesced feed ------------------------------------------------------------------


def test_coalesced_feed_into_mesh_stage():
    """The DispatchCoalescer in front of a mesh run merges N morsels into
    one super-batch => ONE multi-device dispatch covering them all."""
    from daft_tpu.ops.mesh_stage import try_build_mesh_grouped_agg_stage
    from daft_tpu.ops.stage import DispatchCoalescer

    df = daft_tpu.from_pydict({"k": (np.arange(4000) % 3).tolist(),
                               "v": np.arange(4000, dtype=float).tolist()}).collect()
    batch = df._result[0].batches[0]
    morsels = [batch.slice(s, s + 500) for s in range(0, 4000, 500)]
    stage = try_build_mesh_grouped_agg_stage(
        df.schema, None, [col("k")], [col("v").sum().alias("s")], 8)
    run = stage.start_run()
    coal = DispatchCoalescer(run.feed_batch, target_rows=100_000, latency_s=60.0)
    d0 = counters.mesh_dispatches
    for m in morsels:
        coal.add(m)
    coal.close()
    keys, results = run.finalize()
    assert counters.mesh_dispatches - d0 == 1, "morsels were not coalesced"
    got = dict(zip((k[0] for k in keys), results[0][0].tolist()))
    arr = np.arange(4000, dtype=float)
    for k in range(3):
        np.testing.assert_allclose(got[k], arr[np.arange(4000) % 3 == k].sum())


# ---- sharded resident planes ---------------------------------------------------------


def test_repeat_mesh_query_h2d_flat_and_digest():
    """Second identical mesh query reads sharded resident planes: zero new
    h2d bytes (counter-asserted), and the sharded slots publish in the
    residency digest (the heartbeat vocabulary) like any other plane."""
    from daft_tpu.device.residency import manager

    df = daft_tpu.from_pydict({"k": (np.arange(4000) % 5).tolist(),
                               "v": np.arange(4000).tolist()})

    def q(d):
        return d.groupby("k").agg(col("v").sum().alias("s")).sort("k")

    with execution_config_ctx(device_mode="on", mesh_devices=8):
        first = q(df).to_pydict()
        h1 = registry().get("hbm_h2d_bytes")
        second = q(df).to_pydict()
        h2 = registry().get("hbm_h2d_bytes")
    assert first == second
    assert h2 == h1, f"repeat mesh query re-uploaded {h2 - h1} bytes"
    assert len(manager().digest()) > 0, "sharded slots missing from digest"


def test_mesh_planes_pin_under_tiny_hbm_budget():
    """Sharded planes built inside a query pin via the executor's pin_scope:
    a budget far below the working set must not thrash them mid-query."""
    df = daft_tpu.from_pydict({"k": (np.arange(6000) % 7).tolist(),
                               "v": (np.arange(6000) % 101).astype(float).tolist()})

    def q(d):
        return d.groupby("k").agg(col("v").sum().alias("s"),
                                  col("v").count().alias("c")).sort("k")

    with execution_config_ctx(device_mode="off"):
        host_out = q(df).to_pydict()
    counters.reset()
    with execution_config_ctx(device_mode="on", mesh_devices=8,
                              hbm_budget_bytes=1024):
        mesh_out = q(df).to_pydict()
    assert counters.mesh_grouped_runs > 0
    assert counters.hbm_pins > 0, "mesh planes never pinned"
    assert mesh_out["k"] == host_out["k"] and mesh_out["c"] == host_out["c"]
    np.testing.assert_allclose(mesh_out["s"], host_out["s"], rtol=1e-12)


# ---- cost-model ICI tier -------------------------------------------------------------


_PINNED = {
    "DAFT_TPU_COST_RTT": "0.001", "DAFT_TPU_COST_H2D": "1e12",
    "DAFT_TPU_COST_D2H": "1e9", "DAFT_TPU_COST_MM_RATE": "1e9",
    "DAFT_TPU_COST_MM_CELL_RATE": "3e7", "DAFT_TPU_COST_MESH_DISPATCH": "0.05",
    "DAFT_TPU_COST_ICI": "1e12", "DAFT_TPU_COST_HOST_AGG": "1e3",
    "DAFT_TPU_COST_HOST_FACT": "1e9",
}


def test_auto_tier_flips_at_calibrated_boundary():
    """mesh_devices=0: the decision cache picks the mesh for a large-shape
    stage and rejects it for a tiny one — mesh must WIN its placement. Cost
    knobs are env-pinned so the boundary is deterministic on any host."""
    from daft_tpu.execution import executor
    from daft_tpu.ops import costmodel

    os.environ.update(_PINNED)
    costmodel.reset_calibration()
    executor._MESH_TIER_CACHE.clear()
    try:
        big = daft_tpu.from_pydict({
            "k": (np.arange(200_000) % 5).tolist(),
            "v": (np.arange(200_000) % 97).astype(float).tolist()})
        small = daft_tpu.from_pydict({
            "k": (np.arange(2_000) % 5).tolist(),
            "v": (np.arange(2_000) % 97).astype(float).tolist()})

        def q(d):
            return d.groupby("k").agg(col("v").sum().alias("s")).sort("k")

        counters.reset()
        with execution_config_ctx(device_mode="on", mesh_devices=0,
                                  device_min_rows=1):
            big_out = q(big).to_pydict()
        assert counters.mesh_grouped_runs > 0, "auto tier rejected the big shape"
        counters.reset()
        with execution_config_ctx(device_mode="on", mesh_devices=0,
                                  device_min_rows=1):
            q(small).to_pydict()
        assert counters.mesh_grouped_runs == 0, "auto tier took a tiny shape"
        assert counters.device_grouped_batches > 0
        with execution_config_ctx(device_mode="off"):
            host_out = q(big).to_pydict()
        assert big_out["k"] == host_out["k"]
        np.testing.assert_allclose(big_out["s"], host_out["s"], rtol=1e-12)
    finally:
        for k in _PINNED:
            os.environ.pop(k, None)
        costmodel.reset_calibration()
        executor._MESH_TIER_CACHE.clear()


def test_mesh_cost_functions_scale():
    """Unit sanity on the ICI tier terms: mesh amortizes compute by the mesh
    width but pays the dispatch premium and the collective."""
    from daft_tpu.ops import costmodel

    cal = costmodel.Calibration(
        rtt_s=0.001, h2d_bytes_per_s=1e9, d2h_bytes_per_s=1e9,
        mm_plane_rows_per_s=1e9, mm_cell_rate=5e10, scatter_rows_per_s=1e8,
        ext_cell_rate=5e9, host_agg_rate=1.5e8, host_factorize_rate=8e6,
        host_probe_rate=3e7, ici_bytes_per_s=4.5e10, mesh_dispatch_s=2e-3)
    small = costmodel.mesh_ungrouped_cost(cal, 10_000, 0, 2, 8)
    single_small = costmodel.device_ungrouped_cost(cal, 10_000, 0, 2)
    assert small > single_small, "tiny shapes must not prefer the mesh"
    big_mesh = costmodel.mesh_grouped_cost(cal, 500_000_000, 0, 4, 1024, 8,
                                           factorize_rows=0)
    big_single = costmodel.device_grouped_sort_cost(cal, 500_000_000, 0,
                                                    n_planes=4,
                                                    factorize_rows=0)
    assert big_mesh < big_single, "huge shapes must amortize across the mesh"


# ---- forced-mesh fallback + config ---------------------------------------------------


def test_forced_mesh_over_device_count_falls_back_loudly():
    df = daft_tpu.from_pydict({"k": ["a", "b"] * 100,
                               "v": list(range(200))})
    counters.reset()
    with execution_config_ctx(device_mode="on", mesh_devices=16):
        out = df.groupby("k").agg(col("v").sum().alias("s")).sort("k").to_pydict()
    assert counters.mesh_unavailable_fallbacks > 0
    assert counters.mesh_grouped_runs == 0
    assert counters.device_grouped_batches > 0, "fallback must still run device"
    assert out["s"] == [sum(range(0, 200, 2)), sum(range(1, 200, 2))]


def test_default_mesh_rejects_oversized_request():
    from daft_tpu.parallel.distributed import default_mesh

    with pytest.raises(ValueError, match="devices"):
        default_mesh(len(jax.devices()) + 1)


def test_config_rejects_negative_mesh_devices():
    from daft_tpu.config import ExecutionConfig

    with pytest.raises(ValueError, match="mesh_devices"):
        ExecutionConfig(mesh_devices=-1)


# ---- zero-overhead guard -------------------------------------------------------------


def test_mesh_off_means_no_mesh_imports():
    """mesh_devices=1 (the off switch): a device query must not import the
    mesh machinery at all — the zero-overhead contract extension."""
    sys.modules.pop("daft_tpu.ops.mesh_stage", None)
    df = daft_tpu.from_pydict({"k": ["a", "b"] * 50, "v": list(range(100))})
    with execution_config_ctx(device_mode="on", mesh_devices=1):
        df.groupby("k").agg(col("v").sum().alias("s")).to_pydict()
    assert "daft_tpu.ops.mesh_stage" not in sys.modules, \
        "mesh stage imported with the mesh disabled"


# ---- EXPLAIN ANALYZE -----------------------------------------------------------------


def test_explain_analyze_renders_mesh_line():
    df = daft_tpu.from_pydict({"k": (np.arange(2000) % 4).tolist(),
                               "v": np.arange(2000, dtype=float).tolist()})
    with execution_config_ctx(device_mode="on", mesh_devices=8):
        report = (df.groupby("k").agg(col("v").sum().alias("s"))
                  .explain_analyze())
    assert "mesh: 8 devices" in report
    assert "mesh_dispatches" in report  # engine-counter delta table
