"""Breadth features: text/WARC readers, tokenize, DDSketch percentiles,
simplify-expressions, range_between window frames."""

import gzip
import os

import numpy as np
import pytest

import daft_tpu
from daft_tpu import col, lit, Window


def test_read_text(tmp_path):
    p = tmp_path / "a.txt"
    p.write_text("hello\nworld\n\nlast")
    out = daft_tpu.read_text(str(p)).to_pydict()
    assert out == {"text": ["hello", "world", "", "last"]}


def test_read_text_gz_and_limit(tmp_path):
    p = tmp_path / "b.txt.gz"
    with gzip.open(p, "wt") as f:
        f.write("\n".join(f"line{i}" for i in range(100)))
    out = daft_tpu.read_text(str(p)).limit(5).to_pydict()
    assert out["text"] == [f"line{i}" for i in range(5)]


def _write_warc(path, records):
    with open(path, "wb") as f:
        for rid, rtype, uri, body in records:
            payload = body.encode()
            hdr = (f"WARC/1.0\r\n"
                   f"WARC-Record-ID: {rid}\r\n"
                   f"WARC-Type: {rtype}\r\n"
                   + (f"WARC-Target-URI: {uri}\r\n" if uri else "")
                   + f"Content-Length: {len(payload)}\r\n"
                   f"Content-Type: text/plain\r\n\r\n").encode()
            f.write(hdr + payload + b"\r\n\r\n")


def test_read_warc(tmp_path):
    p = tmp_path / "cc.warc"
    _write_warc(p, [
        ("<urn:uuid:1>", "warcinfo", None, "software: test"),
        ("<urn:uuid:2>", "response", "http://example.com", "<html>hi</html>"),
        ("<urn:uuid:3>", "response", "http://example.org", "body text"),
    ])
    out = daft_tpu.read_warc(str(p)).to_pydict()
    assert out["warc_type"] == ["warcinfo", "response", "response"]
    assert out["warc_target_uri"] == [None, "http://example.com", "http://example.org"]
    assert out["content"][1] == "<html>hi</html>"
    assert out["content_length"][2] == len(b"body text")


def test_warc_common_crawl_dedup_shape(tmp_path):
    """The Common Crawl config shape: read_warc -> minhash -> dedup."""
    p = tmp_path / "cc.warc"
    _write_warc(p, [
        ("<urn:uuid:1>", "response", "http://a", "the quick brown fox jumps"),
        ("<urn:uuid:2>", "response", "http://b", "the quick brown fox jumps"),
        ("<urn:uuid:3>", "response", "http://c", "совершенно другой текст"),
    ])
    df = (daft_tpu.read_warc(str(p))
          .where(col("warc_type") == "response")
          .with_column("sig", col("content").minhash(num_hashes=8, ngram_size=2)))
    out = df.to_pydict()
    assert out["sig"][0] == out["sig"][1] != out["sig"][2]


def test_tokenize_bytes_roundtrip():
    df = daft_tpu.from_pydict({"t": ["hello", "héllo", None]})
    enc = df.with_column("ids", col("t").tokenize_encode())
    out = enc.with_column("back", col("ids").tokenize_decode()).to_pydict()
    assert out["back"] == ["hello", "héllo", None]
    assert out["ids"][0] == list(b"hello")


def test_simplify_expressions_folds_plan():
    from daft_tpu.plan import logical as lp
    from daft_tpu.plan.optimizer import simplify_expr

    e = (col("x") + 0) * 1 + (lit(2) + lit(3))
    s = simplify_expr(e)
    assert repr(s) == repr(col("x") + lit(5)), repr(s)
    # boolean identities (Kleene-safe)
    p = (lit(True) & (col("x") > 1)) | lit(False)
    assert repr(simplify_expr(p)) == repr(col("x") > 1)
    # x*0 must NOT fold (null propagation)
    z = col("x") * 0
    assert repr(simplify_expr(z)) == repr(z)
    # end-to-end: results unchanged
    df = daft_tpu.from_pydict({"x": [1, 2, None]})
    assert df.select(((col("x") + 0) * 1).alias("x")).to_pydict() == {"x": [1, 2, None]}


def test_range_between_window():
    df = daft_tpu.from_pydict({
        "g": ["a", "a", "a", "b", "b"],
        "t": [1, 3, 6, 2, 4],
        "v": [10.0, 20.0, 30.0, 5.0, 7.0],
    })
    w = Window().partition_by("g").order_by("t").range_between(-2, 0)
    out = df.select("g", "t", col("v").sum().over(w).alias("s")).sort(["g", "t"]).to_pydict()
    assert out["s"] == [10.0, 30.0, 30.0, 5.0, 12.0]
    wd = Window().partition_by("g").order_by("t", desc=True).range_between(-2, 0)
    outd = df.select("g", "t", col("v").sum().over(wd).alias("s")).sort(["g", "t"]).to_pydict()
    assert outd["s"] == [30.0, 20.0, 30.0, 12.0, 7.0]


def test_range_between_unbounded_and_nulls():
    df = daft_tpu.from_pydict({
        "t": [1, 2, None, 10],
        "v": [1.0, 2.0, 4.0, 8.0],
    })
    w = Window().order_by("t").range_between(Window.unbounded_preceding, 0)
    out = df.select("t", col("v").sum().over(w).alias("s")).sort("t").to_pydict()
    # t=1 -> 1; t=2 -> 3; t=10 -> 11; null key frames over its peer group -> 4
    assert out["s"][:3] == [1.0, 3.0, 11.0]
    assert out["s"][3] == 4.0


def test_approx_percentile_grouped_and_listed():
    rng = np.random.default_rng(1)
    vals = rng.uniform(0, 100, 20_000)
    df = daft_tpu.from_pydict({"k": (np.arange(20_000) % 2).tolist(), "v": vals.tolist()})
    out = df.groupby("k").agg(
        col("v").approx_percentile(0.5).alias("p50"),
        col("v").approx_percentile(0.25, 0.75).alias("pq")).sort("k").to_pydict()
    for i in range(2):
        sel = vals[np.arange(20_000) % 2 == i]
        assert abs(out["p50"][i] - np.percentile(sel, 50)) / 50 < 0.05
        assert len(out["pq"][i]) == 2


def test_simplify_preserves_promotion_dtypes():
    """int_col / 1 promotes to float64 and int_col + 0.0 to float — rewrites
    that would change the resolved dtype must not fire."""
    df = daft_tpu.from_pydict({"a": [1, 2, 3]})
    out = df.select((col("a") / 1).alias("x"))
    assert out.schema["x"].dtype == daft_tpu.DataType.float64()
    assert out.to_pydict()["x"] == [1.0, 2.0, 3.0]
    out2 = df.select((col("a") + 0.0).alias("x"))
    assert out2.to_pydict()["x"] == [1.0, 2.0, 3.0]


def test_range_between_nulls_first():
    df = daft_tpu.from_pydict({
        "t": [None, 1, 2, 3, 4],
        "v": [10.0, 1.0, 1.0, 1.0, 1.0],
    })
    w = Window().order_by("t", nulls_first=True).range_between(-1, 0)
    out = df.select("t", col("v").sum().over(w).alias("s")).to_pydict()
    by_t = dict(zip(out["t"], out["s"]))
    assert by_t[None] == 10.0  # null key frames over its peer group
    assert by_t[1] == 1.0 and by_t[2] == 2.0 and by_t[3] == 2.0 and by_t[4] == 2.0


def test_function_breadth_binary_crypto_bitwise():
    """daft-functions-binary / hash / bitwise parity (registry extra module)."""
    import daft_tpu as dt
    from daft_tpu import col

    df = dt.from_pydict({"s": ["hello", None], "b": [b"\x01\xff", b""],
                         "x": [12, 10], "y": [10, 3]})
    out = df.select(
        col("b").binary.length().alias("bl"),
        col("b").binary.encode_hex().alias("hx"),
        col("s").binary.encode_base64().alias("b64"),
        col("s").str.md5().alias("md5"),
        col("s").str.sha256().alias("sha"),
        col("x")._fn("bitwise_and", col("y")).alias("ba"),
        col("x")._fn("bitwise_or", col("y")).alias("bo"),
        col("x")._fn("bitwise_xor", col("y")).alias("bx"),
        col("x")._fn("shift_left", 2).alias("sl"),
    ).to_pydict()
    assert out["bl"] == [2, 0]
    assert out["hx"] == ["01ff", ""]
    assert out["b64"] == ["aGVsbG8=", None]
    assert out["md5"][0] == "5d41402abc4b2a76b9719d911017c592"
    assert len(out["sha"][0]) == 64 and out["sha"][1] is None
    assert out["ba"] == [8, 2] and out["bo"] == [14, 11] and out["bx"] == [6, 9]
    assert out["sl"] == [48, 40]
    # hex/base64 roundtrip
    rt = df.select(col("b").binary.encode_hex().binary.decode_hex().alias("r")).to_pydict()
    assert rt["r"] == [b"\x01\xff", b""]


def test_function_breadth_json_map_temporal_strings():
    import datetime

    import daft_tpu as dt
    from daft_tpu import col

    df = dt.from_pydict({
        "j": ['{"a": {"b": [10, 20]}}', '{"a": {}}', None],
        "d": [datetime.date(2024, 2, 5), datetime.date(2023, 7, 1), None],
        "s": ["kitten", "saturday", None],
    })
    out = df.select(
        col("j").json.query("$.a.b[1]").alias("jq"),
        col("d").dt.quarter().alias("q"),
        col("d").dt.is_leap_year().alias("ly"),
        col("d").dt.days_in_month().alias("dim"),
        col("s").str.title().alias("t"),
        col("s").str.levenshtein("sitting").alias("lev"),
        col("s").str.jaccard_similarity("saturday").alias("jac"),
    ).to_pydict()
    assert out["jq"] == ["20", None, None]
    assert out["q"] == [1, 3, None]
    assert out["ly"] == [True, False, None]
    assert out["dim"] == [29, 31, None]
    assert out["t"] == ["Kitten", "Saturday", None]
    assert out["lev"] == [3, 6, None]
    assert out["jac"][1] == 1.0 and out["jac"][2] is None


def test_function_breadth_coalesce_and_to_json():
    import daft_tpu as dt
    from daft_tpu import col

    df = dt.from_pydict({"a": [None, 2, None], "b": [10, 20, None], "c": [1, 1, 1]})
    out = df.select(
        col("a")._fn("coalesce", col("b"), col("c")).alias("co"),
        col("a")._fn("to_json").alias("tj"),
    ).to_pydict()
    assert out["co"] == [10, 2, 1]
    assert out["tj"] == [None, "2", None]
