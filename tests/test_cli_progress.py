"""CLI + progress subscriber (reference: daft/cli.py, progress_bar.py)."""

import io
import json

import daft_tpu
from daft_tpu.cli import main


def test_cli_schema_and_sql(tmp_path, capsys):
    daft_tpu.from_pydict({"a": [3, 1, 2], "b": ["x", "y", "z"]}).write_parquet(str(tmp_path / "t"))
    pat = str(tmp_path / "t" / "*.parquet")
    assert main(["schema", pat]) == 0
    out = capsys.readouterr().out
    assert "a: Int64" in out and "b: String" in out

    assert main(["sql", "SELECT a FROM t ORDER BY a DESC", "-t", f"t={pat}", "--json"]) == 0
    out = capsys.readouterr().out
    assert json.loads(out.strip()) == {"a": [3, 2, 1]}


def test_cli_info(capsys):
    assert main(["info"]) == 0
    out = capsys.readouterr().out
    assert "daft_tpu" in out and "execution config" in out


def test_progress_subscriber_reports_queries():
    from daft_tpu.observability.progress import ProgressSubscriber
    from daft_tpu.observability import attach_subscriber, detach_subscriber

    buf = io.StringIO()
    buf.isatty = lambda: False
    sub = ProgressSubscriber(stream=buf)
    attach_subscriber(sub)
    try:
        daft_tpu.from_pydict({"a": [1, 2, 3]}).where(daft_tpu.col("a") > 1).to_pydict()
    finally:
        detach_subscriber(sub)
    text = buf.getvalue()
    assert "✓ query" in text and "2 rows" in text
