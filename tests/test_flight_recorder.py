"""Flight recorder: bounded ring + drop accounting, zero-overhead off-switch,
per-kind anomaly triggers (slow query EMA, query error, ledger pressure,
device fallback, worker death), multi-tenant dump no-bleed under a threaded
serving hammer, and the doctor CLI over committed captures and fresh dumps."""

import json
import os
import sys
import threading
import subprocess

import pytest

import daft_tpu as dt
from daft_tpu import col
from daft_tpu.observability import flight
from daft_tpu.observability.metrics import registry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_recorder():
    """Each test resolves its own recorder from (monkeypatched) env; drop the
    cached resolution on both sides so no test inherits another's knobs."""
    flight._reset_for_tests()
    yield
    flight._reset_for_tests()


def _recorder(monkeypatch, tmp_path, ring=8, wall_k=1.0, min_s=0.0,
              cooldown=0.0):
    monkeypatch.setenv("DAFT_TPU_FLIGHT_RECORDER", "1")
    monkeypatch.setenv("DAFT_TPU_FLIGHT_RING", str(ring))
    monkeypatch.setenv("DAFT_TPU_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("DAFT_TPU_ANOMALY_WALL_K", str(wall_k))
    monkeypatch.setenv("DAFT_TPU_ANOMALY_MIN_S", str(min_s))
    monkeypatch.setenv("DAFT_TPU_ANOMALY_COOLDOWN_S", str(cooldown))
    rec = flight.recorder()
    assert rec is not None
    return rec


def _dumps(tmp_path):
    return sorted(str(p) for p in tmp_path.glob("flight_*.json"))


# ---------------------------------------------------------------------------
# ring discipline
# ---------------------------------------------------------------------------

def test_ring_bounded_with_drop_accounting_and_registry_silent(monkeypatch,
                                                               tmp_path):
    rec = _recorder(monkeypatch, tmp_path, ring=8)
    before = registry().snapshot()
    for i in range(30):
        rec.record("query", query_id=f"q{i}", seconds=0.001)
    assert len(rec.snapshot()) == 8
    assert rec.dropped == 22
    # newest events survive, oldest evicted FIFO
    assert [ev["query_id"] for ev in rec.snapshot()] == \
        [f"q{i}" for i in range(22, 30)]
    assert rec.snapshot(limit=3) == rec.snapshot()[-3:]
    # ring maintenance (appends AND evictions) never touches the registry —
    # the tier-1 empty-diff guard must hold with the recorder ON
    assert registry().diff(before) == {}
    assert not _dumps(tmp_path)


def test_recorder_off_is_none_and_registry_silent(monkeypatch):
    monkeypatch.setenv("DAFT_TPU_FLIGHT_RECORDER", "0")
    before = registry().snapshot()
    assert flight.recorder() is None
    assert flight.recorder() is None  # resolved once, stays None
    # a full query through the native runner with the recorder off must
    # leave no flight_* trace (the hook sites skip on one `is None` test)
    df = dt.from_pydict({"k": [1, 2, 1, 2], "v": [1.0, 2.0, 3.0, 4.0]})
    df.groupby("k").agg(col("v").sum().alias("s")).sort("k").to_pydict()
    after = registry().snapshot()
    assert {k: v for k, v in registry().diff(before).items()
            if k.startswith("flight_")} == {}
    assert after.get("flight_anomalies_total", 0) == \
        before.get("flight_anomalies_total", 0)


def test_ring_hammer_from_many_threads_stays_bounded(monkeypatch, tmp_path):
    rec = _recorder(monkeypatch, tmp_path, ring=16)
    n_threads, per_thread = 8, 200

    def hammer(tid):
        for i in range(per_thread):
            rec.record("query", tenant=f"t{tid}", query_id=f"{tid}-{i}")

    threads = [threading.Thread(target=hammer, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(rec.snapshot()) == 16
    assert rec.dropped == n_threads * per_thread - 16


# ---------------------------------------------------------------------------
# anomaly triggers, one per kind
# ---------------------------------------------------------------------------

def test_slow_query_trigger_via_ema(monkeypatch, tmp_path):
    rec = _recorder(monkeypatch, tmp_path, wall_k=2.0)
    a0 = registry().get("flight_anomalies_total")
    rec.note_query("planA", 0.01)           # seeds the EMA, cannot trigger
    rec.note_query("planA", 0.012)          # within 2x: no trigger
    assert not _dumps(tmp_path)
    rec.note_query("planA", 0.5)            # 0.5 > 2x EMA(~0.01): trigger
    dumps = _dumps(tmp_path)
    assert len(dumps) == 1
    with open(dumps[0]) as f:
        dump = json.load(f)
    assert dump["kind"] == "slow_query"
    assert "planA" in dump["detail"] and "EMA" in dump["detail"]
    assert dump["ema"]["planA"] > 0
    assert [ev["kind"] for ev in dump["ring"]].count("query") == 3
    assert registry().get("flight_anomalies_total") - a0 == 1
    assert rec.dumps == dumps


def test_slow_query_floor_suppresses_fast_queries(monkeypatch, tmp_path):
    rec = _recorder(monkeypatch, tmp_path, wall_k=1.0, min_s=10.0)
    rec.note_query("planA", 0.001)
    rec.note_query("planA", 1.0)            # 1000x the EMA but under the floor
    assert not _dumps(tmp_path)


def test_query_error_trigger(monkeypatch, tmp_path):
    rec = _recorder(monkeypatch, tmp_path)
    rec.note_query("planB", 0.01, query_id="qerr",
                   error="ValueError: boom")
    dumps = _dumps(tmp_path)
    assert len(dumps) == 1
    with open(dumps[0]) as f:
        dump = json.load(f)
    assert dump["kind"] == "query_error"
    assert dump["query_id"] == "qerr"
    assert "boom" in dump["detail"]


def test_ledger_pressure_crossing_triggers(monkeypatch, tmp_path):
    from daft_tpu.config import execution_config_ctx
    from daft_tpu.memory import manager

    _recorder(monkeypatch, tmp_path)
    m = manager()
    m.clear()
    try:
        with execution_config_ctx(memory_limit_bytes=1000,
                                  memory_pressure=0.8):
            m.track(700)                    # below threshold: no anomaly
            assert not _dumps(tmp_path)
            m.track(200)                    # 900 >= 800: upward crossing
            dumps = _dumps(tmp_path)
            assert len(dumps) == 1
            with open(dumps[0]) as f:
                dump = json.load(f)
            assert dump["kind"] == "ledger_pressure"
            ev = [e for e in dump["ring"] if e["kind"] == "ledger_pressure"]
            assert ev and ev[0]["tracked_bytes"] == 900
            assert ev[0]["limit_bytes"] == 1000
            m.track(50)                     # still in pressure: no re-fire
            assert len(_dumps(tmp_path)) == 1
    finally:
        m.clear()


def test_device_fallback_trigger(monkeypatch, tmp_path):
    from daft_tpu.observability import placement

    _recorder(monkeypatch, tmp_path)

    class DeviceFallback(Exception):
        pass

    with pytest.raises(DeviceFallback):
        with placement.feedback(None):
            raise DeviceFallback("device refused the batch")
    dumps = _dumps(tmp_path)
    assert len(dumps) == 1
    with open(dumps[0]) as f:
        dump = json.load(f)
    assert dump["kind"] == "device_fallback"
    assert "device refused the batch" in dump["detail"]


def test_worker_death_trigger(monkeypatch, tmp_path):
    rec = _recorder(monkeypatch, tmp_path)
    rec.note_worker_death("worker-3", "no heartbeat for 1.0s")
    dumps = _dumps(tmp_path)
    assert len(dumps) == 1
    with open(dumps[0]) as f:
        dump = json.load(f)
    assert dump["kind"] == "worker_death"
    assert "worker-3" in dump["detail"]


def test_cooldown_suppresses_dumps_but_counts_anomalies(monkeypatch, tmp_path):
    rec = _recorder(monkeypatch, tmp_path, cooldown=60.0)
    a0 = registry().get("flight_anomalies_total")
    d0 = registry().get("flight_dumps_total")
    for _ in range(5):
        rec.note_query("p", 0.0, error="boom")
    assert len(_dumps(tmp_path)) == 1       # first dump only, rest cooled down
    assert registry().get("flight_anomalies_total") - a0 == 5
    assert registry().get("flight_dumps_total") - d0 == 1


def test_unwritable_dump_dir_degrades_to_counter(monkeypatch, tmp_path):
    bad = tmp_path / "nope"
    bad.write_text("a file, not a directory")
    monkeypatch.setenv("DAFT_TPU_FLIGHT_DIR", str(bad))
    monkeypatch.setenv("DAFT_TPU_ANOMALY_COOLDOWN_S", "0")
    flight._reset_for_tests()
    rec = flight.recorder()
    f0 = registry().get("flight_dump_failures")
    rec.note_query("p", 0.0, error="boom")  # must not raise
    assert registry().get("flight_dump_failures") - f0 == 1
    assert rec.dumps == []


def test_native_runner_records_queries_in_ring(monkeypatch, tmp_path):
    rec = _recorder(monkeypatch, tmp_path, wall_k=100.0, min_s=100.0)
    df = dt.from_pydict({"k": [1, 2, 1, 2], "v": [1.0, 2.0, 3.0, 4.0]})
    out = df.groupby("k").agg(col("v").sum().alias("s")).sort("k").to_pydict()
    assert out == {"k": [1, 2], "s": [4.0, 6.0]}
    queries = [ev for ev in rec.snapshot() if ev["kind"] == "query"]
    assert queries, "native runner never reached the flight recorder"
    q = queries[-1]
    assert q["fingerprint"] and q["seconds"] > 0 and q["query_id"]
    assert q["rows"] == 2
    assert not _dumps(tmp_path)


def test_subscriber_sees_flight_anomaly(monkeypatch, tmp_path):
    from daft_tpu.observability import attach_subscriber, detach_subscriber
    from daft_tpu.observability.subscribers import Subscriber

    rec = _recorder(monkeypatch, tmp_path)
    seen = []

    class Sub(Subscriber):
        def on_flight_anomaly(self, event):
            seen.append(event)

    sub = Sub()
    attach_subscriber(sub)
    try:
        rec.note_query("p", 0.0, query_id="qx", error="boom")
    finally:
        detach_subscriber(sub)
    assert len(seen) == 1
    assert seen[0].kind == "query_error" and seen[0].query_id == "qx"
    assert seen[0].dump_path and os.path.exists(seen[0].dump_path)


# ---------------------------------------------------------------------------
# multi-tenant no-bleed under a threaded serving hammer
# ---------------------------------------------------------------------------

def test_serving_hammer_dump_has_no_cross_tenant_bleed(monkeypatch, tmp_path):
    """N client threads hammer one ServingSession under distinct tenants; one
    tenant's query errors. The ring stays bounded, and the query_error dump
    carries ONLY the erroring tenant's (and engine-global) events — never
    another tenant's queries."""
    from daft_tpu.serving import ServingSession

    rec = _recorder(monkeypatch, tmp_path, ring=64)
    df = dt.from_pydict({"k": [i % 7 for i in range(500)],
                         "v": [float(i) for i in range(500)]})

    @dt.func
    def boom(x: int) -> int:
        raise ValueError("tenant-bad exploded")

    mk_good = lambda: df.groupby("k").agg(col("v").sum().alias("s")).sort("k")
    errors = []
    with ServingSession(max_concurrent=4) as sess:
        def good_client(tid):
            for _ in range(6):
                sess.submit(mk_good(), tenant=f"t{tid}").to_pydict()

        threads = [threading.Thread(target=good_client, args=(t,))
                   for t in range(4)]
        for t in threads:
            t.start()
        try:
            sess.submit(df.select(boom(col("k"))),
                        tenant="bad").result(timeout=60)
        except Exception as e:  # lint: ignore[broad-except] -- the erroring
            # tenant's exception type is the UDF runtime's to choose; the
            # assertion below is on the recorded anomaly, not the type
            errors.append(e)
        for t in threads:
            t.join()
    assert errors, "the bad tenant's query never errored"
    assert len(rec.snapshot()) <= 64
    dumps = [p for p in _dumps(tmp_path) if "query_error" in p]
    assert dumps, "no query_error dump from the serving hammer"
    with open(dumps[-1]) as f:
        dump = json.load(f)
    assert dump["tenant"] == "bad"
    tenants = {ev.get("tenant", "") for ev in dump["ring"]}
    assert tenants <= {"", "bad"}, \
        f"cross-tenant bleed in anomaly dump: {tenants}"
    # the hammer's other tenants DID flow through the recorder (the filter
    # dropped them from the dump; they were not simply absent)
    all_tenants = {ev.get("tenant", "") for ev in rec.snapshot()}
    assert any(t.startswith("t") for t in all_tenants)


# ---------------------------------------------------------------------------
# doctor CLI
# ---------------------------------------------------------------------------

def test_doctor_compare_names_regressed_operators_and_counters():
    """The committed SF10 r04->r05 pair (the 0.62x out-of-core regression)
    must produce concrete attribution: the worst queries ranked, the
    device-tier disengagement, and the streaming-scan/host-ledger tax."""
    out = subprocess.run(
        [sys.executable, "-m", "daft_tpu.tools.doctor", "--compare",
         "BENCH_SF10_r04.json", "BENCH_SF10_r05.json"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert out.returncode == 0, out.stderr
    text = out.stdout
    assert "q1" in text and "46.8" in text          # worst offender, ranked
    assert "device_batches: 4 -> 0" in text
    assert "rss_high_water_bytes" in text
    assert "streaming-scan / host-ledger" in text
    assert "cpu backend" in text                    # host_reasons surfaced


def test_doctor_reads_flight_dump(monkeypatch, tmp_path):
    rec = _recorder(monkeypatch, tmp_path)
    rec.record("admission", tenant="t0", query_id="qa", wait_s=0.25,
               est_pin_bytes=1 << 20)
    rec.note_query("p1", 0.05, query_id="q1", rows=10)
    rec.note_worker_death("worker-1", "connection closed")
    rec.note_query("p1", 0.01, query_id="q2", rows=10,
                   error="RuntimeError: shard lost")
    dumps = _dumps(tmp_path)
    out = subprocess.run(
        [sys.executable, "-m", "daft_tpu.tools.doctor"] + dumps,
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert out.returncode == 0, out.stderr
    # the error dump's triage ranks the error and the worker death first
    assert "shard lost" in out.stdout
    assert "worker death" in out.stdout
    assert "findings (ranked):" in out.stdout
    assert "admission wait" in out.stdout


def test_compare_tolerates_captures_without_profiles(tmp_path, capsys):
    """Satellite: old captures (no per_query_profile) flow through
    bench.compare's attribution section cleanly — shape-tolerant loading,
    capture-level fallback attribution."""
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)
    old = {"metric": "m", "value": 100.0, "per_query_ms": {"q1": 100.0},
           "metrics": {"scan_rows": 10}}
    new = {"metric": "m", "value": 50.0, "per_query_ms": {"q1": 300.0},
           "metrics": {"scan_rows": 10, "spill_bytes": 4096},
           "device_batches": 0}
    po, pn = tmp_path / "old.json", tmp_path / "new.json"
    po.write_text(json.dumps(old))
    pn.write_text(json.dumps(new))
    assert bench.compare(str(po), str(pn)) >= 1
    text = capsys.readouterr().out
    assert "attribution (top regressed queries):" in text
    assert "3.00x slower" in text
    assert "per_query_profile" in text      # degraded-mode notice, not a crash
    assert "worst offenders" in text


def test_compare_attributes_operator_deltas_from_profiles(tmp_path, capsys):
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)

    def prof(scan_s, agg_s, stall_ms):
        return {"q1": {"operators": [
            {"name": "StreamingScan", "rows": 1000, "seconds": scan_s,
             "compute": scan_s * 0.2, "starve": scan_s * 0.7,
             "blocked": scan_s * 0.1},
            {"name": "HashAggregate", "rows": 7, "seconds": agg_s,
             "compute": agg_s, "starve": 0.0, "blocked": 0.0},
        ], "counters": {"scan_stall_ms": stall_ms}}}

    old = {"metric": "m", "value": 100.0, "per_query_ms": {"q1": 100.0},
           "per_query_profile": prof(0.05, 0.04, 0)}
    new = {"metric": "m", "value": 40.0, "per_query_ms": {"q1": 900.0},
           "per_query_profile": prof(0.80, 0.05, 740)}
    po, pn = tmp_path / "old.json", tmp_path / "new.json"
    po.write_text(json.dumps(old))
    pn.write_text(json.dumps(new))
    assert bench.compare(str(po), str(pn)) >= 1
    text = capsys.readouterr().out
    assert "operator StreamingScan: +0.750s" in text
    assert "counter scan_stall_ms: +740" in text
