"""Custom DataSource/DataSink connectors + checkpoint/resume lifecycle."""

import os

import pytest

import daft_tpu
from daft_tpu import col
from daft_tpu.checkpoint import FileCheckpointStore, MemoryCheckpointStore
from daft_tpu.core.micropartition import MicroPartition
from daft_tpu.datatype import DataType, Field
from daft_tpu.io.scan import Pushdowns
from daft_tpu.io.sink import DataSink, WriteResult
from daft_tpu.io.source import DataSource, DataSourceTask
from daft_tpu.schema import Schema


# ---------------------------------------------------------------------------
# DataSource
# ---------------------------------------------------------------------------

_SCHEMA = Schema([Field("id", DataType.int64()), Field("v", DataType.float64())])


class RangeTask(DataSourceTask):
    def __init__(self, start, end):
        self.start, self.end = start, end

    @property
    def schema(self):
        return _SCHEMA

    def read(self):
        ids = list(range(self.start, self.end))
        yield MicroPartition.from_pydict({"id": ids, "v": [float(i) * 0.5 for i in ids]})


class RangeSource(DataSource):
    def __init__(self, n, chunk=100):
        self.n, self.chunk = n, chunk
        self.seen_pushdowns = None

    @property
    def name(self):
        return "range-source"

    @property
    def schema(self):
        return _SCHEMA

    def get_tasks(self, pushdowns: Pushdowns):
        self.seen_pushdowns = pushdowns
        for s in range(0, self.n, self.chunk):
            yield RangeTask(s, min(s + self.chunk, self.n))


def test_data_source_reads_as_dataframe():
    src = RangeSource(1000)
    df = src.read()
    out = df.where(col("id") >= 990).sort("id").to_pydict()
    assert out["id"] == list(range(990, 1000))
    # pushdowns reached the source (filter visible even though tasks ignore it)
    assert src.seen_pushdowns is not None and src.seen_pushdowns.filters is not None


def test_data_source_distributes():
    import daft_tpu.runners as runners
    from daft_tpu.distributed import DistributedRunner

    src = RangeSource(2000, chunk=200)
    r = DistributedRunner(num_workers=2, n_partitions=4)
    runners.set_runner(r)
    try:
        out = (src.read().groupby((col("id") % 7).alias("m"))
               .agg(col("v").sum().alias("s")).sort("m").to_pydict())
    finally:
        runners.set_runner(runners.NativeRunner())
        r.shutdown()
    assert len(out["m"]) == 7


# ---------------------------------------------------------------------------
# DataSink
# ---------------------------------------------------------------------------

class CollectSink(DataSink):
    def __init__(self):
        self.started = 0
        self.rows = []

    def name(self):
        return "collect-sink"

    def schema(self):
        return Schema([Field("written", DataType.int64())])

    def start(self):
        self.started += 1

    def write(self, part):
        n = part.num_rows
        self.rows.extend(part.to_pydict()["id"])
        return WriteResult(rows=n)

    def finalize(self, results):
        total = sum(r.rows for r in results)
        return MicroPartition.from_pydict({"written": [total]})


def test_data_sink_roundtrip():
    df = daft_tpu.from_pydict({"id": list(range(50))})
    sink = CollectSink()
    out = df.write_sink(sink).to_pydict()
    assert out == {"written": [50]}
    assert sink.started == 1
    assert sorted(sink.rows) == list(range(50))


# ---------------------------------------------------------------------------
# Checkpoint lifecycle + resumable writes
# ---------------------------------------------------------------------------

def test_checkpoint_lifecycle_memory():
    st = MemoryCheckpointStore()
    st.stage_keys("c1", [1, 2, 3])
    st.stage_files("c1", ["f1"])
    assert st.get_checkpointed_keys() == set()  # staged is invisible
    st.checkpoint("c1")
    assert st.get_checkpointed_keys() == {1, 2, 3}
    assert st.get_checkpointed_files() == ["f1"]
    st.mark_committed("c1")
    assert st.get_checkpointed_files() == []  # committed files drop out
    assert st.get_checkpointed_keys() == {1, 2, 3}  # keys stay for skip-on-rerun
    with pytest.raises(ValueError):
        st.mark_committed("never-sealed")


def test_file_checkpoint_store_survives_restart(tmp_path):
    p = str(tmp_path / "ckpt.jsonl")
    st = FileCheckpointStore(p)
    st.stage_keys("c1", ["a", "b"])
    st.stage_files("c1", ["f1", "f2"])
    st.checkpoint("c1")
    st.mark_committed("c1")
    st.stage_keys("c2", ["c"])
    st.stage_files("c2", ["f3"])
    st.checkpoint("c2")
    # "restart"
    st2 = FileCheckpointStore(p)
    assert st2.get_checkpointed_keys() == {"a", "b", "c"}
    assert st2.get_checkpointed_files() == ["f3"]  # only the uncommitted seal


def test_checkpointed_write_skips_on_rerun(tmp_path):
    store = MemoryCheckpointStore()
    df = daft_tpu.from_pydict({"k": [1, 2, 3, 4], "v": ["a", "b", "c", "d"]})
    out_dir = str(tmp_path / "out")
    df.write_parquet(out_dir, checkpoint=(store, "k")).to_pydict()
    assert store.get_checkpointed_keys() == {1, 2, 3, 4}

    # rerun with 2 new rows: only the new keys are written
    df2 = daft_tpu.from_pydict({"k": [3, 4, 5, 6], "v": ["c", "d", "e", "f"]})
    df2.write_parquet(out_dir, checkpoint=(store, "k")).to_pydict()
    assert store.get_checkpointed_keys() == {1, 2, 3, 4, 5, 6}
    back = daft_tpu.read_parquet(out_dir + "/**/*.parquet").sort("k").to_pydict()
    assert back["k"] == [1, 2, 3, 4, 5, 6]  # no duplicates from the rerun


def test_checkpointed_write_all_skipped(tmp_path):
    store = MemoryCheckpointStore()
    df = daft_tpu.from_pydict({"k": [1, 2], "v": [1.0, 2.0]})
    d = str(tmp_path / "o")
    df.write_parquet(d, checkpoint=(store, "k")).to_pydict()
    df.write_parquet(d, checkpoint=(store, "k")).to_pydict()  # full rerun: all skipped
    back = daft_tpu.read_parquet(d + "/**/*.parquet").sort("k").to_pydict()
    assert back["k"] == [1, 2]
