"""Elastic fault tolerance tier-1 tests (ISSUE 9).

The recovery paths under test, in dependency order:
- completeness gates: a reduce input with silently-missing map files raises
  ShuffleDataLost naming the precise lost map ids (never a short result)
- fetch-client classification: transient peer restarts retry with backoff;
  a dead peer raises ShufflePeerUnreachable past the budget
- liveness monitor: kill -9 (EOF detection) and SIGSTOP (heartbeat-timeout
  detection) both declare the worker dead, requeue its tasks, and mark it in
  the dashboard's worker table
- lost-map regeneration: a worker that dies AND takes its shuffle files with
  it (fault mode kill_lose) triggers lineage replay of exactly the lost maps
  on the survivors — query completes bit-identical to an undisturbed run
- elastic respawn: DAFT_TPU_WORKER_RESPAWN replaces dead workers, capped
- checkpoint/resume: committed stage boundaries skip on re-submission of the
  same plan fingerprint; zero overhead (no imports, no counters) when unset
- serving cancellation: queued queries leave the admission queue, running
  queries trip the cooperative checks

Process-level tests are gated on POSIX kill/SIGSTOP semantics
(fault_injection.requires_fault_injection) and skip cleanly elsewhere.
"""

import os
import sys
import time

import numpy as np
import pytest

import daft_tpu
import daft_tpu.runners as runners
from daft_tpu import col
from daft_tpu.observability.metrics import registry

from fault_injection import (arm_fault, kill9, requires_fault_injection,
                             sigstop, wait_until)


def _groupby_data(n=10_000, keys=50, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "k": rng.integers(0, keys, n).tolist(),
        "v": rng.uniform(0, 100, n).tolist(),
    }


def _groupby_query(data):
    df = daft_tpu.from_pydict(data)
    return (df.groupby("k")
            .agg(col("v").sum().alias("s"), col("v").count().alias("c"))
            .sort("k"))


def _run_on(runner, q):
    native = runners.NativeRunner()
    runners.set_runner(runner)
    try:
        return q().to_pydict()
    finally:
        runners.set_runner(native)


# ---------------------------------------------------------------------------
# Completeness gates + fetch classification (hermetic, no worker processes)
# ---------------------------------------------------------------------------

def test_missing_map_file_raises_data_lost_with_precise_ids(tmp_path):
    """A reduce that expected maps {0,1} but finds only map 1's file raises
    ShuffleDataLost naming exactly [0] — the regeneration contract."""
    from daft_tpu.core.recordbatch import RecordBatch
    from daft_tpu.distributed.shuffle import (ShuffleDataLost, read_partition,
                                              write_map_output)

    base = str(tmp_path)
    batch = RecordBatch.from_pydict({"a": [1, 2, 3]})
    write_map_output(base, "s1", 0, [[batch]])
    write_map_output(base, "s1", 1, [[batch]])
    schema = batch.schema
    # undisturbed: both maps decode
    got = [p for p in read_partition(base, "s1", 0, schema,
                                     expected_maps=(0, 1))]
    assert sum(p.num_rows for p in got) == 6
    # lose map 0's file (the dead worker's storage)
    os.unlink(os.path.join(base, "s1", "p0", "m0.arrow"))
    with pytest.raises(ShuffleDataLost) as ei:
        list(read_partition(base, "s1", 0, schema, expected_maps=(0, 1)))
    assert ei.value.shuffle_id == "s1"
    assert ei.value.map_ids == (0,)
    # a partition the lineage says has no expected maps stays readable
    assert list(read_partition(base, "s1", 0, schema, expected_maps=())) != []


def test_fetch_peer_unreachable_after_retry_budget(monkeypatch):
    """A peer that never answers classifies as ShufflePeerUnreachable after
    DAFT_TPU_FETCH_RETRIES backed-off attempts (serial + pipelined paths)."""
    import socket

    from daft_tpu.distributed.fetch_server import fetch_partition
    from daft_tpu.distributed.shuffle import ShufflePeerUnreachable
    from daft_tpu.schema import Schema

    with socket.socket() as s:  # a port with nothing listening
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    monkeypatch.setenv("DAFT_TPU_FETCH_RETRIES", "1")
    before = registry().get("fetch_retries_total")
    ep = [("127.0.0.1", port, "ab" * 16)]
    with pytest.raises(ShufflePeerUnreachable):
        list(fetch_partition(ep, "sx", 0, Schema([]), parallelism=1,
                             prefetch=0))
    assert registry().get("fetch_retries_total") - before == 1
    with pytest.raises(ShufflePeerUnreachable):
        list(fetch_partition(ep, "sx", 0, Schema([]), parallelism=2,
                             prefetch=2))


def test_fetch_transient_retry_rides_out_peer_restart(tmp_path, monkeypatch):
    """A peer that comes up a few hundred ms late (mid-restart) is retried
    with backoff and the fetch succeeds — no regeneration triggered."""
    import socket
    import threading

    from daft_tpu.core.recordbatch import RecordBatch
    from daft_tpu.distributed.fetch_server import (ShuffleFetchServer,
                                                   fetch_partition)
    from daft_tpu.distributed.shuffle import write_map_output

    base = str(tmp_path)
    batch = RecordBatch.from_pydict({"a": [1, 2, 3, 4]})
    write_map_output(base, "s2", 0, [[batch]])
    # the peer's (port, authkey) identity exists before the peer does: until
    # the restart thread binds it, connects are REFUSED — the transient
    # window under test
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    authkey = os.urandom(16)
    ep = [("127.0.0.1", port, authkey.hex())]
    srv_slot = {}
    monkeypatch.setenv("DAFT_TPU_FETCH_RETRIES", "8")
    before = registry().get("fetch_retries_total")

    def _late_restart():
        # deterministic "mid-restart" window: come back up only after the
        # client has observably been refused at least once (no wall-clock
        # race under a loaded machine), with a hard fallback
        deadline = time.time() + 5.0
        while (registry().get("fetch_retries_total") == before
               and time.time() < deadline):
            time.sleep(0.01)
        srv_slot["srv"] = ShuffleFetchServer(base, port=port, authkey=authkey)

    threading.Thread(target=_late_restart, daemon=True).start()
    try:
        got = list(fetch_partition(ep, "s2", 0, batch.schema, parallelism=1,
                                   prefetch=0, expected_maps=(0,)))
        assert sum(p.num_rows for p in got) == 4
    finally:
        if "srv" in srv_slot:
            srv_slot["srv"].close()
    assert registry().get("fetch_retries_total") - before >= 1


# ---------------------------------------------------------------------------
# Liveness monitor + elastic respawn (real worker processes)
# ---------------------------------------------------------------------------

def _scan_tasks(n, rows=64):
    from daft_tpu.core.micropartition import MicroPartition
    from daft_tpu.core.series import Series
    from daft_tpu.core.recordbatch import RecordBatch
    from daft_tpu.datatype import DataType
    from daft_tpu.distributed.task import SubPlanTask
    from daft_tpu.plan import physical as pp
    from daft_tpu.schema import Schema

    s = Series.from_pylist(list(range(rows)), "a", DataType.int64())
    schema = Schema([s.field()])
    part = MicroPartition(schema, [RecordBatch(schema, [s], rows)])
    plan = pp.InMemoryScan([part], schema)
    return [SubPlanTask.from_plan(f"t{i}", plan) for i in range(n)]


@requires_fault_injection
def test_heartbeat_timeout_detects_sigstopped_worker(monkeypatch):
    """A SIGSTOP'd worker neither exits nor EOFs — only the heartbeat-timeout
    detector catches it: declared dead, tasks requeued, query completes."""
    from daft_tpu.distributed.worker import WorkerPool

    monkeypatch.setenv("DAFT_TPU_HEARTBEAT_S", "0.2")
    monkeypatch.setenv("DAFT_TPU_HEARTBEAT_TIMEOUT_S", "1.0")
    # speculation would duplicate the stalled task onto the healthy worker
    # and finish the run before the timeout fires — this test must observe
    # DETECTION, not the straggler mitigation
    monkeypatch.setenv("DAFT_TPU_SPECULATIVE", "0")
    fail0 = registry().get("worker_failures_total")
    req0 = registry().get("tasks_requeued_total")
    pool = WorkerPool(2)
    try:
        # warm both workers (first-task jax/daft import is seconds; the
        # timeout must measure a STOPPED worker, not a cold one)
        assert len(pool.run_tasks(_scan_tasks(2))) == 2
        sigstop(pool, "worker-0")
        results = pool.run_tasks(_scan_tasks(4))
        assert len(results) == 4 and all(r.rows == 64 for r in results.values())
        assert "worker-0" in pool.dead_workers
        assert "no heartbeat" in pool.dead_workers["worker-0"]["reason"]
        assert "worker-0" not in pool.workers  # dropped, not zombie-polled
    finally:
        pool.shutdown()
    assert registry().get("worker_failures_total") - fail0 == 1
    assert registry().get("tasks_requeued_total") - req0 >= 1


@requires_fault_injection
def test_idle_pool_liveness_detects_kill9_without_work(monkeypatch, tmp_path):
    """A kill -9'd worker in an IDLE pool (no run_tasks in flight) is
    declared dead within about one heartbeat timeout by the dispatcher's
    idle liveness tick — death detection must not wait for the next query.
    The flight recorder's worker_death anomaly dump rides along."""
    from daft_tpu.distributed.worker import WorkerPool
    from daft_tpu.observability import flight

    monkeypatch.setenv("DAFT_TPU_HEARTBEAT_S", "0.2")
    monkeypatch.setenv("DAFT_TPU_HEARTBEAT_TIMEOUT_S", "1.0")
    monkeypatch.setenv("DAFT_TPU_FLIGHT_RECORDER", "1")
    monkeypatch.setenv("DAFT_TPU_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("DAFT_TPU_ANOMALY_COOLDOWN_S", "0")
    flight._reset_for_tests()
    fail0 = registry().get("worker_failures_total")
    pool = WorkerPool(2)
    try:
        # warm both workers, then go fully idle
        assert len(pool.run_tasks(_scan_tasks(2))) == 2
        kill9(pool, "worker-0")
        # no run_tasks from here on: only the idle tick can notice. The
        # process exit is caught via poll() (faster than the heartbeat
        # timeout); allow a couple of tick intervals of slack.
        wait_until(lambda: "worker-0" in pool.dead_workers, timeout_s=5.0,
                   what="idle liveness tick declaring the killed worker dead")
        assert "worker-0" not in pool.workers  # dropped, not zombie-polled
        # the survivor keeps serving
        assert len(pool.run_tasks(_scan_tasks(2))) == 2
    finally:
        pool.shutdown()
        flight._reset_for_tests()
    assert registry().get("worker_failures_total") - fail0 == 1
    dumps = list(tmp_path.glob("flight_worker_death_*.json"))
    assert dumps, "worker death never reached the flight recorder"


@requires_fault_injection
def test_respawn_cap_honored(monkeypatch):
    """DAFT_TPU_WORKER_RESPAWN=1: the first death spawns one replacement;
    the second death does not (cap), and the pool keeps serving on the
    survivor."""
    from daft_tpu.distributed.worker import WorkerPool

    monkeypatch.setenv("DAFT_TPU_WORKER_RESPAWN", "1")
    # pin queue-pressure autoscaling off: it would race the respawn for the
    # dead worker's freed max_workers headroom (a benign production race —
    # the pool ends whole either way — but this test asserts the RESPAWN
    # path specifically)
    monkeypatch.setenv("DAFT_TPU_AUTOSCALING_THRESHOLD", "1000")
    resp0 = registry().get("worker_respawns_total")
    pool = WorkerPool(2)
    try:
        assert len(pool.run_tasks(_scan_tasks(2))) == 2
        kill9(pool, "worker-0")
        assert len(pool.run_tasks(_scan_tasks(4))) == 4
        # generous timeout: the replacement spawns synchronously in a
        # dispatch pass, and a fresh python importing the engine can take
        # >15s on a loaded machine
        wait_until(lambda: registry().get("worker_respawns_total") - resp0 == 1,
                   timeout_s=45.0, what="replacement worker spawn")
        wait_until(lambda: len(pool.workers) == 2, timeout_s=30.0,
                   what="replacement joining pool")
        # second death: the respawn cap is exhausted — no further respawn
        # (queue-pressure autoscaling may still add workers; that is a
        # separate, pre-existing mechanism) and the pool keeps serving
        victim = sorted(pool.workers)[0]
        kill9(pool, victim)
        assert len(pool.run_tasks(_scan_tasks(4))) == 4
        assert pool._respawn_attempts == 1
    finally:
        pool.shutdown()
    assert registry().get("worker_respawns_total") - resp0 == 1


# ---------------------------------------------------------------------------
# The acceptance scenario: kill -9 one worker mid-shuffle on a 3-worker pool
# ---------------------------------------------------------------------------

@requires_fault_injection
def test_kill9_mid_shuffle_completes_bit_identical(tmp_path, monkeypatch):
    """worker-0 finishes its shuffle map, SIGKILLs itself AND unlinks its
    published map files (kill_lose: the lost-host topology). The reduce
    detects the loss, lineage replays exactly the lost maps on the two
    survivors, and the query completes bit-identical to a native run."""
    from daft_tpu.distributed import DistributedRunner

    data = _groupby_data(seed=7)
    # the reference result: an UNDISTURBED distributed run of the same plan
    # (sorted map-file read order + deterministic lineage replay make the
    # faulted run bit-identical to it, not merely close)
    r_clean = DistributedRunner(num_workers=3, n_partitions=3)
    try:
        clean = _run_on(r_clean, lambda: _groupby_query(data))
    finally:
        r_clean.shutdown()
    arm_fault(monkeypatch, "task_sent", mode="kill_lose", worker="worker-0",
              stage="shuffle", once_dir=str(tmp_path))
    fail0 = registry().get("worker_failures_total")
    regen0 = registry().get("shuffle_maps_regenerated_total")
    r = DistributedRunner(num_workers=3, n_partitions=3)
    try:
        got = _run_on(r, lambda: _groupby_query(data))
    finally:
        r.shutdown()
    assert got == clean  # bit-identical, no tolerance
    native = _run_on(runners.NativeRunner(), lambda: _groupby_query(data))
    assert got["k"] == native["k"] and got["c"] == native["c"]
    np.testing.assert_allclose(got["s"], native["s"], rtol=1e-9)
    assert registry().get("worker_failures_total") - fail0 >= 1
    assert registry().get("shuffle_maps_regenerated_total") - regen0 >= 1


@requires_fault_injection
def test_recovery_renders_in_explain_analyze_and_metrics(tmp_path, monkeypatch):
    """The same crash, traced: EXPLAIN ANALYZE renders the recovery line and
    the registry counters flow into /metrics exposition."""
    from daft_tpu.distributed import DistributedRunner
    from daft_tpu.observability.metrics import prometheus_text

    arm_fault(monkeypatch, "task_sent", mode="kill_lose", worker="worker-1",
              stage="shuffle", once_dir=str(tmp_path))
    data = _groupby_data(seed=11)
    r = DistributedRunner(num_workers=3, n_partitions=3)
    native = runners.NativeRunner()
    runners.set_runner(r)
    try:
        report = _groupby_query(data).explain_analyze()
    finally:
        runners.set_runner(native)
        r.shutdown()
    assert "recovery:" in report
    assert "worker failures" in report
    assert "maps regenerated" in report
    text = prometheus_text()
    assert "daft_tpu_worker_failures_total" in text
    assert "daft_tpu_shuffle_maps_regenerated_total" in text


@requires_fault_injection
def test_dashboard_marks_dead_workers():
    """The liveness monitor's synthetic final beat latches the dead flag in
    the dashboard worker table instead of letting the row go silently stale."""
    from daft_tpu.observability.dashboard import DashboardState
    from daft_tpu.observability.events import WorkerHeartbeat

    def beat(**kw):
        base = dict(worker_id="w0", ts=time.time(), busy_slots=0,
                    total_slots=1, tasks_completed=1, tasks_failed=0,
                    rss_bytes=1 << 20)
        base.update(kw)
        return WorkerHeartbeat(**base)

    state = DashboardState()
    state.on_worker_heartbeat("q1", beat())
    assert state.workers()["w0"]["dead"] is False
    state.on_worker_heartbeat("q1", beat(dead=True,
                                         death_reason="no heartbeat for 6.0s"))
    w = state.workers()["w0"]
    assert w["dead"] is True and "no heartbeat" in w["death_reason"]
    # a respawned worker reusing the id un-latches by beating again
    state.on_worker_heartbeat("q1", beat())
    assert state.workers()["w0"]["dead"] is False


# ---------------------------------------------------------------------------
# Checkpoint / resume
# ---------------------------------------------------------------------------

@requires_fault_injection
def test_checkpoint_resume_skips_committed_stages(tmp_path, monkeypatch):
    """Run a multi-stage query with DAFT_TPU_CHECKPOINT_DIR set; re-submit
    the same plan (same data content -> same fingerprint) on a FRESH runner:
    committed stages restore instead of re-running, results identical."""
    from daft_tpu.distributed import DistributedRunner

    monkeypatch.setenv("DAFT_TPU_CHECKPOINT_DIR", str(tmp_path / "ckpt"))
    data = _groupby_data(seed=3)
    com0 = registry().get("checkpoint_stages_committed")
    skip0 = registry().get("checkpoint_stages_skipped")
    r1 = DistributedRunner(num_workers=2, n_partitions=2)
    try:
        first = _run_on(r1, lambda: _groupby_query(data))
    finally:
        r1.shutdown()
    committed = registry().get("checkpoint_stages_committed") - com0
    assert committed >= 1
    assert registry().get("checkpoint_stages_skipped") - skip0 == 0
    # re-submission: new runner, new DataFrame objects, same CONTENT
    r2 = DistributedRunner(num_workers=2, n_partitions=2)
    try:
        second = _run_on(r2, lambda: _groupby_query(data))
    finally:
        r2.shutdown()
    assert second == first
    assert registry().get("checkpoint_stages_skipped") - skip0 >= 1
    # the resumed run committed nothing new (it restored, not re-ran)
    assert registry().get("checkpoint_stages_committed") - com0 == committed


@requires_fault_injection
def test_checkpoint_zero_overhead_when_unset(monkeypatch):
    """With DAFT_TPU_CHECKPOINT_DIR unset: the stage-checkpoint module is
    never imported and no checkpoint counters move (empty registry diff on
    the checkpoint_* family)."""
    from daft_tpu.distributed import DistributedRunner

    monkeypatch.delenv("DAFT_TPU_CHECKPOINT_DIR", raising=False)
    sys.modules.pop("daft_tpu.checkpoint.stages", None)
    before = registry().snapshot()
    data = _groupby_data(n=4000, seed=5)
    r = DistributedRunner(num_workers=2, n_partitions=2)
    try:
        _run_on(r, lambda: _groupby_query(data))
    finally:
        r.shutdown()
    assert "daft_tpu.checkpoint.stages" not in sys.modules
    diff = registry().diff(before)
    assert not [k for k in diff if k.startswith("checkpoint_")]


# ---------------------------------------------------------------------------
# Serving cancellation
# ---------------------------------------------------------------------------

def _slow_df(n=60, delay_s=0.02):
    import daft_tpu as dt

    @dt.func
    def crawl(x: int) -> int:
        time.sleep(delay_s)
        return x

    df = daft_tpu.from_pydict({"x": list(range(n))})
    return df.select(crawl(col("x")).alias("x"))


def test_cancel_queued_serving_query():
    """cancel() on a still-queued query: pulled from the admission queue,
    resolves immediately with QueryCancelled; neighbors are undisturbed."""
    from daft_tpu.serving import QueryCancelled, ServingSession

    can0 = registry().get("serve_cancelled_total")
    with ServingSession(max_concurrent=1) as sess:
        running = sess.submit(_slow_df(n=60))     # occupies the only worker
        time.sleep(0.3)                            # let it start
        keep = sess.submit(daft_tpu.from_pydict({"y": [1, 2]}))
        victim = sess.submit(daft_tpu.from_pydict({"y": [3, 4]}))
        assert victim.cancel() is True
        assert victim.cancelled is True
        with pytest.raises(QueryCancelled):
            victim.result(timeout=5)
        # the cancelled ticket released its queue slot; the others complete
        assert keep.result(timeout=30)[0].num_rows == 2
        assert sum(p.num_rows for p in running.result(timeout=30)) == 60
    assert registry().get("serve_cancelled_total") - can0 >= 1
    assert registry().snapshot().get("serve_queue_depth") == 0.0


def test_cancel_running_serving_query():
    """cancel() on a RUNNING query trips the cooperative check between
    streamed result partitions: the future resolves with QueryCancelled and
    the session keeps serving."""
    from daft_tpu.serving import QueryCancelled, ServingSession

    with ServingSession(max_concurrent=1) as sess:
        fut = sess.submit(_slow_df(n=100, delay_s=0.02))  # ~2s of UDF time
        time.sleep(0.3)                                   # it is running now
        assert fut.cancel() is True
        with pytest.raises(QueryCancelled):
            fut.result(timeout=30)
        assert fut.cancelled is True
        # session healthy after the cancellation
        out = sess.run(daft_tpu.from_pydict({"z": [1, 2, 3]}))
        assert sum(p.num_rows for p in out) == 3


def test_cancel_resolved_future_returns_false():
    from daft_tpu.serving import ServingSession

    with ServingSession(max_concurrent=1) as sess:
        fut = sess.submit(daft_tpu.from_pydict({"a": [1]}))
        fut.result(timeout=30)
        assert fut.cancel() is False
        assert fut.cancelled is False


def test_admission_queue_remove_preserves_rotation():
    """remove() owns its ticket exactly once and keeps round-robin fairness
    for the remaining tenants."""
    from daft_tpu.serving import FairAdmissionQueue

    q = FairAdmissionQueue()
    q.push("a", "a1")
    q.push("a", "a2")
    q.push("b", "b1")
    assert q.remove("a", "a1") is True
    assert q.remove("a", "a1") is False       # single ownership
    assert q.remove("ghost", "x") is False
    order = [q.pop(timeout=1), q.pop(timeout=1)]
    assert set(order) == {"a2", "b1"}
    assert q.depth() == 0
    # removing a tenant's LAST item retires it from the rotation entirely
    q.push("c", "c1")
    assert q.remove("c", "c1") is True
    assert q.depth() == 0
    assert q.pop(timeout=0.05) is None
