import numpy as np
import pyarrow as pa
import pytest

from daft_tpu import DataType, Series


def test_from_pylist_infer():
    s = Series.from_pylist([1, 2, 3], "x")
    assert s.dtype == DataType.int64()
    assert s.to_pylist() == [1, 2, 3]
    s = Series.from_pylist([1.5, None, 2.5], "x")
    assert s.dtype == DataType.float64()
    assert s.to_pylist() == [1.5, None, 2.5]
    assert s.null_count() == 1
    s = Series.from_pylist(["a", "b", None], "x")
    assert s.dtype == DataType.string()


def test_python_fallback():
    class Obj:
        pass

    o = Obj()
    s = Series.from_pylist([o, None, o], "objs")
    assert s.dtype == DataType.python()
    assert s.to_pylist()[0] is o
    assert s.null_count() == 1
    assert len(s.filter(Series.from_pylist([True, False, True]))) == 2


def test_arithmetic():
    a = Series.from_pylist([1, 2, None], "a")
    b = Series.from_pylist([10, 20, 30], "b")
    assert (a + b).to_pylist() == [11, 22, None]
    assert (a - b).to_pylist() == [-9, -18, None]
    assert (a * b).to_pylist() == [10, 40, None]
    assert (b / a).to_pylist() == [10.0, 10.0, None]
    assert (-a).to_pylist() == [-1, -2, None]
    assert a.abs().to_pylist() == [1, 2, None]


def test_division_by_zero_is_null():
    a = Series.from_pylist([1.0, 2.0], "a")
    z = Series.from_pylist([0.0, 1.0], "z")
    assert (a / z).to_pylist() == [None, 2.0]
    ai = Series.from_pylist([7, 8], "a")
    zi = Series.from_pylist([0, 2], "z")
    assert (ai % zi).to_pylist() == [None, 0]
    assert (ai // zi).to_pylist() == [None, 4]


def test_broadcast_scalar():
    a = Series.from_pylist([1, 2, 3], "a")
    one = Series.from_pylist([10], "b")
    assert (a + one).to_pylist() == [11, 12, 13]
    assert (one * a).to_pylist() == [10, 20, 30]


def test_comparisons_and_logic():
    a = Series.from_pylist([1, 2, None], "a")
    b = Series.from_pylist([2, 2, 2], "b")
    assert (a < b).to_pylist() == [True, False, None]
    assert (a == b).to_pylist() == [False, True, None]
    assert (a != b).to_pylist() == [True, False, None]
    t = Series.from_pylist([True, False, None], "t")
    u = Series.from_pylist([True, True, True], "u")
    assert (t & u).to_pylist() == [True, False, None]
    assert (t | u).to_pylist() == [True, True, True]
    assert (~t).to_pylist() == [False, True, None]


def test_string_concat_add():
    a = Series.from_pylist(["a", "b"], "a")
    b = Series.from_pylist(["x", "y"], "b")
    assert (a + b).to_pylist() == ["ax", "by"]


def test_cast():
    s = Series.from_pylist([1, 2, 3], "x")
    assert s.cast(DataType.float32()).dtype == DataType.float32()
    assert s.cast(DataType.string()).to_pylist() == ["1", "2", "3"]
    s2 = Series.from_pylist(["1", "2"], "x")
    assert s2.cast(DataType.int64()).to_pylist() == [1, 2]


def test_filter_take_slice_concat():
    s = Series.from_pylist([10, 20, 30, 40], "x")
    assert s.filter(Series.from_pylist([True, False, True, None])).to_pylist() == [10, 30]
    assert s.take([3, 0]).to_pylist() == [40, 10]
    assert s.slice(1, 3).to_pylist() == [20, 30]
    c = Series.concat([s, s.slice(0, 1)])
    assert c.to_pylist() == [10, 20, 30, 40, 10]


def test_null_ops():
    s = Series.from_pylist([1, None, 3], "x")
    assert s.is_null().to_pylist() == [False, True, False]
    assert s.not_null().to_pylist() == [True, False, True]
    assert s.fill_null(Series.from_pylist([0])).to_pylist() == [1, 0, 3]
    assert s.drop_nulls().to_pylist() == [1, 3]


def test_sort_argsort():
    s = Series.from_pylist([3, 1, None, 2], "x")
    assert s.sort().to_pylist() == [1, 2, 3, None]
    assert s.sort(descending=True).to_pylist() == [None, 3, 2, 1]
    assert s.sort(descending=True, nulls_first=False).to_pylist() == [3, 2, 1, None]


def test_aggregations():
    s = Series.from_pylist([1, 2, 3, None], "x")
    assert s.sum().to_pylist() == [6]
    assert s.mean().to_pylist() == [2.0]
    assert s.min().to_pylist() == [1]
    assert s.max().to_pylist() == [3]
    assert s.count().to_pylist() == [3]
    assert s.count("null").to_pylist() == [1]
    assert s.count("all").to_pylist() == [4]
    assert s.count_distinct().to_pylist() == [3]
    assert s.sum().dtype == DataType.int64()
    b = Series.from_pylist([True, True, None], "b")
    assert b.bool_and().to_pylist() == [True]
    assert b.bool_or().to_pylist() == [True]
    assert s.agg_list().to_pylist() == [[1, 2, 3, None]]


def test_stddev_var():
    s = Series.from_pylist([1.0, 2.0, 3.0, 4.0], "x")
    assert abs(s.var().to_pylist()[0] - 1.25) < 1e-9
    assert abs(s.stddev().to_pylist()[0] - 1.25**0.5) < 1e-9


def test_hash_deterministic_and_null():
    s = Series.from_pylist([1, 2, 1, None], "x")
    h = s.hash().to_pylist()
    assert h[0] == h[2]
    assert h[0] != h[1]
    s2 = Series.from_pylist(["abc", "abd", "abc", None, ""], "x")
    h2 = s2.hash().to_pylist()
    assert h2[0] == h2[2]
    assert h2[0] != h2[1]
    assert h2[3] != h2[4]  # null differs from empty string
    # float canonicalization: -0.0 == 0.0, int 1 pattern vs float different ok
    f = Series.from_pylist([0.0, -0.0, float("nan"), float("nan")], "f")
    hf = f.hash().to_pylist()
    assert hf[0] == hf[1]
    assert hf[2] == hf[3]


def test_is_in_between_if_else():
    s = Series.from_pylist([1, 2, 3, None], "x")
    assert s.is_in(Series.from_pylist([2, 3])).to_pylist() == [False, True, True, False]
    assert s.between(Series.from_pylist([2]), Series.from_pylist([3])).to_pylist() == [False, True, True, None]
    p = Series.from_pylist([True, False, True], "p")
    t = Series.from_pylist([1, 1, 1], "t")
    f = Series.from_pylist([0, 0, 0], "f")
    assert Series.if_else(p, t, f).to_pylist() == [1, 0, 1]


def test_approx_count_distinct():
    s = Series.from_pylist(list(range(1000)) * 2, "x")
    est = s.approx_count_distinct().to_pylist()[0]
    assert abs(est - 1000) / 1000 < 0.05


def test_embedding_series_from_numpy():
    arr = np.arange(12, dtype=np.float32).reshape(3, 4)
    s = Series.from_numpy(arr, "emb", DataType.embedding(DataType.float32(), 4))
    assert s.dtype == DataType.embedding(DataType.float32(), 4)
    out = s.to_numpy()
    assert out.shape == (3, 4)
    np.testing.assert_array_equal(out, arr)


def test_to_device_padding():
    s = Series.from_pylist([1.0, None, 3.0], "x")
    vals, validity = s.to_device(pad_to=8)
    assert vals.shape == (8,)
    assert validity.tolist() == [True, False, True, False, False, False, False, False]
