"""Device-UDF tier (ops/udf_stage.py): device-vs-host bit-parity, coalesced
dispatch, weight residency + pin safety, fusion into device agg stages, the
zero-overhead host-UDF guard, and the PR's satellite fixes (scan morsel knob,
checkpoint GC, serving admission calibration)."""

import os
import sys
import time

import numpy as np
import pytest

import daft_tpu
from daft_tpu import col
from daft_tpu.config import execution_config_ctx
from daft_tpu.datatype import DataType
from daft_tpu.device.residency import manager
from daft_tpu.functions.ai import classify_text, embed_text
from daft_tpu.observability.metrics import registry
from daft_tpu.ops import counters

LABELS = ["alpha topic", "beta topic", "gamma topic"]


def _texts(n):
    words = [f"term{i}" for i in range(17)]
    return [" ".join(words[(i * k) % len(words)] for k in (1, 3, 7))
            for i in range(n)]


def _score_func(seed=3, dtype=None):
    """A tiny scalar-output device Func (x scaled by a weight sum) — the
    fused-agg and contract tests' model stand-in."""
    w = np.random.default_rng(seed).standard_normal(8).astype(np.float32)

    def fn(params, x):
        return x * params["w"].sum()

    return daft_tpu.func(
        fn, on_device=True, return_dtype=dtype or DataType.float32(),
        device_params=lambda: {"w": w}, device_key=f"test_score:{seed}")


# ======================================================================================
# Bit-parity device vs host
# ======================================================================================

def test_embed_device_vs_host_bit_identical_single_batch():
    """Single-batch input -> identical dispatch shapes -> the device tier and
    the host-UDF path run the SAME compiled program and must agree bit for
    bit (incl. null and empty strings)."""
    texts = _texts(40) + [None, "", None]
    df = daft_tpu.from_pydict({"id": list(range(len(texts))), "t": texts})
    q = lambda: df.select(col("id"),
                          embed_text(col("t"), provider="jax").alias("e")).to_pydict()
    counters.reset()
    with execution_config_ctx(device_mode="on", device_min_rows=1,
                              mesh_devices=1):
        dev = q()
    assert counters.device_udf_dispatches > 0
    assert counters.device_udf_runs > 0
    with execution_config_ctx(device_mode="off"):
        host = q()
    assert dev == host
    assert dev["e"][40] is None and dev["e"][42] is None  # nulls stay null
    assert len(dev["e"][41]) > 0                          # empty string embeds


def test_classify_device_vs_host_multi_batch():
    """The classify pipeline (encoder + label argmax in one program, int32
    codes decoded on host) is exact across batch shapes — multi-batch scans
    through the coalescer must match the host path bit for bit."""
    texts = _texts(120) + [None]
    df = daft_tpu.from_pydict({"t": texts}).into_batches(32).collect()
    q = lambda: (df.select(classify_text(col("t"), LABELS,
                                         provider="jax").alias("lab"))
                   .groupby("lab").agg(col("lab").count().alias("n"))
                   .sort("lab").to_pydict())
    with execution_config_ctx(device_mode="on", device_min_rows=1,
                              mesh_devices=1):
        dev = q()
    with execution_config_ctx(device_mode="off"):
        host = q()
    assert dev == host
    assert sum(dev["n"]) == 120  # the null row groups separately with count 0


def test_empty_partition_and_empty_frame():
    df = daft_tpu.from_pydict({"t": []})
    with execution_config_ctx(device_mode="on", device_min_rows=1,
                              mesh_devices=1):
        out = df.select(embed_text(col("t"), provider="jax").alias("e")).to_pydict()
    assert out["e"] == []


def test_classifier_label_cache_deterministic():
    """Identical label sets share one label-matrix anchor -> one HBM entry
    (no duplicate label matrices); distinct label sets differ ONLY in the
    label part — the encoder part is one shared anchor across every classify
    Func AND the embed Func (one encoder copy in HBM per process)."""
    from daft_tpu.ai.jax_provider import jax_classify_func, jax_embed_func
    from daft_tpu.ops.udf_stage import _func_anchors

    f1 = jax_classify_func(LABELS)
    f2 = jax_classify_func(list(LABELS))
    a1, a2 = _func_anchors(f1), _func_anchors(f2)
    assert a1["lab"] is a2["lab"], "same labels produced distinct anchors"
    assert a1["enc"] is a2["enc"]
    f3 = jax_classify_func(LABELS + ["delta topic"])
    a3 = _func_anchors(f3)
    assert a3["lab"] is not a1["lab"]
    assert a3["enc"] is a1["enc"], "label set change duplicated the encoder"
    emb = _func_anchors(jax_embed_func(None))
    assert emb[None] is a1["enc"], \
        "embed and classify hold separate encoder copies"


# ======================================================================================
# Coalescing + residency
# ======================================================================================

def test_coalesced_feed_one_dispatch():
    """8 small morsels through the DispatchCoalescer -> ONE device-UDF
    dispatch (the RTT amortization the tier exists for)."""
    df = daft_tpu.from_pydict({"t": _texts(64)}).into_batches(8).collect()
    counters.reset()
    with execution_config_ctx(device_mode="on", device_min_rows=1,
                              mesh_devices=1):
        df.select(embed_text(col("t"), provider="jax").alias("e")).to_pydict()
    assert counters.coalesce_morsels_in >= 8
    assert counters.device_udf_dispatches == 1
    assert counters.dispatch_coalesced == 1


def test_batch_size_caps_dispatch_bucket():
    """Func.batch_size chunks the super-batch: 64 rows at batch_size=16 ->
    4 dispatches, results identical to the uncapped run."""
    texts = _texts(64)
    df = daft_tpu.from_pydict({"t": texts})
    counters.reset()
    with execution_config_ctx(device_mode="on", device_min_rows=1,
                              mesh_devices=1):
        capped = df.select(embed_text(col("t"), provider="jax",
                                      batch_size=16).alias("e")).to_pydict()
        assert counters.device_udf_dispatches == 4
    with execution_config_ctx(device_mode="on", device_min_rows=1,
                              mesh_devices=1):
        flat = df.select(embed_text(col("t"), provider="jax").alias("e")).to_pydict()
    for a, b in zip(capped["e"], flat["e"]):
        np.testing.assert_allclose(a, b, atol=1e-5)


def test_weights_resident_and_repeat_h2d_flat():
    """Weights register in the residency manager (hbm_bytes_resident grows,
    the digest carries the content-stable slot) and repeat queries re-upload
    ZERO weight bytes."""
    df = daft_tpu.from_pydict({"t": _texts(32)})
    q = lambda: df.select(embed_text(col("t"), provider="jax").alias("e")).to_pydict()
    manager().clear()  # earlier tests left the weights resident
    counters.reset()
    with execution_config_ctx(device_mode="on", device_min_rows=1,
                              mesh_devices=1):
        q()
        assert counters.device_udf_weight_h2d_bytes > 0
        w1 = counters.device_udf_weight_h2d_bytes
        assert manager().bytes_resident() >= w1
        assert any(nb >= w1 for _k, nb in manager().digest()), \
            "weight slot missing from the heartbeat digest"
        q()
        assert counters.device_udf_weight_h2d_bytes == w1, \
            "repeat query re-uploaded model weights"


def test_tiny_hbm_budget_pin_safety():
    """Weights pinned by an executing query survive a budget far below their
    size; the budget re-enforces after the pin scope exits."""
    df = daft_tpu.from_pydict({"t": _texts(32)})
    counters.reset()
    with execution_config_ctx(device_mode="on", device_min_rows=1,
                              mesh_devices=1, hbm_budget_bytes=1024):
        out = df.select(embed_text(col("t"), provider="jax").alias("e")).to_pydict()
        assert len(out["e"]) == 32 and out["e"][0] is not None
        # post-query: weights are unpinned and must have been shed
        assert manager().bytes_resident() <= 1024
    assert registry().get("hbm_pins") > 0


def test_affinity_fingerprint_carries_weight_slot():
    """plan_fingerprint of a physical plan containing a DeviceUdfProject
    advertises the weight slot the workers' digests publish."""
    from daft_tpu.distributed.affinity import plan_fingerprint
    from daft_tpu.ops.udf_stage import weight_slots

    df = daft_tpu.from_pydict({"t": _texts(16)})
    with execution_config_ctx(device_mode="on", device_min_rows=1):
        q = df.select(embed_text(col("t"), provider="jax").alias("e"))
        optimized = q._builder.optimize()
        from daft_tpu.plan.physical import translate

        phys = translate(optimized.plan)
    fp = plan_fingerprint(phys)
    assert fp, "no fingerprint for a device-UDF plan"
    from daft_tpu.ai.jax_provider import jax_embed_func

    slots = weight_slots(jax_embed_func(None))
    assert slots and all(sk in dict(fp) for sk, _nb in slots)


def test_device_udf_plan_distributes():
    """DeviceUdfProject is a distributable map node (like UDFProject): its
    subtree qualifies for the worker pool — the affinity weight-slot routing
    has something to route — and a pooled run matches the native runner."""
    import daft_tpu.runners as runners
    from daft_tpu.distributed import DistributedRunner
    from daft_tpu.distributed.planner import subtree_distributable
    from daft_tpu.plan.physical import DeviceUdfProject, translate

    score = _score_func(seed=7)
    n = 4000
    df = daft_tpu.from_pydict({"x": [float(i % 31) for i in range(n)],
                               "k": [i % 3 for i in range(n)]})
    q = lambda: (df.select(col("k"), score(col("x")).alias("s"))
                   .groupby("k").agg(col("s").sum().alias("ss")).sort("k"))
    with execution_config_ctx(device_mode="on", device_min_rows=1,
                              mesh_devices=1):
        phys = translate(q()._builder.optimize().plan)
        udf_nodes = [nd for nd in phys.walk()
                     if isinstance(nd, DeviceUdfProject)]
        assert udf_nodes, "plan lost its DeviceUdfProject"
        assert subtree_distributable(udf_nodes[0]), \
            "device-UDF subtree not distributable (driver-localized)"
        expect = q().to_pydict()
        r = DistributedRunner(num_workers=2, n_partitions=2)
        runners.set_runner(r)
        try:
            got = q().to_pydict()
        finally:
            runners.set_runner(runners.NativeRunner())
            r.shutdown()
    assert got["k"] == expect["k"]
    np.testing.assert_allclose(got["ss"], expect["ss"], rtol=1e-5)


# ======================================================================================
# Fusion into a device agg stage
# ======================================================================================

def test_fused_udf_agg_no_intermediate_d2h():
    """A scalar device UDF feeding a device ungrouped agg fuses: the UDF's
    output plane goes straight into the agg program (device_stage_batches
    moves, device_udf_runs does NOT — no standalone finalize d2h), results
    matching the host path."""
    score = _score_func()
    n = 3000
    df = daft_tpu.from_pydict({"x": [float(i % 89) for i in range(n)]})
    q = lambda: df.select(score(col("x")).alias("s")).agg(
        col("s").sum().alias("ss"), col("s").count().alias("c")).to_pydict()
    counters.reset()
    with execution_config_ctx(device_mode="on", device_min_rows=1,
                              mesh_devices=1):
        dev = q()
    assert counters.device_udf_dispatches > 0
    assert counters.device_stage_batches > 0
    assert counters.device_udf_runs == 0, \
        "fused path paid a standalone UDF finalize d2h"
    with execution_config_ctx(device_mode="off"):
        host = q()
    assert dev["c"] == host["c"]
    np.testing.assert_allclose(dev["ss"], host["ss"], rtol=1e-5)


def test_unfused_grouped_pipeline_still_device():
    """Grouped aggs don't fuse (keys factorize on host) but the UDF stage
    still runs on device upstream, with identical results."""
    score = _score_func(seed=11)
    n = 1200
    df = daft_tpu.from_pydict({"x": [float(i % 53) for i in range(n)],
                               "k": [i % 4 for i in range(n)]})
    q = lambda: (df.select(col("k"), score(col("x")).alias("s"))
                   .groupby("k").agg(col("s").sum().alias("ss"))
                   .sort("k").to_pydict())
    counters.reset()
    with execution_config_ctx(device_mode="on", device_min_rows=1,
                              mesh_devices=1):
        dev = q()
    assert counters.device_udf_dispatches > 0
    with execution_config_ctx(device_mode="off"):
        host = q()
    assert dev["k"] == host["k"]
    np.testing.assert_allclose(dev["ss"], host["ss"], rtol=1e-5)


# ======================================================================================
# Contract: @cls device hooks, fallbacks, zero overhead
# ======================================================================================

def test_cls_device_params_hook():
    """@daft_tpu.cls classes declare weights via device_params(); the method
    marked on_device runs through the tier with the instance materialized
    once per process."""
    import daft_tpu.udf as udf_mod

    @udf_mod.cls
    class Scaler:
        def __init__(self, k):
            self.k = float(k)
            self.loads = getattr(Scaler, "_loads", 0) + 1
            Scaler._loads = self.loads

        def device_params(self):
            return {"k": np.float32(self.k)}

        @udf_mod.method(on_device=True, return_dtype=DataType.float32())
        def scale(self, params, x):
            return x * params["k"]

    s = Scaler(2.5)
    df = daft_tpu.from_pydict({"x": [float(i) for i in range(100)]})
    counters.reset()
    with execution_config_ctx(device_mode="on", device_min_rows=1,
                              mesh_devices=1):
        out = df.select(s.scale(col("x")).alias("y")).to_pydict()
    assert counters.device_udf_dispatches > 0
    np.testing.assert_allclose(out["y"], [i * 2.5 for i in range(100)],
                               rtol=1e-6)
    assert Scaler._loads == 1  # one materialization, not one per batch


def test_cls_device_methods_do_not_collide():
    """Two different @cls classes' device methods get distinct program
    fingerprints (the shared `bound` wrapper's code hash would collide) —
    each runs ITS OWN compiled program with its own params structure."""
    import daft_tpu.udf as udf_mod
    from daft_tpu.ops.udf_stage import func_fingerprint

    @udf_mod.cls
    class Mul:
        def device_params(self):
            return {"k": np.float32(3.0)}

        @udf_mod.method(on_device=True, return_dtype=DataType.float32())
        def apply(self, params, x):
            return x * params["k"]

    @udf_mod.cls
    class Add:
        def device_params(self):
            return {"b": np.float32(10.0)}

        @udf_mod.method(on_device=True, return_dtype=DataType.float32())
        def apply(self, params, x):
            return x + params["b"]

    fm, fa = Mul().apply, Add().apply
    f1, f2 = fm(col("x")).func, fa(col("x")).func
    assert func_fingerprint(f1) != func_fingerprint(f2)
    df = daft_tpu.from_pydict({"x": [1.0, 2.0]})
    with execution_config_ctx(device_mode="on", device_min_rows=1,
                              mesh_devices=1):
        assert df.select(fm(col("x")).alias("y")).to_pydict()["y"] == [3.0, 6.0]
        assert df.select(fa(col("x")).alias("y")).to_pydict()["y"] == [11.0, 12.0]


def test_device_func_rejects_kwargs():
    """Keyword arguments don't cross the fn(params, *arrays) contract: the
    host path raises instead of silently dropping them."""
    f = daft_tpu.func(lambda params, x: x, on_device=True,
                      return_dtype=DataType.float32(),
                      device_key="kwargs_guard:v1")
    df = daft_tpu.from_pydict({"x": [1.0]})
    with pytest.raises(TypeError, match="keyword"):
        df.select(f(col("x"), scale=2).alias("y")).to_pydict()


def test_runtime_fallback_misaligned_prepare():
    """A prepare hook returning misaligned arrays trips DeviceFallback: the
    query completes on the host path and the fallback is counted."""
    def bad_prepare(xs):
        return (np.zeros((3,), np.float32),)  # wrong row count

    f = daft_tpu.func(
        lambda params, x: x, on_device=True, return_dtype=DataType.float32(),
        device_prepare=bad_prepare, device_key="bad_prepare:v1")
    df = daft_tpu.from_pydict({"x": [1.0, 2.0, 3.0, 4.0]})
    counters.reset()
    with execution_config_ctx(device_mode="on", device_min_rows=1,
                              mesh_devices=1):
        with pytest.raises(Exception):
            # the HOST path shares the prepare hook, so this shape error is
            # a genuine user bug both tiers surface; what matters here is
            # that the device tier counted its fallback before rerouting
            df.select(f(col("x")).alias("y")).to_pydict()
    assert counters.device_udf_fallbacks > 0


def test_zero_overhead_host_only_udfs():
    """A query with only host UDFs imports nothing from the device-UDF tier
    and leaves an empty device-counter registry diff."""
    sys.modules.pop("daft_tpu.ops.udf_stage", None)

    @daft_tpu.func(return_dtype=DataType.int64())
    def plus_one(x: int) -> int:
        return x + 1

    df = daft_tpu.from_pydict({"x": list(range(64))})
    counters.reset()
    before = registry().snapshot()
    with execution_config_ctx(device_mode="auto"):
        out = df.select(plus_one(col("x")).alias("y"),
                        (col("x") * 2).alias("z")).to_pydict()
    assert out["y"][:3] == [1, 2, 3]
    assert "daft_tpu.ops.udf_stage" not in sys.modules, \
        "host-UDF query imported the device-UDF tier"
    diff = {k: v for k, v in registry().diff(before).items() if v}
    assert not any(k.startswith(("device_udf_", "hbm_", "dispatch_",
                                 "coalesce_")) for k in diff), diff


# ======================================================================================
# Satellites
# ======================================================================================

def test_parquet_scan_honors_morsel_knob(tmp_path):
    """io/parquet.py batches by ExecutionConfig.morsel_size_rows instead of
    the old hardcoded 128Ki — the batching-strategy knob reaches scan-fed
    pipelines."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    path = str(tmp_path / "t.parquet")
    pq.write_table(pa.table({"x": list(range(10_000))}), path)
    with execution_config_ctx(morsel_size_rows=1024):
        df = daft_tpu.read_parquet(path).collect()
        sizes = [b.num_rows for p in df._result for b in p.batches]
    assert sum(sizes) == 10_000
    assert max(sizes) <= 1024, sizes
    # the default config still reads the configured (larger) morsel size
    with execution_config_ctx(morsel_size_rows=128 * 1024):
        df2 = daft_tpu.read_parquet(path).collect()
        sizes2 = [b.num_rows for p in df2._result for b in p.batches]
    assert max(sizes2) > 1024


def test_parquet_reader_batch_rows_function():
    from daft_tpu.io.parquet import _scan_batch_rows

    with execution_config_ctx(morsel_size_rows=2048):
        assert _scan_batch_rows() == 2048
    with execution_config_ctx(morsel_size_rows=128 * 1024):
        assert _scan_batch_rows() == 128 * 1024


def test_checkpoint_gc_ttl(tmp_path, monkeypatch):
    """Committed stages older than DAFT_TPU_CHECKPOINT_TTL_S are swept on
    store open/commit; the opener's own tree and fresh trees survive."""
    from daft_tpu.checkpoint.stages import StageCheckpointer, sweep_expired

    root = str(tmp_path / "ckpt")
    old = StageCheckpointer(root, "oldquery")
    old.commit_result("subtree-0/result", [])
    assert old.committed("subtree-0/result")
    # age the old tree past the TTL
    aged = time.time() - 3600
    os.utime(os.path.join(root, "oldquery"), (aged, aged))

    monkeypatch.setenv("DAFT_TPU_CHECKPOINT_TTL_S", "60")
    before = registry().get("checkpoint_stages_gced")
    fresh = StageCheckpointer(root, "newquery")  # open sweeps
    assert not os.path.isdir(os.path.join(root, "oldquery"))
    assert registry().get("checkpoint_stages_gced") == before + 1
    # the opener's own tree is never reaped, even when aged
    fresh.commit_result("subtree-0/result", [])
    os.utime(os.path.join(root, "newquery"), (aged, aged))
    sweep_expired(root, skip="newquery")
    assert fresh.committed("subtree-0/result")
    # disabled TTL sweeps nothing
    monkeypatch.setenv("DAFT_TPU_CHECKPOINT_TTL_S", "0")
    assert sweep_expired(root) == 0


def test_admission_calibration_monotone_non_increasing():
    """Repeat queries through a ServingSession shrink the prepared entry's
    reservation toward the observed pin-scope high-water: estimates are
    monotone non-increasing, and warm repeats reserve no more than observed
    (admission packing tightens over time)."""
    from daft_tpu.serving import ServingSession

    n = 2000
    df = daft_tpu.from_pydict({"k": [i % 7 for i in range(n)],
                               "v": [float(i % 101) for i in range(n)]})
    q = lambda: df.groupby("k").agg(col("v").sum().alias("s")).sort("k")
    with execution_config_ctx(device_mode="on", device_min_rows=1,
                              mesh_devices=1):
        ref = q().to_pydict()
        sess = ServingSession(max_concurrent=1)
        try:
            estimates = []
            for _ in range(4):
                out = sess.submit(q()).to_pydict()
                assert out == ref
                (entry,) = list(sess.prepared._entries.values())
                estimates.append(entry.est_pin_bytes)
        finally:
            sess.close()
    assert all(a >= b for a, b in zip(estimates, estimates[1:])), estimates
    assert entry.observed_pin_bytes is not None
    assert estimates[-1] <= max(entry.observed_pin_bytes, 0) or \
        estimates[-1] == estimates[0]  # nothing pinned -> estimate untouched


def test_observe_pins_thread_local():
    """observe_pins() brackets this thread's pin scopes (stage threads
    inherit the handle via spawn_stage) and restores prior state on exit."""
    m = manager()
    with m.observe_pins() as observed:
        assert observed() == 0
        with m.pin_scope():
            pass
        assert observed() == 0  # nothing pinned -> zero high-water
    # no observation outside the context
    with m.pin_scope():
        pass
