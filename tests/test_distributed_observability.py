"""Distributed-engine observability: per-task stats, shuffle counters, worker
heartbeats, trace propagation into OTLP, and distributed EXPLAIN ANALYZE
(reference: Flotilla scheduler/worker metrics through the subscriber path +
src/common/metrics/src/ops.rs vocabulary)."""

import json
import threading
import time

import numpy as np
import pytest

import daft_tpu
import daft_tpu.runners as runners
from daft_tpu import col
from daft_tpu.observability.metrics import MetricsRegistry, registry


@pytest.fixture(scope="module")
def dist_runner():
    import os

    from daft_tpu.distributed import DistributedRunner

    os.environ["DAFT_TPU_HEARTBEAT_S"] = "0.2"
    r = DistributedRunner(num_workers=2, n_partitions=2)
    try:
        yield r
    finally:
        r.shutdown()
        os.environ.pop("DAFT_TPU_HEARTBEAT_S", None)


def _groupby_df(n=20_000, seed=0):
    rng = np.random.default_rng(seed)
    return daft_tpu.from_pydict({
        "k": rng.integers(0, 50, n).tolist(),
        "v": rng.uniform(0, 1, n).tolist(),
    })


def _run_distributed(dist_runner, q):
    native = runners.NativeRunner()
    runners.set_runner(dist_runner)
    try:
        return q().to_pydict()
    finally:
        runners.set_runner(native)


# ---------------------------------------------------------------------------
# The acceptance-criteria end-to-end: JSONL with task stats, shuffle bytes,
# heartbeats; explain_analyze skew; OTLP trace join.
# ---------------------------------------------------------------------------

def test_distributed_event_log_has_tasks_shuffles_heartbeats(dist_runner, tmp_path):
    from daft_tpu.observability.event_log import (disable_event_log,
                                                  enable_event_log)

    p = str(tmp_path / "dist_events.jsonl")
    sub = enable_event_log(p)
    df = _groupby_df()
    try:
        out = _run_distributed(
            dist_runner,
            lambda: df.groupby("k").agg(col("v").sum().alias("s")).sort("k"))
        assert len(out["k"]) == 50
    finally:
        disable_event_log(sub)

    events = [json.loads(l) for l in open(p)]
    assert all(e["schema_version"] == 11 for e in events)
    by_kind = {}
    for e in events:
        by_kind.setdefault(e["event"], []).append(e)

    # per-task stats with queue wait / exec time / rows
    tasks = by_kind["task_stats"]
    assert len(tasks) >= 4  # 2 shuffle-map + 2 final tasks
    for t in tasks:
        assert t["worker_id"].startswith("worker-")
        assert t["exec_s"] > 0
        assert t["queue_wait_s"] >= 0
        assert t["schedule_latency_s"] >= 0
        assert "retries" in t and t["retries"] == 0
        assert t["stage_id"]
    assert sum(t["rows_out"] for t in tasks) >= 50
    # worker-side operator stats rode along
    assert any(t["operator_stats"] for t in tasks)
    # v4: per-task worker engine-counter deltas ship in the record
    assert all("engine_counters" in t for t in tasks)

    # per-stage shuffle byte counters
    shuffles = by_kind["shuffle_stats"]
    assert any(s["bytes_written"] > 0 and s["rows_written"] > 0
               for s in shuffles)
    assert any(s["bytes_fetched"] > 0 and s["fetch_requests"] > 0
               for s in shuffles)
    # v5: wire/logical + overlap attribution travels in the record
    assert all("wire_bytes_written" in s and "fetch_wall_seconds" in s
               and "overlap_seconds" in s and "fetch_fanin" in s
               for s in shuffles)
    assert any(s["wire_bytes_written"] > 0 for s in shuffles)

    # >= 1 worker heartbeat with utilization fields
    hbs = by_kind["worker_heartbeat"]
    assert len(hbs) >= 1
    assert all(h["total_slots"] >= 1 and h["rss_bytes"] > 0 for h in hbs)

    # query_end carries the per-query metrics-registry deltas
    end = by_kind["query_end"][0]
    assert end["metrics"].get("shuffle_bytes_written", 0) > 0


def test_distributed_explain_analyze_renders_stage_skew(dist_runner):
    df = _groupby_df(seed=1)
    native = runners.NativeRunner()
    runners.set_runner(dist_runner)
    try:
        report = (df.groupby("k").agg(col("v").sum().alias("s"))
                  .explain_analyze())
    finally:
        runners.set_runner(native)
    assert "== Distributed Stages ==" in report
    assert "min/median/max task" in report
    assert "shuffle:" in report and "final:" in report
    assert "worker-0" in report or "worker-1" in report
    # device/shuffle attribution appears in the report, not only bench.py
    assert "== Engine Counters ==" in report
    assert "shuffle_bytes_written" in report


def test_distributed_otlp_spans_share_query_trace(dist_runner):
    """Worker-side task + operator spans join the driver query's OTLP trace:
    span tree daft.query -> daft.task -> daft.operator, one trace id, and the
    trace id is the stable hash of the query id (otlp._trace_id)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from daft_tpu.observability.otlp import OTLPSubscriber, _trace_id
    from daft_tpu.observability.subscribers import (attach_subscriber,
                                                    detach_subscriber)

    received = []

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            body = self.rfile.read(int(self.headers["Content-Length"]))
            received.append(json.loads(body))
            self.send_response(200)
            self.send_header("Content-Length", "2")
            self.end_headers()
            self.wfile.write(b"{}")

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    sub = OTLPSubscriber(f"http://127.0.0.1:{srv.server_address[1]}",
                         asynchronous=False)
    attach_subscriber(sub)
    df = _groupby_df(seed=2)
    try:
        _run_distributed(
            dist_runner,
            lambda: df.groupby("k").agg(col("v").sum().alias("s")))
    finally:
        detach_subscriber(sub)
        srv.shutdown()

    assert sub.exported == 1 and sub.last_error is None
    spans = received[0]["resourceSpans"][0]["scopeSpans"][0]["spans"]
    roots = [s for s in spans if "parentSpanId" not in s]
    assert len(roots) == 1 and roots[0]["name"] == "daft.query"
    root = roots[0]
    # trace-id stability: derived from the query id via the shared scheme
    qid_attr = {a["key"]: a["value"] for a in root["attributes"]}
    qid = qid_attr["daft.query_id"]["stringValue"]
    assert root["traceId"] == _trace_id(qid)
    # every span (driver ops, worker tasks, worker ops) shares the trace
    assert all(s["traceId"] == root["traceId"] for s in spans)
    task_spans = [s for s in spans if s["name"].startswith("daft.task:")]
    assert len(task_spans) >= 4
    assert all(t["parentSpanId"] == root["spanId"] for t in task_spans)
    task_ids = {t["spanId"] for t in task_spans}
    worker_ops = [s for s in spans if s.get("parentSpanId") in task_ids]
    assert worker_ops, "no worker-side operator spans under task spans"
    names = {s["name"] for s in worker_ops}
    assert any(n.startswith("daft.operator:") for n in names)


def test_dashboard_worker_utilization_endpoint(dist_runner):
    import urllib.request

    from daft_tpu.observability.dashboard import launch

    dash = launch()
    df = _groupby_df(seed=3)
    try:
        _run_distributed(
            dist_runner,
            lambda: df.groupby("k").agg(col("v").mean().alias("m")))
        with urllib.request.urlopen(dash.url + "/api/workers", timeout=5) as r:
            workers = json.loads(r.read())
        assert workers, "no worker heartbeats reached the dashboard"
        w = next(iter(workers.values()))
        assert w["heartbeats"] >= 1 and w["last"]["rss_bytes"] > 0
        # engine endpoint now serves the full registry incl. shuffle volume
        with urllib.request.urlopen(dash.url + "/api/engine", timeout=5) as r:
            eng = json.loads(r.read())
        assert "device_join_batches" in eng
        assert eng.get("shuffle_bytes_written", 0) > 0
    finally:
        dash.shutdown()


def test_pool_trace_survives_worker_death(tmp_path):
    """With one worker dead, the pool still records a full trace for the
    stage: every finished task carries timing + the stamped trace context."""
    from daft_tpu.core.micropartition import MicroPartition
    from daft_tpu.core.recordbatch import RecordBatch
    from daft_tpu.core.series import Series
    from daft_tpu.datatype import DataType
    from daft_tpu.distributed.task import SubPlanTask
    from daft_tpu.distributed.trace import QueryTrace
    from daft_tpu.distributed.worker import WorkerPool
    from daft_tpu.plan import physical as pp
    from daft_tpu.schema import Schema

    pool = WorkerPool(2)
    try:
        s = Series.from_pylist([1, 2, 3], "a", DataType.int64())
        schema = Schema([s.field()])
        part = MicroPartition(schema, [RecordBatch(schema, [s], 3)])
        plan = pp.InMemoryScan([part], schema)
        w0 = pool.workers["worker-0"]
        w0._proc.terminate()
        w0._proc.wait()
        trace = QueryTrace("q-test")
        tasks = [SubPlanTask.from_plan(f"t{i}", plan, stage_id="s0")
                 for i in range(4)]
        results = pool.run_tasks(tasks, stage_id="s0", trace=trace)
        assert len(results) == 4
        assert len(trace.tasks) == 4
        assert all(t.exec_s > 0 for t in trace.tasks)
        # trace context was stamped at dispatch
        assert all(t.trace_id == trace.trace_id for t in trace.tasks)
        summaries = trace.stage_summaries()
        assert summaries[0]["tasks"] == 4
        assert summaries[0]["max_s"] >= summaries[0]["min_s"]
    finally:
        pool.shutdown()


def test_socket_transport_fetch_server_counts_requests():
    """With shuffle_transport='socket', the driver-side fetch server counts
    requests/bytes served (per-server stats + registry counters)."""
    from daft_tpu.distributed import DistributedRunner

    r = DistributedRunner(num_workers=2, n_partitions=2,
                          shuffle_transport="socket")
    native = runners.NativeRunner()
    before = registry().snapshot()
    try:
        df = _groupby_df(n=8_000, seed=4)
        runners.set_runner(r)
        try:
            out = df.groupby("k").agg(col("v").sum().alias("s")).to_pydict()
            assert len(out["k"]) == 50
        finally:
            runners.set_runner(native)
        st = r._fetch_server.stats()
        assert st["requests"] > 0 and st["bytes_served"] > 0
        deltas = registry().diff(before)
        assert deltas.get("shuffle_fetch_server_requests", 0) > 0
    finally:
        r.shutdown()


# ---------------------------------------------------------------------------
# Metrics registry
# ---------------------------------------------------------------------------

def test_metrics_registry_snapshot_and_diff():
    reg = MetricsRegistry()
    reg.declare("a")
    before = reg.snapshot()
    assert before == {"a": 0}
    reg.inc("a", 3)
    reg.inc("b")
    reg.set_gauge("g", 1.5)
    snap = reg.snapshot()
    assert snap == {"a": 3, "b": 1, "g": 1.5}
    d = reg.diff(before)
    assert d == {"a": 3, "b": 1, "g": 1.5}
    reg.reset()
    assert reg.snapshot() == {"a": 0, "b": 0}


def test_metrics_registry_thread_safety():
    reg = MetricsRegistry()

    def work():
        for _ in range(1000):
            reg.inc("n")

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert reg.get("n") == 8000


def test_counters_module_reads_registry():
    """ops.counters module attributes are views over the shared registry."""
    from daft_tpu.ops import counters

    counters.reset()
    assert counters.device_stage_batches == 0
    counters.bump("device_stage_batches", 2)
    assert counters.device_stage_batches == 2
    assert registry().get("device_stage_batches") == 2
    assert counters.snapshot()["device_stage_batches"] == 2
    counters.reset()
    assert counters.device_stage_batches == 0


def test_rejection_log_dropped_counter():
    """Silent truncation of the bounded rejection log is now counted."""
    from daft_tpu.ops import counters

    counters.reset()
    for i in range(300):
        counters.reject("cost", "synthetic template", f"detail {i}")
    assert len(counters.rejection_log) == 256
    assert counters.rejection_log_dropped == 300 - 256
    assert counters.rejections["cost: synthetic template"] == 300
    counters.reset()
    assert counters.rejection_log_dropped == 0
    assert not counters.rejection_log
