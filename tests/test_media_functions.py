"""Audio / process function namespaces (reference: daft/functions/audio.py,
process.py). WAV decode is native (stdlib wave); non-WAV and video gate on
their optional packages like the reference."""

import io
import wave

import numpy as np
import pytest

import daft_tpu
from daft_tpu import col


def _make_wav(path, sr=8000, seconds=0.1, channels=1):
    n = int(sr * seconds)
    t = np.arange(n) / sr
    samples = (np.sin(2 * np.pi * 440 * t) * 32000).astype("<i2")
    if channels == 2:
        samples = np.repeat(samples, 2)
    with wave.open(str(path), "wb") as w:
        w.setnchannels(channels)
        w.setsampwidth(2)
        w.setframerate(sr)
        w.writeframes(samples.tobytes())
    return str(path)


def test_audio_metadata(tmp_path):
    p = _make_wav(tmp_path / "t.wav", sr=8000, channels=2)
    df = daft_tpu.from_pydict({"p": [p, None]})
    out = df.select(daft_tpu.file(col("p"))
                    ._fn("audio_metadata").alias("m")).to_pydict()
    m = out["m"][0]
    assert m["sample_rate"] == 8000 and m["channels"] == 2
    assert m["format"] == "WAV" and m["subtype"] == "PCM_16"
    assert m["frames"] == pytest.approx(800.0)
    assert out["m"][1] is None


def test_audio_resample(tmp_path):
    p = _make_wav(tmp_path / "t.wav", sr=8000)
    df = daft_tpu.from_pydict({"p": [p]})
    out = df.select(daft_tpu.file(col("p"))
                    ._fn("audio_resample", sample_rate=4000).alias("a")).to_pydict()
    arr = out["a"][0]
    assert arr.shape == (400, 1)
    assert np.abs(arr).max() <= 1.0


def test_run_process():
    from daft_tpu.functions import run_process

    df = daft_tpu.from_pydict({"a": ["hello", "daft"]})
    out = df.select(run_process(["echo", col("a")]).alias("o")).to_pydict()
    assert [v.strip() for v in out["o"]] == ["hello", "daft"]


def test_run_process_shell_and_dtype():
    from daft_tpu.functions import run_process

    df = daft_tpu.from_pydict({"x": ["a b c"]})
    out = df.select(run_process("echo " + col("x") + " | wc -w", shell=True,
                                return_dtype=daft_tpu.DataType.int64())
                    .alias("n")).to_pydict()
    assert out["n"] == [3]


def test_run_process_on_error_null():
    from daft_tpu.functions import run_process

    df = daft_tpu.from_pydict({"x": ["zz"]})
    out = df.select(run_process(["false"], on_error="ignore").alias("o")).to_pydict()
    assert out["o"] == [None]


def test_video_gated():
    df = daft_tpu.from_pydict({"p": ["x.mp4"]})
    with pytest.raises((ImportError, Exception)):
        df.select(daft_tpu.file(col("p"))._fn("video_metadata")).to_pydict()
