"""Device (JAX) expression evaluation must agree with host evaluation exactly,
including null semantics — the property the stage compiler relies on."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from daft_tpu import DataType, RecordBatch
from daft_tpu.expressions import col, lit
from daft_tpu.ops.device_eval import build_device_expr, device_agg, is_device_evaluable


def run_both(batch: RecordBatch, expr):
    """Evaluate expr on host and on device (under jit); return (host, device) pylists."""
    host = batch.eval_expression(expr)
    schema = batch.schema
    names = expr.referenced_columns()
    cols = {n: batch.get_column(n).to_device() for n in names}
    fn = build_device_expr(expr, schema)
    jitted = jax.jit(lambda c: fn(c))
    vals, valid = jitted(cols)
    vals = np.asarray(vals)
    valid = np.asarray(valid)
    if valid.ndim == 0:
        valid = np.full(len(vals), bool(valid))
    dev = [vals[i].item() if valid[i] else None for i in range(len(vals))]
    return host.to_pylist(), dev


CASES = [
    (col("a") + col("b")),
    (col("a") - 3),
    (col("a") * col("b") + 1),
    (col("a") / col("b")),
    (col("a") // col("b")),
    (col("a") % col("b")),
    (col("a") > col("b")),
    (col("a") <= 3),
    (col("a") == col("b")),
    ((col("a") > 1) & (col("b") > 1)),
    ((col("a") > 1) | (col("b") > 1)),
    (~(col("a") > 2)),
    (-col("a")),
    (col("a").abs()),
    (col("a").is_null()),
    (col("a").not_null()),
    (col("a").fill_null(0)),
    (col("a").between(1, 3)),
    (col("a").is_in([1, 4])),
    ((col("a") > 2).if_else(col("a"), col("b"))),
    (col("f").sqrt()),
    (col("f").exp()),
    (col("f").log()),
    (col("f").floor()),
    (col("f").ceil()),
    (col("f").round(1)),
    (col("f").float.is_nan()),
    (col("f").float.fill_nan(9.0)),
    (col("a").cast(DataType.float64()) * 2.5),
]


@pytest.mark.parametrize("expr", CASES, ids=[repr(e) for e in CASES])
def test_device_matches_host(expr):
    b = RecordBatch.from_pydict({
        "a": [1, 2, None, 4, 0],
        "b": [2, 0, 2, None, 3],
        "f": [1.5, float("nan"), None, 4.0, 0.25],
    })
    assert is_device_evaluable(expr, b.schema), f"{expr!r} should be device-evaluable"
    host, dev = run_both(b, expr)
    assert len(host) == len(dev)
    for h, d in zip(host, dev):
        if h is None or d is None:
            assert h is None and d is None, (host, dev)
        elif isinstance(h, float):
            if np.isnan(h):
                assert np.isnan(d)
            else:
                assert abs(h - d) < 1e-9, (host, dev)
        else:
            assert bool(h == d), (host, dev)


def test_not_device_evaluable():
    b = RecordBatch.from_pydict({"s": ["x", "y"], "a": [1, 2]})
    assert not is_device_evaluable(col("s").str.upper(), b.schema)
    assert not is_device_evaluable(col("s") + col("s"), b.schema)
    assert is_device_evaluable(col("a") + 1, b.schema)


def test_device_agg_matches_host():
    b = RecordBatch.from_pydict({"x": [1.0, 2.0, None, 4.0]})
    v, m = b.get_column("x").to_device(pad_to=8)
    for op, expected in [("sum", 7.0), ("mean", 7.0 / 3), ("min", 1.0), ("max", 4.0), ("count", 3)]:
        val, valid = jax.jit(lambda v, m, op=op: device_agg(op, v, m))(v, m)
        assert bool(valid)
        assert abs(float(val) - expected) < 1e-9, op


def test_device_agg_all_null():
    b = RecordBatch.from_pydict({"x": [None, None]})
    v, m = b.get_column("x").cast(DataType.float64()).to_device()
    val, valid = device_agg("sum", v, m)
    assert not bool(valid)
    val, valid = device_agg("count", v, m)
    assert bool(valid) and int(val) == 0


def test_padding_invariance():
    """Padded rows must not change live-row results — the static-shape convention.

    Row liveness is tracked by the stage compiler separately from validity (ops like
    fill_null can validly mark padding rows non-null); here we assert the live
    prefix is unaffected by padding.
    """
    b = RecordBatch.from_pydict({"a": [1, 2, None, 4, 0]})
    expr = (col("a") * 2 + 1).fill_null(-1)
    fn = build_device_expr(expr, b.schema)
    v8 = fn({"a": b.get_column("a").to_device(pad_to=8)})
    v5 = fn({"a": b.get_column("a").to_device()})
    np.testing.assert_array_equal(np.asarray(v8[0])[:5], np.asarray(v5[0]))
    np.testing.assert_array_equal(np.asarray(v8[1])[:5], np.asarray(v5[1]))


def test_device_agg_float_sum_uses_f64_accumulation():
    """Float sums must accumulate in f64: an f32 whole-bucket reduction carries
    only ~7 significant digits, corrupting partials before the host combine."""
    n = 200_000
    v = jnp.concatenate([jnp.asarray([1e8], jnp.float32),
                         jnp.full((n,), 0.25, jnp.float32)])
    m = jnp.ones((n + 1,), jnp.bool_)
    val, valid = jax.jit(lambda v, m: device_agg("sum", v, m))(v, m)
    assert bool(valid)
    expect = 1e8 + 0.25 * n
    assert abs(float(val) - expect) < 1.0  # f32 accumulation would be off by ~50k
