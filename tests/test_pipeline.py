"""Pipeline-parallel executor (execution/pipeline.py).

Covers the reference's execution-concurrency contract
(daft-local-execution pipeline.rs + channel.rs + intermediate_op.rs): operator
overlap, bounded-queue backpressure, ordered morsel fan-out, cancellation on
early consumer exit, and error propagation — plus engine-level parity between
the parallel and sequential interpreters.
"""

import threading
import time

import pytest

import daft_tpu
from daft_tpu import col, lit
from daft_tpu.config import execution_config_ctx
from daft_tpu.execution.pipeline import (Channel, StageCancelled, morsels,
                                         pmap_stream, spawn_stage)


def _stage_threads() -> int:
    return sum(1 for t in threading.enumerate() if t.name.startswith("daft-stage"))


# ---- primitives -------------------------------------------------------------------


def test_spawn_stage_streams_and_overlaps():
    def produce():
        for i in range(4):
            time.sleep(0.05)
            yield i

    t0 = time.perf_counter()
    out = []
    for item in spawn_stage(produce()):
        time.sleep(0.05)  # consumer work overlaps producer work
        out.append(item)
    elapsed = time.perf_counter() - t0
    assert out == [0, 1, 2, 3]
    assert elapsed < 0.38  # serial would be ~0.40s+; overlapped ~0.25s


def test_spawn_stage_propagates_errors():
    def produce():
        yield 1
        raise ValueError("boom")

    it = spawn_stage(produce())
    assert next(it) == 1
    with pytest.raises(ValueError, match="boom"):
        next(it)


def test_spawn_stage_cancellation_unwinds_producer():
    cleaned = threading.Event()

    def produce():
        try:
            for i in range(1000):
                yield i
        finally:
            cleaned.set()

    it = spawn_stage(produce(), maxsize=2)
    assert next(it) == 0
    it.close()  # consumer abandons (e.g. a downstream limit)
    assert cleaned.wait(timeout=5.0), "producer finally-block never ran"


def test_channel_backpressure_bounds_producer():
    ch = Channel(maxsize=2)
    produced = []

    def run():
        try:
            for i in range(100):
                ch.put(i)
                produced.append(i)
        except StageCancelled:
            pass

    t = threading.Thread(target=run, daemon=True)
    t.start()
    time.sleep(0.2)
    assert len(produced) <= 3  # 2 queued + 1 in flight: bounded, not run-ahead
    it = iter(ch)
    assert [next(it) for _ in range(5)] == [0, 1, 2, 3, 4]
    it.close()
    t.join(timeout=5.0)
    assert not t.is_alive()


def test_pmap_stream_preserves_order_and_parallelizes():
    from concurrent.futures import ThreadPoolExecutor

    from daft_tpu.utils import pool as pool_mod

    def slow_double(x, i):
        time.sleep(0.03)
        return (i, x * 2)

    # this box may have one core; prove overlap with an explicit 4-worker pool
    prev = pool_mod._POOL
    pool_mod._POOL = ThreadPoolExecutor(max_workers=4, thread_name_prefix="daft-compute")
    try:
        t0 = time.perf_counter()
        out = list(pmap_stream(iter(range(8)), slow_double, window=4))
        elapsed = time.perf_counter() - t0
    finally:
        pool_mod._POOL.shutdown(wait=False)
        pool_mod._POOL = prev
    assert out == [(i, i * 2) for i in range(8)]
    assert elapsed < 0.03 * 8  # sleeps overlap across the window


def test_pmap_stream_propagates_worker_errors():
    def fn(x, i):
        if x == 3:
            raise RuntimeError("worker failed")
        return x

    with pytest.raises(RuntimeError, match="worker failed"):
        list(pmap_stream(iter(range(6)), fn))


def test_morsels_zero_copy_slicing_roundtrip():
    df = daft_tpu.from_pydict({"a": list(range(10_000))}).collect()
    [part] = df.iter_partitions()
    pieces = morsels(part, 1024)
    assert len(pieces) == 10
    total = [v for p in pieces for v in p.batches[0].get_column("a").to_pylist()]
    assert total == list(range(10_000))
    assert morsels(part, 100_000) == [part]  # small inputs pass through


# ---- engine-level -----------------------------------------------------------------


def _queries(df, dim):
    return [
        lambda: df.where(col("a") % 7 != 0).select((col("a") * 3).alias("t"), col("k"))
                  .groupby("k").agg(col("t").sum().alias("s")).sort("k").to_pydict(),
        lambda: df.join(dim, on="k").where(col("w") > 5).count_rows(),
        lambda: df.join(dim, on="k", how="left").select(col("a"), col("w"))
                  .sort(["a"]).limit(17).to_pydict(),
        lambda: df.select(col("a")).limit(13).to_pydict(),
        lambda: df.distinct("k").sort("k").to_pydict(),
    ]


def test_parallel_matches_sequential_results():
    n = 300_000
    df = daft_tpu.from_pydict({
        "a": list(range(n)),
        "k": [i % 53 for i in range(n)],
    }).collect()
    dim = daft_tpu.from_pydict({"k": list(range(53)), "w": [float(i) for i in range(53)]})

    with execution_config_ctx(pipeline_mode="force", morsel_size_rows=32 * 1024):
        par = [q() for q in _queries(df, dim)]
    with execution_config_ctx(pipeline_mode="off"):
        seq = [q() for q in _queries(df, dim)]
    assert par == seq


def test_parallel_limit_leaves_no_stage_threads():
    n = 500_000
    df = daft_tpu.from_pydict({"a": list(range(n))}).collect()
    with execution_config_ctx(pipeline_mode="force", morsel_size_rows=16 * 1024):
        out = df.select((col("a") + 1).alias("b")).limit(5).to_pydict()
    assert out == {"b": [1, 2, 3, 4, 5]}
    deadline = time.time() + 5.0
    while _stage_threads() and time.time() < deadline:
        time.sleep(0.05)
    assert _stage_threads() == 0


def test_parallel_error_propagates_and_cleans_up():
    from daft_tpu.udf import func

    @func
    def explode_on_three(x: int) -> int:
        if x == 3:
            raise ValueError("udf boom")
        return x

    df = daft_tpu.from_pydict({"a": list(range(10))})
    with execution_config_ctx(pipeline_mode="force"):
        with pytest.raises(Exception, match="udf boom"):
            df.select(explode_on_three(col("a"))).to_pydict()
    deadline = time.time() + 5.0
    while _stage_threads() and time.time() < deadline:
        time.sleep(0.05)
    assert _stage_threads() == 0


def test_probe_table_streaming_join_matches_batch_join():
    """JoinProbe (build-once probe-many) must agree with one-shot hash_join
    across join types, incl. nulls on both sides."""
    import numpy as np

    rng = np.random.default_rng(7)
    n = 50_000
    left = daft_tpu.from_pydict({
        "k": [int(x) if x % 11 else None for x in rng.integers(0, 997, n)],
        "v": list(range(n)),
    }).collect()
    right = daft_tpu.from_pydict({
        "k": [int(x) if x % 13 else None for x in rng.integers(0, 997, 900)],
        "w": [float(i) for i in range(900)],
    }).collect()
    for how in ("inner", "left", "semi", "anti"):
        with execution_config_ctx(pipeline_mode="force", morsel_size_rows=8 * 1024):
            par = left.join(right, on="k", how=how).sort(["v"]).to_pydict()
        with execution_config_ctx(pipeline_mode="off"):
            seq = left.join(right, on="k", how=how).sort(["v"]).to_pydict()
        assert par == seq, how


def test_seeded_sample_is_chunking_invariant():
    """Seeded sampling picks the same rows whether the engine runs sequential
    or pipeline-parallel with morselized streams (position-hashed Bernoulli)."""
    n = 200_000
    df = daft_tpu.from_pydict({"a": list(range(n))}).collect()
    with execution_config_ctx(pipeline_mode="force", morsel_size_rows=16 * 1024):
        par = df.select((col("a") * 2).alias("b")).sample(0.01, seed=7).to_pydict()
    with execution_config_ctx(pipeline_mode="off"):
        seq = df.select((col("a") * 2).alias("b")).sample(0.01, seed=7).to_pydict()
    assert par == seq
    assert 0.005 * n < len(par["b"]) < 0.015 * n


def test_unstarted_plan_spawns_no_stage_threads():
    """Building an execution stream and abandoning it before the first pull
    must not leak stage threads (lazy thread start)."""
    df = daft_tpu.from_pydict({"a": list(range(100_000))}).collect()
    with execution_config_ctx(pipeline_mode="force"):
        from daft_tpu.execution.executor import execute_plan
        from daft_tpu.plan.physical import translate

        builder = df.select((col("a") + 1).alias("b"))._builder
        stream = execute_plan(translate(builder.optimize().plan))
        del stream
    time.sleep(0.3)
    assert _stage_threads() == 0
