"""Iceberg REST catalog client against the in-process mock service
(VERDICT r4 next #8: namespace/table listing + load + snapshot read +
write-commit round-trip). Reference: daft/catalog/__iceberg.py."""

import numpy as np
import pytest

import daft_tpu
from daft_tpu.io.iceberg_rest import (IcebergRestCatalog, IcebergRestError,
                                      make_mock_rest_server)


@pytest.fixture()
def rest(tmp_path):
    server, uri = make_mock_rest_server(str(tmp_path / "wh"))
    yield uri
    server.shutdown()


def _df():
    return daft_tpu.from_pydict({
        "id": [1, 2, 3, 4], "region": ["a", "a", "b", "b"],
        "amount": [10.0, 20.0, 30.0, 40.0]})


def test_namespaces_and_listing(rest):
    cat = IcebergRestCatalog(rest, name="ice")
    assert cat.list_namespaces() == []
    cat.create_namespace("sales")
    cat.create_namespace("web.logs")
    assert cat.list_namespaces() == ["sales", "web.logs"]
    assert cat.list_tables() == []
    cat.create_table("sales.orders", _df().schema)
    assert cat.list_tables() == ["sales.orders"]
    assert cat.list_tables("web.logs") == []
    cat.drop_table("sales.orders")
    assert cat.list_tables() == []
    cat.drop_namespace("web.logs")
    assert cat.list_namespaces() == ["sales"]


def test_write_commit_load_roundtrip(rest):
    cat = IcebergRestCatalog(rest)
    cat.create_namespace("sales")
    df = _df()
    cat.write_table("sales.orders", df)          # create + commit
    out = cat.load_table("sales.orders").sort("id").to_pydict()
    assert out == df.sort("id").to_pydict()

    # append: second snapshot through the commit endpoint
    cat.write_table("sales.orders", df)
    out2 = cat.load_table("sales.orders").to_pydict()
    assert len(out2["id"]) == 8
    meta = cat.table_metadata("sales.orders")
    assert len(meta["snapshots"]) >= 2
    assert meta["refs"]["main"]["snapshot-id"] == meta["current-snapshot-id"]

    # snapshot read: the FIRST snapshot still sees 4 rows
    first = meta["snapshots"][0]["snapshot-id"]
    old = cat.load_table("sales.orders", snapshot_id=first).to_pydict()
    assert len(old["id"]) == 4


def test_oauth_and_errors(rest):
    cat = IcebergRestCatalog(rest, credential="user:pass")
    assert cat._token == "mock-token"
    with pytest.raises(Exception):
        IcebergRestCatalog(rest, credential="user:WRONG")
    cat.create_namespace("ns")
    with pytest.raises(IcebergRestError) as ei:
        cat.load_table("ns.missing")
    assert ei.value.status == 404


def test_session_attach_and_sql(rest):
    from daft_tpu.session import Session

    cat = IcebergRestCatalog(rest, name="ice")
    cat.create_namespace("sales")
    cat.write_table("sales.orders", _df())
    s = Session()
    s.attach_catalog(cat, "ice")
    out = s.sql("SELECT region, SUM(amount) AS total FROM ice.sales.orders "
                "GROUP BY region ORDER BY region").to_pydict()
    assert out == {"region": ["a", "b"], "total": [30.0, 70.0]}
