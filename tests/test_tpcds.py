"""TPC-DS store-sales channel: every query cross-checked cell-by-cell against
an independent pandas computation over the same synthetic tables.

Reference parity: benchmarking/tpcds/ (the reference validates against DuckDB
answers; here pandas is the independent oracle).
"""

import os
import sys

import numpy as np
import pandas as pd
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarking.tpcds.datagen import cached_tables, load_dataframes
from benchmarking.tpcds.queries import ALL_QUERIES

SF = 0.05


@pytest.fixture(scope="module")
def tables():
    return {k: v.collect() for k, v in load_dataframes(sf=SF, seed=0).items()}


@pytest.fixture(scope="module")
def pdt():
    return {k: t.to_pandas() for k, t in cached_tables(sf=SF, seed=0).items()}


def _check(out_dict, expected_df):
    got = pd.DataFrame(out_dict).reset_index(drop=True)
    exp = expected_df.reset_index(drop=True)
    assert list(got.columns) == list(exp.columns)
    assert len(got) == len(exp)
    for c in exp.columns:
        if exp[c].dtype.kind == "f":
            assert np.allclose(got[c].astype(float), exp[c].astype(float),
                               rtol=1e-9, atol=1e-6, equal_nan=True), c
        else:
            assert got[c].tolist() == exp[c].tolist(), c


def test_q3(tables, pdt):
    m = pdt["store_sales"].merge(
        pdt["date_dim"][pdt["date_dim"].d_moy == 11],
        left_on="ss_sold_date_sk", right_on="d_date_sk").merge(
        pdt["item"][pdt["item"].i_manufact_id == 128],
        left_on="ss_item_sk", right_on="i_item_sk")
    exp = (m.groupby(["d_year", "i_brand", "i_brand_id"], as_index=False)
           .agg(sum_agg=("ss_ext_sales_price", "sum"))
           .sort_values(["d_year", "sum_agg", "i_brand_id"],
                        ascending=[True, False, True], kind="stable")
           .head(100)
           .rename(columns={"i_brand_id": "brand_id", "i_brand": "brand"})
           [["d_year", "brand_id", "brand", "sum_agg"]])
    assert len(exp) > 0, "q3 selects nothing at this SF; raise SF"
    _check(ALL_QUERIES[3](tables).to_pydict(), exp)


def test_q7(tables, pdt):
    cd = pdt["customer_demographics"]
    cd = cd[(cd.cd_gender == "M") & (cd.cd_marital_status == "S")
            & (cd.cd_education_status == "College")]
    promo = pdt["promotion"]
    promo = promo[(promo.p_channel_email == "N") | (promo.p_channel_event == "N")]
    m = (pdt["store_sales"]
         .merge(cd, left_on="ss_cdemo_sk", right_on="cd_demo_sk")
         .merge(pdt["date_dim"][pdt["date_dim"].d_year == 2000],
                left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(pdt["item"], left_on="ss_item_sk", right_on="i_item_sk")
         .merge(promo, left_on="ss_promo_sk", right_on="p_promo_sk"))
    exp = (m.groupby("i_item_id", as_index=False)
           .agg(agg1=("ss_quantity", "mean"), agg2=("ss_list_price", "mean"),
                agg3=("ss_coupon_amt", "mean"), agg4=("ss_sales_price", "mean"))
           .sort_values("i_item_id", kind="stable").head(100))
    assert len(exp) > 0
    _check(ALL_QUERIES[7](tables).to_pydict(), exp)


def test_q19(tables, pdt):
    dd = pdt["date_dim"]
    m = (pdt["store_sales"]
         .merge(dd[(dd.d_moy == 11) & (dd.d_year == 1998)],
                left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(pdt["item"][pdt["item"].i_manager_id == 8],
                left_on="ss_item_sk", right_on="i_item_sk")
         .merge(pdt["customer"], left_on="ss_customer_sk", right_on="c_customer_sk")
         .merge(pdt["customer_address"], left_on="c_current_addr_sk",
                right_on="ca_address_sk")
         .merge(pdt["store"], left_on="ss_store_sk", right_on="s_store_sk"))
    m = m[m.ca_zip.str[:5] != m.s_zip.str[:5]]
    exp = (m.groupby(["i_brand", "i_brand_id", "i_manufact_id"], as_index=False)
           .agg(ext_price=("ss_ext_sales_price", "sum"))
           .sort_values(["ext_price", "i_brand", "i_brand_id", "i_manufact_id"],
                        ascending=[False, True, True, True], kind="stable")
           .head(100)
           .rename(columns={"i_brand_id": "brand_id", "i_brand": "brand"})
           [["brand_id", "brand", "i_manufact_id", "ext_price"]])
    assert len(exp) > 0
    _check(ALL_QUERIES[19](tables).to_pydict(), exp)


def test_q42(tables, pdt):
    dd = pdt["date_dim"]
    m = (pdt["store_sales"]
         .merge(dd[(dd.d_moy == 11) & (dd.d_year == 2000)],
                left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(pdt["item"][pdt["item"].i_manager_id == 1],
                left_on="ss_item_sk", right_on="i_item_sk"))
    exp = (m.groupby(["d_year", "i_category_id", "i_category"], as_index=False)
           .agg(total=("ss_ext_sales_price", "sum"))
           .sort_values(["total", "d_year", "i_category_id", "i_category"],
                        ascending=[False, True, True, True], kind="stable")
           .head(100))
    assert len(exp) > 0
    _check(ALL_QUERIES[42](tables).to_pydict(), exp)


def test_q52_q55(tables, pdt):
    dd = pdt["date_dim"]
    m52 = (pdt["store_sales"]
           .merge(dd[(dd.d_moy == 11) & (dd.d_year == 2000)],
                  left_on="ss_sold_date_sk", right_on="d_date_sk")
           .merge(pdt["item"][pdt["item"].i_manager_id == 1],
                  left_on="ss_item_sk", right_on="i_item_sk"))
    exp52 = (m52.groupby(["d_year", "i_brand", "i_brand_id"], as_index=False)
             .agg(ext_price=("ss_ext_sales_price", "sum"))
             .sort_values(["d_year", "ext_price", "i_brand_id"],
                          ascending=[True, False, True], kind="stable")
             .head(100)
             .rename(columns={"i_brand_id": "brand_id", "i_brand": "brand"})
             [["d_year", "brand_id", "brand", "ext_price"]])
    assert len(exp52) > 0
    _check(ALL_QUERIES[52](tables).to_pydict(), exp52)

    m55 = (pdt["store_sales"]
           .merge(dd[(dd.d_moy == 11) & (dd.d_year == 1999)],
                  left_on="ss_sold_date_sk", right_on="d_date_sk")
           .merge(pdt["item"][pdt["item"].i_manager_id == 28],
                  left_on="ss_item_sk", right_on="i_item_sk"))
    exp55 = (m55.groupby(["i_brand", "i_brand_id"], as_index=False)
             .agg(ext_price=("ss_ext_sales_price", "sum"))
             .sort_values(["ext_price", "i_brand_id"],
                          ascending=[False, True], kind="stable")
             .head(100)
             .rename(columns={"i_brand_id": "brand_id", "i_brand": "brand"})
             [["brand_id", "brand", "ext_price"]])
    assert len(exp55) > 0
    _check(ALL_QUERIES[55](tables).to_pydict(), exp55)


def test_q96(tables, pdt):
    td = pdt["time_dim"]
    hd = pdt["household_demographics"]
    st = pdt["store"]
    m = (pdt["store_sales"]
         .merge(td[(td.t_hour == 20) & (td.t_minute >= 30)],
                left_on="ss_sold_time_sk", right_on="t_time_sk")
         .merge(hd[hd.hd_dep_count == 7], left_on="ss_hdemo_sk",
                right_on="hd_demo_sk")
         .merge(st[st.s_store_name == "ese"], left_on="ss_store_sk",
                right_on="s_store_sk"))
    got = ALL_QUERIES[96](tables).to_pydict()
    assert got["count"][0] == len(m)
    assert len(m) > 0


def _three_channel_expected(pdt, key, item_mask, d_year, d_moy):
    dd = pdt["date_dim"]
    dd = dd[(dd.d_year == d_year) & (dd.d_moy == d_moy)]
    ca = pdt["customer_address"]
    ca = ca[ca.ca_gmt_offset == -5.0]
    wanted = set(pdt["item"][item_mask][key])
    frames = []
    for fact, prefix, addr in (("store_sales", "ss", "ss_addr_sk"),
                               ("catalog_sales", "cs", "cs_bill_addr_sk"),
                               ("web_sales", "ws", "ws_bill_addr_sk")):
        m = (pdt[fact]
             .merge(dd, left_on=f"{prefix}_sold_date_sk", right_on="d_date_sk")
             .merge(ca, left_on=addr, right_on="ca_address_sk")
             .merge(pdt["item"], left_on=f"{prefix}_item_sk", right_on="i_item_sk"))
        m = m[m[key].isin(wanted)]
        frames.append(m.groupby(key, as_index=False)
                      .agg(total_sales=(f"{prefix}_ext_sales_price", "sum")))
    allf = pd.concat(frames)
    return (allf.groupby(key, as_index=False)
            .agg(total_sales=("total_sales", "sum"))
            .sort_values(["total_sales", key], kind="stable")
            .head(100)[[key, "total_sales"]])


def test_q33(tables, pdt):
    exp = _three_channel_expected(pdt, "i_manufact_id",
                                  pdt["item"].i_category == "Electronics", 1998, 5)
    assert len(exp) > 0
    _check(ALL_QUERIES[33](tables).to_pydict(), exp)


def test_q56(tables, pdt):
    exp = _three_channel_expected(
        pdt, "i_item_id",
        pdt["item"].i_color.isin(["slate", "blanched", "burnished"]), 2001, 2)
    assert len(exp) > 0
    _check(ALL_QUERIES[56](tables).to_pydict(), exp)


# ======================================================================================
# round-5 expansion: window/rollup/report shapes (VERDICT r4 next #9)
# ======================================================================================


def test_q6(tables, pdt):
    dd = pdt["date_dim"]
    target = set(dd[(dd.d_year == 2001) & (dd.d_moy == 1)].d_month_seq)
    months = dd[dd.d_month_seq.isin(target)].d_date_sk
    item = pdt["item"].copy()
    cat_avg = item.groupby("i_category")["i_current_price"].transform("mean")
    pricey = set(item[item.i_current_price > 1.2 * cat_avg].i_item_sk)
    m = (pdt["store_sales"][pdt["store_sales"].ss_sold_date_sk.isin(set(months))
                            & pdt["store_sales"].ss_item_sk.isin(pricey)]
         .merge(pdt["customer"], left_on="ss_customer_sk", right_on="c_customer_sk")
         .merge(pdt["customer_address"], left_on="c_current_addr_sk",
                right_on="ca_address_sk"))
    exp = (m.groupby("ca_state", as_index=False).agg(cnt=("ca_state", "count"))
           .rename(columns={"ca_state": "state"}))
    exp = exp[exp.cnt >= 10].sort_values(["cnt", "state"], kind="stable").head(100)
    assert len(exp) > 0
    _check(ALL_QUERIES[6](tables).to_pydict(), exp)


def _class_ratio_exp(pdt, fact, prefix, categories, lo, hi):
    import datetime

    item = pdt["item"][pdt["item"].i_category.isin(categories)]
    dd = pdt["date_dim"]
    dd = dd[(dd.d_date >= datetime.date(*lo)) & (dd.d_date <= datetime.date(*hi))]
    m = (pdt[fact].merge(item, left_on=f"{prefix}_item_sk", right_on="i_item_sk")
         .merge(dd, left_on=f"{prefix}_sold_date_sk", right_on="d_date_sk"))
    g = (m.groupby(["i_item_id", "i_class", "i_category", "i_current_price"],
                   as_index=False)
         .agg(itemrevenue=(f"{prefix}_ext_sales_price", "sum")))
    g["revenueratio"] = g.itemrevenue * 100.0 \
        / g.groupby("i_class")["itemrevenue"].transform("sum")
    return (g.sort_values(["i_category", "i_class", "i_item_id", "revenueratio"],
                          kind="stable").head(100))


def test_q12_q20_q98(tables, pdt):
    for qn, fact, prefix in ((12, "web_sales", "ws"), (20, "catalog_sales", "cs"),
                             (98, "store_sales", "ss")):
        exp = _class_ratio_exp(pdt, fact, prefix, ["Sports", "Books", "Home"],
                               (1999, 2, 22), (1999, 3, 24))
        assert len(exp) > 0
        _check(ALL_QUERIES[qn](tables).to_pydict(), exp)


def _q27_base(pdt):
    cd = pdt["customer_demographics"]
    cd = cd[(cd.cd_gender == "M") & (cd.cd_marital_status == "S")
            & (cd.cd_education_status == "College")]
    st = pdt["store"][pdt["store"].s_state.isin(
        ["TN", "GA", "AL", "SC", "NC", "KY"])]
    return (pdt["store_sales"]
            .merge(cd, left_on="ss_cdemo_sk", right_on="cd_demo_sk")
            .merge(pdt["date_dim"][pdt["date_dim"].d_year == 2002],
                   left_on="ss_sold_date_sk", right_on="d_date_sk")
            .merge(st, left_on="ss_store_sk", right_on="s_store_sk")
            .merge(pdt["item"], left_on="ss_item_sk", right_on="i_item_sk"))


def test_q27(tables, pdt):
    import pandas as pd

    base = _q27_base(pdt)
    aggs = dict(agg1=("ss_quantity", "mean"), agg2=("ss_list_price", "mean"),
                agg3=("ss_coupon_amt", "mean"), agg4=("ss_sales_price", "mean"))
    l2 = base.groupby(["i_item_id", "s_state"], as_index=False).agg(**aggs)
    l1 = base.groupby(["i_item_id"], as_index=False).agg(**aggs)
    l1["s_state"] = None
    g0 = pd.DataFrame({
        "i_item_id": [None], "s_state": [None],
        "agg1": [base.ss_quantity.mean()], "agg2": [base.ss_list_price.mean()],
        "agg3": [base.ss_coupon_amt.mean()], "agg4": [base.ss_sales_price.mean()]})
    cols = ["i_item_id", "s_state", "agg1", "agg2", "agg3", "agg4"]
    exp = (pd.concat([l2[cols], l1[cols], g0[cols]])
           .sort_values(["i_item_id", "s_state"], kind="stable",
                        na_position="last")
           .head(100))
    for c in ("i_item_id", "s_state"):  # rollup nulls: NaN -> None for _check
        exp[c] = [None if pd.isna(v) else v for v in exp[c]]
    assert len(exp) > 10
    _check(ALL_QUERIES[27](tables).to_pydict(), exp)


def test_q36(tables, pdt):
    st = pdt["store"][pdt["store"].s_state.isin(
        ["TN", "GA", "AL", "SC", "NC", "KY", "VA", "FL"])]
    base = (pdt["store_sales"]
            .merge(pdt["date_dim"][pdt["date_dim"].d_year == 2001],
                   left_on="ss_sold_date_sk", right_on="d_date_sk")
            .merge(pdt["item"], left_on="ss_item_sk", right_on="i_item_sk")
            .merge(st, left_on="ss_store_sk", right_on="s_store_sk"))
    l2 = base.groupby(["i_category", "i_class"], as_index=False).agg(
        np=("ss_net_profit", "sum"), esp=("ss_ext_sales_price", "sum"))
    l2["lochierarchy"] = 0
    l1 = base.groupby(["i_category"], as_index=False).agg(
        np=("ss_net_profit", "sum"), esp=("ss_ext_sales_price", "sum"))
    l1["i_class"] = None
    l1["lochierarchy"] = 1
    g0 = pd.DataFrame({"i_category": [None], "i_class": [None],
                       "np": [base.ss_net_profit.sum()],
                       "esp": [base.ss_ext_sales_price.sum()],
                       "lochierarchy": [2]})
    cols = ["i_category", "i_class", "lochierarchy", "np", "esp"]
    u = pd.concat([l2[cols], l1[cols], g0[cols]]).reset_index(drop=True)
    u["gross_margin"] = u.np / u.esp
    u["parent"] = np.where(u.lochierarchy == 0, u.i_category, None)
    u["rank_within_parent"] = (
        u.groupby(["lochierarchy", "parent"], dropna=False)["gross_margin"]
        .rank(method="min", ascending=True).astype(int))
    exp = (u[["gross_margin", "i_category", "i_class", "lochierarchy",
              "rank_within_parent"]]
           .sort_values(["lochierarchy", "i_category", "rank_within_parent"],
                        ascending=[False, True, True], kind="stable",
                        na_position="last")
           .head(100))
    for c in ("i_category", "i_class"):
        exp[c] = [None if pd.isna(v) else v for v in exp[c]]
    assert len(exp) > 5
    _check(ALL_QUERIES[36](tables).to_pydict(), exp)


def test_q43(tables, pdt):
    m = (pdt["store_sales"]
         .merge(pdt["date_dim"][pdt["date_dim"].d_year == 2000],
                left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(pdt["store"][pdt["store"].s_gmt_offset == -5.0],
                left_on="ss_store_sk", right_on="s_store_sk"))
    days = [("Sunday", "sun_sales"), ("Monday", "mon_sales"),
            ("Tuesday", "tue_sales"), ("Wednesday", "wed_sales"),
            ("Thursday", "thu_sales"), ("Friday", "fri_sales"),
            ("Saturday", "sat_sales")]
    for dname, alias in days:
        m[alias] = np.where(m.d_day_name == dname, m.ss_sales_price, 0.0)
    exp = (m.groupby(["s_store_name", "s_store_id"], as_index=False)
           [[a for _d, a in days]].sum()
           .sort_values(["s_store_name", "s_store_id"], kind="stable")
           .head(100))
    assert len(exp) > 0
    _check(ALL_QUERIES[43](tables).to_pydict(), exp)


def test_q48(tables, pdt):
    m = (pdt["store_sales"]
         .merge(pdt["store"], left_on="ss_store_sk", right_on="s_store_sk")
         .merge(pdt["customer_demographics"], left_on="ss_cdemo_sk",
                right_on="cd_demo_sk")
         .merge(pdt["customer_address"], left_on="ss_addr_sk",
                right_on="ca_address_sk")
         .merge(pdt["date_dim"][pdt["date_dim"].d_year == 2000],
                left_on="ss_sold_date_sk", right_on="d_date_sk"))
    cd_ok = (((m.cd_marital_status == "M") & (m.cd_education_status == "4 yr Degree")
              & m.ss_sales_price.between(100.0, 150.0))
             | ((m.cd_marital_status == "D") & (m.cd_education_status == "2 yr Degree")
                & m.ss_sales_price.between(50.0, 100.0))
             | ((m.cd_marital_status == "S") & (m.cd_education_status == "College")
                & m.ss_sales_price.between(150.0, 200.0)))
    ca_ok = ((m.ca_country == "United States")
             & ((m.ca_state.isin(["TN", "GA", "AL"])
                 & m.ss_net_profit.between(0.0, 2000.0))
                | (m.ca_state.isin(["SC", "NC", "KY"])
                   & m.ss_net_profit.between(150.0, 3000.0))
                | (m.ca_state.isin(["VA", "FL", "MS"])
                   & m.ss_net_profit.between(50.0, 25000.0))))
    total = m[cd_ok & ca_ok].ss_quantity.sum()
    exp = pd.DataFrame({"total_quantity": [total]})
    _check(ALL_QUERIES[48](tables).to_pydict(), exp)


def test_q51(tables, pdt):
    dd = pdt["date_dim"]
    months = dd[dd.d_month_seq.between(1200, 1211)][["d_date_sk", "d_date"]]

    def cume(fact, prefix):
        m = pdt[fact].merge(months, left_on=f"{prefix}_sold_date_sk",
                            right_on="d_date_sk")
        g = (m.groupby([f"{prefix}_item_sk", "d_date"], as_index=False)
             .agg(daily=(f"{prefix}_ext_sales_price", "sum"))
             .rename(columns={f"{prefix}_item_sk": "item_sk"})
             .sort_values(["item_sk", "d_date"], kind="stable"))
        g["cume"] = g.groupby("item_sk")["daily"].cumsum()
        return g[["item_sk", "d_date", "cume"]]

    web, store = cume("web_sales", "ws"), cume("store_sales", "ss")
    j = web.merge(store, on=["item_sk", "d_date"], how="outer",
                  suffixes=("", "_ss")).sort_values(
        ["item_sk", "d_date"], kind="stable")
    # cummax leaves NaN at NaN positions; SQL's running max carries the last
    # seen value through null rows — forward-fill within each item
    j["web_cumulative"] = j.groupby("item_sk")["cume"].cummax()
    j["web_cumulative"] = j.groupby("item_sk")["web_cumulative"].ffill()
    j["store_cumulative"] = j.groupby("item_sk")["cume_ss"].cummax()
    j["store_cumulative"] = j.groupby("item_sk")["store_cumulative"].ffill()
    exp = (j[j.web_cumulative > j.store_cumulative]
           [["item_sk", "d_date", "web_cumulative", "store_cumulative"]]
           .sort_values(["item_sk", "d_date"], kind="stable").head(100))
    assert len(exp) > 0
    _check(ALL_QUERIES[51](tables).to_pydict(), exp)


def test_q59(tables, pdt):
    m = pdt["store_sales"].merge(pdt["date_dim"], left_on="ss_sold_date_sk",
                                 right_on="d_date_sk")
    days = [("Sunday", "sun"), ("Monday", "mon"), ("Tuesday", "tue"),
            ("Wednesday", "wed"), ("Thursday", "thu"), ("Friday", "fri"),
            ("Saturday", "sat")]
    for dname, alias in days:
        m[alias] = np.where(m.d_day_name == dname, m.ss_sales_price, 0.0)
    wss = m.groupby(["d_week_seq", "ss_store_sk"], as_index=False)[
        [a for _d, a in days]].sum()
    dd = pdt["date_dim"]
    w1 = set(dd[dd.d_month_seq.between(1176, 1187)].d_week_seq)
    w2 = set(dd[dd.d_month_seq.between(1188, 1199)].d_week_seq)
    y = (wss[wss.d_week_seq.isin(w1)]
         .merge(pdt["store"], left_on="ss_store_sk", right_on="s_store_sk"))
    y2 = (wss[wss.d_week_seq.isin(w2)]
          .merge(pdt["store"], left_on="ss_store_sk", right_on="s_store_sk"))
    y2 = y2.rename(columns={a: a + "2" for _d, a in days})
    y2["d_week_seq"] = y2.d_week_seq - 52
    j = y.merge(y2[["s_store_id", "d_week_seq"] + [a + "2" for _d, a in days]],
                on=["s_store_id", "d_week_seq"])
    out = pd.DataFrame({
        "s_store_name": j.s_store_name, "s_store_id": j.s_store_id,
        "d_week_seq": j.d_week_seq})
    for _d, a in days:
        out[f"r_{a}"] = j[a] / j[a + "2"]
    exp = (out.sort_values(["s_store_name", "s_store_id", "d_week_seq"],
                           kind="stable").head(100))
    assert len(exp) > 0
    _check(ALL_QUERIES[59](tables).to_pydict(), exp)


def test_q63(tables, pdt):
    it = pdt["item"]
    items = it[((it.i_category.isin(["Books", "Children", "Electronics"])
                 & it.i_class.isin(["accent", "classical", "fiction"]))
                | (it.i_category.isin(["Women", "Music", "Men"])
                   & it.i_class.isin(["dresses", "rock", "pants"])))]
    m = (pdt["store_sales"]
         .merge(items, left_on="ss_item_sk", right_on="i_item_sk")
         .merge(pdt["date_dim"][pdt["date_dim"].d_year == 2000],
                left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(pdt["store"], left_on="ss_store_sk", right_on="s_store_sk"))
    g = (m.groupby(["i_manager_id", "d_moy"], as_index=False)
         .agg(sum_sales=("ss_sales_price", "sum")))
    g["avg_monthly_sales"] = g.groupby("i_manager_id")["sum_sales"].transform("mean")
    g = g[(g.avg_monthly_sales > 0)
          & ((g.sum_sales - g.avg_monthly_sales).abs() / g.avg_monthly_sales > 0.1)]
    exp = (g[["i_manager_id", "sum_sales", "avg_monthly_sales"]]
           .sort_values(["i_manager_id", "avg_monthly_sales", "sum_sales"],
                        kind="stable").head(100))
    assert len(exp) > 0
    _check(ALL_QUERIES[63](tables).to_pydict(), exp)


def test_q65(tables, pdt):
    dd = pdt["date_dim"]
    months = set(dd[dd.d_month_seq.between(1176, 1187)].d_date_sk)
    ss = pdt["store_sales"][pdt["store_sales"].ss_sold_date_sk.isin(months)]
    sales = (ss.groupby(["ss_store_sk", "ss_item_sk"], as_index=False)
             .agg(revenue=("ss_sales_price", "sum")))
    sales["ave"] = sales.groupby("ss_store_sk")["revenue"].transform("mean")
    low = sales[sales.revenue <= 0.1 * sales.ave]
    exp = (low.merge(pdt["store"], left_on="ss_store_sk", right_on="s_store_sk")
           .merge(pdt["item"], left_on="ss_item_sk", right_on="i_item_sk")
           [["s_store_name", "i_item_id", "revenue"]]
           .sort_values(["s_store_name", "i_item_id"], kind="stable").head(100))
    assert len(exp) > 0
    _check(ALL_QUERIES[65](tables).to_pydict(), exp)


def test_q73(tables, pdt):
    hd = pdt["household_demographics"]
    hd = hd[hd.hd_buy_potential.isin([">10000", "Unknown"])
            & (hd.hd_vehicle_count > 0)
            & (hd.hd_dep_count / hd.hd_vehicle_count > 1.0)]
    dd = pdt["date_dim"]
    dd = dd[dd.d_dom.between(1, 2) & dd.d_year.isin([1999, 2000, 2001])]
    st = pdt["store"][pdt["store"].s_county.isin(
        ["Williamson County", "Franklin Parish"])]
    m = (pdt["store_sales"]
         .merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(hd, left_on="ss_hdemo_sk", right_on="hd_demo_sk")
         .merge(st, left_on="ss_store_sk", right_on="s_store_sk"))
    g = (m.groupby(["ss_ticket_number", "ss_customer_sk"], as_index=False)
         .agg(cnt=("ss_ticket_number", "count")))
    g = g[g.cnt.between(1, 5)]
    exp = (g.merge(pdt["customer"], left_on="ss_customer_sk",
                   right_on="c_customer_sk")
           [["c_last_name", "c_first_name", "ss_ticket_number", "cnt"]]
           .sort_values(["cnt", "c_last_name", "ss_ticket_number"],
                        ascending=[False, True, True], kind="stable").head(100))
    assert len(exp) > 0
    _check(ALL_QUERIES[73](tables).to_pydict(), exp)


def test_q79(tables, pdt):
    hd = pdt["household_demographics"]
    hd = hd[(hd.hd_dep_count == 6) | (hd.hd_vehicle_count > 2)]
    dd = pdt["date_dim"]
    dd = dd[(dd.d_dow == 1) & dd.d_year.isin([1999, 2000, 2001])]
    st = pdt["store"][pdt["store"].s_number_employees.between(200, 295)]
    m = (pdt["store_sales"]
         .merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(st, left_on="ss_store_sk", right_on="s_store_sk")
         .merge(hd, left_on="ss_hdemo_sk", right_on="hd_demo_sk"))
    g = (m.groupby(["ss_ticket_number", "ss_customer_sk", "s_city"],
                   as_index=False)
         .agg(amt=("ss_coupon_amt", "sum"), profit=("ss_net_profit", "sum")))
    exp = (g.merge(pdt["customer"], left_on="ss_customer_sk",
                   right_on="c_customer_sk")
           [["c_last_name", "c_first_name", "s_city", "profit",
             "ss_ticket_number", "amt"]]
           .sort_values(["c_last_name", "c_first_name", "s_city", "profit",
                         "ss_ticket_number"], kind="stable").head(100))
    assert len(exp) > 0
    _check(ALL_QUERIES[79](tables).to_pydict(), exp)


def test_q88(tables, pdt):
    hd = pdt["household_demographics"]
    hd = hd[((hd.hd_dep_count == 4) & (hd.hd_vehicle_count <= 6))
            | ((hd.hd_dep_count == 2) & (hd.hd_vehicle_count <= 4))
            | ((hd.hd_dep_count == 0) & (hd.hd_vehicle_count <= 2))]
    base = (pdt["store_sales"]
            .merge(hd, left_on="ss_hdemo_sk", right_on="hd_demo_sk")
            .merge(pdt["store"][pdt["store"].s_store_name == "ese"],
                   left_on="ss_store_sk", right_on="s_store_sk"))
    td = pdt["time_dim"]

    def slot(h, half):
        t_ = td[(td.t_hour == h) & (td.t_minute >= 30 if half else td.t_minute < 30)]
        return len(base.merge(t_, left_on="ss_sold_time_sk", right_on="t_time_sk"))

    exp = pd.DataFrame({
        "h8_30_to_9": [slot(8, True)], "h9_to_9_30": [slot(9, False)],
        "h9_30_to_10": [slot(9, True)], "h10_to_10_30": [slot(10, False)],
        "h10_30_to_11": [slot(10, True)], "h11_to_11_30": [slot(11, False)],
        "h11_30_to_12": [slot(11, True)], "h12_to_12_30": [slot(12, False)]})
    _check(ALL_QUERIES[88](tables).to_pydict(), exp)


def test_q89(tables, pdt):
    it = pdt["item"]
    items = it[((it.i_category.isin(["Books", "Electronics", "Sports"])
                 & it.i_class.isin(["fiction", "portable", "rock"]))
                | (it.i_category.isin(["Men", "Jewelry", "Women"])
                   & it.i_class.isin(["accent", "pants", "dresses"])))]
    m = (pdt["store_sales"]
         .merge(items, left_on="ss_item_sk", right_on="i_item_sk")
         .merge(pdt["date_dim"][pdt["date_dim"].d_year == 1999],
                left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(pdt["store"], left_on="ss_store_sk", right_on="s_store_sk"))
    g = (m.groupby(["i_category", "i_class", "i_brand", "s_store_name",
                    "s_company_name", "d_moy"], as_index=False)
         .agg(sum_sales=("ss_sales_price", "sum")))
    g["avg_monthly_sales"] = g.groupby(
        ["i_category", "i_brand", "s_store_name", "s_company_name"]
    )["sum_sales"].transform("mean")
    g = g[(g.avg_monthly_sales != 0)
          & ((g.sum_sales - g.avg_monthly_sales).abs() / g.avg_monthly_sales > 0.1)]
    cols = ["i_category", "i_class", "i_brand", "s_store_name",
            "s_company_name", "d_moy", "sum_sales", "avg_monthly_sales"]
    exp = (g[cols].sort_values(["sum_sales", "s_store_name"],
                               kind="stable").head(100))
    assert len(exp) > 0
    # ties beyond (sum_sales, s_store_name) are underdetermined by the query:
    # compare both sides under a full-column re-sort
    got = pd.DataFrame(ALL_QUERIES[89](tables).to_pydict())
    _check(got.sort_values(cols, kind="stable").to_dict("list"),
           exp.sort_values(cols, kind="stable"))
