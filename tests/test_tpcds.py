"""TPC-DS store-sales channel: every query cross-checked cell-by-cell against
an independent pandas computation over the same synthetic tables.

Reference parity: benchmarking/tpcds/ (the reference validates against DuckDB
answers; here pandas is the independent oracle).
"""

import os
import sys

import numpy as np
import pandas as pd
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarking.tpcds.datagen import cached_tables, load_dataframes
from benchmarking.tpcds.queries import ALL_QUERIES

SF = 0.05


@pytest.fixture(scope="module")
def tables():
    return {k: v.collect() for k, v in load_dataframes(sf=SF, seed=0).items()}


@pytest.fixture(scope="module")
def pdt():
    return {k: t.to_pandas() for k, t in cached_tables(sf=SF, seed=0).items()}


def _check(out_dict, expected_df):
    got = pd.DataFrame(out_dict).reset_index(drop=True)
    exp = expected_df.reset_index(drop=True)
    assert list(got.columns) == list(exp.columns)
    assert len(got) == len(exp)
    for c in exp.columns:
        if exp[c].dtype.kind == "f":
            assert np.allclose(got[c].astype(float), exp[c].astype(float),
                               rtol=1e-9, atol=1e-6, equal_nan=True), c
        else:
            assert got[c].tolist() == exp[c].tolist(), c


def test_q3(tables, pdt):
    m = pdt["store_sales"].merge(
        pdt["date_dim"][pdt["date_dim"].d_moy == 11],
        left_on="ss_sold_date_sk", right_on="d_date_sk").merge(
        pdt["item"][pdt["item"].i_manufact_id == 128],
        left_on="ss_item_sk", right_on="i_item_sk")
    exp = (m.groupby(["d_year", "i_brand", "i_brand_id"], as_index=False)
           .agg(sum_agg=("ss_ext_sales_price", "sum"))
           .sort_values(["d_year", "sum_agg", "i_brand_id"],
                        ascending=[True, False, True], kind="stable")
           .head(100)
           .rename(columns={"i_brand_id": "brand_id", "i_brand": "brand"})
           [["d_year", "brand_id", "brand", "sum_agg"]])
    assert len(exp) > 0, "q3 selects nothing at this SF; raise SF"
    _check(ALL_QUERIES[3](tables).to_pydict(), exp)


def test_q7(tables, pdt):
    cd = pdt["customer_demographics"]
    cd = cd[(cd.cd_gender == "M") & (cd.cd_marital_status == "S")
            & (cd.cd_education_status == "College")]
    promo = pdt["promotion"]
    promo = promo[(promo.p_channel_email == "N") | (promo.p_channel_event == "N")]
    m = (pdt["store_sales"]
         .merge(cd, left_on="ss_cdemo_sk", right_on="cd_demo_sk")
         .merge(pdt["date_dim"][pdt["date_dim"].d_year == 2000],
                left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(pdt["item"], left_on="ss_item_sk", right_on="i_item_sk")
         .merge(promo, left_on="ss_promo_sk", right_on="p_promo_sk"))
    exp = (m.groupby("i_item_id", as_index=False)
           .agg(agg1=("ss_quantity", "mean"), agg2=("ss_list_price", "mean"),
                agg3=("ss_coupon_amt", "mean"), agg4=("ss_sales_price", "mean"))
           .sort_values("i_item_id", kind="stable").head(100))
    assert len(exp) > 0
    _check(ALL_QUERIES[7](tables).to_pydict(), exp)


def test_q19(tables, pdt):
    dd = pdt["date_dim"]
    m = (pdt["store_sales"]
         .merge(dd[(dd.d_moy == 11) & (dd.d_year == 1998)],
                left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(pdt["item"][pdt["item"].i_manager_id == 8],
                left_on="ss_item_sk", right_on="i_item_sk")
         .merge(pdt["customer"], left_on="ss_customer_sk", right_on="c_customer_sk")
         .merge(pdt["customer_address"], left_on="c_current_addr_sk",
                right_on="ca_address_sk")
         .merge(pdt["store"], left_on="ss_store_sk", right_on="s_store_sk"))
    m = m[m.ca_zip.str[:5] != m.s_zip.str[:5]]
    exp = (m.groupby(["i_brand", "i_brand_id", "i_manufact_id"], as_index=False)
           .agg(ext_price=("ss_ext_sales_price", "sum"))
           .sort_values(["ext_price", "i_brand", "i_brand_id", "i_manufact_id"],
                        ascending=[False, True, True, True], kind="stable")
           .head(100)
           .rename(columns={"i_brand_id": "brand_id", "i_brand": "brand"})
           [["brand_id", "brand", "i_manufact_id", "ext_price"]])
    assert len(exp) > 0
    _check(ALL_QUERIES[19](tables).to_pydict(), exp)


def test_q42(tables, pdt):
    dd = pdt["date_dim"]
    m = (pdt["store_sales"]
         .merge(dd[(dd.d_moy == 11) & (dd.d_year == 2000)],
                left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(pdt["item"][pdt["item"].i_manager_id == 1],
                left_on="ss_item_sk", right_on="i_item_sk"))
    exp = (m.groupby(["d_year", "i_category_id", "i_category"], as_index=False)
           .agg(total=("ss_ext_sales_price", "sum"))
           .sort_values(["total", "d_year", "i_category_id", "i_category"],
                        ascending=[False, True, True, True], kind="stable")
           .head(100))
    assert len(exp) > 0
    _check(ALL_QUERIES[42](tables).to_pydict(), exp)


def test_q52_q55(tables, pdt):
    dd = pdt["date_dim"]
    m52 = (pdt["store_sales"]
           .merge(dd[(dd.d_moy == 11) & (dd.d_year == 2000)],
                  left_on="ss_sold_date_sk", right_on="d_date_sk")
           .merge(pdt["item"][pdt["item"].i_manager_id == 1],
                  left_on="ss_item_sk", right_on="i_item_sk"))
    exp52 = (m52.groupby(["d_year", "i_brand", "i_brand_id"], as_index=False)
             .agg(ext_price=("ss_ext_sales_price", "sum"))
             .sort_values(["d_year", "ext_price", "i_brand_id"],
                          ascending=[True, False, True], kind="stable")
             .head(100)
             .rename(columns={"i_brand_id": "brand_id", "i_brand": "brand"})
             [["d_year", "brand_id", "brand", "ext_price"]])
    assert len(exp52) > 0
    _check(ALL_QUERIES[52](tables).to_pydict(), exp52)

    m55 = (pdt["store_sales"]
           .merge(dd[(dd.d_moy == 11) & (dd.d_year == 1999)],
                  left_on="ss_sold_date_sk", right_on="d_date_sk")
           .merge(pdt["item"][pdt["item"].i_manager_id == 28],
                  left_on="ss_item_sk", right_on="i_item_sk"))
    exp55 = (m55.groupby(["i_brand", "i_brand_id"], as_index=False)
             .agg(ext_price=("ss_ext_sales_price", "sum"))
             .sort_values(["ext_price", "i_brand_id"],
                          ascending=[False, True], kind="stable")
             .head(100)
             .rename(columns={"i_brand_id": "brand_id", "i_brand": "brand"})
             [["brand_id", "brand", "ext_price"]])
    assert len(exp55) > 0
    _check(ALL_QUERIES[55](tables).to_pydict(), exp55)


def test_q96(tables, pdt):
    td = pdt["time_dim"]
    hd = pdt["household_demographics"]
    st = pdt["store"]
    m = (pdt["store_sales"]
         .merge(td[(td.t_hour == 20) & (td.t_minute >= 30)],
                left_on="ss_sold_time_sk", right_on="t_time_sk")
         .merge(hd[hd.hd_dep_count == 7], left_on="ss_hdemo_sk",
                right_on="hd_demo_sk")
         .merge(st[st.s_store_name == "ese"], left_on="ss_store_sk",
                right_on="s_store_sk"))
    got = ALL_QUERIES[96](tables).to_pydict()
    assert got["count"][0] == len(m)
    assert len(m) > 0


def _three_channel_expected(pdt, key, item_mask, d_year, d_moy):
    dd = pdt["date_dim"]
    dd = dd[(dd.d_year == d_year) & (dd.d_moy == d_moy)]
    ca = pdt["customer_address"]
    ca = ca[ca.ca_gmt_offset == -5.0]
    wanted = set(pdt["item"][item_mask][key])
    frames = []
    for fact, prefix, addr in (("store_sales", "ss", "ss_addr_sk"),
                               ("catalog_sales", "cs", "cs_bill_addr_sk"),
                               ("web_sales", "ws", "ws_bill_addr_sk")):
        m = (pdt[fact]
             .merge(dd, left_on=f"{prefix}_sold_date_sk", right_on="d_date_sk")
             .merge(ca, left_on=addr, right_on="ca_address_sk")
             .merge(pdt["item"], left_on=f"{prefix}_item_sk", right_on="i_item_sk"))
        m = m[m[key].isin(wanted)]
        frames.append(m.groupby(key, as_index=False)
                      .agg(total_sales=(f"{prefix}_ext_sales_price", "sum")))
    allf = pd.concat(frames)
    return (allf.groupby(key, as_index=False)
            .agg(total_sales=("total_sales", "sum"))
            .sort_values(["total_sales", key], kind="stable")
            .head(100)[[key, "total_sales"]])


def test_q33(tables, pdt):
    exp = _three_channel_expected(pdt, "i_manufact_id",
                                  pdt["item"].i_category == "Electronics", 1998, 5)
    assert len(exp) > 0
    _check(ALL_QUERIES[33](tables).to_pydict(), exp)


def test_q56(tables, pdt):
    exp = _three_channel_expected(
        pdt, "i_item_id",
        pdt["item"].i_color.isin(["slate", "blanched", "burnished"]), 2001, 2)
    assert len(exp) > 0
    _check(ALL_QUERIES[56](tables).to_pydict(), exp)
