"""Tests proving the engine actually selects the device (JAX) execution path.

VERDICT r1 item #1: the planner must emit Device*Agg nodes and the executor must
run them on device; ops/counters.py records real device batches so these tests
fail if the path silently falls back to host.
"""

import numpy as np
import pytest

import daft_tpu
from daft_tpu import col
from daft_tpu.config import execution_config_ctx
from daft_tpu.ops import counters
from daft_tpu.plan import physical as pp


def _plan(df):
    from daft_tpu.plan.physical import translate

    return translate(df._builder.optimize()._plan)


def _q6_df():
    rng = np.random.default_rng(0)
    n = 10_000
    return daft_tpu.from_pydict({
        "l_quantity": rng.uniform(1, 50, n).tolist(),
        "l_extendedprice": rng.uniform(100, 10000, n).tolist(),
        "l_discount": rng.uniform(0.0, 0.1, n).tolist(),
    })


def _q6_query(df):
    return (
        df.where((col("l_discount") >= 0.05) & (col("l_discount") <= 0.07)
                 & (col("l_quantity") < 24.0))
        .agg((col("l_extendedprice") * col("l_discount")).sum().alias("revenue"))
    )


def test_planner_emits_device_filter_agg():
    with execution_config_ctx(device_mode="on"):
        plan = _plan(_q6_query(_q6_df()))
    assert any(isinstance(n, pp.DeviceFilterAgg) for n in plan.walk()), plan.display()


def test_planner_emits_device_grouped_agg():
    df = daft_tpu.from_pydict({"k": ["a", "b", "a"], "v": [1.0, 2.0, 3.0]})
    q = df.groupby("k").agg(col("v").sum())
    with execution_config_ctx(device_mode="on"):
        plan = _plan(q)
    assert any(isinstance(n, pp.DeviceGroupedAgg) for n in plan.walk()), plan.display()


def test_planner_device_off_no_device_nodes():
    with execution_config_ctx(device_mode="off"):
        plan = _plan(_q6_query(_q6_df()))
    assert not any(isinstance(n, (pp.DeviceFilterAgg, pp.DeviceGroupedAgg))
                   for n in plan.walk())


def test_q6_runs_on_device_and_matches_host():
    df = _q6_df()
    counters.reset()
    with execution_config_ctx(device_mode="on"):
        dev_out = _q6_query(df).to_pydict()
    assert counters.device_stage_batches > 0, "device stage never fed"
    assert counters.device_stage_runs > 0
    with execution_config_ctx(device_mode="off"):
        host_out = _q6_query(df).to_pydict()
    # device compute dtype is f32 (f64 is TPU-emulated; see ops/stage.py) -> ~1e-7 rel
    np.testing.assert_allclose(dev_out["revenue"], host_out["revenue"], rtol=1e-5)


def test_grouped_agg_device_matches_host_string_keys():
    rng = np.random.default_rng(1)
    n = 5000
    df = daft_tpu.from_pydict({
        "flag": rng.choice(["A", "N", "R"], n).tolist(),
        "status": rng.choice(["O", "F"], n).tolist(),
        "qty": rng.uniform(1, 50, n).tolist(),
        "price": rng.uniform(1, 1000, n).tolist(),
    })

    def q(d):
        return (d.groupby("flag", "status")
                .agg(col("qty").sum().alias("sum_qty"),
                     col("price").mean().alias("avg_price"),
                     col("qty").min().alias("min_qty"),
                     col("qty").max().alias("max_qty"),
                     col("qty").count().alias("n"))
                .sort(["flag", "status"]))

    counters.reset()
    with execution_config_ctx(device_mode="on"):
        dev_out = q(df).to_pydict()
    assert counters.device_grouped_batches > 0, "device grouped stage never fed"
    with execution_config_ctx(device_mode="off"):
        host_out = q(df).to_pydict()
    assert dev_out["flag"] == host_out["flag"]
    assert dev_out["status"] == host_out["status"]
    for c in ("sum_qty", "avg_price", "min_qty", "max_qty"):
        np.testing.assert_allclose(dev_out[c], host_out[c], rtol=1e-5)
    assert dev_out["n"] == host_out["n"]


def test_grouped_agg_device_with_filter_and_nulls():
    df = daft_tpu.from_pydict({
        "k": ["x", "y", "x", "y", "x", None],
        "v": [1.0, 2.0, None, 4.0, 5.0, 6.0],
        "w": [10.0, 20.0, 30.0, 40.0, 50.0, 60.0],
    })
    q = lambda d: (d.where(col("w") > 15.0)
                   .groupby("k")
                   .agg(col("v").sum().alias("s"), col("v").count().alias("c"))
                   .sort("k"))
    counters.reset()
    with execution_config_ctx(device_mode="on"):
        dev_out = q(df).to_pydict()
    assert counters.device_grouped_batches > 0
    with execution_config_ctx(device_mode="off"):
        host_out = q(df).to_pydict()
    assert dev_out == host_out


def test_device_count_modes_match_host():
    df = daft_tpu.from_pydict({"v": [1.0, None, 3.0, None, 5.0]})
    q = lambda d: d.agg(
        col("v").count().alias("c_valid"),
        col("v").sum().alias("s"),
        col("v").mean().alias("m"),
        col("v").min().alias("lo"),
        col("v").max().alias("hi"),
    )
    counters.reset()
    with execution_config_ctx(device_mode="on"):
        dev_out = q(df).to_pydict()
    assert counters.device_stage_runs > 0
    with execution_config_ctx(device_mode="off"):
        host_out = q(df).to_pydict()
    assert dev_out == host_out


def test_device_auto_small_input_stays_on_host():
    df = _q6_df()
    counters.reset()
    with execution_config_ctx(device_mode="auto", device_min_rows=10**9):
        out = _q6_query(df).to_pydict()
    assert counters.device_stage_batches == 0
    assert len(out["revenue"]) == 1


def test_device_int_sums_exact():
    df = daft_tpu.from_pydict({"k": ["a", "a", "b"], "v": [2**60, 7, 11]})
    q = lambda d: d.groupby("k").agg(col("v").sum().alias("s")).sort("k")
    with execution_config_ctx(device_mode="on"):
        dev_out = q(df).to_pydict()
    with execution_config_ctx(device_mode="off"):
        host_out = q(df).to_pydict()
    assert dev_out == host_out


def test_abandoned_run_does_not_corrupt_next_run():
    """ADVICE r2 (high): cached stages must not carry accumulator state across
    runs — an interrupted run (exception between feed and finalize) previously
    leaked partials into the next run of the same query (106.0 instead of 6.0)."""
    df = daft_tpu.from_pydict({"v": [1.0, 2.0, 3.0]})
    q = lambda d: d.agg(col("v").sum().alias("s"))
    with execution_config_ctx(device_mode="on"):
        # simulate a run that fed batches then died before finalize
        from daft_tpu.ops.stage import try_build_filter_agg_stage

        plan = _plan(q(df))
        node = next(n for n in plan.walk() if isinstance(n, pp.DeviceFilterAgg))
        stage = try_build_filter_agg_stage(node.input.schema, node.predicate,
                                           node.aggregations)
        run = stage.start_run()
        for part in node.input.partitions:
            for b in part.batches:
                run.feed_batch(b)
        # (no finalize — abandoned)
        out = q(df).to_pydict()
    assert out["s"] == [6.0]


def test_abandoned_grouped_run_does_not_corrupt_next_run():
    df = daft_tpu.from_pydict({"k": ["a", "b", "a"], "v": [1.0, 2.0, 3.0]})
    q = lambda d: d.groupby("k").agg(col("v").sum().alias("s")).sort("k")
    with execution_config_ctx(device_mode="on"):
        from daft_tpu.ops.grouped_stage import try_build_grouped_agg_stage

        plan = _plan(q(df))
        node = next(n for n in plan.walk() if isinstance(n, pp.DeviceGroupedAgg))
        stage = try_build_grouped_agg_stage(node.input.schema, node.predicate,
                                            node.groupby, node.aggregations)
        run = stage.start_run()
        for part in node.input.partitions:
            for b in part.batches:
                run.feed_batch(b)
        out = q(df).to_pydict()
    assert out["k"] == ["a", "b"]
    assert out["s"] == [4.0, 2.0]


def test_grouped_device_int_min_max_exact():
    """ADVICE r2: int min/max must accumulate in int64, not float64 (2^53 cliff)."""
    big = 2**53 + 1
    df = daft_tpu.from_pydict({"k": ["a", "a", "b"], "v": [big, big + 2, 5]})
    q = lambda d: (d.groupby("k")
                   .agg(col("v").min().alias("lo"), col("v").max().alias("hi"))
                   .sort("k"))
    with execution_config_ctx(device_mode="on"):
        dev_out = q(df).to_pydict()
    with execution_config_ctx(device_mode="off"):
        host_out = q(df).to_pydict()
    assert dev_out == host_out
    assert dev_out["hi"][0] == big + 2


def test_tpch_q1_shape_device_matches_host():
    rng = np.random.default_rng(2)
    n = 20_000
    df = daft_tpu.from_pydict({
        "l_returnflag": rng.choice(["A", "N", "R"], n).tolist(),
        "l_linestatus": rng.choice(["O", "F"], n).tolist(),
        "l_quantity": rng.uniform(1, 50, n).tolist(),
        "l_extendedprice": rng.uniform(900, 105000, n).tolist(),
        "l_discount": rng.uniform(0, 0.1, n).tolist(),
        "l_tax": rng.uniform(0, 0.08, n).tolist(),
        "l_shipdate_days": rng.integers(8000, 10000, n).tolist(),
    })

    def q1(d):
        disc_price = col("l_extendedprice") * (1 - col("l_discount"))
        charge = disc_price * (1 + col("l_tax"))
        return (
            d.where(col("l_shipdate_days") <= 9190)
            .groupby("l_returnflag", "l_linestatus")
            .agg(
                col("l_quantity").sum().alias("sum_qty"),
                col("l_extendedprice").sum().alias("sum_base_price"),
                disc_price.sum().alias("sum_disc_price"),
                charge.sum().alias("sum_charge"),
                col("l_quantity").mean().alias("avg_qty"),
                col("l_extendedprice").mean().alias("avg_price"),
                col("l_discount").mean().alias("avg_disc"),
                col("l_quantity").count().alias("count_order"),
            )
            .sort(["l_returnflag", "l_linestatus"])
        )

    counters.reset()
    with execution_config_ctx(device_mode="on"):
        dev_out = q1(df).to_pydict()
    assert counters.device_grouped_batches > 0
    with execution_config_ctx(device_mode="off"):
        host_out = q1(df).to_pydict()
    for k in host_out:
        if isinstance(host_out[k][0], float):
            np.testing.assert_allclose(dev_out[k], host_out[k], rtol=1e-5)
        else:
            assert dev_out[k] == host_out[k], k


def test_high_cardinality_groupby_falls_back_to_host():
    """The one-hot matmul kernel must never see unbounded segment counts: keys
    beyond MAX_MATMUL_SEGMENTS raise DeviceFallback pre-dispatch and the
    executor reruns the stage on host with identical results."""
    n = 20_000  # > MAX_MATMUL_SEGMENTS distinct keys
    df = daft_tpu.from_pydict({
        "k": list(range(n)),
        "v": [float(i % 97) for i in range(n)],
    })
    q = lambda d: d.groupby("k").agg(col("v").sum().alias("s"))
    counters.reset()
    with execution_config_ctx(device_mode="on"):
        dev_out = q(df).to_pydict()
    with execution_config_ctx(device_mode="off"):
        host_out = q(df).to_pydict()
    assert dev_out == host_out


def test_high_cardinality_grouped_agg_sort_path():
    """cap > MAX_MATMUL_SEGMENTS groupbys run on device via the sort-based
    segmented-reduction path (r3 VERDICT item #3: the 4096-segment ceiling),
    matching the host result exactly."""
    rng = np.random.default_rng(7)
    n = 200_000
    n_groups = 20_000  # > MAX_MATMUL_SEGMENTS
    df = daft_tpu.from_pydict({
        "k": rng.integers(0, n_groups, n).tolist(),
        "v": rng.uniform(0, 100, n).tolist(),
        "q": rng.integers(0, 1000, n).tolist(),
    })

    def q(d):
        return (d.groupby("k")
                .agg(col("v").sum().alias("sv"),
                     col("q").sum().alias("sq"),
                     col("q").max().alias("mq"),
                     col("v").count().alias("cv"))
                .sort("k"))

    with execution_config_ctx(device_mode="off"):
        host = q(df).to_pydict()
    counters.reset()
    with execution_config_ctx(device_mode="on"):
        dev_out = q(df).to_pydict()
    assert counters.device_grouped_batches > 0, "sort path never dispatched"
    assert dev_out["k"] == host["k"]
    assert dev_out["sq"] == host["sq"]
    assert dev_out["mq"] == host["mq"]
    assert dev_out["cv"] == host["cv"]
    np.testing.assert_allclose(dev_out["sv"], host["sv"], rtol=1e-6)


def test_sort_path_with_predicate_and_nulls():
    rng = np.random.default_rng(3)
    n = 60_000
    vals = rng.uniform(0, 10, n)
    v = [None if i % 17 == 0 else float(vals[i]) for i in range(n)]
    df = daft_tpu.from_pydict({
        "k": rng.integers(0, 9000, n).tolist(),
        "v": v,
        "w": rng.uniform(0, 1, n).tolist(),
    })

    def q(d):
        return (d.where(col("w") < 0.8)
                .groupby("k")
                .agg(col("v").sum().alias("s"), col("v").count().alias("c"),
                     col("v").min().alias("mn"))
                .sort("k"))

    with execution_config_ctx(device_mode="off"):
        host = q(df).to_pydict()
    with execution_config_ctx(device_mode="on"):
        dev_out = q(df).to_pydict()
    assert dev_out["k"] == host["k"]
    assert dev_out["c"] == host["c"]
    np.testing.assert_allclose(np.array(dev_out["s"], dtype=float),
                               np.array(host["s"], dtype=float),
                               rtol=1e-6, atol=1e-9)
    np.testing.assert_allclose(np.array(dev_out["mn"], dtype=float),
                               np.array(host["mn"], dtype=float), rtol=1e-12)


def test_config_rejects_unknown_modes():
    """DAFT_TPU_DEVICE=force used to silently disable the device while looking
    like an opt-in; unknown mode strings must raise (ADVICE r4 / VERDICT r4)."""
    import pytest

    from daft_tpu.config import ExecutionConfig, execution_config_ctx

    with pytest.raises(ValueError, match="device_mode"):
        ExecutionConfig(device_mode="force")
    with pytest.raises(ValueError, match="pipeline_mode"):
        ExecutionConfig(pipeline_mode="auto")
    with pytest.raises(ValueError, match="device_mode"):
        with execution_config_ctx(device_mode="always"):
            pass
    # valid values construct fine
    ExecutionConfig(device_mode="on", pipeline_mode="force")
