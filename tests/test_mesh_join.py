"""Mesh-sharded device joins (ops/mesh_stage.py MeshJoin*Run) under 8 forced
host devices — the r15 tentpole: star joins as a first-class mesh tier.

Covers: 3-way bit-identity (mesh vs single-chip vs host) for grouped /
ungrouped / TopN join shapes including int64 exactness and null group keys,
dim-filter visibility folding, repeat-query h2d-flat dim planes (including
the filtered/unfiltered slot-thrash regression), tiny-HBM-budget pin safety,
the loud forced-mesh-unavailable fallback, the three-tier cost decision with
all three CostBreakdowns in the placement ledger, the intra-host all_to_all
repartition (bit-identical partitions, zero shuffle wire bytes), the mesh
join cost function, the calibrate tool's mesh-term suggestions, and the
persistent-compile-cache knob. Run standalone via `make test-mesh`.
"""

import os

import numpy as np
import pytest

import jax

import daft_tpu
from daft_tpu import col
from daft_tpu.config import execution_config_ctx
from daft_tpu.observability.metrics import registry
from daft_tpu.ops import counters


pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices — see conftest")


N_FACT = 24_000
N_DIM = 60


@pytest.fixture(scope="module")
def star():
    """A star pair: fact with int64-overflow-scale values + a dim with a
    null-bearing group key and a filterable numeric column."""
    rng = np.random.default_rng(11)
    fact = daft_tpu.from_pydict({
        "fk": rng.integers(0, N_DIM + 5, N_FACT).tolist(),  # some join misses
        "qty": rng.integers(0, 50, N_FACT).tolist(),
        "price": [None if i % 19 == 0 else float(i % 977)
                  for i in range(N_FACT)],
        "big": (2**53 + rng.integers(0, 1000, N_FACT)).tolist(),
    })
    dim = daft_tpu.from_pydict({
        "dk": list(range(N_DIM)),
        "grp": [None if i % 13 == 0 else f"g{i % 7}" for i in range(N_DIM)],
        "weight": [float(i % 11) for i in range(N_DIM)],
        "flag": [i % 4 for i in range(N_DIM)],
    })
    return fact, dim


def _grouped_q(fact, dim):
    return (fact.join(dim, left_on="fk", right_on="dk")
            .where(col("flag") < 3)
            .groupby("grp")
            .agg(col("qty").sum().alias("s"),
                 col("big").sum().alias("bs"),
                 col("weight").mean().alias("mw"),
                 col("price").count().alias("c"),
                 col("qty").min().alias("lo"),
                 col("qty").max().alias("hi"))
            .sort("grp"))


def test_grouped_mesh_join_three_way_parity(star):
    """Grouped star join: mesh vs single-chip vs host identical, null group
    keys preserved, int64 sums exact, and the mesh counters prove the tier
    actually ran."""
    fact, dim = star
    with execution_config_ctx(device_mode="off"):
        host = _grouped_q(fact, dim).to_pydict()
    counters.reset()
    with execution_config_ctx(device_mode="on", mesh_devices=8,
                              device_min_rows=1):
        mesh = _grouped_q(fact, dim).to_pydict()
    assert counters.mesh_join_runs > 0, "mesh join tier never ran"
    assert counters.mesh_dispatches > 0
    counters.reset()
    with execution_config_ctx(device_mode="on", mesh_devices=1,
                              device_min_rows=1):
        single = _grouped_q(fact, dim).to_pydict()
    assert counters.mesh_join_runs == 0, "mesh_devices=1 must stay single-chip"
    assert counters.device_join_batches > 0
    for out in (mesh, single):
        assert out["grp"] == host["grp"]      # incl. the None group
        assert out["c"] == host["c"]
        assert out["lo"] == host["lo"] and out["hi"] == host["hi"]
        np.testing.assert_allclose(np.array(out["mw"], dtype=float),
                                   np.array(host["mw"], dtype=float),
                                   rtol=1e-12)
    assert None in host["grp"], "fixture lost its null group key"
    # int64 exactness: native-dtype mesh reduce must match host bit-for-bit
    assert mesh["bs"] == host["bs"], "mesh int64 join sum not exact"
    assert mesh["s"] == host["s"]


def test_ungrouped_mesh_join_parity(star):
    fact, dim = star

    def q():
        return (fact.join(dim, left_on="fk", right_on="dk")
                .where(col("flag") < 2)
                .agg(col("qty").sum().alias("s"),
                     col("big").sum().alias("bs"),
                     col("price").count().alias("c"),
                     col("weight").mean().alias("m"),
                     col("qty").min().alias("lo"),
                     col("qty").max().alias("hi")))

    with execution_config_ctx(device_mode="off"):
        host = q().to_pydict()
    counters.reset()
    with execution_config_ctx(device_mode="on", mesh_devices=8,
                              device_min_rows=1):
        mesh = q().to_pydict()
    assert counters.mesh_join_runs > 0
    assert mesh["s"] == host["s"] and mesh["bs"] == host["bs"]
    assert mesh["c"] == host["c"]
    assert mesh["lo"] == host["lo"] and mesh["hi"] == host["hi"]
    np.testing.assert_allclose(mesh["m"], host["m"], rtol=1e-12)


def test_topn_mesh_join_parity(star):
    """Fused TopN join on the mesh: only K winners fetch; order, keys and
    aggregates match the host engine exactly (integer sums -> exact in any
    reduction order)."""
    fact, dim = star

    def q():
        return (fact.join(dim, left_on="fk", right_on="dk")
                .groupby("grp")
                .agg(col("qty").sum().alias("s"))
                .sort("s", desc=True).limit(3))

    with execution_config_ctx(device_mode="off"):
        host = q().to_pydict()
    counters.reset()
    with execution_config_ctx(device_mode="on", mesh_devices=8,
                              device_min_rows=1):
        mesh = q().to_pydict()
    assert counters.mesh_join_runs > 0
    assert counters.device_topn_runs > 0
    assert mesh == host


def test_repeat_join_queries_h2d_flat(star):
    """Interleaved repeats of a filtered grouped join and an unfiltered TopN
    join hit resident sharded/replicated planes with ZERO new h2d bytes —
    the filtered and unfiltered index planes must hold separate slots (a
    shared slot thrashes on alternation: the regression this pins)."""
    fact, dim = star

    def q_topn():
        return (fact.join(dim, left_on="fk", right_on="dk")
                .groupby("grp").agg(col("qty").sum().alias("s"))
                .sort("s", desc=True).limit(3))

    with execution_config_ctx(device_mode="on", mesh_devices=8,
                              device_min_rows=1):
        g1 = _grouped_q(fact, dim).to_pydict()
        t1 = q_topn().to_pydict()
        h1 = registry().get("hbm_h2d_bytes")
        g2 = _grouped_q(fact, dim).to_pydict()
        t2 = q_topn().to_pydict()
        h2 = registry().get("hbm_h2d_bytes")
    assert (g2, t2) == (g1, t1)
    assert h2 == h1, f"repeat mesh join re-uploaded {h2 - h1} bytes"


def test_mesh_join_pins_under_tiny_hbm_budget(star):
    """Planes built inside a mesh join pin via the executor's pin_scope: a
    budget far below the working set must not thrash them mid-run."""
    fact, dim = star
    with execution_config_ctx(device_mode="off"):
        host = _grouped_q(fact, dim).to_pydict()
    counters.reset()
    with execution_config_ctx(device_mode="on", mesh_devices=8,
                              device_min_rows=1, hbm_budget_bytes=2048):
        mesh = _grouped_q(fact, dim).to_pydict()
    assert counters.mesh_join_runs > 0
    assert counters.hbm_pins > 0, "mesh join planes never pinned"
    assert mesh["grp"] == host["grp"] and mesh["s"] == host["s"]


def test_forced_mesh_unavailable_falls_back_loudly(star):
    """mesh_devices beyond the local device count: the join runs single-chip
    with the fallback counter bumped — never silently, never wrong."""
    fact, dim = star
    with execution_config_ctx(device_mode="off"):
        host = _grouped_q(fact, dim).to_pydict()
    counters.reset()
    with execution_config_ctx(device_mode="on", mesh_devices=64,
                              device_min_rows=1):
        out = _grouped_q(fact, dim).to_pydict()
    assert counters.mesh_unavailable_fallbacks > 0
    assert counters.mesh_join_runs == 0
    assert counters.device_join_batches > 0, "fallback must still run device"
    assert out["grp"] == host["grp"] and out["s"] == host["s"]


# ---- three-tier cost decision --------------------------------------------------------

_MESH_WINS_PINS = {
    "DAFT_TPU_COST_RTT": "0.0001", "DAFT_TPU_COST_H2D": "1e11",
    "DAFT_TPU_COST_D2H": "1e9", "DAFT_TPU_COST_MM_RATE": "1e8",
    "DAFT_TPU_COST_MM_CELL_RATE": "1e7", "DAFT_TPU_COST_HOST_AGG": "1e6",
    "DAFT_TPU_COST_HOST_FACT": "1e9", "DAFT_TPU_COST_HOST_PROBE": "1e6",
    "DAFT_TPU_COST_ICI": "1e12", "DAFT_TPU_COST_MESH_DISPATCH": "1e-5",
}


def test_auto_join_decision_prices_all_three_tiers(star, monkeypatch):
    """device_mode=auto on a (simulated) accelerator: the join decision's
    ledger record carries device AND host AND mesh CostBreakdowns, and under
    mesh-favoring calibration the mesh tier actually executes the join."""
    from daft_tpu.execution import executor
    from daft_tpu.observability import placement
    from daft_tpu.ops import costmodel

    fact, dim = star
    for k, v in _MESH_WINS_PINS.items():
        monkeypatch.setenv(k, v)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    costmodel.reset_calibration()
    executor._DECISION_CACHE.clear()
    try:
        counters.reset()
        with execution_config_ctx(device_mode="auto", mesh_devices=0,
                                  device_min_rows=1):
            with placement.query_scope() as scope:
                mesh = _grouped_q(fact, dim).to_pydict()
        recs = [r for r in scope.to_dicts() if r.get("site") == "join agg"]
        assert recs, "no join placement record"
        rec = recs[0]
        assert rec["chosen"] == "mesh"
        for tier in ("device", "host", "mesh"):
            assert rec.get(tier, {}).get("total", 0) > 0, \
                f"{tier} CostBreakdown absent from the join decision"
        assert "ici" in rec["mesh"] and "mesh_dispatch" in rec["mesh"]
        assert counters.mesh_join_runs > 0, "costed mesh verdict did not run"
        with execution_config_ctx(device_mode="off"):
            host = _grouped_q(fact, dim).to_pydict()
        assert mesh["grp"] == host["grp"] and mesh["s"] == host["s"]
    finally:
        costmodel.reset_calibration()
        executor._DECISION_CACHE.clear()


def test_auto_join_host_reject_still_prices_mesh_arm(star, monkeypatch):
    """When every device tier loses, the host verdict's record still shows
    what the mesh WOULD have cost — the what-if explain_placement needs."""
    from daft_tpu.execution import executor
    from daft_tpu.observability import placement
    from daft_tpu.ops import costmodel

    fact, dim = star
    hostile = dict(_MESH_WINS_PINS,
                   **{"DAFT_TPU_COST_RTT": "5.0",
                      "DAFT_TPU_COST_MESH_DISPATCH": "5.0",
                      "DAFT_TPU_COST_ICI": "1e3",
                      "DAFT_TPU_COST_HOST_AGG": "1e12",
                      "DAFT_TPU_COST_HOST_FACT": "1e12",
                      "DAFT_TPU_COST_HOST_PROBE": "1e12"})
    for k, v in hostile.items():
        monkeypatch.setenv(k, v)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    costmodel.reset_calibration()
    executor._DECISION_CACHE.clear()
    try:
        counters.reset()
        with execution_config_ctx(device_mode="auto", mesh_devices=0,
                                  device_min_rows=1):
            with placement.query_scope() as scope:
                _grouped_q(fact, dim).to_pydict()
        recs = [r for r in scope.to_dicts() if r.get("site") == "join agg"]
        assert recs and recs[0]["chosen"] == "host"
        assert recs[0].get("mesh", {}).get("total", 0) > 0, \
            "host reject lost the mesh what-if breakdown"
        assert counters.mesh_join_runs == 0
    finally:
        costmodel.reset_calibration()
        executor._DECISION_CACHE.clear()


def test_mesh_join_cost_function_scales():
    """Unit sanity: the mesh join amortizes gather+reduce compute by the mesh
    width but pays the dispatch premium and the ICI table merge."""
    from daft_tpu.ops import costmodel

    cal = costmodel.Calibration(
        rtt_s=0.001, h2d_bytes_per_s=1e9, d2h_bytes_per_s=1e9,
        mm_plane_rows_per_s=1e9, mm_cell_rate=5e10, scatter_rows_per_s=1e8,
        ext_cell_rate=5e9, host_agg_rate=1.5e8, host_factorize_rate=8e6,
        host_probe_rate=3e7, ici_bytes_per_s=4.5e10, mesh_dispatch_s=2e-3)
    small = costmodel.mesh_join_agg_cost(cal, 10_000, 0, 2, 2, 64, 8,
                                         1024, 0)
    single_small = costmodel.device_join_agg_cost(cal, 10_000, 0, 2, 1, 0, 0,
                                                  64, 1024, 0)
    assert small > single_small, "tiny joins must not prefer the mesh"
    big = costmodel.mesh_join_agg_cost(cal, 800_000_000, 0, 4, 3, 4096, 8,
                                       1 << 16, 0)
    big_single = costmodel.device_join_agg_cost(cal, 800_000_000, 0, 4, 2, 1,
                                                0, 4096, 1 << 16, 0)
    assert big < big_single, "huge joins must amortize across the mesh"
    assert {"mesh_dispatch", "ici", "compute"} <= set(big.terms)


# ---- intra-host all_to_all repartition -----------------------------------------------

def test_alltoall_repartition_bit_identical_zero_wire_bytes():
    """Hash repartition over ICI: partition contents AND row order match the
    host path exactly (nulls included), with zero shuffle wire bytes while
    the exchange moved real plane bytes — the co-located-worker wire drop."""
    from daft_tpu.core.recordbatch import RecordBatch

    n = 80_000
    rng = np.random.default_rng(5)
    df = daft_tpu.from_pydict({
        "k": rng.integers(0, 997, n).tolist(),
        "v": (rng.random(n) * 100).tolist(),
        "w": [None if i % 17 == 0 else int(i % 31) for i in range(n)],
    })
    with execution_config_ctx(device_mode="off"):
        host = df.repartition(8, col("k")).collect()
    counters.reset()
    wire0 = registry().get("shuffle_wire_bytes")
    with execution_config_ctx(device_mode="on", mesh_devices=8,
                              device_min_rows=1):
        mesh = df.repartition(8, col("k")).collect()
    assert counters.mesh_alltoall_dispatches > 0, "all_to_all never engaged"
    assert counters.mesh_alltoall_ici_bytes > 0
    assert registry().get("shuffle_wire_bytes") == wire0, \
        "co-located repartition wrote shuffle wire bytes"

    def rows(p):
        bs = [b for b in p.batches if b.num_rows]
        if not bs:
            return {}
        b = bs[0] if len(bs) == 1 else RecordBatch.concat(bs)
        return {c: b.get_column(c).to_pylist() for c in ("k", "v", "w")}

    hp, mp = list(host._result), list(mesh._result)
    assert len(hp) == len(mp) == 8
    for i, (a, b) in enumerate(zip(hp, mp)):
        assert rows(a) == rows(b), f"partition {i} diverged"


def test_alltoall_repartition_stays_off_by_default():
    """Without the explicit mesh opt-in (mesh_devices defaults to auto) the
    repartition path must stay on host bucketing — and string columns must
    reject to host even when the mesh is forced."""
    df = daft_tpu.from_pydict({"k": list(range(1000)),
                               "s": [f"x{i}" for i in range(1000)]})
    counters.reset()
    with execution_config_ctx(device_mode="on", device_min_rows=1):
        df.repartition(8, col("k")).collect()
    assert counters.mesh_alltoall_dispatches == 0
    with execution_config_ctx(device_mode="on", mesh_devices=8,
                              device_min_rows=1):
        out = df.repartition(8, col("k")).collect()
    assert counters.mesh_alltoall_dispatches == 0, \
        "string columns must not ride the device exchange"
    assert sum(p.num_rows for p in out._result) == 1000


# ---- satellites ----------------------------------------------------------------------

def test_calibrate_tool_suggests_mesh_terms():
    """Ledger samples from mesh-tier dispatches drive DAFT_TPU_COST_ICI /
    DAFT_TPU_COST_MESH_DISPATCH suggestions when observation and calibration
    disagree by more than the 2x contract."""
    from daft_tpu.tools.calibrate import suggest

    cal = {"rtt_s": 0.001, "h2d_bytes_per_s": 1e9, "d2h_bytes_per_s": 1e9,
           "ici_bytes_per_s": 4.5e10, "mesh_dispatch_s": 2e-3,
           "mm_plane_rows_per_s": 5e9, "mm_cell_rate": 5e10}
    records = [{
        "site": "join agg", "chosen": "mesh", "rows": 1_000_000,
        "mesh": {"total": 0.05, "compute": 0.001, "ici": 0.004,
                 "mesh_dispatch": 0.002},
        "observed": {"total": 0.2, "dispatch": 0.2, "dispatches": 1},
        "error_ratio": 4.0,
    } for _ in range(3)]
    report = suggest(records, cal)
    assert "DAFT_TPU_COST_MESH_DISPATCH" in report["suggestions"], report
    # observed premium floor = 0.2 - rtt(0.001) = 0.199s >> 2ms calibration
    assert float(report["suggestions"]["DAFT_TPU_COST_MESH_DISPATCH"]) \
        == pytest.approx(0.199, rel=1e-3)
    assert "ici" in report["terms"]
    assert "DAFT_TPU_COST_ICI" in report["suggestions"]


def test_compile_cache_knob_resolution(monkeypatch):
    """DAFT_TPU_COMPILE_CACHE_DIR is the canonical persistent-compile-cache
    knob; the legacy spelling still works; falsy spellings disable."""
    from daft_tpu.utils.jax_setup import compile_cache_dir

    monkeypatch.delenv("DAFT_TPU_COMPILE_CACHE_DIR", raising=False)
    monkeypatch.delenv("DAFT_TPU_COMPILE_CACHE", raising=False)
    assert compile_cache_dir().endswith("daft_tpu_xla")
    monkeypatch.setenv("DAFT_TPU_COMPILE_CACHE_DIR", "/tmp/x1")
    assert compile_cache_dir() == "/tmp/x1"
    monkeypatch.setenv("DAFT_TPU_COMPILE_CACHE", "/tmp/legacy")
    assert compile_cache_dir() == "/tmp/x1", "canonical knob must win"
    monkeypatch.delenv("DAFT_TPU_COMPILE_CACHE_DIR")
    assert compile_cache_dir() == "/tmp/legacy"
    for off in ("0", "off", ""):
        monkeypatch.setenv("DAFT_TPU_COMPILE_CACHE", off)
        assert compile_cache_dir() == ""


def test_mesh_probe_static_on_cpu_backend():
    """The live ICI probe must not run on a forced-multi-device CPU host —
    its 'interconnect' is memcpy and would flip auto verdicts dishonestly;
    the static v5e terms hold instead."""
    from daft_tpu.ops.costmodel import (_STATIC_ICI_BPS,
                                        _STATIC_MESH_DISPATCH_S,
                                        _probe_mesh_terms)

    ici, meshd = _probe_mesh_terms(0.001)
    assert ici == _STATIC_ICI_BPS and meshd == _STATIC_MESH_DISPATCH_S
