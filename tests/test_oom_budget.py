"""Tiny-budget correctness: TPC-H-shaped join/sort/agg queries with
DAFT_TPU_MEMORY_LIMIT at ~10% of the input bytes must stay bit-identical to
the unbudgeted runs while actually spilling — plus the spill-artifact
lifecycle (cancellation GC, dead-pid sweep, tmp + atomic publish)."""

import os
import time

import pytest

import daft_tpu
from daft_tpu import col
from daft_tpu.config import execution_config_ctx
from daft_tpu.execution import memory as mem
from daft_tpu.observability.metrics import registry


@pytest.fixture(autouse=True)
def _clean():
    mem.reset_counters()
    mem.manager().clear()
    yield
    mem.manager().clear()


@pytest.fixture(scope="module")
def tables():
    from benchmarking.tpch.datagen import load_dataframes

    return {k: v.collect() for k, v in load_dataframes(sf=0.05, seed=0).items()}


def _input_bytes(dfs):
    return sum(p.size_bytes() for df in dfs for p in df.iter_partitions())


def _tiny_budget(tables) -> int:
    return max(int(_input_bytes(tables.values()) * 0.1), 1 << 16)


@pytest.mark.parametrize("qnum", [1, 3, 5, 6, 10])
def test_tpch_bit_identical_under_tiny_budget(tables, qnum):
    """Bit-identity at ~10% of input bytes. Pushdowns can legitimately keep
    an individual query's working set under the budget (q6's filter survives
    ~2% of rows), so the spill assertions live in the suite-level test below
    and the shape-controlled join/sort tests."""
    from benchmarking.tpch.queries import ALL_QUERIES

    budget = _tiny_budget(tables)
    with execution_config_ctx(memory_limit_bytes=budget, device_mode="off"):
        capped = ALL_QUERIES[qnum](tables).to_pydict()
    with execution_config_ctx(memory_limit_bytes=0, device_mode="off"):
        unbudgeted = ALL_QUERIES[qnum](tables).to_pydict()
    assert capped == unbudgeted, f"q{qnum} diverged under the budget"


def test_tpch_suite_spills_at_ten_percent(tables):
    """Across the TPC-H subset, a 10% budget must actually engage the
    out-of-core tier: ledger crossings AND disk spill somewhere."""
    from benchmarking.tpch.queries import ALL_QUERIES

    budget = _tiny_budget(tables)
    mem.reset_counters()
    with execution_config_ctx(memory_limit_bytes=budget, device_mode="off"):
        for qnum in (1, 3, 5, 6, 10):
            ALL_QUERIES[qnum](tables).to_pydict()
    assert registry().get("host_over_budget_events") > 0
    assert registry().get("spill_bytes") > 0


def test_join_grace_spills_under_tiny_budget(tables):
    def q():
        return (tables["orders"]
                .join(tables["lineitem"], left_on="o_orderkey",
                      right_on="l_orderkey")
                .groupby("o_orderpriority")
                .agg(col("l_extendedprice").sum().alias("rev"))
                .sort("o_orderpriority"))

    mem.reset_counters()
    # small enough that even the column-pruned build side crosses it
    with execution_config_ctx(memory_limit_bytes=256 * 1024, device_mode="off"):
        capped = q().to_pydict()
    assert registry().get("spill_bytes") > 0, "Grace join never spilled"
    with execution_config_ctx(memory_limit_bytes=0, device_mode="off"):
        unbudgeted = q().to_pydict()
    # Grace partitioning feeds the float sum in spill-partition order, so
    # 'rev' is compared to fp tolerance (the existing out-of-core suite's
    # convention); the group keys must match exactly
    import numpy as np

    assert capped["o_orderpriority"] == unbudgeted["o_orderpriority"]
    np.testing.assert_allclose(capped["rev"], unbudgeted["rev"], rtol=1e-9)


def test_sort_generates_runs_and_merges(tables):
    li = tables["lineitem"].select(
        col("l_orderkey"), col("l_linenumber"), col("l_extendedprice"))
    budget = max(int(_input_bytes(tables.values()) * 0.01), 1 << 16)

    def q():
        return li.sort(["l_extendedprice", "l_orderkey", "l_linenumber"])

    mem.reset_counters()
    with execution_config_ctx(memory_limit_bytes=budget, device_mode="off"):
        capped = q().to_pydict()
    assert registry().get("spill_runs") >= 2, "external sort produced <2 runs"
    assert registry().get("spill_bytes") > 0
    with execution_config_ctx(memory_limit_bytes=0, device_mode="off"):
        unbudgeted = q().to_pydict()
    assert capped == unbudgeted


def test_merge_cascade_over_fanin(tables):
    """Enough runs to exceed the merge fan-in: the cascade (intermediate
    merged runs) must engage and stay exact."""
    # fine-grained batches so run count tracks the budget, not the stored
    # partition chunking (a run flushes at the first over-budget batch)
    li = (tables["lineitem"].select(col("l_orderkey"), col("l_extendedprice"))
          .into_batches(8192).collect())

    def q():
        return li.sort(["l_extendedprice", "l_orderkey"])

    mem.reset_counters()
    with execution_config_ctx(memory_limit_bytes=96 * 1024, device_mode="off"):
        capped = q().to_pydict()
    assert registry().get("spill_merge_passes") > 0, \
        "run count never exceeded the merge fan-in"
    with execution_config_ctx(memory_limit_bytes=0, device_mode="off"):
        unbudgeted = q().to_pydict()
    assert capped == unbudgeted


def test_cancelled_spilling_query_gcs_spill_artifacts(tables):
    """Kill (abandon) a spilling query mid-stream: cancellation propagates
    to the producer threads, their finally blocks run, and no spill artifact
    of this pid survives."""
    from daft_tpu.memory import spill_root
    from daft_tpu.runners import get_or_create_runner

    li = tables["lineitem"].select(col("l_orderkey"), col("l_extendedprice"))
    with execution_config_ctx(memory_limit_bytes=256 * 1024, device_mode="off"):
        q = li.sort(["l_extendedprice", "l_orderkey"])
        it = get_or_create_runner().run_iter(q._builder)
        first = next(it)
        assert first.num_rows > 0
        assert registry().get("spill_files") > 0, "query never spilled"
        it.close()  # consumer abandons the stream mid-merge
    root = spill_root()
    mine_tag = f"{os.getpid()}_"
    deadline = time.time() + 10
    mine = ["?"]
    while time.time() < deadline and mine:
        mine = [n for n in os.listdir(root)
                if mine_tag in n] if os.path.isdir(root) else []
        if mine:
            time.sleep(0.05)
    assert not mine, f"orphaned spill artifacts after cancellation: {mine}"


def _dead_pid() -> int:
    for pid in range(300_000, 300_064):
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            return pid
        except OSError:
            continue
    pytest.skip("could not find a dead pid on this platform")


def test_stale_spill_artifacts_swept(tmp_path):
    """Artifacts from a KILLED process (embedded pid dead) are swept; a live
    process's artifacts are never touched."""
    from daft_tpu.memory import gc_stale_spills

    root = tmp_path / "spillroot"
    root.mkdir()
    dead = _dead_pid()
    (root / f"s{dead}_deadbeef01.arrow").write_bytes(b"x")
    grace = root / f"g{dead}_deadbeef02"
    grace.mkdir()
    (grace / "s1_aa.arrow").write_bytes(b"x")
    live = f"s{os.getpid()}_cafecafe01.arrow"
    (root / live).write_bytes(b"x")
    removed = gc_stale_spills(str(root))
    assert removed == 2
    assert sorted(os.listdir(root)) == [live]
    assert registry().get("spill_dirs_gced") >= 2


def test_spill_file_tmp_publish_discipline(tmp_path):
    """A spill file streams into <name>.tmp and publishes atomically on
    finish; delete removes both names; round-trip preserves content."""
    import numpy as np
    import pyarrow as pa

    from daft_tpu.core.recordbatch import RecordBatch
    from daft_tpu.memory import SpillFile

    batch = RecordBatch.from_arrow(pa.table({"a": np.arange(1000)}))
    f = SpillFile(batch.schema, spill_dir=str(tmp_path))
    f.append(batch)
    f._join_queue()  # async appends land in .tmp off-thread; join to observe
    assert os.path.exists(f._tmp) and not os.path.exists(f.path)
    f.finish()
    assert os.path.exists(f.path) and not os.path.exists(f._tmp)
    got = list(f.read())
    assert sum(b.num_rows for b in got) == 1000
    assert got[0].get_column("a").to_pylist()[:5] == [0, 1, 2, 3, 4]
    f.delete()
    assert not os.path.exists(f.path)
