"""Native C++ kernel tests: parity between native and numpy fallback paths."""

import numpy as np
import pytest

import daft_tpu as dt
from daft_tpu import col
from daft_tpu.native import (
    get_lib,
    native_factorize,
    native_grouped_minmax,
    native_grouped_sum,
    native_join_indices,
)

pytestmark = pytest.mark.skipif(get_lib() is None, reason="native lib not built")


def test_factorize_first_occurrence():
    codes, g = native_factorize(np.array([5, 7, 5, -1, 7, 9], dtype=np.int64))
    assert codes.tolist() == [0, 1, 0, 2, 1, 3]
    assert g == 4


def test_factorize_large_random():
    import pandas as pd

    rng = np.random.default_rng(0)
    keys = rng.integers(-1000, 1000, 100_000)
    codes, g = native_factorize(keys)
    expected = pd.factorize(keys)[0]
    assert np.array_equal(codes, expected)


def test_grouped_sum_matches_numpy():
    rng = np.random.default_rng(1)
    gids = rng.integers(0, 50, 10_000)
    vals = rng.uniform(-5, 5, 10_000)
    valid = rng.random(10_000) < 0.9
    sums, cnt = native_grouped_sum(gids, vals, valid, 50)
    for g in range(50):
        m = (gids == g) & valid
        assert abs(sums[g] - vals[m].sum()) < 1e-9
        assert cnt[g] == m.sum()


def test_grouped_minmax_int():
    gids = np.array([0, 0, 1, 1, 1], dtype=np.int64)
    vals = np.array([3, -2, 10, 4, 8], dtype=np.int64)
    valid = np.array([True, True, False, True, True])
    mn, mx = native_grouped_minmax(gids, vals, valid, 2)
    assert mn.tolist() == [-2, 4] and mx.tolist() == [3, 8]


def test_join_pairs():
    l = np.array([0, 1, 2, -2], dtype=np.int64)
    r = np.array([1, 1, 0, -3], dtype=np.int64)
    out_l, out_r, counts = native_join_indices(l, r, 3)
    pairs = sorted(zip(out_l.tolist(), out_r.tolist()))
    assert pairs == [(0, 2), (1, 0), (1, 1)]
    assert counts.tolist() == [1, 2, 0, 0]


def test_hash_stability_via_series():
    # xxhash column path (engine-level contract from the verify skill)
    assert dt.Series.from_pylist(["abc"]).hash().to_pylist()[0] == 12578444927678923021


def test_engine_parity_native_vs_fallback(monkeypatch):
    df = dt.from_pydict({
        "k": ["a", "b", "a", None, "b"] * 200,
        "v": [1.5, None, 3.0, 4.0, -2.0] * 200,
    })
    expected = {
        "k": ["a", "b", None],
        "s": [450.0 * 2 / 1, None, None],
    }
    native_out = df.groupby("k").agg(
        col("v").sum().alias("s"), col("v").mean().alias("m"),
        col("v").min().alias("lo"), col("v").max().alias("hi"),
        col("v").count().alias("c"),
    ).sort("k", nulls_first=False).to_pydict()
    import daft_tpu.native as na

    monkeypatch.setattr(na, "_LIB", None)
    monkeypatch.setattr(na, "_TRIED", True)
    fallback_out = df.groupby("k").agg(
        col("v").sum().alias("s"), col("v").mean().alias("m"),
        col("v").min().alias("lo"), col("v").max().alias("hi"),
        col("v").count().alias("c"),
    ).sort("k", nulls_first=False).to_pydict()
    monkeypatch.setattr(na, "_TRIED", False)
    assert native_out == fallback_out
