"""Property-based correctness for sort/groupby/join over arbitrary dtypes with
nulls (reference: tests/property_based_testing/strategies.py + test_sort.py).

Each operation is cross-checked against an independent pandas rendition on
hypothesis-generated columns (ints, floats incl. inf, strings, bools, dates,
nulls everywhere)."""

import datetime

import numpy as np
import pandas as pd
import pytest
from hypothesis import given, settings, strategies as st

import daft_tpu
from daft_tpu import col

_settings = settings(max_examples=25, deadline=None)

_scalar_strategies = {
    "int": st.one_of(st.none(), st.integers(-2**40, 2**40)),
    "float": st.one_of(st.none(), st.floats(allow_nan=False, width=64)),
    "string": st.one_of(st.none(), st.text(alphabet="abcXYZ019 _", max_size=8)),
    "bool": st.one_of(st.none(), st.booleans()),
    "date": st.one_of(st.none(), st.dates(datetime.date(1990, 1, 1),
                                          datetime.date(2030, 12, 31))),
}


def _column(dtype_name, n):
    return st.lists(_scalar_strategies[dtype_name], min_size=n, max_size=n)


@st.composite
def sort_case(draw):
    n = draw(st.integers(0, 40))
    dt = draw(st.sampled_from(list(_scalar_strategies)))
    values = draw(_column(dt, n))
    desc = draw(st.booleans())
    return values, desc


@_settings
@given(sort_case())
def test_sort_matches_pandas(case):
    values, desc = case
    df = daft_tpu.from_pydict({"v": values, "i": list(range(len(values)))})
    out = df.sort(["v", "i"], desc=[desc, False]).to_pydict()
    pdf = pd.DataFrame({"v": pd.Series(values, dtype=object), "i": range(len(values))})
    # engine default: nulls last ascending, first descending; stable by i
    expect = pdf.sort_values(["v", "i"], ascending=[not desc, True],
                             na_position="first" if desc else "last",
                             key=lambda s: s if s.name == "i" else s.map(
                                 lambda x: x if x is not None else None))
    assert out["i"] == expect["i"].tolist()


@st.composite
def groupby_case(draw):
    n = draw(st.integers(0, 50))
    key_dt = draw(st.sampled_from(["int", "string", "bool", "date"]))
    keys = draw(_column(key_dt, n))
    vals = draw(_column("float", n))
    return keys, vals


@_settings
@given(groupby_case())
def test_groupby_sum_count_matches_pandas(case):
    keys, vals = case
    df = daft_tpu.from_pydict({"k": keys, "v": vals})
    out = df.groupby("k").agg(
        col("v").sum().alias("s"), col("v").count().alias("c")).to_pydict()
    got = {k: (s, c) for k, s, c in zip(out["k"], out["s"], out["c"])}

    expect = {}
    for k, v in zip(keys, vals):
        s, c = expect.get(k, (None, 0))
        if v is not None:
            s = v if s is None else s + v
            c += 1
        expect[k] = (s, c)
    assert set(got) == set(expect)
    for k in expect:
        es, ec = expect[k]
        gs, gc = got[k]
        assert gc == ec, (k, got[k], expect[k])
        if es is None:
            assert gs is None
        elif es != es:  # NaN (e.g. inf + -inf): both sides must agree
            assert gs != gs
        else:
            assert gs == pytest.approx(es, rel=1e-9, abs=1e-9)


@st.composite
def join_case(draw):
    key_dt = draw(st.sampled_from(["int", "string", "date"]))
    nl = draw(st.integers(0, 30))
    nr = draw(st.integers(0, 30))
    # draw keys from a small domain so joins actually match
    domain = draw(st.lists(_scalar_strategies[key_dt], min_size=4, max_size=4,
                           unique_by=lambda x: (x is None, x)))
    lkeys = draw(st.lists(st.sampled_from(domain), min_size=nl, max_size=nl))
    rkeys = draw(st.lists(st.sampled_from(domain), min_size=nr, max_size=nr))
    how = draw(st.sampled_from(["inner", "left", "semi", "anti"]))
    return lkeys, rkeys, how


@_settings
@given(join_case())
def test_join_matches_manual(case):
    lkeys, rkeys, how = case
    left = daft_tpu.from_pydict({"k": lkeys, "lx": list(range(len(lkeys)))})
    right = daft_tpu.from_pydict({"k": rkeys, "ry": list(range(len(rkeys)))})
    out = left.join(right, on="k", how=how).to_pydict()

    rmatch = {}
    for k, y in zip(rkeys, [*range(len(rkeys))]):
        if k is not None:
            rmatch.setdefault(k, []).append(y)

    expect_rows = []
    for k, x in zip(lkeys, range(len(lkeys))):
        matches = rmatch.get(k, []) if k is not None else []  # null keys never join
        if how == "inner":
            expect_rows += [(k, x, y) for y in matches]
        elif how == "left":
            expect_rows += [(k, x, y) for y in matches] or [(k, x, None)]
        elif how == "semi":
            if matches:
                expect_rows.append((k, x))
        elif how == "anti":
            if not matches:
                expect_rows.append((k, x))

    if how in ("semi", "anti"):
        got_rows = sorted(zip(out["k"], out["lx"]),
                          key=lambda r: (r[1],))
        expect_rows.sort(key=lambda r: (r[1],))
        assert got_rows == expect_rows
    else:
        got_rows = sorted(zip(out["k"], out["lx"], out["ry"]),
                          key=lambda r: (r[1], (r[2] is None, r[2])))
        expect_rows.sort(key=lambda r: (r[1], (r[2] is None, r[2])))
        assert got_rows == expect_rows


class TestProbePathEquivalence:
    """The ProbeTable fast paths (unique-key direct lookup, fused single-key
    C probe) must produce exactly the general join_indices match set/order."""

    @given(
        build=st.lists(st.integers(-5, 40) | st.none(), min_size=0, max_size=30),
        probe=st.lists(st.integers(-5, 40) | st.none(), min_size=0, max_size=60),
        how=st.sampled_from(["inner", "left", "semi", "anti"]),
        unique=st.booleans(),
    )
    @settings(max_examples=200, deadline=None)
    def test_probe_matches_join_indices(self, build, probe, how, unique):
        import numpy as np

        from daft_tpu.core.kernels.join import ProbeTable, join_indices
        from daft_tpu.core.series import Series
        from daft_tpu.datatype import DataType

        if unique:
            seen = set()
            build = [b for b in build
                     if not (b in seen or (b is not None and seen.add(b)))]
        bs = Series.from_pylist(build, "k", dtype=DataType.int64())
        ps = Series.from_pylist(probe, "k", dtype=DataType.int64())
        expect = join_indices([ps], [bs], how=how)
        table = ProbeTable([bs], [DataType.int64()], null_equals_null=False)
        got = table.probe([ps], how)
        np.testing.assert_array_equal(got[0], expect[0], err_msg=f"{how} lidx")
        np.testing.assert_array_equal(got[1], expect[1], err_msg=f"{how} ridx")
