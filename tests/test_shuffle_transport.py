"""Pipelined compressed shuffle transport (distributed/shuffle.py +
fetch_server.py): codec roundtrips, multi-peer fan-in, bounded prefetch
backpressure, truncated-frame error surfacing, serial compatibility guard,
and the 2-worker end-to-end wire-vs-logical / overlap acceptance checks.

Reference bar: src/daft-shuffles (InProgressShuffleCache compressed IPC per
partition + flight-server concurrent do_get streams per reduce task)."""

import os
import time

import numpy as np
import pytest

import daft_tpu
import daft_tpu.runners as runners
import pyarrow as pa
import pyarrow.ipc as ipc
from daft_tpu import col
from daft_tpu.config import ExecutionConfig, execution_config_ctx
from daft_tpu.core.recordbatch import RecordBatch
from daft_tpu.distributed import shuffle as shf
from daft_tpu.distributed.fetch_server import ShuffleFetchServer, fetch_partition
from daft_tpu.observability.metrics import registry


def _batch(n=4000, offset=0):
    # repetitive values so lz4/zstd have something to compress
    return RecordBatch.from_arrow(pa.table({
        "k": [offset + (i % 100) for i in range(n)],
        "v": [float(i % 13) for i in range(n)],
    }))


def _collect(parts):
    out = {}
    for p in parts:
        for k, vs in p.to_pydict().items():
            out.setdefault(k, []).extend(vs)
    return out


def _rows(d):
    return sorted(zip(d.get("k", []), d.get("v", [])))


# ---------------------------------------------------------------------------
# Codec roundtrips + container auto-detection
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("codec", ["none", "lz4", "zstd"])
def test_compression_roundtrip_bit_exact(codec, tmp_path):
    b = _batch()
    w = shf.MapOutputWriter(str(tmp_path), "s1", 0, 1, compression=codec)
    before = registry().snapshot()
    w.append(0, b)
    w.close()
    deltas = registry().diff(before)
    path = os.path.join(shf.partition_dir(str(tmp_path), "s1", 0), "m0.arrow")
    wire = os.path.getsize(path)
    logical = b.to_arrow().nbytes
    assert deltas["shuffle_logical_bytes"] == logical
    assert deltas["shuffle_wire_bytes"] == wire
    if codec == "none":
        # raw buffers: wire carries IPC framing on top of the logical bytes
        assert wire >= logical
    else:
        assert wire < logical, f"{codec} did not compress"
    got = list(shf.read_partition(str(tmp_path), "s1", 0, b.schema))
    merged = pa.concat_tables([p.batches[0].to_arrow() for p in got])
    assert merged.equals(b.to_arrow()), f"{codec} roundtrip not bit-exact"


def test_reader_autodetects_legacy_file_format(tmp_path):
    """Shuffle dirs written by the pre-compression engine (Arrow *file*
    format) still decode — the reader sniffs the ARROW1 magic."""
    b = _batch(500)
    d = shf.partition_dir(str(tmp_path), "old", 0)
    os.makedirs(d)
    t = b.to_arrow()
    with ipc.RecordBatchFileWriter(os.path.join(d, "m0.arrow"), t.schema) as w:
        w.write_table(t)
    got = _collect(shf.read_partition(str(tmp_path), "old", 0, b.schema))
    assert _rows(got) == _rows(b.to_pydict())


def test_streaming_read_yields_per_batch(tmp_path):
    """read_partition streams one MicroPartition per IPC batch — reduce-side
    memory is bounded by a batch, never the whole map file."""
    w = shf.MapOutputWriter(str(tmp_path), "s2", 0, 1, compression="lz4")
    for i in range(8):
        w.append(0, _batch(1000, offset=i * 1000))
    w.close()
    parts = list(shf.read_partition(str(tmp_path), "s2", 0, _batch(1).schema))
    assert len(parts) == 8, "map file was materialized instead of streamed"
    assert sum(p.num_rows for p in parts) == 8000


# ---------------------------------------------------------------------------
# Multi-peer fan-in, backpressure, errors
# ---------------------------------------------------------------------------

def test_multi_endpoint_fanin_merges_out_of_order(tmp_path):
    """Reduce-side fan-in over several endpoints: batches arrive in whatever
    order the peers produce them (a large file on one peer streams while the
    other peer's small files finish first); the merge must be exact."""
    d_big, d_small = str(tmp_path / "big"), str(tmp_path / "small")
    w = shf.MapOutputWriter(d_big, "s3", 0, 1, compression="lz4")
    for i in range(6):
        w.append(0, _batch(5000, offset=100 + i))
    w.close()
    w = shf.MapOutputWriter(d_small, "s3", 1, 1, compression="lz4")
    w.append(0, _batch(50, offset=7))
    w.close()
    expect = _rows(_collect(shf.read_partition(d_big, "s3", 0, _batch(1).schema))) \
        + _rows(_collect(shf.read_partition(d_small, "s3", 0, _batch(1).schema)))
    s_big, s_small = ShuffleFetchServer(d_big), ShuffleFetchServer(d_small)
    try:
        got = _collect(fetch_partition(
            [s_big.endpoint, s_small.endpoint], "s3", 0, _batch(1).schema,
            parallelism=2, prefetch=4))
        assert sorted(_rows(got)) == sorted(expect)
    finally:
        s_big.close()
        s_small.close()


def test_bounded_prefetch_backpressure(tmp_path):
    """The prefetch queue never exceeds the knob: a slow consumer
    backpressures the fetch threads instead of buffering the partition."""
    for m in range(3):
        w = shf.MapOutputWriter(str(tmp_path), "s4", m, 1, compression="lz4")
        for i in range(4):
            w.append(0, _batch(500, offset=m * 10 + i))
        w.close()
    registry().reset(["shuffle_fetch_inflight"])
    srv = ShuffleFetchServer(str(tmp_path))
    try:
        seen = 0
        for _p in fetch_partition([srv.endpoint], "s4", 0, _batch(1).schema,
                                  parallelism=2, prefetch=2):
            seen += 1
            time.sleep(0.01)  # slow reduce: producers must block, not buffer
        assert seen == 12
        hw = registry().snapshot().get("shuffle_fetch_inflight", 0)
        assert 0 < hw <= 2, f"prefetch queue exceeded the knob: {hw}"
    finally:
        srv.close()


@pytest.mark.parametrize("parallelism,prefetch", [(1, 0), (4, 4)])
def test_truncated_file_surfaces_clean_error(tmp_path, parallelism, prefetch):
    """A corrupted/truncated map file must raise promptly on the consumer —
    never hang the reduce task or silently drop rows."""
    w = shf.MapOutputWriter(str(tmp_path), "s5", 0, 1, compression="lz4")
    w.append(0, _batch(5000))
    w.close()
    path = os.path.join(shf.partition_dir(str(tmp_path), "s5", 0), "m0.arrow")
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) // 2)
    srv = ShuffleFetchServer(str(tmp_path))
    try:
        t0 = time.monotonic()
        with pytest.raises(Exception) as ei:
            _collect(fetch_partition([srv.endpoint], "s5", 0, _batch(1).schema,
                                     parallelism=parallelism, prefetch=prefetch))
        assert time.monotonic() - t0 < 30, "truncated fetch hung"
        assert not isinstance(ei.value, (TimeoutError, AssertionError))
    finally:
        srv.close()


def test_accept_loop_survives_bad_handshakes(tmp_path):
    """Rejected handshakes (wrong auth key) must not kill or wedge the accept
    loop — subsequent authenticated fetches still work."""
    import multiprocessing.connection as mpc

    w = shf.MapOutputWriter(str(tmp_path), "s6", 0, 1, compression="none")
    w.append(0, _batch(100))
    w.close()
    srv = ShuffleFetchServer(str(tmp_path))
    try:
        host, port, key = srv.endpoint
        for _ in range(3):
            with pytest.raises(Exception):
                c = mpc.Client((host, port), family="AF_INET", authkey=b"wrong")
                c.close()
        got = _collect(fetch_partition([srv.endpoint], "s6", 0, _batch(1).schema,
                                       parallelism=2, prefetch=2))
        assert len(got["k"]) == 100
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# Serial compatibility guard + recorder over-count fix
# ---------------------------------------------------------------------------

def test_serial_compat_path_matches_and_adds_no_counters(tmp_path):
    """shuffle_fetch_parallelism=1 + shuffle_prefetch_batches=0 +
    shuffle_compression=none reproduces the original serial transport: same
    rows, no pipelined-only counters (overlap/wall/inflight), whole-file
    'fetch' requests."""
    w = shf.MapOutputWriter(str(tmp_path), "s7", 0, 1, compression="none")
    for i in range(3):
        w.append(0, _batch(1000, offset=i))
    w.close()
    srv = ShuffleFetchServer(str(tmp_path))
    registry().reset(["shuffle_fetch_inflight"])
    try:
        expect = _rows(_collect(fetch_partition(
            [srv.endpoint], "s7", 0, _batch(1).schema, parallelism=4, prefetch=4)))
        before = registry().snapshot()
        got = _rows(_collect(fetch_partition(
            [srv.endpoint], "s7", 0, _batch(1).schema, parallelism=1, prefetch=0)))
        deltas = registry().diff(before)
        assert got == expect
        assert deltas.get("shuffle_bytes_fetched", 0) > 0
        for k in ("shuffle_overlap_seconds", "shuffle_fetch_wall_seconds"):
            assert k not in deltas, f"serial path recorded pipelined counter {k}"
    finally:
        srv.close()


def test_recorder_separates_cumulative_and_wall_fetch_time(tmp_path):
    """ShuffleRecorder.fetch_seconds sums per-request in-flight time and
    OVER-COUNTS once requests overlap (by design); fetch_wall_seconds is the
    union transfer window, and their difference is the recorded overlap."""
    for m in range(2):
        w = shf.MapOutputWriter(str(tmp_path), "s8", m, 1, compression="lz4")
        w.append(0, _batch(20_000, offset=m))
        w.close()
    srv = ShuffleFetchServer(str(tmp_path))
    rec = shf.ShuffleRecorder()
    shf.set_recorder(rec)
    try:
        _collect(fetch_partition([srv.endpoint], "s8", 0, _batch(1).schema,
                                 parallelism=2, prefetch=4))
        d = rec.as_dict()
        assert d["fetch_requests"] == 2
        assert d["fetch_wall_seconds"] > 0
        assert d["fetch_seconds"] > d["fetch_wall_seconds"], \
            "pipelined requests should make cumulative exceed wall"
        assert d["overlap_seconds"] == pytest.approx(
            d["fetch_seconds"] - d["fetch_wall_seconds"], rel=0.2)
        assert d["fetch_fanin"] >= 1
    finally:
        shf.set_recorder(None)
        srv.close()


def test_early_generator_close_cleans_up_and_accounts(tmp_path):
    """Closing the reduce iterator mid-partition must unwind the fetch
    threads promptly (no leaked daft-shuffle-fetch-client threads wedged in
    recv) and still account the wire bytes actually transferred."""
    import threading as _threading

    for m in range(4):
        w = shf.MapOutputWriter(str(tmp_path), "s9", m, 1, compression="lz4")
        for i in range(4):
            w.append(0, _batch(2000, offset=m * 10 + i))
        w.close()
    srv = ShuffleFetchServer(str(tmp_path))
    before = registry().snapshot()
    try:
        gen = fetch_partition([srv.endpoint], "s9", 0, _batch(1).schema,
                              parallelism=2, prefetch=2)
        next(gen)
        gen.close()  # runs the finally: stop event + thread join
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and any(
                t.name == "daft-shuffle-fetch-client" and t.is_alive()
                for t in _threading.enumerate()):
            time.sleep(0.05)
        leaked = [t for t in _threading.enumerate()
                  if t.name == "daft-shuffle-fetch-client" and t.is_alive()]
        assert not leaked, f"fetch threads leaked: {leaked}"
        assert registry().diff(before).get("shuffle_bytes_fetched", 0) > 0, \
            "abandoned fetch dropped its transferred bytes from the counters"
    finally:
        srv.close()


def test_serial_early_close_accounts_wire_bytes(tmp_path):
    """Serial path: a consumer stopping mid-file still records the file's
    wire bytes — they were fully received before the first yield."""
    w = shf.MapOutputWriter(str(tmp_path), "s10", 0, 1, compression="lz4")
    for i in range(3):
        w.append(0, _batch(1000, offset=i))
    w.close()
    srv = ShuffleFetchServer(str(tmp_path))
    before = registry().snapshot()
    try:
        gen = fetch_partition([srv.endpoint], "s10", 0, _batch(1).schema,
                              parallelism=1, prefetch=0)
        next(gen)
        gen.close()
        wire = os.path.getsize(os.path.join(
            shf.partition_dir(str(tmp_path), "s10", 0), "m0.arrow"))
        assert registry().diff(before).get("shuffle_bytes_fetched", 0) == wire
    finally:
        srv.close()


def test_shuffle_config_validation():
    with pytest.raises(ValueError, match="shuffle_compression"):
        ExecutionConfig(shuffle_compression="gzip")
    with pytest.raises(ValueError, match="shuffle_fetch_parallelism"):
        ExecutionConfig(shuffle_fetch_parallelism=0)
    with pytest.raises(ValueError, match="shuffle_prefetch_batches"):
        ExecutionConfig(shuffle_prefetch_batches=-1)


# ---------------------------------------------------------------------------
# 2-worker end-to-end acceptance: compressed socket shuffle, overlap, parity
# ---------------------------------------------------------------------------

def test_two_worker_compressed_shuffle_matches_single_host():
    """With 2 workers and shuffle_compression=lz4, a shuffled groupby matches
    the single-host path exactly, ships fewer wire bytes than logical bytes,
    and records transfer overlap under the pipelined fetch."""
    from daft_tpu.distributed.runner import DistributedRunner
    from daft_tpu.observability.runtime_stats import set_collector, StatsCollector

    rng = np.random.default_rng(11)
    n = 30_000
    df = daft_tpu.from_pydict({
        "k": rng.integers(0, 200, n).tolist(),
        "v": rng.uniform(0, 10, n).tolist(),
        "c": rng.integers(0, 5, n).tolist(),
    })

    def q():
        return (df.groupby("k")
                .agg(col("v").sum().alias("s"), col("c").sum().alias("cs"),
                     col("v").count().alias("n"))
                .sort("k"))

    native = runners.NativeRunner()
    runners.set_runner(native)
    expect = q().to_pydict()

    with execution_config_ctx(shuffle_compression="lz4",
                              shuffle_fetch_parallelism=4,
                              shuffle_prefetch_batches=8):
        r = DistributedRunner(num_workers=2, n_partitions=2,
                              shuffle_transport="socket")
        try:
            before = registry().snapshot()
            collector = StatsCollector()  # traced run -> shuffle counters flow back
            runners.set_runner(r)
            set_collector(collector)
            try:
                got = q().to_pydict()
            finally:
                set_collector(None)
                runners.set_runner(native)
            deltas = registry().diff(before)
        finally:
            r.shutdown()

    assert got["k"] == expect["k"]
    assert got["cs"] == expect["cs"]       # int sums: exact across partitionings
    assert got["n"] == expect["n"]
    np.testing.assert_allclose(got["s"], expect["s"], rtol=1e-12)

    wire = deltas.get("shuffle_wire_bytes", 0)
    logical = deltas.get("shuffle_logical_bytes", 0)
    assert 0 < wire < logical, f"compression didn't pay: wire={wire} logical={logical}"
    assert deltas.get("shuffle_overlap_seconds", 0) > 0, \
        "pipelined fetch recorded no transfer overlap"
    assert deltas.get("shuffle_fetch_seconds", 0) > \
        deltas.get("shuffle_fetch_wall_seconds", 0)


def test_distributed_explain_analyze_shows_compression_and_fanin():
    """EXPLAIN ANALYZE on a socket-transport distributed run renders the
    per-stage compression ratio and fetch fan-in lines."""
    from daft_tpu.distributed.runner import DistributedRunner

    rng = np.random.default_rng(12)
    n = 20_000
    df = daft_tpu.from_pydict({
        "k": rng.integers(0, 40, n).tolist(),
        "v": rng.uniform(0, 1, n).tolist(),
    })
    with execution_config_ctx(shuffle_compression="lz4"):
        r = DistributedRunner(num_workers=2, n_partitions=2,
                              shuffle_transport="socket")
        native = runners.NativeRunner()
        runners.set_runner(r)
        try:
            report = df.groupby("k").agg(col("v").sum().alias("s")).explain_analyze()
        finally:
            runners.set_runner(native)
            r.shutdown()
    assert "compression:" in report and "wire" in report
    assert "fan-in" in report
    assert "cumulative" in report and "wall" in report
    assert "shuffle_wire_bytes" in report  # engine-counter section
