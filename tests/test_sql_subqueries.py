

class TestSqlBreadthWave2:
    """ROLLUP/CUBE/GROUPING SETS, VALUES, EXTRACT, positional set-op alignment
    (reference: sqlparser GroupByExpr / Values / Extract lowering)."""

    def _t(self):
        import daft_tpu

        return daft_tpu.from_pydict(
            {"g": ["a", "a", "b"], "v": [1, 2, 3], "d": ["x", "y", "x"]})

    def test_rollup(self):
        import daft_tpu

        out = daft_tpu.sql(
            "SELECT g, SUM(v) s FROM t GROUP BY ROLLUP(g) ORDER BY s, g",
            t=self._t()).to_pydict()
        assert out == {"g": ["a", "b", None], "s": [3, 3, 6]}

    def test_cube_row_count(self):
        import daft_tpu

        out = daft_tpu.sql(
            "SELECT g, d, SUM(v) s FROM t GROUP BY CUBE(g, d)",
            t=self._t()).to_pydict()
        # (g,d): 3 combos; (g): 2; (d): 2; (): 1
        assert len(out["s"]) == 8
        assert sum(1 for g, d in zip(out["g"], out["d"])
                   if g is None and d is None) == 1

    def test_grouping_sets(self):
        import daft_tpu

        out = daft_tpu.sql(
            "SELECT g, SUM(v) s FROM t GROUP BY GROUPING SETS ((g), ()) "
            "ORDER BY s, g", t=self._t()).to_pydict()
        assert out == {"g": ["a", "b", None], "s": [3, 3, 6]}

    def test_values_clause(self):
        import daft_tpu

        out = daft_tpu.sql(
            "SELECT n * 2 AS n2, s FROM (VALUES (1,'a'),(2,'b')) AS x(n, s) "
            "ORDER BY n2", t=self._t()).to_pydict()
        assert out == {"n2": [2, 4], "s": ["a", "b"]}

    def test_extract(self):
        import daft_tpu

        out = daft_tpu.sql(
            "SELECT EXTRACT(YEAR FROM DATE '2024-03-02') y, "
            "EXTRACT(MONTH FROM DATE '2024-03-02') m", t=self._t()).to_pydict()
        assert out["y"] == [2024] and out["m"] == [3]

    def test_setop_positional_alignment(self):
        import daft_tpu

        out = daft_tpu.sql("SELECT v FROM t EXCEPT SELECT 1", t=self._t()).to_pydict()
        assert sorted(out["v"]) == [2, 3]
        out2 = daft_tpu.sql("SELECT v FROM t UNION SELECT 99", t=self._t()).to_pydict()
        assert sorted(out2["v"]) == [1, 2, 3, 99]
