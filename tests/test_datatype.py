import numpy as np
import pyarrow as pa
import pytest

from daft_tpu import DataType, Schema, Field


def test_primitives_roundtrip_arrow():
    for dt in [
        DataType.bool(), DataType.int8(), DataType.int16(), DataType.int32(), DataType.int64(),
        DataType.uint8(), DataType.uint16(), DataType.uint32(), DataType.uint64(),
        DataType.float32(), DataType.float64(), DataType.string(), DataType.binary(),
        DataType.date(), DataType.timestamp("us"), DataType.timestamp("ns", "UTC"),
        DataType.duration("ms"), DataType.decimal128(10, 2), DataType.null(),
    ]:
        assert DataType.from_arrow(dt.to_arrow()) == dt


def test_nested_roundtrip():
    dt = DataType.list(DataType.int64())
    assert DataType.from_arrow(dt.to_arrow()) == dt
    dt = DataType.struct({"a": DataType.int64(), "b": DataType.string()})
    assert DataType.from_arrow(dt.to_arrow()) == dt
    dt = DataType.map(DataType.string(), DataType.int64())
    assert DataType.from_arrow(dt.to_arrow()) == dt
    dt = DataType.fixed_size_list(DataType.float32(), 4)
    assert DataType.from_arrow(dt.to_arrow()) == dt


def test_predicates():
    assert DataType.int32().is_integer()
    assert DataType.int32().is_numeric()
    assert not DataType.int32().is_floating()
    assert DataType.float32().is_floating()
    assert DataType.uint8().is_unsigned_integer()
    assert DataType.string().is_string()
    assert DataType.timestamp().is_temporal()
    assert DataType.list(DataType.int64()).is_nested()
    assert DataType.embedding(DataType.float32(), 128).is_logical()
    assert DataType.image().is_logical()


def test_multimodal_types():
    emb = DataType.embedding(DataType.float32(), 512)
    assert emb.inner == DataType.float32()
    assert emb.size == 512
    assert emb.is_device_compatible()

    img = DataType.fixed_shape_image("RGB", 224, 224)
    assert img.shape == (224, 224, 3)
    assert img.is_device_compatible()

    t = DataType.tensor(DataType.float32(), (3, 4))
    assert t.kind == "fixed_shape_tensor"
    assert t.shape == (3, 4)

    with pytest.raises(ValueError):
        DataType.embedding(DataType.string(), 4)
    with pytest.raises(ValueError):
        DataType.image("BAD")


def test_jax_dtypes():
    import jax.numpy as jnp

    assert DataType.float32().to_jax() == jnp.float32
    assert DataType.int64().to_jax() == jnp.int64
    assert DataType.bool().to_jax() == jnp.bool_
    assert DataType.date().to_jax() == jnp.int32
    assert DataType.embedding(DataType.float32(), 8).to_jax() == jnp.float32
    assert not DataType.string().is_device_compatible()


def test_schema_basic():
    s = Schema.from_pydict({"a": DataType.int64(), "b": DataType.string()})
    assert len(s) == 2
    assert s.column_names() == ["a", "b"]
    assert s["a"].dtype == DataType.int64()
    assert "b" in s
    assert s.index_of("b") == 1
    with pytest.raises(KeyError):
        s["zzz"]
    with pytest.raises(ValueError):
        Schema([Field("x", DataType.int64()), Field("x", DataType.int32())])


def test_schema_ops():
    s = Schema.from_pydict({"a": DataType.int64(), "b": DataType.string(), "c": DataType.float64()})
    assert s.select(["c", "a"]).column_names() == ["c", "a"]
    assert s.exclude(["b"]).column_names() == ["a", "c"]
    s2 = Schema.from_pydict({"d": DataType.bool()})
    assert s.union(s2).column_names() == ["a", "b", "c", "d"]
    assert s.rename({"a": "x"}).column_names() == ["x", "b", "c"]
    arrow = s.to_arrow()
    assert Schema.from_arrow(arrow) == s
