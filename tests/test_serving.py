"""Serving tier: fair admission, HBM budget control, prepared queries,
thread-safety of cross-query state, and speculative re-execution.

Everything here runs on the CPU backend; device-path tests force
device_mode="on" (the capture + residency machinery is backend-agnostic).
"""

import json
import os
import threading
import time
import urllib.request

import pytest

import daft_tpu
from daft_tpu import col
from daft_tpu.config import execution_config_ctx
from daft_tpu.device.residency import manager
from daft_tpu.observability.metrics import registry
from daft_tpu.serving import FairAdmissionQueue, ServingSession


def _table(n=60_000, keys=13):
    return daft_tpu.from_pydict({
        "k": [i % keys for i in range(n)],
        "v": [float(i % 1009) for i in range(n)],
        "w": [i % 83 for i in range(n)],
    })


# ---------------------------------------------------------------------------
# Fair admission queue
# ---------------------------------------------------------------------------

def test_fair_queue_round_robin_across_tenants():
    q = FairAdmissionQueue()
    for i in range(3):
        q.push("a", f"a{i}")
    q.push("b", "b0")
    q.push("c", "c0")
    order = [q.pop(0) for _ in range(5)]
    # one per tenant per rotation: a, b, c interleave before a's backlog drains
    assert order[:3] == ["a0", "b0", "c0"]
    assert order[3:] == ["a1", "a2"]
    assert q.depth() == 0 and q.pop(0) is None


def test_fair_queue_fifo_within_tenant_and_late_tenant():
    q = FairAdmissionQueue()
    for i in range(4):
        q.push("bulk", i)
    assert q.pop(0) == 0
    q.push("interactive", "x")   # arrives behind a backlog
    # the late tenant waits at most one rotation, not the whole backlog
    nxt = [q.pop(0), q.pop(0)]
    assert "x" in nxt
    assert q.pop(0) in (2, 3)


# ---------------------------------------------------------------------------
# HBM admission controller (ResidencyManager.admit)
# ---------------------------------------------------------------------------

def _run_admits(est, n, tenant_budget=0, tenants=None, hold_s=0.03):
    """Run n concurrent admits of `est` bytes; returns (max_concurrent,
    waited_flags)."""
    active = [0]
    peak = [0]
    waited = []
    lock = threading.Lock()

    def go(i):
        t = tenants[i] if tenants else "t"
        with manager().admit(est, tenant=t, tenant_budget=tenant_budget) as w:
            with lock:
                active[0] += 1
                peak[0] = max(peak[0], active[0])
                waited.append(w)
            time.sleep(hold_s)
            with lock:
                active[0] -= 1

    ts = [threading.Thread(target=go, args=(i,)) for i in range(n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    return peak[0], waited


def test_admission_budget_serializes_overbudget_queries():
    before = registry().get("admission_waits_total")
    with execution_config_ctx(hbm_budget_bytes=1000):
        peak, waited = _run_admits(800, 4)
    assert peak == 1                       # 2x800 > 1000: one at a time
    assert sum(waited) >= 3
    assert registry().get("admission_waits_total") - before >= 3
    assert manager().reserved_bytes() == 0  # all released


def test_admission_budget_packs_within_budget():
    with execution_config_ctx(hbm_budget_bytes=1000):
        peak, _ = _run_admits(400, 4, hold_s=0.1)
    assert peak == 2                       # two 400s fit, the third waits


def test_admission_zero_estimate_never_waits():
    with execution_config_ctx(hbm_budget_bytes=10):
        peak, waited = _run_admits(0, 4)
    assert peak == 4 and not any(waited)   # host-only queries sail through


def test_admission_no_deadlock_when_estimate_exceeds_budget():
    # est >> budget: each query must run ALONE (never wait forever, never
    # evict another's pins)
    with execution_config_ctx(hbm_budget_bytes=64):
        done = []

        def go():
            with manager().admit(1 << 20):
                done.append(1)

        ts = [threading.Thread(target=go) for _ in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(10)
        assert len(done) == 3


def test_admission_per_tenant_budget():
    # unbounded global budget, 1000-byte tenant cap: one tenant serializes,
    # two tenants run concurrently
    with execution_config_ctx(hbm_budget_bytes=-1):
        peak_same, _ = _run_admits(800, 2, tenant_budget=1000,
                                   tenants=["a", "a"])
        peak_diff, _ = _run_admits(800, 2, tenant_budget=1000,
                                   tenants=["a", "b"])
    assert peak_same == 1
    assert peak_diff == 2


# ---------------------------------------------------------------------------
# ServingSession end to end
# ---------------------------------------------------------------------------

def test_session_concurrent_results_identical_and_prepared_hits():
    df = _table()
    mk = lambda: df.groupby("k").agg(col("v").sum().alias("s"),
                                     col("w").max().alias("mw")).sort("k")
    ref = mk().to_pydict()
    hits0 = registry().get("serve_prepared_hits")
    with ServingSession(max_concurrent=3) as sess:
        sess.run(mk())                      # warm the prepared cache
        futs = [sess.submit(mk(), tenant=f"t{i % 3}") for i in range(9)]
        outs = [f.to_pydict() for f in futs]
        stats = sess.tenant_stats()
    assert all(o == ref for o in outs)
    assert registry().get("serve_prepared_hits") - hits0 >= 9
    assert sum(s["queries"] for s in stats.values()) == 10
    assert set(stats) == {"default", "t0", "t1", "t2"}  # warm run + 3 tenants
    # queue fully drained
    assert registry().snapshot().get("serve_queue_depth") == 0.0


def test_session_error_propagates_to_future():
    df = _table(1000)
    with ServingSession(max_concurrent=1) as sess:
        with pytest.raises(Exception):
            # schema resolution may raise at submit (client thread) or at
            # planning (session thread -> future); both must surface
            sess.submit(df.select(col("nope"))).result(timeout=30)
        # the session keeps serving after a failed query
        assert sess.run(df.agg(col("v").sum().alias("s"))) is not None


def test_prepared_literal_contract_and_cold_parity():
    """Fingerprint-equal plans with differing literals must NOT share a
    prepared entry (PR 2 literal-compare contract: one slot per shape,
    replanned on literal change), and prepared results are bit-identical to
    cold execution."""
    df = _table()
    q_lo = lambda: df.where(col("w") > 10).agg(col("v").sum().alias("s"))
    q_hi = lambda: df.where(col("w") > 70).agg(col("v").sum().alias("s"))
    cold_lo = q_lo().to_pydict()
    cold_hi = q_hi().to_pydict()
    assert cold_lo != cold_hi
    with ServingSession(max_concurrent=1) as sess:
        a = sess.submit(q_lo()).to_pydict()     # cold -> planned
        b = sess.submit(q_lo()).to_pydict()     # identical repeat -> prepared
        c = sess.submit(q_hi()).to_pydict()     # same shape, new literal
        d = sess.submit(q_lo()).to_pydict()     # literal flips back
        # one slot per plan shape, like the residency cache
        assert len(sess.prepared) == 1
    assert a == b == d == cold_lo
    assert c == cold_hi


def test_session_device_tiny_budget_queues_not_thrashes():
    """Acceptance: under a deliberately tiny HBM budget, over-budget queries
    QUEUE (admission_waits rises) rather than evicting a running query's
    pinned planes; nothing deadlocks or fails."""
    df = _table()
    mk = lambda: df.groupby("k").agg(col("v").sum().alias("s")).sort("k")
    with execution_config_ctx(device_mode="on", device_min_rows=1,
                              mesh_devices=1):
        ref = mk().to_pydict()
        waits0 = registry().get("admission_waits_total")
        with execution_config_ctx(hbm_budget_bytes=2048):
            with ServingSession(max_concurrent=3) as sess:
                sess.run(mk())
                est = sess.prepared.get_or_plan(mk()._builder)[0].est_pin_bytes
                assert est > 2048    # genuinely over budget
                futs = [sess.submit(mk()) for _ in range(6)]
                outs = [f.to_pydict() for f in futs]
        assert all(o == ref for o in outs)
        assert registry().get("admission_waits_total") - waits0 >= 1
        assert manager().reserved_bytes() == 0


def test_serve_query_records_reach_subscribers():
    from daft_tpu.observability import Subscriber, attach_subscriber, \
        detach_subscriber

    class Cap(Subscriber):
        def __init__(self):
            self.recs = []

        def on_serve_query(self, rec):
            self.recs.append(rec)

    df = _table(5000)
    cap = Cap()
    attach_subscriber(cap)
    try:
        with ServingSession(max_concurrent=2) as sess:
            sess.run(df.agg(col("v").sum().alias("s")), tenant="acme")
            sess.run(df.agg(col("v").sum().alias("s")), tenant="acme")
            sess.run(df.agg(col("v").max().alias("m")), tenant="globex")
    finally:
        detach_subscriber(cap)
    assert len(cap.recs) == 3
    by_tenant = {}
    for r in cap.recs:
        by_tenant.setdefault(r.tenant, []).append(r)
    assert set(by_tenant) == {"acme", "globex"}
    assert any(r.prepared_hit for r in by_tenant["acme"])
    assert all(r.error is None and r.seconds > 0 for r in cap.recs)


# ---------------------------------------------------------------------------
# Thread-safety audit smoke (satellite): process-global state under
# concurrent queries — no lost updates, no cross-query span bleed
# ---------------------------------------------------------------------------

def test_many_threads_no_lost_updates_and_no_span_bleed():
    from daft_tpu.observability.dashboard import DashboardState
    from daft_tpu.observability.runtime_stats import (SpanRecorder, set_spans,
                                                      span_scope)
    from daft_tpu.observability.subscribers import (attach_subscriber,
                                                    detach_subscriber)

    df = _table(30_000)
    mk = lambda: df.groupby("k").agg(col("v").sum().alias("s")).sort("k")
    state = DashboardState()
    profiler_rec = SpanRecorder()
    with execution_config_ctx(device_mode="on", device_min_rows=1,
                              mesh_devices=1, pipeline_mode="off"):
        # pre-attach sanity: reference result + proof the device span sites
        # record on an instrumented thread (so the bleed assertion below is
        # meaningful, not vacuously empty)
        ref = mk().to_pydict()
        own = SpanRecorder()
        with span_scope(own):
            mk().to_pydict()
        assert own.drain(), "device span sites recorded nothing"
    attach_subscriber(state)
    set_spans(profiler_rec)    # a profiled query is "in flight" elsewhere
    try:
        with execution_config_ctx(device_mode="on", device_min_rows=1,
                                  mesh_devices=1, pipeline_mode="off"):
            with ServingSession(max_concurrent=4) as sess:
                sess.run(mk())
                futs = [sess.submit(mk(), tenant=f"t{i % 3}")
                        for i in range(24)]
                outs = [f.to_pydict() for f in futs]
        assert all(o == ref for o in outs)
        # serving threads ran under span_scope(None): the profiled query's
        # recorder must not have received any serve-query spans
        assert profiler_rec.drain() == []
        # no lost updates: every serve query observed exactly once
        assert state.query_latency._count == 25
        serving = state.serving()
        assert sum(s["queries"] for s in serving.values()) == 25
        assert all(0 <= s["prepared_hit_rate"] <= 1 for s in serving.values())
    finally:
        set_spans(None)
        detach_subscriber(state)


def test_decision_caches_thread_safe_under_hammer():
    from daft_tpu.execution.executor import _BoundedDecisionCache

    cache = _BoundedDecisionCache(cap=64)
    errs = []

    def hammer(tid):
        try:
            for i in range(3000):
                cache.put((tid, i), i % 2 == 0)
                cache.get((tid, i - 7))
                len(cache)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    ts = [threading.Thread(target=hammer, args=(t,)) for t in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs
    assert len(cache) <= 64


def test_histogram_concurrent_observe_no_lost_updates():
    from daft_tpu.observability.metrics import Histogram

    h = Histogram()
    N, T = 2000, 8

    def obs():
        for i in range(N):
            h.observe(0.001 * (i % 50))

    ts = [threading.Thread(target=obs) for _ in range(T)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert h._count == N * T


# ---------------------------------------------------------------------------
# /metrics: serving gauges/counters + per-tenant latency label (satellite)
# ---------------------------------------------------------------------------

def test_metrics_exposition_has_serving_series():
    from daft_tpu.observability.dashboard import launch

    df = _table(5000)
    dash = launch(port=0)
    try:
        with ServingSession(max_concurrent=2) as sess:
            sess.run(df.agg(col("v").sum().alias("s")), tenant="acme")
            sess.run(df.agg(col("v").sum().alias("s")), tenant="globex")
        body = urllib.request.urlopen(dash.url + "/metrics").read().decode()
        assert "# TYPE daft_tpu_serve_queue_depth gauge" in body
        assert "# TYPE daft_tpu_admission_waits_total counter" in body
        assert "# TYPE daft_tpu_serve_prepared_hits counter" in body
        # the tenant label on the query-latency histogram family — one TYPE
        # line, labeled + unlabeled series under it
        assert body.count("# TYPE daft_tpu_query_latency_seconds histogram") == 1
        assert 'daft_tpu_query_latency_seconds_bucket{tenant="acme",le=' in body
        assert 'daft_tpu_query_latency_seconds_count{tenant="acme"}' in body
        serving = json.loads(
            urllib.request.urlopen(dash.url + "/api/serving").read())
        assert set(serving) >= {"acme", "globex"}
        assert serving["acme"]["queries"] >= 1
        assert "prepared_hit_rate" in serving["acme"]
    finally:
        dash.shutdown()


# ---------------------------------------------------------------------------
# Speculative re-execution (satellite): straggler duplicate-dispatch with
# first-result-wins on the pool dispatcher
# ---------------------------------------------------------------------------

class _LatchTask:
    """DataSource-style scan task: the FIRST attempt creates the latch file
    and stalls; a later (speculative) attempt sees the latch and returns
    immediately — so the duplicate deterministically wins the race."""

    filters_applied = True
    size_bytes = None

    def __init__(self, rows, latch=None, delay=0.0):
        self.rows = rows
        self.latch = latch
        self.delay = delay

    def read(self):
        from daft_tpu.core.micropartition import MicroPartition

        if self.latch is not None:
            if not os.path.exists(self.latch):
                open(self.latch, "w").close()
                time.sleep(self.delay)
        yield MicroPartition.from_pydict({"x": list(range(self.rows))})


def _scan_plan(task):
    from daft_tpu.core.micropartition import MicroPartition
    from daft_tpu.plan import physical as pp

    schema = MicroPartition.from_pydict({"x": [0]}).schema
    return pp.TaskScan([task], schema, None, None)


def test_speculative_duplicate_dispatch_first_result_wins(tmp_path, monkeypatch):
    from daft_tpu.distributed.task import SubPlanTask
    from daft_tpu.distributed.worker import WorkerPool

    monkeypatch.setenv("DAFT_TPU_SPECULATIVE_MIN_S", "0.1")
    monkeypatch.setenv("DAFT_TPU_STRAGGLER_K", "2.0")
    disp0 = registry().get("sched_speculative_dispatches")
    wins0 = registry().get("sched_speculative_wins")
    pool = WorkerPool(2)
    try:
        tasks = [SubPlanTask.from_plan(f"fast-{i}", _scan_plan(_LatchTask(10)))
                 for i in range(3)]
        straggler = SubPlanTask.from_plan(
            "straggler",
            _scan_plan(_LatchTask(10, latch=str(tmp_path / "latch"),
                                  # wide margin: the duplicate ends the stage
                                  # the moment it sees the latch, so a big
                                  # delay costs nothing on the passing path —
                                  # it only keeps a loaded machine (cold
                                  # worker imports) from letting the stalled
                                  # original finish first
                                  delay=45.0)))
        results = pool.run_tasks(tasks + [straggler], stage_id="spec")
        assert set(results) == {"fast-0", "fast-1", "fast-2", "straggler"}
        assert all(r.rows == 10 for r in results.values())
    finally:
        pool.shutdown()
    assert registry().get("sched_speculative_dispatches") - disp0 >= 1
    # the duplicate saw the latch and returned instantly -> it won
    assert registry().get("sched_speculative_wins") - wins0 >= 1


def test_speculation_disabled_by_env(tmp_path, monkeypatch):
    from daft_tpu.distributed.task import SubPlanTask
    from daft_tpu.distributed.worker import WorkerPool

    monkeypatch.setenv("DAFT_TPU_SPECULATIVE", "0")
    monkeypatch.setenv("DAFT_TPU_SPECULATIVE_MIN_S", "0.05")
    disp0 = registry().get("sched_speculative_dispatches")
    pool = WorkerPool(2)
    try:
        tasks = [SubPlanTask.from_plan(f"f{i}", _scan_plan(_LatchTask(5)))
                 for i in range(3)]
        slow = SubPlanTask.from_plan(
            "slow", _scan_plan(_LatchTask(5, latch=str(tmp_path / "l2"),
                                          delay=1.0)))
        results = pool.run_tasks(tasks + [slow], stage_id="nospec")
        assert len(results) == 4
    finally:
        pool.shutdown()
    assert registry().get("sched_speculative_dispatches") == disp0


# ---------------------------------------------------------------------------
# Concurrent distributed queries over one shared pool (tentpole: concurrent
# sub-plan streams interleaved fairly across workers)
# ---------------------------------------------------------------------------

def test_concurrent_distributed_queries_one_pool():
    from daft_tpu.distributed.runner import DistributedRunner

    runner = DistributedRunner(num_workers=2, n_partitions=2)
    try:
        df_a = daft_tpu.from_pydict({
            "k": [i % 11 for i in range(40_000)],
            "v": [float(i % 301) for i in range(40_000)],
        })
        df_b = daft_tpu.from_pydict({
            "k": [i % 7 for i in range(30_000)],
            "v": [float(i % 97) for i in range(30_000)],
        })
        qa = lambda: df_a.groupby("k").agg(col("v").sum().alias("s")).sort("k")
        qb = lambda: df_b.groupby("k").agg(col("v").max().alias("m")).sort("k")
        ref_a = qa().to_pydict()
        ref_b = qb().to_pydict()

        outs = {}
        errs = []

        def run(name, q):
            try:
                parts = runner.run(q()._builder)
                d = {}
                for p in parts:
                    for k, v in p.to_pydict().items():
                        d.setdefault(k, []).extend(v)
                outs[name] = d
            except Exception as e:  # noqa: BLE001
                errs.append((name, e))

        ts = [threading.Thread(target=run, args=("a", qa)),
              threading.Thread(target=run, args=("b", qb)),
              threading.Thread(target=run, args=("a2", qa))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(120)
        assert not errs, errs
        assert outs["a"] == ref_a and outs["a2"] == ref_a
        assert outs["b"] == ref_b
    finally:
        runner.shutdown()


def test_scheduler_round_robin_across_streams():
    """Two concurrent stage streams share worker capacity one-task-per-stream
    per rotation instead of FIFO head-of-line."""
    from daft_tpu.distributed.scheduler import Scheduler
    from daft_tpu.distributed.task import SubPlanTask

    s = Scheduler({"w0": 1, "w1": 1})
    for i in range(4):
        s.submit(SubPlanTask(task_id=f"a{i}", plan_blob=b""), stream_key="qa")
    for i in range(2):
        s.submit(SubPlanTask(task_id=f"b{i}", plan_blob=b""), stream_key="qb")
    assigned = s.schedule()
    assert len(assigned) == 2
    streams = {t.task_id[0] for t, _w in assigned}
    assert streams == {"a", "b"}   # one slot each, not two for the first query
    for _t, w in assigned:
        s.task_finished(w)
    assert len(s.schedule()) == 2
    assert s.pending_count() == 2


# ---------------------------------------------------------------------------
# Config knobs
# ---------------------------------------------------------------------------

def test_serving_config_validation():
    from daft_tpu.config import ExecutionConfig

    with pytest.raises(ValueError, match="max_concurrent_queries"):
        ExecutionConfig(max_concurrent_queries=0)
    with pytest.raises(ValueError, match="tenant_budget_bytes"):
        ExecutionConfig(tenant_budget_bytes=-1)
    with pytest.raises(ValueError, match="max_concurrent"):
        ServingSession(max_concurrent=0)
