"""Engine-invariant linter: fixture cases per rule + the tier-1 gate.

The gate test runs the whole engine over daft_tpu/ and asserts zero
non-baselined findings — the lint IS part of tier-1, so a PR that mutates a
module cache without a lock, reads an undocumented knob, or bumps an event
field without bumping SCHEMA_VERSION fails CI, not review.
"""

import json
import os
import subprocess
import sys

import pytest

from daft_tpu.tools.lint import lint, lint_source
from daft_tpu.tools.lint.engine import (ModuleContext, ProjectContext,
                                        apply_baseline, LintResult)
from daft_tpu.tools.lint.obs_rules import (check_schema_drift,
                                           event_schema_fingerprint,
                                           read_schema_version)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def rules_of(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------

UNLOCKED_CACHE = """
_CACHE = {}

def put(k, v):
    _CACHE[k] = v
"""

LOCKED_CACHE = """
import threading

_CACHE = {}
_LOCK = threading.Lock()

def put(k, v):
    with _LOCK:
        _CACHE[k] = v
"""


def test_lock_discipline_unlocked_mutation_caught():
    findings = lint_source(UNLOCKED_CACHE)
    assert "lock-discipline" in rules_of(findings)
    (f,) = [f for f in findings if f.rule == "lock-discipline"]
    assert "_CACHE" in f.message


def test_lock_discipline_locked_mutation_passes():
    assert "lock-discipline" not in rules_of(lint_source(LOCKED_CACHE))


def test_lock_discipline_import_time_population_exempt():
    src = "_CACHE = {}\n_CACHE['a'] = 1\n"  # module scope = import lock
    assert "lock-discipline" not in rules_of(lint_source(src))


def test_lock_discipline_method_mutations_and_del():
    src = """
_ITEMS = []

def f():
    _ITEMS.append(1)

def g(k):
    del _ITEMS[k]
"""
    findings = [f for f in lint_source(src) if f.rule == "lock-discipline"]
    assert len(findings) == 2


def test_lock_discipline_closure_defined_under_lock_not_credited():
    # the `with` wraps the function DEFINITION, not its execution — the
    # mutation inside the closure body runs lockless (review fix: the
    # first-parent hop used to skip the function-boundary check)
    src = """
import threading

_CACHE = {}
_LOCK = threading.Lock()

with _LOCK:
    def on_event(k, v):
        _CACHE[k] = v
"""
    assert "lock-discipline" in rules_of(lint_source(src))


def test_lock_discipline_wrong_lock_not_credited():
    src = """
import threading

_CACHE = {}

def put(self, k, v):
    with self._lock:   # instance lock cannot guard a module global
        _CACHE[k] = v
"""
    assert "lock-discipline" in rules_of(lint_source(src))


# ---------------------------------------------------------------------------
# blocking-under-lock
# ---------------------------------------------------------------------------

def test_blocking_pickle_under_lock_caught():
    src = """
import pickle
import threading

_LOCK = threading.Lock()

def send(conn, msg):
    with _LOCK:
        buf = pickle.dumps(msg)
        conn.send_bytes(buf)
"""
    findings = [f for f in lint_source(src) if f.rule == "blocking-under-lock"]
    assert len(findings) == 2  # dumps + send_bytes


def test_blocking_outside_lock_passes():
    src = """
import pickle
import threading

_LOCK = threading.Lock()

def send(conn, msg):
    buf = pickle.dumps(msg)
    with _LOCK:
        n = len(buf)
    conn.send_bytes(buf)
"""
    assert "blocking-under-lock" not in rules_of(lint_source(src))


def test_blocking_in_nested_def_under_lock_passes():
    # defining a closure under the lock is not running it under the lock
    src = """
import pickle
import threading

_LOCK = threading.Lock()

def make(msg):
    with _LOCK:
        def later():
            return pickle.dumps(msg)
    return later
"""
    assert "blocking-under-lock" not in rules_of(lint_source(src))


def test_blocking_under_self_lock_caught():
    src = """
class W:
    def flush(self):
        with self._lock:
            open("/tmp/x", "w")
"""
    assert "blocking-under-lock" in rules_of(lint_source(src))


# ---------------------------------------------------------------------------
# env-discipline
# ---------------------------------------------------------------------------

def test_env_discipline_raw_parse_caught():
    src = """
import os

N = int(os.environ.get("DAFT_TPU_THING", 4))
F = float(os.environ.get("DAFT_TPU_OTHER", 1.5))
"""
    findings = [f for f in lint_source(src, readme_text="DAFT_TPU_THING DAFT_TPU_OTHER")
                if f.rule == "env-discipline"]
    assert len(findings) == 2
    assert "env_int" in findings[0].message


def test_env_discipline_getenv_spelling_caught():
    src = 'import os\nN = int(os.getenv("DAFT_TPU_THING", "3"))\n'
    findings = [f for f in lint_source(src, readme_text="DAFT_TPU_THING")
                if f.rule == "env-discipline"]
    assert len(findings) == 1


def test_env_discipline_helper_passes():
    src = """
from daft_tpu.utils.env import env_int

N = env_int("DAFT_TPU_THING", 4)
"""
    findings = lint_source(src, readme_text="DAFT_TPU_THING")
    assert "env-discipline" not in rules_of(findings)


# ---------------------------------------------------------------------------
# knob-registry
# ---------------------------------------------------------------------------

def test_knob_registry_undocumented_caught():
    src = 'import os\nX = os.environ.get("DAFT_TPU_SECRET_KNOB", "")\n'
    findings = [f for f in lint_source(src, readme_text="DAFT_TPU_OTHER")
                if f.rule == "knob-registry"]
    assert len(findings) == 1
    assert "DAFT_TPU_SECRET_KNOB" in findings[0].message


def test_knob_registry_documented_passes():
    src = 'import os\nX = os.environ.get("DAFT_TPU_SECRET_KNOB", "")\n'
    findings = lint_source(src, readme_text="| `DAFT_TPU_SECRET_KNOB` | ... |")
    assert "knob-registry" not in rules_of(findings)


# ---------------------------------------------------------------------------
# counter-discipline
# ---------------------------------------------------------------------------

def test_counter_discipline_undeclared_caught():
    src = """
from daft_tpu.observability.metrics import registry

def f():
    registry().inc("mystery_counter")
    registry().set_gauge("mystery_gauge", 1.0)
"""
    findings = [f for f in lint_source(src, declared_counters={"known"},
                                       declared_gauges={"g"})
                if f.rule == "counter-discipline"]
    assert len(findings) == 2


def test_counter_discipline_declared_passes():
    src = """
from daft_tpu.observability.metrics import registry

def f():
    registry().inc("known")
    registry().set_gauge_max("g", 2.0)
"""
    findings = lint_source(src, declared_counters={"known"},
                           declared_gauges={"g"})
    assert "counter-discipline" not in rules_of(findings)


def test_counter_discipline_dynamic_name_skipped():
    src = """
from daft_tpu.observability.metrics import registry

def f(k):
    registry().inc(f"shuffle_{k}", 1)
"""
    findings = lint_source(src, declared_counters=set(), declared_gauges=set())
    assert "counter-discipline" not in rules_of(findings)


def test_declared_vocabulary_collected_from_metrics_module():
    """The real metrics.py declares the vocabulary the rule checks against —
    resolved through the group-tuple names (DEVICE_COUNTER_NAMES + ...)."""
    with open(os.path.join(REPO, "daft_tpu/observability/metrics.py")) as fh:
        src = fh.read()
    ctx = ModuleContext("daft_tpu/observability/metrics.py",
                        "daft_tpu.observability.metrics", src)
    project = ProjectContext("", [ctx])
    assert "device_stage_batches" in project.declared_counters
    assert "shuffle_wire_bytes" in project.declared_counters
    assert "subscriber_errors" in project.declared_counters
    assert "hbm_bytes_resident" in project.declared_gauges


# ---------------------------------------------------------------------------
# import-discipline
# ---------------------------------------------------------------------------

def test_import_discipline_toplevel_jax_caught():
    src = "import jax\n"
    findings = lint_source(src, rel="daft_tpu/io/foo.py",
                           module="daft_tpu.io.foo")
    assert "import-discipline" in rules_of(findings)


def test_import_discipline_toplevel_tier_module_caught():
    src = "from ..ops.stage import pad_bucket\n"
    findings = lint_source(src, rel="daft_tpu/io/foo.py",
                           module="daft_tpu.io.foo")
    assert "import-discipline" in rules_of(findings)


def test_import_discipline_function_local_passes():
    src = """
def f():
    from ..ops.stage import pad_bucket
    return pad_bucket(7)
"""
    findings = lint_source(src, rel="daft_tpu/io/foo.py",
                           module="daft_tpu.io.foo")
    assert "import-discipline" not in rules_of(findings)


def test_import_discipline_tier_member_exempt():
    src = "import jax\nfrom .stage import pad_bucket\n"
    findings = lint_source(src, rel="daft_tpu/ops/mesh_stage.py",
                           module="daft_tpu.ops.mesh_stage")
    assert "import-discipline" not in rules_of(findings)


def test_import_discipline_type_checking_exempt():
    src = """
from typing import TYPE_CHECKING

if TYPE_CHECKING:
    import jax
"""
    findings = lint_source(src, rel="daft_tpu/io/foo.py",
                           module="daft_tpu.io.foo")
    assert "import-discipline" not in rules_of(findings)


# ---------------------------------------------------------------------------
# broad-except
# ---------------------------------------------------------------------------

def test_broad_except_silent_caught():
    src = """
def f():
    try:
        risky()
    except Exception:
        pass
"""
    assert "broad-except" in rules_of(lint_source(src))


@pytest.mark.parametrize("body", [
    "raise",
    "log.warning('boom')",
    "registry().inc('errors_total')",
    "return str(e)",
    "conn.send(traceback.format_exc())",
])
def test_broad_except_handled_passes(body):
    as_e = " as e" if "e" in body.split("(")[0] else ""
    src = f"""
def f():
    try:
        risky()
    except Exception{as_e}:
        {body}
"""
    assert "broad-except" not in rules_of(
        lint_source(src, declared_counters={"errors_total"}))


def test_broad_except_narrow_passes():
    src = """
def f():
    try:
        risky()
    except (OSError, ValueError):
        pass
"""
    assert "broad-except" not in rules_of(lint_source(src))


# ---------------------------------------------------------------------------
# atomic-publish
# ---------------------------------------------------------------------------

def test_atomic_publish_raw_write_caught():
    src = """
def publish(path, data):
    with open(path, "wb") as f:
        f.write(data)
"""
    findings = lint_source(src, rel="daft_tpu/distributed/shuffle.py",
                           module="daft_tpu.distributed.shuffle")
    assert "atomic-publish" in rules_of(findings)


def test_atomic_publish_tmp_then_replace_passes():
    src = """
import os

def publish(path, data):
    tmp = path + ".tmp-x"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)

def read(path):
    with open(path, "rb") as f:
        return f.read()
"""
    findings = lint_source(src, rel="daft_tpu/distributed/shuffle.py",
                           module="daft_tpu.distributed.shuffle")
    assert "atomic-publish" not in rules_of(findings)


def test_atomic_publish_os_rename_caught():
    src = "import os\n\ndef f(a, b):\n    os.rename(a, b)\n"
    findings = lint_source(src, rel="daft_tpu/checkpoint/stages.py",
                           module="daft_tpu.checkpoint.stages")
    assert "atomic-publish" in rules_of(findings)


def test_atomic_publish_other_modules_unscoped():
    src = "def f(p, d):\n    open(p, 'w').write(d)\n"
    findings = lint_source(src, rel="daft_tpu/io/foo.py",
                           module="daft_tpu.io.foo")
    assert "atomic-publish" not in rules_of(findings)


# ---------------------------------------------------------------------------
# schema-drift
# ---------------------------------------------------------------------------

EVENTS_SRC = """
from dataclasses import dataclass

@dataclass(frozen=True)
class QueryEnd:
    query_id: str
    rows: int
"""

LOG_SRC = "SCHEMA_VERSION = 3\n"


def _schema_project(events_src, log_src, pin):
    events = ModuleContext("daft_tpu/observability/events.py",
                           "daft_tpu.observability.events", events_src)
    log = ModuleContext("daft_tpu/observability/event_log.py",
                        "daft_tpu.observability.event_log", log_src)
    return ProjectContext("", [events, log], schema_pin=pin)


def test_schema_drift_in_sync_passes():
    events = ModuleContext("daft_tpu/observability/events.py",
                           "daft_tpu.observability.events", EVENTS_SRC)
    pin = {"schema_version": 3, "fingerprint": event_schema_fingerprint(events)}
    assert check_schema_drift(_schema_project(EVENTS_SRC, LOG_SRC, pin)) == []


def test_schema_drift_field_added_without_bump_caught():
    events = ModuleContext("daft_tpu/observability/events.py",
                           "daft_tpu.observability.events", EVENTS_SRC)
    pin = {"schema_version": 3, "fingerprint": event_schema_fingerprint(events)}
    grown = EVENTS_SRC + "    seconds: float\n"
    findings = check_schema_drift(_schema_project(grown, LOG_SRC, pin))
    assert [f.rule for f in findings] == ["schema-drift"]
    assert "without bumping" in findings[0].message


def test_schema_drift_bump_requires_repin():
    events = ModuleContext("daft_tpu/observability/events.py",
                           "daft_tpu.observability.events", EVENTS_SRC)
    pin = {"schema_version": 3, "fingerprint": event_schema_fingerprint(events)}
    findings = check_schema_drift(
        _schema_project(EVENTS_SRC, "SCHEMA_VERSION = 4\n", pin))
    assert [f.rule for f in findings] == ["schema-drift"]
    assert "re-pin" in findings[0].message


def test_schema_pin_matches_tree():
    """The committed schema_pin.json matches the committed event modules —
    i.e. the repo itself would pass the drift rule from a cold checkout."""
    with open(os.path.join(REPO, "daft_tpu/tools/lint/schema_pin.json")) as fh:
        pin = json.load(fh)
    with open(os.path.join(REPO, "daft_tpu/observability/events.py")) as fh:
        events = ModuleContext("daft_tpu/observability/events.py",
                               "daft_tpu.observability.events", fh.read())
    with open(os.path.join(REPO, "daft_tpu/observability/event_log.py")) as fh:
        log = ModuleContext("daft_tpu/observability/event_log.py",
                            "daft_tpu.observability.event_log", fh.read())
    assert pin["fingerprint"] == event_schema_fingerprint(events)
    assert pin["schema_version"] == read_schema_version(log)


# ---------------------------------------------------------------------------
# suppressions + baseline
# ---------------------------------------------------------------------------

def test_suppression_with_justification_honored():
    src = """
_CACHE = {}

def put(k, v):
    _CACHE[k] = v  # lint: ignore[lock-discipline] -- single-threaded tool
"""
    assert rules_of(lint_source(src)) == []


def test_suppression_standalone_comment_covers_next_code_line():
    src = """
_CACHE = {}

def put(k, v):
    # lint: ignore[lock-discipline] -- populated before any thread starts,
    # and the justification may wrap over several comment lines
    _CACHE[k] = v
"""
    assert rules_of(lint_source(src)) == []


def test_suppression_without_justification_is_a_finding():
    src = """
_CACHE = {}

def put(k, v):
    _CACHE[k] = v  # lint: ignore[lock-discipline]
"""
    assert "bad-suppression" in rules_of(lint_source(src))


def test_unused_suppression_is_a_finding():
    src = "X = 1  # lint: ignore[lock-discipline] -- nothing fires here\n"
    findings = lint_source(src)
    assert rules_of(findings) == ["bad-suppression"]
    assert "unused" in findings[0].message


def test_baseline_grandfathers_exact_count():
    findings = lint_source(UNLOCKED_CACHE)
    key = ("daft_tpu/_fixture.py", "lock-discipline")
    result = LintResult()
    kept = apply_baseline(findings, {key: {"count": 1, "why": "legacy"}}, result)
    assert kept == []
    assert result.grandfathered[key] == 1


def test_baseline_exceeded_fails():
    src = UNLOCKED_CACHE + "\ndef put2(k, v):\n    _CACHE[k] = v\n"
    findings = [f for f in lint_source(src) if f.rule == "lock-discipline"]
    assert len(findings) == 2
    result = LintResult()
    kept = apply_baseline(
        findings, {("daft_tpu/_fixture.py", "lock-discipline"):
                   {"count": 1, "why": "legacy"}}, result)
    assert len(kept) == 3  # both findings + the exceeds-baseline note
    assert any("exceed" in f.message for f in kept)


# ---------------------------------------------------------------------------
# metrics vocabulary regression (satellite): /metrics exposes every declared
# series at zero before the first increment
# ---------------------------------------------------------------------------

def test_declared_series_scrapeable_at_zero():
    from daft_tpu.observability.metrics import (DECLARED_COUNTERS,
                                                DECLARED_GAUGES,
                                                MetricsRegistry,
                                                declare_vocabulary)

    fresh = MetricsRegistry()
    declare_vocabulary(fresh)
    counters, gauges = fresh.export()
    for name in DECLARED_COUNTERS:
        assert counters.get(name) == 0, name
    for name in DECLARED_GAUGES:
        assert gauges.get(name) == 0.0, name
    # the process registry (import side effect) carries them too: the
    # previously-undeclared recovery/observability names included
    from daft_tpu.observability.metrics import registry
    snap = registry().snapshot()
    for name in ("subscriber_errors", "checkpoint_restore_failures",
                 "shuffle_fetch_server_requests", "hbm_cache_hits"):
        assert name in snap, name


def test_prometheus_text_contains_declared_series():
    from daft_tpu.observability.metrics import (MetricsRegistry,
                                                declare_vocabulary,
                                                prometheus_text)
    import daft_tpu.observability.metrics as m

    fresh = MetricsRegistry()
    declare_vocabulary(fresh)
    old = m._REGISTRY
    m._REGISTRY = fresh
    try:
        text = prometheus_text()
    finally:
        m._REGISTRY = old
    assert "daft_tpu_subscriber_errors 0" in text
    assert "daft_tpu_checkpoint_restore_failures 0" in text
    assert "# TYPE daft_tpu_hbm_bytes_resident gauge" in text


# ---------------------------------------------------------------------------
# tier-1 gate: the tree itself lints clean
# ---------------------------------------------------------------------------

def test_repo_lints_clean():
    """Zero non-baselined findings over daft_tpu/ — the acceptance gate."""
    result = lint(REPO, [os.path.join(REPO, "daft_tpu")],
                  baseline_path=os.path.join(
                      REPO, "daft_tpu/tools/lint/baseline.json"))
    msgs = "\n".join(f.render() for f in result.findings)
    assert result.ok, f"lint findings:\n{msgs}"


def test_cli_json_mode():
    """`python -m daft_tpu.tools.lint --json` exits 0 on the clean tree and
    emits the per-rule counts tooling diffs across PRs."""
    proc = subprocess.run(
        [sys.executable, "-m", "daft_tpu.tools.lint", "--json"],
        capture_output=True, text=True, cwd=REPO, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["ok"] is True
    assert payload["findings"] == []
    assert isinstance(payload["counts"], dict)
    assert payload["suppressed"] > 0  # the justified escape hatches exist
