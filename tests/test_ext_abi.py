"""Native extension ABI: load a C++ module, register + evaluate its functions.

Reference parity: src/daft-ext/src/abi/mod.rs (FFI_Module / FFI_ScalarFunction
over the Arrow C Data Interface) — the contract here is
native/include/daft_tpu_ext.h, loaded by daft_tpu/ext.py.
"""

import os
import shutil
import subprocess

import pytest

import daft_tpu
from daft_tpu import col

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def ext_path(tmp_path_factory):
    if shutil.which("g++") is None:
        pytest.skip("g++ not available")
    out = str(tmp_path_factory.mktemp("ext") / "libexample_ext.so")
    subprocess.run(
        ["g++", "-O2", "-shared", "-fPIC", f"-I{REPO}/native/include",
         f"{REPO}/native/ext_example/example_ext.cpp", "-o", out],
        check=True, capture_output=True)
    return out


def test_load_and_call(ext_path):
    ext = daft_tpu.load_extension(ext_path)
    assert ext.name == "example_ext"
    assert set(ext.functions) == {"ext_double", "ext_add"}

    df = daft_tpu.from_pydict({"x": [1.0, 2.0, None], "y": [10.0, 20.0, 30.0]})
    out = df.select(
        daft_tpu.call_function("ext_double", col("x")),
        daft_tpu.call_function("ext_add", col("x"), col("y")).alias("s"),
    ).to_pydict()
    assert out["x"] == [2.0, 4.0, None]
    assert out["s"] == [11.0, 22.0, None]


def test_int_path_and_schema(ext_path):
    daft_tpu.load_extension(ext_path)
    df = daft_tpu.from_pydict({"i": [3, 4]})
    q = df.select(daft_tpu.call_function("ext_double", col("i")))
    assert q.schema["i"].dtype == daft_tpu.DataType.int64()
    assert q.to_pydict()["i"] == [6, 8]


def test_module_error_surface(ext_path):
    daft_tpu.load_extension(ext_path)
    df = daft_tpu.from_pydict({"s": ["a"]})
    with pytest.raises(ValueError, match="ext_double"):
        df.select(daft_tpu.call_function("ext_double", col("s"))).to_pydict()


def test_bad_library_rejected(tmp_path):
    p = tmp_path / "not_a_module.so"
    p.write_bytes(b"not elf")
    with pytest.raises((OSError, ValueError)):
        daft_tpu.load_extension(str(p))
