"""Distributed engine tests: hermetic scheduler units + multi-process end-to-end.

Mirrors the reference's test strategy (SURVEY.md §4): the scheduler is tested
against mock worker snapshots with no processes (reference
scheduling/scheduler/mod.rs:257-298), shuffle and plan execution run on a real
spawn-based WorkerPool.
"""

import numpy as np
import pytest

import daft_tpu
from daft_tpu import col
from daft_tpu.distributed.scheduler import Scheduler
from daft_tpu.distributed.task import Spread, SubPlanTask, WorkerAffinity


def _task(tid, strategy=None, priority=0, excluded=()):
    return SubPlanTask(task_id=tid, plan_blob=b"", strategy=strategy or Spread(),
                       priority=priority, excluded_workers=tuple(excluded))


# ---------------------------------------------------------------------------
# Scheduler (hermetic, no processes)
# ---------------------------------------------------------------------------

def test_spread_picks_most_available_slots():
    s = Scheduler({"w0": 2, "w1": 4})
    s.submit(_task("t0"))
    [(t, wid)] = s.schedule()
    assert wid == "w1"  # 4 free > 2 free


def test_spread_balances_as_slots_fill():
    s = Scheduler({"w0": 2, "w1": 2})
    for i in range(4):
        s.submit(_task(f"t{i}"))
    assigned = s.schedule()
    by_worker = {}
    for t, wid in assigned:
        by_worker.setdefault(wid, []).append(t.task_id)
    assert len(assigned) == 4
    assert len(by_worker["w0"]) == 2 and len(by_worker["w1"]) == 2


def test_excess_tasks_stay_pending_until_capacity_frees():
    s = Scheduler({"w0": 1})
    s.submit(_task("t0"))
    s.submit(_task("t1"))
    assigned = s.schedule()
    assert [t.task_id for t, _ in assigned] == ["t0"]
    assert s.pending_count() == 1
    assert s.schedule() == []  # still full
    s.task_finished("w0")
    [(t, wid)] = s.schedule()
    assert t.task_id == "t1" and wid == "w0"


def test_priority_order():
    s = Scheduler({"w0": 1})
    s.submit(_task("low", priority=10))
    s.submit(_task("high", priority=0))
    [(t, _)] = s.schedule()
    assert t.task_id == "high"


def test_soft_affinity_prefers_worker_but_falls_back():
    s = Scheduler({"w0": 1, "w1": 1})
    s.submit(_task("t0", strategy=WorkerAffinity("w0")))
    [(_, wid)] = s.schedule()
    assert wid == "w0"
    # w0 now full: soft affinity falls back to any free worker
    s.submit(_task("t1", strategy=WorkerAffinity("w0")))
    [(_, wid2)] = s.schedule()
    assert wid2 == "w1"


def test_hard_affinity_waits_for_its_worker():
    s = Scheduler({"w0": 1, "w1": 1})
    s.submit(_task("t0", strategy=WorkerAffinity("w0", hard=True)))
    [(_, wid)] = s.schedule()
    assert wid == "w0"
    s.submit(_task("t1", strategy=WorkerAffinity("w0", hard=True)))
    assert s.schedule() == []  # w1 free but hard affinity refuses it
    s.task_finished("w0")
    [(_, wid2)] = s.schedule()
    assert wid2 == "w0"


def test_excluded_workers_skipped():
    s = Scheduler({"w0": 4, "w1": 1})
    s.submit(_task("t0", excluded=["w0"]))
    [(_, wid)] = s.schedule()
    assert wid == "w1"  # w0 has more slots but is excluded (failed there before)


# ---------------------------------------------------------------------------
# Cache-affinity placement (hermetic: fingerprints vs heartbeat digests)
# ---------------------------------------------------------------------------

def _fp_task(tid, fp, excluded=()):
    return SubPlanTask(task_id=tid, plan_blob=b"", strategy=Spread(),
                       rfingerprint=tuple(fp), excluded_workers=tuple(excluded))


def test_soft_affinity_wins_when_slots_free():
    """A task whose fingerprint intersects a worker's residency digest lands
    there, even though spread would pick the emptier worker."""
    s = Scheduler({"w0": 4, "w1": 2})
    s.update_residency("w1", [(101, 1 << 20)])
    s.submit(_fp_task("t0", [(101, 1 << 20), (999, 64)]))
    [(_, wid)] = s.schedule()
    assert wid == "w1"
    stats = s.placement_stats()
    assert stats["affinity_hits"] == 1
    assert stats["bytes_avoided"] == 1 << 20


def test_affinity_falls_back_to_spread_when_preferred_full():
    """Saturated resident worker: the task spreads instead of waiting (soft
    policy — no head-of-line blocking), recorded as an affinity miss."""
    s2 = Scheduler({"w0": 2, "w1": 1})
    s2.update_residency("w1", [(7, 1 << 20)])
    s2._workers["w1"].active_tasks = 1  # saturated resident worker
    s2.submit(_fp_task("t0", [(7, 1 << 20)]))
    [(_, wid)] = s2.schedule()
    assert wid == "w0"
    stats = s2.placement_stats()
    assert stats["affinity_hits"] == 0 and stats["affinity_misses"] == 1


def test_affinity_load_penalty_prefers_idle_when_overlap_small():
    """A tiny resident overlap does not justify queueing behind a loaded
    worker: score = bytes − penalty·load must be positive to win."""
    s = Scheduler({"w0": 4, "w1": 4})
    s.update_residency("w1", [(5, 1024)])  # 1KiB resident, far below penalty
    s._workers["w1"].active_tasks = 2      # loaded but not full
    s.submit(_fp_task("t0", [(5, 1024)]))
    [(_, wid)] = s.schedule()
    assert wid == "w0"  # spread wins: locality value below the load penalty


def test_affinity_respects_excluded_workers():
    """A requeued task never returns to the failed worker, resident planes or
    not."""
    s = Scheduler({"w0": 1, "w1": 1})
    s.update_residency("w0", [(42, 1 << 20)])
    s.submit(_fp_task("t0", [(42, 1 << 20)], excluded=["w0"]))
    [(_, wid)] = s.schedule()
    assert wid == "w1"
    assert s.placement_stats()["affinity_hits"] == 0


def test_hard_affinity_blocks_despite_resident_elsewhere():
    """Hard affinity still pins to its worker: residency elsewhere is
    irrelevant."""
    s = Scheduler({"w0": 1, "w1": 1})
    s._workers["w0"].active_tasks = 1
    s.update_residency("w1", [(9, 1 << 20)])
    t = SubPlanTask(task_id="t0", plan_blob=b"",
                    strategy=WorkerAffinity("w0", hard=True),
                    rfingerprint=((9, 1 << 20),))
    s.submit(t)
    assert s.schedule() == []  # waits for w0; never steals w1
    s.task_finished("w0")
    [(_, wid)] = s.schedule()
    assert wid == "w0"


def test_hard_affinity_skip_set_avoids_head_of_line_spin():
    """Once one hard-affinity task finds its preferred worker full, later
    heap entries bound to the same worker are requeued without an eligibility
    scan (counted), and all run once the worker frees up."""
    s = Scheduler({"w0": 1, "w1": 1})
    s._workers["w0"].active_tasks = 1
    for i in range(4):
        s.submit(_task(f"h{i}", strategy=WorkerAffinity("w0", hard=True)))
    assert s.schedule() == []
    # first task discovered the full worker; the other three skipped via the set
    assert s.placement_stats()["affinity_skips"] == 3
    assert s.pending_count() == 4
    s.task_finished("w0")
    done = []
    while s.pending_count():
        for t, wid in s.schedule():
            assert wid == "w0"
            done.append(t.task_id)
            s.task_finished("w0")
    assert sorted(done) == ["h0", "h1", "h2", "h3"]


# ---------------------------------------------------------------------------
# End-to-end on a real worker pool
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def dist_runner():
    from daft_tpu.distributed import DistributedRunner

    r = DistributedRunner(num_workers=4, n_partitions=4)
    yield r
    r.shutdown()


def _run_both(df_build, dist_runner):
    import daft_tpu.runners as runners

    native = runners.NativeRunner()
    runners.set_runner(native)
    expect = df_build().to_pydict()
    runners.set_runner(dist_runner)
    try:
        got = df_build().to_pydict()
    finally:
        runners.set_runner(native)
    return got, expect


def test_distributed_groupby_matches_native(dist_runner):
    rng = np.random.default_rng(0)
    n = 10_000
    data = daft_tpu.from_pydict({
        "k": rng.choice(["a", "b", "c", "d", "e"], n).tolist(),
        "v": rng.uniform(0, 100, n).tolist(),
    })

    def q():
        return (data.groupby("k")
                .agg(col("v").sum().alias("s"), col("v").mean().alias("m"),
                     col("v").count().alias("c"), col("v").min().alias("lo"),
                     col("v").max().alias("hi"))
                .sort("k"))

    got, expect = _run_both(q, dist_runner)
    assert got["k"] == expect["k"]
    assert got["c"] == expect["c"]
    for c in ("s", "m", "lo", "hi"):
        np.testing.assert_allclose(got[c], expect[c], rtol=1e-12)


def test_distributed_join_matches_native(dist_runner):
    rng = np.random.default_rng(1)
    n = 5_000
    left = daft_tpu.from_pydict({
        "id": rng.integers(0, 1000, n).tolist(),
        "x": rng.uniform(0, 10, n).tolist(),
    })
    right = daft_tpu.from_pydict({
        "id": list(range(1000)),
        "name": [f"n{i}" for i in range(1000)],
    })

    def q():
        return (left.join(right, on="id")
                .groupby("name").agg(col("x").sum().alias("sx"))
                .sort("name"))

    got, expect = _run_both(q, dist_runner)
    assert got["name"] == expect["name"]
    # summation order differs across partitionings (broadcast vs shuffle)
    np.testing.assert_allclose(got["sx"], expect["sx"], rtol=1e-9)


def test_distributed_tpch_q5_shape(dist_runner):
    """TPC-H Q5 (multi-join + grouped agg) across 4 worker processes with
    hash-shuffle joins — the VERDICT r2 'done' criterion for the distributed
    skeleton."""
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from benchmarking.tpch.datagen import load_dataframes
    from benchmarking.tpch.queries import ALL_QUERIES

    tables = {k: v.collect() for k, v in load_dataframes(sf=0.01, seed=0).items()}

    def q():
        return ALL_QUERIES[5](tables)

    got, expect = _run_both(q, dist_runner)
    assert got["n_name"] == expect["n_name"]
    np.testing.assert_allclose(got["revenue"], expect["revenue"], rtol=1e-9)


def test_distributed_left_outer_join_matches_native(dist_runner):
    left = daft_tpu.from_pydict({"id": [1, 2, 3, 4], "x": [1.0, 2.0, 3.0, 4.0]})
    right = daft_tpu.from_pydict({"id": [2, 4, 6], "y": ["b", "d", "f"]})

    def q():
        return left.join(right, on="id", how="left").sort("id")

    got, expect = _run_both(q, dist_runner)
    assert got == expect


def test_distributed_dedup_matches_native(dist_runner):
    data = daft_tpu.from_pydict({
        "k": ["a", "b", "a", "c", "b", "a"] * 100,
        "v": list(range(600)),
    })

    def q():
        return data.select("k").distinct().sort("k")

    got, expect = _run_both(q, dist_runner)
    assert got == expect


def test_worker_failure_requeues_on_another_worker():
    """A dead worker's in-flight tasks re-queue with that worker excluded
    (reference: scheduler snapshot re-queue semantics)."""
    from daft_tpu.distributed.worker import WorkerPool
    from daft_tpu.plan import physical as pp
    from daft_tpu.core.micropartition import MicroPartition
    from daft_tpu.core.recordbatch import RecordBatch
    from daft_tpu.schema import Schema
    from daft_tpu.datatype import DataType
    from daft_tpu.core.series import Series

    pool = WorkerPool(2)
    try:
        s = Series.from_pylist([1, 2, 3], "a", DataType.int64())
        schema = Schema([s.field()])
        part = MicroPartition(schema, [RecordBatch(schema, [s], 3)])
        plan = pp.InMemoryScan([part], schema)
        # kill one worker pre-submit; pool should notice and run elsewhere
        w0 = pool.workers["worker-0"]
        w0._proc.terminate()
        w0._proc.wait()
        tasks = [SubPlanTask.from_plan(f"t{i}", plan) for i in range(4)]
        results = pool.run_tasks(tasks)
        assert len(results) == 4
        assert all(r.rows == 3 for r in results.values())
    finally:
        pool.shutdown()


def test_task_error_propagates_with_traceback():
    from daft_tpu.core.micropartition import MicroPartition
    from daft_tpu.core.recordbatch import RecordBatch
    from daft_tpu.core.series import Series
    from daft_tpu.datatype import DataType
    from daft_tpu.distributed.worker import WorkerPool
    from daft_tpu.plan import physical as pp
    from daft_tpu.schema import Schema

    s = Series.from_pylist([1, 2, 3], "a", DataType.int64())
    schema = Schema([s.field()])
    part = MicroPartition(schema, [RecordBatch(schema, [s], 3)])
    # predicate references a column that does not exist -> fails in the worker
    bad = pp.PhysFilter(pp.InMemoryScan([part], schema),
                        col("missing") > 0, schema)
    pool = WorkerPool(1)
    try:
        with pytest.raises(RuntimeError, match="failed on worker-0"):
            pool.run_tasks([SubPlanTask.from_plan("boom", bad)])
    finally:
        pool.shutdown()


def test_distributed_tpch_sweep(dist_runner):
    """Several TPC-H shapes (scan-agg, join-agg-topn, multi-join) through the
    distributed runner must match the native runner."""
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from benchmarking.tpch.datagen import load_dataframes
    from benchmarking.tpch.queries import ALL_QUERIES

    tables = {k: v.collect() for k, v in load_dataframes(sf=0.01, seed=0).items()}
    for qnum in (1, 3, 10, 12):
        def q(qnum=qnum):
            return ALL_QUERIES[qnum](tables)

        got, expect = _run_both(q, dist_runner)
        assert list(got.keys()) == list(expect.keys()), qnum
        for c in expect:
            if expect[c] and isinstance(expect[c][0], float):
                np.testing.assert_allclose(got[c], expect[c], rtol=1e-9,
                                           err_msg=f"q{qnum}.{c}")
            else:
                assert got[c] == expect[c], f"q{qnum}.{c}"


def test_socket_shuffle_transport_matches_native():
    """shuffle_transport='socket': reduce tasks fetch partitions over the
    HMAC-authenticated fetch server ONLY — the ShuffleRead plans they execute
    carry no shuffle_dir, so any filesystem fallback would fail loudly
    (reference: flight_server.rs:72 + client fan-in)."""
    import daft_tpu.runners as runners
    from daft_tpu.distributed import DistributedRunner

    r = DistributedRunner(num_workers=2, n_partitions=3, shuffle_transport="socket")
    native = runners.NativeRunner()
    try:
        rng = np.random.default_rng(3)
        n = 8_000
        data = daft_tpu.from_pydict({
            "k": rng.integers(0, 300, n).tolist(),
            "v": rng.uniform(0, 10, n).tolist(),
        })
        dim = daft_tpu.from_pydict({"k": list(range(300)),
                                    "w": [float(i) for i in range(300)]})

        def q():
            return (data.join(dim, on="k")
                    .groupby("k").agg(col("v").sum().alias("s"),
                                      col("w").max().alias("mw"))
                    .sort("k"))

        runners.set_runner(native)
        expect = q().to_pydict()
        runners.set_runner(r)
        got = q().to_pydict()
        assert got["k"] == expect["k"]
        np.testing.assert_allclose(got["s"], expect["s"], rtol=1e-12)
        np.testing.assert_allclose(got["mw"], expect["mw"], rtol=1e-12)
    finally:
        runners.set_runner(native)
        r.shutdown()


def test_fetch_server_rejects_bad_auth_and_traversal():
    import tempfile

    from daft_tpu.distributed.fetch_server import ShuffleFetchServer, fetch_partition
    from daft_tpu.schema import Schema

    with tempfile.TemporaryDirectory() as td:
        srv = ShuffleFetchServer(td)
        try:
            host, port, key = srv.endpoint
            # wrong auth key never reaches the protocol
            import multiprocessing.connection as mpc

            with pytest.raises(Exception):
                c = mpc.Client((host, port), family="AF_INET", authkey=b"wrong-key")
                c.close()
            # traversal-shaped shuffle ids are refused server-side
            good = mpc.Client((host, port), family="AF_INET",
                              authkey=bytes.fromhex(key))
            good.send(("list", "../etc", 0))
            kind, detail = good.recv()
            assert kind == "error" and "bad shuffle id" in detail
            good.close()
        finally:
            srv.close()


def test_device_nodes_survive_distribution():
    """Shipped sub-plans KEEP DeviceGroupedAgg (VERDICT r4 next #5): the
    two-phase split's partial stage stays a device stage; workers decide
    device-vs-host from their leased config at runtime."""
    import numpy as np

    import daft_tpu
    from daft_tpu import col
    from daft_tpu.config import execution_config_ctx
    from daft_tpu.distributed.planner import DistContext, distribute
    from daft_tpu.distributed.runner import DistributedRunner
    from daft_tpu.plan import physical as pp
    from daft_tpu.plan.physical import translate

    rng = np.random.default_rng(3)
    n = 5000
    df = daft_tpu.from_pydict({
        "k": rng.integers(0, 9, n).tolist(),
        "v": rng.uniform(0, 1, n).tolist(),
    })
    q = df.where(col("v") > 0.2).groupby("k").agg(
        col("v").sum().alias("s"), col("v").count().alias("c"))

    with execution_config_ctx(device_mode="on"):
        phys = translate(q._builder.optimize().plan)
        assert any(isinstance(nd, pp.DeviceGroupedAgg) for nd in phys.walk())

        r = DistributedRunner(num_workers=2, device_workers=1)
        try:
            pool = r._ensure_pool()
            ctx = DistContext(pool=pool, shuffle_dir=r._shuffle_dir,
                              n_partitions=r.n_partitions)
            dist = distribute(ctx, phys)
            # the partial phase of at least one fragment kept the device stage
            kept = [nd for frag in dist.fragments for nd in frag.walk()
                    if isinstance(nd, pp.DeviceGroupedAgg)]
            shuffled = any(isinstance(nd, pp.ShuffleRead)
                           for frag in dist.fragments for nd in frag.walk())
            assert shuffled  # two-phase ran; partials already executed
            # end-to-end through the pool matches local execution
            out = sorted(zip(*[q.to_pydict()[c] for c in ("k", "s", "c")]))
            daft_tpu.runners.set_runner(r)
            try:
                got = sorted(zip(*[q.to_pydict()[c] for c in ("k", "s", "c")]))
            finally:
                daft_tpu.runners.set_runner(None)
            assert [g[0] for g in got] == [o[0] for o in out]
            for g, o in zip(got, out):
                assert abs(g[1] - o[1]) < 1e-9 and g[2] == o[2]
        finally:
            r.shutdown()


def test_hard_affinity_excluded_pref_does_not_poison_skip_set():
    """A hard-affinity task whose preferred worker is merely EXCLUDED (after a
    requeue) must not block siblings whose affinity to that worker is
    satisfiable — only a genuinely full worker enters the skip set."""
    s = Scheduler({"w0": 1, "w1": 1})
    # t_excluded pops first (lower seq) and cannot run on w0; t_ok can
    t_excl = SubPlanTask(task_id="t_excl", plan_blob=b"",
                         strategy=WorkerAffinity("w0", hard=True),
                         excluded_workers=("w0",))
    t_ok = SubPlanTask(task_id="t_ok", plan_blob=b"",
                       strategy=WorkerAffinity("w0", hard=True))
    s.submit(t_excl)
    s.submit(t_ok)
    assigned = {t.task_id: wid for t, wid in s.schedule()}
    assert assigned == {"t_ok": "w0"}  # w0 had a slot; t_ok was not starved
    assert s.placement_stats()["affinity_skips"] == 0


def test_repeat_query_cache_affinity_two_workers(monkeypatch):
    """The acceptance loop for residency-aware scheduling: across two device
    workers, the second run of an identical query places each sub-plan on the
    worker already holding its planes (sched_affinity_hits > 0) and those
    workers re-upload NOTHING (per-worker hbm_h2d_bytes flat), while results
    stay bit-identical."""
    import time

    from daft_tpu.config import execution_config_ctx
    from daft_tpu.distributed.runner import DistributedRunner
    from daft_tpu.observability.metrics import registry

    monkeypatch.setenv("DAFT_TPU_HEARTBEAT_S", "0.2")  # fast digest delivery
    rng = np.random.default_rng(11)
    n = 20_000
    data = daft_tpu.from_pydict({
        "k": rng.integers(0, 8, n).tolist(),
        "v": rng.uniform(0, 1, n).tolist(),
    }).collect()

    def q():
        return (data.groupby("k")
                .agg(col("v").sum().alias("s"), col("v").count().alias("c"))
                .sort("k"))

    def worker_h2d(pool, want, after_ts):
        """Per-worker cumulative upload bytes from beats emitted AFTER
        `after_ts` (the query's completion): a beat sent once the driver has
        all results necessarily postdates every upload the worker's tasks
        made, so stale mid-query beats can neither fake nor mask a
        re-upload."""
        out = {}
        deadline = time.time() + 15
        while time.time() < deadline and set(out) != set(want):
            for hb in pool.drain_heartbeats():
                if hb.get("ts", 0.0) > after_ts:
                    out[hb["worker_id"]] = hb.get("hbm_h2d_bytes", 0)
            time.sleep(0.05)
        assert set(out) == set(want), f"missing fresh heartbeats: {out}"
        return out

    with execution_config_ctx(device_mode="on"):
        r = DistributedRunner(num_workers=2, n_partitions=2, device_workers=2)
        try:
            import daft_tpu.runners as runners

            runners.set_runner(r)
            first = q().to_pydict()
            t_first_done = time.time()
            pool = r._pool
            h2d_after_first = worker_h2d(pool, pool.workers, t_first_done)
            assert any(v > 0 for v in h2d_after_first.values()), \
                "first run never uploaded — device path did not execute"
            before = registry().snapshot()
            second = q().to_pydict()
            t_second_done = time.time()
            diff = registry().diff(before)
            h2d_after_second = worker_h2d(pool, pool.workers, t_second_done)
        finally:
            runners.set_runner(None)
            r.shutdown()
    assert first == second
    assert diff.get("sched_affinity_hits", 0) > 0, diff
    assert diff.get("sched_bytes_avoided", 0) > 0, diff
    assert h2d_after_second == h2d_after_first, \
        "repeat query re-uploaded planes that were resident on its workers"


def test_affinity_saturated_worker_no_deadlock():
    """More fingerprinted tasks than the resident worker has slots: the
    overflow spreads to the other worker and the stage completes (soft
    affinity never deadlocks on a saturated preferred worker)."""
    from daft_tpu.core.micropartition import MicroPartition
    from daft_tpu.core.recordbatch import RecordBatch
    from daft_tpu.core.series import Series
    from daft_tpu.datatype import DataType
    from daft_tpu.distributed.worker import WorkerPool
    from daft_tpu.plan import physical as pp
    from daft_tpu.schema import Schema

    s = Series.from_pylist(list(range(64)), "a", DataType.int64())
    schema = Schema([s.field()])
    part = MicroPartition(schema, [RecordBatch(schema, [s], 64)])
    plan = pp.InMemoryScan([part], schema)
    pool = WorkerPool(2, slots_per_worker=1)
    try:
        # every task claims the same (synthetic) resident slot on worker-0;
        # pin the digest against overwrites from the workers' real (empty)
        # heartbeat digests — these host-only workers hold no device planes
        w0 = pool.workers["worker-0"]
        w0.last_digest = {12345: 1 << 20}
        w0._note_heartbeat = lambda hb, _w=w0: _w.heartbeats.append(hb)
        tasks = [SubPlanTask.from_plan(f"t{i}", plan) for i in range(6)]
        for t in tasks:
            t.rfingerprint = ((12345, 1 << 20),)
        results = pool.run_tasks(tasks)
        assert len(results) == 6
        assert all(r.rows == 64 for r in results.values())
        # both workers participated: the saturated preferred worker did not
        # serialize the whole stage
        assert len({r.worker_id for r in results.values()}) == 2
    finally:
        pool.shutdown()


def test_device_worker_lease_env():
    """Exactly the first `device_workers` workers get the device-mode env;
    the rest stay host-only ("off")."""
    from daft_tpu.config import execution_config_ctx
    from daft_tpu.distributed.worker import WorkerPool

    with execution_config_ctx(device_mode="auto"):
        pool = WorkerPool(3, device_workers=1)
    try:
        # the spawn env is recorded per process; check the children's env via
        # their construction-time choice: worker-0 leased, others off
        import subprocess

        modes = {}
        for wid, w in pool.workers.items():
            # /proc/<pid>/environ carries the spawn env on linux
            with open(f"/proc/{w._proc.pid}/environ", "rb") as f:
                env = dict(x.split(b"=", 1) for x in f.read().split(b"\0") if b"=" in x)
            modes[wid] = env.get(b"DAFT_TPU_DEVICE", b"").decode()
        assert modes["worker-0"] == "auto", modes
        assert modes["worker-1"] == "off" and modes["worker-2"] == "off", modes
    finally:
        pool.shutdown()


def test_worker_pool_plumbs_batching_config_env():
    """Driver-side batching/coalescing config (set via set_execution_config,
    not env vars) reaches worker subprocesses through their spawn env."""
    from daft_tpu.config import execution_config_ctx
    from daft_tpu.distributed.worker import WorkerPool

    with execution_config_ctx(batching_mode="dynamic", batch_fill_target=0.25,
                              batch_latency_ms=12.5, morsel_size_rows=4096):
        pool = WorkerPool(0)  # env assembled at pool construction; no spawns
    try:
        assert pool._env["DAFT_TPU_BATCHING"] == "dynamic"
        assert pool._env["DAFT_TPU_BATCH_FILL"] == "0.25"
        assert pool._env["DAFT_TPU_BATCH_LATENCY_MS"] == "12.5"
        assert pool._env["DAFT_TPU_MORSEL_SIZE"] == "4096"
    finally:
        pool.shutdown()


def test_device_leased_workers_dispatch_on_device_with_counters():
    """VERDICT r5 weak #7: a device-leased distributed worker must actually
    run the device stage. With DAFT_TPU_DEVICE=on leased to both workers (JAX
    CPU backend), the shipped partial DeviceGroupedAgg stages dispatch on the
    workers' devices, and the per-task device-stage counters come back in
    TaskResult.engine_counters -> TaskStats alongside the per-operator stats,
    mirrored into the driver registry for EXPLAIN ANALYZE / QueryEnd."""
    from daft_tpu.config import execution_config_ctx
    from daft_tpu.distributed.runner import DistributedRunner
    from daft_tpu.observability.metrics import registry
    from daft_tpu.observability.runtime_stats import (StatsCollector,
                                                      set_collector)

    rng = np.random.default_rng(19)
    n = 20_000
    df = daft_tpu.from_pydict({
        "k": rng.integers(0, 8, n).tolist(),
        "v": rng.integers(0, 1 << 40, n).tolist(),
    })
    q = (df.groupby("k")
         .agg(col("v").sum().alias("s"), col("v").count().alias("c"))
         .sort("k"))

    with execution_config_ctx(device_mode="on"):
        r = DistributedRunner(num_workers=2, n_partitions=2, device_workers=2)
        try:
            daft_tpu.runners.set_runner(r)
            before = registry().snapshot()
            collector = StatsCollector()
            set_collector(collector)  # ambient collector => traced run
            try:
                got = q.to_pydict()
            finally:
                set_collector(None)
            trace = r.last_trace
            diff = registry().diff(before)
        finally:
            daft_tpu.runners.set_runner(None)
            r.shutdown()
    with execution_config_ctx(device_mode="off"):
        want = q.to_pydict()
    assert got == want  # int64 sums: worker device path is exact

    assert trace is not None and trace.tasks
    per_task = [dict(ts.engine_counters) for ts in trace.tasks]
    dev_batches = sum(t.get("device_grouped_batches", 0) for t in per_task)
    assert dev_batches > 0, \
        f"no device dispatches recorded in task stats: {per_task}"
    # per-operator stats rode along with the engine counters
    assert any(ts.operator_stats for ts in trace.tasks)
    # coalescer ran in the workers and its dispatches were counted
    assert sum(t.get("dispatch_coalesced", 0) for t in per_task) > 0
    # driver registry mirror: the per-query diff carries cluster-wide device
    # attribution (QueryEnd.metrics / distributed EXPLAIN ANALYZE)
    assert diff.get("device_grouped_batches", 0) == dev_batches
