"""Process-wide host memory manager (daft_tpu/memory): ledger semantics,
budget resolution, shared admission across concurrent operators/queries,
pressure backpressure, and the zero-overhead guard for unbudgeted queries."""

import threading

import numpy as np
import pytest

import daft_tpu
from daft_tpu import col
from daft_tpu.config import execution_config_ctx
from daft_tpu.memory import manager
from daft_tpu.memory.manager import system_ram_bytes
from daft_tpu.observability.metrics import registry


@pytest.fixture(autouse=True)
def _clean_manager():
    from daft_tpu.execution import memory as mem

    mem.reset_counters()
    manager().clear()
    yield
    manager().clear()


def test_ledger_track_release_high_water():
    m = manager()
    m.track(1000)
    m.track(500)
    assert m.tracked_bytes() == 1500
    m.release(600)
    assert m.tracked_bytes() == 900
    assert m.high_water_bytes() == 1500
    snap = registry().snapshot()
    assert snap["host_bytes_tracked"] == 900.0
    assert snap["host_bytes_high_water"] == 1500.0
    m.release(10_000)  # over-release clamps at zero, never goes negative
    assert m.tracked_bytes() == 0


def test_limit_resolution_modes():
    m = manager()
    with execution_config_ctx(memory_limit_bytes=12345):
        assert m.limit_bytes() == 12345
    with execution_config_ctx(memory_limit_bytes=0):
        assert m.limit_bytes() == 0  # unbounded/untracked default
    with execution_config_ctx(memory_limit_bytes=-1, memory_fraction=0.5):
        auto = m.limit_bytes()
        ram = system_ram_bytes()
        if ram > 0:
            assert auto == int(ram * 0.5)
        else:
            assert auto == 0


def test_shared_budget_across_operators():
    """Two admission handles draw down ONE ledger: the second operator sees
    over-budget once the combined holdings cross the limit (the serving-tier
    'concurrent queries share one budget' satellite, at manager level)."""
    m = manager()
    with execution_config_ctx(memory_limit_bytes=1000):
        a = m.operator_budget()
        b = m.operator_budget()
        assert a.admit(600)
        assert not b.admit(600)  # ledger at 1200 > 1000: B must spill
        assert registry().get("host_over_budget_events") == 1
        b.release_all()
        assert m.tracked_bytes() == 600
        a.close()
        assert m.tracked_bytes() == 0


def test_inert_budget_when_unbudgeted():
    m = manager()
    with execution_config_ctx(memory_limit_bytes=0):
        b = m.operator_budget()
        assert b.admit(10**12)
        assert m.tracked_bytes() == 0  # nothing touched the ledger
        b.close()


def test_pressure_threshold_and_callbacks():
    m = manager()
    fired = []
    unsub = m.on_pressure(lambda tracked, limit: fired.append((tracked, limit)))
    with execution_config_ctx(memory_limit_bytes=1000, memory_pressure=0.8):
        m.track(700)
        assert not m.under_pressure()
        m.track(200)  # 900 >= 800: upward crossing fires once
        assert m.under_pressure()
        assert len(fired) == 1
        m.track(50)  # still in pressure: no re-fire
        assert len(fired) == 1
        m.release(900)  # 50 < 800: pressure clears
        assert not m.under_pressure()
        m.track(850)  # re-cross fires again
        assert len(fired) == 2
        unsub()
        m.release(900)
        m.track(900)
        assert len(fired) == 2


def test_wait_for_headroom_bounded_and_counted():
    m = manager()
    with execution_config_ctx(memory_limit_bytes=1000, memory_pressure=0.5):
        m.track(900)
        t = threading.Timer(0.05, lambda: m.release(900))
        t.start()
        stalled = m.wait_for_headroom(max_wait_s=5.0)
        t.join()
        assert 0.0 < stalled < 5.0  # woke on the release, not the deadline
        assert registry().get("scan_backpressure_stalls") == 1
        assert registry().get("scan_stall_ms") >= 1
        # pressure that never clears: returns at the bound (pacing, not a gate)
        m.track(900)
        stalled = m.wait_for_headroom(max_wait_s=0.05)
        assert stalled >= 0.05
        m.release(900)


def test_query_scope_observes_peak():
    m = manager()
    with execution_config_ctx(memory_limit_bytes=10_000):
        m.track(100)
        with m.query_scope() as scope:
            assert scope.peak_bytes() == 100  # pre-existing holdings count
            m.track(700)
            m.release(500)
            m.track(100)
        assert scope.peak_bytes() == 800
        m.release(400)
        assert scope.peak_bytes() == 800  # frozen after exit


def test_zero_overhead_unbudgeted_query():
    """Acceptance guard: an unbudgeted in-memory query allocates no
    manager/spill state and shows an EMPTY registry diff."""
    import os

    from daft_tpu.memory import spill_root

    df = daft_tpu.from_pydict({
        "k": [i % 7 for i in range(10_000)],
        "v": [float(i) for i in range(10_000)],
    })

    def q():
        return df.groupby("k").agg(col("v").sum().alias("s")).sort("k")

    with execution_config_ctx(memory_limit_bytes=0, device_mode="off"):
        q().to_pydict()  # warm one run (pools, kernels)
        before = registry().snapshot()
        q().to_pydict()
        diff = registry().diff(before)
    assert diff == {}, f"unbudgeted query left a registry diff: {diff}"
    assert manager().tracked_bytes() == 0
    assert manager().high_water_bytes() == 0
    root = spill_root()
    if os.path.isdir(root):
        assert not [n for n in os.listdir(root) if f"{os.getpid()}_" in n]


def test_concurrent_queries_share_ledger_and_stay_exact():
    """Four concurrent spilling queries under one tiny shared budget: all
    bit-identical to the unbudgeted run, ledger drains to zero after."""
    rng = np.random.default_rng(3)
    df = daft_tpu.from_pydict({
        "k": rng.integers(0, 50, 40_000).tolist(),
        "v": rng.uniform(0, 1, 40_000).tolist(),
    })

    def q():
        return (df.groupby("k").agg(col("v").sum().alias("s"))
                .sort("k").to_pydict())

    with execution_config_ctx(memory_limit_bytes=0, device_mode="off"):
        expected = q()
    results = [None] * 4
    errs = []
    with execution_config_ctx(memory_limit_bytes=128 * 1024, device_mode="off"):
        def run(i):
            try:
                results[i] = q()
            except Exception as e:  # noqa: BLE001 — surfaced via the errs assert
                errs.append(e)

        threads = [threading.Thread(target=run, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert not errs
    assert all(r == expected for r in results)
    assert registry().get("spill_batches") > 0
    assert manager().tracked_bytes() == 0, "a query leaked ledger bytes"
