"""Multimodal tests: image kernels, url fetch, embeddings, AI functions, minhash
(reference test model: tests/io multimodal + daft-image tests + tests/ai)."""

import io
import os

import numpy as np
import pytest

import daft_tpu as dt
from daft_tpu import col
from daft_tpu.datatype import DataType


def _png(w, h, color):
    from PIL import Image

    im = Image.new("RGB", (w, h), color)
    b = io.BytesIO()
    im.save(b, format="PNG")
    return b.getvalue()


@pytest.fixture
def img_df():
    return dt.from_pydict({
        "bytes": [_png(8, 6, (255, 0, 0)), _png(10, 4, (0, 255, 0)), None],
    })


def test_image_decode(img_df):
    out = img_df.with_column("img", col("bytes").image.decode())
    assert out.schema["img"].dtype.kind == "image"
    d = out.to_pydict()
    assert d["img"][0]["height"] == 6 and d["img"][0]["width"] == 8
    assert d["img"][2] is None


def test_image_resize_encode_roundtrip(img_df):
    from PIL import Image

    out = (img_df.with_column("img", col("bytes").image.decode())
           .with_column("small", col("img").image.resize(4, 3))
           .with_column("re", col("small").image.encode("PNG"))).to_pydict()
    im = Image.open(io.BytesIO(out["re"][0]))
    assert im.size == (4, 3)
    assert out["re"][2] is None


def test_image_crop_and_mode(img_df):
    out = (img_df.with_column("img", col("bytes").image.decode())
           .with_column("c", col("img").image.crop((0, 0, 2, 2)))
           .with_column("g", col("img").image.to_mode("L"))).to_pydict()
    assert out["c"][0]["height"] == 2 and out["c"][0]["width"] == 2
    assert out["g"][0]["channels"] == 1


def test_image_to_fixed_shape(img_df):
    out = (img_df.with_column("img", col("bytes").image.decode())
           .with_column("t", col("img").image.to_fixed_shape("RGB", 4, 4))).to_pydict()
    assert out["t"][0].shape == (4, 4, 3)
    assert out["t"][2] is None
    # red image stays red after resize
    assert out["t"][0][0, 0, 0] == 255


def test_image_decode_on_error_null():
    d = dt.from_pydict({"b": [b"notanimage", _png(2, 2, (1, 2, 3))]})
    out = d.with_column("img", col("b").image.decode(on_error="null")).to_pydict()
    assert out["img"][0] is None and out["img"][1] is not None


def test_url_roundtrip(tmp_path, img_df):
    up = (img_df.where(col("bytes").not_null())
          .with_column("p", col("bytes").url.upload(str(tmp_path)))).to_pydict()
    assert all(os.path.exists(p) for p in up["p"])
    dl = dt.from_pydict({"p": up["p"]}).with_column("d", col("p").url.download()).to_pydict()
    assert dl["d"] == up["bytes"]


def test_url_download_missing_null():
    d = dt.from_pydict({"p": ["/nonexistent/file.bin"]})
    out = d.with_column("d", col("p").url.download(on_error="null")).to_pydict()
    assert out["d"] == [None]
    with pytest.raises(Exception):
        d.with_column("d", col("p").url.download()).to_pydict()


def test_embedding_distances():
    e = dt.from_pydict({"a": [[1.0, 0.0], [0.0, 1.0]], "b": [[1.0, 0.0], [1.0, 0.0]]})
    out = e.select(
        col("a").embedding.cosine_distance(col("b")).alias("cos"),
        col("a").embedding.dot(col("b")).alias("dot"),
        col("a").embedding.euclidean_distance(col("b")).alias("l2"),
    ).to_pydict()
    assert abs(out["cos"][0]) < 1e-9 and abs(out["cos"][1] - 1.0) < 1e-9
    assert out["dot"] == [1.0, 0.0]
    assert abs(out["l2"][1] - np.sqrt(2)) < 1e-9


def test_ai_embed_classify_dummy():
    from daft_tpu.functions import classify_text, embed_text

    df = dt.from_pydict({"t": ["hello", "world", None]})
    out = df.with_column("e", embed_text(col("t"), provider="dummy")).to_pydict()
    assert len(out["e"][0]) == 16 and out["e"][2] is None
    # deterministic
    out2 = df.with_column("e", embed_text(col("t"), provider="dummy")).to_pydict()
    assert out["e"][0] == out2["e"][0]
    c = df.with_column("c", classify_text(col("t"), ["x", "y"], provider="dummy")).to_pydict()
    assert c["c"][0] in ("x", "y") and c["c"][2] is None


def test_minhash_dedup_shape():
    d = dt.from_pydict({"s": ["the quick brown fox", "the quick brown fox!", "zzz totally different"]})
    out = d.with_column("m", col("s").minhash(num_hashes=16, ngram_size=2)).to_pydict()
    assert all(len(m) == 16 for m in out["m"])
    sim_close = sum(a == b for a, b in zip(out["m"][0], out["m"][1])) / 16
    sim_far = sum(a == b for a, b in zip(out["m"][0], out["m"][2])) / 16
    assert sim_close > sim_far


def test_approx_count_distinct():
    import random

    random.seed(0)
    vals = [f"v{random.randrange(500)}" for _ in range(5000)]
    d = dt.from_pydict({"x": vals})
    approx = d.agg(col("x").approx_count_distinct().alias("a")).to_pydict()["a"][0]
    exact = d.agg(col("x").count_distinct().alias("e")).to_pydict()["e"][0]
    assert abs(approx - exact) / exact < 0.15


def test_image_embed_and_llm_generate():
    """AI tier: image embedding protocol + the LLM-generation operator shape
    (stateful batched prompter isolated into its own pipeline node)."""
    import daft_tpu
    from daft_tpu import col
    from daft_tpu.functions import embed_image, llm_generate

    df = daft_tpu.from_pydict({
        "img": [b"\x00\x01\x02", b"\x03\x04\x05", None],
        "q": ["what is 2+2?", None, "name a color"],
    })
    out = df.select(
        embed_image(col("img")).alias("e"),
        llm_generate(col("q"), provider="dummy", model="m1").alias("a"),
    ).to_pydict()
    assert len(out["e"][0]) == 16 and out["e"][2] is None
    assert out["a"][0].startswith("[m1] what is 2+2?")
    assert out["a"][1] is None


def test_llm_generate_process_actor_pool():
    import daft_tpu
    from daft_tpu import col
    from daft_tpu.functions import llm_generate

    df = daft_tpu.from_pydict({"q": [f"q{i}" for i in range(20)]})
    out = df.select(llm_generate(col("q"), provider="dummy", use_process=True,
                                 max_concurrency=2).alias("a")).to_pydict()
    assert all(a.endswith(q) for a, q in zip(out["a"], [f"q{i}" for i in range(20)]))


class _MockOpenAI:
    """In-process OpenAI-compatible server: /embeddings + /chat/completions,
    with auth check, one injected 500 (retry path), and a high-water mark of
    concurrent in-flight requests."""

    def __init__(self):
        import http.server
        import json as _json
        import threading
        import time as _time

        self.inflight = 0
        self.max_inflight = 0
        self.requests = []
        self.fail_next = 0
        self._lock = threading.Lock()
        mock = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                body = _json.loads(self.rfile.read(int(self.headers["Content-Length"])))
                with mock._lock:
                    if mock.fail_next > 0:
                        mock.fail_next -= 1
                        self.send_response(500)
                        self.end_headers()
                        return
                    mock.inflight += 1
                    mock.max_inflight = max(mock.max_inflight, mock.inflight)
                    mock.requests.append((self.path, self.headers.get("Authorization")))
                _time.sleep(0.05)  # hold the request so concurrency is observable
                try:
                    if self.path == "/v1/embeddings":
                        data = [{"index": i, "embedding": [float(len(t)), 1.0]}
                                for i, t in enumerate(body["input"])]
                        out = {"data": data}
                    else:
                        content = "echo: " + body["messages"][-1]["content"][:32]
                        out = {"choices": [{"message": {"content": content}}]}
                    payload = _json.dumps(out).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                finally:
                    with mock._lock:
                        mock.inflight -= 1

        from http.server import ThreadingHTTPServer

        class Server(ThreadingHTTPServer):
            daemon_threads = True

        self.server = Server(("127.0.0.1", 0), Handler)
        self.port = self.server.server_address[1]
        threading.Thread(target=self.server.serve_forever, daemon=True).start()

    def close(self):
        self.server.shutdown()


def test_openai_provider_embeddings_and_generation_with_concurrency():
    """OpenAI-compatible HTTP provider against a mock server: embeddings batch
    through /embeddings, generation fans out concurrent /chat/completions
    (reference: daft/ai/openai + the vLLM prompt operator)."""
    import daft_tpu
    from daft_tpu import col
    from daft_tpu.ai.openai_provider import OpenAIProvider
    from daft_tpu.ai.provider import register_provider
    from daft_tpu.functions.ai import embed_text, llm_generate

    mock = _MockOpenAI()
    try:
        provider = OpenAIProvider(base_url=f"http://127.0.0.1:{mock.port}/v1",
                                  api_key="sk-test", request_concurrency=4)
        register_provider(provider, name="openai_test")
        df = daft_tpu.from_pydict({"t": ["alpha", "bz", None, "gamma!", "dd", "eee"]})
        out = df.select(embed_text(col("t"), provider="openai_test",
                                   model="emb-1").alias("e")).to_pydict()
        assert out["e"][2] is None
        assert out["e"][0] == [5.0, 1.0] and out["e"][1] == [2.0, 1.0]
        # auth header reached the server
        assert all(a == "Bearer sk-test" for _p, a in mock.requests)

        out = df.select(llm_generate(col("t"), provider="openai_test",
                                     model="m").alias("g")).to_pydict()
        assert out["g"][0] == "echo: alpha" and out["g"][2] is None
        assert mock.max_inflight > 1, "generation requests never overlapped"
    finally:
        mock.close()


def test_openai_provider_retries_on_500():
    from daft_tpu.ai.openai_provider import OpenAIProvider

    mock = _MockOpenAI()
    try:
        mock.fail_next = 2
        p = OpenAIProvider(base_url=f"http://127.0.0.1:{mock.port}/v1",
                           api_key="k", max_retries=3)
        got = p.get_prompter("m").prompt(["hi"])
        assert got == ["echo: hi"]
    finally:
        mock.close()


def test_openai_classifier_routes_through_prompts():
    from daft_tpu.ai.openai_provider import OpenAIProvider

    mock = _MockOpenAI()
    try:
        p = OpenAIProvider(base_url=f"http://127.0.0.1:{mock.port}/v1", api_key="k")
        # mock echoes the prompt; 'spam' appears in the echoed label list
        out = p.get_text_classifier("m").classify_text(["buy pills"], ["spam", "ham"])
        assert out == ["spam"]
    finally:
        mock.close()


def test_llm_generate_prefix_routed_process_pool():
    """vLLM-style prefix-affinity routing: rows sharing a prompt prefix land on
    one replica; outputs come back in input row order (reference:
    src/daft-distributed/src/pipeline_node/vllm.rs prefix-routed actor pool)."""
    import daft_tpu
    from daft_tpu import col
    from daft_tpu.functions import llm_generate

    prompts = [f"family-{i % 3}: question {i}" for i in range(12)]
    df = daft_tpu.from_pydict({"p": prompts})
    out = df.select(llm_generate(col("p"), provider="dummy", max_concurrency=3,
                                 use_process=True, route_prefix_len=9)
                    .alias("r")).to_pydict()
    assert len(out["r"]) == 12
    # dummy prompter echoes deterministically — row order must be preserved
    for i, r in enumerate(out["r"]):
        assert f"question {i}" in r, (i, r)
