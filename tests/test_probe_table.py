"""ProbeTable (build-once probe-many join index) parity with join_indices.

The streaming/parallel join path (core/relational.py JoinProbe) rests on this
contract: for every dtype, null pattern, and join type, ProbeTable.probe must
return EXACTLY the same (left, right) index arrays — including row order — as
the one-shot joint encoding in join_indices (reference:
src/daft-recordbatch/src/probeable/ + src/daft-local-execution/src/join/).
"""

import numpy as np
import pytest

from daft_tpu.core.kernels.join import ProbeTable, join_indices
from daft_tpu.core.series import Series


def _mk(vals):
    return Series.from_pylist(list(vals), "k")


def _gen_col(rng, kind, n):
    if kind == 0:  # small dense ints
        return [int(x) if rng.random() > 0.15 else None for x in rng.integers(0, 8, n)]
    if kind == 1:  # floats with NaN
        v = [float(x) if rng.random() > 0.15 else None for x in rng.integers(0, 5, n)]
        return [x if x != 3.0 else float("nan") for x in v]
    if kind == 2:  # strings
        return [chr(65 + int(x)) if rng.random() > 0.15 else None
                for x in rng.integers(0, 6, n)]
    if kind == 3:  # bools
        return [bool(x) if rng.random() > 0.15 else None for x in rng.integers(0, 2, n)]
    # sparse ints (forces the hashmap/sorted lookup path)
    return [int(x) * 100_000 + 7 if rng.random() > 0.15 else None
            for x in rng.integers(0, 50, n)]


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_probe_table_matches_join_indices_fuzzed(seed):
    rng = np.random.default_rng(seed)
    for _ in range(25):
        ncols = int(rng.integers(1, 4))
        nl, nr = int(rng.integers(0, 60)), int(rng.integers(0, 60))
        kinds = [int(rng.integers(0, 5)) for _ in range(ncols)]
        lks = [_mk(_gen_col(rng, k, nl)) for k in kinds]
        rks = [_mk(_gen_col(rng, k, nr)) for k in kinds]
        for how in ("inner", "left", "semi", "anti"):
            for nen in (False, True):
                li, ri = join_indices(lks, rks, how, nen)
                pt = ProbeTable(rks, [s.dtype for s in lks], nen)
                pl, pr = pt.probe(lks, how)
                assert np.array_equal(li, pl) and np.array_equal(ri, pr), \
                    (kinds, how, nen)


def test_probe_table_mixed_dtypes_and_empty_build():
    import pyarrow as pa

    l = Series.from_arrow(pa.array([1, 2, 3, None], pa.int32()), "k")
    r = Series.from_arrow(pa.array([2.0, 3.0, 9.0, None], pa.float64()), "k")
    for how in ("inner", "left", "semi", "anti"):
        for nen in (False, True):
            li, ri = join_indices([l], [r], how, nen)
            pt = ProbeTable([r], [l.dtype], nen)
            pl, pr = pt.probe([l], how)
            assert np.array_equal(li, pl) and np.array_equal(ri, pr)
    empty = _mk([]).cast(l.dtype)
    pt = ProbeTable([empty], [l.dtype], False)
    li, ri = join_indices([l], [empty], "anti", False)
    pl, _ = pt.probe([l], "anti")
    assert np.array_equal(li, pl)


def test_probe_table_reuse_across_many_batches():
    """One build, many probes — the whole point; results must match per-batch
    one-shot joins."""
    rng = np.random.default_rng(11)
    r = _mk([int(x) for x in rng.integers(0, 500, 1000)])
    pt = ProbeTable([r], [r.dtype], False)
    for _ in range(5):
        l = _mk([int(x) for x in rng.integers(0, 600, 300)])
        li, ri = join_indices([l], [r], "inner", False)
        pl, pr = pt.probe([l], "inner")
        assert np.array_equal(li, pl) and np.array_equal(ri, pr)
