"""DataFrame API tests (reference test model: tests/dataframe/*)."""

import os

import pytest

import daft_tpu as dt
from daft_tpu import col, lit


@pytest.fixture
def df():
    return dt.from_pydict({
        "a": [1, 2, 3, 4, 5],
        "b": [10.0, 20.0, 30.0, 40.0, 50.0],
        "k": ["x", "y", "x", "y", "x"],
    })


def test_select_project(df):
    out = df.select(col("a"), (col("b") * 2).alias("b2")).to_pydict()
    assert out == {"a": [1, 2, 3, 4, 5], "b2": [20.0, 40.0, 60.0, 80.0, 100.0]}


def test_filter(df):
    out = df.where(col("a") > 3).to_pydict()
    assert out["a"] == [4, 5]


def test_with_column(df):
    out = df.with_column("c", col("a") + col("b")).to_pydict()
    assert out["c"] == [11.0, 22.0, 33.0, 44.0, 55.0]
    assert list(out.keys()) == ["a", "b", "k", "c"]


def test_exclude_rename(df):
    assert df.exclude("b").column_names == ["a", "k"]
    assert df.with_column_renamed("a", "aa").column_names == ["aa", "b", "k"]


def test_limit_offset(df):
    assert df.limit(2).to_pydict()["a"] == [1, 2]
    assert df.offset(3).to_pydict()["a"] == [4, 5]
    assert df.offset(1).limit(2).to_pydict()["a"] == [2, 3]


def test_sort(df):
    assert df.sort("b", desc=True).to_pydict()["a"] == [5, 4, 3, 2, 1]
    out = df.sort(["k", "b"], desc=[False, True]).to_pydict()
    assert out["k"] == ["x", "x", "x", "y", "y"]
    assert out["b"] == [50.0, 30.0, 10.0, 40.0, 20.0]


def test_topn_via_sort_limit(df):
    out = df.sort("b", desc=True).limit(2).to_pydict()
    assert out["b"] == [50.0, 40.0]


def test_grouped_agg(df):
    out = df.groupby("k").agg(
        col("b").sum(),
        col("a").count().alias("n"),
        col("b").mean().alias("avg"),
        col("a").min().alias("lo"),
        col("a").max().alias("hi"),
    ).sort("k").to_pydict()
    assert out["k"] == ["x", "y"]
    assert out["b"] == [90.0, 60.0]
    assert out["n"] == [3, 2]
    assert out["avg"] == [30.0, 30.0]
    assert out["lo"] == [1, 2]
    assert out["hi"] == [5, 4]


def test_global_agg(df):
    out = df.agg(col("b").sum().alias("s"), col("a").mean().alias("m")).to_pydict()
    assert out == {"s": [150.0], "m": [3.0]}


def test_count_rows(df):
    assert len(df) == 5
    assert df.where(col("k") == "x").count_rows() == 3


def test_distinct(df):
    out = df.select("k").distinct().sort("k").to_pydict()
    assert out["k"] == ["x", "y"]


def test_grouped_agg_nulls():
    d = dt.from_pydict({"k": ["a", "a", "b", None], "v": [1, None, 3, 4]})
    out = d.groupby("k").agg(
        col("v").sum(), col("v").count().alias("n")
    ).sort("k", nulls_first=False).to_pydict()
    assert out["k"] == ["a", "b", None]
    assert out["v"] == [1, 3, 4]
    assert out["n"] == [1, 1, 1]


def test_joins():
    left = dt.from_pydict({"k": [1, 2, 3], "x": ["a", "b", "c"]})
    right = dt.from_pydict({"k": [2, 3, 4], "y": [20, 30, 40]})
    inner = left.join(right, on="k").sort("k").to_pydict()
    assert inner == {"k": [2, 3], "x": ["b", "c"], "y": [20, 30]}
    l = left.join(right, on="k", how="left").sort("k").to_pydict()
    assert l == {"k": [1, 2, 3], "x": ["a", "b", "c"], "y": [None, 20, 30]}
    outer = left.join(right, on="k", how="outer").sort("k").to_pydict()
    assert outer["k"] == [1, 2, 3, 4]
    assert outer["y"] == [None, 20, 30, 40]
    anti = left.join(right, on="k", how="anti").to_pydict()
    assert anti == {"k": [1], "x": ["a"]}
    semi = left.join(right, on="k", how="semi").sort("k").to_pydict()
    assert semi == {"k": [2, 3], "x": ["b", "c"]}


def test_join_name_collision():
    left = dt.from_pydict({"k": [1, 2], "v": [1.0, 2.0]})
    right = dt.from_pydict({"k": [1, 2], "v": [10.0, 20.0]})
    out = left.join(right, on="k").sort("k").to_pydict()
    assert out == {"k": [1, 2], "v": [1.0, 2.0], "right.v": [10.0, 20.0]}


def test_cross_join():
    a = dt.from_pydict({"x": [1, 2]})
    b = dt.from_pydict({"y": ["p", "q"]})
    out = a.join(b, how="cross").to_pydict()
    assert out == {"x": [1, 1, 2, 2], "y": ["p", "q", "p", "q"]}


def test_concat(df):
    out = df.concat(df).count_rows()
    assert out == 10


def test_explode():
    d = dt.from_pydict({"id": [1, 2, 3], "vals": [[1, 2], [], [3]]})
    out = d.explode("vals").to_pydict()
    assert out["id"] == [1, 1, 2, 3]
    assert out["vals"] == [1, 2, None, 3]


def test_unpivot():
    d = dt.from_pydict({"id": [1, 2], "x": [10, 20], "y": [100, 200]})
    out = d.unpivot(["id"], ["x", "y"]).to_pydict()
    assert out["id"] == [1, 1, 2, 2]
    assert out["variable"] == ["x", "y", "x", "y"]
    assert out["value"] == [10, 100, 20, 200]


def test_pivot():
    d = dt.from_pydict({"g": ["a", "a", "b"], "p": ["x", "y", "x"], "v": [1, 2, 3]})
    out = d.pivot("g", "p", "v", "sum").sort("g").to_pydict()
    assert out == {"g": ["a", "b"], "x": [1, 3], "y": [2, None]}


def test_sample(df):
    out = df.sample(0.6, seed=42)
    assert 0 <= out.count_rows() <= 5


def test_monotonic_id(df):
    out = df.add_monotonically_increasing_id().to_pydict()
    assert out["id"] == [0, 1, 2, 3, 4]


def test_iter_rows(df):
    rows = list(df.limit(2))
    assert rows == [{"a": 1, "b": 10.0, "k": "x"}, {"a": 2, "b": 20.0, "k": "y"}]


def test_into_batches(df):
    parts = list(df.into_batches(2).iter_partitions())
    sizes = [p.num_rows for p in parts]
    assert sizes == [2, 2, 1]


def test_repartition_hash(df):
    out = df.repartition(3, "k")
    assert out.count_rows() == 5


def test_intersect_except():
    a = dt.from_pydict({"x": [1, 2, 3, 3]})
    b = dt.from_pydict({"x": [2, 3, 4]})
    assert sorted(a.intersect(b).to_pydict()["x"]) == [2, 3]
    assert sorted(a.except_distinct(b).to_pydict()["x"]) == [1]


def test_collect_caches(df):
    c = df.collect()
    assert c.to_pydict()["a"] == [1, 2, 3, 4, 5]
    # downstream query on collected df
    assert c.where(col("a") > 4).to_pydict()["a"] == [5]


def test_to_pandas_arrow(df):
    pdf = df.to_pandas()
    assert list(pdf["a"]) == [1, 2, 3, 4, 5]
    t = df.to_arrow()
    assert t.num_rows == 5


def test_show_smoke(df, capsys):
    df.show()
    out = capsys.readouterr().out
    assert "Showing" in out


def test_explain(df):
    s = df.where(col("a") > 1).explain(True)
    assert "Filter" in s and "Physical" in s


def test_agg_list_concat():
    d = dt.from_pydict({"k": ["a", "a", "b"], "v": [1, 2, 3]})
    out = d.groupby("k").agg_list("v").sort("k").to_pydict()
    assert out["v"] == [[1, 2], [3]]


def test_any_value():
    d = dt.from_pydict({"k": ["a", "a", "b"], "v": [None, 2, 3]})
    out = d.groupby("k").any_value("v").sort("k").to_pydict()
    assert out["v"][1] == 3


def test_stddev_grouped():
    d = dt.from_pydict({"k": ["a", "a", "a", "b"], "v": [1.0, 2.0, 3.0, 5.0]})
    out = d.groupby("k").agg(col("v").stddev().alias("sd")).sort("k").to_pydict()
    assert out["sd"][0] == pytest.approx(0.8164965809)
    assert out["sd"][1] == 0.0


def test_count_distinct_grouped():
    d = dt.from_pydict({"k": ["a", "a", "a", "b"], "v": [1, 1, 2, None]})
    out = d.groupby("k").agg(col("v").count_distinct().alias("n")).sort("k").to_pydict()
    assert out["n"] == [2, 0]


def test_api_breadth_methods():
    import daft_tpu
    from daft_tpu import col

    df = daft_tpu.from_pydict({"a": [1, 2, None, 4], "b": [1.0, float("nan"), 3.0, 4.0]})
    assert len(df) == 4
    assert df.drop_null("a").count_rows() == 3
    assert df.drop_nan("b").count_rows() == 3
    ids = df.add_monotonically_increasing_id("rid").to_pydict()["rid"]
    assert len(set(ids)) == 4
    out = df.pipe(lambda d, k: d.where(col("a") > k), 1).to_pydict()
    assert out["a"] == [2, 4]
    d = df.drop_null("a").select("a").describe().to_pydict()
    assert d["a_count"] == [3] and d["a_min"] == [1] and d["a_max"] == [4]

    x = daft_tpu.from_pydict({"k": [1, 2, 3]})
    y = daft_tpu.from_pydict({"k": [2]})
    assert x.except_(y).sort("k").to_pydict() == {"k": [1, 3]}


def test_set_ops_null_semantics():
    """SQL set-op semantics: NULL keys match NULL keys in EXCEPT/INTERSECT."""
    import daft_tpu

    a = daft_tpu.from_pydict({"k": [1, None, 2], "v": [1.0, 2.0, 3.0]})
    b = daft_tpu.from_pydict({"k": [None, 2], "v": [2.0, 3.0]})
    assert a.except_(b).to_pydict() == {"k": [1], "v": [1.0]}
    got = a.intersect(b).sort("v").to_pydict()
    assert got == {"k": [None, 2], "v": [2.0, 3.0]}
