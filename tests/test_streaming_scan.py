"""Streaming parquet scans: StreamingScan translation, row-group split
planning, small-file merging, ledger-keyed backpressure, and bit-identity
with the pushdowns applied."""

import os

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import daft_tpu as dt
from daft_tpu import col
from daft_tpu.config import execution_config_ctx
from daft_tpu.memory import manager
from daft_tpu.observability.metrics import registry
from daft_tpu.plan import physical as pp

N_ROWS = 40_000


@pytest.fixture(autouse=True)
def _clean():
    from daft_tpu.execution import memory as mem

    mem.reset_counters()
    manager().clear()
    yield
    manager().clear()


def _physical(df):
    from daft_tpu.plan.physical import translate

    return translate(df._builder.optimize().plan)


def _streaming_scans(phys):
    return [n for n in phys.walk() if isinstance(n, pp.StreamingScan)]


@pytest.fixture
def big_file(tmp_path):
    t = pa.table({
        "a": list(range(N_ROWS)),
        "v": [float(i % 1009) for i in range(N_ROWS)],
        "s": [f"x{i % 97}" for i in range(N_ROWS)],
    })
    path = str(tmp_path / "big.parquet")
    pq.write_table(t, path, row_group_size=4000)  # 10 row groups
    return path, t


def test_translates_to_streaming_scan(big_file):
    path, _ = big_file
    assert _streaming_scans(_physical(dt.read_parquet(path)))


def test_row_group_split_planning(big_file):
    path, t = big_file
    size = os.path.getsize(path)
    with execution_config_ctx(scan_split_bytes=max(size // 5, 1)):
        df = dt.read_parquet(path)
        scan = _streaming_scans(_physical(df))[0]
        assert len(scan.tasks) > 1, "large file never split by row groups"
        assert registry().get("scan_tasks_split") >= len(scan.tasks)
        out = df.to_pydict()
    assert out["a"] == t.column("a").to_pylist()  # order + content preserved
    assert registry().get("scan_batches") > 0
    assert registry().get("scan_rows") == N_ROWS


def test_split_disabled_keeps_one_task_per_file(big_file):
    path, _ = big_file
    with execution_config_ctx(scan_split_bytes=0):
        scan = _streaming_scans(_physical(dt.read_parquet(path)))[0]
        assert len(scan.tasks) == 1


def test_split_with_filter_pushdown_matches(big_file):
    """Split tasks don't evaluate the arrow predicate (filters_applied is
    False); the executor re-applies it — results must match exactly, and
    zone maps drop fully-excluded row groups at plan time."""
    path, _ = big_file
    size = os.path.getsize(path)
    with execution_config_ctx(scan_split_bytes=max(size // 5, 1),
                              device_mode="off"):
        df = dt.read_parquet(path).where(col("a") >= 35_000)
        scan = _streaming_scans(_physical(df))[0]
        # row groups 0..7 (a < 32000) are provably excluded by the zone map
        assert sum(t.num_rows or 0 for t in scan.tasks) <= 2 * 4000
        out = df.to_pydict()
    assert sorted(out["a"]) == list(range(35_000, N_ROWS))


def test_projection_pushdown_through_split(big_file):
    path, _ = big_file
    size = os.path.getsize(path)
    with execution_config_ctx(scan_split_bytes=max(size // 5, 1)):
        out = dt.read_parquet(path).select("a").to_pydict()
    assert out["a"] == list(range(N_ROWS))


def test_limit_pushdown_streaming(big_file):
    path, _ = big_file
    with execution_config_ctx(scan_split_bytes=0):
        assert dt.read_parquet(path).limit(7).count_rows() == 7


def test_small_file_merge(tmp_path):
    d = tmp_path / "many"
    d.mkdir()
    n_files, rows = 8, 1000
    for i in range(n_files):
        t = pa.table({"a": list(range(i * rows, (i + 1) * rows))})
        pq.write_table(t, d / f"f{i:02d}.parquet")
    with execution_config_ctx(scan_split_bytes=1 << 30):
        df = dt.read_parquet(str(d))
        scan = _streaming_scans(_physical(df))[0]
        assert len(scan.tasks) == 1, "tiny files never merged"
        assert registry().get("scan_tasks_merged") >= n_files - 1
        out = df.to_pydict()
    assert out["a"] == list(range(n_files * rows))  # order preserved


def test_scan_backpressure_stalls_bounded(big_file):
    """A saturated ledger makes the scan stall (counted) but NEVER deadlock:
    the wait is bounded pacing, so the query still completes exactly."""
    path, _ = big_file
    m = manager()
    with execution_config_ctx(memory_limit_bytes=1 << 20, memory_pressure=0.5,
                              device_mode="off"):
        m.track(1 << 20)  # someone else holds the whole budget
        try:
            out = dt.read_parquet(path).select("a").to_pydict()
        finally:
            m.release(1 << 20)
    assert out["a"] == list(range(N_ROWS))
    assert registry().get("scan_backpressure_stalls") > 0
    assert registry().get("scan_stall_ms") > 0


def test_unbudgeted_scan_skips_sizing_and_ledger_reads(big_file, monkeypatch):
    """Zero-overhead guard for the unbudgeted fast path: with the ledger
    unbounded the scan must not size morsels (the arrow-buffer walk behind
    size_bytes), must never consult the ledger's admit/stall surface, and
    must flush its batch/row counts per TASK, not per morsel (no per-morsel
    registry lock traffic). scan_bytes stays zero — it is only meaningful
    when a budget makes morsel sizing load-bearing."""
    from daft_tpu.core.micropartition import MicroPartition

    path, t = big_file
    size = os.path.getsize(path)
    m = manager()
    calls = {"size_bytes": 0, "under_pressure": 0, "wait_for_headroom": 0}
    orig_size = MicroPartition.size_bytes

    def counting_size(self):
        calls["size_bytes"] += 1
        return orig_size(self)

    monkeypatch.setattr(MicroPartition, "size_bytes", counting_size)
    monkeypatch.setattr(m, "under_pressure", lambda: (
        calls.__setitem__("under_pressure", calls["under_pressure"] + 1)
        or False))
    monkeypatch.setattr(m, "wait_for_headroom", lambda *a, **k: (
        calls.__setitem__("wait_for_headroom",
                          calls["wait_for_headroom"] + 1)))
    inc_names = []
    reg = registry()
    orig_inc = reg.inc

    def counting_inc(name, n=1):
        inc_names.append(name)
        orig_inc(name, n)

    monkeypatch.setattr(reg, "inc", counting_inc)
    with execution_config_ctx(memory_limit_bytes=0,
                              scan_split_bytes=max(size // 5, 1),
                              device_mode="off"):
        df = dt.read_parquet(path)
        n_tasks = len(_streaming_scans(_physical(df))[0].tasks)
        out = df.to_pydict()
    assert out["a"] == t.column("a").to_pylist()
    assert calls["size_bytes"] == 0, "unbudgeted scan walked arrow buffers"
    assert calls["under_pressure"] == 0 and calls["wait_for_headroom"] == 0, \
        "unbudgeted scan consulted the ledger per morsel"
    assert registry().get("scan_bytes") == 0
    # 10 row groups split across n_tasks: flush granularity is per task
    scan_incs = inc_names.count("scan_rows")
    assert 0 < scan_incs <= n_tasks + 1, \
        f"{scan_incs} scan_rows incs for {n_tasks} tasks — per-morsel flush?"
    assert registry().get("scan_rows") == N_ROWS


def test_unbudgeted_scan_distributed_path_skips_sizing(big_file, tmp_path):
    """The fast-path guard extended to the distributed engine: worker-side
    scans with an unbounded ledger never size morsels, so the per-task
    engine-counter deltas propagated to the driver land scan_rows with
    scan_bytes == 0 (sizing only happens when a budget makes it
    load-bearing — monkeypatching cannot cross the spawn boundary, so the
    propagated counters ARE the assertion surface)."""
    import json

    import daft_tpu.runners as runners
    from daft_tpu.distributed import DistributedRunner
    from daft_tpu.observability.event_log import (disable_event_log,
                                                  enable_event_log)

    path, t = big_file
    p = str(tmp_path / "scan_events.jsonl")
    r = DistributedRunner(num_workers=1, n_partitions=2)
    native = runners.NativeRunner()
    sub = enable_event_log(p)
    runners.set_runner(r)
    try:
        # a groupby ships the scan inside the shuffle-map tasks — a bare
        # scan+select short-circuits on the driver and tests nothing
        out = (dt.read_parquet(path).groupby("s")
               .agg(col("a").count().alias("c")).to_pydict())
    finally:
        runners.set_runner(native)
        disable_event_log(sub)
        r.shutdown()
    assert sum(out["c"]) == N_ROWS
    events = [json.loads(l) for l in open(p)]
    task_counters = [dict(e["engine_counters"]) for e in events
                     if e["event"] == "task_stats"]
    assert task_counters, "no task stats propagated from the workers"
    scanned = sum(c.get("scan_rows", 0) for c in task_counters)
    assert scanned == N_ROWS, \
        f"worker-side scans reported {scanned} rows via engine counters"
    assert all(c.get("scan_bytes", 0) == 0 for c in task_counters), \
        "unbudgeted distributed scan sized morsels (scan_bytes != 0)"
    ends = [e for e in events if e["event"] == "query_end"]
    assert all(e["metrics"].get("scan_bytes", 0) == 0 for e in ends)


def test_streaming_scan_feeds_spilling_sort_exactly(big_file):
    """End-to-end out-of-core pipeline: streaming scan -> external sort under
    a budget far below the file size, bit-identical to unbudgeted."""
    path, _ = big_file
    size = os.path.getsize(path)

    def q():
        return dt.read_parquet(path).sort(["v", "a"])

    with execution_config_ctx(scan_split_bytes=max(size // 5, 1),
                              memory_limit_bytes=128 * 1024,
                              device_mode="off"):
        capped = q().to_pydict()
    assert registry().get("spill_runs") > 0
    with execution_config_ctx(memory_limit_bytes=0, device_mode="off"):
        unbudgeted = q().to_pydict()
    assert capped == unbudgeted
