"""Test configuration.

Forces JAX onto a virtual 8-device CPU mesh (SURVEY.md §7 / task environment notes)
so multi-chip sharding paths are exercised without TPU hardware. Must run before the
first ``import jax`` anywhere in the test process.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (xla_flags + " --xla_force_host_platform_device_count=8").strip()

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture
def make_df():
    import daft_tpu

    def _make(data):
        return daft_tpu.from_pydict(data)

    return _make
