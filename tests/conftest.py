"""Test configuration.

Forces JAX onto a virtual 8-device CPU mesh (SURVEY.md §7 / task environment notes)
so multi-chip sharding paths are exercised without TPU hardware.

NOTE: this environment pre-imports jax (sitecustomize) with JAX_PLATFORMS=axon (a
tunneled TPU), so setting the env var here is too late for the config default —
we must go through jax.config, which works as long as no backend has initialized
yet (backends init lazily on first device use).
"""

import os
import sys

# XLA_FLAGS is read at backend-init time, so mutating it here still works.
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (xla_flags + " --xla_force_host_platform_device_count=8").strip()
# Deliberately NOT exporting JAX_PLATFORMS=cpu: it's a no-op in-process (jax is
# pre-imported) and a child python inheriting it hangs in the axon shim.

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture
def make_df():
    import daft_tpu

    def _make(data):
        return daft_tpu.from_pydict(data)

    return _make
