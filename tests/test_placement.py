"""Placement observability: CostBreakdown terms, the decision ledger,
per-query scopes under concurrency, recalibration cache invalidation,
calibration gauges, explain_placement / EXPLAIN PLACEMENT, QueryEnd
placements, the /api/placement endpoint, the calibrate tool, and the
zero-overhead guard (PR 6 discipline: a host query leaves the registry AND
the ledger untouched)."""

from __future__ import annotations

import json
import threading

import pytest

import daft_tpu
from daft_tpu import col
from daft_tpu.config import execution_config_ctx
from daft_tpu.observability import placement
from daft_tpu.observability.metrics import registry
from daft_tpu.ops import costmodel


def _cal(rtt: float = 0.001) -> costmodel.Calibration:
    return costmodel.Calibration(
        rtt_s=rtt, h2d_bytes_per_s=1e9, d2h_bytes_per_s=2e6,
        mm_plane_rows_per_s=5e9, mm_cell_rate=5e10, scatter_rows_per_s=1e8,
        ext_cell_rate=5e9, host_agg_rate=1.5e8, host_factorize_rate=8e6,
        host_probe_rate=3e7)


# ---------------------------------------------------------------------------
# CostBreakdown: float-compatible totals + named terms
# ---------------------------------------------------------------------------

def test_cost_breakdown_terms_and_float_surface():
    cal = _cal(0.010)
    dev = costmodel.device_ungrouped_cost(cal, 1_000_000, 4_000_000, 2,
                                          coalesce=4.0, resident_bytes=8_000)
    assert set(dev.terms) == {"rtt", "h2d", "compute"}
    assert dev.terms["rtt"] == pytest.approx(0.010 / 4.0)
    assert dev.terms["h2d"] == pytest.approx(4_000_000 / 1e9)
    assert dev.total == pytest.approx(sum(dev.terms.values()))
    assert dev.notes["coalesce"] == 4.0
    assert dev.notes["residency_credit_s"] == pytest.approx(8_000 / 1e9)
    # float-compatible comparison/arithmetic (the decision-site contract)
    host = costmodel.host_agg_cost(cal, 1_000_000, 2, grouped=True,
                                   has_predicate=True)
    assert "factorize" in host.terms and "compute" in host.terms
    assert (dev < host) == (dev.total < host.total)
    assert dev * 1e3 == pytest.approx(dev.total * 1e3)
    assert float(dev) == dev.total
    assert (dev + 0.5).total == pytest.approx(dev.total + 0.5)
    d = dev.as_dict()
    assert d["total"] == pytest.approx(dev.total)
    assert d["note_residency_credit_s"] == pytest.approx(8_000 / 1e9)


def test_cost_breakdown_terms_cover_every_tier():
    cal = _cal()
    join = costmodel.device_join_agg_cost(cal, 100_000, 1_000_000, 3, 2, 1,
                                          0, 64, 4096, 100_000)
    assert {"rtt", "h2d", "compute", "d2h", "factorize"} <= set(join.terms)
    mesh = costmodel.mesh_grouped_cost(cal, 1_000_000, 0, 4, 1024, 8,
                                       factorize_rows=1_000_000)
    assert {"mesh_dispatch", "ici", "compute", "factorize"} <= set(mesh.terms)
    hj = costmodel.host_join_agg_cost(cal, 100_000, 3, 2, True, False)
    assert "probe" in hj.terms
    udf = costmodel.device_udf_cost(cal, 4096, 4096 * 1024, 1e9, 4096 * 512)
    assert {"rtt", "h2d", "compute", "d2h"} <= set(udf.terms)
    # add() folds into a named term in place
    before = join.terms["compute"]
    join.add("compute", 0.25)
    assert join.terms["compute"] == pytest.approx(before + 0.25)


# ---------------------------------------------------------------------------
# Ledger records, margins, rendering
# ---------------------------------------------------------------------------

def test_ledger_record_margin_and_render():
    led = placement.PlacementLedger(cap=16)
    cal = _cal(0.090)
    dev = costmodel.device_ungrouped_cost(cal, 200_000, 0, 1)
    host = costmodel.host_agg_cost(cal, 200_000, 1, grouped=False,
                                   has_predicate=True)
    rec = led.record("agg", "host", 200_000, device=dev, host=host,
                     detail="1 aggs, filtered")
    assert rec is not None
    m = rec.margin()
    assert m == pytest.approx(max(dev.total, host.total)
                              / min(dev.total, host.total))
    text = placement.render(led.records())
    assert "#1 agg" in text and "-> host" in text
    assert "rtt" in text and "margin:" in text and "TOTAL" in text
    # observation feeds back into the record and the render
    led.observe(rec, 0.5, term_seconds={"h2d": 0.1, "dispatch": 0.3},
                rows=400_000, dispatches=2)
    assert rec.observed["total"] == 0.5
    assert rec.error_ratio is not None
    assert "observed:" in placement.render(led.records())


def test_ledger_bounded_with_drop_counter():
    led = placement.PlacementLedger(cap=4)
    for i in range(10):
        led.record("agg", "host", i)
    st = led.stats()
    assert st["records"] == 4 and st["dropped"] == 6 and st["seq"] == 10
    # the newest records survive (FIFO eviction of the oldest)
    assert [r.rows for r in led.records()] == [6, 7, 8, 9]
    led_off = placement.PlacementLedger(cap=0)
    assert led_off.record("agg", "host", 1) is None
    assert led_off.stats()["records"] == 0


# ---------------------------------------------------------------------------
# Satellite: concurrent serving — no lost / cross-query-bled records
# ---------------------------------------------------------------------------

def test_concurrent_scopes_no_bleed_no_loss():
    """Hammer the ledger from N session threads, each inside its own
    query_scope: every scope must see exactly its own records (no
    cross-query bleed, none lost) and the process ledger stays bounded with
    an exact drop count — the SpanRecorder cap discipline."""
    led = placement.PlacementLedger(cap=64)
    N, M = 8, 40
    results = {}
    errors = []

    def worker(tid: int) -> None:
        try:
            with placement.query_scope(cap=M) as scope:
                for i in range(M):
                    led.record("agg", "host", rows=tid * 1000 + i,
                               detail=f"t{tid}")
                results[tid] = scope.to_dicts()
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(N)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    for tid in range(N):
        recs = results[tid]
        assert len(recs) == M, f"thread {tid} lost records"
        assert all(r["detail"] == f"t{tid}" for r in recs), "cross-query bleed"
        assert sorted(r["rows"] for r in recs) == [tid * 1000 + i
                                                  for i in range(M)]
    st = led.stats()
    assert st["records"] == 64
    assert st["dropped"] == N * M - 64
    assert st["seq"] == N * M


def test_scope_propagates_to_stage_threads():
    """Decision sites fire on pipeline stage threads; the scope must ride
    spawn_stage like the stats collector (a scope-less stage thread would
    silently drop the query's records)."""
    from daft_tpu.execution.pipeline import spawn_stage

    led = placement.ledger()
    with placement.query_scope() as scope:
        def gen():
            # runs on the spawned stage thread
            led.gate("agg", "stage-thread probe", 123, only_scoped=True)
            yield daft_tpu.from_pydict({"a": [1]})._materialize()[0]

        list(spawn_stage(gen()))
    recs = scope.to_dicts()
    assert len(recs) == 1 and recs[0]["reason"] == "stage-thread probe"


# ---------------------------------------------------------------------------
# Satellite: recalibration invalidates cached placement verdicts
# ---------------------------------------------------------------------------

def test_reset_calibration_invalidates_decision_caches():
    """Regression: reset_calibration() used to leave stale verdicts in the
    executor's decision/mesh-tier caches — a recalibrated process kept
    routing repeat shapes on prices from the discarded Calibration."""
    from daft_tpu.execution import executor

    executor._DECISION_CACHE.put(("stale", "join"), False)
    executor._MESH_TIER_CACHE.put(("stale", "mesh"), True)
    assert len(executor._DECISION_CACHE) and len(executor._MESH_TIER_CACHE)
    costmodel.reset_calibration()
    assert len(executor._DECISION_CACHE) == 0, \
        "stale join verdict survived recalibration"
    assert len(executor._MESH_TIER_CACHE) == 0, \
        "stale mesh verdict survived recalibration"


# ---------------------------------------------------------------------------
# Satellite: effective calibration exported as gauges
# ---------------------------------------------------------------------------

def test_calibration_terms_exported_as_gauges(monkeypatch):
    monkeypatch.setenv("DAFT_TPU_COST_RTT", "0.042")
    monkeypatch.setenv("DAFT_TPU_COST_H2D", "2e9")
    monkeypatch.setenv("DAFT_TPU_COST_D2H", "3e6")
    # the mesh terms are live-probed like rtt/h2d when unset (r15) — pin
    # them so the gauge assertion is deterministic on any device count
    monkeypatch.setenv("DAFT_TPU_COST_ICI", "4.5e10")
    monkeypatch.setenv("DAFT_TPU_COST_MESH_DISPATCH", "2e-3")
    costmodel.reset_calibration()
    try:
        cal = costmodel.calibrate()
        assert cal.rtt_s == 0.042
        snap = registry().snapshot()
        assert snap["cost_rtt_s"] == 0.042
        assert snap["cost_h2d_bytes_per_s"] == 2e9
        assert snap["cost_d2h_bytes_per_s"] == 3e6
        assert snap["cost_ici_bytes_per_s"] == 4.5e10
        d = costmodel.calibration_dict()
        assert d["rtt_s"] == 0.042 and d["mm_cell_rate"] == 5e10
    finally:
        costmodel.reset_calibration()
    # reset zeroes the gauges (no stale terms after recalibration) and
    # calibration_dict reports un-calibrated honestly
    assert registry().snapshot()["cost_rtt_s"] == 0.0
    assert costmodel.calibration_dict() == {}


# ---------------------------------------------------------------------------
# Zero-overhead guard (PR 6 discipline)
# ---------------------------------------------------------------------------

def test_placement_zero_overhead_on_host_path():
    """A plain host query (no scope) must leave the process ledger AND the
    metrics registry untouched — placement observability can never tax the
    unobserved path. Covers BOTH common host routes: device_mode=off, and
    the default auto mode on a CPU backend where a large query crosses the
    min-rows AND backend gates (those are only_scoped — scope-less queries
    record nothing)."""
    led = placement.ledger()
    seq_before = led.stats()["seq"]
    before = registry().snapshot()
    df = daft_tpu.from_pydict({"a": list(range(1000)), "b": ["x", "y"] * 500})
    with execution_config_ctx(device_mode="off"):
        out = (df.where(col("a") >= 500)
               .groupby("b").agg(col("a").sum().alias("s")).to_pydict())
    assert len(out["b"]) == 2
    big = daft_tpu.from_pydict({"k": [i % 3 for i in range(80_000)],
                                "v": [float(i) for i in range(80_000)]})
    with execution_config_ctx(device_mode="auto", device_min_rows=1,
                              mesh_devices=1):
        big.groupby("k").agg(col("v").sum().alias("s")).to_pydict()
    assert led.stats()["seq"] == seq_before, "ledger touched on host path"
    assert registry().diff(before) == {}, "registry touched on host path"


# ---------------------------------------------------------------------------
# End to end: costed auto decision on a (simulated) accelerator backend
# ---------------------------------------------------------------------------

def test_explain_placement_costed_decision(monkeypatch):
    """The auto tier on a 90ms tunneled link cost-rejects a grouped agg to
    host; explain_placement must show BOTH per-term tables, the margin, and
    the host verdict — and the placement counters must attribute it."""
    import jax

    monkeypatch.setenv("DAFT_TPU_COST_RTT", "0.090")
    monkeypatch.setenv("DAFT_TPU_COST_H2D", "1e6")   # slow link: host wins
    monkeypatch.setenv("DAFT_TPU_COST_D2H", "1e6")
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    costmodel.reset_calibration()
    before = registry().snapshot()
    try:
        df = daft_tpu.from_pydict({
            "k": [i % 7 for i in range(80_000)],
            "v": [float(i % 101) for i in range(80_000)]})
        with execution_config_ctx(device_mode="auto", device_min_rows=1,
                                  mesh_devices=1):
            q = df.groupby("k").agg(col("v").sum().alias("s"))
            text = q.explain_placement()
    finally:
        costmodel.reset_calibration()
    assert "grouped agg" in text and "-> host" in text
    assert "margin:" in text and "rtt" in text and "factorize" in text
    diff = registry().diff(before)
    assert diff.get("placement_decisions_total", 0) >= 1
    assert diff.get("placement_host_wins", 0) >= 1


def test_forced_priced_run_feeds_back_observed(monkeypatch):
    """device_mode=on + DAFT_TPU_PLACEMENT_PRICE_FORCED: the forced dispatch
    carries a priced breakdown AND an observation (total seconds, per-term
    span seconds, dispatches, rows), the error-ratio gauge moves, and
    QueryEnd.placements ships the record."""
    monkeypatch.setenv("DAFT_TPU_PLACEMENT_PRICE_FORCED", "1")
    from daft_tpu.observability.subscribers import (attach_subscriber,
                                                    detach_subscriber)

    ends = []

    class _Sub:
        def on_query_end(self, e):
            ends.append(e)

    before = registry().snapshot()
    sub = _Sub()
    attach_subscriber(sub)
    try:
        df = daft_tpu.from_pydict({
            "k": [i % 13 for i in range(50_000)],
            "v": [float(i % 97) for i in range(50_000)]})
        with execution_config_ctx(device_mode="on", device_min_rows=1,
                                  mesh_devices=1):
            out = (df.groupby("k").agg(col("v").sum().alias("s"))
                   .sort("k").to_pydict())
        assert len(out["k"]) == 13
    finally:
        detach_subscriber(sub)
    diff = registry().diff(before)
    assert diff.get("placement_forced_runs", 0) >= 1
    assert diff.get("placement_feedback_total", 0) >= 1
    assert "cost_model_error_ratio" in diff
    placements = [p for e in ends for p in e.placements]
    assert placements, "QueryEnd carried no placement records"
    rec = next(p for p in placements if p.get("observed"))
    assert rec["forced"] and rec["chosen"] == "device"
    assert rec["device"]["total"] > 0          # priced under PRICE_FORCED
    assert rec["observed"]["total"] > 0
    # observed total is the DEVICE span sum, not the feed-loop wall clock
    # (which includes draining upstream host work) — wall rides along
    assert rec["observed"]["wall"] >= rec["observed"]["total"]
    assert rec["observed"].get("dispatches", 0) >= 1
    assert rec["observed"].get("rows", 0) == 50_000
    assert "error_ratio" in rec


def test_feedback_tee_does_not_steal_profiler_spans(monkeypatch):
    """A query profiled (SpanRecorder active) while placement feedback tees
    device spans must still receive every span — the tee forwards."""
    from daft_tpu.observability.runtime_stats import (SpanRecorder,
                                                      current_spans,
                                                      set_spans)

    outer = SpanRecorder()
    prev = current_spans()
    set_spans(outer)
    try:
        df = daft_tpu.from_pydict({
            "k": [i % 5 for i in range(20_000)],
            "v": [float(i) for i in range(20_000)]})
        with execution_config_ctx(device_mode="on", device_min_rows=1,
                                  mesh_devices=1):
            df.groupby("k").agg(col("v").sum().alias("s")).to_pydict()
    finally:
        set_spans(prev)
    names = {s["name"] for s in outer.drain()}
    assert any(n.startswith("device.") for n in names), \
        f"profiler lost device spans to the placement tee: {names}"


# ---------------------------------------------------------------------------
# Surfaces: SQL EXPLAIN PLACEMENT, /api/placement, event-log v9
# ---------------------------------------------------------------------------

def test_sql_explain_placement():
    df = daft_tpu.from_pydict({"a": [1, 2, 3], "b": [1.0, 2.0, 3.0]})
    out = daft_tpu.sql("EXPLAIN PLACEMENT SELECT a, sum(b) AS s FROM df "
                       "GROUP BY a", df=df).to_pydict()
    assert out["explain"][0] == "== Placement Decisions =="
    with pytest.raises(ValueError, match="requires a query"):
        daft_tpu.sql("EXPLAIN PLACEMENT")


def test_api_placement_endpoint():
    from daft_tpu.observability.dashboard import launch
    from urllib.request import urlopen

    d = launch()
    try:
        placement.ledger().record("agg", "host", 42,
                                  device=costmodel.device_ungrouped_cost(
                                      _cal(), 42, 0, 1),
                                  host=costmodel.host_agg_cost(
                                      _cal(), 42, 1, False, False))
        body = json.loads(urlopen(d.url + "/api/placement").read())
        assert {"records", "stats", "error", "calibration"} <= set(body)
        assert body["stats"]["records"] >= 1
        assert any(r["site"] == "agg" for r in body["records"])
        # the placement counters are scrapeable from the first scrape
        text = urlopen(d.url + "/metrics").read().decode()
        assert "daft_tpu_placement_decisions_total" in text
        assert "daft_tpu_cost_model_error_ratio" in text
        assert "daft_tpu_cost_rtt_s" in text
    finally:
        d.shutdown()


def test_event_log_query_end_carries_placements(tmp_path, monkeypatch):
    from daft_tpu.observability.event_log import (disable_event_log,
                                                  enable_event_log)

    monkeypatch.setenv("DAFT_TPU_PLACEMENT_PRICE_FORCED", "1")
    p = str(tmp_path / "ev.jsonl")
    sub = enable_event_log(p)
    try:
        df = daft_tpu.from_pydict({
            "k": [i % 3 for i in range(20_000)],
            "v": [float(i) for i in range(20_000)]})
        with execution_config_ctx(device_mode="on", device_min_rows=1,
                                  mesh_devices=1):
            df.groupby("k").agg(col("v").sum().alias("s")).to_pydict()
    finally:
        disable_event_log(sub)
    events = [json.loads(line) for line in open(p)]
    ends = [e for e in events if e["event"] == "query_end"]
    assert ends and all(e["schema_version"] == 11 for e in events)
    placements = [p for e in ends for p in e.get("placements", [])]
    assert placements and placements[0]["site"] in ("agg", "grouped agg")


# ---------------------------------------------------------------------------
# Calibrate tool
# ---------------------------------------------------------------------------

def test_calibrate_suggest_from_records():
    from daft_tpu.tools import calibrate as cal_tool

    calibration = {f.name: getattr(_cal(0.001), f.name)
                   for f in _cal(0.001).__dataclass_fields__.values()}
    # a device-chosen record whose observed h2d ran 4x slower than priced
    # and whose dispatch window (minus the 2-dispatch rtt floor) ran 10x the
    # predicted compute term
    records = [{
        "site": "agg", "chosen": "device", "rows": 100_000,
        "device": {"total": 0.011, "rtt": 0.001, "h2d": 0.004,
                   "compute": 0.006},
        "host": {"total": 0.02, "compute": 0.02},
        "observed": {"total": 0.078, "h2d": 0.016, "dispatch": 0.062,
                     "d2h": 0.0, "rows": 100_000, "dispatches": 2},
        "error_ratio": 7.4,
    }]
    report = cal_tool.suggest(records, calibration)
    assert report["samples"] == 1
    assert report["terms"]["h2d"]["observed_over_predicted"] == 4.0
    # h2d bandwidth scales down by the observed ratio: 1e9 / 4
    assert float(report["suggestions"]["DAFT_TPU_COST_H2D"]) == \
        pytest.approx(2.5e8)
    assert "DAFT_TPU_COST_MM_RATE" in report["suggestions"]
    assert report["error_ratio_median"] == 7.4
    text = cal_tool.render(report)
    assert "suggested overrides" in text and "DAFT_TPU_COST_H2D" in text


def test_calibrate_cli_ledger_mode(tmp_path, capsys):
    from daft_tpu.tools import calibrate as cal_tool

    dump = {"records": [], "calibration": {}}
    p = tmp_path / "ledger.json"
    p.write_text(json.dumps(dump))
    assert cal_tool.main(["--ledger", str(p), "--json"]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["samples"] == 0 and report["suggestions"] == {}


# ---------------------------------------------------------------------------
# bench --compare cost-model drift warning (satellite)
# ---------------------------------------------------------------------------

def test_bench_compare_warns_on_error_ratio_drift(tmp_path, capsys):
    import bench

    old = {"metric": "m", "value": 100.0, "unit": "rows/sec",
           "per_query_ms": {"q1": 10.0}, "cost_model_error_ratio": 1.2}
    new = {"metric": "m", "value": 101.0, "unit": "rows/sec",
           "per_query_ms": {"q1": 9.9}, "cost_model_error_ratio": 5.0}
    po, pn = tmp_path / "old.json", tmp_path / "new.json"
    po.write_text(json.dumps(old))
    pn.write_text(json.dumps(new))
    assert bench.compare(str(po), str(pn)) == 0  # drift warns, never gates
    out = capsys.readouterr().out
    assert "WARNING: cost_model_error_ratio drifted" in out
    # within 2x: silent
    new["cost_model_error_ratio"] = 1.9
    pn.write_text(json.dumps(new))
    bench.compare(str(po), str(pn))
    assert "WARNING" not in capsys.readouterr().out
