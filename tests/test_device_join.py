"""Device join+aggregate fusion (ops/device_join.py): the gather-network join
must produce EXACTLY the host engine's results — nulls, filtered dims, chained
dims, string predicates, and fallbacks included. device_mode="on" forces the
device path (these tests run it on the CPU backend, where jit semantics are
identical to TPU)."""

import numpy as np
import pytest

import daft_tpu
from daft_tpu import col, lit
from daft_tpu.config import execution_config_ctx
from daft_tpu.ops import counters


def _both(q):
    with execution_config_ctx(device_mode="off"):
        host = q().to_pydict()
    counters.reset()
    with execution_config_ctx(device_mode="on"):
        dev = q().to_pydict()
    return host, dev, counters.device_join_batches


def _assert_close(host, dev):
    assert list(host.keys()) == list(dev.keys())
    for c in host:
        hv, dv = host[c], dev[c]
        assert len(hv) == len(dv), (c, len(hv), len(dv))
        for a, b in zip(hv, dv):
            if isinstance(a, float) and isinstance(b, float):
                assert abs(a - b) <= 1e-6 * max(1.0, abs(a)), (c, a, b)
            else:
                assert a == b, (c, a, b)


@pytest.fixture(scope="module")
def star():
    rng = np.random.default_rng(9)
    n = 20_000
    fact = daft_tpu.from_pydict({
        "f_k1": [int(x) if x % 37 else None for x in rng.integers(0, 500, n)],
        "f_v": rng.uniform(0, 100, n).tolist(),
        "f_tag": rng.choice(["aa", "bb", "cc", "dd"], n).tolist(),
        "f_q": rng.integers(1, 50, n).tolist(),
    }).collect()
    d1 = daft_tpu.from_pydict({           # keyed dim with a chained FK
        "d1_k": list(range(500)),
        "d1_grp": [f"g{i % 7}" for i in range(500)],
        "d1_w": [float(i % 13) for i in range(500)],
        "d1_k2": [i % 40 for i in range(500)],
    }).collect()
    d2 = daft_tpu.from_pydict({           # second-hop dim
        "d2_k": list(range(40)),
        "d2_name": [f"n{i % 5}" for i in range(40)],
        "d2_flag": [i % 3 == 0 for i in range(40)],
    }).collect()
    return fact, d1, d2


def test_single_dim_grouped_matches(star):
    fact, d1, _ = star

    def q():
        return (fact.join(d1, left_on="f_k1", right_on="d1_k")
                .groupby("d1_grp")
                .agg(col("f_v").sum().alias("sv"),
                     (col("f_v") * col("d1_w")).sum().alias("svw"),
                     col("f_v").count().alias("c"))
                .sort("d1_grp"))

    host, dev, jb = _both(q)
    assert jb > 0, "device join path never ran"
    _assert_close(host, dev)


def test_chained_dims_and_dim_filter(star):
    fact, d1, d2 = star

    def q():
        return (fact.join(d1, left_on="f_k1", right_on="d1_k")
                .join(d2, left_on="d1_k2", right_on="d2_k")
                .where(col("d2_flag") == lit(True))
                .groupby("d2_name")
                .agg(col("f_v").sum().alias("sv"))
                .sort("d2_name"))

    host, dev, jb = _both(q)
    assert jb > 0
    _assert_close(host, dev)


def test_fact_string_predicate_lowered_to_codes(star):
    fact, d1, _ = star

    def q():
        return (fact.where(col("f_tag").is_in(["aa", "cc"]))
                .join(d1, left_on="f_k1", right_on="d1_k")
                .groupby("d1_grp")
                .agg(col("f_q").sum().alias("sq"))
                .sort("d1_grp"))

    host, dev, jb = _both(q)
    assert jb > 0
    _assert_close(host, dev)


def test_fact_string_group_key_with_dim_math(star):
    fact, d1, _ = star

    def q():
        return (fact.join(d1, left_on="f_k1", right_on="d1_k")
                .groupby("f_tag")
                .agg((col("f_v") * (1 - col("d1_w") / 100)).sum().alias("rev"))
                .sort("f_tag"))

    host, dev, jb = _both(q)
    assert jb > 0
    _assert_close(host, dev)


def test_ungrouped_join_agg(star):
    fact, d1, _ = star

    def q():
        return (fact.join(d1, left_on="f_k1", right_on="d1_k")
                .where(col("d1_grp").is_in(["g1", "g3"]))
                .agg(col("f_v").sum().alias("s"), col("f_v").mean().alias("m"),
                     col("f_v").count().alias("c")))

    host, dev, jb = _both(q)
    assert jb > 0
    _assert_close(host, dev)


def test_null_fact_keys_never_match(star):
    fact, d1, _ = star
    # ~1/37 of f_k1 are null; inner-join must drop them on both paths

    def q():
        return (fact.join(d1, left_on="f_k1", right_on="d1_k")
                .agg(col("f_v").count().alias("c")))

    host, dev, jb = _both(q)
    assert jb > 0
    _assert_close(host, dev)
    with execution_config_ctx(device_mode="off"):
        total = fact.count_rows()
    assert host["c"][0] < total  # nulls really were dropped


def test_non_unique_dim_key_falls_back_to_host(star):
    fact, _, _ = star
    dup = daft_tpu.from_pydict({
        "d_k": [1, 2, 2, 3], "d_w": [1.0, 2.0, 3.0, 4.0]}).collect()

    def q():
        return (fact.join(dup, left_on="f_k1", right_on="d_k")
                .agg(col("d_w").sum().alias("s")))

    host, dev, jb = _both(q)
    assert jb == 0, "non-unique dim keys must not take the device join"
    _assert_close(host, dev)


def test_high_cardinality_groups_fall_back(star):
    fact, _, _ = star
    big_dim = daft_tpu.from_pydict({
        "b_k": list(range(500)),
        "b_id": [f"id{i}" for i in range(500)],
    }).collect()

    def q():
        # group by (b_id x f_q): cardinality 500*49 >> 4096 matmul ceiling
        return (fact.join(big_dim, left_on="f_k1", right_on="b_k")
                .groupby("b_id", "f_q")
                .agg(col("f_v").sum().alias("s"))
                .sort(["b_id", "f_q"]).limit(50))

    host, dev, _jb = _both(q)
    _assert_close(host, dev)


def test_tpch_device_join_sweep():
    """All 22 TPC-H queries with device_mode=on match host exactly, and the
    star-join queries actually ride the device join path."""
    from benchmarking.tpch.datagen import load_dataframes
    from benchmarking.tpch.queries import ALL_QUERIES

    tables = {k: v.collect() for k, v in load_dataframes(sf=0.01, seed=0).items()}
    rode_device = []
    for qn in range(1, 23):
        with execution_config_ctx(device_mode="off"):
            host = ALL_QUERIES[qn](tables).to_pydict()
        counters.reset()
        with execution_config_ctx(device_mode="on"):
            dev = ALL_QUERIES[qn](tables).to_pydict()
        if counters.device_join_batches:
            rode_device.append(qn)
        _assert_close(host, dev)
    assert set(rode_device) >= {3, 5, 10, 12, 14, 19}, rode_device


def test_tpch_q3_q10_ride_device_topn():
    """The ORDER BY + LIMIT tails of q3/q10 fuse into the device program
    (DeviceJoinTopN): group tables never leave the device, only K winner rows
    are fetched — the shape that makes orderkey-cardinality groupbys
    device-viable (VERDICT r4 next #1/#4)."""
    from benchmarking.tpch.datagen import load_dataframes
    from benchmarking.tpch.queries import ALL_QUERIES

    tables = {k: v.collect() for k, v in load_dataframes(sf=0.01, seed=0).items()}
    for qn in (3, 10):
        with execution_config_ctx(device_mode="off"):
            host = ALL_QUERIES[qn](tables).to_pydict()
        counters.reset()
        with execution_config_ctx(device_mode="on"):
            dev = ALL_QUERIES[qn](tables).to_pydict()
        assert counters.device_topn_runs == 1, \
            (qn, counters.device_topn_runs, counters.rejections)
        _assert_close(host, dev)


def test_wide_int_dim_planes_exact_past_2_24(star):
    """Dim-side int64/int32 columns ride the packed f32 gather as digit
    planes and recombine exactly — and must STAY exact through the stage
    compiler (ADVICE r5 high: the f64 recombine was downcast to f32 by fcast,
    quantizing values past 2^24). SUM/MIN/MAX over wide int dim columns must
    match the host bit-for-bit."""
    fact, _, _ = star
    wide = daft_tpu.from_pydict({
        "w_k": list(range(500)),
        # int64 values far past 2^24 (and sums past 2^32)
        "w_big": [300_266_000_000 + i * 7_919 for i in range(500)],
        # int32 values past 2^24 (f32 quantizes these)
        "w_mid": np.asarray([16_777_216 + i * 3 for i in range(500)],
                            dtype=np.int32),
        "w_grp": [f"g{i % 5}" for i in range(500)],
    }).collect()

    def q():
        return (fact.join(wide, left_on="f_k1", right_on="w_k")
                .groupby("w_grp")
                .agg(col("w_big").sum().alias("s64"),
                     col("w_big").min().alias("mn64"),
                     col("w_big").max().alias("mx64"),
                     col("w_mid").sum().alias("s32"),
                     col("w_mid").min().alias("mn32"),
                     col("w_mid").max().alias("mx32"))
                .sort("w_grp"))

    host, dev, jb = _both(q)
    assert jb > 0, "device join path never ran"
    # bit-for-bat integer equality — no float tolerance
    for c in host:
        assert host[c] == dev[c], (c, host[c], dev[c])


def test_auto_mode_cpu_backend_stays_on_host(star):
    """auto mode on a CPU backend must run the host plan AND record why
    (rejection log, VERDICT r4 next #1) — device joins only engage on a real
    accelerator via the measured cost model."""
    fact, d1, _ = star

    def q():
        return (fact.join(d1, left_on="f_k1", right_on="d1_k")
                .groupby("d1_grp").agg(col("f_v").sum().alias("s")).sort("d1_grp"))

    counters.reset()
    with execution_config_ctx(device_mode="auto", device_min_rows=1):
        out = q().to_pydict()
    assert counters.device_join_batches == 0
    assert any("cpu backend" in k for k in counters.rejections), \
        counters.rejections
    with execution_config_ctx(device_mode="off"):
        assert out == q().to_pydict()
