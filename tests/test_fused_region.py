"""Whole-stage device fusion (ops/region.py + the Pallas kernel tier).

The planner collapses a maximal Filter/Project chain under an Aggregate into
ONE fused device region — a single jit program priced jointly by the cost
model and dispatched behind the usual start_run()/feed_batch()/finalize()
contract. These tests pin the region's correctness contract:

- region vs unfused-per-operator device vs host: 3-way bit-identity
  (including int64 exactness past 2^53 and null group keys)
- a mid-region DeviceFallback reruns the ENTIRE buffered region on host,
  bit-identically
- the Pallas segment-reduce kernels match jax.ops.segment_* in interpret
  mode, and the DAFT_TPU_PALLAS=on end-to-end path matches the XLA tiers
- device_mode=off queries import neither the region module nor the Pallas
  tier and leave an empty device-counter registry diff (zero overhead)
"""

import sys

import numpy as np
import pytest

import daft_tpu
from daft_tpu import col
from daft_tpu.config import execution_config_ctx
from daft_tpu.observability.metrics import registry
from daft_tpu.ops import counters


def _chain_query(d):
    """filter -> project -> groupby-agg: the canonical fused-region shape."""
    return (d.select(col("k"), (col("v") * 3).alias("w"), col("v"))
            .where(col("w") > -2400)
            .groupby("k")
            .agg(col("w").sum().alias("s"),
                 col("w").count().alias("c"),
                 col("v").min().alias("lo"))
            .sort("k"))


def _data(n=4096, null_keys=False, big=False):
    rng = np.random.default_rng(7)
    keys = rng.choice(["a", "b", "c", "d", None] if null_keys
                      else ["a", "b", "c", "d"], n).tolist()
    if big:
        # past 2^53: only the stage's int64 digit/scatter planes keep these
        # exact — any float round-trip would corrupt low bits
        base = (1 << 60) + 12345
        vals = [(base + int(i)) * (1 if i % 2 else -1) for i in range(n)]
    else:
        vals = rng.integers(-1000, 1000, n).tolist()
    return {"k": keys, "v": vals}


@pytest.mark.parametrize("null_keys", [False, True])
def test_region_three_way_bit_identity(null_keys):
    data = _data(null_keys=null_keys)
    with execution_config_ctx(device_mode="on", region_mode="on"):
        fused = _chain_query(daft_tpu.from_pydict(data)).to_pydict()
    with execution_config_ctx(device_mode="on", region_mode="off"):
        unfused = _chain_query(daft_tpu.from_pydict(data)).to_pydict()
    with execution_config_ctx(device_mode="off"):
        host = _chain_query(daft_tpu.from_pydict(data)).to_pydict()
    assert fused == unfused
    assert fused == host


def test_region_int64_exactness_past_2_53():
    data = _data(n=512, big=True)
    q = lambda d: (d.where(col("v") != 0).groupby("k")
                   .agg(col("v").sum().alias("s"), col("v").max().alias("hi"))
                   .sort("k"))
    with execution_config_ctx(device_mode="on", region_mode="on"):
        fused = q(daft_tpu.from_pydict(data)).to_pydict()
    with execution_config_ctx(device_mode="off"):
        host = q(daft_tpu.from_pydict(data)).to_pydict()
    assert fused == host
    assert any(abs(v) > (1 << 53) for v in fused["hi"])


def test_region_attribution_counters_and_explain():
    data = _data()
    counters.reset()
    with execution_config_ctx(device_mode="on", region_mode="on"):
        report = _chain_query(daft_tpu.from_pydict(data)).explain_analyze()
    assert counters.device_region_dispatches > 0
    # project+filter+agg = 3 ops amortized over every region dispatch
    assert (counters.device_region_ops_fused
            == 3 * counters.device_region_dispatches)
    assert "fused region: 3 ops" in report
    assert "project" in report and "filter" in report


def test_region_fuses_fewer_dispatches_than_unfused():
    """The tentpole's perf claim at counter granularity: the fused region
    dispatches ONE device program where the unfused plan runs the chain as
    separate host operators feeding a bare-agg device stage."""
    data = _data()
    counters.reset()
    with execution_config_ctx(device_mode="on", region_mode="on"):
        fused = _chain_query(daft_tpu.from_pydict(data)).to_pydict()
    fused_d = counters.device_grouped_batches
    assert counters.device_region_dispatches == fused_d > 0
    counters.reset()
    with execution_config_ctx(device_mode="on", region_mode="off"):
        unfused = _chain_query(daft_tpu.from_pydict(data)).to_pydict()
    assert fused == unfused
    # legacy capture still serves the agg on device, but the region path must
    # not dispatch MORE often than it
    assert fused_d <= max(counters.device_grouped_batches, 1)


def test_mid_region_fallback_reruns_whole_region_on_host(monkeypatch):
    """A DeviceFallback AFTER batches were fed and buffered discards every
    partial device accumulation and replays the ENTIRE buffered region
    through the host operators, bit-identically."""
    from daft_tpu.ops import grouped_stage as gs

    data = _data()
    with execution_config_ctx(device_mode="off"):
        host = _chain_query(daft_tpu.from_pydict(data)).to_pydict()

    fed = {"n": 0}
    real_feed = gs.GroupedAggRun.feed_batch

    def feeding(self, batch):
        real_feed(self, batch)
        fed["n"] += 1

    def exploding_finalize(self):
        raise gs.DeviceFallback("injected mid-region failure")

    monkeypatch.setattr(gs.GroupedAggRun, "feed_batch", feeding)
    monkeypatch.setattr(gs.GroupedAggRun, "finalize", exploding_finalize)
    with execution_config_ctx(device_mode="on", region_mode="on"):
        out = _chain_query(daft_tpu.from_pydict(data)).to_pydict()
    assert fed["n"] > 0, "device region never accumulated before the fallback"
    assert out == host


# ======================================================================================
# Pallas kernel tier
# ======================================================================================

def test_pallas_windowed_sum_matches_segment_sum():
    import jax.numpy as jnp
    import jax.ops

    from daft_tpu.ops.pallas_kernels import segment_sum_planes_windowed

    rng = np.random.default_rng(1)
    N, P, CAP = 65536, 4, 4096
    planes = rng.integers(0, 256, (N, P)).astype(np.float32)  # digit planes
    codes = rng.integers(0, CAP + 1, N).astype(np.int32)      # CAP = trash
    out = np.asarray(segment_sum_planes_windowed(planes, codes, CAP,
                                                 interpret=True))
    ref = jax.ops.segment_sum(jnp.asarray(planes, jnp.float64),
                              jnp.asarray(codes), num_segments=CAP + 1)[:CAP]
    assert (out == np.asarray(ref)).all(), "windowed kernel is not bit-exact"


def test_pallas_extremes_match_segment_min_max():
    import jax.numpy as jnp
    import jax.ops

    from daft_tpu.ops.pallas_kernels import segment_extreme_planes

    rng = np.random.default_rng(2)
    N, Q, CAP = 8192, 3, 512
    planes = rng.normal(size=(N, Q)).astype(np.float32)
    codes = rng.integers(0, CAP + 1, N).astype(np.int32)
    mn = np.asarray(segment_extreme_planes(planes, codes, CAP, "min",
                                           interpret=True))
    mx = np.asarray(segment_extreme_planes(planes, codes, CAP, "max",
                                           interpret=True))
    jc = jnp.asarray(codes)
    ref_mn = jax.ops.segment_min(jnp.asarray(planes), jc,
                                 num_segments=CAP + 1)[:CAP]
    ref_mx = jax.ops.segment_max(jnp.asarray(planes), jc,
                                 num_segments=CAP + 1)[:CAP]
    # segment_min/max yield +/-inf fill for empty segments too (f32)
    assert (mn == np.asarray(ref_mn)).all()
    assert (mx == np.asarray(ref_mx)).all()


def test_pallas_end_to_end_parity_and_counters():
    """DAFT_TPU_PALLAS=on forces the kernel tier (interpret mode off-silicon);
    results must match the XLA tiers bit for bit and the dispatch counter
    must attribute the kernel runs."""
    rng = np.random.default_rng(3)
    n = 6000
    data = {"k": rng.integers(0, 300, n).tolist(),
            "v": rng.integers(-1000, 1000, n).tolist()}
    q = lambda d: (d.where(col("v") > -500).groupby("k")
                   .agg(col("v").sum().alias("s"),
                        col("v").count().alias("c"),
                        col("v").mean().alias("m"))
                   .sort("k"))
    counters.reset()
    with execution_config_ctx(device_mode="on", pallas_mode="on"):
        r_pallas = q(daft_tpu.from_pydict(data)).to_pydict()
    assert counters.pallas_dispatches > 0
    assert counters.pallas_fallbacks == 0
    with execution_config_ctx(device_mode="on", pallas_mode="off"):
        r_xla = q(daft_tpu.from_pydict(data)).to_pydict()
    with execution_config_ctx(device_mode="off"):
        r_host = q(daft_tpu.from_pydict(data)).to_pydict()
    assert r_pallas == r_xla
    assert r_pallas == r_host


def test_pallas_lowering_failure_falls_back_to_xla(monkeypatch):
    """A kernel that fails to lower latches the stage onto the XLA tiers —
    the batch reruns through the standard program and the fallback counter
    attributes the reroute."""
    from daft_tpu.ops import grouped_stage as gs
    from daft_tpu.ops import pallas_kernels as pk

    def broken(*a, **k):
        raise RuntimeError("mosaic lowering failed (injected)")

    monkeypatch.setattr(pk, "segment_sum_planes_windowed", broken)
    rng = np.random.default_rng(4)
    data = {"k": rng.integers(0, 50, 2048).tolist(),
            "v": rng.integers(0, 100, 2048).tolist()}
    q = lambda d: (d.groupby("k").agg(col("v").sum().alias("s")).sort("k"))
    counters.reset()
    with execution_config_ctx(device_mode="on", pallas_mode="on"):
        out = q(daft_tpu.from_pydict(data)).to_pydict()
    with execution_config_ctx(device_mode="off"):
        host = q(daft_tpu.from_pydict(data)).to_pydict()
    assert out == host
    assert counters.pallas_fallbacks > 0
    assert counters.pallas_dispatches == 0
    assert gs is not None  # keep the import referenced


def test_pallas_ineligible_stages_stay_on_xla():
    """f64-exact stages (float min/max) must never route to the f32 kernel
    tier, even under DAFT_TPU_PALLAS=on."""
    rng = np.random.default_rng(5)
    data = {"k": rng.integers(0, 20, 1024).tolist(),
            "f": rng.normal(size=1024).tolist()}
    q = lambda d: (d.groupby("k").agg(col("f").min().alias("lo"),
                                      col("f").sum().alias("s")).sort("k"))
    counters.reset()
    with execution_config_ctx(device_mode="on", pallas_mode="on"):
        out = q(daft_tpu.from_pydict(data)).to_pydict()
    assert counters.pallas_dispatches == 0
    # forcing the kernel tier changed nothing: ineligible stages keep the
    # exact XLA program (host comparison would only re-test the pre-existing
    # f32-vs-f64 device sum contract, not the gate)
    with execution_config_ctx(device_mode="on", pallas_mode="off"):
        xla = q(daft_tpu.from_pydict(data)).to_pydict()
    assert out == xla


def test_region_host_path_narrows_to_referenced_columns():
    """Absorbing a pruning Project moves the region's base BELOW it, so the
    raw stream is full-width; the executor must narrow to the referenced
    columns before the host path filters/buffers (the SF10 q1 regression: a
    wide never-referenced string column riding whole through filter/concat)."""
    from daft_tpu.execution.executor import _region_keep_columns
    from daft_tpu.plan import physical as pp
    from daft_tpu.plan.physical import translate

    n = 512
    data = {"k": [i % 7 for i in range(n)],
            "v": list(range(n)),
            "pad": ["x" * 64] * n}  # never referenced by the region
    q = lambda d: (d.select("k", "v", (col("v") * 2).alias("w"))
                   .where(col("w") > 4)
                   .groupby("k").agg(col("w").sum().alias("s"))
                   .sort("k"))
    with execution_config_ctx(device_mode="on", region_mode="on"):
        plan = translate(q(daft_tpu.from_pydict(data))._builder.optimize()._plan)
        node = next(nd for nd in plan.walk()
                    if isinstance(nd, pp.DeviceGroupedAgg))
        keep = _region_keep_columns(node, grouped=True)
        fused = q(daft_tpu.from_pydict(data)).to_pydict()
    assert "pad" in node.input.schema.column_names()  # base IS the wide table
    assert keep is not None and "pad" not in keep
    assert set(keep) == {"k", "v"}
    with execution_config_ctx(device_mode="off"):
        host = q(daft_tpu.from_pydict(data)).to_pydict()
    assert fused == host


# ======================================================================================
# Zero overhead when the device tier is off
# ======================================================================================

def test_zero_overhead_device_off():
    """device_mode=off queries import neither ops.region nor the Pallas tier
    and leave an empty device/pallas counter diff."""
    sys.modules.pop("daft_tpu.ops.region", None)
    sys.modules.pop("daft_tpu.ops.pallas_kernels", None)

    data = _data(n=256)
    counters.reset()
    before = registry().snapshot()
    with execution_config_ctx(device_mode="off"):
        out = _chain_query(daft_tpu.from_pydict(data)).to_pydict()
    assert len(out["k"]) > 0
    assert "daft_tpu.ops.region" not in sys.modules, \
        "host-only query imported the fused-region module"
    assert "daft_tpu.ops.pallas_kernels" not in sys.modules, \
        "host-only query imported the Pallas kernel tier"
    diff = {k: v for k, v in registry().diff(before).items() if v}
    assert not any(k.startswith(("device_", "pallas_")) for k in diff), diff
