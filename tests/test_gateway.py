"""Gateway wire layer: auth, framing, reconnect-resume, concurrent tenants,
result caching/invalidation/eviction, QoS caps on the wire, and the
restartable driver (kill -9 the gateway mid-replay, relaunch, resume).

Everything runs on the CPU backend against loopback sockets. The kill -9
test launches ``python -m daft_tpu.gateway`` as a real subprocess (the only
honest way to test SIGKILL) and is guarded by requires_fault_injection.
"""

import os
import signal
import socket
import struct
import subprocess
import sys
import threading
import time

import pytest

import daft_tpu
from daft_tpu.gateway import (CachedResult, GatewayClient, GatewayError,
                              GatewayServer, ResultCache)
from daft_tpu.gateway import protocol as proto
from daft_tpu.observability.metrics import registry
from daft_tpu.serving import FairAdmissionQueue, TenantQueueFull

from fault_injection import requires_fault_injection

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

GROUPBY_SQL = "SELECT k, SUM(v) AS s FROM t GROUP BY k ORDER BY k"


def _table(n=20_000, keys=13, salt=0):
    return daft_tpu.from_pydict({
        "k": [i % keys for i in range(n)],
        "v": [float((i + salt) % 1009) for i in range(n)],
        "w": [i % 83 for i in range(n)],
    })


def _ref(df, sql=GROUPBY_SQL):
    return daft_tpu.sql(sql, t=df).to_pydict()


# ---------------------------------------------------------------------------
# auth + framing
# ---------------------------------------------------------------------------

def test_bad_token_rejected_with_typed_error():
    with GatewayServer(tables={"t": _table()},
                       tokens={"acme": "s3cret"}) as srv:
        before = registry().get("gateway_auth_failures")
        with pytest.raises(GatewayError) as ei:
            GatewayClient(srv.host, srv.port, tenant="acme", token="wrong")
        assert ei.value.code == "bad_token"
        # unknown tenant is the same typed rejection (no tenant oracle)
        with pytest.raises(GatewayError) as ei:
            GatewayClient(srv.host, srv.port, tenant="nobody", token="s3cret")
        assert ei.value.code == "bad_token"
        assert registry().get("gateway_auth_failures") >= before + 2
        # the right token still works after the failures
        with GatewayClient(srv.host, srv.port, tenant="acme",
                           token="s3cret") as c:
            assert c.query("SELECT COUNT(*) AS n FROM t")["n"] == [20_000]


def test_open_mode_accepts_any_tenant():
    with GatewayServer(tables={"t": _table()}) as srv:
        with GatewayClient(srv.host, srv.port, tenant="anyone") as c:
            assert c.query("SELECT COUNT(*) AS n FROM t")["n"] == [20_000]


def test_truncated_frame_gets_clean_error_and_server_survives():
    df = _table()
    with GatewayServer(tables={"t": df}) as srv:
        # claim 100 payload bytes, deliver 9, hang up mid-frame
        s = socket.create_connection((srv.host, srv.port), timeout=5)
        s.sendall(struct.pack(">I", 100) + b"J" + b"x" * 9)
        s.close()
        # oversized length prefix: answered with a TYPED error before any
        # payload allocation, then the connection drops
        s = socket.create_connection((srv.host, srv.port), timeout=5)
        proto.send_json(s, {"verb": "hello", "tenant": "a", "token": ""})
        assert proto.recv_json(s)["ok"]
        s.sendall(struct.pack(">I", 1 << 31) + b"J")
        reply = proto.recv_json(s)
        assert reply["ok"] is False and reply["code"] == "frame_too_large"
        s.close()
        # the accept loop and other connections are unharmed
        with GatewayClient(srv.host, srv.port, tenant="a") as c:
            assert c.query(GROUPBY_SQL) == _ref(df)


def test_hello_must_come_first():
    with GatewayServer(tables={"t": _table()}) as srv:
        s = socket.create_connection((srv.host, srv.port), timeout=5)
        proto.send_json(s, {"verb": "execute", "sql": GROUPBY_SQL})
        reply = proto.recv_json(s)
        assert reply["ok"] is False and reply["code"] == "bad_request"
        s.close()


# ---------------------------------------------------------------------------
# prepared handles across reconnects
# ---------------------------------------------------------------------------

def test_reconnect_resumes_prepared_handle():
    df = _table()
    with GatewayServer(tables={"t": df}) as srv:
        c = GatewayClient(srv.host, srv.port, tenant="acme")
        handle = c.prepare(GROUPBY_SQL)
        out1 = c.fetch_pydict(c.execute(handle=handle))
        c.close()
        # a brand-new connection executes by the SAME handle — handles are
        # server-scoped, not connection-scoped
        with GatewayClient(srv.host, srv.port, tenant="acme") as c2:
            out2 = c2.fetch_pydict(c2.execute(handle=handle))
        assert out1 == out2 == _ref(df)


def test_unknown_handle_is_typed_and_client_reprepares():
    df = _table()
    with GatewayServer(tables={"t": df}) as srv:
        with GatewayClient(srv.host, srv.port, tenant="acme") as c:
            with pytest.raises(GatewayError) as ei:
                c.execute(handle="feedfacedeadbeef01234567")
            assert ei.value.code == "unknown_handle"
            # a handle the CLIENT prepared transparently re-prepares from the
            # remembered SQL even after the server forgets it
            handle = c.prepare(GROUPBY_SQL)
            srv._handles.clear()  # simulate eviction/restart
            assert c.fetch_pydict(c.execute(handle=handle)) == _ref(df)


# ---------------------------------------------------------------------------
# concurrent tenants: wire results bit-identical to in-process execution
# ---------------------------------------------------------------------------

def test_concurrent_tenants_bit_identical_to_in_process():
    df = _table(30_000)
    sqls = {
        "groupby": GROUPBY_SQL,
        "filter": "SELECT SUM(v) AS s FROM t WHERE w > 40",
        "minmax": "SELECT w, MIN(v) AS lo, MAX(v) AS hi FROM t "
                  "GROUP BY w ORDER BY w",
    }
    ref = {name: _ref(df, s) for name, s in sqls.items()}
    failures = []
    with GatewayServer(tables={"t": df}, max_concurrent=2) as srv:

        def tenant_thread(tid):
            try:
                with GatewayClient(srv.host, srv.port,
                                   tenant=f"tenant-{tid}") as c:
                    names = list(sqls)
                    for i in range(6):
                        name = names[(tid + i) % len(names)]
                        out = c.query(sqls[name])
                        if out != ref[name]:
                            failures.append((tid, name))
            except Exception as e:  # noqa: BLE001 — surfaced via the list
                failures.append((tid, repr(e)))

        threads = [threading.Thread(target=tenant_thread, args=(t,))
                   for t in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
    assert not failures, failures


# ---------------------------------------------------------------------------
# result cache: hits, source-change invalidation, eviction, thrash
# ---------------------------------------------------------------------------

def test_result_cache_hit_on_repeat_and_invalidation_on_source_change():
    df = _table(salt=0)
    with GatewayServer(tables={"t": df}) as srv:
        with GatewayClient(srv.host, srv.port, tenant="a") as c:
            out1 = c.query(GROUPBY_SQL)
            assert c.last_source == "executed"
            out2 = c.query(GROUPBY_SQL)
            assert c.last_source == "result_cache"
            assert out1 == out2
            # rebind the table to DIFFERENT data: content fingerprints
            # change, the old cache key is unreachable, the query
            # re-executes and returns the NEW data's answer
            df2 = _table(salt=7)
            srv.set_table("t", df2)
            out3 = c.query(GROUPBY_SQL)
            assert c.last_source == "executed"
            assert out3 == _ref(df2) and out3 != out1
            # and the new result caches independently
            assert c.query(GROUPBY_SQL) == out3
            assert c.last_source == "result_cache"


def test_result_cache_bounded_eviction_under_tiny_budget():
    cache = ResultCache(budget_bytes=1000)
    def entry(size):
        return CachedResult([b"x" * size], rows=1, columns=["a"])
    before = registry().get("result_cache_evictions")
    cache.put("k1", entry(400))
    cache.put("k2", entry(400))
    assert cache.stats()["entries"] == 2
    cache.put("k3", entry(400))  # over budget: k1 (LRU) evicted
    st = cache.stats()
    assert st["entries"] == 2 and st["bytes"] <= 1000
    assert cache.get("k1") is None
    assert cache.get("k3") is not None
    assert registry().get("result_cache_evictions") > before
    # an entry larger than the whole budget is refused, not thrashed in
    assert cache.put("huge", entry(2000)) is False
    assert cache.get("k3") is not None


def test_result_cache_zero_budget_disables():
    cache = ResultCache(budget_bytes=0)
    assert cache.put("k", CachedResult([b"x"], 1, ["a"])) is False
    assert cache.get("k") is None


def test_result_cache_thrash_detection(monkeypatch):
    monkeypatch.setenv("DAFT_TPU_GATEWAY_THRASH_WINDOW", "8")
    cache = ResultCache(budget_bytes=100)
    # repeat traffic (2 distinct keys) that never hits: thrash
    for _ in range(4):
        cache.get("a")
        cache.get("b")
    detail = cache.note_thrash()
    assert detail is not None and "thrash" in detail
    # window consumed: one sustained burst -> one trigger
    assert cache.note_thrash() is None


# ---------------------------------------------------------------------------
# QoS: queue caps surface as typed wire errors
# ---------------------------------------------------------------------------

def test_tenant_queue_cap_raises_tenant_queue_full(monkeypatch):
    monkeypatch.setenv("DAFT_TPU_TENANT_QUEUE_CAP_CAPPED", "2")
    q = FairAdmissionQueue()
    q.push("capped", "x0")
    q.push("capped", "x1")
    with pytest.raises(TenantQueueFull):
        q.push("capped", "x2")
    # other tenants are unaffected
    for i in range(5):
        q.push("free", f"y{i}")


def test_tenant_weights_order(monkeypatch):
    monkeypatch.setenv("DAFT_TPU_TENANT_WEIGHT_HEAVY", "3")
    q = FairAdmissionQueue()
    for i in range(6):
        q.push("heavy", f"h{i}")
    for i in range(3):
        q.push("light", f"l{i}")
    order = [q.pop(0) for _ in range(9)]
    # weight-3 tenant drains 3 per rotation visit, weight-1 gets 1
    assert order == ["h0", "h1", "h2", "l0", "h3", "h4", "h5", "l1", "l2"]


def test_over_capacity_maps_to_typed_wire_error():
    df = _table()
    with GatewayServer(tables={"t": df}) as srv:
        def full(*a, **k):
            raise TenantQueueFull("a", 1, 1)
        srv._session.submit = full
        # bypass the result cache (fresh query text) so execute reaches submit
        with GatewayClient(srv.host, srv.port, tenant="a") as c:
            with pytest.raises(GatewayError) as ei:
                c.execute(sql="SELECT SUM(w) AS sw FROM t")
            assert ei.value.code == "over_capacity"


# ---------------------------------------------------------------------------
# cancellation over the wire
# ---------------------------------------------------------------------------

def test_cancel_queued_query_yields_typed_cancelled_error():
    df = _table()
    with GatewayServer(tables={"t": df}, max_concurrent=1) as srv:
        with GatewayClient(srv.host, srv.port, tenant="a") as c:
            qid = c.execute(sql=GROUPBY_SQL)
            assert c.cancel(qid) in (True, False)
            # whichever side won the race, fetch answers deterministically:
            # a typed cancelled error or the full (correct) result
            try:
                out = c.fetch_pydict(qid)
                assert out == _ref(df)
            except GatewayError as e:
                assert e.code == "cancelled"


# ---------------------------------------------------------------------------
# observability: /api/gateway rollup + gateway query records
# ---------------------------------------------------------------------------

def test_gateway_query_records_and_dashboard_rollup():
    import json as _json
    import urllib.request

    from daft_tpu.observability.dashboard import launch

    df = _table()
    dash = launch()
    try:
        with GatewayServer(tables={"t": df}) as srv:
            with GatewayClient(srv.host, srv.port, tenant="acme") as c:
                c.query(GROUPBY_SQL)
                c.query(GROUPBY_SQL)
        with urllib.request.urlopen(dash.url + "/api/gateway",
                                    timeout=10) as r:
            body = _json.load(r)
        acme = body["tenants"]["acme"]
        assert acme["queries"] == 2
        assert acme["executed"] == 1 and acme["result_cache"] == 1
        assert acme["cache_hit_rate"] == 0.5
        assert acme["bytes_streamed"] > 0
        assert body["counters"].get("result_cache_hits", 0) >= 1
    finally:
        dash.shutdown()


def test_gateway_error_and_thrash_are_flight_anomalies(monkeypatch, tmp_path):
    from daft_tpu.observability import flight

    monkeypatch.setenv("DAFT_TPU_FLIGHT_RECORDER", "1")
    monkeypatch.setenv("DAFT_TPU_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("DAFT_TPU_ANOMALY_COOLDOWN_S", "0")
    flight._reset_for_tests()
    try:
        with GatewayServer(tables={"t": _table()},
                           tokens={"acme": "good"}) as srv:
            with pytest.raises(GatewayError):
                GatewayClient(srv.host, srv.port, tenant="acme", token="bad")
            frec = flight.recorder()
            assert frec is not None
            assert frec.dumps, "auth failure produced no dump"
        # thrash trigger path: tiny budget + repeat misses through execute
        with GatewayServer(tables={"t": _table(5000)},
                           result_cache_budget=64) as srv:
            with GatewayClient(srv.host, srv.port, tenant="a") as c:
                # results never fit in 64 bytes -> every repeat misses; the
                # sliding window fills and fires cache_thrash
                for _ in range(40):
                    c.query(GROUPBY_SQL)
        dumps_text = " ".join(frec.dumps)
        assert "cache_thrash" in dumps_text, frec.dumps
    finally:
        flight._reset_for_tests()


def test_doctor_triages_gateway_dump(tmp_path):
    import json as _json

    from daft_tpu.tools.doctor import triage_dump

    dump = {
        "kind": "cache_thrash",
        "detail": "result-cache thrash: hit rate 0.10 over last 32 lookups",
        "ring": [],
        "metrics": {"result_cache_hits": 3, "result_cache_misses": 29,
                    "result_cache_evictions": 14, "result_cache_bytes": 512,
                    "gateway_connections_total": 5},
    }
    lines = "\n".join(triage_dump(dump, "dump.json"))
    assert "result-cache thrash" in lines
    assert "hit rate" in lines
    gw = {
        "kind": "gateway_error",
        "detail": "auth failure for tenant 'acme'",
        "ring": [],
        "metrics": {"gateway_auth_failures": 3,
                    "gateway_connections_total": 7},
    }
    lines = "\n".join(triage_dump(gw, "gw.json"))
    assert "gateway error" in lines and "auth_failures=3" in lines


# ---------------------------------------------------------------------------
# restartable driver: kill -9 the gateway, relaunch, resume from checkpoints
# ---------------------------------------------------------------------------

def _spawn_gateway(ckpt_dir, rows=8000):
    """Launch python -m daft_tpu.gateway as a real subprocess and parse the
    bound port from its banner. The child env drops JAX_PLATFORMS (a child
    inheriting =cpu hangs in this environment's axon shim — see conftest)
    and forces the host path so no device backend ever initializes."""
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    env["DAFT_TPU_DEVICE"] = "off"
    env["DAFT_TPU_CHECKPOINT_DIR"] = str(ckpt_dir)
    proc = subprocess.Popen(
        [sys.executable, "-m", "daft_tpu.gateway", "--port", "0",
         "--demo-rows", str(rows)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=REPO, env=env)
    banner = []

    def read():
        banner.append(proc.stdout.readline())

    t = threading.Thread(target=read, daemon=True)
    t.start()
    t.join(timeout=120)
    assert banner and banner[0], \
        f"gateway printed no banner (rc={proc.poll()})"
    assert "gateway listening on" in banner[0], banner[0]
    host, port = banner[0].rsplit(" ", 1)[1].strip().rsplit(":", 1)
    return proc, host, int(port)


@requires_fault_injection
def test_kill9_gateway_mid_replay_relaunch_resumes(tmp_path):
    """The restartable-driver acceptance: SIGKILL the gateway process while
    a replay stream is in flight, relaunch against the same checkpoint root,
    and the relaunched gateway serves every committed query from checkpoint
    (bit-identical) and re-runs the rest — no client-visible wrong result."""
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    sqls = [
        "SELECT k, SUM(v) AS s FROM t GROUP BY k ORDER BY k",
        "SELECT SUM(v) AS s FROM t WHERE w > 48",
        "SELECT w, MIN(v) AS lo FROM t GROUP BY w ORDER BY w",
    ]
    proc, host, port = _spawn_gateway(ckpt)
    try:
        c = GatewayClient(host, port, tenant="replay", timeout=120)
        first = {}
        # two queries complete (and COMMIT checkpoints); the third is
        # submitted and the gateway dies before its fetch completes
        first[0] = c.query(sqls[0])
        first[1] = c.query(sqls[1])
        assert c.last_source == "executed"
        c.execute(sql=sqls[2])  # in flight, never fetched
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)
        with pytest.raises((GatewayError, OSError, EOFError)):
            c.query(sqls[0])
        c.close()
    finally:
        if proc.poll() is None:
            proc.kill()
    # relaunch over the same checkpoint root: same demo table (deterministic
    # construction -> same content fingerprints -> same checkpoint keys)
    proc2, host2, port2 = _spawn_gateway(ckpt)
    try:
        with GatewayClient(host2, port2, tenant="replay", timeout=120) as c2:
            # committed queries come back from CHECKPOINT, bit-identical
            out0 = c2.fetch_pydict(c2.execute(sql=sqls[0]))
            assert c2.last_source == "checkpoint", c2.last_source
            assert out0 == first[0]
            out1 = c2.fetch_pydict(c2.execute(sql=sqls[1]))
            assert c2.last_source == "checkpoint"
            assert out1 == first[1]
            # the in-flight (uncommitted) query simply re-runs — correct
            # result, no stale serve
            out2 = c2.fetch_pydict(c2.execute(sql=sqls[2]))
            assert c2.last_source in ("executed", "checkpoint")
            assert len(out2["w"]) > 0
    finally:
        proc2.kill()
        proc2.wait(timeout=30)


def test_checkpoint_restore_across_server_instances(tmp_path, monkeypatch):
    """In-process flavor of the restartable driver (no subprocess): a second
    GatewayServer over the same checkpoint root serves the first server's
    committed result from disk."""
    monkeypatch.setenv("DAFT_TPU_CHECKPOINT_DIR", str(tmp_path))
    df = _table()
    with GatewayServer(tables={"t": df}) as srv:
        with GatewayClient(srv.host, srv.port, tenant="a") as c:
            out1 = c.query(GROUPBY_SQL)
            assert c.last_source == "executed"
    with GatewayServer(tables={"t": df}) as srv2:
        with GatewayClient(srv2.host, srv2.port, tenant="a") as c:
            out2 = c.query(GROUPBY_SQL)
            assert c.last_source == "checkpoint"
            assert out2 == out1
