"""Statistics + cost-based optimization: zone-map pruning, join reordering,
broadcast build-side selection."""

import os

import numpy as np
import pytest

import daft_tpu
from daft_tpu import col
from daft_tpu.plan import physical as pp
from daft_tpu.plan.stats import estimate_rows, selectivity


def _phys(df):
    from daft_tpu.plan.physical import translate

    return translate(df._builder.optimize()._plan)


# ---------------------------------------------------------------------------
# estimates
# ---------------------------------------------------------------------------

def test_estimate_rows_filter_and_join():
    a = daft_tpu.from_pydict({"k": list(range(1000)), "v": [1.0] * 1000})
    b = daft_tpu.from_pydict({"k": list(range(100))})
    assert estimate_rows(a._builder._plan) == 1000
    filtered = a.where(col("v") == 1.0)
    est = estimate_rows(filtered._builder._plan)
    assert 50 <= est <= 200  # eq selectivity around 0.1
    joined = a.join(b, on="k")
    est_j = estimate_rows(joined._builder._plan)
    assert est_j == 1000  # FK assumption: max side


def test_selectivity_composition():
    p = (col("a") == 1) & (col("b") > 2)
    assert selectivity(p) == pytest.approx(0.1 * 0.3)
    assert selectivity((col("a") == 1) | (col("b") == 2)) == pytest.approx(0.2)


# ---------------------------------------------------------------------------
# zone-map pruning
# ---------------------------------------------------------------------------

def test_zone_map_prunes_files(tmp_path):
    """Files whose row-group stats contradict the predicate never become scan
    tasks (metadata-only decision)."""
    for i in range(4):
        chunk = daft_tpu.from_pydict({
            "id": list(range(i * 1000, (i + 1) * 1000)),
            "v": [float(i)] * 1000,
        })
        chunk.write_parquet(str(tmp_path / f"f{i}"))
    pattern = str(tmp_path / "*" / "*.parquet")

    df = daft_tpu.read_parquet(pattern).where(col("id") >= 3500)
    plan = _phys(df)
    scans = [n for n in plan.walk() if isinstance(n, pp.TaskScan)]
    assert scans and len(scans[0].tasks) == 1  # only the id in [3000,4000) file
    out = df.to_pydict()
    assert sorted(out["id"]) == list(range(3500, 4000))


def test_zone_map_all_files_pruned(tmp_path):
    d = daft_tpu.from_pydict({"id": list(range(100))})
    d.write_parquet(str(tmp_path / "t"))
    df = daft_tpu.read_parquet(str(tmp_path / "t" / "*.parquet")).where(col("id") > 10**9)
    assert df.to_pydict() == {"id": []}


def test_zone_map_never_prunes_matching(tmp_path):
    d = daft_tpu.from_pydict({"id": [5, 10, 15]})
    d.write_parquet(str(tmp_path / "t"))
    out = (daft_tpu.read_parquet(str(tmp_path / "t" / "*.parquet"))
           .where((col("id") >= 10) & (col("id") <= 10)).to_pydict())
    assert out == {"id": [10]}


# ---------------------------------------------------------------------------
# join reordering
# ---------------------------------------------------------------------------

def _chain_dfs():
    rng = np.random.default_rng(0)
    big = daft_tpu.from_pydict({
        "bk": rng.integers(0, 50, 20_000).tolist(),
        "bval": rng.uniform(0, 1, 20_000).tolist(),
    })
    mid = daft_tpu.from_pydict({
        "bk": list(range(50)), "mk": [i % 10 for i in range(50)],
    })
    small = daft_tpu.from_pydict({
        "mk": list(range(10)), "label": [f"l{i}" for i in range(10)],
    })
    return big, mid, small


def test_join_reorder_starts_from_smallest():
    big, mid, small = _chain_dfs()
    q = big.join(mid, on="bk").join(small, on="mk")
    optimized = q._builder.optimize()._plan

    # find the deepest join: its inputs should be the two SMALL relations
    from daft_tpu.plan import logical as lp

    joins = [n for n in optimized.walk() if isinstance(n, lp.Join)]
    assert joins, "no joins left?"
    deepest = joins[-1]
    l_est = estimate_rows(deepest.left)
    r_est = estimate_rows(deepest.right)
    assert max(l_est, r_est) <= 100, (l_est, r_est)  # big table joins last


def test_join_reorder_preserves_results():
    big, mid, small = _chain_dfs()
    q = (big.join(mid, on="bk").join(small, on="mk")
         .groupby("label").agg(col("bval").sum().alias("s")).sort("label"))
    out = q.to_pydict()
    # manual reference via pandas
    import pandas as pd

    b = big.to_pandas()
    m = mid.to_pandas()
    s = small.to_pandas()
    expect = (b.merge(m, on="bk").merge(s, on="mk")
              .groupby("label")["bval"].sum().reset_index().sort_values("label"))
    assert out["label"] == expect["label"].tolist()
    np.testing.assert_allclose(out["s"], expect["bval"].to_numpy(), rtol=1e-9)


def test_join_reorder_skips_outer_joins():
    big, mid, small = _chain_dfs()
    q = big.join(mid, on="bk", how="left").join(small, on="mk", how="left")
    out = q.count_rows()
    assert out == 20_000


# ---------------------------------------------------------------------------
# broadcast build-side selection
# ---------------------------------------------------------------------------

def test_small_left_side_becomes_build():
    tiny = daft_tpu.from_pydict({"k": list(range(10)), "t": ["x"] * 10})
    big = daft_tpu.from_pydict({
        "k": [i % 10 for i in range(50_000)],
        "v": [float(i) for i in range(50_000)],
    })
    q = tiny.join(big, on="k")
    plan = _phys(q)
    hj = next(n for n in plan.walk() if isinstance(n, pp.HashJoin))
    # right child of the physical join must be the TINY side (the build)
    from daft_tpu.plan.stats import estimate_rows as est  # noqa: F401

    def scan_rows(n):
        while not isinstance(n, pp.InMemoryScan):
            n = n.input
        return sum(p.num_rows for p in n.partitions)

    assert scan_rows(hj.right) == 10
    # results and column order unchanged
    out = q.sort(["k", "v"]).to_pydict()
    assert list(out.keys()) == ["k", "t", "v"]
    assert len(out["k"]) == 50_000


def test_join_reorder_refuses_shared_nonkey_column_names():
    """Relations sharing a NON-key column name must not reorder: the rebuilt
    chain would bind same-named outputs to the wrong source relation."""
    a = daft_tpu.from_pydict({
        "k1": [i % 50 for i in range(20_000)],
        "x": [float(i) / 1e6 for i in range(20_000)],  # all < 1
    })
    b = daft_tpu.from_pydict({"k1": list(range(50)), "k2": [i % 10 for i in range(50)]})
    c = daft_tpu.from_pydict({"k2": list(range(10)), "x": [100.0 + i for i in range(10)]})
    q = a.join(b, on="k1").join(c, on="k2").sort(["k1", "x"]).limit(5)
    out = q.to_pydict()
    # 'x' must still be relation A's values (<1), 'right.x' relation C's (>=100)
    assert all(v < 1.0 for v in out["x"])
    assert all(v >= 100.0 for v in out["right.x"])


def test_join_reorder_preserves_null_equals_null():
    """A reorderable >=3-relation chain with null_equals_null=True must keep
    nulls-match semantics (the rebuilt chain used to drop the flag)."""
    from daft_tpu import col

    a = daft_tpu.from_pydict({"k1": [1, None], "v1": [10, 20]})
    b = daft_tpu.from_pydict({"k1": [1, None], "k2": [5, 6]})
    c = daft_tpu.from_pydict({"k2": [5, 6], "v3": [100, 200]})
    j = (a.join(b, on=col("k1"), null_equals_null=True)
          .join(c, on=col("k2"), null_equals_null=True))
    assert sorted(j.to_pydict()["v1"]) == [10, 20]


def test_simplify_null_predicate_if_else_stays_null():
    """Literal-NULL if_else predicates yield NULL (pc.if_else semantics); the
    optimizer must not fold them to the if_false branch."""
    import daft_tpu as dt
    from daft_tpu import col, lit

    df = daft_tpu.from_pydict({"a": [1, 2, 3]})
    pred = lit(None).cast(dt.DataType.bool())
    out = df.select(pred.if_else(col("a"), col("a") * 10).alias("r")).to_pydict()
    assert out == {"r": [None, None, None]}
