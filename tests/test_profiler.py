"""Query timeline profiler: stall-attributed operator time, Chrome-trace
export with per-worker task lanes and device spans, the Prometheus /metrics
surface, straggler detection, spill-counter registry plumbing, and the bench
perf-regression gate (ISSUE 6)."""

import json
import os
import sys
import time
import urllib.request

import numpy as np
import pytest

import daft_tpu
import daft_tpu.runners as runners
from daft_tpu import col
from daft_tpu.config import execution_config_ctx
from daft_tpu.observability.events import OperatorStats, TaskStats
from daft_tpu.observability.runtime_stats import (SpanRecorder, StatsCollector,
                                                 current_spans, profile_span,
                                                 set_collector, set_spans)


class _FakeNode:
    def __init__(self, name):
        self._name = name

    def name(self):
        return self._name


class _Part:
    num_rows = 1


# ---------------------------------------------------------------------------
# Stall attribution: starve / blocked split through the pipeline channels
# ---------------------------------------------------------------------------

def test_channel_starve_attributed_to_consumer():
    """A slow producer starves its consumer: the wait shows up as the
    consumer's starve_seconds, and compute+starve+blocked == seconds."""
    from daft_tpu.execution.pipeline import spawn_stage

    c = StatsCollector()
    producer, consumer = _FakeNode("producer"), _FakeNode("consumer")

    def produce():
        for _ in range(3):
            time.sleep(0.03)
            yield _Part()

    set_collector(c)
    try:
        upstream = spawn_stage(c.wrap(producer, produce()), node=producer)

        def consume():
            for part in upstream:
                yield part

        n = sum(p.num_rows for p in c.wrap(consumer, consume()))
    finally:
        set_collector(None)
    assert n == 3
    stats = {s.name: s for s in c.finish()}
    cons = stats["consumer"]
    assert cons.starve_seconds > 0.05, cons
    assert cons.compute_seconds < cons.starve_seconds
    for s in stats.values():
        assert s.seconds == pytest.approx(
            s.compute_seconds + s.starve_seconds + s.blocked_seconds)


def test_channel_blocked_attributed_to_producer():
    """A slow consumer backpressures the producer through the bounded
    channel: the producer's blocked_seconds captures the put-side waits."""
    from daft_tpu.execution.pipeline import spawn_stage

    c = StatsCollector()
    producer = _FakeNode("producer")

    def produce():
        for _ in range(8):
            yield _Part()

    set_collector(c)
    try:
        upstream = spawn_stage(c.wrap(producer, produce()), maxsize=1,
                               node=producer)
        n = 0
        for part in upstream:
            time.sleep(0.02)  # slow consumer -> full channel upstream
            n += part.num_rows
    finally:
        set_collector(None)
    assert n == 8
    prod = {s.name: s for s in c.finish()}["producer"]
    assert prod.blocked_seconds > 0.03, prod
    assert prod.seconds == pytest.approx(
        prod.compute_seconds + prod.starve_seconds + prod.blocked_seconds)


def test_stable_node_ids_survive_id_reuse():
    """Sequential node ids: two distinct nodes never share stats even if
    CPython hands the second the first's recycled id() (the collector anchors
    every wrapped node, making reuse impossible while it is alive)."""
    c = StatsCollector()
    ids = set()
    for i in range(50):
        # no reference kept by the caller — without anchoring, id() reuse
        # across iterations would be near-certain here
        nid = c.node_id(_FakeNode(f"n{i}"))
        assert nid not in ids
        ids.add(nid)
    assert ids == set(range(1, 51))


def test_explain_analyze_shows_stall_columns():
    rng = np.random.default_rng(0)
    df = daft_tpu.from_pydict({
        "k": rng.choice(["a", "b", "c"], 20_000).tolist(),
        "v": rng.uniform(0, 1, 20_000).tolist(),
    })
    report = (df.where(col("v") > 0.25)
              .groupby("k").agg(col("v").sum().alias("s"))
              .explain_analyze())
    assert "compute" in report and "starve" in report and "blocked" in report
    assert "== Runtime Stats ==" in report


# ---------------------------------------------------------------------------
# SpanRecorder + device spans
# ---------------------------------------------------------------------------

def test_span_recorder_profile_span_and_cap():
    rec = SpanRecorder(cap=2)
    set_spans(rec)
    try:
        with profile_span("a", "device", rows=5):
            pass
        with profile_span("b", "io"):
            pass
        with profile_span("c", "io"):  # over cap -> dropped, not grown
            pass
    finally:
        set_spans(None)
    assert current_spans() is None
    spans = rec.drain()
    assert [s["name"] for s in spans] == ["a", "b"]
    assert spans[0]["args"] == {"rows": 5}
    assert rec.dropped == 1
    # no recorder active: profile_span must not record anywhere
    with profile_span("ghost", "device"):
        pass
    assert rec.drain() == []


def test_device_stage_records_dispatch_spans():
    """DAFT_TPU_DEVICE=on (JAX CPU backend): the device agg path emits
    h2d/dispatch/d2h spans while a recorder is installed."""
    rng = np.random.default_rng(1)
    df = daft_tpu.from_pydict({
        "k": rng.integers(0, 8, 30_000).tolist(),
        "v": rng.uniform(0, 100, 30_000).tolist(),
    })
    rec = SpanRecorder()
    set_spans(rec)
    try:
        with execution_config_ctx(device_mode="on"):
            out = df.groupby("k").agg(col("v").sum().alias("s")).to_pydict()
    finally:
        set_spans(None)
    assert len(out["k"]) == 8
    names = {s["name"] for s in rec.drain()}
    assert "device.dispatch" in names, names
    assert "device.d2h" in names, names


# ---------------------------------------------------------------------------
# Chrome trace export
# ---------------------------------------------------------------------------

def _mk_task(stage, task_id, worker, started, exec_s, ops=(), **kw):
    return TaskStats(stage_id=stage, task_id=task_id, worker_id=worker,
                     queue_wait_s=0.0, schedule_latency_s=0.0, exec_s=exec_s,
                     rows_out=10, bytes_out=100, retries=0,
                     started_at=started, operator_stats=tuple(ops), **kw)


def test_chrome_trace_synthetic_lanes_and_offsets():
    from daft_tpu.distributed.trace import QueryTrace

    tr = QueryTrace("qtest")
    t0 = tr.started_wall
    op = OperatorStats(node_id=1, name="PhysAgg", rows_out=10, batches_out=1,
                      seconds=0.3, compute_seconds=0.1, starve_seconds=0.15,
                      blocked_seconds=0.05)
    tr.tasks.append(_mk_task("s0", "t0", "worker-0", t0 + 0.1, 0.5, [op]))
    tr.tasks.append(_mk_task("s0", "t1", "worker-1", t0 + 0.1, 0.4))
    tr.task_spans["t0"] = [{"name": "device.dispatch", "cat": "device",
                            "ts": t0 + 0.2, "dur": 0.05,
                            "args": {"rows": 10}}]
    # heartbeats: worker-1's clock runs 2s behind the driver
    tr.add_heartbeat({"worker_id": "worker-1", "ts": t0 - 2.0,
                      "recv_ts": t0 + 0.001})
    tr.add_heartbeat({"worker_id": "worker-1", "ts": t0 - 1.5,
                      "recv_ts": t0 + 0.6})
    offs = tr.clock_offsets()
    assert offs["worker-1"] == pytest.approx(2.001, abs=1e-6)

    data = tr.to_chrome_trace(total_seconds=1.0)
    evs = data["traceEvents"]
    assert all(isinstance(e["pid"], int) or e["ph"] == "M" for e in evs)
    xs = [e for e in evs if e["ph"] == "X"]
    assert all(e["dur"] >= 0 and isinstance(e["ts"], float) for e in xs)
    # two worker processes with task slices
    task_pids = {e["pid"] for e in xs if e["cat"] == "task"}
    assert len(task_pids) == 2
    # the device span landed on worker-0's device/io lane at a real offset
    disp = [e for e in xs if e["name"] == "device.dispatch"]
    assert len(disp) == 1 and disp[0]["ts"] == pytest.approx(0.2e6, abs=1e3)
    # operator + stall slices
    assert any(e["cat"] == "operator" and e["name"] == "PhysAgg" for e in xs)
    assert any(e["name"] == "starve:PhysAgg" for e in xs)
    # stage + query slices on the driver (pid 0)
    assert any(e["cat"] == "stage" and e["pid"] == 0 for e in xs)
    assert any(e["cat"] == "query" and e["pid"] == 0 for e in xs)
    assert data["metadata"]["clock_offsets_s"]["worker-1"] > 1.9
    json.dumps(data)  # wholly serializable


def test_straggler_report_thresholds(monkeypatch):
    from daft_tpu.distributed.trace import QueryTrace

    tr = QueryTrace("qs")
    tr._stage_order.append("s0")   # normally set by record_task
    tr._shuffle["s0"] = {}
    for i in range(4):
        tr.tasks.append(_mk_task("s0", f"t{i}", "w0", 0.0, 0.1))
    tr.tasks.append(_mk_task("s0", "slow", "w1", 0.0, 1.0))
    flagged = tr.straggler_report(threshold=2.0)
    assert [r["task_id"] for r in flagged] == ["slow"]
    assert flagged[0]["ratio"] == pytest.approx(10.0)
    assert tr.straggler_report(threshold=20.0) == []
    # env knob steers the default
    monkeypatch.setenv("DAFT_TPU_STRAGGLER_K", "20")
    assert tr.straggler_report() == []
    monkeypatch.setenv("DAFT_TPU_STRAGGLER_K", "2")
    rep = tr.straggler_report()
    assert len(rep) == 1
    # and the EXPLAIN ANALYZE render names it
    assert "stragglers" in tr.render() and "slow" in tr.render()


def test_distributed_groupby_join_chrome_trace_e2e(tmp_path):
    """Acceptance: a 2-worker distributed groupby-join query with device
    leases produces a Chrome trace with task lanes from both workers and at
    least one device-dispatch slice, via explain_analyze(profile=...)."""
    from daft_tpu.distributed.runner import DistributedRunner

    rng = np.random.default_rng(7)
    n = 40_000
    fact = daft_tpu.from_pydict({
        "k": rng.integers(0, 40, n).tolist(),
        "v": rng.uniform(0, 100, n).tolist(),
    })
    dim = daft_tpu.from_pydict({
        "k": list(range(40)),
        "grp": [i % 5 for i in range(40)],
    })
    q = (fact.join(dim, on="k")
         .groupby("grp").agg(col("v").sum().alias("s"))
         .sort("grp"))

    path = str(tmp_path / "trace.json")
    native = runners.NativeRunner()
    with execution_config_ctx(device_mode="on"):
        r = DistributedRunner(num_workers=2, n_partitions=2, device_workers=2)
        try:
            runners.set_runner(r)
            report = q.explain_analyze(profile=path)
        finally:
            runners.set_runner(native)
            r.shutdown()
    assert "== Distributed Stages ==" in report
    with open(path) as f:
        data = json.load(f)
    evs = data["traceEvents"]
    xs = [e for e in evs if e["ph"] == "X"]
    # task lanes from >= 2 workers
    workers = {e["args"]["worker_id"] for e in xs if e["cat"] == "task"}
    assert len(workers) >= 2, workers
    # >= 1 device-dispatch slice shipped back from a device-leased worker
    assert any(e["name"] == "device.dispatch" for e in xs), \
        sorted({e["name"] for e in xs})
    # per-operator stall split rides along and reconciles
    ops = [e for e in xs if e["cat"] == "operator"]
    assert ops
    for e in ops:
        a = e["args"]
        assert a["compute_s"] >= 0 and a["starve_s"] >= 0 and a["blocked_s"] >= 0


# ---------------------------------------------------------------------------
# Dashboard HTTP surface: /metrics + trace download + JSON endpoints
# ---------------------------------------------------------------------------

def _get(url, timeout=5):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.headers.get("Content-Type", ""), r.read()


def _parse_prometheus(text):
    """{"name": value} for plain samples; histogram samples keep labels."""
    out = {}
    types = {}
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# TYPE"):
            _, _, name, typ = line.split()
            types[name] = typ
            continue
        assert not line.startswith("#"), line
        name, val = line.rsplit(" ", 1)
        out[name] = float(val)
    return out, types


def test_metrics_endpoint_prometheus_format():
    from daft_tpu.observability.dashboard import launch

    dash = launch()
    try:
        daft_tpu.from_pydict({"a": list(range(100))}).where(
            col("a") > 10).to_pydict()
        ctype, body = _get(dash.url + "/metrics")
        assert ctype.startswith("text/plain")
        samples, types = _parse_prometheus(body.decode())
        # acceptance: hbm_bytes_resident served as a gauge
        assert "daft_tpu_hbm_bytes_resident" in samples
        assert types["daft_tpu_hbm_bytes_resident"] == "gauge"
        # engine counters exported with counter TYPE
        assert types.get("daft_tpu_device_stage_batches") == "counter"
        # spill counters reach the scrape surface (registry-backed)
        assert "daft_tpu_spill_batches" in samples
        # query-latency histogram: count >= 1, cumulative buckets monotone,
        # +Inf bucket == count
        assert types["daft_tpu_query_latency_seconds"] == "histogram"
        assert samples["daft_tpu_query_latency_seconds_count"] >= 1
        buckets = [(k, v) for k, v in samples.items()
                   if k.startswith("daft_tpu_query_latency_seconds_bucket")]
        assert buckets
        vals = [v for _, v in buckets]
        assert vals == sorted(vals)
        assert vals[-1] == samples["daft_tpu_query_latency_seconds_count"]
    finally:
        dash.shutdown()


def test_histogram_quantiles():
    from daft_tpu.observability.metrics import Histogram

    h = Histogram()
    for _ in range(90):
        h.observe(0.02)
    for _ in range(10):
        h.observe(4.0)
    assert h.quantile(0.5) == 0.025   # bucket upper bound containing p50
    assert h.quantile(0.99) == 5.0
    lines = h.prometheus_lines("m")
    assert lines[0] == "# TYPE m histogram"
    assert 'm_bucket{le="+Inf"} 100' in lines
    assert "m_count 100" in lines


def test_dashboard_trace_download_and_endpoints():
    """Distributed query through an attached dashboard: every JSON endpoint
    answers with the right shape and /api/query/<id>/trace serves the
    Chrome-trace download."""
    from daft_tpu.distributed.runner import DistributedRunner
    from daft_tpu.observability.dashboard import launch

    rng = np.random.default_rng(3)
    df = daft_tpu.from_pydict({
        "k": rng.integers(0, 20, 10_000).tolist(),
        "v": rng.uniform(0, 1, 10_000).tolist(),
    })
    dash = launch()
    native = runners.NativeRunner()
    r = DistributedRunner(num_workers=2, n_partitions=2)
    try:
        runners.set_runner(r)
        out = df.groupby("k").agg(col("v").sum().alias("s")).to_pydict()
        assert len(out["k"]) == 20
        _, body = _get(dash.url + "/api/queries")
        queries = json.loads(body)
        assert queries and queries[0]["done"]
        qid = queries[0]["query_id"]
        _, body = _get(dash.url + f"/api/query/{qid}")
        assert json.loads(body)["query_id"] == qid
        _, body = _get(dash.url + f"/api/query/{qid}/trace")
        trace = json.loads(body)
        assert trace["traceEvents"], trace.get("error_404")
        assert any(e.get("cat") == "task" for e in trace["traceEvents"])
        _, body = _get(dash.url + "/api/query/nope/trace")
        assert json.loads(body)["error_404"] is True
        _, body = _get(dash.url + "/api/engine")
        assert "device_stage_batches" in json.loads(body)
        _, body = _get(dash.url + "/api/workers")
        workers = json.loads(body)
        assert isinstance(workers, dict)
        for w in workers.values():
            assert "busy_fraction" in w and "hbm_bytes" in w
    finally:
        runners.set_runner(native)
        r.shutdown()
        dash.shutdown()


# ---------------------------------------------------------------------------
# Spill counters in the registry (satellite)
# ---------------------------------------------------------------------------

def test_spill_counters_flow_through_registry():
    from daft_tpu.execution import memory as mem
    from daft_tpu.observability.metrics import registry

    rng = np.random.default_rng(5)
    df = daft_tpu.from_pydict({
        "k": rng.integers(0, 500, 50_000).tolist(),
        "v": rng.uniform(0, 1, 50_000).tolist(),
    })
    mem.reset_counters()
    before = registry().snapshot()
    with execution_config_ctx(memory_limit_bytes=64 * 1024, device_mode="off"):
        df.groupby("k").agg(col("v").sum().alias("s")).to_pydict()
    diff = registry().diff(before)
    assert diff.get("spill_batches", 0) > 0, diff
    assert diff.get("spill_bytes", 0) > 0, diff
    # the historical module attributes are a live view over the registry
    assert mem.spills == registry().get("spill_batches")
    assert mem.spill_bytes == registry().get("spill_bytes")
    mem.reset_counters()
    assert mem.spills == 0 and mem.spill_bytes == 0


# ---------------------------------------------------------------------------
# Event log schema round trip (satellite)
# ---------------------------------------------------------------------------

def test_event_log_round_trip(tmp_path):
    from daft_tpu.observability.event_log import (SCHEMA_VERSION,
                                                  disable_event_log,
                                                  enable_event_log)

    assert SCHEMA_VERSION == 11
    p = str(tmp_path / "ev.jsonl")
    sub = enable_event_log(p)
    try:
        daft_tpu.from_pydict({"a": list(range(100))}).where(
            col("a") > 4).to_pydict()
    finally:
        disable_event_log(sub)
    events = [json.loads(l) for l in open(p)]
    assert events and all(e["schema_version"] == 11 for e in events)
    ops = [e for e in events if e["event"] == "operator_stats"]
    assert ops
    for o in ops:
        for f in ("compute_seconds", "starve_seconds", "blocked_seconds"):
            assert f in o, o
        assert o["seconds"] == pytest.approx(
            o["compute_seconds"] + o["starve_seconds"] + o["blocked_seconds"])


# ---------------------------------------------------------------------------
# bench.py --compare perf gate (satellite)
# ---------------------------------------------------------------------------

def _bench_mod():
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import bench

    return bench


def test_bench_compare_flags_regressions(tmp_path, capsys):
    bench = _bench_mod()
    old = {"metric": "tpch_sf1", "value": 1000.0,
           "per_query_ms": {"q1": 100.0, "q3": 200.0, "q6": 50.0}}
    new_ok = {"metric": "tpch_sf1", "value": 1040.0,
              "per_query_ms": {"q1": 95.0, "q3": 198.0, "q6": 49.0}}
    new_bad = {"metric": "tpch_sf1", "value": 900.0,
               "per_query_ms": {"q1": 100.0, "q3": 260.0, "q6": 50.0}}
    po, pok, pbad = (tmp_path / n for n in ("old.json", "ok.json", "bad.json"))
    po.write_text(json.dumps(old))
    pok.write_text(json.dumps(new_ok))
    pbad.write_text(json.dumps(new_bad))

    assert bench.compare(str(po), str(pok)) == 0
    out = capsys.readouterr().out
    assert "OK: no regressions" in out

    n = bench.compare(str(po), str(pbad))
    out = capsys.readouterr().out
    assert n == 2  # q3 (+30%) and the headline rows/sec (-10%)
    assert "REGRESSION" in out and "q3" in out
    # within-tolerance jitter never trips the gate
    new_jitter = {"metric": "tpch_sf1", "value": 980.0,
                  "per_query_ms": {"q1": 103.0, "q3": 204.0, "q6": 51.0}}
    pj = tmp_path / "jitter.json"
    pj.write_text(json.dumps(new_jitter))
    assert bench.compare(str(po), str(pj)) == 0
    # a query missing from NEW is lost coverage -> counted as a regression
    new_dropped = {"metric": "tpch_sf1", "value": 1000.0,
                   "per_query_ms": {"q1": 100.0, "q6": 50.0}}
    pd = tmp_path / "dropped.json"
    pd.write_text(json.dumps(new_dropped))
    assert bench.compare(str(po), str(pd)) == 1
    assert "missing from NEW" in capsys.readouterr().out
