"""Async spill IO: overlapped writes with deferred-error surfacing,
prefetching readers, the carry-preserving merge's tier-1 microbench, the
.tmp-aware dead-pid sweep, and the zero-overhead guards (sync compat path
and unbudgeted queries must never touch the pool, the queue, or the new
counters)."""

import errno
import os
import time

import numpy as np
import pyarrow as pa
import pytest

import daft_tpu
from daft_tpu.config import execution_config, execution_config_ctx
from daft_tpu.execution import memory as mem
from daft_tpu.observability.metrics import registry


@pytest.fixture(autouse=True)
def _clean():
    mem.reset_counters()
    mem.manager().clear()
    yield
    mem.manager().clear()


def _mixed_batch(n=4000):
    from daft_tpu.core.recordbatch import RecordBatch

    rng = np.random.default_rng(3)
    return RecordBatch.from_arrow(pa.table({
        "i": pa.array(rng.integers(-1000, 1000, size=n)),
        "f": pa.array(rng.standard_normal(n)),
        "s": pa.array([f"row-{x % 97}" for x in range(n)]),
        "b": pa.array((np.arange(n) % 3 == 0)),
        "maybe": pa.array([None if x % 7 == 0 else x for x in range(n)],
                          type=pa.int64()),
    }))


def test_async_round_trip_prefetch(tmp_path):
    """Async appends + prefetching read-back round-trip bit-identically
    across mixed dtypes; the prefetch high-water gauge never exceeds the
    configured depth; the cumulative/wall counter pairs both moved."""
    from daft_tpu.memory import SpillFile

    batch = _mixed_batch()
    with execution_config_ctx(memory_limit_bytes=1 << 24,
                              spill_io_threads=2, spill_prefetch_batches=2):
        f = SpillFile(batch.schema, spill_dir=str(tmp_path))
        for _ in range(6):
            f.append(batch)
        f.finish_async()  # publish rides the queue; read() joins below
        got = list(f.read())
    assert sum(b.num_rows for b in got) == 6 * batch.num_rows
    for col in ("i", "f", "s", "b", "maybe"):
        assert got[0].get_column(col).to_pylist() == \
            batch.get_column(col).to_pylist()
    assert registry().get("spill_write_seconds") > 0
    assert registry().get("spill_read_seconds") > 0
    assert registry().snapshot().get("spill_prefetch_inflight", 0) <= 2
    f.delete()
    assert not os.path.exists(f.path) and not os.path.exists(f._tmp)


def test_deferred_write_error_surfaces_and_cleans(tmp_path, monkeypatch):
    """A spill write that fails off-thread (ENOSPC at publish) surfaces as a
    RuntimeError at the next join point (finish/read/append), the ledger
    drops back to zero, and delete() leaves no artifacts behind."""
    from daft_tpu.memory import SpillFile
    from daft_tpu.memory import spill as spill_mod

    batch = _mixed_batch(1000)
    with execution_config_ctx(memory_limit_bytes=1 << 24,
                              spill_io_threads=2, spill_prefetch_batches=2):
        f = SpillFile(batch.schema, spill_dir=str(tmp_path))
        f.append(batch)

        def _enospc(src, dst):
            raise OSError(errno.ENOSPC, "No space left on device", dst)

        monkeypatch.setattr(spill_mod.os, "replace", _enospc)
        f.finish_async()  # the drainer hits ENOSPC publishing off-thread
        deadline = time.time() + 10
        while time.time() < deadline and f._io_err is None:
            time.sleep(0.01)
        assert f._io_err is not None, "drainer never surfaced the IO error"
        with pytest.raises(RuntimeError, match="deferred spill write failed"):
            f.finish()
        with pytest.raises(RuntimeError, match="deferred spill write failed"):
            f.append(batch)
        monkeypatch.undo()
        assert mem.manager().tracked_bytes() == 0, \
            "failed async spill leaked ledger bytes"
        f.delete()
    assert os.listdir(tmp_path) == [], "failed spill left artifacts behind"


def test_gc_sweeps_dead_pid_tmp_not_live(tmp_path):
    """The dead-pid sweep takes half-written .tmp names too (a killed writer
    never publishes them) while a LIVE process's .tmp survives — the
    fully-anchored artifact regex must not let a live writer's in-progress
    file be parsed as anything else."""
    from daft_tpu.memory import gc_stale_spills

    dead = None
    for pid in range(300_000, 300_064):
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            dead = pid
            break
        except OSError:
            continue
    if dead is None:
        pytest.skip("could not find a dead pid on this platform")
    root = tmp_path / "spillroot"
    root.mkdir()
    live_tmp = f"s{os.getpid()}_cafecafe01.arrow.tmp"
    (root / live_tmp).write_bytes(b"x")
    (root / f"s{dead}_deadbeef01.arrow.tmp").write_bytes(b"x")
    (root / f"s{dead}_deadbeef02.arrow").write_bytes(b"x")
    # names that merely RESEMBLE artifacts must never parse a pid out of a
    # prefix match (a bogus dead pid would delete a file we do not own)
    (root / f"s{dead}_deadbeef03.arrow.tmp.bak").write_bytes(b"x")
    removed = gc_stale_spills(str(root))
    assert removed == 2
    assert sorted(os.listdir(root)) == sorted(
        [live_tmp, f"s{dead}_deadbeef03.arrow.tmp.bak"])


def test_merge_microbench_tier1():
    """The bench-oom quick mode's body as a tier-1 gate: a >=32-run external
    sort is bit-identical (asserted inside), the carry-preserving merge
    keys each row once per level (far below the old re-argsort bound), and
    the prefetch high-water respects the knob."""
    import bench

    r = bench.merge_microbench(80_000)
    assert r["runs"] >= 32, f"expected a >=32-run cascade, got {r['runs']}"
    assert 0 < r["merge_sort_rows"] < r["old_merge_bound_rows"], \
        "merge argsort volume not below the old per-round re-sort bound"
    assert r["prefetch_high_water"] <= r["prefetch_depth"]
    assert r["metrics"].get("spill_io_overlap_ratio", 0) >= 0


def test_sync_compat_path_touches_no_async_counters():
    """DAFT_TPU_SPILL_IO_THREADS=0 + PREFETCH=0 reproduces the synchronous
    path exactly: the run still spills and stays bit-identical, but none of
    the async-era counters (write/read cumulative+wall pairs, prefetch
    gauge) ever move."""
    rng = np.random.default_rng(11)
    n = 40_000
    df = daft_tpu.from_pydict({
        "k": rng.integers(0, n, size=n),
        "v": rng.standard_normal(n),
    }).into_batches(1024).collect()
    with execution_config_ctx(memory_limit_bytes=0, device_mode="off"):
        expected = df.sort(["k"]).to_pydict()
    before = registry().snapshot()
    with execution_config_ctx(memory_limit_bytes=64 << 10, device_mode="off",
                              spill_io_threads=0, spill_prefetch_batches=0):
        got = df.sort(["k"]).to_pydict()
    diff = registry().diff(before)
    assert got == expected
    assert diff.get("spill_bytes", 0) > 0, "budget never spilled"
    for name in ("spill_write_seconds", "spill_write_wall_seconds",
                 "spill_read_seconds", "spill_read_wall_seconds"):
        assert not diff.get(name), f"sync compat path moved {name}: {diff}"
    assert registry().snapshot().get("spill_prefetch_inflight", 0) == \
        before.get("spill_prefetch_inflight", 0)


def test_unbudgeted_query_touches_no_spill_state():
    """Zero-overhead guard: with no memory budget the whole spill subsystem
    stays cold — no spill counters move and no IO pool is created for the
    query's sake."""
    from daft_tpu.memory import spill as spill_mod

    rng = np.random.default_rng(13)
    df = daft_tpu.from_pydict({
        "k": rng.integers(0, 1000, size=20_000),
        "v": rng.standard_normal(20_000),
    })
    pools_before = dict(spill_mod._POOLS)
    before = registry().snapshot()
    with execution_config_ctx(memory_limit_bytes=0, device_mode="off"):
        df.sort(["k"]).to_pydict()
        df.groupby("k").agg(daft_tpu.col("v").sum()).to_pydict()
    diff = registry().diff(before)
    spilled = {k: v for k, v in diff.items() if k.startswith("spill_")}
    assert not spilled, f"unbudgeted query moved spill counters: {spilled}"
    assert spill_mod._POOLS == pools_before, \
        "unbudgeted query created a spill IO pool"
