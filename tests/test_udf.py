"""UDF tests (reference test model: tests/udf/* + tests/actor_pool/*)."""

import asyncio

import pytest

import daft_tpu as dt
from daft_tpu import col
from daft_tpu.datatype import DataType


@pytest.fixture
def df():
    return dt.from_pydict({"x": [1, 2, 3], "s": ["a", "b", "c"]})


def test_row_udf(df):
    @dt.func
    def double(x: int) -> int:
        return x * 2

    assert df.select(double(col("x"))).to_pydict() == {"x": [2, 4, 6]}


def test_udf_return_dtype_inference(df):
    @dt.func
    def as_str(x: int) -> str:
        return f"v{x}"

    out = df.select(as_str(col("x")).alias("y"))
    assert out.schema["y"].dtype == DataType.string()
    assert out.to_pydict() == {"y": ["v1", "v2", "v3"]}


def test_batch_udf(df):
    @dt.func(is_batch=True, return_dtype=DataType.float64())
    def scaled(s):
        return dt.Series.from_numpy(s.to_numpy() * 1.5, "x")

    assert df.select(scaled(col("x"))).to_pydict() == {"x": [1.5, 3.0, 4.5]}


def test_multi_arg_udf_with_literal(df):
    @dt.func
    def combine(x: int, s: str, suffix: str) -> str:
        return f"{s}{x}{suffix}"

    out = df.select(combine(col("x"), col("s"), "!").alias("c")).to_pydict()
    assert out == {"c": ["a1!", "b2!", "c3!"]}


def test_process_udf(df):
    @dt.func(use_process=True, max_concurrency=2)
    def sq(x: int) -> int:
        return x * x

    assert df.select(sq(col("x")).alias("y")).to_pydict() == {"y": [1, 4, 9]}


def test_process_udf_error_propagates(df):
    @dt.func(use_process=True)
    def boom(x: int) -> int:
        raise RuntimeError("kapow")

    with pytest.raises(RuntimeError, match="kapow"):
        df.select(boom(col("x"))).to_pydict()


def test_async_udf(df):
    @dt.func
    async def aplus(x: int) -> int:
        await asyncio.sleep(0)
        return x + 10

    assert df.select(aplus(col("x"))).to_pydict() == {"x": [11, 12, 13]}


def test_generator_udf(df):
    @dt.func(return_dtype=DataType.int64())
    def expand(x: int):
        for i in range(x):
            yield i

    out = df.select(col("x"), expand(col("x")).alias("e"))
    assert out.to_pydict()["e"] == [[0], [0, 1], [0, 1, 2]]
    # explode to one row per yielded item
    assert out.explode("e").to_pydict()["e"] == [0, 0, 1, 0, 1, 2]


def test_stateful_cls(df):
    init_count = {"n": 0}

    @dt.cls
    class Adder:
        def __init__(self, base):
            init_count["n"] += 1
            self.base = base

        def add(self, x: int) -> int:
            return self.base + x

    a = Adder(100)
    assert init_count["n"] == 0  # lazy: not constructed at wrap time
    assert df.select(a.add(col("x"))).to_pydict() == {"x": [101, 102, 103]}
    assert init_count["n"] == 1
    df.select(a.add(col("x"))).to_pydict()
    assert init_count["n"] == 1  # instance reused


def test_stateful_cls_in_process(df):
    @dt.cls(use_process=True)
    class Counter:
        def __init__(self):
            self.n = 0

        def tick(self, x: int) -> int:
            self.n += 1
            return self.n

    c = Counter()
    assert df.select(c.tick(col("x")).alias("t")).to_pydict() == {"t": [1, 2, 3]}


def test_legacy_udf_decorator(df):
    @dt.udf(return_dtype=DataType.int64())
    def plus1(s):
        return dt.Series.from_numpy(s.to_numpy() + 1, "x")

    assert df.select(plus1(col("x"))).to_pydict() == {"x": [2, 3, 4]}


def test_udf_split_into_udfproject(df):
    @dt.func
    def double(x: int) -> int:
        return x * 2

    q = df.select(col("s"), double(col("x")).alias("d"), (col("x") + 1).alias("p"))
    from daft_tpu.plan.logical import UDFProject

    opt = q._builder.optimize().plan
    assert any(isinstance(n, UDFProject) for n in opt.walk())
    out = q.to_pydict()
    assert out == {"s": ["a", "b", "c"], "d": [2, 4, 6], "p": [2, 3, 4]}


def test_udf_apply_method(df):
    out = df.select(col("x").apply(lambda v: v * 7, return_dtype=DataType.int64()))
    assert out.to_pydict() == {"x": [7, 14, 21]}


def test_multiple_udfs_in_one_projection_all_isolated():
    import daft_tpu
    from daft_tpu import col
    from daft_tpu.udf import func
    from daft_tpu.plan import logical as lp

    @func
    def f1(x: int) -> int:
        return x + 1

    @func
    def f2(x: int) -> int:
        return x * 2

    df = daft_tpu.from_pydict({"a": [1, 2, 3], "b": [10, 20, 30]})
    q = df.select(f1(col("a")).alias("u1"), f2(col("b")).alias("u2"),
                  (col("a") + col("b")).alias("c"))
    plan = q._builder.optimize()._plan
    n_udf_nodes = sum(1 for n in plan.walk() if isinstance(n, lp.UDFProject))
    assert n_udf_nodes == 2, plan.describe_tree() if hasattr(plan, "describe_tree") else n_udf_nodes
    out = q.to_pydict()
    assert out == {"u1": [2, 3, 4], "u2": [20, 40, 60], "c": [11, 22, 33]}


def test_udf_output_shadowing_input_column_name():
    import daft_tpu
    from daft_tpu import col
    from daft_tpu.udf import func

    @func
    def f1(x: int) -> int:
        return x + 1

    df = daft_tpu.from_pydict({"x": [1, 2], "y": [5, 6]})
    out = df.select(f1(col("y")).alias("x"), (col("x") + 100).alias("keep")).to_pydict()
    assert out == {"x": [6, 7], "keep": [101, 102]}
