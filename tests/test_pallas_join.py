"""Pallas join-probe kernel + in-kernel ICI ring permute (the r19 tentpole)
— interpret-mode parity for every new kernel family under the 8 forced host
devices from conftest.

Covers: hash_probe_index bit-identity vs a host dict probe (int64 past 2^53,
negative keys, null keys, misses), duplicate-key/sentinel probe-table
refusals, the fused probe+segment-sum kernel vs numpy, segment_extreme_int64
exactness past 2^53 (both ops, empty segments), the ring-permute repartition
step bit-identical to the classic all_to_all step, end-to-end device joins
through the probe kernel (single chip + mesh) with lowering-failure fallback
latch / exact host replay, the widened groupby eligibility (int64 extremes on
the kernel tier), the fused repartition's zero-standalone-all_to_all counter
assert, the Pallas what-if side on every join placement record (including
Pallas-ineligible stages), the device_join_pallas_cost arm, calibrate's
kernel-rate suggestions, and the DAFT_TPU_PALLAS=off no-import guard. Run
standalone via `make test-pallas`.
"""

import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import daft_tpu
from daft_tpu import col
from daft_tpu.config import execution_config_ctx
from daft_tpu.ops import counters
from daft_tpu.ops import pallas_kernels as pk


needs_mesh = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices — see conftest")

BIG = (1 << 53) + 11   # past f64's exact-integer range


# ---- kernel-level parity -----------------------------------------------------


def _host_probe(fact_keys, fact_valid, dim_keys, dim_valid):
    lut = {int(k): i for i, (k, v) in enumerate(zip(dim_keys, dim_valid)) if v}
    return np.array([lut.get(int(k), -1) if v else -1
                     for k, v in zip(fact_keys, fact_valid)], dtype=np.int32)


def test_hash_probe_index_matches_host_probe():
    rng = np.random.default_rng(0)
    n_dim = 300
    dim_keys = np.concatenate([
        rng.choice(10_000, n_dim - 100, replace=False).astype(np.int64),
        BIG + np.arange(50, dtype=np.int64),
        -(1 << 62) - np.arange(50, dtype=np.int64),
    ])
    dim_valid = np.ones(n_dim, dtype=bool)
    dim_valid[::41] = False            # null dim keys never match
    n = 4096
    fact_keys = dim_keys[rng.integers(0, n_dim, n)].copy()
    fact_keys[::7] += 1_000_000        # misses
    fact_valid = rng.random(n) > 0.1   # null fact keys
    tbl = pk.build_probe_table(dim_keys, dim_valid)
    fh, fl = pk.probe_key_digits(jnp.asarray(fact_keys),
                                 jnp.asarray(fact_valid))
    idx = np.asarray(pk.hash_probe_index(
        fh, fl, jnp.asarray(tbl[0]), jnp.asarray(tbl[1]), jnp.asarray(tbl[2]),
        interpret=True))
    expect = _host_probe(fact_keys, fact_valid, dim_keys, dim_valid)
    np.testing.assert_array_equal(idx, expect)


def test_probe_table_refuses_duplicates_and_sentinel():
    with pytest.raises(ValueError, match="not unique"):
        pk.build_probe_table(np.array([3, 7, 3], dtype=np.int64))
    with pytest.raises(ValueError, match="sentinel"):
        pk.build_probe_table(np.array([1, pk.PROBE_SENTINEL], dtype=np.int64))
    # a duplicate hidden behind a null mask is fine — nulls never match
    tbl = pk.build_probe_table(np.array([3, 7, 3], dtype=np.int64),
                               np.array([True, True, False]))
    assert tbl[0].shape == (1, 128)


def test_hash_probe_segment_sum_matches_numpy():
    rng = np.random.default_rng(1)
    n_dim, n, cap, p = 200, 4096, 64, 3
    dim_keys = np.concatenate([
        rng.choice(5_000, n_dim - 40, replace=False).astype(np.int64),
        BIG + np.arange(40, dtype=np.int64)])
    planes = rng.integers(0, 100, (n_dim, p)).astype(np.float32)
    fact_keys = dim_keys[rng.integers(0, n_dim, n)].copy()
    fact_keys[::5] = -9               # misses
    fact_valid = rng.random(n) > 0.15
    codes = rng.integers(0, cap, n).astype(np.int32)
    tbl = pk.build_probe_table(dim_keys)
    # pad the value planes to the table slot count (row i -> slot i)
    t = tbl[0].shape[1]
    tp = np.zeros((t, p), dtype=np.float32)
    tp[:n_dim] = planes
    fh, fl = pk.probe_key_digits(jnp.asarray(fact_keys),
                                 jnp.asarray(fact_valid))
    sums, counts = pk.hash_probe_segment_sum(
        fh, fl, jnp.asarray(codes), jnp.asarray(tbl[0]), jnp.asarray(tbl[1]),
        jnp.asarray(tbl[2]), jnp.asarray(tp), cap, interpret=True)
    exp_sums = np.zeros((cap, p), dtype=np.float64)
    exp_counts = np.zeros(cap, dtype=np.int64)
    lut = {int(k): i for i, k in enumerate(dim_keys)}
    for i in range(n):
        if not fact_valid[i]:
            continue
        row = lut.get(int(fact_keys[i]), -1)
        if row < 0:
            continue
        exp_sums[codes[i]] += planes[row]
        exp_counts[codes[i]] += 1
    np.testing.assert_array_equal(np.asarray(sums), exp_sums)
    np.testing.assert_array_equal(np.asarray(counts).astype(np.int64),
                                  exp_counts)


@pytest.mark.parametrize("op", ["min", "max"])
def test_segment_extreme_int64_exact_past_2_53(op):
    rng = np.random.default_rng(2)
    n, cap = 4096, 16
    vals = (1 << 62) + rng.integers(-1000, 1000, n) * (1 << 11)
    vals[::3] = -(1 << 61) - rng.integers(0, 1 << 20, n)[::3]
    mask = rng.random(n) > 0.2
    codes = rng.integers(0, cap - 2, n)   # segments cap-2, cap-1 stay empty
    out, nonempty = pk.segment_extreme_int64(
        jnp.asarray(vals), jnp.asarray(mask), jnp.asarray(codes), cap, op,
        interpret=True)
    info = np.iinfo(np.int64)
    ident = info.max if op == "min" else info.min
    expect = np.full(cap, ident, dtype=np.int64)
    seen = np.zeros(cap, dtype=bool)
    red = np.minimum if op == "min" else np.maximum
    for v, m, c in zip(vals, mask, codes):
        if m:
            expect[c] = red(expect[c], v)
            seen[c] = True
    np.testing.assert_array_equal(np.asarray(out), expect)
    np.testing.assert_array_equal(np.asarray(nonempty), seen)


# ---- ring-permute repartition step -------------------------------------------


@needs_mesh
def test_ring_repartition_step_bit_identical_to_alltoall():
    from daft_tpu.parallel.distributed import (
        default_mesh, sharded_alltoall_repartition_step,
        sharded_ring_repartition_step)

    rng = np.random.default_rng(3)
    n_dev, S = 8, 512
    total = n_dev * S
    mesh = default_mesh(n_dev)
    dest = rng.integers(0, n_dev, total).astype(np.int64)
    row_mask = rng.random(total) > 0.1
    planes = (rng.standard_normal(total),                       # f64
              rng.random(total) > 0.5,                          # bool validity
              (1 << 62) + rng.integers(0, 1 << 20, total))      # int64
    dtypes = tuple(np.asarray(p).dtype for p in planes)
    classic = sharded_alltoall_repartition_step(mesh, dtypes)
    ring = sharded_ring_repartition_step(mesh, dtypes, interpret=True)
    c_counts, c_planes = classic(dest, row_mask, *planes)
    r_counts, r_planes = ring(dest, row_mask, *planes)
    np.testing.assert_array_equal(np.asarray(c_counts), np.asarray(r_counts))
    for cp, rp in zip(c_planes, r_planes):
        np.testing.assert_array_equal(np.asarray(cp), np.asarray(rp))


@needs_mesh
def test_fused_repartition_zero_alltoall_dispatches():
    """The acceptance assert: under pallas_mode=on the repartition + permute
    compile into one program — ZERO standalone all_to_all dispatches while
    the fused-permute counter attributes the exchange, partitions
    bit-identical to the host shuffle."""
    rng = np.random.default_rng(4)
    n = 16_000
    df = daft_tpu.from_pydict({
        "k": rng.integers(0, 997, n).tolist(),
        "v": (rng.random(n) * 100).tolist(),
        "w": [None if i % 17 == 0 else int(i % 31) for i in range(n)],
        "big": (2**53 + rng.integers(0, 1000, n)).tolist(),
    })
    with execution_config_ctx(device_mode="off"):
        host = df.repartition(8, col("k")).collect()
    counters.reset()
    with execution_config_ctx(device_mode="on", mesh_devices=8,
                              device_min_rows=1, pallas_mode="on"):
        fused = df.repartition(8, col("k")).collect()
    assert counters.mesh_alltoall_dispatches == 0
    assert counters.mesh_fused_permute_dispatches > 0
    assert counters.pallas_fallbacks == 0

    from daft_tpu.core.recordbatch import RecordBatch

    def rows(p):
        bs = [b for b in p.batches if b.num_rows]
        if not bs:
            return {}
        b = bs[0] if len(bs) == 1 else RecordBatch.concat(bs)
        return {c: b.get_column(c).to_pylist() for c in ("k", "v", "w", "big")}

    for i, (a, b) in enumerate(zip(host._result, fused._result)):
        assert rows(a) == rows(b), f"partition {i} diverged"


@needs_mesh
def test_ring_permute_failure_latches_to_alltoall(monkeypatch):
    """A runtime lowering failure in the fused exchange latches back onto
    the all_to_all tier and replays the batch exactly — attributed by the
    fallback counter, with identical partitions."""
    from daft_tpu.execution import executor as ex
    from daft_tpu.parallel import distributed as dist

    def broken(*a, **k):
        raise RuntimeError("mosaic lowering failed (injected)")

    rng = np.random.default_rng(5)
    n = 8_000
    df = daft_tpu.from_pydict({
        "k": rng.integers(0, 97, n).tolist(),
        "v": (rng.random(n) * 10).tolist(),
    })
    with execution_config_ctx(device_mode="off"):
        host = df.repartition(8, col("k")).collect()
    monkeypatch.setattr(dist, "sharded_ring_repartition_step", broken)
    counters.reset()
    try:
        with execution_config_ctx(device_mode="on", mesh_devices=8,
                                  device_min_rows=1, pallas_mode="on"):
            out = df.repartition(8, col("k")).collect()
        assert counters.pallas_fallbacks > 0
        assert counters.mesh_alltoall_dispatches > 0
        assert counters.mesh_fused_permute_dispatches == 0
        assert ex._RING_PERMUTE_BROKEN[0]

        from daft_tpu.core.recordbatch import RecordBatch

        def rows(p):
            bs = [b for b in p.batches if b.num_rows]
            if not bs:
                return {}
            b = bs[0] if len(bs) == 1 else RecordBatch.concat(bs)
            return {c: b.get_column(c).to_pylist() for c in ("k", "v")}

        for a, b in zip(host._result, out._result):
            assert rows(a) == rows(b)
    finally:
        # the latch is process-wide: un-latch so later tests see the kernel
        ex._RING_PERMUTE_BROKEN[0] = False


# ---- end-to-end device joins through the probe kernel ------------------------


def _star_tables():
    rng = np.random.default_rng(9)
    n = 6_000
    fact = daft_tpu.from_pydict({
        "f_k1": [int(x) if x % 37 else None for x in rng.integers(0, 200, n)],
        "f_k64": [int(BIG + (x % 150)) if x % 31 else None
                  for x in rng.integers(0, 10_000, n)],
        "f_v": rng.uniform(0, 100, n).tolist(),
        "f_q": rng.integers(1, 50, n).tolist(),
    }).collect()
    d1 = daft_tpu.from_pydict({
        "d1_k": list(range(200)),
        "d1_grp": [f"g{i % 7}" for i in range(200)],
        "d1_w": [float(i % 13) for i in range(200)],
        "d1_k2": [i % 40 for i in range(200)],
    }).collect()
    d2 = daft_tpu.from_pydict({
        "d2_k": list(range(40)),
        "d2_name": [f"n{i % 5}" for i in range(40)],
    }).collect()
    d64 = daft_tpu.from_pydict({
        "d64_k": [int(BIG + i) for i in range(150)],
        "d64_w": [float(i % 17) for i in range(150)],
    }).collect()
    return fact, d1, d2, d64


def _star_query(fact, d1, d2, d64):
    return (fact.join(d1, left_on="f_k1", right_on="d1_k")
                .join(d2, left_on="d1_k2", right_on="d2_k")
                .join(d64, left_on="f_k64", right_on="d64_k")
                .groupby("d1_grp", "d2_name")
                .agg(col("f_v").sum().alias("sv"),
                     col("d64_w").sum().alias("s64"),
                     col("f_q").count().alias("cq"))
                .sort("d1_grp", "d2_name").collect())


def _assert_close(host, dev):
    assert list(host.keys()) == list(dev.keys())
    for c in host:
        for a, b in zip(host[c], dev[c]):
            if isinstance(a, float):
                assert abs(a - b) <= 1e-6 * max(1.0, abs(a)), (c, a, b)
            else:
                assert a == b, (c, a, b)


def test_device_join_probe_end_to_end_parity():
    """Single-chip star join through hash_probe_index: fact-adjacent dims
    (int64 past 2^53 with nulls included) probe in-kernel, the chained dim
    keeps the host index path — results match the host, off-mode is
    bit-identical with zero probe dispatches."""
    fact, d1, d2, d64 = _star_tables()
    with execution_config_ctx(device_mode="off"):
        host = _star_query(fact, d1, d2, d64).to_pydict()
    counters.reset()
    with execution_config_ctx(device_mode="on", pallas_mode="on"):
        dev = _star_query(fact, d1, d2, d64).to_pydict()
    snap = counters.snapshot()
    # two fact-adjacent dims (d1, d64) probe in-kernel; d2 chains off d1
    assert snap.get("pallas_probe_dispatches", 0) >= 2
    assert snap.get("pallas_fallbacks", 0) == 0
    _assert_close(host, dev)
    counters.reset()
    with execution_config_ctx(device_mode="on", pallas_mode="off"):
        dev2 = _star_query(fact, d1, d2, d64).to_pydict()
    assert counters.snapshot().get("pallas_probe_dispatches", 0) == 0
    assert dev2 == dev


def test_device_join_probe_failure_replays_on_host_tier(monkeypatch):
    """A probe kernel that fails at runtime latches the context back onto
    the host index-plane tier and replays the SAME batch — attributed by
    the fallback counter, bit-identical results."""
    def broken(*a, **k):
        raise RuntimeError("mosaic lowering failed (injected)")

    # patch the LIVE module: earlier no-import-guard tests pop the kernel
    # module from sys.modules, so device_join's function-local import may
    # bind a fresher object than this file's module-level `pk`
    import importlib

    pk_live = importlib.import_module("daft_tpu.ops.pallas_kernels")
    monkeypatch.setattr(pk_live, "hash_probe_index", broken)
    fact, d1, d2, d64 = _star_tables()
    with execution_config_ctx(device_mode="off"):
        host = _star_query(fact, d1, d2, d64).to_pydict()
    counters.reset()
    with execution_config_ctx(device_mode="on", pallas_mode="on"):
        dev = _star_query(fact, d1, d2, d64).to_pydict()
    assert counters.pallas_fallbacks > 0
    assert counters.pallas_probe_dispatches == 0
    _assert_close(host, dev)


@needs_mesh
def test_mesh_join_probe_end_to_end_parity():
    """Mesh star join: the sharded index plane builds through the probe
    kernel inside the shard_map program; a filtered dim declines the kernel
    (host visibility folding) and stays identical."""
    rng = np.random.default_rng(11)
    n_fact, n_dim = 12_000, 60
    fact = daft_tpu.from_pydict({
        "fk": rng.integers(0, n_dim + 5, n_fact).tolist(),
        "qty": rng.integers(0, 50, n_fact).tolist(),
        "big": (2**53 + rng.integers(0, 1000, n_fact)).tolist(),
    })
    dim = daft_tpu.from_pydict({
        "dk": list(range(n_dim)),
        "grp": [None if i % 13 == 0 else f"g{i % 7}" for i in range(n_dim)],
        "weight": [float(i % 11) for i in range(n_dim)],
    })

    def q():
        return (fact.join(dim, left_on="fk", right_on="dk")
                .groupby("grp")
                .agg(col("qty").sum().alias("sq"),
                     col("big").sum().alias("sb"))
                .sort("grp").collect())

    with execution_config_ctx(device_mode="off"):
        host = q().to_pydict()
    counters.reset()
    with execution_config_ctx(device_mode="on", mesh_devices=8,
                              pallas_mode="on"):
        mesh_out = q().to_pydict()
    snap = counters.snapshot()
    assert snap.get("mesh_join_runs", 0) > 0
    assert snap.get("pallas_probe_dispatches", 0) > 0
    assert snap.get("pallas_fallbacks", 0) == 0
    assert host == mesh_out

    def qf():
        return (fact.join(dim, left_on="fk", right_on="dk")
                .where(col("weight") < 8)
                .groupby("grp").agg(col("qty").sum().alias("sq"))
                .sort("grp").collect())

    with execution_config_ctx(device_mode="off"):
        host_f = qf().to_pydict()
    with execution_config_ctx(device_mode="on", mesh_devices=8,
                              pallas_mode="on"):
        mesh_f = qf().to_pydict()
    assert host_f == mesh_f


# ---- widened groupby eligibility ---------------------------------------------


def test_widened_groupby_int64_extremes_parity():
    """int64 min/max (sct slots) and integer ext planes no longer disqualify
    a grouped stage from the kernel tier: exact at 1<<62 with nulls and
    negative extremes, off-mode bit-identical."""
    rng = np.random.default_rng(5)
    n = 6_000
    big = 1 << 62
    df = daft_tpu.from_pydict({
        "g": [f"k{i % 37}" for i in range(n)],
        "i64": [None if i % 23 == 0
                else int(big + rng.integers(-1000, 1000) * (1 << 11))
                for i in range(n)],
        "neg": [int(-(1 << 61) - x) for x in rng.integers(0, 1 << 20, n)],
        "i32": rng.integers(-(2**31) + 1, 2**31 - 1, n).tolist(),
        "q": rng.integers(0, 50, n).tolist(),
    }).collect()

    def q():
        return (df.groupby("g")
                .agg(col("i64").min().alias("mn64"),
                     col("i64").max().alias("mx64"),
                     col("neg").min().alias("mnneg"),
                     col("i32").min().alias("mn32"),
                     col("i32").max().alias("mx32"),
                     col("q").sum().alias("sq"))
                .sort("g").collect())

    with execution_config_ctx(device_mode="off"):
        host = q().to_pydict()
    counters.reset()
    with execution_config_ctx(device_mode="on", pallas_mode="on"):
        dev = q().to_pydict()
    assert counters.pallas_dispatches > 0
    assert counters.pallas_fallbacks == 0
    assert host == dev
    counters.reset()
    with execution_config_ctx(device_mode="on", pallas_mode="off"):
        dev2 = q().to_pydict()
    assert counters.pallas_dispatches == 0
    assert dev2 == host


# ---- placement ledger / cost model / calibrate -------------------------------


def test_join_records_carry_pallas_whatif(monkeypatch):
    """Every join decision records the Pallas arm's what-if breakdown —
    including when the kernel is ineligible (pallas_mode=off here): the
    PR 14 host-reject-keeps-mesh-what-if discipline, one tier further."""
    from daft_tpu.observability import placement as _placement

    monkeypatch.setenv("DAFT_TPU_PLACEMENT_PRICE_FORCED", "1")
    fact, d1, d2, d64 = _star_tables()
    with _placement.query_scope() as scope:
        with execution_config_ctx(device_mode="on", pallas_mode="off"):
            _star_query(fact, d1, d2, d64).to_pydict()
    recs = [r for r in scope.to_dicts()
            if r.get("site") in ("join agg", "join topn")]
    assert recs, "no join placement records"
    carrying = [r for r in recs if r.get("pallas")]
    assert carrying, "join records lost the pallas what-if side"
    for r in carrying:
        assert "probe" in r["pallas"], r["pallas"]
        assert r["pallas"].get("total", 0) > 0
        # the arm is a what-if: never a chosen value of its own
        assert r.get("chosen") != "pallas"


def test_device_join_pallas_cost_terms():
    from daft_tpu.ops import costmodel as cm

    cal = cm.calibrate()
    c = cm.device_join_pallas_cost(cal, 100_000, 1 << 20, 1024, 2, 1, 1,
                                   512, 4096, 10_000)
    for term in ("probe", "compute", "factorize", "d2h"):
        assert c.terms.get(term, 0) > 0, (term, c.terms)
    # probe seconds scale with the padded table slots
    c2 = cm.device_join_pallas_cost(cal, 100_000, 1 << 20, 4096, 2, 1, 1,
                                    512, 4096, 10_000)
    assert c2.terms["probe"] > c.terms["probe"]
    assert c2.terms["compute"] == c.terms["compute"]


def test_calibrate_suggests_pallas_rates():
    """Ledger samples whose pallas arm won its gate drive the two kernel-rate
    suggestions; a sample whose arm lost contributes nothing."""
    from daft_tpu.tools.calibrate import suggest

    cal = {"pallas_cell_rate": 1e12, "pallas_probe_cell_rate": 2e12,
           "rtt_s": 0.0005, "h2d_bytes_per_s": 1e9, "d2h_bytes_per_s": 1e9}
    recs = []
    for _ in range(3):
        recs.append({   # grouped shape: compute residual 4x the prediction
            "site": "grouped agg", "chosen": "device", "rows": 100_000,
            "device": {"total": 0.01, "compute": 0.002},
            "pallas": {"total": 0.005, "compute": 0.001},
            "observed": {"dispatch": 0.0045, "dispatches": 1}})
        recs.append({   # join shape: probe residual 0.25x the prediction
            "site": "join agg", "chosen": "device", "rows": 100_000,
            "device": {"total": 0.02, "compute": 0.004},
            "pallas": {"total": 0.006, "probe": 0.002, "compute": 0.001},
            "observed": {"dispatch": 0.002, "dispatches": 1}})
    report = suggest(recs, cal)
    assert report["terms"]["pallas_compute"]["samples"] == 3
    assert report["terms"]["pallas_probe"]["samples"] == 3
    assert float(report["suggestions"]["DAFT_TPU_COST_PALLAS_RATE"]) \
        == pytest.approx(2.5e11)
    assert float(report["suggestions"]["DAFT_TPU_COST_PALLAS_PROBE_RATE"]) \
        == pytest.approx(8e12)
    # an arm that LOST its gate (what-if dwarfs the chosen tier) is not a
    # kernel observation
    lost = suggest([{
        "site": "grouped agg", "chosen": "device", "rows": 1,
        "device": {"total": 0.001, "compute": 0.0005},
        "pallas": {"total": 0.5, "compute": 0.4},
        "observed": {"dispatch": 0.001, "dispatches": 1}}], cal)
    assert "pallas_compute" not in lost["terms"]


def test_pallas_off_join_keeps_kernels_unimported():
    """The zero-overhead contract, extended to the join/repartition wiring:
    DAFT_TPU_PALLAS=off runs never import the kernel module (all new imports
    are gate-guarded and function-local)."""
    sys.modules.pop("daft_tpu.ops.pallas_kernels", None)
    fact, d1, _d2, _d64 = _star_tables()

    def q():
        return (fact.join(d1, left_on="f_k1", right_on="d1_k")
                .groupby("d1_grp").agg(col("f_q").sum().alias("s"))
                .sort("d1_grp").collect())

    with execution_config_ctx(device_mode="on", pallas_mode="off"):
        q().to_pydict()
    assert "daft_tpu.ops.pallas_kernels" not in sys.modules, \
        "off-mode join imported the kernel module"
