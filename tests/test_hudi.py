"""Hudi copy-on-write reader: timeline replay, latest-slice selection,
uncommitted-write invisibility (reference: daft/io/hudi/pyhudi)."""

import os

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import daft_tpu
from daft_tpu import col


def _write_base_file(table, part, file_id, token, instant, data):
    pdir = os.path.join(table, part) if part else table
    os.makedirs(pdir, exist_ok=True)
    path = os.path.join(pdir, f"{file_id}_{token}_{instant}.parquet")
    pq.write_table(pa.table(data), path)
    return path


def _commit(table, instant):
    with open(os.path.join(table, ".hoodie", f"{instant}.commit"), "w") as f:
        f.write("{}")


@pytest.fixture
def hudi_table(tmp_path):
    table = str(tmp_path / "hudi_tbl")
    os.makedirs(os.path.join(table, ".hoodie"))
    with open(os.path.join(table, ".hoodie", "hoodie.properties"), "w") as f:
        f.write("hoodie.table.name=t1\nhoodie.table.type=COPY_ON_WRITE\n")
    # commit 1: two file groups
    _write_base_file(table, "", "fg1", "0-1-0", "001",
                     {"id": [1, 2], "v": ["a", "b"]})
    _write_base_file(table, "", "fg2", "0-1-0", "001",
                     {"id": [3], "v": ["c"]})
    _commit(table, "001")
    # commit 2: fg1 rewritten (update) — reader must take ONLY the new slice
    _write_base_file(table, "", "fg1", "0-2-0", "002",
                     {"id": [1, 2], "v": ["a2", "b2"]})
    _commit(table, "002")
    # uncommitted write: invisible
    _write_base_file(table, "", "fg3", "0-3-0", "003",
                     {"id": [9], "v": ["zz"]})
    with open(os.path.join(table, ".hoodie", "003.commit.inflight"), "w") as f:
        f.write("{}")
    return table


def test_hudi_snapshot_read(hudi_table):
    out = daft_tpu.read_hudi(hudi_table).sort("id").to_pydict()
    assert out == {"id": [1, 2, 3], "v": ["a2", "b2", "c"]}


def test_hudi_filter_pushdown(hudi_table):
    out = daft_tpu.read_hudi(hudi_table).where(col("id") >= 2).sort("id").to_pydict()
    assert out == {"id": [2, 3], "v": ["b2", "c"]}


def test_hudi_partitioned(tmp_path):
    table = str(tmp_path / "p_tbl")
    os.makedirs(os.path.join(table, ".hoodie"))
    with open(os.path.join(table, ".hoodie", "hoodie.properties"), "w") as f:
        f.write("hoodie.table.name=t2\nhoodie.table.type=COPY_ON_WRITE\n"
                "hoodie.table.partition.fields=region\n")
    _write_base_file(table, "region=eu", "fga", "0-1-0", "001",
                     {"id": [1], "region": ["eu"]})
    _write_base_file(table, "region=us", "fgb", "0-1-0", "001",
                     {"id": [2], "region": ["us"]})
    _commit(table, "001")
    out = daft_tpu.read_hudi(table).sort("id").to_pydict()
    assert out["region"] == ["eu", "us"]


def test_hudi_mor_rejected(tmp_path):
    table = str(tmp_path / "mor")
    os.makedirs(os.path.join(table, ".hoodie"))
    with open(os.path.join(table, ".hoodie", "hoodie.properties"), "w") as f:
        f.write("hoodie.table.type=MERGE_ON_READ\n")
    with pytest.raises(NotImplementedError, match="CoW"):
        daft_tpu.read_hudi(table)


def test_hudi_not_a_table(tmp_path):
    with pytest.raises(FileNotFoundError):
        daft_tpu.read_hudi(str(tmp_path / "nope"))


def test_hudi_replacecommit_excludes_replaced_groups(tmp_path):
    """Clustering/insert_overwrite: replaced file groups must vanish from
    snapshot reads (reference: pyhudi replacecommit handling)."""
    import json

    table = str(tmp_path / "rc_tbl")
    os.makedirs(os.path.join(table, ".hoodie"))
    with open(os.path.join(table, ".hoodie", "hoodie.properties"), "w") as f:
        f.write("hoodie.table.name=t3\nhoodie.table.type=COPY_ON_WRITE\n")
    _write_base_file(table, "", "old1", "0-1-0", "001", {"id": [1], "v": ["a"]})
    _write_base_file(table, "", "old2", "0-1-0", "001", {"id": [2], "v": ["b"]})
    _commit(table, "001")
    # clustering rewrites both groups into one new file group
    _write_base_file(table, "", "newg", "0-2-0", "002",
                     {"id": [1, 2], "v": ["a", "b"]})
    with open(os.path.join(table, ".hoodie", "002.replacecommit"), "w") as f:
        json.dump({"partitionToReplaceFileIds": {"": ["old1", "old2"]}}, f)
    out = daft_tpu.read_hudi(table).sort("id").to_pydict()
    assert out == {"id": [1, 2], "v": ["a", "b"]}  # no duplicates
