"""Driver-side fault-injection harness (the test half; the worker-side
tripwires live in daft_tpu/distributed/faults.py).

Faults are armed entirely through the environment: WorkerProcess children
inherit ``os.environ`` at spawn, so a test sets the ``DAFT_TPU_FAULT_*``
variables (monkeypatch) BEFORE constructing the pool/runner and the chosen
worker trips at the named point — no production code path changes per test.

Helpers here cover the second half of the harness: acting on a LIVE worker
process from the driver (kill -9 mid-query, SIGSTOP to simulate a hung host)
and the polling/skip plumbing the recovery tests share.
"""

from __future__ import annotations

import os
import signal
import time
import uuid

import pytest

# SIGKILL/SIGSTOP semantics (and the multiprocessing fork/AF_UNIX worker
# transport these tests drive) are POSIX-only; skip cleanly elsewhere.
HAVE_POSIX_SIGNALS = (os.name == "posix" and hasattr(signal, "SIGKILL")
                      and hasattr(signal, "SIGSTOP"))

requires_fault_injection = pytest.mark.skipif(
    not HAVE_POSIX_SIGNALS,
    reason="fault injection needs POSIX kill/SIGSTOP semantics")


def fault_env(point: str, mode: str = "kill", worker: str = "",
              stage: str = "", once_dir: str = "") -> dict:
    """The env-var set that arms one tripwire (see faults.py for the point
    and mode vocabulary). ``once_dir`` non-empty adds a fresh once-file so
    the fault fires at most ONCE across every worker process sharing it —
    without it a regenerated map task re-trips forever."""
    env = {"DAFT_TPU_FAULT_POINT": point, "DAFT_TPU_FAULT_MODE": mode}
    if worker:
        env["DAFT_TPU_FAULT_WORKER"] = worker
    if stage:
        env["DAFT_TPU_FAULT_STAGE"] = stage
    if once_dir:
        env["DAFT_TPU_FAULT_ONCE_FILE"] = os.path.join(
            once_dir, f"fault-once-{uuid.uuid4().hex[:8]}")
    return env


def arm_fault(monkeypatch, point: str, mode: str = "kill", worker: str = "",
              stage: str = "", once_dir: str = "") -> None:
    """Arm a tripwire for every worker spawned AFTER this call (children
    inherit os.environ). The driver process itself is immune: faults.py reads
    DAFT_TPU_FAULT_POINT once at import, which for the driver happened before
    the test set it."""
    for k, v in fault_env(point, mode, worker=worker, stage=stage,
                          once_dir=once_dir).items():
        monkeypatch.setenv(k, v)


def kill9(pool, worker_id: str) -> int:
    """SIGKILL one live pool worker (the hard mid-query crash). Returns the
    killed pid."""
    pid = pool.workers[worker_id]._proc.pid
    os.kill(pid, signal.SIGKILL)
    return pid


def sigstop(pool, worker_id: str) -> int:
    """SIGSTOP one live pool worker: the process neither exits nor EOFs its
    connection — only the heartbeat-timeout detector can catch it. Returns
    the stopped pid (SIGCONT or pool shutdown cleans it up)."""
    pid = pool.workers[worker_id]._proc.pid
    os.kill(pid, signal.SIGSTOP)
    return pid


def sigcont(pid: int) -> None:
    try:
        os.kill(pid, signal.SIGCONT)
    except (OSError, ProcessLookupError):
        pass


def wait_until(predicate, timeout_s: float = 15.0, interval_s: float = 0.05,
               what: str = "condition") -> None:
    """Poll until predicate() is truthy; pytest.fail on timeout (recovery is
    asynchronous — detection, requeue, and respawn all happen on the pool's
    dispatcher thread)."""
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if predicate():
            return
        time.sleep(interval_s)
    pytest.fail(f"timed out after {timeout_s}s waiting for {what}")
