"""DataFrame API breadth: agg variants, multiset set-ops, by-name unions,
shuffle, map_groups, delta/SQL writers, skip_existing.

Reference parity: daft/dataframe/dataframe.py (agg_set, string_agg,
union_by_name, except_all/intersect_all, shuffle, map_groups,
write_deltalake, write_sql, skip_existing).
"""

import os
import sqlite3

import pytest

import daft_tpu
from daft_tpu import col


@pytest.fixture
def df():
    return daft_tpu.from_pydict({
        "g": ["a", "a", "b", "b", "b"],
        "v": [1, 1, 3, 4, 4],
        "s": ["p", "q", "r", "s", "t"],
    })


def test_agg_set_grouped(df):
    out = df.groupby("g").agg_set("v").sort("g").to_pydict()
    assert out == {"g": ["a", "b"], "v": [[1], [3, 4]]}


def test_agg_set_global(df):
    assert df.agg_set("v").to_pydict() == {"v": [[1, 3, 4]]}


def test_list_agg_distinct_alias(df):
    assert df.list_agg_distinct("v").to_pydict() == {"v": [[1, 3, 4]]}


def test_string_agg(df):
    assert df.string_agg("s", delimiter=",").to_pydict() == {"s": ["p,q,r,s,t"]}
    out = df.groupby("g").string_agg("s", delimiter="|").sort("g").to_pydict()
    assert out == {"g": ["a", "b"], "s": ["p|q", "r|s|t"]}


def test_global_stat_shortcuts(df):
    assert df.var("v").to_pydict()["v"][0] == pytest.approx(1.84)
    assert df.stddev("v").to_pydict()["v"][0] == pytest.approx(1.84 ** 0.5)
    assert df.any_value("g").to_pydict()["g"][0] in ("a", "b")


def test_columns_property(df):
    assert [e.name() for e in df.columns] == ["g", "v", "s"]


def test_union_all_by_name():
    d1 = daft_tpu.from_pydict({"x": [1], "y": [4]})
    d2 = daft_tpu.from_pydict({"y": [6], "z": ["a"]})
    out = d1.union_all_by_name(d2).sort("y").to_pydict()
    assert out == {"x": [1, None], "y": [4, 6], "z": [None, "a"]}


def test_union_by_name_dedupes():
    d1 = daft_tpu.from_pydict({"x": [1, 1]})
    d2 = daft_tpu.from_pydict({"x": [1, 2]})
    assert sorted(d1.union_by_name(d2).to_pydict()["x"]) == [1, 2]


def test_except_all_multiset():
    l = daft_tpu.from_pydict({"x": [1, 1, 1, 2]})
    r = daft_tpu.from_pydict({"x": [1, 2, 3]})
    assert sorted(l.except_all(r).to_pydict()["x"]) == [1, 1]


def test_intersect_all_multiset():
    l = daft_tpu.from_pydict({"x": [1, 1, 1, 2]})
    r = daft_tpu.from_pydict({"x": [1, 1, 3]})
    assert sorted(l.intersect_all(r).to_pydict()["x"]) == [1, 1]


def test_shuffle_preserves_rows(df):
    out = df.shuffle(seed=7).to_pydict()
    assert sorted(out["v"]) == [1, 1, 3, 4, 4]


def test_map_groups(df):
    from daft_tpu.udf import udf

    @udf(return_dtype=daft_tpu.DataType.int64())
    def group_sum(v):
        return [sum(v.to_pylist())]

    out = df.groupby("g").map_groups(group_sum(col("v"))).sort("g").to_pydict()
    assert out == {"g": ["a", "b"], "v": [2, 11]}


def test_map_groups_multi_row(df):
    from daft_tpu.udf import udf

    @udf(return_dtype=daft_tpu.DataType.int64())
    def twice_sorted(v):
        vals = sorted(v.to_pylist())
        return vals[:2]

    out = df.groupby("g").map_groups(twice_sorted(col("v"))).sort(["g", "v"]).to_pydict()
    assert out == {"g": ["a", "a", "b", "b"], "v": [1, 1, 3, 4]}


def test_metrics(df):
    m = df.where(col("v") > 1).metrics().to_pydict()
    assert "operator" in m and len(m["operator"]) >= 1


def test_write_sql_roundtrip(df):
    conn = sqlite3.connect(":memory:")
    res = df.write_sql("t1", conn).to_pydict()
    assert res["rows"] == [5]
    assert len(conn.execute("SELECT * FROM t1").fetchall()) == 5
    df.write_sql("t1", conn, mode="overwrite")
    assert len(conn.execute("SELECT * FROM t1").fetchall()) == 5


def test_write_deltalake_roundtrip(tmp_path, df):
    tp = str(tmp_path / "tbl")
    df.write_deltalake(tp)
    assert daft_tpu.read_deltalake(tp).count_rows() == 5
    df.write_deltalake(tp, mode="append")
    assert daft_tpu.read_deltalake(tp).count_rows() == 10
    df.write_deltalake(tp, mode="overwrite")
    assert daft_tpu.read_deltalake(tp).count_rows() == 5
    with pytest.raises(FileExistsError):
        df.write_deltalake(tp, mode="error")


def test_write_deltalake_partitioned(tmp_path, df):
    tp = str(tmp_path / "ptbl")
    df.write_deltalake(tp, partition_cols=["g"])
    back = daft_tpu.read_deltalake(tp).sort("v").to_pydict()
    assert back["g"] == ["a", "a", "b", "b", "b"]
    # partition pruning path still yields correct subsets
    sub = daft_tpu.read_deltalake(tp).where(col("g") == "b").to_pydict()
    assert sorted(sub["v"]) == [3, 4, 4]


def test_skip_existing(tmp_path, df):
    pdir = str(tmp_path / "prev")
    os.makedirs(pdir)
    daft_tpu.from_pydict({"v": [1, 3], "g": ["a", "b"], "s": ["p", "r"]}) \
        .write_parquet(pdir)
    rem = df.skip_existing(pdir, "v")
    assert sorted(rem.to_pydict()["v"]) == [4, 4]


def test_write_iceberg_roundtrip(tmp_path, df):
    tp = str(tmp_path / "ice")
    res = df.write_iceberg(tp).to_pydict()
    assert sum(res["rows"]) == 5
    assert daft_tpu.read_iceberg(tp).count_rows() == 5
    df.write_iceberg(tp, mode="append")
    assert daft_tpu.read_iceberg(tp).count_rows() == 10
    df.write_iceberg(tp, mode="overwrite")
    assert daft_tpu.read_iceberg(tp).count_rows() == 5
    with pytest.raises(FileExistsError):
        df.write_iceberg(tp, mode="error")


def test_write_iceberg_partitioned(tmp_path, df):
    tp = str(tmp_path / "icep")
    df.write_iceberg(tp, partition_cols=["g"])
    back = daft_tpu.read_iceberg(tp)
    assert back.count_rows() == 5
    sub = back.where(col("g") == "b").to_pydict()
    assert sorted(sub["v"]) == [3, 4, 4]


def test_read_sql_roundtrip(df):
    conn = sqlite3.connect(":memory:")
    df.write_sql("src", conn)
    back = daft_tpu.read_sql("SELECT g, v FROM src", conn).sort(["g", "v"]).to_pydict()
    assert back["v"] == [1, 1, 3, 4, 4]
    # partitioned range read
    back2 = daft_tpu.read_sql("SELECT g, v FROM src", conn,
                              partition_col="v", num_partitions=2)
    assert sorted(back2.to_pydict()["v"]) == [1, 1, 3, 4, 4]
