"""Window function tests (reference test model: tests/window/*)."""

import pytest

import daft_tpu as dt
from daft_tpu import Window, col
from daft_tpu.functions import cume_dist, dense_rank, ntile, percent_rank, rank, row_number


@pytest.fixture
def df():
    return dt.from_pydict({
        "k": ["a", "a", "a", "b", "b"],
        "t": [1, 2, 2, 1, 2],
        "v": [10.0, 20.0, 30.0, 5.0, 15.0],
    })


def test_row_number(df):
    w = Window().partition_by("k").order_by("t")
    out = df.select(col("k"), col("v"), row_number().over(w).alias("rn")).sort(["k", "v"]).to_pydict()
    assert out["rn"] == [1, 2, 3, 1, 2]


def test_rank_dense_rank(df):
    w = Window().partition_by("k").order_by("t")
    out = df.select(
        col("k"), col("v"),
        rank().over(w).alias("rk"),
        dense_rank().over(w).alias("dr"),
    ).sort(["k", "v"]).to_pydict()
    assert out["rk"] == [1, 2, 2, 1, 2]
    assert out["dr"] == [1, 2, 2, 1, 2]


def test_rank_with_gaps():
    d = dt.from_pydict({"g": ["x"] * 4, "s": [1, 1, 2, 3]})
    w = Window().partition_by("g").order_by("s")
    out = d.select(col("s"), rank().over(w).alias("rk"), dense_rank().over(w).alias("dr")).sort("s").to_pydict()
    assert out["rk"] == [1, 1, 3, 4]
    assert out["dr"] == [1, 1, 2, 3]


def test_percent_rank_cume_dist():
    d = dt.from_pydict({"g": ["x"] * 4, "s": [1, 2, 2, 3]})
    w = Window().partition_by("g").order_by("s")
    out = d.select(col("s"), percent_rank().over(w).alias("pr"), cume_dist().over(w).alias("cd")).sort("s").to_pydict()
    assert out["pr"] == [0.0, 1 / 3, 1 / 3, 1.0]
    assert out["cd"] == [0.25, 0.75, 0.75, 1.0]


def test_ntile():
    d = dt.from_pydict({"g": ["x"] * 5, "s": [1, 2, 3, 4, 5]})
    w = Window().partition_by("g").order_by("s")
    out = d.select(col("s"), ntile(2).over(w).alias("nt")).sort("s").to_pydict()
    assert out["nt"] == [1, 1, 1, 2, 2]


def test_running_sum_includes_peers(df):
    w = Window().partition_by("k").order_by("t")
    out = df.select(col("k"), col("v"), col("v").sum().over(w).alias("rs")).sort(["k", "v"]).to_pydict()
    assert out["rs"] == [10.0, 60.0, 60.0, 5.0, 20.0]


def test_partition_only_agg(df):
    w = Window().partition_by("k")
    out = df.select(col("k"), col("v"), col("v").mean().over(w).alias("m")).sort(["k", "v"]).to_pydict()
    assert out["m"] == [20.0, 20.0, 20.0, 10.0, 10.0]


def test_rows_between(df):
    w = Window().partition_by("k").order_by("t", desc=False).rows_between(-1, 0)
    out = df.select(col("k"), col("t"), col("v"), col("v").sum().over(w).alias("s")).sort(["k", "t", "v"]).to_pydict()
    assert out["s"] == [10.0, 30.0, 50.0, 5.0, 20.0]


def test_rows_between_unbounded():
    d = dt.from_pydict({"g": ["x"] * 3, "s": [1, 2, 3], "v": [1.0, 2.0, 3.0]})
    w = Window().partition_by("g").order_by("s").rows_between(Window.unbounded_preceding, Window.unbounded_following)
    out = d.select(col("s"), col("v").sum().over(w).alias("tot")).sort("s").to_pydict()
    assert out["tot"] == [6.0, 6.0, 6.0]


def test_lag_lead(df):
    w = Window().partition_by("k").order_by("t")
    out = df.select(
        col("k"), col("v"),
        col("v").lag(1).over(w).alias("prev"),
        col("v").lead(1).over(w).alias("next"),
        col("v").lag(1, default=-1.0).over(w).alias("prev_d"),
    ).sort(["k", "v"]).to_pydict()
    assert out["prev"] == [None, 10.0, 20.0, None, 5.0]
    assert out["next"] == [20.0, 30.0, None, 15.0, None]
    assert out["prev_d"] == [-1.0, 10.0, 20.0, -1.0, 5.0]


def test_first_last_value(df):
    w = Window().partition_by("k").order_by("t")
    out = df.select(
        col("k"), col("v"),
        col("v").first_value().over(w).alias("f"),
        col("v").last_value().over(w).alias("l"),
    ).sort(["k", "v"]).to_pydict()
    assert out["f"] == [10.0, 10.0, 10.0, 5.0, 5.0]
    # last_value default frame ends at current peer group
    assert out["l"] == [10.0, 30.0, 30.0, 5.0, 15.0]


def test_window_min_max():
    d = dt.from_pydict({"g": ["x"] * 4, "s": [1, 2, 3, 4], "v": [3.0, 1.0, 4.0, 2.0]})
    w = Window().partition_by("g").order_by("s").rows_between(-1, 1)
    out = d.select(
        col("s"),
        col("v").min().over(w).alias("mn"),
        col("v").max().over(w).alias("mx"),
    ).sort("s").to_pydict()
    assert out["mn"] == [1.0, 1.0, 1.0, 2.0]
    assert out["mx"] == [3.0, 4.0, 4.0, 4.0]


def test_window_count_with_nulls():
    d = dt.from_pydict({"g": ["x", "x", "y"], "v": [1.0, None, 2.0]})
    w = Window().partition_by("g")
    out = d.select(col("g"), col("v").count().over(w).alias("c")).sort(["g"]).to_pydict()
    assert out["c"] == [1, 1, 1]


def test_window_no_partition():
    d = dt.from_pydict({"s": [3, 1, 2]})
    w = Window().order_by("s")
    out = d.select(col("s"), row_number().over(w).alias("rn")).sort("s").to_pydict()
    assert out["rn"] == [1, 2, 3]


def test_window_stddev():
    d = dt.from_pydict({"g": ["x", "x", "x"], "v": [1.0, 2.0, 3.0]})
    w = Window().partition_by("g")
    out = d.select(col("v").stddev().over(w).alias("sd")).to_pydict()
    assert all(abs(x - 0.816496580927726) < 1e-12 for x in out["sd"])


def test_empty_frames_are_null():
    d = dt.from_pydict({"g": ["x"] * 3, "s": [1, 2, 3], "v": [1.0, 2.0, 3.0]})
    w = Window().partition_by("g").order_by("s").rows_between(-3, -2)
    out = d.select(col("s"), col("v").sum().over(w).alias("sm")).sort("s").to_pydict()
    assert out["sm"] == [None, None, 1.0]
    w2 = Window().partition_by("g").order_by("s").rows_between(2, 4)
    out2 = d.select(col("s"), col("v").sum().over(w2).alias("sm")).sort("s").to_pydict()
    assert out2["sm"] == [3.0, None, None]


def test_int64_precision_preserved():
    big = 2**60
    d = dt.from_pydict({"g": ["x", "x"], "v": [big, big + 1]})
    w = Window().partition_by("g")
    out = d.select(col("v").max().over(w).alias("m"), col("v").sum().over(w).alias("s")).to_pydict()
    assert out["m"] == [big + 1] * 2
    assert out["s"] == [2 * big + 1] * 2


def test_first_value_respects_frame():
    d = dt.from_pydict({"g": ["x"] * 3, "v": [1.0, 2.0, 3.0]})
    w = Window().partition_by("g").order_by("v").rows_between(-1, 0)
    out = d.select(col("v"), col("v").first_value().over(w).alias("f")).sort("v").to_pydict()
    assert out["f"] == [1.0, 1.0, 2.0]


def test_min_periods():
    d = dt.from_pydict({"g": ["x"] * 3, "v": [1.0, 2.0, 3.0]})
    w = Window().partition_by("g").order_by("v").rows_between(-2, 0, min_periods=3)
    out = d.select(col("v"), col("v").sum().over(w).alias("s")).sort("v").to_pydict()
    assert out["s"] == [None, None, 6.0]


def test_null_dtype_window_agg():
    d = dt.from_pydict({"k": ["a", "a"], "v": [None, None]})
    out = d.select(col("v").mean().over(Window().partition_by("k")).alias("m")).to_pydict()
    assert out["m"] == [None, None]
