"""Remote IO: S3-compatible object store against an in-process mock server.

Mirrors the reference's test strategy (MinIO/moto integration + MockSource
failure injection, daft-io mock.rs / tests/integration/io): a threaded HTTP
server emulates the S3 REST surface (ranged GET, PUT, DELETE, ListObjectsV2)
with on-demand failure injection, and the engine's read_parquet/csv/json +
write_parquet run against s3:// URLs end-to-end.
"""

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, unquote, urlparse

import numpy as np
import pytest

import daft_tpu
from daft_tpu import col
from daft_tpu.io.io_config import IOConfig, S3Config, set_io_config
from daft_tpu.io.object_store import (
    MockSource,
    NotFoundError,
    ObjectSourceError,
    S3Source,
    TransientError,
    resolve_source,
)


class _S3Handler(BaseHTTPRequestHandler):
    server_version = "MockS3/0.1"

    def log_message(self, *a):  # quiet
        pass

    def _store(self):
        return self.server.store

    def _fail_maybe(self) -> bool:
        if self.server.fail_next > 0:
            self.server.fail_next -= 1
            self.send_response(503)
            self.end_headers()
            self.wfile.write(b"injected failure")
            return True
        return False

    def _parse(self):
        u = urlparse(self.path)
        parts = u.path.lstrip("/").split("/", 1)
        bucket = parts[0]
        key = unquote(parts[1]) if len(parts) > 1 else ""
        return bucket, key, parse_qs(u.query)

    def do_GET(self):
        if self._fail_maybe():
            return
        bucket, key, q = self._parse()
        self.server.requests.append(("GET", bucket, key))
        if "list-type" in q:
            prefix = q.get("prefix", [""])[0]
            keys = sorted(k for (b, k) in self._store() if b == bucket
                          and k.startswith(prefix))
            body = "<ListBucketResult>"
            for k in keys:
                body += f"<Contents><Key>{k}</Key></Contents>"
            body += "<IsTruncated>false</IsTruncated></ListBucketResult>"
            data = body.encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
            return
        obj = self._store().get((bucket, key))
        if obj is None:
            self.send_response(404)
            self.end_headers()
            return
        rng = self.headers.get("Range")
        if rng:
            spec = rng.split("=")[1]
            start_s, end_s = spec.split("-")
            start = int(start_s)
            end = int(end_s) if end_s else len(obj) - 1
            piece = obj[start:end + 1]
            self.server.bytes_served += len(piece)
            self.send_response(206)
            self.send_header("Content-Range", f"bytes {start}-{end}/{len(obj)}")
            self.send_header("Content-Length", str(len(piece)))
            self.end_headers()
            self.wfile.write(piece)
            return
        self.server.bytes_served += len(obj)
        self.send_response(200)
        self.send_header("Content-Length", str(len(obj)))
        self.end_headers()
        self.wfile.write(obj)

    def do_HEAD(self):
        if self._fail_maybe():
            return
        bucket, key, _ = self._parse()
        obj = self._store().get((bucket, key))
        if obj is None:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(obj)))
        self.end_headers()

    def do_PUT(self):
        if self._fail_maybe():
            return
        bucket, key, _ = self._parse()
        n = int(self.headers.get("Content-Length", 0))
        self._store()[(bucket, key)] = self.rfile.read(n)
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_DELETE(self):
        if self._fail_maybe():
            return
        bucket, key, _ = self._parse()
        self._store().pop((bucket, key), None)
        self.send_response(204)
        self.end_headers()


@pytest.fixture(scope="module")
def s3_server():
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _S3Handler)
    srv.store = {}
    srv.fail_next = 0
    srv.bytes_served = 0
    srv.requests = []
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    endpoint = f"http://127.0.0.1:{srv.server_port}"
    prev = set_io_config(IOConfig(s3=S3Config(
        endpoint_url=endpoint, access_key_id="test", secret_access_key="secret",
        region="us-east-1", retry_initial_backoff_ms=1)))
    yield srv
    srv.shutdown()


@pytest.fixture
def df():
    rng = np.random.default_rng(0)
    n = 2000
    return daft_tpu.from_pydict({
        "id": list(range(n)),
        "v": rng.uniform(0, 100, n).tolist(),
        "s": rng.choice(["x", "y", "z"], n).tolist(),
    })


def test_s3_put_get_roundtrip(s3_server):
    src = S3Source()
    src.put("bkt/a/b.txt", b"hello world")
    assert src.get("bkt/a/b.txt") == b"hello world"
    assert src.get("bkt/a/b.txt", range=(6, 11)) == b"world"
    assert src.get_size("bkt/a/b.txt") == 11
    src.delete("bkt/a/b.txt")
    with pytest.raises(NotFoundError):
        src.get("bkt/a/b.txt")


def test_s3_glob(s3_server):
    src = S3Source()
    for i in range(3):
        src.put(f"bkt/data/part-{i}.parquet", b"x")
    src.put("bkt/data/other.txt", b"y")
    got = src.glob("bkt/data/part-*.parquet")
    assert got == [f"bkt/data/part-{i}.parquet" for i in range(3)]


def test_write_then_read_parquet_s3(s3_server, df):
    df.write_parquet("s3://bkt/tbl").to_pydict()
    back = daft_tpu.read_parquet("s3://bkt/tbl/*.parquet").sort("id").to_pydict()
    assert back == df.sort("id").to_pydict()


def test_s3_parquet_with_pushdowns(s3_server, df):
    df.write_parquet("s3://bkt/tbl2").to_pydict()
    out = (daft_tpu.read_parquet("s3://bkt/tbl2/*.parquet")
           .where(col("v") > 50.0)
           .select("id", "v")
           .sort("id")
           .to_pydict())
    expect = df.where(col("v") > 50.0).select("id", "v").sort("id").to_pydict()
    assert out == expect


def test_s3_column_pruning_reads_fewer_bytes(s3_server):
    """Ranged reads + column pruning must download materially fewer bytes than
    a full-file read (the file is much larger than the readahead window)."""
    rng = np.random.default_rng(1)
    n = 200_000
    wide = daft_tpu.from_pydict({
        "id": list(range(n)),
        "payload": ["".join(rng.choice(list("abcdefgh"), 64)) for _ in range(n)],
    })
    wide.write_parquet("s3://bkt/tbl3").to_pydict()
    s3_server.bytes_served = 0
    daft_tpu.read_parquet("s3://bkt/tbl3/*.parquet").select("id").to_pydict()
    pruned = s3_server.bytes_served
    s3_server.bytes_served = 0
    daft_tpu.read_parquet("s3://bkt/tbl3/*.parquet").to_pydict()
    full = s3_server.bytes_served
    assert pruned < full / 2, (pruned, full)


def test_transient_failures_are_retried(s3_server):
    src = S3Source()
    src.put("bkt/r.txt", b"retry me")
    s3_server.fail_next = 2
    assert src.get("bkt/r.txt") == b"retry me"  # retries absorb 2x 503


def test_too_many_failures_raise(s3_server):
    src = S3Source()
    src.put("bkt/r2.txt", b"data")
    s3_server.fail_next = 50
    with pytest.raises(TransientError):
        src.get("bkt/r2.txt")
    s3_server.fail_next = 0


def test_csv_roundtrip_s3(s3_server, df):
    df.write_csv("s3://bkt/csvs").to_pydict()
    back = daft_tpu.read_csv("s3://bkt/csvs/*.csv").sort("id").to_pydict()
    expect = df.sort("id").to_pydict()
    assert back["id"] == expect["id"]
    np.testing.assert_allclose(back["v"], expect["v"], rtol=1e-12)


def test_mock_source_failure_injection():
    from daft_tpu.io.object_store import LocalSource, with_retries

    inner = LocalSource()
    mock = MockSource(inner, fail_first=2)
    import tempfile, os
    with tempfile.TemporaryDirectory() as td:
        p = os.path.join(td, "f.txt")
        inner.put(p, b"abc")
        # two injected transient failures, then success via retry wrapper
        out = with_retries(lambda: mock.get(p), max_retries=3, initial_backoff_ms=1)
        assert out == b"abc"
        # fatal errors are not retried
        fatal = MockSource(inner, fail_first=1, error=ObjectSourceError("fatal"))
        with pytest.raises(ObjectSourceError):
            with_retries(lambda: fatal.get(p), max_retries=3, initial_backoff_ms=1)


def test_resolve_source_schemes():
    from daft_tpu.io.object_store import HTTPSource, LocalSource

    s, rel = resolve_source("s3://b/k.parquet")
    assert isinstance(s, S3Source) and rel == "b/k.parquet"
    s, rel = resolve_source("https://host/x.csv")
    assert isinstance(s, HTTPSource) and rel == "https://host/x.csv"
    s, rel = resolve_source("/tmp/x.csv")
    assert isinstance(s, LocalSource)


def test_s3_directory_read_without_glob(s3_server, df):
    """write -> read of a bare s3 'directory' prefix round-trips (prefix list)."""
    df.write_parquet("s3://bkt/dirtbl").to_pydict()
    back = daft_tpu.read_parquet("s3://bkt/dirtbl").sort("id").to_pydict()
    assert back == df.sort("id").to_pydict()


def test_s3_overwrite_replaces_objects(s3_server, df):
    df.write_parquet("s3://bkt/ow").to_pydict()
    half = df.where(col("id") < 1000)
    half.write_parquet("s3://bkt/ow", write_mode="overwrite").to_pydict()
    back = daft_tpu.read_parquet("s3://bkt/ow").to_pydict()
    assert len(back["id"]) == 1000


def test_s3_glob_does_not_cross_directories(s3_server):
    src = S3Source()
    src.put("bkt/g/a.parquet", b"1")
    src.put("bkt/g/sub/b.parquet", b"2")
    assert src.glob("bkt/g/*.parquet") == ["bkt/g/a.parquet"]
    assert src.glob("bkt/g/**.parquet") == ["bkt/g/a.parquet", "bkt/g/sub/b.parquet"]


class _MockCloud:
    """One mock server speaking enough GCS JSON API + Azure Blob REST +
    HuggingFace resolve-path to test the readers end-to-end."""

    def __init__(self, objects):
        import json as _json
        import threading
        import urllib.parse as up
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        mock = self
        self.objects = objects  # {"bucket/key": bytes}

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code, body=b"", ctype="application/octet-stream"):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if self.command != "HEAD":
                    self.wfile.write(body)

            def do_HEAD(self):
                self.do_GET()

            def do_GET(self):
                parsed = up.urlparse(self.path)
                q = dict(up.parse_qsl(parsed.query))
                parts = parsed.path.lstrip("/").split("/")
                # ---- GCS JSON API
                if parts[0] == "storage":
                    bucket = parts[3]
                    if len(parts) >= 6 and parts[4] == "o" and parts[5]:
                        key = up.unquote(parts[5])
                        data = mock.objects.get(f"{bucket}/{key}")
                        if data is None:
                            return self._send(404)
                        if q.get("alt") == "media":
                            rng = self.headers.get("Range")
                            if rng:
                                lo, hi = rng.split("=")[1].split("-")
                                data = data[int(lo):int(hi) + 1]
                            return self._send(200, data)
                        return self._send(200, _json.dumps(
                            {"size": str(len(data))}).encode(), "application/json")
                    # list
                    prefix = q.get("prefix", "")
                    items = [{"name": k.split("/", 1)[1]}
                             for k in sorted(mock.objects)
                             if k.startswith(f"{bucket}/") and
                             k.split("/", 1)[1].startswith(prefix)]
                    return self._send(200, _json.dumps({"items": items}).encode(),
                                      "application/json")
                # ---- HuggingFace resolve path
                if "resolve" in parts:
                    key = "hf/" + parts[-1]
                    data = mock.objects.get(key)
                    return self._send(200 if data else 404, data or b"")
                # ---- Azure Blob REST
                container = parts[0]
                if q.get("comp") == "list":
                    prefix = q.get("prefix", "")
                    names = [k.split("/", 1)[1] for k in sorted(mock.objects)
                             if k.startswith(f"{container}/")
                             and k.split("/", 1)[1].startswith(prefix)]
                    xml = ("<EnumerationResults><Blobs>"
                           + "".join(f"<Blob><Name>{n}</Name></Blob>" for n in names)
                           + "</Blobs></EnumerationResults>").encode()
                    return self._send(200, xml, "application/xml")
                key = up.unquote("/".join(parts[1:]))
                data = mock.objects.get(f"{container}/{key}")
                if data is None:
                    return self._send(404)
                rng = self.headers.get("Range")
                if rng:
                    lo, hi = rng.split("=")[1].split("-")
                    data = data[int(lo):int(hi) + 1]
                self._send(200, data)

        class Server(ThreadingHTTPServer):
            daemon_threads = True

        self.server = Server(("127.0.0.1", 0), Handler)
        self.port = self.server.server_address[1]
        threading.Thread(target=self.server.serve_forever, daemon=True).start()

    def close(self):
        self.server.shutdown()


def test_gcs_source_get_size_ls_glob_and_read_csv():
    import daft_tpu
    from daft_tpu.io.io_config import GCSConfig, IOConfig
    from daft_tpu.io.object_store import GCSSource

    csv = b"a,b\n1,x\n2,y\n"
    mock = _MockCloud({"bkt/data/t1.csv": csv, "bkt/data/t2.csv": csv,
                       "bkt/other/t3.csv": csv})
    try:
        cfg = IOConfig(gcs=GCSConfig(endpoint_url=f"http://127.0.0.1:{mock.port}",
                                     token="tok"))
        src = GCSSource(cfg)
        assert src.get("bkt/data/t1.csv") == csv
        assert src.get("bkt/data/t1.csv", range=(0, 3)) == csv[:3]
        assert src.get_size("bkt/data/t1.csv") == len(csv)
        assert src.ls("bkt/data/") == ["bkt/data/t1.csv", "bkt/data/t2.csv"]
        assert src.glob("bkt/data/*.csv") == ["bkt/data/t1.csv", "bkt/data/t2.csv"]
    finally:
        mock.close()


def test_azure_source_get_ls_glob():
    from daft_tpu.io.io_config import AzureConfig, IOConfig
    from daft_tpu.io.object_store import AzureBlobSource

    data = b"hello azure"
    mock = _MockCloud({"cont/x/a.bin": data, "cont/x/b.bin": data, "cont/y/c.bin": data})
    try:
        cfg = IOConfig(azure=AzureConfig(endpoint_url=f"http://127.0.0.1:{mock.port}",
                                         sas_token="sig=abc"))
        src = AzureBlobSource(cfg)
        assert src.get("cont/x/a.bin") == data
        assert src.get("cont/x/a.bin", range=(6, 11)) == b"azure"
        assert src.get_size("cont/x/a.bin") == len(data)
        assert src.ls("cont/x/") == ["cont/x/a.bin", "cont/x/b.bin"]
        assert src.glob("cont/*/\x61.bin") == ["cont/x/a.bin"]
    finally:
        mock.close()


def test_hf_path_resolution(monkeypatch):
    from daft_tpu.io.object_store import HTTPSource, resolve_source

    mock = _MockCloud({"hf/train.csv": b"a\n1\n"})
    try:
        monkeypatch.setenv("DAFT_TPU_HF_ENDPOINT", f"http://127.0.0.1:{mock.port}")
        src, rel = resolve_source("hf://datasets/org/repo/train.csv")
        assert isinstance(src, HTTPSource)
        assert rel.endswith("/datasets/org/repo/resolve/main/train.csv")
        assert src.get(rel) == b"a\n1\n"
    finally:
        mock.close()


def test_abfs_authority_parsing_and_hf_glob_rejection():
    from daft_tpu.io.object_store import (AzureBlobSource, ObjectSourceError,
                                          resolve_source)

    src, rel = resolve_source("abfss://data@myacct.dfs.core.windows.net/dir/p.parquet")
    assert isinstance(src, AzureBlobSource)
    assert src.endpoint == "https://myacct.blob.core.windows.net"
    assert rel == "data/dir/p.parquet"
    with pytest.raises(ObjectSourceError, match="glob"):
        resolve_source("hf://datasets/org/repo/*.parquet")
