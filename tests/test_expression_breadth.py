"""Flat Expression API + function-registry breadth wave 2.

Reference parity: daft/expressions/expressions.py flat methods (upper/day/
list_sum/... exposed top-level), math long-tail, case conversions, parse_url,
compress/serialize families, tz ops, duration totals, iceberg partition
transforms, product/string_agg aggregations, unnest.
"""

import datetime
import math

import pytest

import daft_tpu
from daft_tpu import col, lit


@pytest.fixture
def df():
    return daft_tpu.from_pydict({
        "s": ["helloWorld", "a_b"], "n": [1.0, 2.0], "i": [5, 9],
        "l": [[1, 2], [3]], "b": [b"xy", b"z"],
    })


def test_flat_namespace_aliases(df):
    out = df.select(col("s").upper().alias("u"), col("s").left(3).alias("l3"),
                    col("n").is_nan().alias("nn")).to_pydict()
    assert out == {"u": ["HELLOWORLD", "A_B"], "l3": ["hel", "a_b"],
                   "nn": [False, False]}


def test_math_long_tail(df):
    out = df.select(col("n").cosh().alias("ch"), col("n").arcsinh().alias("ash"),
                    col("n").sec().alias("sec"),
                    col("n").arctan2(lit(1.0)).alias("at2")).to_pydict()
    assert out["ch"][0] == pytest.approx(math.cosh(1.0))
    assert out["ash"][1] == pytest.approx(math.asinh(2.0))
    assert out["sec"][0] == pytest.approx(1 / math.cos(1.0))
    assert out["at2"][1] == pytest.approx(math.atan2(2.0, 1.0))


def test_case_conversions():
    df = daft_tpu.from_pydict({"s": ["helloWorldFoo", "SOME_value", "kebab-case"]})
    assert df.select(col("s").to_snake_case()).to_pydict()["s"] == \
        ["hello_world_foo", "some_value", "kebab_case"]
    assert df.select(col("s").to_camel_case()).to_pydict()["s"] == \
        ["helloWorldFoo", "someValue", "kebabCase"]
    assert df.select(col("s").to_upper_kebab_case()).to_pydict()["s"] == \
        ["HELLO-WORLD-FOO", "SOME-VALUE", "KEBAB-CASE"]


def test_dtype_dispatch(df):
    out = df.select(col("l").length().alias("ll"), col("s").length().alias("sl"),
                    col("b").length().alias("bl"), col("l").get(0).alias("g"),
                    col("s").contains("ello").alias("sc"),
                    col("l").contains(3).alias("lc")).to_pydict()
    assert out == {"ll": [2, 1], "sl": [10, 3], "bl": [2, 1], "g": [1, 3],
                   "sc": [True, False], "lc": [False, True]}


def test_dtype_dispatch_unsupported(df):
    with pytest.raises(ValueError, match="does not support"):
        df.select(col("n").get(0)).to_pydict()


def test_parse_url():
    df = daft_tpu.from_pydict({"u": ["https://u:p@h.io:8080/a?q=1#f"]})
    v = df.select(col("u").parse_url()).to_pydict()["u"][0]
    assert v["scheme"] == "https" and v["host"] == "h.io" and v["port"] == 8080
    assert v["path"] == "/a" and v["query"] == "q=1" and v["fragment"] == "f"


def test_compress_roundtrip(df):
    for codec in ("gzip", "zlib", "bz2"):
        out = df.select(col("s").compress(codec).decompress(codec)
                        .decode("utf-8").alias("rt")).to_pydict()
        assert out["rt"] == ["helloWorld", "a_b"]


def test_try_decompress_null_on_garbage():
    df = daft_tpu.from_pydict({"b": [b"not gzip"]})
    assert df.select(col("b").try_decompress("gzip")).to_pydict()["b"] == [None]


def test_serialize_deserialize():
    df = daft_tpu.from_pydict({"j": ['{"a": 5}']})
    dt = daft_tpu.DataType.struct({"a": daft_tpu.DataType.int64()})
    assert df.select(col("j").deserialize(dtype=dt)).to_pydict()["j"] == [{"a": 5}]
    df2 = daft_tpu.from_pydict({"x": [{"a": 1}]})
    assert df2.select(col("x").serialize()).to_pydict()["x"] == ['{"a": 1}']


def test_timezone_ops():
    df = daft_tpu.from_pydict({"t": [datetime.datetime(2024, 1, 1, 12, 0)]})
    aware = df.select(col("t").replace_time_zone(tz="UTC"))
    v = aware.to_pydict()["t"][0]
    assert v.utcoffset() == datetime.timedelta(0)
    conv = aware.select(col("t").convert_time_zone("America/New_York")).to_pydict()["t"][0]
    assert conv.hour == 7  # UTC noon == 7am EST


def test_duration_totals():
    df = daft_tpu.from_pydict({"d": [datetime.timedelta(days=1, hours=2)]})
    out = df.select(col("d").total_hours().alias("h"),
                    col("d").total_seconds().alias("s")).to_pydict()
    assert out == {"h": [26], "s": [26 * 3600]}


def test_iceberg_partition_transforms():
    df = daft_tpu.from_pydict({"i": [34], "s": ["iceberg"],
                               "d": [datetime.date(2024, 3, 1)]})
    out = df.select(col("i").partition_iceberg_bucket(n=16).alias("b"),
                    col("s").partition_iceberg_truncate(w=3).alias("t"),
                    col("d").partition_months().alias("m"),
                    col("d").partition_years().alias("y")).to_pydict()
    # iceberg spec test vector: murmur3_32(int 34) = 2017239379; 2017239379 % 16 = 3
    assert out["b"] == [2017239379 % 16]
    assert out["t"] == ["ice"]
    assert out["m"] == [(2024 - 1970) * 12 + 2]
    assert out["y"] == [54]


def test_product_agg():
    df = daft_tpu.from_pydict({"g": ["a", "a", "b"], "x": [2, 3, 4],
                               "f": [0.5, 4.0, None]})
    assert df.agg(col("x").product()).to_pydict() == {"x": [24]}
    out = df.groupby("g").agg(col("x").product().alias("p"),
                              col("f").product().alias("fp")).sort("g").to_pydict()
    assert out["p"] == [6, 4]
    assert out["fp"] == [2.0, None]


def test_string_agg_expression():
    df = daft_tpu.from_pydict({"g": ["a", "a", "b"], "s": ["x", None, "z"]})
    assert df.agg(col("s").string_agg("-")).to_pydict() == {"s": ["x-z"]}
    out = df.groupby("g").agg(col("s").string_agg("|")).sort("g").to_pydict()
    assert out["s"] == ["x", "z"]


def test_unnest():
    df = daft_tpu.from_pydict({"st": [{"u": 1, "v": "m"}, {"u": 2, "v": "n"}]})
    assert df.select(col("st").unnest()).to_pydict() == {"u": [1, 2], "v": ["m", "n"]}


def test_list_extras(df):
    out = df.select(col("l").list_append(lit(9)).alias("ap")).to_pydict()
    assert out["ap"] == [[1, 2, 9], [3, 9]]
    df2 = daft_tpu.from_pydict({"bl": [[True, True], [True, False], [None, None]]})
    assert df2.select(col("bl").list_bool_and()).to_pydict()["bl"] == [True, False, None]
    assert df2.select(col("bl").list_bool_or()).to_pydict()["bl"] == [True, True, None]


def test_regexp_variants():
    df = daft_tpu.from_pydict({"s": ["aXbXc"]})
    assert df.select(col("s").regexp_split("X")).to_pydict()["s"] == [["a", "b", "c"]]
    assert df.select(col("s").regexp_replace("[abc]", "_")).to_pydict()["s"] == ["_X_X_"]
    assert df.select(col("s").regexp_count("[abc]")).to_pydict()["s"] == [3]


def test_image_accessors():
    import numpy as np

    from daft_tpu.core.kernels.image import build_image_series

    imgs = [np.zeros((4, 6, 3), np.uint8), None]
    s = build_image_series("im", imgs, ["RGB", None])
    from daft_tpu.api import _from_partitions

    schema = daft_tpu.Schema([s.field()])
    df = _from_partitions(
        [daft_tpu.MicroPartition(schema, [daft_tpu.RecordBatch(schema, [s])])],
        schema)
    out = df.select(col("im").image_attribute("height").alias("h"),
                    col("im").image_attribute("width").alias("w"),
                    col("im").image_mode().alias("m")).to_pydict()
    assert out == {"h": [4, None], "w": [6, None], "m": ["RGB", None]}
