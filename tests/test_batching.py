"""Adaptive batching + device dispatch coalescing (ISSUE 4).

Covers the acceptance criteria end to end on the CPU backend, no chip needed:

- Coalescing microbench: >= 8 morsels into one device agg stage dispatch as
  ONE coalesced super-batch (>= 2x fewer compiled dispatches than morsels
  consumed, mean bucket fill >= 0.5) with results BIT-IDENTICAL to the
  uncoalesced path, including the int64 exactness guarantees from PR 2.
- DynamicBatching converges: a synthetic operator with a throughput knee
  pulls the morsel size to within one pow2 step of the knee.
- Cost model: the measured-constant decision boundary flips with the link
  RTT, and the coalescing horizon flips a previously-rejected morsel shape
  to the device — asserted via the decision functions with pinned
  calibration constants, never wall clock.
- Zero-overhead guard: batching_mode="static" runs the host path with no
  strategy/coalescer allocation and no registry writes.
"""

import numpy as np
import pytest

import daft_tpu
from daft_tpu import col
from daft_tpu.config import ExecutionConfig, execution_config_ctx
from daft_tpu.core.recordbatch import RecordBatch
from daft_tpu.core.series import Series
from daft_tpu.datatype import DataType
from daft_tpu.execution.batching import (DynamicBatching,
                                         LatencyConstrainedBatching,
                                         StaticBatching,
                                         adaptive_morsel_stream)
from daft_tpu.ops import costmodel, counters
from daft_tpu.ops.grouped_stage import try_build_grouped_agg_stage
from daft_tpu.ops.stage import DispatchCoalescer, pad_bucket
from daft_tpu.schema import Schema


# ---------------------------------------------------------------------------
# Coalescing microbench (acceptance criterion)
# ---------------------------------------------------------------------------

def _morsel_batches(n_batches=8, rows=1024):
    """Morsels whose int64 values stress PR 2's exactness guarantees: sums
    near 2^53 via ~2^40 addends, min/max over magnitudes past 2^53 (the i64
    scatter path — f64 would round them)."""
    rng = np.random.default_rng(7)
    schema = Schema.from_pydict({"k": DataType.int64(), "v": DataType.int64(),
                                 "w": DataType.int64()})
    out = []
    for _ in range(n_batches):
        k = rng.integers(0, 8, rows)
        v = rng.integers(0, 1 << 40, rows)
        w = rng.integers(-(1 << 60), 1 << 60, rows) | 1  # odd: f64-inexact
        cols = [Series.from_numpy(k, "k", DataType.int64()),
                Series.from_numpy(v, "v", DataType.int64()),
                Series.from_numpy(w, "w", DataType.int64())]
        out.append(RecordBatch(schema, cols, rows))
    return schema, out


_AGGS = lambda: [col("v").sum().alias("s"), col("v").mean().alias("m"),  # noqa: E731
                 col("w").min().alias("lo"), col("w").max().alias("hi"),
                 col("v").count().alias("c")]


def test_coalescing_microbench_grouped_bit_identical():
    schema, batches = _morsel_batches(8, 1024)
    stage = try_build_grouped_agg_stage(schema, None, [col("k")], _AGGS())
    assert stage is not None

    counters.reset()
    run = stage.start_run()
    coal = DispatchCoalescer(run.feed_batch, target_rows=65536, latency_s=3600.0)
    for b in batches:
        coal.add(b)
    coal.close()
    keys_c, res_c = run.finalize()

    # >= 2x fewer compiled dispatches than morsels consumed
    assert counters.coalesce_morsels_in == 8
    assert counters.dispatch_coalesced * 2 <= counters.coalesce_morsels_in
    # mean bucket fill ratio >= 0.5 (8192 rows pad to exactly the 8192 bucket)
    fill = counters.bucket_fill_rows / counters.bucket_capacity_rows
    assert fill >= 0.5
    # each flush is exactly one compiled dispatch
    assert counters.device_grouped_batches == counters.dispatch_coalesced

    # uncoalesced reference: one dispatch per morsel
    run2 = stage.start_run()
    for b in batches:
        run2.feed_batch(b)
    keys_u, res_u = run2.finalize()

    assert keys_c == keys_u
    for (vc, okc), (vu, oku) in zip(res_c, res_u):
        assert np.array_equal(np.asarray(okc), np.asarray(oku))
        assert np.array_equal(np.asarray(vc), np.asarray(vu)), \
            "coalesced device results drifted from per-morsel dispatch"


def test_coalescing_end_to_end_device_agg():
    """Executor wiring: a multi-part stream into DeviceGroupedAgg coalesces
    (counters prove it) and matches the host path exactly on int64 sums."""
    rng = np.random.default_rng(3)

    def chunk():
        n = 1024
        return daft_tpu.from_pydict({
            "k": rng.integers(0, 6, n).tolist(),
            "v": rng.integers(0, 1 << 40, n).tolist(),
        })

    df = chunk()
    for _ in range(7):
        df = df.concat(chunk())

    def q(mode):
        with execution_config_ctx(device_mode=mode, batch_latency_ms=60_000.0):
            out = (df.groupby("k")
                   .agg(col("v").sum().alias("s"), col("v").count().alias("c"))
                   .sort("k").to_pydict())
        return out

    counters.reset()
    dev = q("on")
    assert counters.coalesce_morsels_in >= 8
    assert counters.dispatch_coalesced * 2 <= counters.coalesce_morsels_in
    assert counters.device_grouped_batches == counters.dispatch_coalesced
    # the fill gauge reached the registry (flows to QueryEnd.metrics/EXPLAIN)
    assert counters.snapshot().get("bucket_fill_ratio", 0) >= 0.5
    host = q("off")
    assert dev == host, "device+coalesced result differs from host"


def test_coalescer_latency_deadline_flushes_partial():
    """latency_s=0: every add is already past the deadline — morsels dispatch
    1:1 (the no-coalescing degenerate), proving the deadline path flushes
    partial super-batches instead of waiting for fill."""
    schema, batches = _morsel_batches(4, 256)
    stage = try_build_grouped_agg_stage(schema, None, [col("k")], _AGGS())
    counters.reset()
    run = stage.start_run()
    coal = DispatchCoalescer(run.feed_batch, target_rows=1 << 20, latency_s=0.0)
    for b in batches:
        coal.add(b)
    coal.close()
    run.finalize()
    assert counters.dispatch_coalesced == 4
    assert counters.coalesce_morsels_in == 4


def test_coalescer_fill_threshold_batches_pairs():
    schema, batches = _morsel_batches(8, 1024)
    fed = []
    coal = DispatchCoalescer(fed.append, target_rows=2048, latency_s=3600.0)
    for b in batches:
        coal.add(b)
    coal.close()
    assert len(fed) == 4  # pairs of 1024-row morsels
    assert all(b.num_rows == 2048 for b in fed)


def test_coalescer_single_batch_preserves_identity():
    """One pending batch flushes as the ORIGINAL object — batch-identity-keyed
    device caches (resident tables, device_join series_keyed slots) must
    survive coalescing."""
    schema, batches = _morsel_batches(1, 512)
    fed = []
    coal = DispatchCoalescer(fed.append, target_rows=1 << 20, latency_s=3600.0)
    coal.add(batches[0])
    coal.close()
    assert fed[0] is batches[0]
    coal.close()  # idempotent: nothing pending, nothing dispatched
    assert len(fed) == 1


# ---------------------------------------------------------------------------
# Batching strategies
# ---------------------------------------------------------------------------

def test_dynamic_batching_converges_to_knee():
    """Acceptance criterion: a synthetic operator whose throughput peaks at a
    knee pulls the morsel size from 16x above it to within one pow2 step,
    within a bounded number of morsels."""
    knee = 32 * 1024
    strat = DynamicBatching(initial=512 * 1024, min_rows=1024,
                            max_rows=8 * 1024 * 1024)
    counters.reset()

    def seconds(rows, size):
        # peaked throughput: fixed per-morsel overhead below the knee, cache
        # pressure above it — maximal exactly at size == knee
        rate = 2e8 / (knee / size + size / knee)
        return rows / rate

    sizes = []
    for _ in range(60):  # 3-sample aggregation => 20 climb decisions
        s = strat.current_size()
        sizes.append(s)
        strat.record(s, seconds(s, s))
    assert knee // 2 <= strat.current_size() <= knee * 2, sizes
    assert counters.morsel_resize > 0, "convergence never resized"


def test_dynamic_batching_noise_robust():
    """Contention jitter inside the deadband must not random-walk the size:
    flat true throughput with ±4% multiplicative noise (under the 5%
    deadband after 3-sample averaging) holds the ladder step."""
    strat = DynamicBatching(initial=64 * 1024, min_rows=1024,
                            max_rows=16 * 1024 * 1024)
    jitter = [1.0, 0.96, 1.04]
    i = 0
    start_sizes = set()
    for _ in range(30):
        s = strat.current_size()
        start_sizes.add(s)
        strat.record(s, s / (1e8 * jitter[i % 3]))
        i += 1
    # one probe step away from the initial size is allowed; no runaway
    assert strat.current_size() in (64 * 1024, 128 * 1024), start_sizes


def test_dynamic_batching_respects_bounds_and_deadband():
    strat = DynamicBatching(initial=4096, min_rows=4096, max_rows=8192)
    for _ in range(10):
        strat.record(strat.current_size(), 1.0)  # flat throughput
    assert 4096 <= strat.current_size() <= 8192


def test_dynamic_batching_honors_small_configured_initial():
    """A morsel_size_rows below the default floor must not be silently
    quadrupled up: the floor clamps to the configured initial."""
    strat = DynamicBatching(initial=1024)
    assert strat.current_size() == 1024


def test_latency_constrained_caps_slow_operator():
    strat = LatencyConstrainedBatching(0.01, initial=128 * 1024)
    strat.record(128 * 1024, 1.0)  # 131Ki rows/s observed -> ~1.3Ki rows/10ms
    assert strat.current_size() <= 2048
    fast = LatencyConstrainedBatching(0.01, initial=128 * 1024)
    fast.record(128 * 1024, 0.001)  # 1.3e8 rows/s: big morsels stay fine
    assert fast.current_size() >= 128 * 1024


def test_static_batching_is_fixed():
    s = StaticBatching(1000)
    s.record(10, 100.0)
    assert s.current_size() == 1000


def test_adaptive_morsel_stream_follows_strategy():
    from daft_tpu.core.micropartition import MicroPartition

    n = 100_000
    s = Series.from_numpy(np.arange(n), "a", DataType.int64())
    schema = Schema.from_pydict({"a": DataType.int64()})
    part = MicroPartition(schema, [RecordBatch(schema, [s], n)])
    strat = StaticBatching(10_000)
    out = list(adaptive_morsel_stream(iter([part]), strat))
    assert len(out) == 10
    assert sum(p.num_rows for p in out) == n


def test_adaptive_morsel_stream_resizes_mid_partition():
    """A resize recorded while a partition is being split must apply to the
    REMAINDER of that partition — a single-partition table is the common
    case, so per-partition-only consultation would make feedback a no-op."""
    from daft_tpu.core.micropartition import MicroPartition

    n = 64_000
    s = Series.from_numpy(np.arange(n), "a", DataType.int64())
    schema = Schema.from_pydict({"a": DataType.int64()})
    part = MicroPartition(schema, [RecordBatch(schema, [s], n)])

    class Shrinking:
        def __init__(self):
            self.sizes = [16_000, 16_000, 4_000]  # consulted per slice

        def current_size(self):
            return self.sizes.pop(0) if len(self.sizes) > 1 else self.sizes[0]

        def record(self, rows, seconds):
            pass

    got = [p.num_rows for p in adaptive_morsel_stream(iter([part]), Shrinking())]
    assert got[0] == 16_000 and 4_000 in got, got
    assert sum(got) == n


def test_adaptive_morsel_stream_merges_small_batches():
    """A 'grow' decision must be real even when the source emits fixed small
    batches: undersized batches group (zero-copy, multi-batch partitions)
    until they reach the current size."""
    from daft_tpu.core.micropartition import MicroPartition

    schema = Schema.from_pydict({"a": DataType.int64()})

    def part(rows):
        s = Series.from_numpy(np.arange(rows), "a", DataType.int64())
        return MicroPartition(schema, [RecordBatch(schema, [s], rows)])

    parts = [part(1024) for _ in range(8)]
    out = list(adaptive_morsel_stream(iter(parts), StaticBatching(4096)))
    assert [p.num_rows for p in out] == [4096, 4096]
    assert all(len(p.batches) == 4 for p in out)  # grouped, never concatenated
    # a trailing remainder still flushes at stream end
    out2 = list(adaptive_morsel_stream(iter([part(1024) for _ in range(5)]),
                                       StaticBatching(4096)))
    assert [p.num_rows for p in out2] == [4096, 1024]


def test_dynamic_mode_end_to_end_results_match_static():
    """Full pipeline under batching_mode=dynamic (forced pipeline so morsel
    fan-out actually runs): ordered results identical to static mode."""
    n = 50_000
    df = daft_tpu.from_pydict({"a": list(range(n)),
                               "b": [float(i % 97) for i in range(n)]})
    q = lambda d: d.where(col("a") % 3 == 0).select(  # noqa: E731
        col("a"), (col("b") * 2).alias("b2")).to_pydict()
    with execution_config_ctx(batching_mode="static"):
        want = q(df)
    with execution_config_ctx(batching_mode="dynamic", pipeline_mode="force",
                              morsel_size_rows=1024):
        got = q(df)
    assert got == want
    with execution_config_ctx(batching_mode="latency", pipeline_mode="force",
                              morsel_size_rows=1024, batch_latency_ms=5.0):
        got_lat = q(df)
    assert got_lat == want


# ---------------------------------------------------------------------------
# Cost model: decision boundary + coalescing horizon
# ---------------------------------------------------------------------------

def _cal(rtt: float) -> costmodel.Calibration:
    """Pinned calibration: measured v5e compute rates, parameterized link."""
    return costmodel.Calibration(
        rtt_s=rtt, h2d_bytes_per_s=1e9, d2h_bytes_per_s=2e6,
        mm_plane_rows_per_s=5e9, mm_cell_rate=5e10, scatter_rows_per_s=1e8,
        ext_cell_rate=5e9, host_agg_rate=1.5e8, host_factorize_rate=8e6,
        host_probe_rate=3e7)


def test_cost_decision_boundary_flips_with_measured_rtt():
    """Satellite: two calibration points straddling the device/host boundary.
    Same 200k-row filter+agg shape: a ~1ms co-located link picks the device,
    the measured ~90ms tunneled link picks the host."""
    rows = 200_000
    fast, slow = _cal(0.001), _cal(0.090)
    host_fast = costmodel.host_agg_cost(fast, rows, 1, grouped=False,
                                        has_predicate=True)
    host_slow = costmodel.host_agg_cost(slow, rows, 1, grouped=False,
                                        has_predicate=True)
    assert host_fast == host_slow  # host price doesn't depend on the link
    assert costmodel.device_ungrouped_cost(fast, rows, 0, 1) < host_fast
    assert costmodel.device_ungrouped_cost(slow, rows, 0, 1) > host_slow


def test_coalescing_horizon_flips_rejected_shape_to_device():
    """Acceptance criterion: a 4096-row morsel stream of a grouped 4-agg query
    is a cost rejection at coalesce=1 (full RTT per half-empty bucket) and an
    honest device win once the coalescer covers 16 morsels per dispatch."""
    cal = _cal(0.005)
    rows = 4096
    host = costmodel.host_agg_cost(cal, rows, 4, grouped=True,
                                   has_predicate=False)
    kw = dict(n_mm=9, n_ext=1, n_sct=0, cap=64, factorize_rows=0)
    rejected = costmodel.device_grouped_cost(cal, rows, 0, **kw)
    horizon = costmodel.expected_coalesce_factor(rows, 65536)
    assert horizon == 16.0
    flipped = costmodel.device_grouped_cost(cal, rows, 0, coalesce=horizon, **kw)
    assert rejected > host, "shape must start as a cost rejection"
    assert flipped < host, "coalescing horizon failed to flip the decision"


def test_expected_coalesce_factor_properties():
    f = costmodel.expected_coalesce_factor
    assert f(4096, 65536) == 16.0
    assert f(65536, 65536) == 1.0       # bucket-filling morsels: no optimism
    assert f(200_000, 65536) == 1.0
    assert f(1, 1 << 30) == 64.0        # capped like device_amortize_runs
    assert f(0, 65536) == 1.0
    assert f(4096, 0) == 1.0            # coalescing disabled


def test_executor_coalesce_horizon_batch_granularity():
    """The real decision path: the horizon comes from the first partition's
    BATCH granularity (what the coalescer merges) capped by the observed
    batch count — a single-batch partition gets no optimism however small,
    and a many-small-batch partition engages at DEFAULT knobs."""
    from daft_tpu.core.micropartition import MicroPartition
    from daft_tpu.execution.executor import _coalesce_horizon

    schema, batches = _morsel_batches(8, 4096)
    multi = MicroPartition(schema, batches)        # 8 x 4096-row batches
    single = MicroPartition(schema, [batches[0]])  # one batch: can't coalesce
    with execution_config_ctx(batch_fill_target=0.5,
                              morsel_size_rows=128 * 1024):
        assert _coalesce_horizon([multi]) == 8.0  # min(65536/4096, 8 batches)
        assert _coalesce_horizon([single]) == 1.0
        # a peeked second partition widens the horizon to the morsels
        # actually OBSERVED — never past them (2 seen => at most 2x)
        assert _coalesce_horizon([single, single]) == 2.0
        assert _coalesce_horizon([multi, multi]) == 16.0
    with execution_config_ctx(batch_fill_target=0.0):
        assert _coalesce_horizon([multi]) == 1.0


# ---------------------------------------------------------------------------
# Zero-overhead guard + config validation (satellites)
# ---------------------------------------------------------------------------

def test_static_mode_zero_overhead_guard(monkeypatch):
    """Tier-1 guard: with batching_mode=static the host path must not
    allocate a strategy or coalescer, and must not touch the metrics
    registry — byte-identical behavior to the pre-batching engine."""
    from daft_tpu.execution import batching
    from daft_tpu.observability.metrics import registry
    from daft_tpu.ops import stage as stage_mod

    def _forbidden(*a, **k):
        raise AssertionError("batching machinery touched on the static host path")

    monkeypatch.setattr(batching.StaticBatching, "__init__", _forbidden)
    monkeypatch.setattr(batching.DynamicBatching, "__init__", _forbidden)
    monkeypatch.setattr(batching.LatencyConstrainedBatching, "__init__", _forbidden)
    monkeypatch.setattr(stage_mod.DispatchCoalescer, "__init__", _forbidden)

    before = registry().snapshot()
    df = daft_tpu.from_pydict({"a": list(range(2000)), "b": ["x", "y"] * 1000})
    with execution_config_ctx(batching_mode="static", device_mode="off"):
        out = (df.where(col("a") >= 1000)
               .groupby("b").agg(col("a").sum().alias("s")).to_pydict())
    assert len(out["b"]) == 2
    assert registry().diff(before) == {}, "registry touched on the static path"


def test_agg_morsel_rows_unified_with_config():
    """Satellite: the partial-agg splitter's morsel size follows the config
    (was a hardcoded 256Ki drifting from the 128Ki default)."""
    from daft_tpu.execution.executor import _agg_morsel_rows

    assert _agg_morsel_rows() == ExecutionConfig().morsel_size_rows
    with execution_config_ctx(morsel_size_rows=4096):
        assert _agg_morsel_rows() == 4096


def test_batching_config_validation():
    with pytest.raises(ValueError, match="batching_mode"):
        ExecutionConfig(batching_mode="bogus")
    with pytest.raises(ValueError, match="batch_fill_target"):
        ExecutionConfig(batch_fill_target=1.5)
    with pytest.raises(ValueError, match="batch_fill_target"):
        ExecutionConfig(batch_fill_target=-0.1)
    with pytest.raises(ValueError, match="batch_latency_ms"):
        ExecutionConfig(batch_latency_ms=0.0)
    # 0 fill target is legal: it disables coalescing
    assert ExecutionConfig(batch_fill_target=0.0).batch_fill_target == 0.0
