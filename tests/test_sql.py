"""SQL frontend tests (reference test model: tests/sql/*)."""

import pytest

import daft_tpu as dt


@pytest.fixture
def df():
    return dt.from_pydict({
        "k": ["x", "y", "x", "y", "x"],
        "a": [1, 2, 3, 4, 5],
        "b": [10.0, 20.0, 30.0, 40.0, 50.0],
    })


@pytest.fixture
def d2():
    return dt.from_pydict({"k": ["x", "z"], "v": [100, 200]})


def test_select_project_filter(df):
    out = dt.sql("SELECT a, b * 2 AS b2 FROM df WHERE a > 2", df=df).to_pydict()
    assert out == {"a": [3, 4, 5], "b2": [60.0, 80.0, 100.0]}


def test_group_by(df):
    out = dt.sql("SELECT k, SUM(b) AS s, COUNT(*) AS n FROM df GROUP BY k ORDER BY k", df=df).to_pydict()
    assert out == {"k": ["x", "y"], "s": [90.0, 60.0], "n": [3, 2]}


def test_group_by_position_having(df):
    out = dt.sql("SELECT k, SUM(b) AS s FROM df GROUP BY 1 HAVING SUM(b) > 70", df=df).to_pydict()
    assert out == {"k": ["x"], "s": [90.0]}


def test_join(df, d2):
    out = dt.sql("SELECT df.k, a, v FROM df JOIN d2 ON df.k = d2.k ORDER BY a", df=df, d2=d2).to_pydict()
    assert out == {"k": ["x", "x", "x"], "a": [1, 3, 5], "v": [100, 100, 100]}


def test_left_join(df, d2):
    out = dt.sql("SELECT df.k, v FROM df LEFT JOIN d2 ON df.k = d2.k ORDER BY a", df=df, d2=d2).to_pydict()
    assert out["v"] == [100, None, 100, None, 100]


def test_order_limit(df):
    out = dt.sql("SELECT * FROM df ORDER BY a DESC LIMIT 2", df=df).to_pydict()
    assert out["a"] == [5, 4]


def test_order_by_source_column(df):
    out = dt.sql("SELECT UPPER(k) AS ku FROM df WHERE k LIKE 'x%' ORDER BY a", df=df).to_pydict()
    assert out == {"ku": ["X", "X", "X"]}


def test_case_when(df):
    out = dt.sql("SELECT CASE WHEN a > 3 THEN 'big' ELSE 'small' END AS size FROM df ORDER BY a", df=df).to_pydict()
    assert out["size"] == ["small", "small", "small", "big", "big"]


def test_cte(df):
    out = dt.sql("WITH big AS (SELECT * FROM df WHERE a >= 3) SELECT COUNT(*) AS n FROM big", df=df).to_pydict()
    assert out == {"n": [3]}


def test_subquery(df):
    out = dt.sql("SELECT a*a AS sq FROM (SELECT a FROM df WHERE a <= 3) t ORDER BY sq", df=df).to_pydict()
    assert out["sq"] == [1, 4, 9]


def test_window_in_sql(df):
    out = dt.sql("SELECT a, ROW_NUMBER() OVER (PARTITION BY k ORDER BY a) AS rn FROM df ORDER BY a", df=df).to_pydict()
    assert out["rn"] == [1, 1, 2, 2, 3]
    out2 = dt.sql("SELECT a, SUM(b) OVER (ORDER BY a) AS rs FROM df ORDER BY a", df=df).to_pydict()
    assert out2["rs"] == [10.0, 30.0, 60.0, 100.0, 150.0]


def test_union(df):
    out = dt.sql("SELECT a FROM df UNION ALL SELECT a FROM df ORDER BY a LIMIT 3", df=df).to_pydict()
    assert out["a"] == [1, 1, 2]
    out2 = dt.sql("SELECT k FROM df UNION SELECT k FROM df ORDER BY k", df=df).to_pydict()
    assert out2["k"] == ["x", "y"]


def test_in_between_not(df):
    assert dt.sql("SELECT a FROM df WHERE a IN (1, 3, 9) ORDER BY a", df=df).to_pydict()["a"] == [1, 3]
    assert dt.sql("SELECT a FROM df WHERE a NOT IN (1, 3, 9) ORDER BY a", df=df).to_pydict()["a"] == [2, 4, 5]
    assert dt.sql("SELECT a FROM df WHERE a BETWEEN 2 AND 4 AND NOT k = 'y'", df=df).to_pydict()["a"] == [3]


def test_string_ops(df):
    out = dt.sql("SELECT k || '_s' AS kk FROM df LIMIT 1", df=df).to_pydict()
    assert out == {"kk": ["x_s"]}
    out2 = dt.sql("SELECT SUBSTR('hello', 2, 3) AS s").to_pydict()
    assert out2 == {"s": ["ell"]}


def test_scalar_functions():
    out = dt.sql("SELECT ABS(-3) AS x, ROUND(2.567, 1) AS y, COALESCE(NULL, 7) AS z").to_pydict()
    assert out["x"] == [3] and abs(out["y"][0] - 2.6) < 1e-9 and out["z"] == [7]


def test_cast(df):
    out = dt.sql("SELECT CAST(a AS DOUBLE) AS ad, a::BIGINT AS ab FROM df LIMIT 1", df=df).to_pydict()
    assert out == {"ad": [1.0], "ab": [1]}


def test_literal_select():
    assert dt.sql("SELECT 1 + 2 AS three").to_pydict() == {"three": [3]}


def test_is_null(df):
    d = dt.from_pydict({"x": [1, None, 3]})
    assert dt.sql("SELECT COUNT(*) AS n FROM d WHERE x IS NULL", d=d).to_pydict() == {"n": [1]}
    assert dt.sql("SELECT COUNT(*) AS n FROM d WHERE x IS NOT NULL", d=d).to_pydict() == {"n": [2]}


def test_count_distinct(df):
    out = dt.sql("SELECT COUNT(DISTINCT k) AS n FROM df", df=df).to_pydict()
    assert out == {"n": [2]}


def test_agg_expression_arithmetic(df):
    out = dt.sql("SELECT MAX(a) - MIN(a) AS spread FROM df", df=df).to_pydict()
    assert out == {"spread": [4]}


def test_session_temp_table(df):
    from daft_tpu.session import current_session

    current_session().create_temp_table("t_sql_test", df)
    out = dt.sql("SELECT COUNT(*) AS n FROM t_sql_test").to_pydict()
    assert out == {"n": [5]}
    current_session().drop_temp_table("t_sql_test")


def test_sql_expr():
    e = dt.sql_expr("a + 1 > 2")
    d = dt.from_pydict({"a": [0, 2, 5]})
    assert d.where(e).to_pydict()["a"] == [2, 5]


def test_using_join(df, d2):
    out = dt.sql("SELECT k, v FROM df JOIN d2 USING (k) ORDER BY a", df=df, d2=d2).to_pydict()
    assert out["v"] == [100, 100, 100]


def test_cross_join():
    a = dt.from_pydict({"x": [1, 2]})
    b = dt.from_pydict({"y": ["p", "q"]})
    out = dt.sql("SELECT x, y FROM a CROSS JOIN b ORDER BY x, y", a=a, b=b).to_pydict()
    assert out == {"x": [1, 1, 2, 2], "y": ["p", "q", "p", "q"]}


def test_count_star_over():
    df = dt.from_pydict({"k": ["x", "y", "x"], "a": [1, 2, 3]})
    out = dt.sql("SELECT COUNT(*) OVER (PARTITION BY t.k) AS c FROM df t", df=df).to_pydict()
    assert sorted(out["c"]) == [1, 2, 2]


def test_lag_non_literal_offset_rejected():
    df = dt.from_pydict({"k": ["x"], "a": [1], "o": [2]})
    with pytest.raises(ValueError, match="literal"):
        dt.sql("SELECT LAG(a, o) OVER (PARTITION BY k ORDER BY a) AS l FROM df", df=df)


def test_distinct_window_specs_not_merged():
    from daft_tpu import Window, col

    d = dt.from_pydict({"g": ["x"] * 4, "s": [1, 2, 3, 4], "v": [1.0, 2.0, 3.0, 4.0]})
    out = d.select(
        col("s"),
        col("v").sum().over(Window().partition_by("g").order_by("s")).alias("up"),
        col("v").sum().over(Window().partition_by("g").order_by("s", desc=True)).alias("dn"),
    ).sort("s").to_pydict()
    assert out["up"] == [1.0, 3.0, 6.0, 10.0]
    assert out["dn"] == [10.0, 9.0, 7.0, 4.0]


def test_window_partition_col_survives_pruning(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    from daft_tpu import Window, col

    pq.write_table(pa.table({"g": ["a", "a", "b"], "v": [1.0, 2.0, 3.0]}), tmp_path / "x.parquet")
    out = dt.read_parquet(str(tmp_path)).select(
        col("v").sum().over(Window().partition_by("g")).alias("s")
    ).to_pydict()
    assert sorted(out["s"]) == [3.0, 3.0, 3.0]


def test_sql_in_subquery_semi_and_anti():
    import daft_tpu

    orders = daft_tpu.from_pydict({"okey": [1, 2, 3, 4], "amt": [10, 20, 30, 40]})
    big = daft_tpu.from_pydict({"k": [2, 4, 9]})
    out = daft_tpu.sql(
        "SELECT okey FROM orders WHERE okey IN (SELECT k FROM big) ORDER BY okey",
        orders=orders, big=big).to_pydict()
    assert out == {"okey": [2, 4]}
    out = daft_tpu.sql(
        "SELECT okey FROM orders WHERE okey NOT IN (SELECT k FROM big) AND amt > 10 "
        "ORDER BY okey", orders=orders, big=big).to_pydict()
    assert out == {"okey": [3]}


def test_sql_interval_literal():
    import datetime

    import daft_tpu

    df = daft_tpu.from_pydict({
        "d": [datetime.date(1994, 1, 1), datetime.date(1994, 6, 1)],
        "v": [1, 2],
    })
    out = daft_tpu.sql(
        "SELECT v FROM t WHERE d < DATE '1994-01-01' + INTERVAL '90' DAY", t=df
    ).to_pydict()
    assert out == {"v": [1]}


def test_sql_not_in_subquery_three_valued_nulls():
    """NOT IN three-valued semantics (reference: sqlparser NOT IN planning +
    unnest_subquery): NULL in the subquery -> zero rows; NULL left keys pass
    only against an empty subquery."""
    import daft_tpu

    df = daft_tpu.from_pydict({"x": [1, 2, 3, None]})
    q = "SELECT x FROM df WHERE x NOT IN (SELECT y FROM sub)"
    # any NULL in the subquery nullifies the predicate for every row
    sub = daft_tpu.from_pydict({"y": [1, None]})
    assert daft_tpu.sql(q, df=df, sub=sub).to_pydict() == {"x": []}
    # no NULLs: left NULL keys are dropped, non-matching rows kept
    sub = daft_tpu.from_pydict({"y": [1]})
    assert sorted(daft_tpu.sql(q, df=df, sub=sub).to_pydict()["x"]) == [2, 3]
    # empty subquery: vacuously true for every row, including NULL keys
    sub = daft_tpu.from_pydict({"y": []})
    assert daft_tpu.sql(q, df=df, sub=sub).to_pydict()["x"] == [1, 2, 3, None]


def test_sql_exists_correlated_tpch_q4_shape():
    """TPC-H Q4 shape: correlated EXISTS lowered to a semi join (reference:
    planner.rs:321 + unnest_subquery.rs)."""
    import daft_tpu

    orders = daft_tpu.from_pydict({
        "o_orderkey": [1, 2, 3, 4], "o_pri": ["H", "L", "H", "M"]})
    lineitem = daft_tpu.from_pydict({
        "l_orderkey": [1, 1, 2, 4], "l_commit": [5, 9, 3, 7], "l_receipt": [6, 2, 9, 7]})
    out = daft_tpu.sql(
        "SELECT o_pri, COUNT(*) AS n FROM orders WHERE EXISTS "
        "(SELECT 1 FROM lineitem WHERE l_orderkey = o_orderkey AND l_commit < l_receipt) "
        "GROUP BY o_pri ORDER BY o_pri", orders=orders, lineitem=lineitem).to_pydict()
    assert out == {"o_pri": ["H", "L"], "n": [1, 1]}
    # dataframe equivalent for cross-checking
    from daft_tpu import col
    sub = lineitem.where(col("l_commit") < col("l_receipt"))
    expect = (orders.join(sub, left_on="o_orderkey", right_on="l_orderkey", how="semi")
              .groupby("o_pri").agg(col("o_pri").count().alias("n"))
              .sort("o_pri").to_pydict())
    assert out["n"] == expect["n"]


def test_sql_not_exists_and_uncorrelated_exists():
    import daft_tpu

    orders = daft_tpu.from_pydict({"o_orderkey": [1, 2, 3]})
    lineitem = daft_tpu.from_pydict({"l_orderkey": [1, 2], "l_x": [5, -1]})
    out = daft_tpu.sql(
        "SELECT o_orderkey FROM orders WHERE NOT EXISTS "
        "(SELECT 1 FROM lineitem WHERE l_orderkey = o_orderkey) ORDER BY o_orderkey",
        orders=orders, lineitem=lineitem).to_pydict()
    assert out == {"o_orderkey": [3]}
    # uncorrelated: empty subquery -> no rows; nonempty -> all rows
    out = daft_tpu.sql(
        "SELECT o_orderkey FROM orders WHERE EXISTS (SELECT 1 FROM lineitem WHERE l_x > 100)",
        orders=orders, lineitem=lineitem).to_pydict()
    assert out == {"o_orderkey": []}
    out = daft_tpu.sql(
        "SELECT o_orderkey FROM orders WHERE EXISTS (SELECT 1 FROM lineitem WHERE l_x > 0) "
        "ORDER BY o_orderkey", orders=orders, lineitem=lineitem).to_pydict()
    assert out == {"o_orderkey": [1, 2, 3]}


def test_sql_scalar_subquery_tpch_q17_shape():
    """TPC-H Q17 shape: correlated scalar aggregate bound via grouped left
    join; NULL thresholds (keys absent from the subquery) filter out."""
    import daft_tpu

    part = daft_tpu.from_pydict({"p_partkey": [10, 20, 30], "p_brand": ["A", "B", "C"]})
    li = daft_tpu.from_pydict({
        "l_partkey": [10, 10, 10, 20, 20, 30],
        "l_qty": [1.0, 2.0, 9.0, 4.0, 4.0, 2.0],
        "l_price": [5.0, 6.0, 7.0, 8.0, 9.0, 1.0]})
    out = daft_tpu.sql(
        "SELECT SUM(l_price) AS rev FROM li, part WHERE p_partkey = l_partkey "
        "AND l_qty < (SELECT 0.5 * AVG(l_qty) FROM li WHERE l_partkey = p_partkey)",
        li=li, part=part).to_pydict()
    # pk10: avg 4 -> thr 2 -> qty 1 (5.0); pk20: thr 2 -> none; pk30: thr 1 -> none
    assert out == {"rev": [5.0]}


def test_sql_scalar_subquery_uncorrelated():
    import daft_tpu

    li = daft_tpu.from_pydict({"q": [1.0, 2.0, 9.0, 4.0]})
    out = daft_tpu.sql("SELECT q FROM li WHERE q > (SELECT AVG(q) FROM li) ORDER BY q",
                       li=li).to_pydict()
    assert out == {"q": [9.0]}


def test_sql_comma_join_plans_as_hash_join():
    """SQL-92 comma FROM lists must execute as equi hash joins, not cartesian
    products (rule_cross_join_to_inner)."""
    import daft_tpu
    from daft_tpu.plan import logical as lp
    from daft_tpu.sql.planner import plan_sql

    a = daft_tpu.from_pydict({"x": list(range(200)), "v": list(range(200))})
    b = daft_tpu.from_pydict({"y": list(range(0, 200, 2)), "w": list(range(100))})
    df = plan_sql("SELECT SUM(v) AS s FROM a, b WHERE x = y AND w >= 0", {"a": a, "b": b})
    plan = df._builder.optimize().plan
    crosses = []
    inners = []

    def walk(n):
        if isinstance(n, lp.Join):
            (crosses if n.how == "cross" else inners).append(n)
        for c in n.children():
            walk(c)

    walk(plan)
    assert not crosses and inners, "comma join was not rewritten to an inner join"
    assert df.to_pydict() == {"s": [sum(range(0, 200, 2))]}


def test_sql_scalar_subquery_multi_row_errors_and_empty_binds_null():
    import pytest

    import daft_tpu

    t = daft_tpu.from_pydict({"x": [1, 5, 9]})
    multi = daft_tpu.from_pydict({"q": [1.0, 2.0]})
    with pytest.raises(ValueError, match="more than one row"):
        daft_tpu.sql("SELECT x FROM t WHERE x > (SELECT q FROM multi)", t=t, multi=multi)
    empty = daft_tpu.from_pydict({"q": []})
    out = daft_tpu.sql(
        "SELECT x FROM t WHERE x = 1 OR x > (SELECT q FROM empty) ORDER BY x",
        t=t, empty=empty).to_pydict()
    assert out == {"x": [1]}  # NULL comparison is NULL; OR keeps the x=1 row


def test_sql_exists_limit_zero_is_false():
    import daft_tpu

    orders = daft_tpu.from_pydict({"o": [1, 2]})
    li = daft_tpu.from_pydict({"l": [1, 2]})
    out = daft_tpu.sql(
        "SELECT o FROM orders WHERE EXISTS (SELECT 1 FROM li WHERE l = o LIMIT 0)",
        orders=orders, li=li).to_pydict()
    assert out == {"o": []}
    out = daft_tpu.sql(
        "SELECT o FROM orders WHERE NOT EXISTS (SELECT 1 FROM li WHERE l = o LIMIT 0) "
        "ORDER BY o", orders=orders, li=li).to_pydict()
    assert out == {"o": [1, 2]}
    # LIMIT >= 1 doesn't change existence
    out = daft_tpu.sql(
        "SELECT o FROM orders WHERE EXISTS (SELECT 1 FROM li WHERE l = o LIMIT 5) "
        "ORDER BY o", orders=orders, li=li).to_pydict()
    assert out == {"o": [1, 2]}


def test_sql_correlated_scalar_unsupported_shapes_raise_cleanly():
    import pytest

    import daft_tpu

    t = daft_tpu.from_pydict({"k": [1, 2]})
    s = daft_tpu.from_pydict({"k2": [1, 2], "v": [10, 20]})
    with pytest.raises(NotImplementedError, match="aggregate"):
        daft_tpu.sql("SELECT k FROM t WHERE k > (SELECT v FROM s WHERE k2 = k)", t=t, s=s)
    with pytest.raises(NotImplementedError, match="LIMIT"):
        daft_tpu.sql(
            "SELECT k FROM t WHERE k > (SELECT MAX(v) FROM s WHERE k2 = k LIMIT 1)",
            t=t, s=s)
