"""HBM residency manager (daft_tpu/device/residency.py): budget-bounded LRU
eviction, one-slot reuse for varying predicate literals, pin-during-execution
safety, cache hits with zero re-transfer, and the zero-overhead host-path
guard. Device paths run with device_mode="on" on the CPU backend (jit
semantics identical to TPU)."""

import numpy as np
import pytest

import daft_tpu
from daft_tpu import col, lit
from daft_tpu.config import execution_config_ctx
from daft_tpu.device.residency import identity_token, manager
from daft_tpu.observability.metrics import registry
from daft_tpu.ops import counters


@pytest.fixture(scope="module")
def star():
    rng = np.random.default_rng(17)
    n = 8_192
    fact = daft_tpu.from_pydict({
        "f_k": [int(x) for x in rng.integers(0, 400, n)],
        "f_v": rng.uniform(0, 100, n).tolist(),
        "f_q": rng.integers(1, 50, n).tolist(),
    }).collect()
    dim = daft_tpu.from_pydict({
        "d_k": list(range(400)),
        "d_grp": [f"g{i % 6}" for i in range(400)],
        "d_w": [float(i % 17) for i in range(400)],
    }).collect()
    return fact, dim


def _query(fact, dim, threshold: float):
    return (fact.join(dim, left_on="f_k", right_on="d_k")
            .where(col("d_w") < lit(threshold))
            .groupby("d_grp")
            .agg(col("f_v").sum().alias("sv"), col("f_q").sum().alias("sq"))
            .sort("d_grp"))


def _host_result(fact, dim, threshold: float):
    with execution_config_ctx(device_mode="off"):
        return _query(fact, dim, threshold).to_pydict()


def _assert_close(host, dev):
    assert list(host.keys()) == list(dev.keys())
    for c in host:
        assert len(host[c]) == len(dev[c]), c
        for a, b in zip(host[c], dev[c]):
            if isinstance(a, float) and isinstance(b, float):
                assert abs(a - b) <= 1e-6 * max(1.0, abs(a)), (c, a, b)
            else:
                assert a == b, (c, a, b)


def test_budget_bounded_eviction_varying_literals(star):
    """A loop of device-join queries with varying filter literals keeps
    registered device bytes <= budget (evictions observed via counters) and
    returns host-identical results."""
    fact, dim = star
    manager().clear()
    counters.reset()
    budget = 96 * 1024  # well below the query's full working set
    with execution_config_ctx(device_mode="on", hbm_budget_bytes=budget):
        for i in range(6):
            threshold = float(3 + i)
            dev = _query(fact, dim, threshold).to_pydict()
            _assert_close(_host_result(fact, dim, threshold), dev)
            resident = manager().bytes_resident()
            assert resident <= budget, \
                f"iteration {i}: {resident} bytes resident > {budget} budget"
    assert counters.hbm_evictions > 0, "budget never forced an eviction"
    assert registry().get("hbm_eviction_bytes") > 0


def test_varying_literals_reuse_one_slot(star):
    """Literal-dependent caches (visibility planes, packed dim matrices) are
    structure-keyed: re-running the same query shape with a different literal
    must not add entries (the ADVICE r5 unbounded-growth bug)."""
    fact, dim = star
    manager().clear()
    with execution_config_ctx(device_mode="on"):
        _query(fact, dim, 5.0).to_pydict()
        entries_after_first = manager().entry_count()
        _query(fact, dim, 9.0).to_pydict()   # same shape, new literal
        _query(fact, dim, 2.0).to_pydict()
        assert manager().entry_count() == entries_after_first
        # and the varying-literal runs still compute the literal's result
        _assert_close(_host_result(fact, dim, 2.0),
                      _query(fact, dim, 2.0).to_pydict())


def test_cache_hit_second_identical_query(star):
    """The second run of an identical query is served from HBM: residency
    hits, no new uploads (zero h2d delta — the QueryEnd.metrics contract)."""
    fact, dim = star
    manager().clear()
    counters.reset()
    with execution_config_ctx(device_mode="on"):
        first = _query(fact, dim, 7.0).to_pydict()
        h2d_after_first = registry().get("hbm_h2d_bytes")
        hits_after_first = registry().get("hbm_cache_hits")
        assert h2d_after_first > 0  # first run really uploaded
        second = _query(fact, dim, 7.0).to_pydict()
    _assert_close(first, second)
    assert registry().get("hbm_cache_hits") > hits_after_first
    assert registry().get("hbm_h2d_bytes") == h2d_after_first, \
        "second identical query re-uploaded column planes"


def test_pin_during_execution_under_tiny_budget(star):
    """With a budget far below the query's working set, in-flight buffers are
    pinned (never evicted mid-run) and results stay correct; the budget
    re-enforces after the query ends."""
    fact, dim = star
    manager().clear()
    counters.reset()
    budget = 4 * 1024
    with execution_config_ctx(device_mode="on", hbm_budget_bytes=budget):
        dev = _query(fact, dim, 8.0).to_pydict()
        _assert_close(_host_result(fact, dim, 8.0), dev)
        # post-query: everything unpinned, budget enforced again
        assert manager().bytes_resident() <= budget
    assert registry().get("hbm_pins") > 0, "no entry was pinned during the run"


def test_zero_overhead_when_no_device_used(star):
    """A host-only query never touches the manager: no entries, no counters."""
    fact, dim = star
    manager().clear()
    counters.reset()
    with execution_config_ctx(device_mode="off"):
        _query(fact, dim, 4.0).to_pydict()
    stats = manager().stats()
    assert stats["hbm_entries"] == 0
    assert stats["hbm_bytes_resident"] == 0
    assert registry().get("hbm_cache_misses") == 0
    assert registry().get("hbm_h2d_bytes") == 0


def test_budget_env_and_gauges(star):
    """The gauges land in the metrics registry snapshot (the path QueryEnd /
    explain_analyze / bench read), and high-water >= resident."""
    fact, dim = star
    manager().clear()
    with execution_config_ctx(device_mode="on"):
        _query(fact, dim, 6.0).to_pydict()
    snap = registry().snapshot()
    assert snap.get("hbm_bytes_resident", 0) > 0
    assert snap.get("hbm_bytes_high_water", 0) >= snap["hbm_bytes_resident"]
    assert manager().stats()["hbm_bytes_resident"] == snap["hbm_bytes_resident"]


def test_entries_die_with_their_series():
    """Entries anchored on a collected table are released when the table's
    Series die (no leak of device buffers past their host owner)."""
    manager().clear()
    fact = daft_tpu.from_pydict({
        "k": list(range(2048)), "v": [float(i) for i in range(2048)],
    }).collect()
    with execution_config_ctx(device_mode="on"):
        fact.agg(col("v").sum().alias("s")).to_pydict()
    assert manager().entry_count() > 0
    del fact
    import gc

    gc.collect()
    assert manager().entry_count() == 0


def test_identity_token_monotonic_and_sticky():
    a = daft_tpu.from_pydict({"x": [1]}).collect()
    b = daft_tpu.from_pydict({"x": [2]}).collect()
    ta1, ta2 = identity_token(a), identity_token(a)
    tb = identity_token(b)
    assert ta1 == ta2
    assert ta1 != tb


def test_identity_token_not_pickled():
    """Tokens are process-local: shipping one to a worker would collide with
    the receiver's independently-counted tokens and alias distinct objects
    in advisory caches (the id()-reuse bug class, cross-process edition)."""
    import pickle

    from daft_tpu.core.micropartition import MicroPartition
    from daft_tpu.core.series import Series

    mp = MicroPartition.from_pydict({"x": [1, 2]})
    identity_token(mp)
    assert getattr(pickle.loads(pickle.dumps(mp)), "_rtoken", None) is None
    s = Series.from_pylist([1, 2], "s")
    identity_token(s)
    assert getattr(pickle.loads(pickle.dumps(s)), "_rtoken", None) is None


def test_cost_weighted_eviction_cheapest_first():
    """Under budget pressure, the cheaper-to-rebuild entry in the oldest
    recency bucket evicts first: a plain (re-uploadable) plane goes before an
    equally-recent expensive one (join index / dictionary planes carry host
    factorize work via rebuild_rows), and the saved rebuild cost is counted."""
    import jax.numpy as jnp

    m = manager()
    m.clear()
    saved_before = registry().get("hbm_evict_cost_saved")

    class Anchor:  # plain object: identity-keyed, no stable content
        pass

    dear, cheap, extra = Anchor(), Anchor(), Anchor()

    def one_kb():
        # explicit f32: entry size must not depend on whether x64 mode was
        # enabled by earlier tests (jax_setup import order)
        return jnp.ones(256, dtype=jnp.float32)

    with execution_config_ctx(hbm_budget_bytes=2 * 1024 + 512):
        # insert the EXPENSIVE entry first: it is the LRU-oldest, so pure
        # recency eviction would take it — cost weighting must not
        m.get_or_build(dear, ("d",), (), one_kb, rebuild_rows=50_000_000)
        m.get_or_build(cheap, ("c",), (), one_kb)
        m.get_or_build(extra, ("x",), (), one_kb)
        assert m.is_resident(dear, ("d",)), \
            "expensive-to-rebuild plane was evicted despite a cheap candidate"
        assert not m.is_resident(cheap, ("c",))
        assert m.bytes_resident() <= 2 * 1024 + 512
    assert registry().get("hbm_evict_cost_saved") > saved_before
    m.clear()


def test_eviction_keeps_recency_with_few_entries():
    """Cost weighting must not invert recency wholesale: with only a cold
    expensive entry and a hot cheap one, the eviction bucket is the oldest
    HALF (= the cold entry alone), so the squatter leaves and the hot plane
    stays — not the thrash of re-uploading the hot plane every query."""
    import jax.numpy as jnp

    m = manager()
    m.clear()

    class Anchor:
        pass

    cold_dear, hot_cheap = Anchor(), Anchor()

    def one_kb():
        return jnp.ones(256, dtype=jnp.float32)

    with execution_config_ctx(hbm_budget_bytes=1024 + 512):
        m.get_or_build(cold_dear, ("d",), (), one_kb, rebuild_rows=50_000_000)
        m.get_or_build(hot_cheap, ("c",), (), one_kb)  # over budget now
        assert m.is_resident(hot_cheap, ("c",))
        assert not m.is_resident(cold_dear, ("d",)), \
            "rebuild cost protected a cold squatter over the hot plane"
    m.clear()


def test_eviction_bucket_ignores_pinned_padding():
    """Pinned entries must not widen the recency window: with one pinned
    entry plus a cold expensive and a hot cheap plane, the oldest-half bucket
    spans the UNPINNED entries only (= the cold one), so the hot plane
    survives."""
    import jax.numpy as jnp

    m = manager()
    m.clear()

    class Anchor:
        pass

    pinned, cold_dear, hot_cheap = Anchor(), Anchor(), Anchor()

    def one_kb():
        return jnp.ones(256, dtype=jnp.float32)

    with execution_config_ctx(hbm_budget_bytes=2 * 1024 + 512):
        # both registered (and released) under budget first
        with m.pin_scope():
            m.get_or_build(pinned, ("pin",), (), one_kb)
            m.get_or_build(cold_dear, ("d",), (), one_kb,
                           rebuild_rows=50_000_000)
        with m.pin_scope():
            # re-pin one entry (moves to MRU), then push over budget: LRU
            # order is [cold_dear, pinned, hot_cheap] with only cold_dear and
            # hot_cheap unpinned — the half-window must span those two, not
            # all three, so the single candidate is cold_dear
            m.get_or_build(pinned, ("pin",), (), one_kb)
            m.get_or_build(hot_cheap, ("c",), (), one_kb)
            assert m.is_resident(hot_cheap, ("c",)), \
                "pinned padding widened the bucket onto the hot plane"
            assert not m.is_resident(cold_dear, ("d",))
    m.clear()


def test_cost_weighted_eviction_never_touches_pins():
    """Pinned entries stay resident whatever their rebuild cost: a pinned
    cheap plane survives while unpinned entries (even expensive ones) evict."""
    import jax.numpy as jnp

    m = manager()
    m.clear()

    class Anchor:
        pass

    pinned_cheap, dear = Anchor(), Anchor()

    def one_kb():
        return jnp.ones(256, dtype=jnp.float32)

    with execution_config_ctx(hbm_budget_bytes=1024 + 512):
        # expensive entry registered OUTSIDE any pin scope: evictable
        m.get_or_build(dear, ("d",), (), one_kb, rebuild_rows=10_000_000)
        with m.pin_scope():
            # pushes over budget; the only unpinned candidate is `dear`,
            # whose high rebuild cost must not protect it from a pin
            m.get_or_build(pinned_cheap, ("p",), (), one_kb)
            assert m.is_resident(pinned_cheap, ("p",))
            assert not m.is_resident(dear, ("d",)), \
                "unpinned entry should have evicted, not the pinned one"
    m.clear()


def test_stable_rebind_serves_unpickled_copy_without_reupload():
    """A content-identical Series (e.g. a worker's freshly-unpickled repeat
    sub-plan input) rebinds the existing slot: one entry, no new h2d bytes,
    and the digest advertises the slot under the same stable key both
    times."""
    import pickle

    from daft_tpu.core.series import Series
    from daft_tpu.device.residency import stable_slot_key

    m = manager()
    m.clear()
    s = Series.from_pylist(list(range(4096)), "c")
    s.to_device_cached(4096, f32=True)
    h2d = registry().get("hbm_h2d_bytes")
    rehits = registry().get("hbm_stable_rehits")
    digest1 = dict(m.digest())
    assert stable_slot_key(s, ("col", 4096, True)) in digest1

    s2 = pickle.loads(pickle.dumps(s))
    assert s2 is not s and getattr(s2, "_rtoken", None) is None
    s2.to_device_cached(4096, f32=True)
    assert registry().get("hbm_h2d_bytes") == h2d, "rebind re-uploaded"
    assert registry().get("hbm_stable_rehits") == rehits + 1
    assert m.entry_count() == 1
    assert dict(m.digest()) == digest1
    m.clear()


def test_orphan_retention_is_opt_in(monkeypatch):
    """Driver default (DAFT_TPU_HBM_ORPHANS unset): entries still die with
    their anchor. With a positive cap (the worker-pool environment), a stable
    entry survives its anchor and a content-equal anchor rebinds it."""
    import gc
    import pickle

    from daft_tpu.core.series import Series

    m = manager()
    m.clear()
    blob = pickle.dumps(Series.from_pylist(list(range(512)), "c"))

    s = pickle.loads(blob)
    s.to_device_cached(512, f32=True)
    del s
    gc.collect()
    assert m.entry_count() == 0  # strict anchor-coupled lifetime by default

    monkeypatch.setenv("DAFT_TPU_HBM_ORPHANS", "8")
    m.clear()  # re-reads the cap
    s = pickle.loads(blob)
    s.to_device_cached(512, f32=True)
    h2d = registry().get("hbm_h2d_bytes")
    del s
    gc.collect()
    assert m.entry_count() == 1  # orphaned but retained (content-addressed)
    s2 = pickle.loads(blob)
    s2.to_device_cached(512, f32=True)  # rebinds the orphan
    assert registry().get("hbm_h2d_bytes") == h2d
    assert m.entry_count() == 1
    m.clear()


def test_rebuild_in_place_keeps_pin():
    """A dep/literal mismatch inside a pin scope rebuilds the slot in place;
    the replacement must inherit the pin so a tight budget cannot evict a
    plane the executing query is about to read."""
    import jax.numpy as jnp

    from daft_tpu.core.series import Series

    m = manager()
    m.clear()
    anchor = Series.from_pylist(list(range(8)), "anchor")
    d1, d2 = object(), object()
    with execution_config_ctx(hbm_budget_bytes=1):  # below any entry's size
        with m.pin_scope():
            m.get_or_build(anchor, ("k",), (d1,), lambda: jnp.ones(1024))
            m.get_or_build(anchor, ("k",), (d2,), lambda: jnp.ones(1024))
            # pinned despite the over-budget rebuild: still resident
            assert m.entry_count() == 1
            assert m.bytes_resident() > 1
        # scope closed: the pin released exactly once, budget re-enforces
        assert m.entry_count() == 0
    m.clear()
