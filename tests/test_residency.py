"""HBM residency manager (daft_tpu/device/residency.py): budget-bounded LRU
eviction, one-slot reuse for varying predicate literals, pin-during-execution
safety, cache hits with zero re-transfer, and the zero-overhead host-path
guard. Device paths run with device_mode="on" on the CPU backend (jit
semantics identical to TPU)."""

import numpy as np
import pytest

import daft_tpu
from daft_tpu import col, lit
from daft_tpu.config import execution_config_ctx
from daft_tpu.device.residency import identity_token, manager
from daft_tpu.observability.metrics import registry
from daft_tpu.ops import counters


@pytest.fixture(scope="module")
def star():
    rng = np.random.default_rng(17)
    n = 8_192
    fact = daft_tpu.from_pydict({
        "f_k": [int(x) for x in rng.integers(0, 400, n)],
        "f_v": rng.uniform(0, 100, n).tolist(),
        "f_q": rng.integers(1, 50, n).tolist(),
    }).collect()
    dim = daft_tpu.from_pydict({
        "d_k": list(range(400)),
        "d_grp": [f"g{i % 6}" for i in range(400)],
        "d_w": [float(i % 17) for i in range(400)],
    }).collect()
    return fact, dim


def _query(fact, dim, threshold: float):
    return (fact.join(dim, left_on="f_k", right_on="d_k")
            .where(col("d_w") < lit(threshold))
            .groupby("d_grp")
            .agg(col("f_v").sum().alias("sv"), col("f_q").sum().alias("sq"))
            .sort("d_grp"))


def _host_result(fact, dim, threshold: float):
    with execution_config_ctx(device_mode="off"):
        return _query(fact, dim, threshold).to_pydict()


def _assert_close(host, dev):
    assert list(host.keys()) == list(dev.keys())
    for c in host:
        assert len(host[c]) == len(dev[c]), c
        for a, b in zip(host[c], dev[c]):
            if isinstance(a, float) and isinstance(b, float):
                assert abs(a - b) <= 1e-6 * max(1.0, abs(a)), (c, a, b)
            else:
                assert a == b, (c, a, b)


def test_budget_bounded_eviction_varying_literals(star):
    """A loop of device-join queries with varying filter literals keeps
    registered device bytes <= budget (evictions observed via counters) and
    returns host-identical results."""
    fact, dim = star
    manager().clear()
    counters.reset()
    budget = 96 * 1024  # well below the query's full working set
    with execution_config_ctx(device_mode="on", hbm_budget_bytes=budget):
        for i in range(6):
            threshold = float(3 + i)
            dev = _query(fact, dim, threshold).to_pydict()
            _assert_close(_host_result(fact, dim, threshold), dev)
            resident = manager().bytes_resident()
            assert resident <= budget, \
                f"iteration {i}: {resident} bytes resident > {budget} budget"
    assert counters.hbm_evictions > 0, "budget never forced an eviction"
    assert registry().get("hbm_eviction_bytes") > 0


def test_varying_literals_reuse_one_slot(star):
    """Literal-dependent caches (visibility planes, packed dim matrices) are
    structure-keyed: re-running the same query shape with a different literal
    must not add entries (the ADVICE r5 unbounded-growth bug)."""
    fact, dim = star
    manager().clear()
    with execution_config_ctx(device_mode="on"):
        _query(fact, dim, 5.0).to_pydict()
        entries_after_first = manager().entry_count()
        _query(fact, dim, 9.0).to_pydict()   # same shape, new literal
        _query(fact, dim, 2.0).to_pydict()
        assert manager().entry_count() == entries_after_first
        # and the varying-literal runs still compute the literal's result
        _assert_close(_host_result(fact, dim, 2.0),
                      _query(fact, dim, 2.0).to_pydict())


def test_cache_hit_second_identical_query(star):
    """The second run of an identical query is served from HBM: residency
    hits, no new uploads (zero h2d delta — the QueryEnd.metrics contract)."""
    fact, dim = star
    manager().clear()
    counters.reset()
    with execution_config_ctx(device_mode="on"):
        first = _query(fact, dim, 7.0).to_pydict()
        h2d_after_first = registry().get("hbm_h2d_bytes")
        hits_after_first = registry().get("hbm_cache_hits")
        assert h2d_after_first > 0  # first run really uploaded
        second = _query(fact, dim, 7.0).to_pydict()
    _assert_close(first, second)
    assert registry().get("hbm_cache_hits") > hits_after_first
    assert registry().get("hbm_h2d_bytes") == h2d_after_first, \
        "second identical query re-uploaded column planes"


def test_pin_during_execution_under_tiny_budget(star):
    """With a budget far below the query's working set, in-flight buffers are
    pinned (never evicted mid-run) and results stay correct; the budget
    re-enforces after the query ends."""
    fact, dim = star
    manager().clear()
    counters.reset()
    budget = 4 * 1024
    with execution_config_ctx(device_mode="on", hbm_budget_bytes=budget):
        dev = _query(fact, dim, 8.0).to_pydict()
        _assert_close(_host_result(fact, dim, 8.0), dev)
        # post-query: everything unpinned, budget enforced again
        assert manager().bytes_resident() <= budget
    assert registry().get("hbm_pins") > 0, "no entry was pinned during the run"


def test_zero_overhead_when_no_device_used(star):
    """A host-only query never touches the manager: no entries, no counters."""
    fact, dim = star
    manager().clear()
    counters.reset()
    with execution_config_ctx(device_mode="off"):
        _query(fact, dim, 4.0).to_pydict()
    stats = manager().stats()
    assert stats["hbm_entries"] == 0
    assert stats["hbm_bytes_resident"] == 0
    assert registry().get("hbm_cache_misses") == 0
    assert registry().get("hbm_h2d_bytes") == 0


def test_budget_env_and_gauges(star):
    """The gauges land in the metrics registry snapshot (the path QueryEnd /
    explain_analyze / bench read), and high-water >= resident."""
    fact, dim = star
    manager().clear()
    with execution_config_ctx(device_mode="on"):
        _query(fact, dim, 6.0).to_pydict()
    snap = registry().snapshot()
    assert snap.get("hbm_bytes_resident", 0) > 0
    assert snap.get("hbm_bytes_high_water", 0) >= snap["hbm_bytes_resident"]
    assert manager().stats()["hbm_bytes_resident"] == snap["hbm_bytes_resident"]


def test_entries_die_with_their_series():
    """Entries anchored on a collected table are released when the table's
    Series die (no leak of device buffers past their host owner)."""
    manager().clear()
    fact = daft_tpu.from_pydict({
        "k": list(range(2048)), "v": [float(i) for i in range(2048)],
    }).collect()
    with execution_config_ctx(device_mode="on"):
        fact.agg(col("v").sum().alias("s")).to_pydict()
    assert manager().entry_count() > 0
    del fact
    import gc

    gc.collect()
    assert manager().entry_count() == 0


def test_identity_token_monotonic_and_sticky():
    a = daft_tpu.from_pydict({"x": [1]}).collect()
    b = daft_tpu.from_pydict({"x": [2]}).collect()
    ta1, ta2 = identity_token(a), identity_token(a)
    tb = identity_token(b)
    assert ta1 == ta2
    assert ta1 != tb


def test_identity_token_not_pickled():
    """Tokens are process-local: shipping one to a worker would collide with
    the receiver's independently-counted tokens and alias distinct objects
    in advisory caches (the id()-reuse bug class, cross-process edition)."""
    import pickle

    from daft_tpu.core.micropartition import MicroPartition
    from daft_tpu.core.series import Series

    mp = MicroPartition.from_pydict({"x": [1, 2]})
    identity_token(mp)
    assert getattr(pickle.loads(pickle.dumps(mp)), "_rtoken", None) is None
    s = Series.from_pylist([1, 2], "s")
    identity_token(s)
    assert getattr(pickle.loads(pickle.dumps(s)), "_rtoken", None) is None


def test_rebuild_in_place_keeps_pin():
    """A dep/literal mismatch inside a pin scope rebuilds the slot in place;
    the replacement must inherit the pin so a tight budget cannot evict a
    plane the executing query is about to read."""
    import jax.numpy as jnp

    from daft_tpu.core.series import Series

    m = manager()
    m.clear()
    anchor = Series.from_pylist(list(range(8)), "anchor")
    d1, d2 = object(), object()
    with execution_config_ctx(hbm_budget_bytes=1):  # below any entry's size
        with m.pin_scope():
            m.get_or_build(anchor, ("k",), (d1,), lambda: jnp.ones(1024))
            m.get_or_build(anchor, ("k",), (d2,), lambda: jnp.ones(1024))
            # pinned despite the over-budget rebuild: still resident
            assert m.entry_count() == 1
            assert m.bytes_resident() > 1
        # scope closed: the pin released exactly once, budget re-enforces
        assert m.entry_count() == 0
    m.clear()
