"""Observability: subscriber lifecycle events, per-operator runtime stats,
EXPLAIN ANALYZE (reference: tests/test_subscribers.py / test_events.py)."""

import numpy as np
import pytest

import daft_tpu
from daft_tpu import col
from daft_tpu.observability import (
    OperatorStats,
    QueryEnd,
    QueryOptimized,
    QueryStart,
    Subscriber,
    attach_subscriber,
    detach_subscriber,
)


class Recorder(Subscriber):
    def __init__(self):
        self.events = []

    def on_query_start(self, e):
        self.events.append(("start", e))

    def on_query_optimized(self, e):
        self.events.append(("optimized", e))

    def on_operator_stats(self, qid, s):
        self.events.append(("op", s))

    def on_query_end(self, e):
        self.events.append(("end", e))


@pytest.fixture
def recorder():
    r = Recorder()
    attach_subscriber(r)
    yield r
    detach_subscriber(r)


def test_event_sequence_and_contents(recorder):
    df = daft_tpu.from_pydict({"a": list(range(100)), "b": ["x", "y"] * 50})
    out = df.where(col("a") >= 50).select("a").to_pydict()
    assert len(out["a"]) == 50

    kinds = [k for k, _ in recorder.events]
    assert kinds[0] == "start"
    assert kinds[1] == "optimized"
    assert kinds[-1] == "end"
    assert "op" in kinds

    start = recorder.events[0][1]
    assert isinstance(start, QueryStart) and start.query_id
    optimized = recorder.events[1][1]
    assert isinstance(optimized, QueryOptimized)
    assert "Filter" in start.unoptimized_plan
    assert optimized.physical_plan  # physical display present
    end = recorder.events[-1][1]
    assert isinstance(end, QueryEnd)
    assert end.rows == 50
    assert end.error is None
    assert end.query_id == start.query_id
    # operator stats cover the pipeline with real row counts
    ops = {s.name: s for k, s in recorder.events if k == "op"}
    assert any(s.rows_out == 50 for s in ops.values()), ops


def test_error_reported_in_query_end(recorder):
    df = daft_tpu.from_pydict({"a": [1, 2, 3]})

    @daft_tpu.func
    def boom(x: int) -> int:
        raise ValueError("nope")

    with pytest.raises(Exception):
        df.select(boom(col("a"))).to_pydict()
    end = recorder.events[-1][1]
    assert isinstance(end, QueryEnd)
    assert end.error is not None and ("nope" in end.error or "ValueError" in end.error)


def test_broken_subscriber_never_fails_query():
    class Broken(Subscriber):
        def on_query_start(self, e):
            raise RuntimeError("subscriber bug")

    b = Broken()
    attach_subscriber(b)
    try:
        out = daft_tpu.from_pydict({"a": [1]}).to_pydict()
        assert out == {"a": [1]}
    finally:
        detach_subscriber(b)


def test_no_subscribers_no_overhead_path():
    """Without subscribers the collector stays None (zero-overhead path)."""
    from daft_tpu.observability.runtime_stats import current_collector

    daft_tpu.from_pydict({"a": [1, 2]}).where(col("a") > 1).to_pydict()
    assert current_collector() is None


def test_overhead_guard_zero_subscribers_zero_instrumentation(monkeypatch):
    """Tier-1 overhead guard: with no subscribers attached, a query must take
    the zero-overhead path — no StatsCollector wrapping anywhere in the
    executor, no timeline span recording, no stall-clock reads on the
    pipeline channels, and the metrics registry untouched — so observability
    can never silently tax the hot path."""
    from daft_tpu.execution import pipeline
    from daft_tpu.observability import runtime_stats
    from daft_tpu.observability.metrics import registry
    from daft_tpu.observability.subscribers import subscribers_active

    assert not subscribers_active(), \
        "leaked subscriber from another test would invalidate this guard"
    assert runtime_stats.current_spans() is None, \
        "leaked span recorder from another test would invalidate this guard"

    def _forbidden_wrap(self, node, iterator):
        raise AssertionError("StatsCollector.wrap called on the zero-overhead path")

    def _forbidden_span(self, *a, **k):
        raise AssertionError("SpanRecorder.record called on the zero-overhead path")

    def _forbidden_stall(self, *a, **k):
        raise AssertionError("stall attribution ran on the zero-overhead path")

    monkeypatch.setattr(runtime_stats.StatsCollector, "wrap", _forbidden_wrap)
    monkeypatch.setattr(runtime_stats.SpanRecorder, "record", _forbidden_span)
    monkeypatch.setattr(runtime_stats.StatsCollector, "note_starve",
                        _forbidden_stall)
    monkeypatch.setattr(runtime_stats.StatsCollector, "note_blocked",
                        _forbidden_stall)

    # every stage channel must be UNPROFILED with no collector active
    orig_channel_init = pipeline.Channel.__init__

    def _checked_init(self, maxsize=4, profile=None):
        assert profile is None, "profiled Channel on the zero-overhead path"
        orig_channel_init(self, maxsize, profile)

    monkeypatch.setattr(pipeline.Channel, "__init__", _checked_init)
    before = registry().snapshot()
    df = daft_tpu.from_pydict({"a": list(range(1000)), "b": ["x", "y"] * 500})
    out = (df.where(col("a") >= 500)
           .groupby("b").agg(col("a").sum().alias("s")).to_pydict())
    assert len(out["b"]) == 2
    assert registry().diff(before) == {}, "registry touched with no observers"


def test_stats_collector_nested_self_time():
    """Self-time attribution with nested operators: the parent's attributed
    time excludes its child's production time (runtime_stats contract)."""
    import time as _time

    from daft_tpu.observability.runtime_stats import StatsCollector

    class FakeNode:
        def __init__(self, name):
            self._name = name

        def name(self):
            return self._name

    class Part:
        num_rows = 1

    child_node, parent_node = FakeNode("child"), FakeNode("parent")
    c = StatsCollector()

    def child_gen():
        for _ in range(3):
            _time.sleep(0.02)  # child production time
            yield Part()

    child_stream = c.wrap(child_node, child_gen())

    def parent_gen():
        for part in child_stream:
            _time.sleep(0.005)  # parent's own work per batch
            yield part

    parent_stream = c.wrap(parent_node, parent_gen())
    assert sum(p.num_rows for p in parent_stream) == 3
    stats = {s.name: s for s in c.finish()}
    assert stats["child"].rows_out == 3 and stats["parent"].rows_out == 3
    # child self time ~3*20ms; parent self time ~3*5ms and must NOT include
    # the child's 60ms of production time
    assert stats["child"].seconds >= 0.05
    assert stats["parent"].seconds < stats["child"].seconds
    assert stats["parent"].seconds < 0.045


def test_otlp_trace_id_stable_and_derived_from_query_id():
    """The OTLP trace id is a pure function of the query id (hash scheme
    shared with the distributed task stamping), so repeated encodes of the
    same query land in the same trace."""
    from daft_tpu.observability.otlp import _span_id, _trace_id

    assert _trace_id("abc") == _trace_id("abc")
    assert _trace_id("abc") != _trace_id("abd")
    assert len(_trace_id("abc")) == 32
    assert _span_id("abc", "task", "t0") == _span_id("abc", "task", "t0")
    assert len(_span_id("abc", "task", "t0")) == 16


def test_explain_analyze_reports_operators():
    rng = np.random.default_rng(0)
    df = daft_tpu.from_pydict({
        "k": rng.choice(["a", "b", "c"], 10_000).tolist(),
        "v": rng.uniform(0, 1, 10_000).tolist(),
    })
    report = (df.where(col("v") > 0.5)
              .groupby("k").agg(col("v").sum().alias("s"))
              .sort("k")
              .explain_analyze())
    assert "== Physical Plan ==" in report
    assert "== Runtime Stats ==" in report
    assert "rows out" in report
    assert "PhysSort" in report or "Sort" in report
    # the final sort emits exactly 3 groups
    assert " 3 " in report or "3" in report


def test_dashboard_serves_query_history():
    import json
    import urllib.request

    import daft_tpu
    from daft_tpu import col
    from daft_tpu.observability.dashboard import launch

    dash = launch()
    try:
        daft_tpu.from_pydict({"a": list(range(10))}).where(col("a") > 4).to_pydict()
        with urllib.request.urlopen(dash.url + "/api/queries", timeout=5) as r:
            data = json.loads(r.read())
        assert data and data[0]["done"] and data[0]["rows"] == 5
        assert data[0]["operators"], "no operator stats recorded"
        with urllib.request.urlopen(dash.url + "/", timeout=5) as r:
            assert b"daft_tpu" in r.read()
    finally:
        dash.shutdown()


def test_event_log_writes_jsonl(tmp_path):
    import json as _json

    import daft_tpu
    from daft_tpu import col
    from daft_tpu.observability.event_log import disable_event_log, enable_event_log

    p = str(tmp_path / "events.jsonl")
    sub = enable_event_log(p)
    try:
        daft_tpu.from_pydict({"a": [1, 2, 3]}).where(col("a") > 1).to_pydict()
    finally:
        disable_event_log(sub)
    events = [_json.loads(l) for l in open(p)]
    kinds = [e["event"] for e in events]
    assert kinds[0] == "query_start" and kinds[-1] == "query_end"
    assert "operator_stats" in kinds
    assert events[-1]["rows"] == 2


def test_otlp_subscriber_exports_span_tree():
    """OTLP/HTTP JSON export: one root query span with optimize + operator
    children, asserted against a mock collector (reference:
    common/tracing/src/config.rs OTLP exporter)."""
    import json as _json
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    received = []

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            body = self.rfile.read(int(self.headers["Content-Length"]))
            received.append((self.path, _json.loads(body)))
            self.send_response(200)
            self.send_header("Content-Length", "2")
            self.end_headers()
            self.wfile.write(b"{}")

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        import daft_tpu
        from daft_tpu import col
        from daft_tpu.observability.otlp import OTLPSubscriber
        from daft_tpu.observability.subscribers import (attach_subscriber,
                                                        detach_subscriber)

        sub = OTLPSubscriber(f"http://127.0.0.1:{srv.server_address[1]}",
                             asynchronous=False)
        attach_subscriber(sub)
        try:
            df = daft_tpu.from_pydict({"a": list(range(100))})
            df.where(col("a") % 2 == 0).select((col("a") * 3).alias("b")).to_pydict()
        finally:
            detach_subscriber(sub)

        assert sub.exported == 1 and sub.last_error is None
        path, payload = received[0]
        assert path == "/v1/traces"
        rs = payload["resourceSpans"][0]
        svc = {a["key"]: a["value"] for a in rs["resource"]["attributes"]}
        assert svc["service.name"]["stringValue"] == "daft_tpu"
        spans = rs["scopeSpans"][0]["spans"]
        roots = [s for s in spans if "parentSpanId" not in s]
        assert len(roots) == 1 and roots[0]["name"] == "daft.query"
        root = roots[0]
        children = [s for s in spans if s.get("parentSpanId") == root["spanId"]]
        names = {s["name"] for s in children}
        assert "daft.optimize" in names
        assert any(n.startswith("daft.operator:") for n in names)
        assert all(s["traceId"] == root["traceId"] for s in spans)
        # timing sanity: children end within the root span
        assert all(int(s["endTimeUnixNano"]) <= int(root["endTimeUnixNano"]) + 10**9
                   for s in children)
    finally:
        srv.shutdown()


def test_dashboard_detail_and_engine_endpoints():
    """Per-query DAG detail (/api/query/{id}) and live engine counters
    (/api/engine) — the reference dashboard's live query-DAG surface
    (daft-dashboard/src/lib.rs)."""
    import json as _json
    import urllib.request

    import daft_tpu
    from daft_tpu import col
    from daft_tpu.observability.dashboard import launch

    dash = launch()
    try:
        df = daft_tpu.from_pydict({"a": list(range(50))})
        df.where(col("a") > 5).groupby(col("a") % 3).agg(
            col("a").sum().alias("s")).to_pydict()
        with urllib.request.urlopen(dash.url + "/api/queries", timeout=5) as r:
            queries = _json.loads(r.read())
        assert queries and queries[0]["done"]
        qid = queries[0]["query_id"]
        with urllib.request.urlopen(dash.url + f"/api/query/{qid}", timeout=5) as r:
            detail = _json.loads(r.read())
        assert detail["query_id"] == qid
        assert "physical_plan" in detail and detail["operators"]
        assert any(o["rows_out"] > 0 for o in detail["operators"])
        with urllib.request.urlopen(dash.url + "/api/engine", timeout=5) as r:
            eng = _json.loads(r.read())
        assert "device_join_batches" in eng
        with urllib.request.urlopen(dash.url + "/", timeout=5) as r:
            html = r.read().decode()
        assert "physical plan" in html and "/api/engine" in html
        with urllib.request.urlopen(dash.url + "/api/query/nope", timeout=5) as r:
            assert _json.loads(r.read())["error_404"] is True
    finally:
        dash.shutdown()
