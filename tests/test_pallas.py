"""Pallas TPU kernels: correctness in interpret mode (SURVEY.md §7).

(The build environment's tunneled device rejects Mosaic remote compilation,
so on-chip dispatch is validated on co-located TPU runtimes, not here.)"""

import numpy as np

from daft_tpu.ops.pallas_kernels import pallas_available, segment_sum_planes


def test_segment_sum_planes_matches_numpy():
    assert pallas_available()
    rng = np.random.default_rng(0)
    N, P, CAP = 8192, 6, 16
    planes = rng.standard_normal((N, P)).astype(np.float32)
    codes = rng.integers(0, CAP + 1, N).astype(np.int32)  # CAP = trash (dropped)
    out = np.asarray(segment_sum_planes(planes, codes, CAP, interpret=True))
    expect = np.zeros((CAP, P), np.float32)
    for g in range(CAP):
        expect[g] = planes[codes == g].sum(axis=0)
    np.testing.assert_allclose(out, expect, atol=1e-3)


def test_segment_sum_planes_empty_segments_and_single_block():
    planes = np.ones((1024, 2), np.float32)
    codes = np.zeros(1024, np.int32)  # everything in segment 0
    out = np.asarray(segment_sum_planes(planes, codes, 8, interpret=True))
    assert out[0, 0] == 1024.0
    assert (out[1:] == 0).all()
