// Example daft_tpu extension module (reference parity: the reference's
// daft-ext template cdylibs). Registers two scalar functions:
//
//   ext_double(x: float64|int64) -> same   — multiplies by 2
//   ext_add(x, y: float64) -> float64      — elementwise sum
//
// Build:
//   g++ -O2 -shared -fPIC -I../include example_ext.cpp -o libexample_ext.so
//
// Data crosses the boundary via the Arrow C Data Interface; this module
// allocates its own result buffers and hands them to the host with a release
// callback (the host — pyarrow — calls it when the array is dropped).

#include "../include/daft_tpu_ext.h"

#include <cstdlib>
#include <cstring>
#include <string>

namespace {

char* dup_cstr(const std::string& s) {
  char* out = (char*)std::malloc(s.size() + 1);
  std::memcpy(out, s.c_str(), s.size() + 1);
  return out;
}

// ---- minimal Arrow C struct builders ------------------------------------------

struct OwnedArray {
  void* validity;
  void* data;
  const void* buffers[2];
};

void release_array(struct ArrowArray* a) {
  if (!a || !a->release) return;
  OwnedArray* o = (OwnedArray*)a->private_data;
  if (o) {
    std::free(o->validity);
    std::free(o->data);
    delete o;
  }
  a->release = nullptr;
}

void release_schema(struct ArrowSchema* s) {
  if (!s || !s->release) return;
  std::free((void*)s->format);
  std::free((void*)s->name);
  s->release = nullptr;
}

void make_schema(struct ArrowSchema* out, const char* format, const char* name) {
  std::memset(out, 0, sizeof(*out));
  out->format = dup_cstr(format);
  out->name = dup_cstr(name ? name : "");
  out->flags = ARROW_FLAG_NULLABLE;
  out->release = release_schema;
}

// primitive array with optional validity bitmap (both module-allocated)
void make_array(struct ArrowArray* out, int64_t length, int64_t null_count,
                void* validity, void* data) {
  std::memset(out, 0, sizeof(*out));
  OwnedArray* o = new OwnedArray();
  o->validity = validity;
  o->data = data;
  o->buffers[0] = validity;
  o->buffers[1] = data;
  out->length = length;
  out->null_count = null_count;
  out->n_buffers = 2;
  out->buffers = o->buffers;
  out->private_data = o;
  out->release = release_array;
}

bool fmt_is(const struct ArrowSchema* s, const char* f) {
  return s->format && std::strcmp(s->format, f) == 0;
}

void* copy_validity(const struct ArrowArray* a) {
  if (!a->buffers || !a->buffers[0]) return nullptr;
  size_t nbytes = (size_t)((a->length + a->offset + 7) / 8);
  void* out = std::malloc(nbytes);
  std::memcpy(out, a->buffers[0], nbytes);
  return out;
}

// ---- ext_double ----------------------------------------------------------------

const char* double_name(const void*) { return "ext_double"; }

int double_ret_field(const void*, const struct ArrowSchema* args, size_t argc,
                     struct ArrowSchema* ret, char** errmsg) {
  if (argc != 1 || !(fmt_is(&args[0], "g") || fmt_is(&args[0], "l"))) {
    *errmsg = dup_cstr("ext_double expects one float64 or int64 argument");
    return 1;
  }
  make_schema(ret, args[0].format, "ext_double");
  return 0;
}

int double_call(const void*, const struct ArrowArray* args,
                const struct ArrowSchema* schemas, size_t argc,
                struct ArrowArray* ret_array, struct ArrowSchema* ret_schema,
                char** errmsg) {
  if (argc != 1) {
    *errmsg = dup_cstr("ext_double expects one argument");
    return 1;
  }
  const struct ArrowArray* a = &args[0];
  const int64_t n = a->length;
  const bool is_float = fmt_is(&schemas[0], "g");
  void* data = std::malloc((size_t)n * 8);
  if (is_float) {
    const double* in = (const double*)a->buffers[1] + a->offset;
    double* out = (double*)data;
    for (int64_t i = 0; i < n; i++) out[i] = in[i] * 2.0;
  } else {
    const int64_t* in = (const int64_t*)a->buffers[1] + a->offset;
    int64_t* out = (int64_t*)data;
    for (int64_t i = 0; i < n; i++) out[i] = in[i] * 2;
  }
  // validity: reuse input bitmap (copied; offsets folded by re-reading bits)
  void* validity = nullptr;
  int64_t null_count = a->null_count;
  if (a->buffers && a->buffers[0]) {
    const uint8_t* vin = (const uint8_t*)a->buffers[0];
    uint8_t* vout = (uint8_t*)std::malloc((size_t)((n + 7) / 8));
    std::memset(vout, 0, (size_t)((n + 7) / 8));
    for (int64_t i = 0; i < n; i++) {
      int64_t j = i + a->offset;
      if (vin[j >> 3] & (1 << (j & 7))) vout[i >> 3] |= (1 << (i & 7));
    }
    validity = vout;
  }
  make_array(ret_array, n, null_count, validity, data);
  make_schema(ret_schema, schemas[0].format, "ext_double");
  return 0;
}

void noop_fini(void*) {}

// ---- ext_add -------------------------------------------------------------------

const char* add_name(const void*) { return "ext_add"; }

int add_ret_field(const void*, const struct ArrowSchema* args, size_t argc,
                  struct ArrowSchema* ret, char** errmsg) {
  if (argc != 2 || !fmt_is(&args[0], "g") || !fmt_is(&args[1], "g")) {
    *errmsg = dup_cstr("ext_add expects two float64 arguments");
    return 1;
  }
  make_schema(ret, "g", "ext_add");
  return 0;
}

int add_call(const void*, const struct ArrowArray* args,
             const struct ArrowSchema* schemas, size_t argc,
             struct ArrowArray* ret_array, struct ArrowSchema* ret_schema,
             char** errmsg) {
  if (argc != 2 || args[0].length != args[1].length) {
    *errmsg = dup_cstr("ext_add expects two equal-length float64 arrays");
    return 1;
  }
  const int64_t n = args[0].length;
  const size_t nbytes_bitmap = (size_t)((n > 0 ? n + 7 : 8) / 8);
  const double* x = (const double*)args[0].buffers[1] + args[0].offset;
  const double* y = (const double*)args[1].buffers[1] + args[1].offset;
  double* out = (double*)std::malloc((size_t)(n > 0 ? n : 1) * 8);
  for (int64_t i = 0; i < n; i++) out[i] = x[i] + y[i];
  // null if either input is null: AND the bitmaps
  void* validity = nullptr;
  int64_t null_count = 0;
  if ((args[0].buffers && args[0].buffers[0]) || (args[1].buffers && args[1].buffers[0])) {
    uint8_t* vout = (uint8_t*)std::malloc(nbytes_bitmap);
    std::memset(vout, 0xFF, nbytes_bitmap);
    for (int64_t i = 0; i < n; i++) {
      bool ok = true;
      for (int k = 0; k < 2; k++) {
        const struct ArrowArray* a = &args[k];
        if (a->buffers && a->buffers[0]) {
          int64_t j = i + a->offset;
          const uint8_t* v = (const uint8_t*)a->buffers[0];
          if (!(v[j >> 3] & (1 << (j & 7)))) ok = false;
        }
      }
      if (!ok) {
        vout[i >> 3] &= ~(1 << (i & 7));
        null_count++;
      }
    }
    validity = vout;
  }
  make_array(ret_array, n, null_count, validity, out);
  make_schema(ret_schema, "g", "ext_add");
  return 0;
}

// ---- module entry --------------------------------------------------------------

int module_init(DaftTpuSessionContext* session) {
  DaftTpuScalarFunction f1 = {nullptr, double_name, double_ret_field, double_call,
                              noop_fini};
  if (session->define_function(session->ctx, f1) != 0) return 1;
  DaftTpuScalarFunction f2 = {nullptr, add_name, add_ret_field, add_call, noop_fini};
  if (session->define_function(session->ctx, f2) != 0) return 1;
  return 0;
}

void module_free_string(char* s) { std::free(s); }

}  // namespace

extern "C" DaftTpuModule daft_tpu_module_magic(void) {
  DaftTpuModule m;
  m.abi_version = DAFT_TPU_ABI_VERSION;
  m.name = "example_ext";
  m.init = module_init;
  m.free_string = module_free_string;
  return m;
}
