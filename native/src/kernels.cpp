// daft_tpu native host kernels.
//
// Reference parity: the hot inner loops of src/daft-core (Rust vectorized
// kernels), src/daft-groupby (group index construction) and
// src/daft-recordbatch/src/probeable (hash-join probe tables) — implemented as a
// C ABI shared library loaded via ctypes (the engine's Python layer passes raw
// numpy buffers). All kernels are single-pass O(n) and allocation-light.
//
// Build: cmake -S native -B native/build && cmake --build native/build
// (or: g++ -O3 -march=native -shared -fPIC -o libdaft_native.so kernels.cpp)

#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------------
// xxhash64 (public domain algorithm, fresh implementation)
// ---------------------------------------------------------------------------------

static const uint64_t P1 = 0x9E3779B185EBCA87ULL;
static const uint64_t P2 = 0xC2B2AE3D27D4EB4FULL;
static const uint64_t P3 = 0x165667B19E3779F9ULL;
static const uint64_t P4 = 0x85EBCA77C2B2AE63ULL;
static const uint64_t P5 = 0x27D4EB2F165667C5ULL;

static inline uint64_t rotl(uint64_t x, int r) { return (x << r) | (x >> (64 - r)); }

static inline uint64_t round1(uint64_t acc, uint64_t input) {
  acc += input * P2;
  acc = rotl(acc, 31);
  acc *= P1;
  return acc;
}

static inline uint64_t merge_round(uint64_t acc, uint64_t val) {
  val = round1(0, val);
  acc ^= val;
  acc = acc * P1 + P4;
  return acc;
}

uint64_t xxhash64(const uint8_t* data, uint64_t len, uint64_t seed) {
  uint64_t h;
  const uint8_t* p = data;
  const uint8_t* end = data + len;
  if (len >= 32) {
    uint64_t v1 = seed + P1 + P2, v2 = seed + P2, v3 = seed, v4 = seed - P1;
    do {
      uint64_t k;
      memcpy(&k, p, 8); v1 = round1(v1, k); p += 8;
      memcpy(&k, p, 8); v2 = round1(v2, k); p += 8;
      memcpy(&k, p, 8); v3 = round1(v3, k); p += 8;
      memcpy(&k, p, 8); v4 = round1(v4, k); p += 8;
    } while (p + 32 <= end);
    h = rotl(v1, 1) + rotl(v2, 7) + rotl(v3, 12) + rotl(v4, 18);
    h = merge_round(h, v1); h = merge_round(h, v2);
    h = merge_round(h, v3); h = merge_round(h, v4);
  } else {
    h = seed + P5;
  }
  h += len;
  while (p + 8 <= end) {
    uint64_t k; memcpy(&k, p, 8);
    h ^= round1(0, k);
    h = rotl(h, 27) * P1 + P4;
    p += 8;
  }
  if (p + 4 <= end) {
    uint32_t k; memcpy(&k, p, 4);
    h ^= (uint64_t)k * P1;
    h = rotl(h, 23) * P2 + P3;
    p += 4;
  }
  while (p < end) {
    h ^= (*p) * P5;
    h = rotl(h, 11) * P1;
    p++;
  }
  h ^= h >> 33; h *= P2; h ^= h >> 29; h *= P3; h ^= h >> 32;
  return h;
}

// hash a binary column given arrow offsets (int64) + data buffer
void hash_binary_column(const uint8_t* data, const int64_t* offsets, int64_t n,
                        uint64_t seed, uint64_t* out) {
  for (int64_t i = 0; i < n; i++) {
    out[i] = xxhash64(data + offsets[i], (uint64_t)(offsets[i + 1] - offsets[i]), seed);
  }
}

void hash_u64_column(const uint64_t* vals, int64_t n, uint64_t seed, uint64_t* out) {
  for (int64_t i = 0; i < n; i++) {
    uint64_t v = vals[i];
    out[i] = xxhash64((const uint8_t*)&v, 8, seed);
  }
}

// ---------------------------------------------------------------------------------
// factorize: int64 keys -> dense first-occurrence codes (open addressing)
// ---------------------------------------------------------------------------------

int64_t factorize_i64(const int64_t* keys, int64_t n, int64_t* out_codes) {
  if (n == 0) return 0;
  // table size: next pow2 >= 2n
  uint64_t cap = 16;
  while (cap < (uint64_t)(n * 2)) cap <<= 1;
  const uint64_t mask = cap - 1;
  std::vector<int64_t> slot_key(cap);
  std::vector<int64_t> slot_code(cap, -1);  // -1 = empty
  int64_t next_code = 0;
  for (int64_t i = 0; i < n; i++) {
    const int64_t k = keys[i];
    uint64_t h = (uint64_t)k;
    // splitmix64 finalizer as the hash
    h ^= h >> 30; h *= 0xBF58476D1CE4E5B9ULL;
    h ^= h >> 27; h *= 0x94D049BB133111EBULL;
    h ^= h >> 31;
    uint64_t s = h & mask;
    for (;;) {
      int64_t c = slot_code[s];
      if (c == -1) {
        slot_key[s] = k;
        slot_code[s] = next_code;
        out_codes[i] = next_code;
        next_code++;
        break;
      }
      if (slot_key[s] == k) {
        out_codes[i] = c;
        break;
      }
      s = (s + 1) & mask;
    }
  }
  return next_code;
}

// combine two compact code columns into pair codes, then factorize:
// out = factorize(a * (max_b + 2) + b) without materializing the pair array twice
int64_t combine_factorize_i64(const int64_t* a, const int64_t* b, int64_t n,
                              int64_t b_domain, int64_t* out_codes) {
  std::vector<int64_t> pair(n);
  const int64_t g = b_domain + 2;
  for (int64_t i = 0; i < n; i++) pair[i] = (a[i] + 1) * g + (b[i] + 1);
  return factorize_i64(pair.data(), n, out_codes);
}

// ---------------------------------------------------------------------------------
// grouped aggregation: single-pass scatter over group ids
// ---------------------------------------------------------------------------------

void grouped_sum_f64(const int64_t* gids, const double* vals, const uint8_t* valid,
                     int64_t n, int64_t num_groups, double* out_sum, int64_t* out_cnt) {
  memset(out_sum, 0, sizeof(double) * num_groups);
  memset(out_cnt, 0, sizeof(int64_t) * num_groups);
  for (int64_t i = 0; i < n; i++) {
    if (valid[i]) {
      out_sum[gids[i]] += vals[i];
      out_cnt[gids[i]]++;
    }
  }
}

void grouped_sum_i64(const int64_t* gids, const int64_t* vals, const uint8_t* valid,
                     int64_t n, int64_t num_groups, int64_t* out_sum, int64_t* out_cnt) {
  memset(out_sum, 0, sizeof(int64_t) * num_groups);
  memset(out_cnt, 0, sizeof(int64_t) * num_groups);
  for (int64_t i = 0; i < n; i++) {
    if (valid[i]) {
      out_sum[gids[i]] += vals[i];
      out_cnt[gids[i]]++;
    }
  }
}

void grouped_minmax_f64(const int64_t* gids, const double* vals, const uint8_t* valid,
                        int64_t n, int64_t num_groups, double* out_min, double* out_max) {
  for (int64_t g = 0; g < num_groups; g++) {
    out_min[g] = 1.0 / 0.0;   // +inf
    out_max[g] = -1.0 / 0.0;  // -inf
  }
  for (int64_t i = 0; i < n; i++) {
    if (valid[i]) {
      const int64_t g = gids[i];
      const double v = vals[i];
      if (v < out_min[g]) out_min[g] = v;
      if (v > out_max[g]) out_max[g] = v;
    }
  }
}

void grouped_minmax_i64(const int64_t* gids, const int64_t* vals, const uint8_t* valid,
                        int64_t n, int64_t num_groups, int64_t* out_min, int64_t* out_max) {
  for (int64_t g = 0; g < num_groups; g++) {
    out_min[g] = INT64_MAX;
    out_max[g] = INT64_MIN;
  }
  for (int64_t i = 0; i < n; i++) {
    if (valid[i]) {
      const int64_t g = gids[i];
      const int64_t v = vals[i];
      if (v < out_min[g]) out_min[g] = v;
      if (v > out_max[g]) out_max[g] = v;
    }
  }
}

// ---------------------------------------------------------------------------------
// bucket join on compact codes (codes in [0, G); negatives never match)
// ---------------------------------------------------------------------------------

// Phase 1: returns total number of matched pairs and fills per-left counts.
int64_t join_count(const int64_t* lcodes, int64_t nl, const int64_t* rcodes, int64_t nr,
                   int64_t num_codes, int64_t* bucket_counts /* size num_codes */,
                   int64_t* l_match_counts /* size nl */) {
  memset(bucket_counts, 0, sizeof(int64_t) * num_codes);
  for (int64_t j = 0; j < nr; j++) {
    if (rcodes[j] >= 0) bucket_counts[rcodes[j]]++;
  }
  int64_t total = 0;
  for (int64_t i = 0; i < nl; i++) {
    const int64_t c = lcodes[i];
    const int64_t m = (c >= 0 && c < num_codes) ? bucket_counts[c] : 0;
    l_match_counts[i] = m;
    total += m;
  }
  return total;
}

// Phase 2: fill matched index pairs. bucket_offsets = exclusive prefix of counts.
void join_fill(const int64_t* lcodes, int64_t nl, const int64_t* rcodes, int64_t nr,
               int64_t num_codes, const int64_t* bucket_offsets,
               int64_t* bucket_rows /* size nr */, int64_t* out_l, int64_t* out_r) {
  // scatter right rows into buckets (stable)
  std::vector<int64_t> cursor(bucket_offsets, bucket_offsets + num_codes);
  for (int64_t j = 0; j < nr; j++) {
    if (rcodes[j] >= 0) bucket_rows[cursor[rcodes[j]]++] = j;
  }
  int64_t out = 0;
  for (int64_t i = 0; i < nl; i++) {
    const int64_t c = lcodes[i];
    if (c < 0 || c >= num_codes) continue;
    const int64_t s = bucket_offsets[c];
    const int64_t e = cursor[c];
    for (int64_t j = s; j < e; j++) {
      out_l[out] = i;
      out_r[out] = bucket_rows[j];
      out++;
    }
  }
}

// ---------------------------------------------------------------------------------
// probe-table lookups: buckets prebuilt ONCE (ProbeTable), probed per morsel
// ---------------------------------------------------------------------------------

// Count matches per left row against prebuilt bucket counts. Returns total.
int64_t probe_count(const int64_t* lcodes, int64_t nl, int64_t num_codes,
                    const int64_t* bucket_counts, int64_t* l_match_counts) {
  int64_t total = 0;
  for (int64_t i = 0; i < nl; i++) {
    const int64_t c = lcodes[i];
    const int64_t m = (c >= 0 && c < num_codes) ? bucket_counts[c] : 0;
    l_match_counts[i] = m;
    total += m;
  }
  return total;
}

// ---------------------------------------------------------------------------------
// open-addressing int64 -> int64 map (power-of-2 capacity, linear probing):
// sparse-domain join-key dictionaries where dense subtraction doesn't apply
// ---------------------------------------------------------------------------------

static inline uint64_t mix64(uint64_t x) {
  x ^= x >> 33; x *= 0xff51afd7ed558ccdULL;
  x ^= x >> 33; x *= 0xc4ceb9fe1a85ec53ULL;
  x ^= x >> 33;
  return x;
}

// keys must be unique; slot_vals pre-filled with -1 (empty marker).
void i64_map_build(const int64_t* keys, int64_t n, int64_t cap,
                   int64_t* slot_keys, int64_t* slot_vals) {
  const uint64_t mask = (uint64_t)cap - 1;
  for (int64_t i = 0; i < n; i++) {
    uint64_t h = mix64((uint64_t)keys[i]) & mask;
    while (slot_vals[h] != -1) h = (h + 1) & mask;
    slot_keys[h] = keys[i];
    slot_vals[h] = i;
  }
}

void i64_map_lookup(const int64_t* slot_keys, const int64_t* slot_vals, int64_t cap,
                    const int64_t* vals, int64_t n, int64_t* out) {
  const uint64_t mask = (uint64_t)cap - 1;
  for (int64_t i = 0; i < n; i++) {
    uint64_t h = mix64((uint64_t)vals[i]) & mask;
    int64_t r = -1;
    while (slot_vals[h] != -1) {
      if (slot_keys[h] == vals[i]) { r = slot_vals[h]; break; }
      h = (h + 1) & mask;
    }
    out[i] = r;
  }
}

// Arrow boolean bitmap -> selection vector in one word-wise pass (replaces
// the Python fill_null -> to_numpy(bytes) -> flatnonzero chain, which
// materializes a byte mask and scans twice). Emits row indices where
// value bit is set AND validity bit (if present) is set; returns the count.
int64_t bool_mask_indices(const uint8_t* bits, const uint8_t* validity,
                          int64_t offset, int64_t n, int64_t* out) {
  int64_t m = 0;
  int64_t i = 0;
  // head: unaligned bits until offset+i is a multiple of 64
  while (i < n && ((offset + i) & 63) != 0) {
    const int64_t j = offset + i;
    bool v = bits[j >> 3] & (1u << (j & 7));
    if (v && validity) v = validity[j >> 3] & (1u << (j & 7));
    if (v) out[m++] = i;
    i++;
  }
  // body: 64 rows per iteration, iterate set bits only
  while (i + 64 <= n) {
    const int64_t w = (offset + i) >> 6;
    uint64_t word;
    memcpy(&word, ((const uint64_t*)bits) + w, 8);
    if (validity) {
      uint64_t vw;
      memcpy(&vw, ((const uint64_t*)validity) + w, 8);
      word &= vw;
    }
    while (word) {
      out[m++] = i + __builtin_ctzll(word);
      word &= word - 1;
    }
    i += 64;
  }
  // tail
  while (i < n) {
    const int64_t j = offset + i;
    bool v = bits[j >> 3] & (1u << (j & 7));
    if (v && validity) v = validity[j >> 3] & (1u << (j & 7));
    if (v) out[m++] = i;
    i++;
  }
  return m;
}

// Interleaved (key,val) pair layout: one cache line serves both the key check
// and the value read, halving the random accesses per probe vs the split
// slot_keys/slot_vals arrays above. slots[2h] = key, slots[2h+1] = val
// (-1 = empty). keys must be unique; slots pre-filled with val = -1.
void i64_pairmap_build(const int64_t* keys, int64_t n, int64_t cap, int64_t* slots) {
  const uint64_t mask = (uint64_t)cap - 1;
  for (int64_t i = 0; i < n; i++) {
    uint64_t h = mix64((uint64_t)keys[i]) & mask;
    while (slots[2 * h + 1] != -1) h = (h + 1) & mask;
    slots[2 * h] = keys[i];
    slots[2 * h + 1] = i;
  }
}

void i64_pairmap_lookup(const int64_t* slots, int64_t cap,
                        const int64_t* vals, int64_t n, int64_t* out) {
  const uint64_t mask = (uint64_t)cap - 1;
  const int64_t D = 24;
  for (int64_t i = 0; i < n; i++) {
    if (i + D < n)
      __builtin_prefetch(&slots[2 * (mix64((uint64_t)vals[i + D]) & mask)], 0, 1);
    uint64_t h = mix64((uint64_t)vals[i]) & mask;
    int64_t r = -1;
    while (slots[2 * h + 1] != -1) {
      if (slots[2 * h] == vals[i]) { r = slots[2 * h + 1]; break; }
      h = (h + 1) & mask;
    }
    out[i] = r;
  }
}

// Fused pairmap lookup + match count (pair-layout variant of
// probe_lookup_count_hash).
int64_t probe_lookup_count_pair(const int64_t* vals, const uint8_t* valid,
                                int64_t n, const int64_t* slots, int64_t cap,
                                const int64_t* bucket_counts, int64_t num_codes,
                                int64_t* codes_out, int64_t* l_match) {
  const uint64_t mask = (uint64_t)cap - 1;
  const int64_t D = 24;
  int64_t total = 0;
  for (int64_t i = 0; i < n; i++) {
    if (i + D < n && (!valid || valid[i + D]))
      __builtin_prefetch(&slots[2 * (mix64((uint64_t)vals[i + D]) & mask)], 0, 1);
    int64_t code = -1;
    if (!valid || valid[i]) {
      const int64_t v = vals[i];
      uint64_t h = mix64((uint64_t)v) & mask;
      while (slots[2 * h + 1] != -1) {
        if (slots[2 * h] == v) { code = slots[2 * h + 1]; break; }
        h = (h + 1) & mask;
      }
    }
    codes_out[i] = code;
    const int64_t m = (code >= 0 && code < num_codes) ? bucket_counts[code] : 0;
    l_match[i] = m;
    total += m;
  }
  return total;
}

// Emit matched pairs from prebuilt buckets (left-major; build rows in
// original order within a key — bucket_rows is stable-sorted by code).
void probe_fill(const int64_t* lcodes, int64_t nl, int64_t num_codes,
                const int64_t* bucket_offsets, const int64_t* bucket_counts,
                const int64_t* bucket_rows, int64_t* out_l, int64_t* out_r) {
  const int64_t D = 24;
  int64_t out = 0;
  for (int64_t i = 0; i < nl; i++) {
    if (i + D < nl) {
      const int64_t cp = lcodes[i + D];
      if (cp >= 0 && cp < num_codes) {
        __builtin_prefetch(&bucket_offsets[cp], 0, 1);
        __builtin_prefetch(&bucket_counts[cp], 0, 1);
      }
    }
    const int64_t c = lcodes[i];
    if (c < 0 || c >= num_codes) continue;
    const int64_t s = bucket_offsets[c];
    const int64_t e = s + bucket_counts[c];
    for (int64_t j = s; j < e; j++) {
      out_l[out] = i;
      out_r[out] = bucket_rows[j];
      out++;
    }
  }
}

// Fused single-int64-key probe lookups: map probe values straight to build
// joint codes AND count matches in ONE pass, instead of the Python chain of
// lookup -> -1/-2 fixup writes -> probe_count (each a full O(n) sweep).
// valid may be null (all rows valid); invalid rows never match. Returns total
// match count; codes_out feeds probe_fill.
int64_t probe_lookup_count_hash(const int64_t* vals, const uint8_t* valid,
                                int64_t n, const int64_t* slot_keys,
                                const int64_t* slot_vals, int64_t cap,
                                const int64_t* bucket_counts, int64_t num_codes,
                                int64_t* codes_out, int64_t* l_match) {
  const uint64_t mask = (uint64_t)cap - 1;
  const int64_t D = 24;  // prefetch distance: probes are DRAM-latency-bound
                         // once the slot table outgrows LLC (~40ns/lookup
                         // measured); prefetching ahead overlaps the misses
  int64_t total = 0;
  for (int64_t i = 0; i < n; i++) {
    if (i + D < n && (!valid || valid[i + D])) {
      const uint64_t hp = mix64((uint64_t)vals[i + D]) & mask;
      __builtin_prefetch(&slot_keys[hp], 0, 1);
      __builtin_prefetch(&slot_vals[hp], 0, 1);
    }
    int64_t code = -1;
    if (!valid || valid[i]) {
      const int64_t v = vals[i];
      uint64_t h = mix64((uint64_t)v) & mask;
      while (slot_vals[h] != -1) {
        if (slot_keys[h] == v) { code = slot_vals[h]; break; }
        h = (h + 1) & mask;
      }
    }
    codes_out[i] = code;
    const int64_t m = (code >= 0 && code < num_codes) ? bucket_counts[code] : 0;
    l_match[i] = m;
    total += m;
  }
  return total;
}

// Same fusion for dense-domain keys (code = value - lo).
int64_t probe_lookup_count_dense(const int64_t* vals, const uint8_t* valid,
                                 int64_t n, int64_t lo, int64_t hi,
                                 const int64_t* bucket_counts, int64_t num_codes,
                                 int64_t* codes_out, int64_t* l_match) {
  const int64_t D = 24;
  int64_t total = 0;
  for (int64_t i = 0; i < n; i++) {
    if (i + D < n) {
      const int64_t vp = vals[i + D];
      if (vp >= lo && vp <= hi) __builtin_prefetch(&bucket_counts[vp - lo], 0, 1);
    }
    int64_t code = -1;
    if ((!valid || valid[i]) && vals[i] >= lo && vals[i] <= hi) code = vals[i] - lo;
    codes_out[i] = code;
    const int64_t m = (code >= 0 && code < num_codes) ? bucket_counts[code] : 0;
    l_match[i] = m;
    total += m;
  }
  return total;
}

// One-pass bucket build for ProbeTable: per-code counts + exclusive prefix
// offsets. codes < 0 (null / unmatchable) are skipped. Replaces the Python
// np.bincount + np.cumsum pair, which allocates and scans the full code
// domain twice for dense join keys.
int64_t bucket_build(const int64_t* codes, int64_t n, int64_t num_codes,
                     int64_t* counts /* size num_codes */,
                     int64_t* offsets /* size num_codes */) {
  memset(counts, 0, sizeof(int64_t) * num_codes);
  for (int64_t i = 0; i < n; i++) {
    if (codes[i] >= 0) counts[codes[i]]++;
  }
  int64_t acc = 0, mx = 0;
  for (int64_t g = 0; g < num_codes; g++) {
    offsets[g] = acc;
    acc += counts[g];
    if (counts[g] > mx) mx = counts[g];
  }
  return mx;  // max bucket size: 1 => unique build keys => direct-lookup joins
}

// Unique-build-key probe: ONE random access per probe row. slots is a
// (key, build_row) pairmap over ALL valid build rows (legal only when keys
// are unique — bucket_build reported max count 1). Writes the full per-row
// build-row array (-1 = no match) AND the compacted matched (l, r) pairs in
// the same pass; returns the match count. This replaces the general
// lookup -> counts -> offsets -> bucket_rows chain (3-4 dependent random
// accesses per row) for the dimension-join shape where keys are unique.
int64_t probe_unique_pair(const int64_t* vals, const uint8_t* valid, int64_t n,
                          const int64_t* slots, int64_t cap,
                          int64_t* ridx_full, int64_t* out_l, int64_t* out_r) {
  const uint64_t mask = (uint64_t)cap - 1;
  const int64_t D = 24;
  int64_t m = 0;
  for (int64_t i = 0; i < n; i++) {
    if (i + D < n && (!valid || valid[i + D]))
      __builtin_prefetch(&slots[2 * (mix64((uint64_t)vals[i + D]) & mask)], 0, 1);
    int64_t r = -1;
    if (!valid || valid[i]) {
      const int64_t v = vals[i];
      uint64_t h = mix64((uint64_t)v) & mask;
      while (slots[2 * h + 1] != -1) {
        if (slots[2 * h] == v) { r = slots[2 * h + 1]; break; }
        h = (h + 1) & mask;
      }
    }
    ridx_full[i] = r;
    if (r >= 0) {
      out_l[m] = i;
      out_r[m] = r;
      m++;
    }
  }
  return m;
}

// Dense-domain variant: row_of_code[v - lo] is the build row (-1 = absent).
int64_t probe_unique_dense(const int64_t* vals, const uint8_t* valid, int64_t n,
                           int64_t lo, int64_t hi, const int64_t* row_of_code,
                           int64_t* ridx_full, int64_t* out_l, int64_t* out_r) {
  const int64_t D = 24;
  int64_t m = 0;
  for (int64_t i = 0; i < n; i++) {
    if (i + D < n) {
      const int64_t vp = vals[i + D];
      if (vp >= lo && vp <= hi) __builtin_prefetch(&row_of_code[vp - lo], 0, 1);
    }
    int64_t r = -1;
    if ((!valid || valid[i]) && vals[i] >= lo && vals[i] <= hi)
      r = row_of_code[vals[i] - lo];
    ridx_full[i] = r;
    if (r >= 0) {
      out_l[m] = i;
      out_r[m] = r;
      m++;
    }
  }
  return m;
}

// Stable counting-sort scatter of build rows into their buckets — O(n + G),
// replaces the O(n log n) np.argsort in ProbeTable._ensure_bucket_rows.
void bucket_scatter(const int64_t* codes, int64_t n, int64_t num_codes,
                    const int64_t* offsets, int64_t* rows /* size sum(counts) */) {
  std::vector<int64_t> cursor(offsets, offsets + num_codes);
  for (int64_t i = 0; i < n; i++) {
    if (codes[i] >= 0) rows[cursor[codes[i]]++] = i;
  }
}

}  // extern "C"
