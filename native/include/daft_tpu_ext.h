/* daft_tpu stable extension ABI (version 1).
 *
 * Reference parity: src/daft-ext/src/abi/mod.rs — the reference defines a
 * repr(C) contract (FFI_Module / FFI_ScalarFunction / FFI_SessionContext)
 * that extension cdylibs implement; functions exchange data through the
 * Arrow C Data Interface. This header is the same contract expressed as a
 * plain C header: a module shared library exports
 *
 *     DaftTpuModule daft_tpu_module_magic(void);
 *
 * and the host (daft_tpu/ext.py) loads it, checks the ABI version, calls
 * init() with a session vtable, and registers every function the module
 * defines into the engine's scalar-function registry. All array data crosses
 * the boundary as Arrow C Data Interface structs — zero copies, zero
 * dependencies on the host's internals.
 */

#ifndef DAFT_TPU_EXT_H
#define DAFT_TPU_EXT_H

#include <stdint.h>
#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

#define DAFT_TPU_ABI_VERSION 1
#define DAFT_TPU_MODULE_MAGIC_SYMBOL "daft_tpu_module_magic"

/* ---- Arrow C Data Interface (standard definition) --------------------------- */

#ifndef ARROW_C_DATA_INTERFACE
#define ARROW_C_DATA_INTERFACE

#define ARROW_FLAG_DICTIONARY_ORDERED 1
#define ARROW_FLAG_NULLABLE 2
#define ARROW_FLAG_MAP_KEYS_SORTED 4

struct ArrowSchema {
  const char* format;
  const char* name;
  const char* metadata;
  int64_t flags;
  int64_t n_children;
  struct ArrowSchema** children;
  struct ArrowSchema* dictionary;
  void (*release)(struct ArrowSchema*);
  void* private_data;
};

struct ArrowArray {
  int64_t length;
  int64_t null_count;
  int64_t offset;
  int64_t n_buffers;
  int64_t n_children;
  const void** buffers;
  struct ArrowArray** children;
  struct ArrowArray* dictionary;
  void (*release)(struct ArrowArray*);
  void* private_data;
};

#endif /* ARROW_C_DATA_INTERFACE */

/* ---- scalar function vtable ------------------------------------------------- */

/* The host calls through these pointers; ctx is module-owned and opaque.
 * Error contract: non-zero return + *errmsg set to a message the host frees
 * via DaftTpuModule.free_string. */
typedef struct DaftTpuScalarFunction {
  const void* ctx;

  /* Null-terminated UTF-8 function name; borrows from ctx, valid until fini. */
  const char* (*name)(const void* ctx);

  /* Output field for the given input fields (Arrow C schemas). */
  int (*get_return_field)(const void* ctx, const struct ArrowSchema* args,
                          size_t args_count, struct ArrowSchema* ret,
                          char** errmsg);

  /* Evaluate on Arrow arrays; writes the result array + schema. */
  int (*call)(const void* ctx, const struct ArrowArray* args,
              const struct ArrowSchema* args_schemas, size_t args_count,
              struct ArrowArray* ret_array, struct ArrowSchema* ret_schema,
              char** errmsg);

  /* Free all module-side resources for this function. */
  void (*fini)(void* ctx);
} DaftTpuScalarFunction;

/* ---- host session ----------------------------------------------------------- */

typedef struct DaftTpuSessionContext {
  void* ctx; /* host-owned, opaque */

  /* Register a function; the host takes ownership of the vtable on success. */
  int (*define_function)(void* ctx, DaftTpuScalarFunction function);
} DaftTpuSessionContext;

/* ---- module entry ----------------------------------------------------------- */

typedef struct DaftTpuModule {
  uint32_t abi_version; /* must equal DAFT_TPU_ABI_VERSION */
  const char* name;     /* static, null-terminated */
  int (*init)(DaftTpuSessionContext* session);
  void (*free_string)(char* s);
} DaftTpuModule;

/* Every module exports: DaftTpuModule daft_tpu_module_magic(void); */

#ifdef __cplusplus
}
#endif

#endif /* DAFT_TPU_EXT_H */
