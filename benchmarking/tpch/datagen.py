"""TPC-H data generation (synthetic, dbgen-free).

Reference parity: benchmarking/tpch/ (which shells out to dbgen). Here tables are
synthesized with deterministic numpy RNG following the public TPC-H schema and
value domains (row counts scale with SF: lineitem ~= 6M * SF). Not bit-identical
to dbgen output, but schema- and distribution-faithful enough for correctness
cross-checks (vs pandas) and throughput benchmarks. String columns are built
with vectorized pyarrow kernels (dictionary decode + element-wise join) so SF1
generates in seconds, not minutes.
"""

from __future__ import annotations

import datetime
import os
from typing import Dict, Sequence

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

EPOCH = datetime.date(1970, 1, 1)
D_1992 = (datetime.date(1992, 1, 1) - EPOCH).days
D_1998 = (datetime.date(1998, 12, 1) - EPOCH).days

REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1), ("EGYPT", 4),
    ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3), ("INDIA", 2), ("INDONESIA", 2),
    ("IRAN", 4), ("IRAQ", 4), ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0),
    ("MOROCCO", 0), ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3), ("UNITED KINGDOM", 3),
    ("UNITED STATES", 1),
]
SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
SHIPMODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
INSTRUCTIONS = ["DELIVER IN PERSON", "COLLECT COD", "NONE", "TAKE BACK RETURN"]
TYPES_P1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
TYPES_P2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
TYPES_P3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
COLORS = ["green", "blue", "red", "ivory", "forest", "lime", "navy"]
CONTAINERS_P1 = ["SM", "LG", "MED", "JUMBO", "WRAP"]
CONTAINERS_P2 = ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]


def _dates(rng, n, lo=D_1992, hi=D_1998):
    return rng.integers(lo, hi, n).astype("int32")


def _pick(rng, choices: Sequence[str], n: int, p=None) -> pa.Array:
    """Vectorized random choice: int codes + dictionary decode."""
    if p is None:
        codes = rng.integers(0, len(choices), n).astype(np.int32)
    else:
        codes = rng.choice(len(choices), n, p=p).astype(np.int32)
    d = pa.DictionaryArray.from_arrays(pa.array(codes), pa.array(list(choices)))
    return d.cast(pa.string())


def _istr(a) -> pa.Array:
    return pc.cast(pa.array(np.asarray(a)), pa.string())


def _join(*parts) -> pa.Array:
    """Element-wise string concat; python str args broadcast as scalars."""
    return pc.binary_join_element_wise(*parts, "")


def _maybe_prefix(rng, n: int, prob: float, prefix: str, body: pa.Array) -> pa.Array:
    mask = pa.array(rng.random(n) < prob)
    return _join(pc.if_else(mask, prefix, ""), body)


def generate(sf: float = 0.01, seed: int = 0) -> Dict[str, pa.Table]:
    """Generate all 8 TPC-H tables as arrow tables."""
    rng = np.random.default_rng(seed)

    n_part = max(int(200_000 * sf), 20)
    n_supp = max(int(10_000 * sf), 5)
    n_cust = max(int(150_000 * sf), 15)
    n_ord = max(int(1_500_000 * sf), 150)

    region = pa.table({
        "r_regionkey": pa.array(range(5), pa.int64()),
        "r_name": REGIONS,
        "r_comment": [f"region {r}" for r in REGIONS],
    })

    nation = pa.table({
        "n_nationkey": pa.array(range(25), pa.int64()),
        "n_name": [n for n, _ in NATIONS],
        "n_regionkey": pa.array([r for _, r in NATIONS], pa.int64()),
        "n_comment": [f"nation {n}" for n, _ in NATIONS],
    })

    p_idx = _istr(np.arange(1, n_part + 1))
    part = pa.table({
        "p_partkey": pa.array(range(1, n_part + 1), pa.int64()),
        "p_name": _join(_pick(rng, COLORS, n_part), " ",
                        _pick(rng, COLORS, n_part), " part ", p_idx),
        "p_mfgr": _join("Manufacturer#", _istr(rng.integers(1, 6, n_part))),
        "p_brand": _join("Brand#", _istr(rng.integers(1, 6, n_part)),
                         _istr(rng.integers(1, 6, n_part))),
        "p_type": _join(_pick(rng, TYPES_P1, n_part), " ",
                        _pick(rng, TYPES_P2, n_part), " ",
                        _pick(rng, TYPES_P3, n_part)),
        "p_size": pa.array(rng.integers(1, 51, n_part), pa.int32()),
        "p_container": _join(_pick(rng, CONTAINERS_P1, n_part), " ",
                             _pick(rng, CONTAINERS_P2, n_part)),
        "p_retailprice": pa.array(np.round(rng.uniform(900, 2000, n_part), 2)),
        "p_comment": _join("part comment ", _istr(np.arange(n_part))),
    })

    def _phone(n):
        return _join(_istr(rng.integers(10, 35, n)), "-",
                     _istr(rng.integers(100, 1000, n)), "-",
                     _istr(rng.integers(100, 1000, n)), "-",
                     _istr(rng.integers(1000, 10000, n)))

    supplier = pa.table({
        "s_suppkey": pa.array(range(1, n_supp + 1), pa.int64()),
        "s_name": _join("Supplier#", pc.utf8_lpad(_istr(np.arange(1, n_supp + 1)), 9, "0")),
        "s_address": _join("addr ", _istr(np.arange(n_supp))),
        "s_nationkey": pa.array(rng.integers(0, 25, n_supp), pa.int64()),
        "s_phone": _phone(n_supp),
        "s_acctbal": pa.array(np.round(rng.uniform(-999.99, 9999.99, n_supp), 2)),
        "s_comment": _maybe_prefix(rng, n_supp, 0.01, "Customer Complaints ",
                                   _join("supplier comment ", _istr(np.arange(n_supp)))),
    })

    n_psupp = n_part * 4
    ps_partkey = np.repeat(np.arange(1, n_part + 1), 4)
    ps_suppkey = ((ps_partkey + np.tile(np.arange(4), n_part) * (n_supp // 4 + 1)) % n_supp) + 1
    partsupp = pa.table({
        "ps_partkey": pa.array(ps_partkey, pa.int64()),
        "ps_suppkey": pa.array(ps_suppkey, pa.int64()),
        "ps_availqty": pa.array(rng.integers(1, 10_000, n_psupp), pa.int32()),
        "ps_supplycost": pa.array(np.round(rng.uniform(1.0, 1000.0, n_psupp), 2)),
        "ps_comment": _join("ps comment ", _istr(np.arange(n_psupp))),
    })

    customer = pa.table({
        "c_custkey": pa.array(range(1, n_cust + 1), pa.int64()),
        "c_name": _join("Customer#", pc.utf8_lpad(_istr(np.arange(1, n_cust + 1)), 9, "0")),
        "c_address": _join("caddr ", _istr(np.arange(n_cust))),
        "c_nationkey": pa.array(rng.integers(0, 25, n_cust), pa.int64()),
        "c_phone": _phone(n_cust),
        "c_acctbal": pa.array(np.round(rng.uniform(-999.99, 9999.99, n_cust), 2)),
        "c_mktsegment": _pick(rng, SEGMENTS, n_cust),
        "c_comment": _join("customer comment ", _istr(np.arange(n_cust))),
    })

    o_orderdate = _dates(rng, n_ord, D_1992, D_1998 - 151)
    orders = pa.table({
        "o_orderkey": pa.array(range(1, n_ord + 1), pa.int64()),
        "o_custkey": pa.array(rng.integers(1, n_cust + 1, n_ord), pa.int64()),
        "o_orderstatus": _pick(rng, ["O", "F", "P"], n_ord, p=[0.49, 0.49, 0.02]),
        "o_totalprice": pa.array(np.round(rng.uniform(800, 500_000, n_ord), 2)),
        "o_orderdate": pa.array(o_orderdate, pa.date32()),
        "o_orderpriority": _pick(rng, PRIORITIES, n_ord),
        "o_clerk": _join("Clerk#", pc.utf8_lpad(_istr(rng.integers(1, 1001, n_ord)), 9, "0")),
        "o_shippriority": pa.array(np.zeros(n_ord, dtype=np.int32)),
        "o_comment": _maybe_prefix(rng, n_ord, 0.02, "special requests ",
                                   _join("order comment ", _istr(np.arange(n_ord)))),
    })

    lines_per_order = rng.integers(1, 8, n_ord)
    n_line = int(lines_per_order.sum())
    l_orderkey = np.repeat(np.arange(1, n_ord + 1), lines_per_order)
    l_orderdate = np.repeat(o_orderdate, lines_per_order)
    l_shipdate = l_orderdate + rng.integers(1, 122, n_line)
    l_commitdate = l_orderdate + rng.integers(30, 91, n_line)
    l_receiptdate = l_shipdate + rng.integers(1, 31, n_line)
    l_quantity = rng.integers(1, 51, n_line).astype(np.float64)
    l_extendedprice = np.round(l_quantity * rng.uniform(900, 2000, n_line) / 10, 2)
    linenumber = np.concatenate([np.arange(1, c + 1) for c in lines_per_order]) if n_ord else np.empty(0, np.int64)

    lineitem = pa.table({
        "l_orderkey": pa.array(l_orderkey, pa.int64()),
        "l_partkey": pa.array(rng.integers(1, n_part + 1, n_line), pa.int64()),
        "l_suppkey": pa.array(rng.integers(1, n_supp + 1, n_line), pa.int64()),
        "l_linenumber": pa.array(linenumber, pa.int32()),
        "l_quantity": pa.array(l_quantity),
        "l_extendedprice": pa.array(l_extendedprice),
        "l_discount": pa.array(np.round(rng.uniform(0.0, 0.10, n_line), 2)),
        "l_tax": pa.array(np.round(rng.uniform(0.0, 0.08, n_line), 2)),
        "l_returnflag": _pick(rng, ["R", "A", "N"], n_line),
        "l_linestatus": _pick(rng, ["O", "F"], n_line),
        "l_shipdate": pa.array(l_shipdate.astype("int32"), pa.date32()),
        "l_commitdate": pa.array(l_commitdate.astype("int32"), pa.date32()),
        "l_receiptdate": pa.array(l_receiptdate.astype("int32"), pa.date32()),
        "l_shipinstruct": _pick(rng, INSTRUCTIONS, n_line),
        "l_shipmode": _pick(rng, SHIPMODES, n_line),
        "l_comment": _join("line comment ", _istr(np.arange(n_line))),
    })

    return {
        "region": region, "nation": nation, "part": part, "supplier": supplier,
        "partsupp": partsupp, "customer": customer, "orders": orders, "lineitem": lineitem,
    }


def write_parquet(tables: Dict[str, pa.Table], root: str) -> None:
    import pyarrow.parquet as pq

    os.makedirs(root, exist_ok=True)
    for name, t in tables.items():
        pq.write_table(t, os.path.join(root, f"{name}.parquet"))


def cached_tables(sf: float = 0.01, seed: int = 0) -> Dict[str, pa.Table]:
    """generate() with a parquet disk cache keyed by (sf, seed)."""
    import pyarrow.parquet as pq

    root = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "_cache", f"sf{sf}_s{seed}")
    names = ["region", "nation", "supplier", "customer", "part", "partsupp",
             "orders", "lineitem"]
    if all(os.path.exists(os.path.join(root, f"{n}.parquet")) for n in names):
        return {n: pq.read_table(os.path.join(root, f"{n}.parquet")) for n in names}
    tables = generate(sf, seed)
    write_parquet(tables, root)
    return tables


def load_dataframes(sf: float = 0.01, seed: int = 0):
    """Tables as in-memory daft_tpu DataFrames."""
    import daft_tpu as dt

    return {name: dt.from_arrow(t) for name, t in cached_tables(sf, seed).items()}
