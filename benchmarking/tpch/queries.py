"""TPC-H Q1-Q22 as daft_tpu DataFrame programs.

Reference parity: benchmarking/tpch/answers.py (dataframe-form queries). Queries
follow the public TPC-H specification; correlated subqueries are expressed as
join rewrites (the standard dataframe formulation).
"""

from __future__ import annotations

import datetime

from daft_tpu import col, lit


def _d(y, m, d):
    return lit(datetime.date(y, m, d))


def q1(t):
    L = t["lineitem"]
    return (
        L.where(col("l_shipdate") <= _d(1998, 9, 2))
        .groupby("l_returnflag", "l_linestatus")
        .agg(
            col("l_quantity").sum().alias("sum_qty"),
            col("l_extendedprice").sum().alias("sum_base_price"),
            (col("l_extendedprice") * (1 - col("l_discount"))).sum().alias("sum_disc_price"),
            (col("l_extendedprice") * (1 - col("l_discount")) * (1 + col("l_tax"))).sum().alias("sum_charge"),
            col("l_quantity").mean().alias("avg_qty"),
            col("l_extendedprice").mean().alias("avg_price"),
            col("l_discount").mean().alias("avg_disc"),
            col("l_quantity").count().alias("count_order"),
        )
        .sort(["l_returnflag", "l_linestatus"])
    )


def q2(t):
    P, S, PS, N, R = t["part"], t["supplier"], t["partsupp"], t["nation"], t["region"]
    europe = (
        R.where(col("r_name") == "EUROPE")
        .join(N, left_on="r_regionkey", right_on="n_regionkey")
        .join(S, left_on="n_nationkey", right_on="s_nationkey")
        .join(PS, left_on="s_suppkey", right_on="ps_suppkey")
    )
    brass = P.where((col("p_size") == 15) & col("p_type").str.endswith("BRASS"))
    merged = europe.join(brass, left_on="ps_partkey", right_on="p_partkey")
    min_cost = merged.groupby("ps_partkey").agg(col("ps_supplycost").min().alias("min_cost"))
    return (
        merged.join(min_cost, on="ps_partkey")
        .where(col("ps_supplycost") == col("min_cost"))
        .select("s_acctbal", "s_name", "n_name", col("ps_partkey").alias("p_partkey"),
                "p_mfgr", "s_address", "s_phone", "s_comment")
        .sort(["s_acctbal", "n_name", "s_name", "p_partkey"], desc=[True, False, False, False])
        .limit(100)
    )


def q3(t):
    C, O, L = t["customer"], t["orders"], t["lineitem"]
    return (
        C.where(col("c_mktsegment") == "BUILDING")
        .join(O, left_on="c_custkey", right_on="o_custkey")
        .where(col("o_orderdate") < _d(1995, 3, 15))
        .join(L, left_on="o_orderkey", right_on="l_orderkey")
        .where(col("l_shipdate") > _d(1995, 3, 15))
        .groupby(col("o_orderkey").alias("l_orderkey"), "o_orderdate", "o_shippriority")
        .agg((col("l_extendedprice") * (1 - col("l_discount"))).sum().alias("revenue"))
        .select("l_orderkey", "revenue", "o_orderdate", "o_shippriority")
        .sort(["revenue", "o_orderdate"], desc=[True, False])
        .limit(10)
    )


def q4(t):
    O, L = t["orders"], t["lineitem"]
    late = L.where(col("l_commitdate") < col("l_receiptdate"))
    return (
        O.where((col("o_orderdate") >= _d(1993, 7, 1)) & (col("o_orderdate") < _d(1993, 10, 1)))
        .join(late, left_on="o_orderkey", right_on="l_orderkey", how="semi")
        .groupby("o_orderpriority")
        .agg(col("o_orderkey").count().alias("order_count"))
        .sort("o_orderpriority")
    )


def q5(t):
    C, O, L, S, N, R = t["customer"], t["orders"], t["lineitem"], t["supplier"], t["nation"], t["region"]
    return (
        R.where(col("r_name") == "ASIA")
        .join(N, left_on="r_regionkey", right_on="n_regionkey")
        .join(C, left_on="n_nationkey", right_on="c_nationkey")
        .join(O, left_on="c_custkey", right_on="o_custkey")
        .where((col("o_orderdate") >= _d(1994, 1, 1)) & (col("o_orderdate") < _d(1995, 1, 1)))
        .join(L, left_on="o_orderkey", right_on="l_orderkey")
        # supplier must be in the same nation as the customer
        .join(S, left_on=["l_suppkey", "n_nationkey"], right_on=["s_suppkey", "s_nationkey"])
        .groupby("n_name")
        .agg((col("l_extendedprice") * (1 - col("l_discount"))).sum().alias("revenue"))
        .sort("revenue", desc=True)
    )


def q6(t):
    L = t["lineitem"]
    return (
        L.where(
            (col("l_shipdate") >= _d(1994, 1, 1)) & (col("l_shipdate") < _d(1995, 1, 1))
            & (col("l_discount") >= 0.05) & (col("l_discount") <= 0.07)
            & (col("l_quantity") < 24)
        )
        .agg((col("l_extendedprice") * col("l_discount")).sum().alias("revenue"))
    )


def q7(t):
    C, O, L, S, N = t["customer"], t["orders"], t["lineitem"], t["supplier"], t["nation"]
    n1 = N.select(col("n_nationkey").alias("supp_nationkey"), col("n_name").alias("supp_nation"))
    n2 = N.select(col("n_nationkey").alias("cust_nationkey"), col("n_name").alias("cust_nation"))
    return (
        L.where((col("l_shipdate") >= _d(1995, 1, 1)) & (col("l_shipdate") <= _d(1996, 12, 31)))
        .join(S, left_on="l_suppkey", right_on="s_suppkey")
        .join(n1, left_on="s_nationkey", right_on="supp_nationkey")
        .join(O, left_on="l_orderkey", right_on="o_orderkey")
        .join(C, left_on="o_custkey", right_on="c_custkey")
        .join(n2, left_on="c_nationkey", right_on="cust_nationkey")
        .where(
            ((col("supp_nation") == "FRANCE") & (col("cust_nation") == "GERMANY"))
            | ((col("supp_nation") == "GERMANY") & (col("cust_nation") == "FRANCE"))
        )
        .with_column("l_year", col("l_shipdate").dt.year())
        .with_column("volume", col("l_extendedprice") * (1 - col("l_discount")))
        .groupby("supp_nation", "cust_nation", "l_year")
        .agg(col("volume").sum().alias("revenue"))
        .sort(["supp_nation", "cust_nation", "l_year"])
    )


def q8(t):
    P, S, L, O, C, N, R = (t["part"], t["supplier"], t["lineitem"], t["orders"],
                           t["customer"], t["nation"], t["region"])
    n1 = N.select(col("n_nationkey").alias("cust_nationkey"), col("n_regionkey").alias("cust_regionkey"))
    n2 = N.select(col("n_nationkey").alias("supp_nationkey"), col("n_name").alias("supp_nation"))
    return (
        P.where(col("p_type") == "ECONOMY ANODIZED STEEL")
        .join(L, left_on="p_partkey", right_on="l_partkey")
        .join(S, left_on="l_suppkey", right_on="s_suppkey")
        .join(O, left_on="l_orderkey", right_on="o_orderkey")
        .where((col("o_orderdate") >= _d(1995, 1, 1)) & (col("o_orderdate") <= _d(1996, 12, 31)))
        .join(C, left_on="o_custkey", right_on="c_custkey")
        .join(n1, left_on="c_nationkey", right_on="cust_nationkey")
        .join(R.where(col("r_name") == "AMERICA"), left_on="cust_regionkey", right_on="r_regionkey")
        .join(n2, left_on="s_nationkey", right_on="supp_nationkey")
        .with_column("o_year", col("o_orderdate").dt.year())
        .with_column("volume", col("l_extendedprice") * (1 - col("l_discount")))
        .with_column("brazil_volume",
                     (col("supp_nation") == "BRAZIL").if_else(col("volume"), lit(0.0)))
        .groupby("o_year")
        .agg(col("brazil_volume").sum().alias("brazil"), col("volume").sum().alias("total"))
        .select(col("o_year"), (col("brazil") / col("total")).alias("mkt_share"))
        .sort("o_year")
    )


def q9(t):
    P, S, L, PS, O, N = (t["part"], t["supplier"], t["lineitem"], t["partsupp"],
                         t["orders"], t["nation"])
    return (
        P.where(col("p_name").str.contains("green"))
        .join(L, left_on="p_partkey", right_on="l_partkey")
        .join(S, left_on="l_suppkey", right_on="s_suppkey")
        .join(PS, left_on=["l_suppkey", "p_partkey"], right_on=["ps_suppkey", "ps_partkey"])
        .join(O, left_on="l_orderkey", right_on="o_orderkey")
        .join(N, left_on="s_nationkey", right_on="n_nationkey")
        .with_column("o_year", col("o_orderdate").dt.year())
        .with_column("amount",
                     col("l_extendedprice") * (1 - col("l_discount"))
                     - col("ps_supplycost") * col("l_quantity"))
        .groupby(col("n_name").alias("nation"), "o_year")
        .agg(col("amount").sum().alias("sum_profit"))
        .sort(["nation", "o_year"], desc=[False, True])
    )


def q10(t):
    C, O, L, N = t["customer"], t["orders"], t["lineitem"], t["nation"]
    return (
        O.where((col("o_orderdate") >= _d(1993, 10, 1)) & (col("o_orderdate") < _d(1994, 1, 1)))
        .join(L.where(col("l_returnflag") == "R"), left_on="o_orderkey", right_on="l_orderkey")
        .join(C, left_on="o_custkey", right_on="c_custkey")
        .join(N, left_on="c_nationkey", right_on="n_nationkey")
        .groupby(col("o_custkey").alias("c_custkey"), "c_name", "c_acctbal", "c_phone",
                 "n_name", "c_address", "c_comment")
        .agg((col("l_extendedprice") * (1 - col("l_discount"))).sum().alias("revenue"))
        .select("c_custkey", "c_name", "revenue", "c_acctbal", "n_name", "c_address",
                "c_phone", "c_comment")
        .sort(["revenue", "c_custkey"], desc=[True, False])
        .limit(20)
    )


def q11(t):
    PS, S, N = t["partsupp"], t["supplier"], t["nation"]
    germany = (
        N.where(col("n_name") == "GERMANY")
        .join(S, left_on="n_nationkey", right_on="s_nationkey")
        .join(PS, left_on="s_suppkey", right_on="ps_suppkey")
        .with_column("value", col("ps_supplycost") * col("ps_availqty"))
    )
    total = germany.agg(col("value").sum().alias("total"))
    by_part = germany.groupby("ps_partkey").agg(col("value").sum().alias("value"))
    return (
        by_part.join(total, how="cross")
        .where(col("value") > col("total") * 0.0001)
        .select("ps_partkey", "value")
        .sort(["value", "ps_partkey"], desc=[True, False])
    )


def q12(t):
    O, L = t["orders"], t["lineitem"]
    high = col("o_orderpriority").is_in(["1-URGENT", "2-HIGH"])
    return (
        L.where(
            col("l_shipmode").is_in(["MAIL", "SHIP"])
            & (col("l_commitdate") < col("l_receiptdate"))
            & (col("l_shipdate") < col("l_commitdate"))
            & (col("l_receiptdate") >= _d(1994, 1, 1)) & (col("l_receiptdate") < _d(1995, 1, 1))
        )
        .join(O, left_on="l_orderkey", right_on="o_orderkey")
        .with_column("high_line", high.if_else(lit(1), lit(0)))
        .with_column("low_line", (~high).if_else(lit(1), lit(0)))
        .groupby("l_shipmode")
        .agg(col("high_line").sum().alias("high_line_count"),
             col("low_line").sum().alias("low_line_count"))
        .sort("l_shipmode")
    )


def q13(t):
    C, O = t["customer"], t["orders"]
    filtered = O.where(~col("o_comment").str.contains("special requests"))
    per_cust = (
        C.join(filtered, left_on="c_custkey", right_on="o_custkey", how="left")
        .groupby("c_custkey")
        .agg(col("o_orderkey").count().alias("c_count"))
    )
    return (
        per_cust.groupby("c_count")
        .agg(col("c_custkey").count().alias("custdist"))
        .sort(["custdist", "c_count"], desc=[True, True])
    )


def q14(t):
    L, P = t["lineitem"], t["part"]
    return (
        L.where((col("l_shipdate") >= _d(1995, 9, 1)) & (col("l_shipdate") < _d(1995, 10, 1)))
        .join(P, left_on="l_partkey", right_on="p_partkey")
        .with_column("revenue", col("l_extendedprice") * (1 - col("l_discount")))
        .with_column("promo", col("p_type").str.startswith("PROMO").if_else(col("revenue"), lit(0.0)))
        .agg(col("promo").sum().alias("promo_sum"), col("revenue").sum().alias("total_sum"))
        .select((lit(100.0) * col("promo_sum") / col("total_sum")).alias("promo_revenue"))
    )


def q15(t):
    L, S = t["lineitem"], t["supplier"]
    revenue = (
        L.where((col("l_shipdate") >= _d(1996, 1, 1)) & (col("l_shipdate") < _d(1996, 4, 1)))
        .groupby(col("l_suppkey").alias("supplier_no"))
        .agg((col("l_extendedprice") * (1 - col("l_discount"))).sum().alias("total_revenue"))
    )
    max_rev = revenue.agg(col("total_revenue").max().alias("max_revenue"))
    return (
        revenue.join(max_rev, how="cross")
        .where(col("total_revenue") == col("max_revenue"))
        .join(S, left_on="supplier_no", right_on="s_suppkey")
        .select(col("supplier_no").alias("s_suppkey"), "s_name", "s_address", "s_phone", "total_revenue")
        .sort("s_suppkey")
    )


def q16(t):
    PS, P, S = t["partsupp"], t["part"], t["supplier"]
    complainers = S.where(col("s_comment").str.contains("Customer Complaints"))
    return (
        P.where(
            (col("p_brand") != "Brand#45")
            & ~col("p_type").str.startswith("MEDIUM POLISHED")
            & col("p_size").is_in([49, 14, 23, 45, 19, 3, 36, 9])
        )
        .join(PS, left_on="p_partkey", right_on="ps_partkey")
        .join(complainers, left_on="ps_suppkey", right_on="s_suppkey", how="anti")
        .distinct("p_brand", "p_type", "p_size", "ps_suppkey")
        .groupby("p_brand", "p_type", "p_size")
        .agg(col("ps_suppkey").count().alias("supplier_cnt"))
        .sort(["supplier_cnt", "p_brand", "p_type", "p_size"], desc=[True, False, False, False])
    )


def q17(t):
    L, P = t["lineitem"], t["part"]
    brand = P.where((col("p_brand") == "Brand#23") & (col("p_container") == "MED BOX"))
    joined = L.join(brand, left_on="l_partkey", right_on="p_partkey")
    avg_qty = (
        joined.groupby(col("l_partkey").alias("avg_partkey"))
        .agg(col("l_quantity").mean().alias("avg_quantity"))
    )
    return (
        joined.join(avg_qty, left_on="l_partkey", right_on="avg_partkey")
        .where(col("l_quantity") < 0.2 * col("avg_quantity"))
        .agg(col("l_extendedprice").sum().alias("sum_extendedprice"))
        .select((col("sum_extendedprice") / 7.0).alias("avg_yearly"))
    )


def q18(t):
    C, O, L = t["customer"], t["orders"], t["lineitem"]
    big = (
        L.groupby("l_orderkey")
        .agg(col("l_quantity").sum().alias("sum_qty"))
        .where(col("sum_qty") > 300)
    )
    return (
        O.join(big, left_on="o_orderkey", right_on="l_orderkey", how="semi")
        .join(C, left_on="o_custkey", right_on="c_custkey")
        .join(L, left_on="o_orderkey", right_on="l_orderkey")
        .groupby("c_name", col("o_custkey").alias("c_custkey"), "o_orderkey",
                 "o_orderdate", "o_totalprice")
        .agg(col("l_quantity").sum().alias("col6"))
        .sort(["o_totalprice", "o_orderdate"], desc=[True, False])
        .limit(100)
    )


def q19(t):
    L, P = t["lineitem"], t["part"]
    joined = L.where(
        col("l_shipmode").is_in(["AIR", "REG AIR"])
        & (col("l_shipinstruct") == "DELIVER IN PERSON")
    ).join(P, left_on="l_partkey", right_on="p_partkey")
    sm = (col("p_brand") == "Brand#12") & col("p_container").is_in(
        ["SM CASE", "SM BOX", "SM PACK", "SM PKG"]
    ) & (col("l_quantity") >= 1) & (col("l_quantity") <= 11) & (col("p_size") <= 5)
    med = (col("p_brand") == "Brand#23") & col("p_container").is_in(
        ["MED BAG", "MED BOX", "MED PKG", "MED PACK"]
    ) & (col("l_quantity") >= 10) & (col("l_quantity") <= 20) & (col("p_size") <= 10)
    lg = (col("p_brand") == "Brand#34") & col("p_container").is_in(
        ["LG CASE", "LG BOX", "LG PACK", "LG PKG"]
    ) & (col("l_quantity") >= 20) & (col("l_quantity") <= 30) & (col("p_size") <= 15)
    return (
        joined.where((col("p_size") >= 1) & (sm | med | lg))
        .agg((col("l_extendedprice") * (1 - col("l_discount"))).sum().alias("revenue"))
    )


def q20(t):
    S, N, PS, P, L = t["supplier"], t["nation"], t["partsupp"], t["part"], t["lineitem"]
    forest_parts = P.where(col("p_name").str.startswith("forest"))
    shipped = (
        L.where((col("l_shipdate") >= _d(1994, 1, 1)) & (col("l_shipdate") < _d(1995, 1, 1)))
        .groupby(col("l_partkey").alias("spk"), col("l_suppkey").alias("ssk"))
        .agg(col("l_quantity").sum().alias("total_shipped"))
    )
    qualified = (
        PS.join(forest_parts, left_on="ps_partkey", right_on="p_partkey", how="semi")
        .join(shipped, left_on=["ps_partkey", "ps_suppkey"], right_on=["spk", "ssk"])
        .where(col("ps_availqty") > 0.5 * col("total_shipped"))
    )
    return (
        S.join(qualified, left_on="s_suppkey", right_on="ps_suppkey", how="semi")
        .join(N.where(col("n_name") == "CANADA"), left_on="s_nationkey", right_on="n_nationkey", how="semi")
        .select("s_name", "s_address")
        .sort("s_name")
    )


def q21(t):
    S, L, O, N = t["supplier"], t["lineitem"], t["orders"], t["nation"]
    late = L.where(col("l_receiptdate") > col("l_commitdate"))
    # orders with >1 distinct supplier
    multi_supp = (
        L.groupby("l_orderkey").agg(col("l_suppkey").count_distinct().alias("nsupp"))
        .where(col("nsupp") > 1)
    )
    # orders where ONLY one supplier was late
    single_late = (
        late.groupby("l_orderkey").agg(col("l_suppkey").count_distinct().alias("nlate"))
        .where(col("nlate") == 1)
    )
    return (
        late.join(O.where(col("o_orderstatus") == "F"), left_on="l_orderkey", right_on="o_orderkey", how="semi")
        .join(multi_supp, on="l_orderkey", how="semi")
        .join(single_late, on="l_orderkey", how="semi")
        .join(S, left_on="l_suppkey", right_on="s_suppkey")
        .join(N.where(col("n_name") == "SAUDI ARABIA"), left_on="s_nationkey",
              right_on="n_nationkey", how="semi")
        .groupby("s_name")
        .agg(col("l_orderkey").count().alias("numwait"))
        .sort(["numwait", "s_name"], desc=[True, False])
        .limit(100)
    )


def q22(t):
    C, O = t["customer"], t["orders"]
    codes = ["13", "31", "23", "29", "30", "18", "17"]
    with_code = C.with_column("cntrycode", col("c_phone").str.left(2))
    eligible = with_code.where(col("cntrycode").is_in(codes))
    avg_bal = (
        eligible.where(col("c_acctbal") > 0.0)
        .agg(col("c_acctbal").mean().alias("avg_acctbal"))
    )
    return (
        eligible.join(O, left_on="c_custkey", right_on="o_custkey", how="anti")
        .join(avg_bal, how="cross")
        .where(col("c_acctbal") > col("avg_acctbal"))
        .groupby("cntrycode")
        .agg(col("c_acctbal").count().alias("numcust"),
             col("c_acctbal").sum().alias("totacctbal"))
        .sort("cntrycode")
    )


ALL_QUERIES = {i: globals()[f"q{i}"] for i in range(1, 23)}
