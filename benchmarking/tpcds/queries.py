"""TPC-DS store-sales-channel queries as daft_tpu dataframe programs.

Reference parity: benchmarking/tpcds/queries/*.sql (the official texts; the
numbered functions here implement the same semantics over the synthetic
tables from datagen.py). The set covers the star-join + aggregate shapes
(q3/q42/q52/q55), multi-dimension filters (q7), and selective count joins
(q96) that dominate the store_sales channel.
"""

from __future__ import annotations

from daft_tpu import col


def q3(t):
    """queries/03.sql: brand revenue by year for one manufacturer in November."""
    return (t["store_sales"]
            .join(t["date_dim"].where(col("d_moy") == 11),
                  left_on="ss_sold_date_sk", right_on="d_date_sk")
            .join(t["item"].where(col("i_manufact_id") == 128),
                  left_on="ss_item_sk", right_on="i_item_sk")
            .groupby("d_year", "i_brand", "i_brand_id")
            .agg(col("ss_ext_sales_price").sum().alias("sum_agg"))
            .sort(["d_year", "sum_agg", "i_brand_id"], desc=[False, True, False])
            .limit(100)
            .select("d_year", col("i_brand_id").alias("brand_id"),
                    col("i_brand").alias("brand"), "sum_agg"))


def q7(t):
    """queries/07.sql: average sales stats by item for one demographic slice."""
    cd = t["customer_demographics"].where(
        (col("cd_gender") == "M") & (col("cd_marital_status") == "S")
        & (col("cd_education_status") == "College"))
    promo = t["promotion"].where(
        (col("p_channel_email") == "N") | (col("p_channel_event") == "N"))
    return (t["store_sales"]
            .join(cd, left_on="ss_cdemo_sk", right_on="cd_demo_sk")
            .join(t["date_dim"].where(col("d_year") == 2000),
                  left_on="ss_sold_date_sk", right_on="d_date_sk")
            .join(t["item"], left_on="ss_item_sk", right_on="i_item_sk")
            .join(promo, left_on="ss_promo_sk", right_on="p_promo_sk")
            .groupby("i_item_id")
            .agg(col("ss_quantity").mean().alias("agg1"),
                 col("ss_list_price").mean().alias("agg2"),
                 col("ss_coupon_amt").mean().alias("agg3"),
                 col("ss_sales_price").mean().alias("agg4"))
            .sort("i_item_id")
            .limit(100))


def q19(t):
    """queries/19.sql: brand revenue where customer and store zips differ."""
    return (t["store_sales"]
            .join(t["date_dim"].where((col("d_moy") == 11) & (col("d_year") == 1998)),
                  left_on="ss_sold_date_sk", right_on="d_date_sk")
            .join(t["item"].where(col("i_manager_id") == 8),
                  left_on="ss_item_sk", right_on="i_item_sk")
            .join(t["customer"], left_on="ss_customer_sk", right_on="c_customer_sk")
            .join(t["customer_address"], left_on="c_current_addr_sk",
                  right_on="ca_address_sk")
            .join(t["store"], left_on="ss_store_sk", right_on="s_store_sk")
            .where(col("ca_zip").str.left(5) != col("s_zip").str.left(5))
            .groupby("i_brand", "i_brand_id", "i_manufact_id")
            .agg(col("ss_ext_sales_price").sum().alias("ext_price"))
            .sort(["ext_price", "i_brand", "i_brand_id", "i_manufact_id"],
                  desc=[True, False, False, False])
            .limit(100)
            .select(col("i_brand_id").alias("brand_id"),
                    col("i_brand").alias("brand"), "i_manufact_id", "ext_price"))


def q42(t):
    """queries/42.sql: category revenue for manager 1, Nov 2000."""
    return (t["store_sales"]
            .join(t["date_dim"].where((col("d_moy") == 11) & (col("d_year") == 2000)),
                  left_on="ss_sold_date_sk", right_on="d_date_sk")
            .join(t["item"].where(col("i_manager_id") == 1),
                  left_on="ss_item_sk", right_on="i_item_sk")
            .groupby("d_year", "i_category_id", "i_category")
            .agg(col("ss_ext_sales_price").sum().alias("total"))
            .sort(["total", "d_year", "i_category_id", "i_category"],
                  desc=[True, False, False, False])
            .limit(100))


def q52(t):
    """queries/52.sql: brand revenue for manager 1, Nov 2000."""
    return (t["store_sales"]
            .join(t["date_dim"].where((col("d_moy") == 11) & (col("d_year") == 2000)),
                  left_on="ss_sold_date_sk", right_on="d_date_sk")
            .join(t["item"].where(col("i_manager_id") == 1),
                  left_on="ss_item_sk", right_on="i_item_sk")
            .groupby("d_year", "i_brand", "i_brand_id")
            .agg(col("ss_ext_sales_price").sum().alias("ext_price"))
            .sort(["d_year", "ext_price", "i_brand_id"], desc=[False, True, False])
            .limit(100)
            .select("d_year", col("i_brand_id").alias("brand_id"),
                    col("i_brand").alias("brand"), "ext_price"))


def q55(t):
    """queries/55.sql: brand revenue for manager 28, Nov 1999."""
    return (t["store_sales"]
            .join(t["date_dim"].where((col("d_moy") == 11) & (col("d_year") == 1999)),
                  left_on="ss_sold_date_sk", right_on="d_date_sk")
            .join(t["item"].where(col("i_manager_id") == 28),
                  left_on="ss_item_sk", right_on="i_item_sk")
            .groupby("i_brand", "i_brand_id")
            .agg(col("ss_ext_sales_price").sum().alias("ext_price"))
            .sort(["ext_price", "i_brand_id"], desc=[True, False])
            .limit(100)
            .select(col("i_brand_id").alias("brand_id"),
                    col("i_brand").alias("brand"), "ext_price"))


def q96(t):
    """queries/96.sql: count of evening sales for one store/demographic."""
    return (t["store_sales"]
            .join(t["time_dim"].where((col("t_hour") == 20) & (col("t_minute") >= 30)),
                  left_on="ss_sold_time_sk", right_on="t_time_sk")
            .join(t["household_demographics"].where(col("hd_dep_count") == 7),
                  left_on="ss_hdemo_sk", right_on="hd_demo_sk")
            .join(t["store"].where(col("s_store_name") == "ese"),
                  left_on="ss_store_sk", right_on="s_store_sk")
            .count())


ALL_QUERIES = {3: q3, 7: q7, 19: q19, 42: q42, 52: q52, 55: q55, 96: q96}


def _three_channel_total(t, key_col: str, item_filter, d_year: int, d_moy: int):
    """Shared shape of q33/q56: per-channel revenue for a filtered item set in
    one month, restricted to ca_gmt_offset = -5, summed across channels."""
    from daft_tpu import col

    wanted = (t["item"].where(item_filter).select(key_col).distinct())
    dd = t["date_dim"].where((col("d_year") == d_year) & (col("d_moy") == d_moy))
    ca = t["customer_address"].where(col("ca_gmt_offset") == -5.0)

    def channel(fact: str, prefix: str):
        return (t[fact]
                .join(dd, left_on=f"{prefix}_sold_date_sk", right_on="d_date_sk")
                .join(ca, left_on=(f"{prefix}_addr_sk" if prefix == "ss"
                                   else f"{prefix}_bill_addr_sk"),
                      right_on="ca_address_sk")
                .join(t["item"], left_on=f"{prefix}_item_sk", right_on="i_item_sk")
                .join(wanted, left_on=key_col, right_on=key_col, how="semi")
                .groupby(key_col)
                .agg(col(f"{prefix}_ext_sales_price").sum().alias("total_sales")))

    ss = channel("store_sales", "ss")
    cs = channel("catalog_sales", "cs")
    ws = channel("web_sales", "ws")
    return (ss.concat(cs).concat(ws)
            .groupby(key_col)
            .agg(col("total_sales").sum().alias("total_sales"))
            .sort(["total_sales", key_col])
            .limit(100))


def q33(t):
    """queries/33.sql: Electronics revenue by manufacturer across all three
    sales channels, May 1998."""
    from daft_tpu import col

    return _three_channel_total(t, "i_manufact_id",
                                col("i_category") == "Electronics", 1998, 5)


def q56(t):
    """queries/56.sql: colored-item revenue by item id across all three
    sales channels, Feb 2001."""
    from daft_tpu import col

    return _three_channel_total(
        t, "i_item_id",
        col("i_color").is_in(["slate", "blanched", "burnished"]), 2001, 2)


ALL_QUERIES[33] = q33
ALL_QUERIES[56] = q56
