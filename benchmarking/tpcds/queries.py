"""TPC-DS store-sales-channel queries as daft_tpu dataframe programs.

Reference parity: benchmarking/tpcds/queries/*.sql (the official texts; the
numbered functions here implement the same semantics over the synthetic
tables from datagen.py). The set covers the star-join + aggregate shapes
(q3/q42/q52/q55), multi-dimension filters (q7), and selective count joins
(q96) that dominate the store_sales channel.
"""

from __future__ import annotations

from daft_tpu import col
from daft_tpu.datatype import DataType as _DT


def q3(t):
    """queries/03.sql: brand revenue by year for one manufacturer in November."""
    return (t["store_sales"]
            .join(t["date_dim"].where(col("d_moy") == 11),
                  left_on="ss_sold_date_sk", right_on="d_date_sk")
            .join(t["item"].where(col("i_manufact_id") == 128),
                  left_on="ss_item_sk", right_on="i_item_sk")
            .groupby("d_year", "i_brand", "i_brand_id")
            .agg(col("ss_ext_sales_price").sum().alias("sum_agg"))
            .sort(["d_year", "sum_agg", "i_brand_id"], desc=[False, True, False])
            .limit(100)
            .select("d_year", col("i_brand_id").alias("brand_id"),
                    col("i_brand").alias("brand"), "sum_agg"))


def q7(t):
    """queries/07.sql: average sales stats by item for one demographic slice."""
    cd = t["customer_demographics"].where(
        (col("cd_gender") == "M") & (col("cd_marital_status") == "S")
        & (col("cd_education_status") == "College"))
    promo = t["promotion"].where(
        (col("p_channel_email") == "N") | (col("p_channel_event") == "N"))
    return (t["store_sales"]
            .join(cd, left_on="ss_cdemo_sk", right_on="cd_demo_sk")
            .join(t["date_dim"].where(col("d_year") == 2000),
                  left_on="ss_sold_date_sk", right_on="d_date_sk")
            .join(t["item"], left_on="ss_item_sk", right_on="i_item_sk")
            .join(promo, left_on="ss_promo_sk", right_on="p_promo_sk")
            .groupby("i_item_id")
            .agg(col("ss_quantity").mean().alias("agg1"),
                 col("ss_list_price").mean().alias("agg2"),
                 col("ss_coupon_amt").mean().alias("agg3"),
                 col("ss_sales_price").mean().alias("agg4"))
            .sort("i_item_id")
            .limit(100))


def q19(t):
    """queries/19.sql: brand revenue where customer and store zips differ."""
    return (t["store_sales"]
            .join(t["date_dim"].where((col("d_moy") == 11) & (col("d_year") == 1998)),
                  left_on="ss_sold_date_sk", right_on="d_date_sk")
            .join(t["item"].where(col("i_manager_id") == 8),
                  left_on="ss_item_sk", right_on="i_item_sk")
            .join(t["customer"], left_on="ss_customer_sk", right_on="c_customer_sk")
            .join(t["customer_address"], left_on="c_current_addr_sk",
                  right_on="ca_address_sk")
            .join(t["store"], left_on="ss_store_sk", right_on="s_store_sk")
            .where(col("ca_zip").str.left(5) != col("s_zip").str.left(5))
            .groupby("i_brand", "i_brand_id", "i_manufact_id")
            .agg(col("ss_ext_sales_price").sum().alias("ext_price"))
            .sort(["ext_price", "i_brand", "i_brand_id", "i_manufact_id"],
                  desc=[True, False, False, False])
            .limit(100)
            .select(col("i_brand_id").alias("brand_id"),
                    col("i_brand").alias("brand"), "i_manufact_id", "ext_price"))


def q42(t):
    """queries/42.sql: category revenue for manager 1, Nov 2000."""
    return (t["store_sales"]
            .join(t["date_dim"].where((col("d_moy") == 11) & (col("d_year") == 2000)),
                  left_on="ss_sold_date_sk", right_on="d_date_sk")
            .join(t["item"].where(col("i_manager_id") == 1),
                  left_on="ss_item_sk", right_on="i_item_sk")
            .groupby("d_year", "i_category_id", "i_category")
            .agg(col("ss_ext_sales_price").sum().alias("total"))
            .sort(["total", "d_year", "i_category_id", "i_category"],
                  desc=[True, False, False, False])
            .limit(100))


def q52(t):
    """queries/52.sql: brand revenue for manager 1, Nov 2000."""
    return (t["store_sales"]
            .join(t["date_dim"].where((col("d_moy") == 11) & (col("d_year") == 2000)),
                  left_on="ss_sold_date_sk", right_on="d_date_sk")
            .join(t["item"].where(col("i_manager_id") == 1),
                  left_on="ss_item_sk", right_on="i_item_sk")
            .groupby("d_year", "i_brand", "i_brand_id")
            .agg(col("ss_ext_sales_price").sum().alias("ext_price"))
            .sort(["d_year", "ext_price", "i_brand_id"], desc=[False, True, False])
            .limit(100)
            .select("d_year", col("i_brand_id").alias("brand_id"),
                    col("i_brand").alias("brand"), "ext_price"))


def q55(t):
    """queries/55.sql: brand revenue for manager 28, Nov 1999."""
    return (t["store_sales"]
            .join(t["date_dim"].where((col("d_moy") == 11) & (col("d_year") == 1999)),
                  left_on="ss_sold_date_sk", right_on="d_date_sk")
            .join(t["item"].where(col("i_manager_id") == 28),
                  left_on="ss_item_sk", right_on="i_item_sk")
            .groupby("i_brand", "i_brand_id")
            .agg(col("ss_ext_sales_price").sum().alias("ext_price"))
            .sort(["ext_price", "i_brand_id"], desc=[True, False])
            .limit(100)
            .select(col("i_brand_id").alias("brand_id"),
                    col("i_brand").alias("brand"), "ext_price"))


def q96(t):
    """queries/96.sql: count of evening sales for one store/demographic."""
    return (t["store_sales"]
            .join(t["time_dim"].where((col("t_hour") == 20) & (col("t_minute") >= 30)),
                  left_on="ss_sold_time_sk", right_on="t_time_sk")
            .join(t["household_demographics"].where(col("hd_dep_count") == 7),
                  left_on="ss_hdemo_sk", right_on="hd_demo_sk")
            .join(t["store"].where(col("s_store_name") == "ese"),
                  left_on="ss_store_sk", right_on="s_store_sk")
            .count())


ALL_QUERIES = {3: q3, 7: q7, 19: q19, 42: q42, 52: q52, 55: q55, 96: q96}


def _three_channel_total(t, key_col: str, item_filter, d_year: int, d_moy: int):
    """Shared shape of q33/q56: per-channel revenue for a filtered item set in
    one month, restricted to ca_gmt_offset = -5, summed across channels."""
    from daft_tpu import col

    wanted = (t["item"].where(item_filter).select(key_col).distinct())
    dd = t["date_dim"].where((col("d_year") == d_year) & (col("d_moy") == d_moy))
    ca = t["customer_address"].where(col("ca_gmt_offset") == -5.0)

    def channel(fact: str, prefix: str):
        return (t[fact]
                .join(dd, left_on=f"{prefix}_sold_date_sk", right_on="d_date_sk")
                .join(ca, left_on=(f"{prefix}_addr_sk" if prefix == "ss"
                                   else f"{prefix}_bill_addr_sk"),
                      right_on="ca_address_sk")
                .join(t["item"], left_on=f"{prefix}_item_sk", right_on="i_item_sk")
                .join(wanted, left_on=key_col, right_on=key_col, how="semi")
                .groupby(key_col)
                .agg(col(f"{prefix}_ext_sales_price").sum().alias("total_sales")))

    ss = channel("store_sales", "ss")
    cs = channel("catalog_sales", "cs")
    ws = channel("web_sales", "ws")
    return (ss.concat(cs).concat(ws)
            .groupby(key_col)
            .agg(col("total_sales").sum().alias("total_sales"))
            .sort(["total_sales", key_col])
            .limit(100))


def q33(t):
    """queries/33.sql: Electronics revenue by manufacturer across all three
    sales channels, May 1998."""
    from daft_tpu import col

    return _three_channel_total(t, "i_manufact_id",
                                col("i_category") == "Electronics", 1998, 5)


def q56(t):
    """queries/56.sql: colored-item revenue by item id across all three
    sales channels, Feb 2001."""
    from daft_tpu import col

    return _three_channel_total(
        t, "i_item_id",
        col("i_color").is_in(["slate", "blanched", "burnished"]), 2001, 2)


ALL_QUERIES[33] = q33
ALL_QUERIES[56] = q56


# ======================================================================================
# round-5 expansion: window/rollup-heavy + report shapes (VERDICT r4 next #9)
# ======================================================================================


def q6(t):
    """queries/06.sql: states with >= 10 customers who bought items priced at
    1.2x their category's average, for one month."""
    from daft_tpu import col, lit

    target = (t["date_dim"]
              .where((col("d_year") == 2001) & (col("d_moy") == 1))
              .select("d_month_seq").distinct())
    cat_avg = (t["item"].groupby("i_category")
               .agg(col("i_current_price").mean().alias("cat_avg")))
    pricey = (t["item"].join(cat_avg, on="i_category")
              .where(col("i_current_price") > 1.2 * col("cat_avg"))
              .select("i_item_sk"))
    return (t["store_sales"]
            .join(t["date_dim"], left_on="ss_sold_date_sk", right_on="d_date_sk")
            .join(target, on="d_month_seq", how="semi")
            .join(pricey, left_on="ss_item_sk", right_on="i_item_sk", how="semi")
            .join(t["customer"], left_on="ss_customer_sk", right_on="c_customer_sk")
            .join(t["customer_address"], left_on="c_current_addr_sk",
                  right_on="ca_address_sk")
            .groupby(col("ca_state").alias("state"))
            .agg(col("ca_state").count().alias("cnt"))
            .where(col("cnt") >= 10)
            .sort(["cnt", "state"])
            .limit(100))


def _channel_class_ratio(t, fact: str, prefix: str, categories, lo, hi):
    """Shared q12/q20/q98 shape: per-item revenue + 100 * revenue / class
    total (window sum over i_class) for a 30-day window."""
    import datetime

    from daft_tpu import Window, col

    w = Window().partition_by("i_class")
    return (t[fact]
            .join(t["item"].where(col("i_category").is_in(categories)),
                  left_on=f"{prefix}_item_sk", right_on="i_item_sk")
            .join(t["date_dim"].where(
                col("d_date").between(datetime.date(*lo), datetime.date(*hi))),
                  left_on=f"{prefix}_sold_date_sk", right_on="d_date_sk")
            .groupby("i_item_id", "i_class", "i_category", "i_current_price")
            .agg(col(f"{prefix}_ext_sales_price").sum().alias("itemrevenue"))
            .with_column("revenueratio",
                         col("itemrevenue") * 100.0
                         / col("itemrevenue").sum().over(w))
            .sort(["i_category", "i_class", "i_item_id", "revenueratio"])
            .limit(100))


def q12(t):
    """queries/12.sql: web revenue share of class, 30 days from 1999-02-22."""
    return _channel_class_ratio(t, "web_sales", "ws",
                                ["Sports", "Books", "Home"],
                                (1999, 2, 22), (1999, 3, 24))


def q20(t):
    """queries/20.sql: catalog revenue share of class, 30 days."""
    return _channel_class_ratio(t, "catalog_sales", "cs",
                                ["Sports", "Books", "Home"],
                                (1999, 2, 22), (1999, 3, 24))


def q98(t):
    """queries/98.sql: store revenue share of class, 30 days."""
    return _channel_class_ratio(t, "store_sales", "ss",
                                ["Sports", "Books", "Home"],
                                (1999, 2, 22), (1999, 3, 24))


def q27(t):
    """queries/27.sql: demographic slice averages with ROLLUP(i_item_id,
    s_state) — emulated as the union of the three grouping levels."""
    from daft_tpu import col, lit

    base = (t["store_sales"]
            .join(t["customer_demographics"].where(
                (col("cd_gender") == "M") & (col("cd_marital_status") == "S")
                & (col("cd_education_status") == "College")),
                  left_on="ss_cdemo_sk", right_on="cd_demo_sk")
            .join(t["date_dim"].where(col("d_year") == 2002),
                  left_on="ss_sold_date_sk", right_on="d_date_sk")
            .join(t["store"].where(col("s_state").is_in(
                ["TN", "GA", "AL", "SC", "NC", "KY"])),
                  left_on="ss_store_sk", right_on="s_store_sk")
            .join(t["item"], left_on="ss_item_sk", right_on="i_item_sk"))

    def level(gb):
        aggs = (col("ss_quantity").mean().alias("agg1"),
                col("ss_list_price").mean().alias("agg2"),
                col("ss_coupon_amt").mean().alias("agg3"),
                col("ss_sales_price").mean().alias("agg4"))
        if gb == 2:
            return base.groupby("i_item_id", "s_state").agg(*aggs)
        if gb == 1:
            return (base.groupby("i_item_id").agg(*aggs)
                    .with_column("s_state", lit(None).cast(_DT.string()))
                    .select("i_item_id", "s_state", "agg1", "agg2", "agg3", "agg4"))
        return (base.agg(*aggs)
                .with_column("i_item_id", lit(None).cast(_DT.string()))
                .with_column("s_state", lit(None).cast(_DT.string()))
                .select("i_item_id", "s_state", "agg1", "agg2", "agg3", "agg4"))

    return (level(2).concat(level(1)).concat(level(0))
            .sort(["i_item_id", "s_state"])
            .limit(100))


def q36(t):
    """queries/36.sql: gross-margin ratio over ROLLUP(i_category, i_class)
    with a rank within each hierarchy level."""
    from daft_tpu import Window, col, lit

    base = (t["store_sales"]
            .join(t["date_dim"].where(col("d_year") == 2001),
                  left_on="ss_sold_date_sk", right_on="d_date_sk")
            .join(t["item"], left_on="ss_item_sk", right_on="i_item_sk")
            .join(t["store"].where(col("s_state").is_in(
                ["TN", "GA", "AL", "SC", "NC", "KY", "VA", "FL"])),
                  left_on="ss_store_sk", right_on="s_store_sk"))

    def level(gb):
        aggs = (col("ss_net_profit").sum().alias("np"),
                col("ss_ext_sales_price").sum().alias("esp"))
        if gb == 2:
            out = base.groupby("i_category", "i_class").agg(*aggs) \
                .with_column("lochierarchy", lit(0))
        elif gb == 1:
            out = (base.groupby("i_category").agg(*aggs)
                   .with_column("i_class", lit(None).cast(_DT.string()))
                   .with_column("lochierarchy", lit(1)))
        else:
            out = (base.agg(*aggs)
                   .with_column("i_category", lit(None).cast(_DT.string()))
                   .with_column("i_class", lit(None).cast(_DT.string()))
                   .with_column("lochierarchy", lit(2)))
        return out.select("i_category", "i_class", "lochierarchy", "np", "esp")

    w = (Window()
         .partition_by("lochierarchy", "parent")
         .order_by("gross_margin", desc=False))
    from daft_tpu.functions import rank

    return (level(2).concat(level(1)).concat(level(0))
            .with_column("gross_margin", col("np") / col("esp"))
            .with_column("parent",
                         (col("lochierarchy") == 0).if_else(col("i_category"),
                                                            lit(None).cast(_DT.string())))
            .with_column("rank_within_parent", rank().over(w))
            .select("gross_margin", "i_category", "i_class", "lochierarchy",
                    "rank_within_parent")
            .sort(["lochierarchy", "i_category", "rank_within_parent"],
                  desc=[True, False, False])
            .limit(100))


def q43(t):
    """queries/43.sql: per-store weekday sales pivot for one year."""
    from daft_tpu import col

    def day(name, alias):
        return ((col("d_day_name") == name)
                .if_else(col("ss_sales_price"), 0.0)).sum().alias(alias)

    return (t["store_sales"]
            .join(t["date_dim"].where(col("d_year") == 2000),
                  left_on="ss_sold_date_sk", right_on="d_date_sk")
            .join(t["store"].where(col("s_gmt_offset") == -5.0),
                  left_on="ss_store_sk", right_on="s_store_sk")
            .groupby("s_store_name", "s_store_id")
            .agg(day("Sunday", "sun_sales"), day("Monday", "mon_sales"),
                 day("Tuesday", "tue_sales"), day("Wednesday", "wed_sales"),
                 day("Thursday", "thu_sales"), day("Friday", "fri_sales"),
                 day("Saturday", "sat_sales"))
            .sort(["s_store_name", "s_store_id"])
            .limit(100))


def q48(t):
    """queries/48.sql: quantity sum under OR-of-AND demographic/address/price
    bands."""
    from daft_tpu import col

    cd_ok = (((col("cd_marital_status") == "M")
              & (col("cd_education_status") == "4 yr Degree")
              & col("ss_sales_price").between(100.0, 150.0))
             | ((col("cd_marital_status") == "D")
                & (col("cd_education_status") == "2 yr Degree")
                & col("ss_sales_price").between(50.0, 100.0))
             | ((col("cd_marital_status") == "S")
                & (col("cd_education_status") == "College")
                & col("ss_sales_price").between(150.0, 200.0)))
    ca_ok = ((col("ca_country") == "United States")
             & ((col("ca_state").is_in(["TN", "GA", "AL"])
                 & col("ss_net_profit").between(0.0, 2000.0))
                | (col("ca_state").is_in(["SC", "NC", "KY"])
                   & col("ss_net_profit").between(150.0, 3000.0))
                | (col("ca_state").is_in(["VA", "FL", "MS"])
                   & col("ss_net_profit").between(50.0, 25000.0))))
    return (t["store_sales"]
            .join(t["store"], left_on="ss_store_sk", right_on="s_store_sk")
            .join(t["customer_demographics"], left_on="ss_cdemo_sk",
                  right_on="cd_demo_sk")
            .join(t["customer_address"], left_on="ss_addr_sk",
                  right_on="ca_address_sk")
            .join(t["date_dim"].where(col("d_year") == 2000),
                  left_on="ss_sold_date_sk", right_on="d_date_sk")
            .where(cd_ok & ca_ok)
            .agg(col("ss_quantity").sum().alias("total_quantity")))


def q51(t):
    """queries/51.sql: items whose web cumulative revenue overtakes their
    store cumulative revenue (windowed running sums over a FULL OUTER join)."""
    from daft_tpu import Window, col

    months = (t["date_dim"].where(col("d_month_seq").between(1200, 1211))
              .select("d_date_sk", "d_date"))
    web = (t["web_sales"].join(months, left_on="ws_sold_date_sk",
                               right_on="d_date_sk")
           .groupby(col("ws_item_sk").alias("item_sk"), "d_date")
           .agg(col("ws_ext_sales_price").sum().alias("daily")))
    store = (t["store_sales"].join(months, left_on="ss_sold_date_sk",
                                   right_on="d_date_sk")
             .groupby(col("ss_item_sk").alias("item_sk"), "d_date")
             .agg(col("ss_ext_sales_price").sum().alias("daily")))
    wrun = Window().partition_by("item_sk").order_by("d_date") \
        .rows_between(Window.unbounded_preceding, Window.current_row)
    web = web.with_column("cume", col("daily").sum().over(wrun)) \
        .select("item_sk", "d_date", "cume")
    store = store.with_column("cume", col("daily").sum().over(wrun)) \
        .select("item_sk", "d_date", "cume")
    j = web.join(store, on=["item_sk", "d_date"], how="outer",
                 suffix="_ss")
    wmax = Window().partition_by("item_sk").order_by("d_date") \
        .rows_between(Window.unbounded_preceding, Window.current_row)
    j = (j.with_column("web_cumulative", col("cume").max().over(wmax))
         .with_column("store_cumulative", col("cume_ss").max().over(wmax)))
    return (j.where(col("web_cumulative") > col("store_cumulative"))
            .select("item_sk", "d_date", "web_cumulative", "store_cumulative")
            .sort(["item_sk", "d_date"])
            .limit(100))


def q59(t):
    """queries/59.sql: week-over-year weekly sales ratio per store (two
    pivoted half-years joined on week_seq - 52)."""
    from daft_tpu import col

    def day(name, alias):
        return ((col("d_day_name") == name)
                .if_else(col("ss_sales_price"), 0.0)).sum().alias(alias)

    wss = (t["store_sales"]
           .join(t["date_dim"], left_on="ss_sold_date_sk", right_on="d_date_sk")
           .groupby("d_week_seq", "ss_store_sk")
           .agg(day("Sunday", "sun"), day("Monday", "mon"), day("Tuesday", "tue"),
                day("Wednesday", "wed"), day("Thursday", "thu"),
                day("Friday", "fri"), day("Saturday", "sat")))
    weeks1 = (t["date_dim"].where(col("d_month_seq").between(1176, 1187))
              .select("d_week_seq").distinct())
    weeks2 = (t["date_dim"].where(col("d_month_seq").between(1188, 1199))
              .select("d_week_seq").distinct())
    y = (wss.join(weeks1, on="d_week_seq", how="semi")
         .join(t["store"], left_on="ss_store_sk", right_on="s_store_sk")
         .select("s_store_name", "s_store_id", "d_week_seq", "sun", "mon",
                 "tue", "wed", "thu", "fri", "sat"))
    y2 = (wss.join(weeks2, on="d_week_seq", how="semi")
          .join(t["store"], left_on="ss_store_sk", right_on="s_store_sk")
          .with_column("d_week_seq", col("d_week_seq") - 52)
          .select("s_store_id", "d_week_seq", col("sun").alias("sun2"),
                  col("mon").alias("mon2"), col("tue").alias("tue2"),
                  col("wed").alias("wed2"), col("thu").alias("thu2"),
                  col("fri").alias("fri2"), col("sat").alias("sat2")))
    j = y.join(y2, on=["s_store_id", "d_week_seq"])
    return (j.select(
        "s_store_name", "s_store_id", "d_week_seq",
        (col("sun") / col("sun2")).alias("r_sun"),
        (col("mon") / col("mon2")).alias("r_mon"),
        (col("tue") / col("tue2")).alias("r_tue"),
        (col("wed") / col("wed2")).alias("r_wed"),
        (col("thu") / col("thu2")).alias("r_thu"),
        (col("fri") / col("fri2")).alias("r_fri"),
        (col("sat") / col("sat2")).alias("r_sat"))
        .sort(["s_store_name", "s_store_id", "d_week_seq"])
        .limit(100))


def q63(t):
    """queries/63.sql: manager monthly sales vs their 12-month average."""
    from daft_tpu import Window, col

    items = t["item"].where(
        ((col("i_category").is_in(["Books", "Children", "Electronics"])
          & col("i_class").is_in(["accent", "classical", "fiction"]))
         | (col("i_category").is_in(["Women", "Music", "Men"])
            & col("i_class").is_in(["dresses", "rock", "pants"]))))
    w = Window().partition_by("i_manager_id")
    return (t["store_sales"]
            .join(items, left_on="ss_item_sk", right_on="i_item_sk")
            .join(t["date_dim"].where(col("d_year") == 2000),
                  left_on="ss_sold_date_sk", right_on="d_date_sk")
            .join(t["store"], left_on="ss_store_sk", right_on="s_store_sk")
            .groupby("i_manager_id", "d_moy")
            .agg(col("ss_sales_price").sum().alias("sum_sales"))
            .with_column("avg_monthly_sales",
                         col("sum_sales").mean().over(w))
            .where((col("avg_monthly_sales") > 0)
                   & ((col("sum_sales") - col("avg_monthly_sales")).abs()
                      / col("avg_monthly_sales") > 0.1))
            .select("i_manager_id", "sum_sales", "avg_monthly_sales")
            .sort(["i_manager_id", "avg_monthly_sales", "sum_sales"])
            .limit(100))


def q65(t):
    """queries/65.sql: store items selling at <= 10% of the store's average
    item revenue."""
    from daft_tpu import col

    months = (t["date_dim"].where(col("d_month_seq").between(1176, 1187))
              .select("d_date_sk"))
    sales = (t["store_sales"]
             .join(months, left_on="ss_sold_date_sk", right_on="d_date_sk",
                   how="semi")
             .groupby("ss_store_sk", "ss_item_sk")
             .agg(col("ss_sales_price").sum().alias("revenue")))
    store_avg = (sales.groupby("ss_store_sk")
                 .agg(col("revenue").mean().alias("ave")))
    return (sales.join(store_avg, on="ss_store_sk")
            .where(col("revenue") <= 0.1 * col("ave"))
            .join(t["store"], left_on="ss_store_sk", right_on="s_store_sk")
            .join(t["item"], left_on="ss_item_sk", right_on="i_item_sk")
            .select("s_store_name", "i_item_id", "revenue")
            .sort(["s_store_name", "i_item_id"])
            .limit(100))


def q73(t):
    """queries/73.sql: customers with 1-5 items per ticket under household
    constraints."""
    from daft_tpu import col

    hd = t["household_demographics"].where(
        col("hd_buy_potential").is_in([">10000", "Unknown"])
        & (col("hd_vehicle_count") > 0)
        & (col("hd_dep_count").cast(_DT.float64()) / col("hd_vehicle_count") > 1.0))
    tickets = (t["store_sales"]
               .join(t["date_dim"].where(
                   col("d_dom").between(1, 2)
                   & col("d_year").is_in([1999, 2000, 2001])),
                     left_on="ss_sold_date_sk", right_on="d_date_sk")
               .join(hd, left_on="ss_hdemo_sk", right_on="hd_demo_sk")
               .join(t["store"].where(
                   col("s_county").is_in(["Williamson County", "Franklin Parish"])),
                     left_on="ss_store_sk", right_on="s_store_sk")
               .groupby("ss_ticket_number", "ss_customer_sk")
               .agg(col("ss_ticket_number").count().alias("cnt"))
               .where(col("cnt").between(1, 5)))
    return (tickets.join(t["customer"], left_on="ss_customer_sk",
                         right_on="c_customer_sk")
            .select("c_last_name", "c_first_name", "ss_ticket_number", "cnt")
            .sort(["cnt", "c_last_name", "ss_ticket_number"],
                  desc=[True, False, False])
            .limit(100))


def q79(t):
    """queries/79.sql: per-ticket profit/coupon for Monday shoppers at
    mid-size stores."""
    from daft_tpu import col

    hd = t["household_demographics"].where(
        (col("hd_dep_count") == 6) | (col("hd_vehicle_count") > 2))
    tickets = (t["store_sales"]
               .join(t["date_dim"].where(
                   (col("d_dow") == 1) & col("d_year").is_in([1999, 2000, 2001])),
                     left_on="ss_sold_date_sk", right_on="d_date_sk")
               .join(t["store"].where(col("s_number_employees").between(200, 295)),
                     left_on="ss_store_sk", right_on="s_store_sk")
               .join(hd, left_on="ss_hdemo_sk", right_on="hd_demo_sk")
               .groupby("ss_ticket_number", "ss_customer_sk", "s_city")
               .agg(col("ss_coupon_amt").sum().alias("amt"),
                    col("ss_net_profit").sum().alias("profit")))
    return (tickets.join(t["customer"], left_on="ss_customer_sk",
                         right_on="c_customer_sk")
            .select("c_last_name", "c_first_name", "s_city", "profit",
                    "ss_ticket_number", "amt")
            .sort(["c_last_name", "c_first_name", "s_city", "profit",
                   "ss_ticket_number"])
            .limit(100))


def q88(t):
    """queries/88.sql: store traffic in eight half-hour slots (cross-joined
    scalar counts)."""
    from daft_tpu import col

    hd = t["household_demographics"].where(
        ((col("hd_dep_count") == 4) & (col("hd_vehicle_count") <= 6))
        | ((col("hd_dep_count") == 2) & (col("hd_vehicle_count") <= 4))
        | ((col("hd_dep_count") == 0) & (col("hd_vehicle_count") <= 2)))
    base = (t["store_sales"]
            .join(hd, left_on="ss_hdemo_sk", right_on="hd_demo_sk")
            .join(t["store"].where(col("s_store_name") == "ese"),
                  left_on="ss_store_sk", right_on="s_store_sk"))

    def slot(h, half, alias):
        td = t["time_dim"].where(
            (col("t_hour") == h)
            & (col("t_minute") >= 30 if half else col("t_minute") < 30))
        return (base.join(td, left_on="ss_sold_time_sk", right_on="t_time_sk")
                .agg(col("ss_sold_time_sk").count().alias(alias)))

    out = slot(8, True, "h8_30_to_9")
    for h, half, alias in [(9, False, "h9_to_9_30"), (9, True, "h9_30_to_10"),
                           (10, False, "h10_to_10_30"), (10, True, "h10_30_to_11"),
                           (11, False, "h11_to_11_30"), (11, True, "h11_30_to_12"),
                           (12, False, "h12_to_12_30")]:
        out = out.join(slot(h, half, alias), how="cross")
    return out


def q89(t):
    """queries/89.sql: store-month class sales deviating from the yearly
    average (window avg over item/store partitions)."""
    from daft_tpu import Window, col

    items = t["item"].where(
        ((col("i_category").is_in(["Books", "Electronics", "Sports"])
          & col("i_class").is_in(["fiction", "portable", "rock"]))
         | (col("i_category").is_in(["Men", "Jewelry", "Women"])
            & col("i_class").is_in(["accent", "pants", "dresses"]))))
    w = Window().partition_by("i_category", "i_brand", "s_store_name",
                              "s_company_name")
    out = (t["store_sales"]
           .join(items, left_on="ss_item_sk", right_on="i_item_sk")
           .join(t["date_dim"].where(col("d_year") == 1999),
                 left_on="ss_sold_date_sk", right_on="d_date_sk")
           .join(t["store"], left_on="ss_store_sk", right_on="s_store_sk")
           .groupby("i_category", "i_class", "i_brand", "s_store_name",
                    "s_company_name", "d_moy")
           .agg(col("ss_sales_price").sum().alias("sum_sales"))
           .with_column("avg_monthly_sales", col("sum_sales").mean().over(w)))
    return (out.where(
        (col("avg_monthly_sales") != 0)
        & ((col("sum_sales") - col("avg_monthly_sales")).abs()
           / col("avg_monthly_sales") > 0.1))
        .select("i_category", "i_class", "i_brand", "s_store_name",
                "s_company_name", "d_moy", "sum_sales", "avg_monthly_sales")
        .sort(["sum_sales", "s_store_name"], desc=[False, False])
        .limit(100))


for _n, _q in [(6, q6), (12, q12), (20, q20), (27, q27), (36, q36), (43, q43),
               (48, q48), (51, q51), (59, q59), (63, q63), (65, q65), (73, q73),
               (79, q79), (88, q88), (89, q89), (98, q98)]:
    ALL_QUERIES[_n] = _q
