"""TPC-DS data generation (synthetic, dsdgen-free) for the store_sales channel.

Reference parity: benchmarking/tpcds/datagen.py (which shells out to DuckDB's
dsdgen). Here the store_sales star (fact + 8 dimensions) is synthesized with
deterministic numpy RNG following the public TPC-DS schema and value domains —
row counts scale with SF like the spec (store_sales ~= 2.88M rows * SF,
item 18k, customer 100k, store 12/SF1). Not bit-identical to dsdgen, but
schema- and distribution-faithful enough for correctness cross-checks (the
tests recompute every query in pandas) and throughput benchmarks.
"""

from __future__ import annotations

import datetime
import os
from typing import Dict

import numpy as np
import pyarrow as pa

EPOCH = datetime.date(1970, 1, 1)

CATEGORIES = ["Books", "Children", "Electronics", "Home", "Jewelry",
              "Men", "Music", "Shoes", "Sports", "Women"]
CLASSES = ["accent", "classical", "dresses", "fiction", "fragrances",
           "infants", "pants", "portable", "reference", "rock"]
GENDERS = ["M", "F"]
MARITAL = ["M", "S", "D", "W", "U"]
EDUCATION = ["Primary", "Secondary", "College", "2 yr Degree", "4 yr Degree",
             "Advanced Degree", "Unknown"]
BUY_POTENTIAL = [">10000", "5001-10000", "1001-5000", "501-1000", "0-500", "Unknown"]
CREDIT = ["Low Risk", "High Risk", "Good", "Unknown"]
STORE_NAMES = ["ought", "able", "ese", "anti", "cally", "ation", "eing", "bar"]
STATES = ["TN", "GA", "AL", "SC", "NC", "KY", "VA", "FL", "MS", "LA"]
CHANNELS = ["N", "Y"]


def _money(rng, n, lo=0.5, hi=200.0):
    return np.round(rng.uniform(lo, hi, n), 2)


def generate(sf: float = 0.01, seed: int = 0) -> Dict[str, pa.Table]:
    rng = np.random.default_rng(seed)

    # ---- date_dim: 1998-01-01 .. 2002-12-31 (TPC-DS's active window) ------------
    d0 = datetime.date(1998, 1, 1)
    n_dates = (datetime.date(2002, 12, 31) - d0).days + 1
    dates = [d0 + datetime.timedelta(days=i) for i in range(n_dates)]
    # d_month_seq/d_week_seq count months/weeks from 1900/1970 — absolute
    # values only matter for range filters, which the queries state in the
    # same coordinates
    date_dim = pa.table({
        "d_date_sk": pa.array(np.arange(n_dates, dtype=np.int64) + 2_450_000),
        "d_date": pa.array(dates, pa.date32()),
        "d_year": pa.array(np.array([d.year for d in dates], np.int32)),
        "d_moy": pa.array(np.array([d.month for d in dates], np.int32)),
        "d_dom": pa.array(np.array([d.day for d in dates], np.int32)),
        "d_qoy": pa.array(np.array([(d.month - 1) // 3 + 1 for d in dates], np.int32)),
        "d_day_name": pa.array([d.strftime("%A") for d in dates]),
        "d_month_seq": pa.array(np.array(
            [(d.year - 1900) * 12 + d.month - 1 for d in dates], np.int32)),
        "d_week_seq": pa.array(np.array(
            [((d - EPOCH).days + 3) // 7 for d in dates], np.int32)),
        "d_dow": pa.array(np.array([d.isoweekday() % 7 for d in dates], np.int32)),
    })

    # ---- time_dim: 86400 seconds ------------------------------------------------
    secs = np.arange(86400, dtype=np.int64)
    time_dim = pa.table({
        "t_time_sk": secs,
        "t_hour": (secs // 3600).astype(np.int32),
        "t_minute": ((secs // 60) % 60).astype(np.int32),
        "t_second": (secs % 60).astype(np.int32),
    })

    # ---- item -------------------------------------------------------------------
    # dsdgen keeps item at 18k for SF1 and never below 2k at tiny SFs (the
    # dimension does not scale linearly with the fact table)
    n_item = max(int(18_000 * min(sf, 1.0) + 2_000 * max(sf - 1.0, 0)), 2_000)
    isk = np.arange(n_item, dtype=np.int64) + 1
    brand_id = rng.integers(1, 1001, n_item).astype(np.int32)
    cat_id = rng.integers(0, len(CATEGORIES), n_item)
    class_id = rng.integers(0, len(CLASSES), n_item)
    item = pa.table({
        "i_item_sk": isk,
        "i_item_id": pa.array([f"AAAAAAAA{k:08d}" for k in isk]),
        "i_brand_id": brand_id,
        "i_brand": pa.array([f"brand#{b}" for b in brand_id]),
        "i_class_id": pa.array((class_id + 1).astype(np.int32)),
        "i_class": pa.array([CLASSES[c] for c in class_id]),
        "i_category_id": pa.array((cat_id + 1).astype(np.int32)),
        "i_category": pa.array([CATEGORIES[c] for c in cat_id]),
        "i_color": pa.array([["slate","blanched","burnished","powder","ghost",
                              "peach","salmon","mint","azure","rose"][i]
                             for i in rng.integers(0, 10, n_item)]),
        "i_manufact_id": rng.integers(1, 1001, n_item).astype(np.int32),
        "i_manager_id": rng.integers(1, 101, n_item).astype(np.int32),
        "i_current_price": _money(rng, n_item, 0.09, 99.99),
    })

    # ---- customer_demographics (fixed 1.92M in spec; scaled down) ---------------
    n_cd = max(int(19_200 * max(sf, 0.01)), 500)
    cd = pa.table({
        "cd_demo_sk": np.arange(n_cd, dtype=np.int64) + 1,
        "cd_gender": pa.array([GENDERS[i] for i in rng.integers(0, 2, n_cd)]),
        "cd_marital_status": pa.array([MARITAL[i] for i in rng.integers(0, len(MARITAL), n_cd)]),
        "cd_education_status": pa.array([EDUCATION[i] for i in rng.integers(0, len(EDUCATION), n_cd)]),
        "cd_purchase_estimate": rng.integers(500, 10_000, n_cd).astype(np.int32),
        "cd_credit_rating": pa.array([CREDIT[i] for i in rng.integers(0, len(CREDIT), n_cd)]),
        "cd_dep_count": rng.integers(0, 7, n_cd).astype(np.int32),
    })

    # ---- household_demographics -------------------------------------------------
    n_hd = 7_200
    hd = pa.table({
        "hd_demo_sk": np.arange(n_hd, dtype=np.int64) + 1,
        "hd_income_band_sk": rng.integers(1, 21, n_hd).astype(np.int64),
        "hd_buy_potential": pa.array([BUY_POTENTIAL[i] for i in rng.integers(0, len(BUY_POTENTIAL), n_hd)]),
        "hd_dep_count": rng.integers(0, 10, n_hd).astype(np.int32),
        "hd_vehicle_count": rng.integers(-1, 5, n_hd).astype(np.int32),
    })

    # ---- customer_address --------------------------------------------------------
    n_ca = max(int(50_000 * sf), 200)
    zips = rng.integers(10_000, 99_999, n_ca)
    ca = pa.table({
        "ca_address_sk": np.arange(n_ca, dtype=np.int64) + 1,
        "ca_city": pa.array([f"city_{i}" for i in rng.integers(0, 600, n_ca)]),
        "ca_state": pa.array([STATES[i] for i in rng.integers(0, len(STATES), n_ca)]),
        "ca_zip": pa.array([f"{z:05d}" for z in zips]),
        "ca_country": pa.array(["United States"] * n_ca),
        "ca_gmt_offset": np.full(n_ca, -5.0),
    })

    # ---- customer ----------------------------------------------------------------
    n_cust = max(int(100_000 * sf), 300)
    csk = np.arange(n_cust, dtype=np.int64) + 1
    customer = pa.table({
        "c_customer_sk": csk,
        "c_customer_id": pa.array([f"AAAAAAAA{k:08d}" for k in csk]),
        "c_current_cdemo_sk": rng.integers(1, n_cd + 1, n_cust).astype(np.int64),
        "c_current_hdemo_sk": rng.integers(1, n_hd + 1, n_cust).astype(np.int64),
        "c_current_addr_sk": rng.integers(1, n_ca + 1, n_cust).astype(np.int64),
        "c_first_name": pa.array([f"first{i}" for i in rng.integers(0, 5_000, n_cust)]),
        "c_last_name": pa.array([f"last{i}" for i in rng.integers(0, 6_000, n_cust)]),
        "c_birth_year": rng.integers(1924, 1993, n_cust).astype(np.int32),
    })

    # ---- store -------------------------------------------------------------------
    n_store = max(int(12 * max(sf, 0.25)), 3)
    szips = rng.integers(10_000, 99_999, n_store)
    counties = ["Williamson County", "Franklin Parish", "Walker County",
                "Ziebach County", "Daviess County"]
    store = pa.table({
        "s_store_sk": np.arange(n_store, dtype=np.int64) + 1,
        "s_store_id": pa.array([f"AAAAAAAA{k:08d}" for k in range(1, n_store + 1)]),
        "s_store_name": pa.array([STORE_NAMES[i % len(STORE_NAMES)] for i in range(n_store)]),
        "s_state": pa.array([STATES[i] for i in rng.integers(0, len(STATES), n_store)]),
        "s_county": pa.array([counties[i % len(counties)] for i in range(n_store)]),
        "s_city": pa.array([["Midway", "Fairview", "Oak Grove", "Five Points",
                             "Centerville"][i % 5] for i in range(n_store)]),
        "s_company_name": pa.array(["Unknown"] * n_store),
        "s_number_employees": rng.integers(200, 301, n_store).astype(np.int32),
        "s_zip": pa.array([f"{z:05d}" for z in szips]),
        "s_gmt_offset": np.full(n_store, -5.0),
    })

    # ---- promotion ---------------------------------------------------------------
    n_promo = max(int(300 * max(sf, 0.1)), 30)
    promotion = pa.table({
        "p_promo_sk": np.arange(n_promo, dtype=np.int64) + 1,
        "p_promo_id": pa.array([f"AAAAAAAA{k:08d}" for k in range(1, n_promo + 1)]),
        "p_channel_email": pa.array([CHANNELS[i] for i in rng.integers(0, 2, n_promo)]),
        "p_channel_event": pa.array([CHANNELS[i] for i in rng.integers(0, 2, n_promo)]),
        "p_channel_tv": pa.array([CHANNELS[i] for i in rng.integers(0, 2, n_promo)]),
    })

    # ---- store_sales fact --------------------------------------------------------
    n_ss = int(2_880_000 * sf)
    qty = rng.integers(1, 101, n_ss).astype(np.int32)
    list_price = _money(rng, n_ss, 1.0, 200.0)
    sales_price = np.round(list_price * rng.uniform(0.2, 1.0, n_ss), 2)
    wholesale = np.round(list_price * rng.uniform(0.3, 0.7, n_ss), 2)
    store_sales = pa.table({
        "ss_sold_date_sk": (rng.integers(0, n_dates, n_ss) + 2_450_000).astype(np.int64),
        "ss_sold_time_sk": rng.integers(0, 86_400, n_ss).astype(np.int64),
        "ss_item_sk": rng.integers(1, n_item + 1, n_ss).astype(np.int64),
        "ss_customer_sk": rng.integers(1, n_cust + 1, n_ss).astype(np.int64),
        "ss_cdemo_sk": rng.integers(1, n_cd + 1, n_ss).astype(np.int64),
        "ss_hdemo_sk": rng.integers(1, n_hd + 1, n_ss).astype(np.int64),
        "ss_addr_sk": rng.integers(1, n_ca + 1, n_ss).astype(np.int64),
        "ss_store_sk": rng.integers(1, n_store + 1, n_ss).astype(np.int64),
        "ss_promo_sk": rng.integers(1, n_promo + 1, n_ss).astype(np.int64),
        "ss_ticket_number": rng.integers(1, max(n_ss // 10, 2), n_ss).astype(np.int64),
        "ss_quantity": qty,
        "ss_wholesale_cost": wholesale,
        "ss_list_price": list_price,
        "ss_sales_price": sales_price,
        "ss_coupon_amt": np.round(rng.uniform(0, 500, n_ss) * (rng.random(n_ss) < 0.2), 2),
        "ss_ext_sales_price": np.round(sales_price * qty, 2),
        "ss_ext_list_price": np.round(list_price * qty, 2),
        "ss_ext_wholesale_cost": np.round(wholesale * qty, 2),
        "ss_net_profit": np.round((sales_price - wholesale) * qty, 2),
    })

    # ---- catalog_sales / web_sales facts (the other two sales channels;
    # ~1.44M / ~0.72M rows per SF like the spec's 2:1:0.5 channel ratios) ------
    def _channel(prefix: str, n_rows: int) -> pa.Table:
        q = rng.integers(1, 101, n_rows).astype(np.int32)
        lp = _money(rng, n_rows, 1.0, 200.0)
        sp = np.round(lp * rng.uniform(0.2, 1.0, n_rows), 2)
        return pa.table({
            f"{prefix}_sold_date_sk": (rng.integers(0, n_dates, n_rows)
                                       + 2_450_000).astype(np.int64),
            f"{prefix}_item_sk": rng.integers(1, n_item + 1, n_rows).astype(np.int64),
            f"{prefix}_bill_customer_sk": rng.integers(1, n_cust + 1, n_rows).astype(np.int64),
            f"{prefix}_bill_addr_sk": rng.integers(1, n_ca + 1, n_rows).astype(np.int64),
            f"{prefix}_quantity": q,
            f"{prefix}_list_price": lp,
            f"{prefix}_sales_price": sp,
            f"{prefix}_ext_sales_price": np.round(sp * q, 2),
        })

    catalog_sales = _channel("cs", int(1_440_000 * sf))
    web_sales = _channel("ws", int(720_000 * sf))

    return {
        "date_dim": date_dim, "time_dim": time_dim, "item": item,
        "customer_demographics": cd, "household_demographics": hd,
        "customer_address": ca, "customer": customer, "store": store,
        "promotion": promotion, "store_sales": store_sales,
        "catalog_sales": catalog_sales, "web_sales": web_sales,
    }


_CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_cache")


def cached_tables(sf: float = 0.01, seed: int = 0) -> Dict[str, pa.Table]:
    """Parquet-cached generation (same scheme as benchmarking/tpch/datagen.py)."""
    import pyarrow.parquet as pq

    key = f"sf{sf}_seed{seed}_v2"  # v2: d_month_seq/d_week_seq/d_dow + s_county/s_number_employees
    d = os.path.join(_CACHE_DIR, key)
    names = ["date_dim", "time_dim", "item", "customer_demographics",
             "household_demographics", "customer_address", "customer", "store",
             "promotion", "store_sales", "catalog_sales", "web_sales"]
    if os.path.isdir(d) and all(
            os.path.exists(os.path.join(d, f"{n}.parquet")) for n in names):
        return {n: pq.read_table(os.path.join(d, f"{n}.parquet")) for n in names}
    tables = generate(sf, seed)
    os.makedirs(d, exist_ok=True)
    for n, t in tables.items():
        pq.write_table(t, os.path.join(d, f"{n}.parquet"))
    return tables


def load_dataframes(sf: float = 0.01, seed: int = 0):
    """Tables as in-memory daft_tpu DataFrames."""
    import daft_tpu as dt

    return {name: dt.from_arrow(t) for name, t in cached_tables(sf, seed).items()}
