# Developer/CI entry points. The perf gate compares a fresh bench capture
# against the newest committed BENCH_r*.json and fails loudly on >5% per-query
# regressions (bench.py --compare).

PY ?= python
LATEST_BENCH := $(shell ls BENCH_r*.json 2>/dev/null | sort -V | tail -1)
NEW_BENCH ?= /tmp/daft_tpu_bench_new.json

.PHONY: test bench bench-gate bench-compare

test:
	env JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider

bench:
	$(PY) bench.py

# CI perf gate: run the bench, diff against the latest committed capture.
bench-gate:
	@test -n "$(LATEST_BENCH)" || (echo "no BENCH_r*.json capture to gate against" && exit 2)
	$(PY) bench.py > $(NEW_BENCH)
	$(PY) bench.py --compare $(LATEST_BENCH) $(NEW_BENCH)

# Ad-hoc: make bench-compare OLD=BENCH_r04.json NEW=BENCH_r05.json
bench-compare:
	$(PY) bench.py --compare $(OLD) $(NEW)
