# Developer/CI entry points. The perf gate compares a fresh bench capture
# against the newest committed BENCH_r*.json and fails loudly on >5% per-query
# regressions (bench.py --compare).

PY ?= python
LATEST_BENCH := $(shell ls BENCH_r*.json 2>/dev/null | sort -V | tail -1)
NEW_BENCH ?= /tmp/daft_tpu_bench_new.json

.PHONY: test lint lint-json test-ai test-fusion test-pallas test-mesh test-fault test-oom test-gateway bench bench-ai bench-fusion bench-pallas bench-mesh bench-serve bench-serve-net bench-oom bench-oom-quick bench-tpcds bench-gate bench-compare calibrate-report doctor serve

# `make test` includes the lint gate via tests/test_lint.py (tier-1).
test:
	env JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider

# Engine-invariant linter (daft_tpu/tools/lint): lock discipline, env-knob
# discipline, counter pre-declaration, tier import discipline, broad-except
# audit, atomic publish, event-schema drift. Exits non-zero on any
# non-baselined finding.
lint:
	$(PY) -m daft_tpu.tools.lint

# Machine-readable finding counts (diff across PRs like bench.py captures).
lint-json:
	@$(PY) -m daft_tpu.tools.lint --json

# Elastic fault-tolerance suite: kill -9 / SIGSTOP real pool workers
# mid-query and assert recovery (detection, lost-map regeneration,
# respawn, checkpoint resume, serving cancellation). Recovery bugs tend to
# present as hangs, so the whole run gets a hard timeout; the process-level
# tests skip cleanly on platforms without POSIX kill/SIGSTOP semantics.
# GNU timeout is absent on stock macOS — fall back to an unbounded run there
# (the pytest-level skips still guard the POSIX-signal tests themselves)
TIMEOUT_CMD := $(shell command -v timeout >/dev/null 2>&1 && echo "timeout -k 10 600")
test-fault:
	$(TIMEOUT_CMD) env JAX_PLATFORMS=cpu $(PY) -m pytest \
		tests/test_fault_tolerance.py -q -p no:cacheprovider

# Device-UDF tier suite: device-vs-host bit-parity, coalesced dispatches,
# weight residency/pin safety, zero-overhead guard, plus the jax provider.
test-ai:
	env JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_device_udf.py \
		tests/test_jax_provider.py -q -p no:cacheprovider

# Whole-stage fusion suite (tier-1; also runs under `make test`): fused
# region 3-way bit-identity, mid-region fallback, Pallas interpret parity,
# zero-overhead guard.
test-fusion:
	env JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_fused_region.py \
		-q -p no:cacheprovider

# Pallas kernel-tier suite (tier-1; also runs under `make test`): interpret-
# mode parity for the segment-reduce, hash-probe join, and ICI ring-permute
# kernels — int64 exactness past 2^53, null keys, lowering-failure fallback
# counters, fused-repartition zero-all_to_all assert, no-import guard.
# 8 forced host devices so the mesh/ring sections run off-silicon.
test-pallas:
	env JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
		$(PY) -m pytest tests/test_pallas_join.py tests/test_fused_region.py \
		-q -p no:cacheprovider

# Pallas kernel-tier capture (bench.py pallas_microbench): grouped aggs
# through the segment-reduce kernel, a star join-agg through the hash-probe
# kernel, a repartition through the in-kernel ICI ring permute (zero
# standalone all_to_all) — bit-checked vs the XLA tiers, derived
# pallas_dispatch_ratio in the JSON.
bench-pallas:
	env BENCH_PALLAS=1 JAX_PLATFORMS=cpu $(PY) bench.py

# Whole-stage fusion capture (bench.py fusion_microbench): an 8-morsel
# filter→project→UDF→agg chain, fused vs unfused dispatch counts,
# bit-identical results, derived fused_dispatch_ratio.
bench-fusion:
	env BENCH_FUSION=1 JAX_PLATFORMS=cpu $(PY) bench.py

# AI pipeline capture on the device-UDF tier (bench.py ai_bench): seeded
# encoder, embed + zero-shot classify + groupby count, bit-identical vs the
# host-UDF path, zero repeat weight re-upload, coalesced super-batches.
bench-ai:
	env BENCH_SUITE=ai JAX_PLATFORMS=cpu $(PY) bench.py

# In-mesh SPMD suite under 8 forced host devices (the MULTICHIP harness
# environment): bit-exact mesh vs single-chip vs host parity, sharded
# residency, cost-tier flips.
test-mesh:
	env JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
		$(PY) -m pytest tests/test_mesh_stage.py tests/test_mesh_join.py \
		tests/test_distributed.py \
		-q -p no:cacheprovider

# CPU-CI mesh capture: a TPC-H-shaped groupby sharded across 8 simulated
# devices, bit-identical across mesh/single-chip/host (bench.py mesh_microbench).
bench-mesh:
	env BENCH_MESH=1 JAX_PLATFORMS=cpu \
		XLA_FLAGS=--xla_force_host_platform_device_count=8 $(PY) bench.py

# Serving-tier capture: a 2-worker ServingSession replaying a mixed
# repeat-heavy stream from 4 concurrent clients on the CPU backend —
# p50/p99 + queries/sec, bit-identical vs serial, prepared hits > 0,
# hbm_h2d flat across repeats (bench.py serve_bench).
bench-serve:
	env BENCH_SERVE=1 JAX_PLATFORMS=cpu $(PY) bench.py

# Gateway capture: the same mixed stream replayed over the WIRE — an
# in-process gateway serving a multi-process client swarm (bench.py
# serve_bench_net): p50/p99/QPS, result-cache hit rate, warm-vs-uncached
# repeat latency, bit-identical vs in-process serial.
bench-serve-net:
	env BENCH_SERVE=1 BENCH_SERVE_NET=1 JAX_PLATFORMS=cpu $(PY) bench.py

# Wire-layer gateway suite: auth, framing, reconnect-resume, concurrent
# tenants, result-cache invalidation/eviction, QoS caps, kill -9 resume.
test-gateway:
	$(TIMEOUT_CMD) env JAX_PLATFORMS=cpu $(PY) -m pytest \
		tests/test_gateway.py -q -p no:cacheprovider

# Run the serving gateway standalone (the network front door). Override:
# make serve SERVE_ARGS="--port 8642 --demo-rows 200000".
SERVE_ARGS ?= --port 8642 --demo-rows 200000
serve:
	env JAX_PLATFORMS=$${JAX_PLATFORMS:-cpu} $(PY) -m daft_tpu.gateway $(SERVE_ARGS)

# Out-of-core suite: host memory manager ledger/pressure semantics,
# streaming-scan split planning + backpressure, tiny-budget (~10% of input
# bytes) join/sort/agg bit-identity, spill-dir lifecycle/GC. Budget bugs
# tend to present as hangs (a stalled producer waiting on a ledger nobody
# drains), so the whole run gets a hard timeout.
test-oom:
	$(TIMEOUT_CMD) env JAX_PLATFORMS=cpu $(PY) -m pytest \
		tests/test_host_memory.py tests/test_streaming_scan.py \
		tests/test_oom_budget.py tests/test_out_of_core.py \
		-q -p no:cacheprovider

# Out-of-core capture: the TPC-H subset with lineitem through parquet
# streaming scans under DAFT_TPU_MEMORY_LIMIT pinned to a fraction of the
# dataset — bit-identical vs unbudgeted, spill counters + RSS high-water in
# the JSON. SF100-capable: BENCH_SF=100 make bench-oom on a big box.
bench-oom:
	env BENCH_OOM=1 JAX_PLATFORMS=cpu $(PY) bench.py

# Quick mode: the synthetic carry-preserving-merge microbench (no TPC-H
# datagen) — BENCH_OOM_ROWS rows forced through a multi-run external sort
# under a tiny budget, asserting bit-identity, the merge's O(rows)/level
# sort bound, and the prefetch high-water. The same body runs in tier-1
# via tests/test_spill_async.py.
BENCH_OOM_ROWS ?= 200000
bench-oom-quick:
	env BENCH_OOM=1 BENCH_OOM_ROWS=$(BENCH_OOM_ROWS) JAX_PLATFORMS=cpu \
		$(PY) bench.py

# TPC-DS store-sales capture (the star-join-heavy suite the mesh join tier
# targets): same one-JSON-line contract; pair with BENCH_MESH-style env on
# real silicon to record which join queries flip (bench.py --compare shows
# the per-query placement-flip column against a prior capture).
bench-tpcds:
	env BENCH_SUITE=tpcds $(PY) bench.py

bench:
	$(PY) bench.py

# CI perf gate: run the bench, diff against the latest committed capture.
bench-gate:
	@test -n "$(LATEST_BENCH)" || (echo "no BENCH_r*.json capture to gate against" && exit 2)
	$(PY) bench.py > $(NEW_BENCH)
	$(PY) bench.py --compare $(LATEST_BENCH) $(NEW_BENCH)

# Ad-hoc: make bench-compare OLD=BENCH_r04.json NEW=BENCH_r05.json
bench-compare:
	$(PY) bench.py --compare $(OLD) $(NEW)

# Regression-attribution triage (daft_tpu/tools/doctor.py): rank what got
# slower between two bench captures (per-operator/counter deltas when the
# captures carry per_query_profile, capture-level movement otherwise), or
# triage flight-recorder anomaly dumps: make doctor DUMPS="dump1.json ...".
# Defaults to the committed SF10 pair that bracketed the out-of-core tier.
DOCTOR_OLD ?= BENCH_SF10_r04.json
DOCTOR_NEW ?= BENCH_SF10_r05.json
doctor:
ifdef DUMPS
	$(PY) -m daft_tpu.tools.doctor $(DUMPS)
else
	$(PY) -m daft_tpu.tools.doctor --compare $(DOCTOR_OLD) $(DOCTOR_NEW)
endif

# Cost-model calibration report (daft_tpu/tools/calibrate.py): run a forced
# priced probe workload, replay the placement ledger's observed-vs-predicted
# samples, and print suggested DAFT_TPU_COST_* overrides. On real silicon,
# run WITHOUT JAX_PLATFORMS=cpu so the link terms are measured on the device.
calibrate-report:
	env JAX_PLATFORMS=$${JAX_PLATFORMS:-cpu} $(PY) -m daft_tpu.tools.calibrate
