"""Second function-registry breadth wave: math long-tail, string case
conversions, URL parsing, compression, serialization, timezone ops, duration
totals, and image accessors.

Reference parity: daft-functions numeric long-tail (cot/sec/csc, inverse
hyperbolics, atan2), daft-functions-utf8 case conversions
(src/daft-functions-utf8), daft-functions-uri (parse_url), the
compress/decompress + serialize/deserialize expression families
(daft/expressions/expressions.py), daft-functions-temporal timezone ops and
duration total_* accessors, and daft-image accessor kernels.
"""

from __future__ import annotations

import bz2 as _bz2
import gzip as _gzip
import json as _json
import re as _re
import zlib as _zlib
from typing import List

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from ..core.series import Series, _combine
from ..datatype import DataType, Field
from .extra import _value_map
from .registry import (_binary_arrow, _np1, _rt_const, _rt_float, _rt_same,
                       register)

# ===================================================================================
# math long-tail (reference: daft-functions numeric crates)
# ===================================================================================

register("arccosh", _rt_float, _np1(np.arccosh))
register("arcsinh", _rt_float, _np1(np.arcsinh))
register("arctanh", _rt_float, _np1(np.arctanh))
register("cot", _rt_float, _np1(lambda v: 1.0 / np.tan(v)))
register("sec", _rt_float, _np1(lambda v: 1.0 / np.cos(v)))
register("csc", _rt_float, _np1(lambda v: 1.0 / np.sin(v)))


def _atan2_host(args: List[Series], kwargs) -> Series:
    y, x = args[0], args[1]
    yv = y.to_numpy().astype(np.float64)
    xv = x.to_numpy().astype(np.float64)
    if len(xv) == 1 and len(yv) != 1:
        xv = np.broadcast_to(xv, yv.shape)
    with np.errstate(all="ignore"):
        out = np.arctan2(yv, xv)
    arr = pa.array(out)
    valid = y.validity_numpy()
    xvalid = x.validity_numpy()
    if len(xvalid) == len(valid):
        valid = valid & xvalid
    if not valid.all():
        arr = pc.if_else(pa.array(valid), arr, pa.nulls(len(arr), arr.type))
    return Series(y.name, DataType.float64(), _combine(arr))


register("arctan2", _rt_const(DataType.float64()), _atan2_host)

# ===================================================================================
# string case conversions (reference: src/daft-functions-utf8 casing)
# ===================================================================================

_WORD_RE = _re.compile(r"[A-Za-z0-9]+")


def _words(v: str) -> List[str]:
    # split camelCase / PascalCase / snake / kebab / spaces into word runs
    spaced = _re.sub(r"([a-z0-9])([A-Z])", r"\1 \2", v)
    spaced = _re.sub(r"([A-Z]+)([A-Z][a-z])", r"\1 \2", spaced)
    return [w.lower() for w in _WORD_RE.findall(spaced)]


def _case_fn(joiner):
    def conv(v: str, kwargs) -> str:
        return joiner(_words(v))

    return conv


register("to_snake_case", _rt_const(DataType.string()),
         _value_map(_case_fn(lambda ws: "_".join(ws)), DataType.string()))
register("to_kebab_case", _rt_const(DataType.string()),
         _value_map(_case_fn(lambda ws: "-".join(ws)), DataType.string()))
register("to_camel_case", _rt_const(DataType.string()),
         _value_map(_case_fn(lambda ws: (ws[0] + "".join(w.title() for w in ws[1:]))
                             if ws else ""), DataType.string()))
register("to_upper_camel_case", _rt_const(DataType.string()),
         _value_map(_case_fn(lambda ws: "".join(w.title() for w in ws)),
                    DataType.string()))
register("to_upper_snake_case", _rt_const(DataType.string()),
         _value_map(_case_fn(lambda ws: "_".join(w.upper() for w in ws)),
                    DataType.string()))
register("to_upper_kebab_case", _rt_const(DataType.string()),
         _value_map(_case_fn(lambda ws: "-".join(w.upper() for w in ws)),
                    DataType.string()))
register("to_title_case", _rt_const(DataType.string()),
         _value_map(_case_fn(lambda ws: " ".join(w.title() for w in ws)),
                    DataType.string()))

# ===================================================================================
# URL parsing (reference: daft-functions-uri / Expression.parse_url)
# ===================================================================================

_URL_STRUCT = DataType.struct({
    "scheme": DataType.string(), "username": DataType.string(),
    "password": DataType.string(), "host": DataType.string(),
    "port": DataType.int32(), "path": DataType.string(),
    "query": DataType.string(), "fragment": DataType.string(),
})


def _parse_url(v: str, kwargs):
    from urllib.parse import urlsplit

    try:
        u = urlsplit(v)
    except ValueError:
        return None
    return {
        "scheme": u.scheme or None, "username": u.username,
        "password": u.password, "host": u.hostname,
        "port": u.port, "path": u.path or None,
        "query": u.query or None, "fragment": u.fragment or None,
    }


register("parse_url", _rt_const(_URL_STRUCT), _value_map(_parse_url, _URL_STRUCT))

# ===================================================================================
# compression (reference: Expression.compress/decompress; codecs gzip/zlib/bz2)
# ===================================================================================

_CODECS = {
    "gzip": (_gzip.compress, _gzip.decompress),
    "zlib": (_zlib.compress, _zlib.decompress),
    "deflate": (_zlib.compress, _zlib.decompress),
    "bz2": (_bz2.compress, _bz2.decompress),
}


def _compress(v, kwargs):
    codec = kwargs.get("codec", "gzip")
    if codec not in _CODECS:
        raise ValueError(f"unknown codec {codec!r}; supported: {sorted(_CODECS)}")
    data = v.encode() if isinstance(v, str) else v
    return _CODECS[codec][0](data)


def _decompress(v, kwargs):
    codec = kwargs.get("codec", "gzip")
    if codec not in _CODECS:
        raise ValueError(f"unknown codec {codec!r}; supported: {sorted(_CODECS)}")
    return _CODECS[codec][1](v)


def _try(fn):
    def wrapped(v, kwargs):
        try:
            return fn(v, kwargs)
        except ValueError:
            raise
        except Exception:  # lint: ignore[broad-except] -- row-level best-effort: errors are nulls
            return None

    return wrapped


register("compress", _rt_const(DataType.binary()),
         _value_map(_compress, DataType.binary()))
register("decompress", _rt_const(DataType.binary()),
         _value_map(_decompress, DataType.binary()))
register("try_compress", _rt_const(DataType.binary()),
         _value_map(_try(_compress), DataType.binary()))
register("try_decompress", _rt_const(DataType.binary()),
         _value_map(_try(_decompress), DataType.binary()))

# ===================================================================================
# serialization (reference: Expression.serialize/deserialize, format="json")
# ===================================================================================


def _serialize(v, kwargs):
    fmt = kwargs.get("format", "json")
    if fmt != "json":
        raise ValueError(f"unsupported serialize format {fmt!r} (supported: json)")
    return _json.dumps(v, default=str)


register("serialize", _rt_const(DataType.string()),
         _value_map(_serialize, DataType.string()))


def _rt_deserialize(fields, kwargs):
    dt = kwargs.get("dtype")
    return dt if dt is not None else DataType.string()


def _deserialize_host(args: List[Series], kwargs) -> Series:
    s = args[0]
    fmt = kwargs.get("format", "json")
    if fmt != "json":
        raise ValueError(f"unsupported deserialize format {fmt!r} (supported: json)")
    dt = kwargs.get("dtype") or DataType.string()
    strict = kwargs.get("strict", True)
    out = []
    for v in s.to_pylist():
        if v is None:
            out.append(None)
            continue
        try:
            out.append(_json.loads(v))
        except Exception:
            if strict:
                raise
            out.append(None)
    return Series.from_pylist(out, s.name, dtype=dt)


register("deserialize", _rt_deserialize, _deserialize_host)
register("try_deserialize", _rt_deserialize,
         lambda a, k: _deserialize_host(a, {**k, "strict": False}))

# ===================================================================================
# timezone ops (reference: daft-functions-temporal tz handling)
# ===================================================================================


def _rt_replace_tz(fields, kwargs):
    dt = fields[0].dtype
    return DataType.timestamp(dt.params[0] if dt.params else "us", kwargs.get("tz"))


def _replace_tz_host(args: List[Series], kwargs) -> Series:
    s = args[0]
    tz = kwargs.get("tz")
    arr = s.to_arrow()
    if hasattr(arr, "combine_chunks"):
        arr = arr.combine_chunks()
    unit = s.dtype.params[0] if s.dtype.params else "us"
    if pa.types.is_timestamp(arr.type) and arr.type.tz is not None:
        # drop or swap the zone WITHOUT changing the wall-clock reading
        local = arr.cast(pa.timestamp(unit))  # instant -> utc wall time? no:
        # pyarrow cast tz-aware -> naive keeps the UTC instant; to keep local
        # wall time, render via strftime-free path: use assume_timezone inverse
        local = pc.local_timestamp(arr)
        arr = local
    if tz is None:
        out = arr
    else:
        out = pc.assume_timezone(arr, tz, ambiguous="earliest",
                                 nonexistent="earliest")
    return Series(s.name, DataType.from_arrow(out.type), _combine(out))


register("replace_time_zone", _rt_replace_tz, _replace_tz_host)


def _rt_convert_tz(fields, kwargs):
    dt = fields[0].dtype
    return DataType.timestamp(dt.params[0] if dt.params else "us", kwargs.get("tz"))


def _convert_tz_host(args: List[Series], kwargs) -> Series:
    s = args[0]
    tz = kwargs.get("tz")
    arr = s.to_arrow()
    if hasattr(arr, "combine_chunks"):
        arr = arr.combine_chunks()
    if not pa.types.is_timestamp(arr.type) or arr.type.tz is None:
        raise ValueError("convert_time_zone requires a timezone-aware timestamp; "
                         "use replace_time_zone on naive timestamps")
    out = arr.cast(pa.timestamp(arr.type.unit, tz))
    return Series(s.name, DataType.from_arrow(out.type), _combine(out))


register("convert_time_zone", _rt_convert_tz, _convert_tz_host)

# ===================================================================================
# duration totals (reference: Expression.total_seconds etc. over duration dtype)
# ===================================================================================

_UNIT_NS = {"s": 1_000_000_000, "ms": 1_000_000, "us": 1_000, "ns": 1}


def _total_host(target_ns: int):
    def host(args: List[Series], kwargs) -> Series:
        s = args[0]
        if s.dtype.kind != "duration":
            raise ValueError(f"total_* requires a duration column, got {s.dtype}")
        unit = s.dtype.params[0] if s.dtype.params else "us"
        scale = _UNIT_NS[unit]
        vals = s.to_numpy().astype(np.int64)
        out = vals * scale // target_ns
        arr = pa.array(out)
        valid = s.validity_numpy()
        if not valid.all():
            arr = pc.if_else(pa.array(valid), arr, pa.nulls(len(arr), arr.type))
        return Series(s.name, DataType.int64(), _combine(arr))

    return host


for _name, _ns in [("total_days", 86_400_000_000_000),
                   ("total_hours", 3_600_000_000_000),
                   ("total_minutes", 60_000_000_000),
                   ("total_seconds", 1_000_000_000),
                   ("total_milliseconds", 1_000_000),
                   ("total_microseconds", 1_000),
                   ("total_nanoseconds", 1)]:
    register(_name, _rt_const(DataType.int64()), _total_host(_ns))

# ===================================================================================
# image accessors (reference: daft-image attribute kernels)
# ===================================================================================


def _image_accessor(attr_index: int):
    """attr: 0=height, 1=width, 2=channels (image struct carries h/w/c)."""

    def host(args: List[Series], kwargs) -> Series:
        from ..core.kernels.image import unpack_images

        out = [None if im is None else int(im.shape[attr_index])
               for im, _mode in unpack_images(args[0])]
        return Series.from_pylist(out, args[0].name, dtype=DataType.uint32())

    return host


register("image_height", _rt_const(DataType.uint32()), _image_accessor(0))
register("image_width", _rt_const(DataType.uint32()), _image_accessor(1))


def _image_channel_host(args: List[Series], kwargs) -> Series:
    from ..core.kernels.image import unpack_images

    out = [None if im is None else (1 if im.ndim == 2 else int(im.shape[2]))
           for im, _mode in unpack_images(args[0])]
    return Series.from_pylist(out, args[0].name, dtype=DataType.uint32())


register("image_channel", _rt_const(DataType.uint32()), _image_channel_host)


def _image_hash_host(args: List[Series], kwargs) -> Series:
    """Perceptual average-hash (aHash, 8x8 grayscale) as a hex string."""
    from ..core.kernels.image import unpack_images

    out = []
    for im, _mode in unpack_images(args[0]):
        if im is None:
            out.append(None)
            continue
        a = im.astype(np.float64)
        if a.ndim == 3:
            a = a.mean(axis=2)
        h, w = a.shape
        ys = (np.arange(8) * h // 8)
        xs = (np.arange(8) * w // 8)
        small = a[ys][:, xs]
        bits = (small > small.mean()).flatten()
        val = 0
        for b in bits:
            val = (val << 1) | int(b)
        out.append(f"{val:016x}")
    return Series.from_pylist(out, args[0].name, dtype=DataType.string())


register("image_hash", _rt_const(DataType.string()), _image_hash_host)

# ===================================================================================
# misc: unix_date, nanosecond, product aggregation support helpers
# ===================================================================================

register("unix_date", _rt_const(DataType.int64()),
         lambda a, k: _unix_date_host(a))


def _unix_date_host(args: List[Series]) -> Series:
    s = args[0]
    arr = s.to_arrow()
    if hasattr(arr, "combine_chunks"):
        arr = arr.combine_chunks()
    days = arr.cast(pa.date32()).cast(pa.int32()).cast(pa.int64())
    return Series(s.name, DataType.int64(), _combine(days))


def _nanosecond_host(args: List[Series], kwargs) -> Series:
    s = args[0]
    arr = s.to_arrow()
    if hasattr(arr, "combine_chunks"):
        arr = arr.combine_chunks()
    # sub-second remainder in nanoseconds (our timestamps are us-precision)
    us = pc.microsecond(arr)
    ns = pc.multiply(us.cast(pa.int64()), pa.scalar(1000, pa.int64()))
    return Series(s.name, DataType.int64(), _combine(ns))


register("dt_nanosecond", _rt_const(DataType.int64()), _nanosecond_host)


# ===================================================================================
# list long-tail (reference: daft-functions-list append/bool aggregates)
# ===================================================================================


def _list_append_host(args: List[Series], kwargs) -> Series:
    s, v = args[0], args[1]
    vv = v.to_pylist()
    if len(vv) == 1 and len(s) != 1:
        vv = vv * len(s)
    out = [(None if lst is None else list(lst) + [item])
           for lst, item in zip(s.to_pylist(), vv)]
    return Series.from_pylist(out, s.name, dtype=s.dtype)


register("list_append", _rt_same, _list_append_host)


def _list_bool(op_all: bool):
    def host(args: List[Series], kwargs) -> Series:
        out = []
        for lst in args[0].to_pylist():
            if lst is None:
                out.append(None)
                continue
            vals = [bool(v) for v in lst if v is not None]
            if not vals:
                out.append(None)
            else:
                out.append(all(vals) if op_all else any(vals))
        return Series.from_pylist(out, args[0].name, dtype=DataType.bool())

    return host


register("list_bool_and", _rt_const(DataType.bool()), _list_bool(True))
register("list_bool_or", _rt_const(DataType.bool()), _list_bool(False))

# ===================================================================================
# charset/codec encode/decode (reference: Expression.encode/decode families)
# ===================================================================================

_TEXT_CODECS = {"utf-8", "utf8", "ascii", "latin-1"}


def _encode(v, kwargs):
    codec = kwargs.get("codec", "utf-8")
    if codec in _TEXT_CODECS:
        return v.encode(codec) if isinstance(v, str) else v
    if codec == "base64":
        import base64

        return base64.b64encode(v.encode() if isinstance(v, str) else v)
    if codec == "hex":
        data = v.encode() if isinstance(v, str) else v
        return data.hex().encode()
    if codec in _CODECS:
        return _compress(v, {"codec": codec})
    raise ValueError(f"unknown codec {codec!r}")


def _decode(v, kwargs):
    codec = kwargs.get("codec", "utf-8")
    if codec in _TEXT_CODECS:
        return v.decode(codec) if isinstance(v, (bytes, bytearray)) else v
    if codec == "base64":
        import base64

        return base64.b64decode(v)
    if codec == "hex":
        return bytes.fromhex(v.decode() if isinstance(v, (bytes, bytearray)) else v)
    if codec in _CODECS:
        return _decompress(v, {"codec": codec})
    raise ValueError(f"unknown codec {codec!r}")


def _rt_codec_encode(fields, kwargs):
    codec = kwargs.get("codec", "utf-8")
    return DataType.binary()


def _rt_codec_decode(fields, kwargs):
    codec = kwargs.get("codec", "utf-8")
    return DataType.string() if codec in _TEXT_CODECS else DataType.binary()


register("codec_encode", _rt_codec_encode, _value_map(_encode, DataType.binary()))
register("try_codec_encode", _rt_codec_encode,
         _value_map(_try(_encode), DataType.binary()))


def _decode_host(args: List[Series], kwargs) -> Series:
    s = args[0]
    codec = kwargs.get("codec", "utf-8")
    dt = DataType.string() if codec in _TEXT_CODECS else DataType.binary()
    strict = kwargs.get("strict", True)
    out = []
    for v in s.to_pylist():
        if v is None:
            out.append(None)
            continue
        try:
            out.append(_decode(v, kwargs))
        except ValueError:
            raise
        except Exception:
            if strict:
                raise
            out.append(None)
    return Series.from_pylist(out, s.name, dtype=dt)


register("codec_decode", _rt_codec_decode, _decode_host)
register("try_codec_decode", _rt_codec_decode,
         lambda a, k: _decode_host(a, {**k, "strict": False}))

# ===================================================================================
# iceberg partition transforms (reference: Expression.partition_* over the
# iceberg spec: bucket = murmur3_32, truncate, and temporal projections)
# ===================================================================================


def _murmur3_32(data: bytes, seed: int = 0) -> int:
    """Iceberg's bucket hash (murmur3 x86 32-bit, public algorithm)."""
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h = seed & 0xFFFFFFFF
    n = len(data)
    for i in range(0, n - n % 4, 4):
        k = int.from_bytes(data[i:i + 4], "little")
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
        h = ((h << 13) | (h >> 19)) & 0xFFFFFFFF
        h = (h * 5 + 0xE6546B64) & 0xFFFFFFFF
    tail = data[n - n % 4:]
    if tail:
        k = int.from_bytes(tail.ljust(4, b"\0"), "little")
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
    h ^= n
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & 0xFFFFFFFF
    h ^= h >> 16
    return h


def _iceberg_bucket_host(args: List[Series], kwargs) -> Series:
    s = args[0]
    n = kwargs["n"]
    dt = s.dtype
    out = []
    for v in s.to_pylist():
        if v is None:
            out.append(None)
            continue
        if dt.is_integer():
            data = int(v).to_bytes(8, "little", signed=True)
        elif dt.is_string():
            data = v.encode()
        elif dt.is_binary():
            data = v
        else:
            raise ValueError(f"iceberg_bucket unsupported for {dt}")
        out.append((_murmur3_32(data) & 0x7FFFFFFF) % n)
    return Series.from_pylist(out, s.name, dtype=DataType.int32())


register("partition_iceberg_bucket", _rt_const(DataType.int32()),
         _iceberg_bucket_host)


def _iceberg_truncate_host(args: List[Series], kwargs) -> Series:
    s = args[0]
    w = kwargs["w"]
    dt = s.dtype
    if dt.is_integer():
        vals = s.to_numpy().astype(np.int64)
        out_np = vals - (((vals % w) + w) % w)
        arr = pa.array(out_np)
        valid = s.validity_numpy()
        if not valid.all():
            arr = pc.if_else(pa.array(valid), arr, pa.nulls(len(arr), arr.type))
        return Series(s.name, DataType.int64(), _combine(arr))
    if dt.is_string():
        return Series.from_pylist(
            [None if v is None else v[:w] for v in s.to_pylist()],
            s.name, dtype=DataType.string())
    raise ValueError(f"iceberg_truncate unsupported for {dt}")


register("partition_iceberg_truncate", _rt_same, _iceberg_truncate_host)


def _partition_temporal(unit: str):
    def host(args: List[Series], kwargs) -> Series:
        s = args[0]
        arr = s.to_arrow()
        if hasattr(arr, "combine_chunks"):
            arr = arr.combine_chunks()
        days = arr.cast(pa.date32()).cast(pa.int32())
        if unit == "days":
            out = days
        else:
            import datetime as _dtmod

            py = arr.cast(pa.date32()).to_pylist()
            if unit == "months":
                out = pa.array([None if d is None else (d.year - 1970) * 12 + d.month - 1
                                for d in py], pa.int32())
            elif unit == "years":
                out = pa.array([None if d is None else d.year - 1970 for d in py],
                               pa.int32())
            else:  # hours (timestamps only)
                us = arr.cast(pa.timestamp("us")).cast(pa.int64())
                out = pc.divide(us, pa.scalar(3_600_000_000, pa.int64())).cast(pa.int32())
        return Series(s.name, DataType.int32(), _combine(out))

    return host


for _u in ("days", "hours", "months", "years"):
    register(f"partition_{_u}", _rt_const(DataType.int32()), _partition_temporal(_u))

# ===================================================================================
# image mode/attribute accessors
# ===================================================================================


def _image_mode_host(args: List[Series], kwargs) -> Series:
    from ..core.kernels.image import unpack_images

    out = []
    for im, mode in unpack_images(args[0]):
        if im is None:
            out.append(None)
        else:
            out.append(str(mode) if mode is not None else
                       ("L" if im.ndim == 2 or im.shape[2] == 1 else
                        "RGB" if im.shape[2] == 3 else "RGBA"))
    return Series.from_pylist(out, args[0].name, dtype=DataType.string())


register("image_mode", _rt_const(DataType.string()), _image_mode_host)


# ===================================================================================
# File type (reference: daft-file/src/functions.rs — file/file_path/file_size
# over the lazy File dtype; bytes move only when read)
# ===================================================================================


def _file_host(args: List[Series], kwargs) -> Series:
    s = args[0]
    out = [None if v is None else {"path": v, "data": None} for v in s.to_pylist()]
    return Series.from_pylist(out, s.name, dtype=DataType.file())


register("file", _rt_const(DataType.file()), _file_host)


def _file_path_host(args: List[Series], kwargs) -> Series:
    out = [None if v is None else v.get("path") for v in args[0].to_pylist()]
    return Series.from_pylist(out, args[0].name, dtype=DataType.string())


register("file_path", _rt_const(DataType.string()), _file_path_host)


def _file_size_host(args: List[Series], kwargs) -> Series:
    from ..filetype import File

    io_config = kwargs.get("io_config")
    out = []
    for v in args[0].to_pylist():
        if v is None:
            out.append(None)
        elif v.get("data") is not None:
            out.append(len(v["data"]))
        else:
            out.append(File(v["path"], io_config).size())
    return Series.from_pylist(out, args[0].name, dtype=DataType.int64())


register("file_size", _rt_const(DataType.int64()), _file_size_host)


def _file_read_host(args: List[Series], kwargs) -> Series:
    from ..filetype import File

    io_config = kwargs.get("io_config")
    offset = kwargs.get("offset", 0)
    length = kwargs.get("length")
    out = []
    for v in args[0].to_pylist():
        if v is None:
            out.append(None)
            continue
        if v.get("data") is not None:
            data = v["data"]
            out.append(data[offset:offset + length] if length is not None
                       else data[offset:])
            continue
        with File(v["path"], io_config).open() as f:
            if offset:
                f.seek(offset)
            out.append(f.read(length if length is not None else -1))
    return Series.from_pylist(out, args[0].name, dtype=DataType.binary())


register("file_read", _rt_const(DataType.binary()), _file_read_host)
