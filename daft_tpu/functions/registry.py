"""Scalar function registry.

Reference parity: the ScalarUDF trait + per-domain function crates
(src/daft-dsl/src/functions/scalar.rs:205; src/daft-functions-utf8, -list,
-temporal, numeric ops in daft-functions). Each FunctionSpec carries a return-type
rule and a host kernel; device-compatible functions also register a jax kernel used
by the stage compiler (daft_tpu/ops/device_eval.py).
"""

from __future__ import annotations

import dataclasses
import re
import threading
from typing import Any, Callable, Dict, List, Optional

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from ..core.series import Series, _combine
from ..datatype import DataType, Field

_REGISTRY: Dict[str, "FunctionSpec"] = {}
# Registration is mostly import-time (the `from . import extra` side effects)
# but register() is public API callable from any thread in a live session.
_REGISTRY_LOCK = threading.Lock()


@dataclasses.dataclass
class FunctionSpec:
    name: str
    return_type: Callable[[List[Field], Dict[str, Any]], DataType]
    host: Callable[[List[Series], Dict[str, Any]], Series]
    device: Optional[Callable] = None  # jax kernel: (*(vals, valid) pairs, **kwargs) -> (vals, valid)


def register(name: str, return_type, host, device=None, aliases=()):
    spec = FunctionSpec(name, return_type, host, device)
    with _REGISTRY_LOCK:
        _REGISTRY[name] = spec
        for a in aliases:
            _REGISTRY[a] = spec
    return spec


def get_function(name: str) -> FunctionSpec:
    spec = _REGISTRY.get(name)
    if spec is None:
        raise ValueError(f"unknown function {name!r}; known: {sorted(_REGISTRY)[:40]}...")
    return spec


def has_function(name: str) -> bool:
    return name in _REGISTRY


# ---- return-type helpers ----------------------------------------------------------


def _rt_same(fields, kwargs):
    return fields[0].dtype


def _rt_const(dt: DataType):
    return lambda fields, kwargs: dt


def _rt_float(fields, kwargs):
    return DataType.float32() if fields[0].dtype.kind == "float32" else DataType.float64()


def _rt_inner(fields, kwargs):
    return fields[0].dtype.inner


# ---- host kernel helpers ----------------------------------------------------------


def _pc1(fn, out_dt=None, pre_cast=None):
    """Lift a unary pyarrow.compute kernel to a host function."""

    def host(args: List[Series], kwargs) -> Series:
        s = args[0]
        arr = s.to_arrow()
        if pre_cast is not None:
            arr = arr.cast(pre_cast)
        out = _combine(fn(arr))
        dt = out_dt or DataType.from_arrow(out.type)
        return Series(s.name, dt, out)

    return host


def _np1(fn, out_np_dtype=None):
    """Lift a unary numpy ufunc-style kernel; preserves validity."""

    def host(args: List[Series], kwargs) -> Series:
        s = args[0]
        vals = s.to_numpy().astype(np.float64 if s.dtype.kind != "float32" else np.float32)
        with np.errstate(all="ignore"):
            out = fn(vals)
        if out_np_dtype is not None:
            out = out.astype(out_np_dtype)
        arr = pa.array(out)
        valid = s.validity_numpy()
        if not valid.all():
            arr = pc.if_else(pa.array(valid), arr, pa.nulls(len(arr), type=arr.type))
        return Series(s.name, DataType.from_arrow(arr.type), _combine(arr))

    return host


def _np1_keep_dtype(fn):
    """Like _np1 but casts the result back to the input dtype (floor/ceil on ints
    must return ints so materialized data matches the planned schema)."""
    base = _np1(fn)

    def host(args: List[Series], kwargs) -> Series:
        out = base(args, kwargs)
        if out.dtype != args[0].dtype:
            out = out.cast(args[0].dtype)
        return out

    return host


def _binary_arrow(fn):
    """Lift a binary arrow kernel with length-1 broadcasting."""

    def host(args: List[Series], kwargs) -> Series:
        a, b = args[0], args[1]
        return a._binary(b, fn)

    return host


# ===================================================================================
# numeric
# ===================================================================================

for _name, _np_fn in [
    ("exp", np.exp), ("sqrt", np.sqrt), ("sin", np.sin), ("cos", np.cos),
    ("tan", np.tan), ("arctan", np.arctan), ("arcsin", np.arcsin),
    ("arccos", np.arccos), ("log2", np.log2), ("log10", np.log10),
    ("cbrt", np.cbrt), ("expm1", np.expm1), ("log1p", np.log1p),
    ("sinh", np.sinh), ("cosh", np.cosh), ("tanh", np.tanh),
    ("degrees", np.degrees), ("radians", np.radians),
]:
    register(_name, _rt_float, _np1(_np_fn), device=_np_fn)


def _log_host(args, kwargs):
    base = kwargs.get("base")
    s = args[0]
    vals = s.to_numpy().astype(np.float64)
    with np.errstate(all="ignore"):
        out = np.log(vals) if not base else np.log(vals) / np.log(base)
    arr = pa.array(out)
    valid = s.validity_numpy()
    if not valid.all():
        arr = pc.if_else(pa.array(valid), arr, pa.nulls(len(arr), type=arr.type))
    return Series(s.name, DataType.float64(), _combine(arr))


register("log", _rt_float, _log_host)
register("floor", _rt_same, _np1_keep_dtype(np.floor))
register("ceil", _rt_same, _np1_keep_dtype(np.ceil))
register("sign", _rt_same, _pc1(pc.sign))


def _round_host(args, kwargs):
    d = kwargs.get("decimals", 0)
    s = args[0]
    out = _combine(pc.round(s.to_arrow(), ndigits=d))
    return Series(s.name, s.dtype, out)


register("round", _rt_same, _round_host)


def _clip_host(args, kwargs):
    s = args[0]
    lo, hi = kwargs.get("clip_min"), kwargs.get("clip_max")
    vals = s.to_numpy()
    out = np.clip(vals, lo, hi)
    arr = pa.array(out)
    valid = s.validity_numpy()
    if not valid.all():
        arr = pc.if_else(pa.array(valid), arr, pa.nulls(len(arr), type=arr.type))
    return Series(s.name, DataType.from_arrow(arr.type), _combine(arr))


register("clip", _rt_same, _clip_host)


def _hash_host(args, kwargs):
    seed = kwargs.get("seed")
    seed_series = None
    if seed is not None:
        seed_series = Series.from_numpy(np.full(len(args[0]), seed, dtype=np.uint64), "seed")
    return args[0].hash(seed_series)


register("hash", _rt_const(DataType.uint64()), _hash_host)


# ===================================================================================
# float namespace
# ===================================================================================

register("is_nan", _rt_const(DataType.bool()), _pc1(pc.is_nan))


def _is_inf_host(args, kwargs):
    s = args[0]
    return Series(s.name, DataType.bool(), _combine(pc.is_inf(s.to_arrow())))


register("is_inf", _rt_const(DataType.bool()), _is_inf_host)


def _not_nan_host(args, kwargs):
    s = args[0]
    return Series(s.name, DataType.bool(), _combine(pc.invert(pc.is_nan(s.to_arrow()))))


register("not_nan", _rt_const(DataType.bool()), _not_nan_host)


def _fill_nan_host(args, kwargs):
    s, fill = args[0], args[1]
    nan_mask = pc.is_nan(s.to_arrow())
    fill_arr = fill.to_arrow()
    fv = fill_arr[0] if len(fill_arr) == 1 else fill_arr
    out = _combine(pc.if_else(nan_mask, fv, s.to_arrow()))
    return Series(s.name, s.dtype, out)


register("fill_nan", _rt_same, _fill_nan_host)


# ===================================================================================
# utf8
# ===================================================================================

register("utf8_upper", _rt_same, _pc1(pc.utf8_upper))
register("utf8_lower", _rt_same, _pc1(pc.utf8_lower))
register("utf8_length", _rt_const(DataType.uint64()), _pc1(pc.utf8_length, DataType.uint64()))
register("utf8_length_bytes", _rt_const(DataType.uint64()), _pc1(pc.binary_length, DataType.uint64()))
register("utf8_capitalize", _rt_same, _pc1(pc.utf8_capitalize))
register("utf8_reverse", _rt_same, _pc1(pc.utf8_reverse))
register("utf8_lstrip", _rt_same, _pc1(pc.utf8_ltrim_whitespace))
register("utf8_rstrip", _rt_same, _pc1(pc.utf8_rtrim_whitespace))
register("utf8_strip", _rt_same, _pc1(pc.utf8_trim_whitespace))


def _scalar_arg(s: Series):
    """Extract a python scalar from a length-1 Series argument."""
    vals = s.to_pylist()
    if len(vals) != 1:
        raise ValueError("expected a scalar argument")
    return vals[0]


def _utf8_contains(args, kwargs):
    s, pat = args[0], args[1]
    if len(pat) == 1:
        out = pc.match_substring(s.to_arrow(), _scalar_arg(pat))
    else:
        out = pa.array([
            None if (a is None or b is None) else (b in a)
            for a, b in zip(s.to_pylist(), pat.to_pylist())
        ])
    return Series(s.name, DataType.bool(), _combine(out))


register("utf8_contains", _rt_const(DataType.bool()), _utf8_contains)


def _utf8_startswith(args, kwargs):
    s, pat = args[0], args[1]
    out = pc.starts_with(s.to_arrow(), _scalar_arg(pat))
    return Series(s.name, DataType.bool(), _combine(out))


def _utf8_endswith(args, kwargs):
    s, pat = args[0], args[1]
    out = pc.ends_with(s.to_arrow(), _scalar_arg(pat))
    return Series(s.name, DataType.bool(), _combine(out))


register("utf8_startswith", _rt_const(DataType.bool()), _utf8_startswith)
register("utf8_endswith", _rt_const(DataType.bool()), _utf8_endswith)


def _utf8_match(args, kwargs):
    s, pat = args[0], args[1]
    out = pc.match_substring_regex(s.to_arrow(), _scalar_arg(pat))
    return Series(s.name, DataType.bool(), _combine(out))


register("utf8_match", _rt_const(DataType.bool()), _utf8_match)


def _utf8_split(args, kwargs):
    s, pat = args[0], args[1]
    p = _scalar_arg(pat)
    if kwargs.get("regex"):
        out = pc.split_pattern_regex(s.to_arrow(), p)
    else:
        out = pc.split_pattern(s.to_arrow(), p)
    return Series(s.name, DataType.list(DataType.string()), _combine(out).cast(pa.large_list(pa.large_string())))


register("utf8_split", lambda f, k: DataType.list(DataType.string()), _utf8_split)


def _utf8_substr(args, kwargs):
    s = args[0]
    start = _scalar_arg(args[1])
    length = _scalar_arg(args[2]) if len(args) > 2 else None
    stop = None if length is None else start + length
    out = pc.utf8_slice_codeunits(s.to_arrow(), start=start, stop=stop)
    return Series(s.name, DataType.string(), _combine(out))


register("utf8_substr", _rt_const(DataType.string()), _utf8_substr)


def _utf8_replace(args, kwargs):
    s, pat, rep = args[0], args[1], args[2]
    p, r = _scalar_arg(pat), _scalar_arg(rep)
    if kwargs.get("regex"):
        out = pc.replace_substring_regex(s.to_arrow(), pattern=p, replacement=r)
    else:
        out = pc.replace_substring(s.to_arrow(), pattern=p, replacement=r)
    return Series(s.name, DataType.string(), _combine(out))


register("utf8_replace", _rt_const(DataType.string()), _utf8_replace)


def _utf8_extract(args, kwargs):
    s, pat = args[0], args[1]
    p = _scalar_arg(pat)
    idx = kwargs.get("index", 0)
    rx = re.compile(p)

    def f(v):
        if v is None:
            return None
        m = rx.search(v)
        if m is None:
            return None
        return m.group(idx)

    return Series.from_pylist([f(v) for v in s.to_pylist()], s.name, DataType.string())


register("utf8_extract", _rt_const(DataType.string()), _utf8_extract)


def _utf8_extract_all(args, kwargs):
    s, pat = args[0], args[1]
    p = _scalar_arg(pat)
    idx = kwargs.get("index", 0)
    rx = re.compile(p)

    def f(v):
        if v is None:
            return None
        return [m.group(idx) for m in rx.finditer(v)]

    return Series.from_pylist([f(v) for v in s.to_pylist()], s.name, DataType.list(DataType.string()))


register("utf8_extract_all", lambda f, k: DataType.list(DataType.string()), _utf8_extract_all)


def _utf8_find(args, kwargs):
    s, sub = args[0], args[1]
    out = pc.find_substring(s.to_arrow(), _scalar_arg(sub))
    return Series(s.name, DataType.int64(), _combine(out).cast(pa.int64()))


register("utf8_find", _rt_const(DataType.int64()), _utf8_find)


def _utf8_left(args, kwargs):
    s, n = args[0], _scalar_arg(args[1])
    out = pc.utf8_slice_codeunits(s.to_arrow(), start=0, stop=n)
    return Series(s.name, DataType.string(), _combine(out))


def _utf8_right(args, kwargs):
    s, n = args[0], _scalar_arg(args[1])
    if n <= 0:
        arr = s.to_arrow()
        out = pc.if_else(pc.is_valid(arr), pa.array([""] * len(arr), pa.large_string()),
                         pa.nulls(len(arr), pa.large_string()))
        return Series(s.name, DataType.string(), _combine(out))
    arr = s.to_arrow()
    lengths = pc.utf8_length(arr)
    starts = pc.max_element_wise(pc.subtract(lengths, n), 0)
    # per-row start offsets: pyarrow has no vectorized per-row slice, so python loop
    out = pa.array([
        None if v is None else v[st:]
        for v, st in zip(arr.to_pylist(), starts.to_pylist())
    ], type=pa.large_string())
    return Series(s.name, DataType.string(), out)


register("utf8_left", _rt_const(DataType.string()), _utf8_left)
register("utf8_right", _rt_const(DataType.string()), _utf8_right)


def _utf8_repeat(args, kwargs):
    s, n = args[0], _scalar_arg(args[1])
    out = pc.binary_repeat(s.to_arrow(), n)
    return Series(s.name, DataType.string(), _combine(out))


register("utf8_repeat", _rt_const(DataType.string()), _utf8_repeat)


def _utf8_concat(args, kwargs):
    a, b = args[0], args[1]

    def k(x, y):
        # null || anything = null (SQL concat semantics)
        sx = pc.cast(x, pa.large_string()) if not pa.types.is_large_string(x.type) else x
        sy = pc.cast(y, pa.large_string()) if not pa.types.is_large_string(y.type) else y
        sep = pa.scalar("", type=pa.large_string())
        return pc.binary_join_element_wise(sx, sy, sep)

    return a._binary(b, k, out_dtype=DataType.string())


register("utf8_concat", _rt_const(DataType.string()), _utf8_concat)


def _like_to_regex(pattern: str, case_insensitive: bool) -> re.Pattern:
    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return re.compile("^" + "".join(out) + "$", re.IGNORECASE if case_insensitive else 0)


def _utf8_like(args, kwargs, ci=False):
    s, pat = args[0], args[1]
    rx = _like_to_regex(_scalar_arg(pat), ci)
    return Series.from_pylist(
        [None if v is None else bool(rx.match(v)) for v in s.to_pylist()], s.name, DataType.bool()
    )


register("utf8_like", _rt_const(DataType.bool()), _utf8_like)
register("utf8_ilike", _rt_const(DataType.bool()), lambda a, k: _utf8_like(a, k, ci=True))


def _utf8_pad(args, kwargs, left: bool):
    s, n, pad = args[0], _scalar_arg(args[1]), _scalar_arg(args[2])
    fn = pc.utf8_lpad if left else pc.utf8_rpad
    out = fn(s.to_arrow(), width=n, padding=pad)
    return Series(s.name, DataType.string(), _combine(out))


register("utf8_lpad", _rt_const(DataType.string()), lambda a, k: _utf8_pad(a, k, True))
register("utf8_rpad", _rt_const(DataType.string()), lambda a, k: _utf8_pad(a, k, False))


def _utf8_to_date(args, kwargs):
    s = args[0]
    fmt = kwargs["format"]
    out = pc.strptime(s.to_arrow(), format=fmt, unit="s", error_is_null=True)
    return Series(s.name, DataType.date(), _combine(out.cast(pa.date32())))


register("utf8_to_date", _rt_const(DataType.date()), _utf8_to_date)


def _utf8_to_datetime(args, kwargs):
    s = args[0]
    fmt = kwargs["format"]
    tz = kwargs.get("timezone")
    out = pc.strptime(s.to_arrow(), format=fmt, unit="us", error_is_null=True)
    dt = DataType.timestamp("us", tz)
    if tz:
        out = out.cast(pa.timestamp("us")).cast(pa.timestamp("us", tz))
    return Series(s.name, dt, _combine(out))


register(
    "utf8_to_datetime",
    lambda f, k: DataType.timestamp("us", k.get("timezone")),
    _utf8_to_datetime,
)


def _utf8_normalize(args, kwargs):
    import unicodedata

    s = args[0]

    def f(v):
        if v is None:
            return None
        if kwargs.get("nfd_unicode"):
            v = unicodedata.normalize("NFD", v)
        if kwargs.get("lowercase"):
            v = v.lower()
        if kwargs.get("remove_punct"):
            v = re.sub(r"[^\w\s]", "", v)
        if kwargs.get("white_space"):
            v = " ".join(v.split())
        return v

    return Series.from_pylist([f(v) for v in s.to_pylist()], s.name, DataType.string())


register("utf8_normalize", _rt_const(DataType.string()), _utf8_normalize)


def _utf8_count_matches(args, kwargs):
    s, patterns = args[0], args[1]
    pats = patterns.to_pylist()
    if pats and isinstance(pats[0], list):
        pats = pats[0]
    ci = not kwargs.get("case_sensitive", True)
    ww = kwargs.get("whole_words", False)
    parts = [(r"\b" + re.escape(p) + r"\b") if ww else re.escape(p) for p in pats]
    rx = re.compile("|".join(parts), re.IGNORECASE if ci else 0)
    return Series.from_pylist(
        [None if v is None else len(rx.findall(v)) for v in s.to_pylist()],
        s.name,
        DataType.uint64(),
    )


register("utf8_count_matches", _rt_const(DataType.uint64()), _utf8_count_matches)


# ===================================================================================
# temporal
# ===================================================================================

def _dt1(fn, out_dt):
    def host(args: List[Series], kwargs) -> Series:
        s = args[0]
        out = _combine(fn(s.to_arrow()))
        return Series(s.name, out_dt, out.cast(out_dt.to_arrow()))

    return host


register("dt_year", _rt_const(DataType.int32()), _dt1(pc.year, DataType.int32()))
register("dt_month", _rt_const(DataType.uint32()), _dt1(pc.month, DataType.uint32()))
register("dt_day", _rt_const(DataType.uint32()), _dt1(pc.day, DataType.uint32()))
register("dt_hour", _rt_const(DataType.uint32()), _dt1(pc.hour, DataType.uint32()))
register("dt_minute", _rt_const(DataType.uint32()), _dt1(pc.minute, DataType.uint32()))
register("dt_second", _rt_const(DataType.uint32()), _dt1(pc.second, DataType.uint32()))
register("dt_millisecond", _rt_const(DataType.uint32()), _dt1(pc.millisecond, DataType.uint32()))
register("dt_microsecond", _rt_const(DataType.uint32()), _dt1(pc.microsecond, DataType.uint32()))
register("dt_day_of_year", _rt_const(DataType.uint32()), _dt1(pc.day_of_year, DataType.uint32()))
register("dt_week_of_year", _rt_const(DataType.uint32()), _dt1(pc.iso_week, DataType.uint32()))


def _dt_day_of_week(args, kwargs):
    s = args[0]
    out = _combine(pc.day_of_week(s.to_arrow()))  # Monday=0
    return Series(s.name, DataType.uint32(), out.cast(pa.uint32()))


register("dt_day_of_week", _rt_const(DataType.uint32()), _dt_day_of_week)


def _dt_date(args, kwargs):
    s = args[0]
    return Series(s.name, DataType.date(), _combine(s.to_arrow().cast(pa.date32())))


register("dt_date", _rt_const(DataType.date()), _dt_date)


def _dt_time(args, kwargs):
    s = args[0]
    out = _combine(pc.cast(s.to_arrow(), pa.time64("us")))
    return Series(s.name, DataType.time("us"), out)


register("dt_time", lambda f, k: DataType.time("us"), _dt_time)


def _dt_truncate(args, kwargs):
    s = args[0]
    interval = kwargs["interval"]  # e.g. "1 day", "1 hour"
    count, unit = interval.split()
    unit = unit.rstrip("s")
    out = _combine(pc.floor_temporal(s.to_arrow(), multiple=int(count), unit=unit))
    return Series(s.name, s.dtype, out)


register("dt_truncate", _rt_same, _dt_truncate)


def _dt_to_unix_epoch(args, kwargs):
    s = args[0]
    unit = kwargs.get("unit", "s")
    arr = s.to_arrow()
    if pa.types.is_date(arr.type):
        arr = arr.cast(pa.timestamp("s"))
    target_unit = {"s": "s", "ms": "ms", "us": "us", "ns": "ns"}[unit]
    arr = arr.cast(pa.timestamp(target_unit)) if not pa.types.is_timestamp(arr.type) else arr.cast(
        pa.timestamp(target_unit, getattr(arr.type, "tz", None))
    )
    return Series(s.name, DataType.int64(), _combine(arr.cast(pa.int64())))


register("dt_to_unix_epoch", _rt_const(DataType.int64()), _dt_to_unix_epoch)


def _dt_strftime(args, kwargs):
    s = args[0]
    fmt = kwargs.get("format") or "%Y-%m-%dT%H:%M:%S%.f"
    arr = s.to_arrow()
    if pa.types.is_date(arr.type):
        fmt = kwargs.get("format") or "%Y-%m-%d"
        arr = arr.cast(pa.timestamp("s"))
    fmt = fmt.replace("%.f", "%f")
    out = pc.strftime(arr, format=fmt)
    return Series(s.name, DataType.string(), _combine(out).cast(pa.large_string()))


register("dt_strftime", _rt_const(DataType.string()), _dt_strftime)


# ===================================================================================
# list
# ===================================================================================


def _list_length(args, kwargs):
    s = args[0]
    out = pc.list_value_length(s.to_arrow())
    return Series(s.name, DataType.uint64(), _combine(out).cast(pa.uint64()))


register("list_length", _rt_const(DataType.uint64()), _list_length)


def _list_offsets_values(arr: pa.Array):
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    offsets = np.asarray(arr.offsets.to_numpy(zero_copy_only=False), dtype=np.int64)
    return offsets, arr.values


def _list_agg(np_reduce, needs_float):
    def host(args: List[Series], kwargs) -> Series:
        s = args[0]
        arr = s.to_arrow()
        offsets, values = _list_offsets_values(arr)
        inner = Series("v", s.dtype.inner, values)
        vals = inner.to_numpy().astype(np.float64)
        valid_inner = inner.validity_numpy()
        n = len(arr)
        out = np.empty(n, dtype=np.float64)
        out_valid = np.empty(n, dtype=bool)
        for i in range(n):
            seg = vals[offsets[i] : offsets[i + 1]]
            segv = valid_inner[offsets[i] : offsets[i + 1]]
            seg = seg[segv]
            if len(seg) == 0:
                out_valid[i] = False
                out[i] = 0
            else:
                out_valid[i] = True
                out[i] = np_reduce(seg)
        out_valid &= s.validity_numpy()
        res = pa.array(out)
        if needs_float:
            dt = DataType.float64()
        else:
            dt = s.dtype.inner
            res = res.cast(dt.to_arrow())
        res = pc.if_else(pa.array(out_valid), res, pa.nulls(n, type=res.type))
        return Series(s.name, dt, _combine(res))

    return host


register("list_sum", lambda f, k: f[0].dtype.inner, _list_agg(np.sum, False))
register("list_mean", _rt_const(DataType.float64()), _list_agg(np.mean, True))
register("list_min", _rt_inner, _list_agg(np.min, False))
register("list_max", _rt_inner, _list_agg(np.max, False))


def _list_get(args, kwargs):
    s = args[0]
    idx = _scalar_arg(args[1])
    default = args[2].to_pylist()[0] if len(args) > 2 and args[2] is not None else None

    def f(v):
        if v is None:
            return None
        if -len(v) <= idx < len(v):
            return v[idx]
        return default

    return Series.from_pylist([f(v) for v in s.to_pylist()], s.name, s.dtype.inner)


register("list_get", _rt_inner, _list_get)


def _list_join(args, kwargs):
    s, delim = args[0], _scalar_arg(args[1])
    out = pc.binary_join(s.to_arrow(), pa.scalar(delim, type=pa.large_string()))
    return Series(s.name, DataType.string(), _combine(out).cast(pa.large_string()))


register("list_join", _rt_const(DataType.string()), _list_join)


def _list_contains(args, kwargs):
    s, v = args[0], args[1]
    target = v.to_pylist()[0]
    return Series.from_pylist(
        [None if row is None else (target in row) for row in s.to_pylist()],
        s.name,
        DataType.bool(),
    )


register("list_contains", _rt_const(DataType.bool()), _list_contains)


def _list_slice(args, kwargs):
    s = args[0]
    start = _scalar_arg(args[1])
    end = _scalar_arg(args[2]) if len(args) > 2 and args[2] is not None else None
    return Series.from_pylist(
        [None if v is None else v[start:end] for v in s.to_pylist()], s.name, s.dtype
    )


register("list_slice", _rt_same, _list_slice)


def _list_sort(args, kwargs):
    s = args[0]
    desc = kwargs.get("desc", False)
    return Series.from_pylist(
        [None if v is None else sorted([x for x in v if x is not None], reverse=desc) + [None] * sum(1 for x in v if x is None) for v in s.to_pylist()],
        s.name,
        s.dtype,
    )


register("list_sort", _rt_same, _list_sort)


def _list_distinct(args, kwargs):
    s = args[0]

    def f(v):
        if v is None:
            return None
        seen = set()
        out = []
        for x in v:
            if x is not None and x not in seen:
                seen.add(x)
                out.append(x)
        return out

    return Series.from_pylist([f(v) for v in s.to_pylist()], s.name, s.dtype)


register("list_distinct", _rt_same, _list_distinct)


def _list_chunk(args, kwargs):
    s = args[0]
    size = kwargs["size"]

    def f(v):
        if v is None:
            return None
        return [v[i : i + size] for i in range(0, len(v) - size + 1, size)]

    return Series.from_pylist([f(v) for v in s.to_pylist()], s.name, DataType.list(s.dtype))


register("list_chunk", lambda f, k: DataType.list(f[0].dtype), _list_chunk)


def _list_count(args, kwargs):
    s = args[0]
    mode = kwargs.get("mode", "valid")

    def f(v):
        if v is None:
            return 0
        if mode == "valid":
            return sum(1 for x in v if x is not None)
        if mode == "null":
            return sum(1 for x in v if x is None)
        return len(v)

    return Series.from_pylist([f(v) for v in s.to_pylist()], s.name, DataType.uint64())


register("list_count", _rt_const(DataType.uint64()), _list_count)


def _list_value_counts(args, kwargs):
    s = args[0]

    def f(v):
        if v is None:
            return None
        counts: Dict[Any, int] = {}
        for x in v:
            if x is not None:
                counts[x] = counts.get(x, 0) + 1
        return [{"key": k2, "value": c} for k2, c in counts.items()]

    inner = s.dtype.inner
    return Series.from_pylist(
        [f(v) for v in s.to_pylist()],
        s.name,
        DataType.list(DataType.struct({"key": inner, "value": DataType.uint64()})),
    )


register(
    "list_value_counts",
    lambda f, k: DataType.list(DataType.struct({"key": f[0].dtype.inner, "value": DataType.uint64()})),
    _list_value_counts,
)


# ===================================================================================
# struct
# ===================================================================================


def _struct_get(args, kwargs):
    s = args[0]
    name = kwargs["name"]
    out = pc.struct_field(s.to_arrow(), name)
    return Series(name, DataType.from_arrow(out.type), _combine(out))


def _rt_struct_get(fields, kwargs):
    for n, t in fields[0].dtype.struct_fields:
        if n == kwargs["name"]:
            return t
    raise ValueError(f"struct has no field {kwargs['name']!r}")


register("struct_get", _rt_struct_get, _struct_get)


# ===================================================================================
# embedding / vector distance
# ===================================================================================


def _vec_2d(s) -> np.ndarray:
    """(n, d) float64 view of an embedding/fixed-size-list OR variable list column
    (variable lists must be rectangular)."""
    v = s.to_numpy()
    if v.dtype == object or v.ndim == 1:
        rows = s.to_pylist()
        d = next((len(r) for r in rows if r is not None), 0)
        out = np.zeros((len(rows), d), dtype=np.float64)
        for i, r in enumerate(rows):
            if r is not None:
                out[i] = np.asarray(r, dtype=np.float64)
        return out
    return v.astype(np.float64)


def _vec_pair(args):
    a, b = args[0], args[1]
    av, bv = _vec_2d(a), _vec_2d(b)
    if len(b) == 1 and len(a) != 1:
        bv = np.broadcast_to(bv, (len(a), bv.shape[1]))
    valid = a.validity_numpy() & (b.validity_numpy() if len(b) == len(a) else np.ones(len(a), bool))
    return a, av, bv, valid


def _mk_dist(fn):
    def host(args, kwargs):
        a, av, bv, valid = _vec_pair(args)
        with np.errstate(all="ignore"):
            out = fn(av, bv)
        arr = pa.array(out)
        arr = pc.if_else(pa.array(valid), arr, pa.nulls(len(arr), type=arr.type))
        return Series(a.name, DataType.float64(), _combine(arr))

    return host


def _cosine(av, bv):
    num = (av * bv).sum(axis=1)
    den = np.linalg.norm(av, axis=1) * np.linalg.norm(bv, axis=1)
    return 1.0 - num / den


register("cosine_distance", _rt_const(DataType.float64()), _mk_dist(_cosine))
register("dot", _rt_const(DataType.float64()), _mk_dist(lambda a, b: (a * b).sum(axis=1)))
register(
    "euclidean_distance",
    _rt_const(DataType.float64()),
    _mk_dist(lambda a, b: np.linalg.norm(a - b, axis=1)),
)


def _embedding_norm(args, kwargs):
    s = args[0]
    av = s.to_numpy().astype(np.float64)
    out = np.linalg.norm(av, axis=1)
    arr = pa.array(out)
    arr = pc.if_else(pa.array(s.validity_numpy()), arr, pa.nulls(len(arr), type=arr.type))
    return Series(s.name, DataType.float64(), _combine(arr))


register("embedding_norm", _rt_const(DataType.float64()), _embedding_norm)


# ===================================================================================
# minhash (LSH dedup; reference: src/daft-minhash)
# ===================================================================================


def _minhash(args, kwargs):
    from ..core.kernels.minhash import minhash_series

    return minhash_series(
        args[0],
        num_hashes=kwargs.get("num_hashes", 16),
        ngram_size=kwargs.get("ngram_size", 1),
        seed=kwargs.get("seed", 1),
    )


register(
    "minhash",
    lambda f, k: DataType.fixed_size_list(DataType.uint64(), k.get("num_hashes", 16)),
    _minhash,
)


# ===================================================================================
# tokenize (reference: src/daft-functions-tokenize — BPE encode/decode)
# ===================================================================================

_TOKENIZERS: Dict[str, object] = {}
_TOKENIZERS_LOCK = threading.Lock()


def _load_tokenizer(name: str):
    """'bytes' builtin (UTF-8 byte ids, reversible, dependency-free) or a path
    to a HuggingFace tokenizers JSON file (BPE etc., no network needed)."""
    with _TOKENIZERS_LOCK:
        if name in _TOKENIZERS:
            return _TOKENIZERS[name]
    if name == "bytes":
        tok = None
    else:
        try:
            from tokenizers import Tokenizer
        except ImportError as e:  # pragma: no cover
            raise ValueError(
                "tokenize with a model file requires the 'tokenizers' package") from e
        # loaded OUTSIDE the lock (file IO); a racing loader just builds the
        # same immutable tokenizer and last-write-wins below
        tok = Tokenizer.from_file(name)
    with _TOKENIZERS_LOCK:
        _TOKENIZERS[name] = tok
    return tok


def _tokenize_encode(args, kwargs):
    name = kwargs.get("tokenizer", "bytes")
    tok = _load_tokenizer(name)
    out = []
    for text in args[0].to_pylist():
        if text is None:
            out.append(None)
        elif tok is None:
            out.append(list(text.encode("utf-8")))
        else:
            out.append(tok.encode(text).ids)
    return Series.from_pylist(out, args[0].name, DataType.list(DataType.uint32()))


def _tokenize_decode(args, kwargs):
    name = kwargs.get("tokenizer", "bytes")
    tok = _load_tokenizer(name)
    out = []
    for ids in args[0].to_pylist():
        if ids is None:
            out.append(None)
        elif tok is None:
            out.append(bytes(ids).decode("utf-8", "replace"))
        else:
            out.append(tok.decode(ids))
    return Series.from_pylist(out, args[0].name, DataType.string())


register("tokenize_encode", _rt_const(DataType.list(DataType.uint32())), _tokenize_encode)
register("tokenize_decode", _rt_const(DataType.string()), _tokenize_decode)


# ===================================================================================
# misc
# ===================================================================================


def _monotonically_increasing_id(args, kwargs):
    raise ValueError("monotonically_increasing_id is evaluated by the executor, not as a scalar fn")


register("monotonically_increasing_id", _rt_const(DataType.uint64()), _monotonically_increasing_id)


def _uuid_host(args, kwargs):
    import uuid as _uuid

    n = kwargs.get("__num_rows", 1)
    return Series.from_pylist([str(_uuid.uuid4()) for _ in range(n)], "uuid", DataType.string())


register("uuid", _rt_const(DataType.string()), _uuid_host)


# ===================================================================================
# image (reference: src/daft-image/src/ops.rs via daft-functions image module)
# ===================================================================================


def _img(args):
    return args[0]


register("image_decode", _rt_const(DataType.image()),
         lambda a, k: __import__("daft_tpu.core.kernels.image", fromlist=["decode"]).decode(
             a[0], k.get("mode"), k.get("on_error", "raise")))
register("image_encode", _rt_const(DataType.binary()),
         lambda a, k: __import__("daft_tpu.core.kernels.image", fromlist=["encode"]).encode(
             a[0], k.get("image_format", "PNG")))
register("image_resize", _rt_const(DataType.image()),
         lambda a, k: __import__("daft_tpu.core.kernels.image", fromlist=["resize"]).resize(
             a[0], k["w"], k["h"]))
register("image_crop", _rt_const(DataType.image()),
         lambda a, k: __import__("daft_tpu.core.kernels.image", fromlist=["crop"]).crop(
             a[0], k["bbox"]))
register("image_to_mode", _rt_const(DataType.image()),
         lambda a, k: __import__("daft_tpu.core.kernels.image", fromlist=["to_mode"]).to_mode(
             a[0], k["mode"]))


def _image_fixed_rt(fields, kwargs):
    return DataType.fixed_shape_image(kwargs["mode"], kwargs["h"], kwargs["w"])


register("image_to_fixed_shape", _image_fixed_rt,
         lambda a, k: __import__("daft_tpu.core.kernels.image", fromlist=["to_fixed_shape"]).to_fixed_shape(
             a[0], k["mode"], k["h"], k["w"]))


# ===================================================================================
# url (reference: daft-functions-uri url download/upload — multimodal fetch)
# ===================================================================================


def _url_download(args, kwargs):
    s = args[0]
    on_error = kwargs.get("on_error", "raise")
    out = []
    for v in s.to_pylist():
        if v is None:
            out.append(None)
            continue
        try:
            if v.startswith("http://") or v.startswith("https://"):
                import urllib.request

                with urllib.request.urlopen(v, timeout=kwargs.get("timeout", 30)) as r:
                    out.append(r.read())
            else:
                path = v[len("file://"):] if v.startswith("file://") else v
                with open(path, "rb") as f:
                    out.append(f.read())
        except Exception:
            if on_error == "raise":
                raise
            out.append(None)
    return Series(s.name, DataType.binary(), pa.array(out, pa.large_binary()))


register("url_download", _rt_const(DataType.binary()), _url_download)


def _url_upload(args, kwargs):
    import os as _os
    import uuid as _uuid

    s = args[0]
    location = kwargs["location"]
    _os.makedirs(location, exist_ok=True)
    out = []
    for v in s.to_pylist():
        if v is None:
            out.append(None)
            continue
        path = _os.path.join(location, _uuid.uuid4().hex)
        with open(path, "wb") as f:
            f.write(v)
        out.append(path)
    return Series(s.name, DataType.string(), pa.array(out, pa.large_string()))


register("url_upload", _rt_const(DataType.string()), _url_upload)


# breadth modules register on import (binary/crypto/bitwise/json/map/...)
from . import extra  # noqa: E402,F401  (registration side effects)
from . import breadth  # noqa: E402,F401  (registration side effects)
from . import media  # noqa: E402,F401  (registration side effects)
