"""Ranking window functions (reference parity: daft/functions/window.py)."""

from __future__ import annotations

from ..expressions.expressions import _UnboundWindowFn


def row_number():
    return _UnboundWindowFn("row_number", None, {})


def rank():
    return _UnboundWindowFn("rank", None, {})


def dense_rank():
    return _UnboundWindowFn("dense_rank", None, {})


def percent_rank():
    return _UnboundWindowFn("percent_rank", None, {})


def cume_dist():
    return _UnboundWindowFn("cume_dist", None, {})


def ntile(n: int):
    return _UnboundWindowFn("ntile", None, {"n": n})
