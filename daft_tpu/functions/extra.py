"""Function-registry breadth: binary, crypto, bitwise, JSON, map, temporal and
string-distance kernels.

Reference parity: the daft-functions-* crates — daft-functions-binary
(length/concat/slice/encode/decode), daft-functions-utf8 (title/levenshtein/
normalize), daft-functions-temporal (quarter/leap-year), daft-functions-json
(json_query via jsonpath), daft-functions-map, plus hash/bitwise kernels from
daft-functions. Host implementations ride pyarrow.compute where a kernel
exists; value-level paths (crypto, json, map) run vectorized Python over
Arrow values — these are auxiliary functions, not the hot path.
"""

from __future__ import annotations

import base64 as _b64
import binascii
import hashlib
import json as _json
from typing import List

import pyarrow as pa
import pyarrow.compute as pc

from ..core.series import Series, _combine
from ..datatype import DataType
from .registry import (_binary_arrow, _dt1, _rt_const, _rt_same, register)


def _value_map(fn, out_dtype: DataType):
    """Lift a per-value python function (None-safe) to a host kernel."""

    def host(args: List[Series], kwargs) -> Series:
        s = args[0]
        out = [None if v is None else fn(v, kwargs) for v in s.to_pylist()]
        return Series.from_pylist(out, s.name, dtype=out_dtype)

    return host


# ===================================================================================
# binary (reference: daft-functions-binary)
# ===================================================================================

register("binary_length", _rt_const(DataType.uint64()),
         _dt1(pc.binary_length, DataType.uint64()))
register("binary_concat", _rt_same,
         _binary_arrow(lambda a, b: pc.binary_join_element_wise(a, b, b"")))


def _binary_slice(args, kwargs):
    s = args[0]
    start = int(kwargs.get("start", 0))
    length = kwargs.get("length")
    out = [None if v is None
           else (v[start:start + int(length)] if length is not None else v[start:])
           for v in s.to_pylist()]
    return Series.from_pylist(out, s.name, dtype=DataType.binary())


register("binary_slice", _rt_const(DataType.binary()), _binary_slice)

register("encode_hex", _rt_const(DataType.string()),
         _value_map(lambda v, k: (v.encode() if isinstance(v, str) else v).hex(),
                    DataType.string()))
register("decode_hex", _rt_const(DataType.binary()),
         _value_map(lambda v, k: binascii.unhexlify(v), DataType.binary()))
register("encode_base64", _rt_const(DataType.string()),
         _value_map(lambda v, k: _b64.b64encode(
             v.encode() if isinstance(v, str) else v).decode(), DataType.string()))
register("decode_base64", _rt_const(DataType.binary()),
         _value_map(lambda v, k: _b64.b64decode(v), DataType.binary()))


# ===================================================================================
# crypto hashes
# ===================================================================================

def _hasher(name):
    def one(v, _k):
        data = v.encode() if isinstance(v, str) else bytes(v)
        return hashlib.new(name, data).hexdigest()

    return one


for _algo in ("md5", "sha1", "sha256", "sha512"):
    register(_algo, _rt_const(DataType.string()),
             _value_map(_hasher(_algo), DataType.string()))


# ===================================================================================
# bitwise (pyarrow kernels; int-preserving)
# ===================================================================================

register("bitwise_and", _rt_same, _binary_arrow(pc.bit_wise_and))
register("bitwise_or", _rt_same, _binary_arrow(pc.bit_wise_or))
register("bitwise_xor", _rt_same, _binary_arrow(pc.bit_wise_xor))
def _bitwise_not(args, kwargs):
    s0 = args[0]
    return Series(s0.name, s0.dtype, _combine(pc.bit_wise_not(s0.to_arrow())))


register("bitwise_not", _rt_same, _bitwise_not)
register("shift_left", _rt_same, _binary_arrow(pc.shift_left))
register("shift_right", _rt_same, _binary_arrow(pc.shift_right))


# ===================================================================================
# temporal breadth (reference: daft-functions-temporal)
# ===================================================================================

register("dt_quarter", _rt_const(DataType.uint32()),
         _dt1(pc.quarter, DataType.uint32()))
register("dt_is_leap_year", _rt_const(DataType.bool()),
         _dt1(pc.is_leap_year, DataType.bool()))


def _dt_days_in_month(args, kwargs):
    import calendar

    s = args[0]
    out = [None if v is None else calendar.monthrange(v.year, v.month)[1]
           for v in s.to_pylist()]
    return Series.from_pylist(out, s.name, dtype=DataType.uint32())


register("dt_days_in_month", _rt_const(DataType.uint32()), _dt_days_in_month)


# ===================================================================================
# JSON (reference: daft-functions-json jsonpath queries)
# ===================================================================================

def _json_get(doc, path: str):
    """Minimal jsonpath: $.a.b[2].c — object keys and array indices."""
    cur = doc
    if path.startswith("$"):
        path = path[1:]
    for part in path.replace("]", "").split("."):
        if not part:
            continue
        for piece in part.split("["):
            if piece == "":
                continue
            if cur is None:
                return None
            if isinstance(cur, list):
                try:
                    cur = cur[int(piece)]
                except (ValueError, IndexError):
                    return None
            elif isinstance(cur, dict):
                cur = cur.get(piece)
            else:
                return None
    return cur


def _json_query(args, kwargs):
    s = args[0]
    path = kwargs.get("path", "$")
    out = []
    for v in s.to_pylist():
        if v is None:
            out.append(None)
            continue
        try:
            res = _json_get(_json.loads(v), path)
        except (ValueError, TypeError):
            res = None
        if res is None:
            out.append(None)
        elif isinstance(res, str):
            out.append(res)
        else:  # JSON text, not Python reprs (true/false, not True/False)
            out.append(_json.dumps(res))
    return Series.from_pylist(out, s.name, dtype=DataType.string())


register("json_query", _rt_const(DataType.string()), _json_query)


def _to_json(args, kwargs):
    s = args[0]
    out = [None if v is None else _json.dumps(v, default=str) for v in s.to_pylist()]
    return Series.from_pylist(out, s.name, dtype=DataType.string())


register("to_json", _rt_const(DataType.string()), _to_json)


# ===================================================================================
# map (reference: daft-functions-map map_get)
# ===================================================================================

def _map_value_dtype(dt: DataType, key) -> DataType:
    if dt.kind == "map":
        return dt.params[1]  # (key, value) dtypes
    if dt.kind == "struct":
        for name, fdt in dt.struct_fields:
            if name == key:
                return fdt
    return DataType.string()


def _map_get(args, kwargs):
    s = args[0]
    key = kwargs["key"]
    out = []
    for v in s.to_pylist():
        if v is None:
            out.append(None)
        elif isinstance(v, dict):
            out.append(v.get(key))
        else:  # arrow maps decode as [(k, val), ...]
            out.append(next((val for k, val in v if k == key), None))
    # dtype from the input type, NOT value inference: an all-missing morsel
    # must still produce the planned dtype so per-morsel results concat
    return Series.from_pylist(out, s.name, dtype=_map_value_dtype(s.dtype, key))


def _rt_map_value(fields, kwargs):
    return _map_value_dtype(fields[0].dtype, kwargs.get("key"))


register("map_get", _rt_map_value, _map_get)


# ===================================================================================
# string breadth: title, normalize-ascii, levenshtein, jaccard similarity
# ===================================================================================

register("utf8_title", _rt_const(DataType.string()),
         _value_map(lambda v, k: v.title(), DataType.string()))


def _levenshtein(args, kwargs):
    a, b = args[0], args[1]
    av, bv = a.to_pylist(), b.to_pylist()
    if len(bv) == 1 and len(av) != 1:
        bv = bv * len(av)
    out = []
    for x, y in zip(av, bv):
        if x is None or y is None:
            out.append(None)
            continue
        if len(x) < len(y):
            x, y = y, x
        prev = list(range(len(y) + 1))
        for i, cx in enumerate(x):
            cur = [i + 1]
            for j, cy in enumerate(y):
                cur.append(min(prev[j + 1] + 1, cur[j] + 1, prev[j] + (cx != cy)))
            prev = cur
        out.append(prev[-1])
    return Series.from_pylist(out, a.name, dtype=DataType.uint32())


register("levenshtein", _rt_const(DataType.uint32()), _levenshtein)


def _jaccard(args, kwargs):
    a, b = args[0], args[1]
    n = int(kwargs.get("ngram", 2))
    av, bv = a.to_pylist(), b.to_pylist()
    if len(bv) == 1 and len(av) != 1:
        bv = bv * len(av)

    def grams(s):
        return {s[i:i + n] for i in range(max(len(s) - n + 1, 1))}

    out = []
    for x, y in zip(av, bv):
        if x is None or y is None:
            out.append(None)
            continue
        gx, gy = grams(x), grams(y)
        union = len(gx | gy)
        out.append(len(gx & gy) / union if union else 1.0)
    return Series.from_pylist(out, a.name, dtype=DataType.float64())


register("jaccard_similarity", _rt_const(DataType.float64()), _jaccard)


# ===================================================================================
# misc: coalesce (variadic), null_if
# ===================================================================================

def _coalesce(args, kwargs):
    out = args[0].to_arrow()
    for s in args[1:]:
        nxt = s.to_arrow()
        if len(nxt) == 1 and len(out) != 1:
            nxt = pa.chunked_array([pa.array(nxt.to_pylist() * len(out), type=nxt.type)])
        out = pc.coalesce(out, nxt)
    return Series(args[0].name, DataType.from_arrow(out.type), _combine(out))


def _rt_coalesce(fields, kwargs):
    for f in fields:
        if not f.dtype.is_null():
            return f.dtype
    return fields[0].dtype


register("coalesce", _rt_coalesce, _coalesce)
