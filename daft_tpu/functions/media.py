"""Audio / video / process function namespaces.

Reference parity: daft/functions/audio.py (audio_metadata, resample),
daft/functions/video.py (video_metadata, video_keyframes — gated on `av`),
daft/functions/process.py (run_process), daft/functions/similarity.py.
WAV audio is handled natively with the stdlib `wave` module + numpy (zero
extra dependencies); other codecs route through `soundfile` when installed,
exactly like the reference routes through its optional deps.
"""

from __future__ import annotations

import io
import subprocess
from typing import Any, List, Optional

import numpy as np

from ..core.series import Series
from ..datatype import DataType
from .registry import _rt_const, register

AUDIO_META_STRUCT = DataType.struct({
    "sample_rate": DataType.int64(), "channels": DataType.int64(),
    "frames": DataType.float64(), "format": DataType.string(),
    "subtype": DataType.string(),
})


def _file_bytes(v, io_config=None) -> Optional[bytes]:
    """Materialize one file-column value's bytes (lazy File struct or bytes)."""
    if v is None:
        return None
    if isinstance(v, (bytes, bytearray)):
        return bytes(v)
    if isinstance(v, dict):
        if v.get("data") is not None:
            return v["data"]
        from ..filetype import File

        return File(v["path"], io_config).read()
    if isinstance(v, str):
        from ..filetype import File

        return File(v, io_config).read()
    raise ValueError(f"cannot read audio from value of type {type(v).__name__}")


_WAV_SUBTYPES = {1: "PCM_8", 2: "PCM_16", 3: "PCM_24", 4: "PCM_32"}


def _wav_decode(data: bytes):
    """(samples float64 [frames, channels], sample_rate, subtype) via stdlib."""
    import wave

    with wave.open(io.BytesIO(data), "rb") as w:
        sr = w.getframerate()
        nch = w.getnchannels()
        width = w.getsampwidth()
        nframes = w.getnframes()
        raw = w.readframes(nframes)
    if width == 1:
        arr = (np.frombuffer(raw, np.uint8).astype(np.float64) - 128.0) / 128.0
    elif width == 2:
        arr = np.frombuffer(raw, "<i2").astype(np.float64) / 32768.0
    elif width == 3:
        b = np.frombuffer(raw, np.uint8).reshape(-1, 3)
        vals = (b[:, 0].astype(np.int32) | (b[:, 1].astype(np.int32) << 8)
                | (b[:, 2].astype(np.int32) << 16))
        vals = np.where(vals >= 1 << 23, vals - (1 << 24), vals)
        arr = vals.astype(np.float64) / float(1 << 23)
    elif width == 4:
        arr = np.frombuffer(raw, "<i4").astype(np.float64) / float(1 << 31)
    else:
        raise ValueError(f"unsupported WAV sample width {width}")
    return arr.reshape(-1, nch), sr, _WAV_SUBTYPES.get(width, f"PCM_{8 * width}")


def _is_wav(data: bytes) -> bool:
    return len(data) >= 12 and data[:4] == b"RIFF" and data[8:12] == b"WAVE"


def _audio_meta_one(data: bytes) -> dict:
    if _is_wav(data):
        samples, sr, subtype = _wav_decode(data)
        return {"sample_rate": sr, "channels": samples.shape[1],
                "frames": float(samples.shape[0]), "format": "WAV",
                "subtype": subtype}
    try:
        import soundfile as sf
    except ImportError as e:
        raise ImportError(
            "non-WAV audio requires the 'soundfile' package "
            "(WAV is handled natively)") from e
    info = sf.info(io.BytesIO(data))
    return {"sample_rate": int(info.samplerate), "channels": int(info.channels),
            "frames": float(info.frames), "format": info.format,
            "subtype": info.subtype}


def _audio_metadata_host(args: List[Series], kwargs) -> Series:
    io_config = kwargs.get("io_config")
    out = []
    for v in args[0].to_pylist():
        data = _file_bytes(v, io_config)
        out.append(None if data is None else _audio_meta_one(data))
    return Series.from_pylist(out, args[0].name, dtype=AUDIO_META_STRUCT)


register("audio_metadata", _rt_const(AUDIO_META_STRUCT), _audio_metadata_host)


def _linear_resample(samples: np.ndarray, sr: int, target: int) -> np.ndarray:
    if sr == target or samples.shape[0] == 0:
        return samples
    n_out = max(int(round(samples.shape[0] * target / sr)), 1)
    x_old = np.linspace(0.0, 1.0, samples.shape[0], endpoint=False)
    x_new = np.linspace(0.0, 1.0, n_out, endpoint=False)
    return np.stack([np.interp(x_new, x_old, samples[:, c])
                     for c in range(samples.shape[1])], axis=1)


def _audio_resample_host(args: List[Series], kwargs) -> Series:
    target = kwargs["sample_rate"]
    io_config = kwargs.get("io_config")
    out = []
    for v in args[0].to_pylist():
        data = _file_bytes(v, io_config)
        if data is None:
            out.append(None)
            continue
        if _is_wav(data):
            samples, sr, _sub = _wav_decode(data)
        else:
            try:
                import soundfile as sf
            except ImportError as e:
                raise ImportError("non-WAV audio requires 'soundfile'") from e
            samples, sr = sf.read(io.BytesIO(data), always_2d=True)
        out.append(_linear_resample(samples, sr, target))
    return Series.from_pylist(out, args[0].name, dtype=DataType.python())


register("audio_resample", lambda f, k: DataType.python(), _audio_resample_host)


# ---- video (gated: no codec library in this environment) ----------------------------


def _video_gate(*_a, **_k):
    try:
        import av  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "video functions require the 'av' package (PyAV)") from e


VIDEO_META_STRUCT = DataType.struct({
    "width": DataType.int64(), "height": DataType.int64(),
    "fps": DataType.float64(), "frames": DataType.int64(),
    "duration_s": DataType.float64(), "codec": DataType.string(),
})


def _video_metadata_host(args: List[Series], kwargs) -> Series:
    _video_gate()
    import av

    io_config = kwargs.get("io_config")
    out = []
    for v in args[0].to_pylist():
        data = _file_bytes(v, io_config)
        if data is None:
            out.append(None)
            continue
        with av.open(io.BytesIO(data)) as c:
            vs = c.streams.video[0]
            out.append({"width": vs.width, "height": vs.height,
                        "fps": float(vs.average_rate or 0),
                        "frames": vs.frames,
                        "duration_s": float((vs.duration or 0) * vs.time_base),
                        "codec": vs.codec_context.name})
    return Series.from_pylist(out, args[0].name, dtype=VIDEO_META_STRUCT)


register("video_metadata", _rt_const(VIDEO_META_STRUCT), _video_metadata_host)


# ---- run_process (reference: daft/functions/process.py) -----------------------------


def run_process(args, *, shell: bool = False, on_error: str = "log",
                return_dtype: Optional[DataType] = None):
    """Run an external process per row, stdout becomes the column value
    (reference: daft.functions.run_process)."""
    from ..expressions.expressions import Expression, Literal
    from ..udf import udf

    dt = return_dtype or DataType.string()
    if not isinstance(args, (list, tuple)):
        args = [args]
    # bare python values (incl. strings like "echo") are literals — only
    # Expressions reference columns
    exprs = [a if isinstance(a, Expression) else Literal(a) for a in args]

    @udf(return_dtype=dt)
    def _run(*cols):
        n = max(len(c) for c in cols)
        pycols = [c.to_pylist() for c in cols]
        pycols = [c * n if len(c) == 1 and n != 1 else c for c in pycols]
        out: List[Any] = []
        for row in zip(*pycols):
            argv = [str(a) for a in row]
            try:
                if shell:
                    res = subprocess.run(" ".join(argv), shell=True,
                                         capture_output=True, text=True,
                                         check=True)
                else:
                    res = subprocess.run(argv, capture_output=True, text=True,
                                         check=True)
                val = res.stdout
                if dt.is_integer():
                    val = int(val.strip())
                elif dt.is_floating():
                    val = float(val.strip())
                out.append(val)
            except Exception as e:
                if on_error == "raise":
                    raise
                if on_error == "log":
                    import logging

                    logging.getLogger(__name__).warning("run_process failed: %s", e)
                out.append(None)
        return out

    return _run(*exprs)
