"""AI expression functions (reference parity: daft/functions/ai/__init__.py:72-453
embed_text/classify_text/prompt over the provider protocol layer)."""

from __future__ import annotations

from typing import List, Optional

from ..datatype import DataType
from ..expressions import Expression
from ..udf.udf import Func


def _batch_func(fn, name: str, return_dtype: DataType, max_concurrency=None,
                use_process: bool = False, route_prefix_len=None) -> Func:
    return Func(fn=fn, return_dtype=return_dtype, is_batch=True, name=name,
                max_concurrency=max_concurrency, use_process=use_process,
                route_prefix_len=route_prefix_len)


def embed_text(expr: Expression, provider: str = "transformers",
               model: Optional[str] = None, **options) -> Expression:
    """Embed a text column via the named provider; model loads lazily per worker.

    ``provider="jax"`` returns a DEVICE UDF (ops/udf_stage.py): the encoder
    runs as a staged device dispatch with weights resident in HBM, and the
    planner can fuse it into downstream device stages. Other providers stay
    plain host batch UDFs."""
    if provider == "jax":
        from ..ai.jax_provider import jax_embed_func

        batch_size = options.pop("batch_size", None)
        if options:
            raise TypeError(
                f"embed_text(provider='jax') got unsupported options "
                f"{sorted(options)}; the device tier accepts batch_size only")
        return jax_embed_func(model, batch_size=batch_size)(expr)
    from ..ai.provider import get_provider
    from ..core.series import Series

    state = {}

    def run(s: Series) -> Series:
        if "embedder" not in state:
            state["embedder"] = get_provider(provider).get_text_embedder(model, **options)
        texts = s.to_pylist()
        mask = [t is not None for t in texts]
        vecs = state["embedder"].embed_text([t for t in texts if t is not None])
        it = iter(vecs)
        out = [list(map(float, next(it))) if m else None for m in mask]
        return Series.from_pylist(out, s.name, DataType.list(DataType.float32()))

    return _batch_func(run, "embed_text", DataType.list(DataType.float32()))(expr)


def classify_text(expr: Expression, labels: List[str], provider: str = "dummy",
                  model: Optional[str] = None, **options) -> Expression:
    """Zero-shot classify a text column. ``provider="jax"`` runs encoder +
    label argmax as ONE device-UDF program (only int32 winner codes leave
    the device); other providers stay host batch UDFs."""
    if provider == "jax":
        from ..ai.jax_provider import jax_classify_func

        batch_size = options.pop("batch_size", None)
        if options:
            raise TypeError(
                f"classify_text(provider='jax') got unsupported options "
                f"{sorted(options)}; the device tier accepts batch_size only")
        return jax_classify_func(labels, model, batch_size=batch_size)(expr)
    from ..ai.provider import get_provider
    from ..core.series import Series

    state = {}

    def run(s: Series) -> Series:
        if "clf" not in state:
            state["clf"] = get_provider(provider).get_text_classifier(model, **options)
        texts = s.to_pylist()
        mask = [t is not None for t in texts]
        res = state["clf"].classify_text([t for t in texts if t is not None], labels)
        it = iter(res)
        out = [next(it) if m else None for m in mask]
        return Series.from_pylist(out, s.name, DataType.string())

    return _batch_func(run, "classify_text", DataType.string())(expr)


def prompt(expr: Expression, provider: str, model: Optional[str] = None, **options) -> Expression:
    from ..ai.provider import get_provider
    from ..core.series import Series

    state = {}

    def run(s: Series) -> Series:
        if "p" not in state:
            state["p"] = get_provider(provider).get_prompter(model, **options)
        texts = s.to_pylist()
        mask = [t is not None for t in texts]
        res = state["p"].prompt([t for t in texts if t is not None])
        it = iter(res)
        out = [next(it) if m else None for m in mask]
        return Series.from_pylist(out, s.name, DataType.string())

    return _batch_func(run, "prompt", DataType.string())(expr)


def embed_image(expr: Expression, provider: str = "dummy",
                model: Optional[str] = None, **options) -> Expression:
    """Embed an image column via the named provider (reference:
    daft/functions/ai embed_image over the ImageEmbedder protocol)."""
    from ..ai.provider import get_provider
    from ..core.series import Series

    state = {}

    def run(s: Series) -> Series:
        if "e" not in state:
            state["e"] = get_provider(provider).get_image_embedder(model, **options)
        imgs = s.to_pylist()
        mask = [i is not None for i in imgs]
        vecs = state["e"].embed_image([i for i in imgs if i is not None])
        it = iter(vecs)
        out = [list(map(float, next(it))) if m else None for m in mask]
        return Series.from_pylist(out, s.name, DataType.list(DataType.float32()))

    return _batch_func(run, "embed_image", DataType.list(DataType.float32()))(expr)


def llm_generate(expr: Expression, provider: str = "dummy",
                 model: Optional[str] = None, max_concurrency: int = 1,
                 use_process: bool = False, prefix_routing: bool = True,
                 route_prefix_len: int = 128, **options) -> Expression:
    """LLM generation operator (reference: the VLLMExpr first-class operator +
    actor pool, daft-dsl expr/mod.rs:311). Runs the provider's prompter as a
    batched stateful operator: the optimizer's split-UDF rule isolates it into
    its own pipeline node, and max_concurrency replicas serve batches
    (use_process=True puts each replica in its own worker process — the
    engine's actor-pool execution tier)."""
    from ..ai.provider import get_provider
    from ..core.series import Series

    state = {}

    def run(s: Series) -> Series:
        if "p" not in state:
            state["p"] = get_provider(provider).get_prompter(model, **options)
        texts = s.to_pylist()
        mask = [t is not None for t in texts]
        res = state["p"].prompt([t for t in texts if t is not None])
        it = iter(res)
        out = [next(it) if m else None for m in mask]
        return Series.from_pylist(out, s.name, DataType.string())

    return _batch_func(
        run, "llm_generate", DataType.string(),
        max_concurrency=max_concurrency, use_process=use_process,
        route_prefix_len=(route_prefix_len
                          if prefix_routing and max_concurrency > 1 else None),
    )(expr)
