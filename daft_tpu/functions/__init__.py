from .registry import FunctionSpec, get_function, has_function, register
from .ai import classify_text, embed_image, embed_text, llm_generate, prompt
from .window import cume_dist, dense_rank, ntile, percent_rank, rank, row_number

__all__ = [
    "FunctionSpec", "get_function", "has_function", "register",
    "row_number", "rank", "dense_rank", "percent_rank", "cume_dist", "ntile",
    "embed_text", "embed_image", "classify_text", "prompt", "llm_generate",
]
from ..filetype import DaftFile, File
from .media import run_process
