from .registry import FunctionSpec, get_function, has_function, register

__all__ = ["FunctionSpec", "get_function", "has_function", "register"]
