"""daft_tpu: a TPU-native multimodal data engine.

A brand-new implementation of the reference's capabilities (see SURVEY.md): lazy
DataFrame + SQL over a columnar Arrow-compatible core, rule/cost-based optimizer,
streaming morsel-driven execution, and TPU-first compute — relational operator
pipelines fused into jit-compiled JAX/XLA stage programs over mesh-sharded arrays.
"""

from .datatype import DataType, Field, ImageMode, TimeUnit
from .schema import Schema
from .core import Series, RecordBatch, MicroPartition

__version__ = "0.1.0"

__all__ = [
    "DataType",
    "Field",
    "ImageMode",
    "TimeUnit",
    "Schema",
    "Series",
    "RecordBatch",
    "MicroPartition",
]


def __getattr__(name):
    # Lazy attributes filled in as the API surface lands (DataFrame, col, lit, ...).
    if name.startswith("_") or name == "api":
        raise AttributeError(f"module 'daft_tpu' has no attribute {name!r}")
    from . import api as _api

    try:
        return getattr(_api, name)
    except AttributeError:
        raise AttributeError(f"module 'daft_tpu' has no attribute {name!r}") from None
