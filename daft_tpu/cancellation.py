"""Cooperative query cancellation token.

A thread-local cancellation event installed around a query's execution (the
serving tier installs the ServeFuture's cancel event in its session worker
thread). Engine layers that reach natural yield points — the distributed
planner between task stages, the serving executor between streamed result
partitions — call ``raise_if_cancelled()``; nothing polls, nothing pays when
no token is installed (one thread-local attribute read).

Cancellation is BEST-EFFORT by design: a stage already running on the worker
pool completes (its results are simply discarded), device dispatches are never
interrupted mid-kernel, and a query past its last check point resolves
normally. What is guaranteed: a cancelled query stops consuming new pool
stages, and a still-queued serving query never starts at all
(ServeFuture.cancel pulls it from the FairAdmissionQueue).
"""

from __future__ import annotations

import threading


class QueryCancelled(RuntimeError):
    """Raised inside a cancelled query's execution; carried to the caller by
    whatever future/iterator was driving it."""


_TL = threading.local()


def set_cancel_event(ev) -> None:
    """Install (or clear, with None) this thread's cancellation event."""
    _TL.ev = ev


def cancel_event():
    return getattr(_TL, "ev", None)


def raise_if_cancelled(message: str = "query cancelled") -> None:
    ev = getattr(_TL, "ev", None)
    if ev is not None and ev.is_set():
        raise QueryCancelled(message)
